// Self-describing file-ID codec, bit-compatible with
// fastdfs_tpu/common/fileid.py (cross-checked by golden tests).
//
// Reference: storage/storage_service.c:storage_gen_filename(),
// common/fdfs_global.c:fdfs_check_data_filename().
#pragma once

#include <cstdint>
#include <optional>
#include <string>
#include <string_view>

namespace fdfs {

inline constexpr uint64_t kFileSizeMask = (1ULL << 48) - 1;
inline constexpr int kUniqShift = 48;
inline constexpr uint64_t kUniqMask = 0xFFF;
inline constexpr uint64_t kFlagSlave = 1ULL << 60;
inline constexpr uint64_t kFlagTrunk = 1ULL << 61;
inline constexpr uint64_t kFlagAppender = 1ULL << 62;
inline constexpr int kDefaultSubdirCount = 256;

// Location of a small file packed inside a trunk file (reference:
// FDFSTrunkFullInfo in storage/trunk_mgr/trunk_shared.h; trunk IDs carry it
// as an extra 16-char base64 segment after the 27-char stem, the analogue
// of upstream's longer FDFS_TRUNK_LOGIC_FILENAME_LENGTH names).
struct TrunkLocation {
  uint32_t trunk_id = 0;    // trunk file number within the store path
  uint32_t offset = 0;      // slot start (its 24-byte header) in the file
  uint32_t alloc_size = 0;  // whole slot size including the header
};

inline constexpr int kTrunkSuffixLength = 16;  // base64(12 bytes)

std::string EncodeTrunkSuffix(const TrunkLocation& loc);
std::optional<TrunkLocation> DecodeTrunkSuffix(std::string_view suffix);

struct FileIdParts {
  std::string group;
  int store_path_index = 0;
  int subdir1 = 0;
  int subdir2 = 0;
  std::string filename;  // 27 b64 chars + optional slave prefix + .ext
  std::string prefix;    // slave-file name prefix ("" for master files)
  std::optional<TrunkLocation> trunk_loc;  // set iff trunk flag present

  // Decoded blob facts.
  uint32_t source_ip = 0;  // packed IPv4
  uint32_t create_timestamp = 0;
  uint64_t file_size = 0;
  uint32_t crc32 = 0;
  int uniquifier = 0;
  bool appender = false;
  bool trunk = false;
  bool slave = false;

  std::string RemoteFilename() const;  // "Mxx/aa/bb/name[.ext]"
  std::string FullId() const;          // "group/Mxx/aa/bb/name[.ext]"
};

struct EncodeFileIdArgs {
  std::string_view group;
  int store_path_index = 0;
  uint32_t source_ip = 0;  // packed IPv4 (use PackIp)
  uint32_t create_timestamp = 0;
  uint64_t file_size = 0;
  uint32_t crc32 = 0;
  std::string_view ext;  // without dot; may be empty
  int uniquifier = 0;
  bool appender = false;
  bool trunk = false;   // requires trunk_loc
  bool slave = false;
  const TrunkLocation* trunk_loc = nullptr;
  int subdir_count = kDefaultSubdirCount;
};

// Returns empty optional on invalid args (bad group/ext length, ranges).
std::optional<std::string> EncodeFileId(const EncodeFileIdArgs& args);

// Full-ID parse+validate (group/Mxx/aa/bb/b64[.ext]); nullopt if malformed
// or the subdir pair does not match the blob hash.
std::optional<FileIdParts> DecodeFileId(std::string_view file_id,
                                        int subdir_count = kDefaultSubdirCount);

// Strict wire-grammar check for "Mxx/aa/bb/name[.ext]" (path-traversal
// guard); returns local path "<base>/data/aa/bb/name" or nullopt.
std::optional<std::string> LocalPath(std::string_view base_path,
                                     std::string_view remote_filename);

uint32_t PackIp(std::string_view dotted);  // 0 on parse failure ("0.0.0.0" ok)
std::string UnpackIp(uint32_t ip);

}  // namespace fdfs
