#include "common/net.h"

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <vector>

#include "common/bytes.h"
#include "common/threadreg.h"

namespace fdfs {

int64_t NowMs() {
  struct timeval tv;
  gettimeofday(&tv, nullptr);
  return static_cast<int64_t>(tv.tv_sec) * 1000 + tv.tv_usec / 1000;
}

bool SetNonBlocking(int fd) {
  int flags = fcntl(fd, F_GETFL, 0);
  return flags >= 0 && fcntl(fd, F_SETFL, flags | O_NONBLOCK) == 0;
}

void SetNoDelay(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

namespace {

// Shared bind+listen tail of the two listen variants.
int ListenOn(int fd, const std::string& bind_addr, int port,
             std::string* error) {
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind_addr.empty() || bind_addr == "0.0.0.0") {
    addr.sin_addr.s_addr = INADDR_ANY;
  } else if (inet_pton(AF_INET, bind_addr.c_str(), &addr.sin_addr) != 1) {
    *error = "bad bind address: " + bind_addr;
    close(fd);
    return -1;
  }
  if (bind(fd, reinterpret_cast<struct sockaddr*>(&addr), sizeof(addr)) != 0) {
    *error = std::string("bind: ") + strerror(errno);
    close(fd);
    return -1;
  }
  if (listen(fd, 128) != 0) {
    *error = std::string("listen: ") + strerror(errno);
    close(fd);
    return -1;
  }
  return fd;
}

}  // namespace

int TcpListen(const std::string& bind_addr, int port, std::string* error) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) {
    *error = std::string("socket: ") + strerror(errno);
    return -1;
  }
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  return ListenOn(fd, bind_addr, port, error);
}

int TcpListenReuseport(const std::string& bind_addr, int port,
                       std::string* error) {
#ifndef SO_REUSEPORT
  *error = "SO_REUSEPORT not supported on this platform";
  return -1;
#else
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) {
    *error = std::string("socket: ") + strerror(errno);
    return -1;
  }
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  // The refusal callers fall back on: an old kernel (< 3.9) or a
  // filtered sockopt answers here, before any bind happens.
  if (setsockopt(fd, SOL_SOCKET, SO_REUSEPORT, &one, sizeof(one)) != 0) {
    *error = std::string("setsockopt(SO_REUSEPORT): ") + strerror(errno);
    close(fd);
    return -1;
  }
  return ListenOn(fd, bind_addr, port, error);
#endif
}

int TcpConnect(const std::string& host, int port, int timeout_ms,
               std::string* error) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) {
    *error = std::string("socket: ") + strerror(errno);
    return -1;
  }
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    *error = "bad address: " + host;
    close(fd);
    return -1;
  }
  SetNonBlocking(fd);
  int rc = connect(fd, reinterpret_cast<struct sockaddr*>(&addr), sizeof(addr));
  if (rc != 0 && errno != EINPROGRESS) {
    *error = std::string("connect: ") + strerror(errno);
    close(fd);
    return -1;
  }
  if (rc != 0) {
    struct pollfd pfd = {fd, POLLOUT, 0};
    rc = poll(&pfd, 1, timeout_ms);
    if (rc <= 0) {
      *error = rc == 0 ? "connect timeout" : strerror(errno);
      close(fd);
      return -1;
    }
    int err = 0;
    socklen_t len = sizeof(err);
    getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len);
    if (err != 0) {
      *error = std::string("connect: ") + strerror(err);
      close(fd);
      return -1;
    }
  }
  // Back to blocking for simple request/response use.
  int flags = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, flags & ~O_NONBLOCK);
  SetNoDelay(fd);
  return fd;
}

bool SendAll(int fd, const void* data, size_t len, int timeout_ms) {
  const uint8_t* p = static_cast<const uint8_t*>(data);
  while (len > 0) {
    // A beat per poll round: socket IO that makes PROGRESS is a live
    // thread (large sync shipments legitimately sit here for longer
    // than any watchdog threshold); a wedged fd times out the poll and
    // returns, so a genuinely stuck caller stops beating.
    BeatThreadHeartbeat();
    struct pollfd pfd = {fd, POLLOUT, 0};
    int rc = poll(&pfd, 1, timeout_ms);
    if (rc <= 0) return false;
    ssize_t n = send(fd, p, len, MSG_NOSIGNAL);
    if (n <= 0) {
      if (n < 0 && (errno == EAGAIN || errno == EINTR)) continue;
      return false;
    }
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

bool RecvAll(int fd, void* data, size_t len, int timeout_ms) {
  uint8_t* p = static_cast<uint8_t*>(data);
  while (len > 0) {
    BeatThreadHeartbeat();  // see SendAll
    struct pollfd pfd = {fd, POLLIN, 0};
    int rc = poll(&pfd, 1, timeout_ms);
    if (rc <= 0) return false;
    ssize_t n = recv(fd, p, len, 0);
    if (n <= 0) {
      if (n < 0 && (errno == EAGAIN || errno == EINTR)) continue;
      return false;
    }
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

namespace {

std::atomic<RpcObserver> g_rpc_observer{nullptr};

bool NetRpcInner(int fd, uint8_t cmd, const std::string& body,
                 std::string* resp, uint8_t* status, int64_t max_resp,
                 int timeout_ms) {
  // 10-byte header framing shared with protocol_gen.h kHeaderSize; kept
  // as a literal here so net.{h,cc} stays below the generated header in
  // the include graph.
  uint8_t hdr[10];
  PutInt64BE(static_cast<int64_t>(body.size()), hdr);
  hdr[8] = cmd;
  hdr[9] = 0;
  if (!SendAll(fd, hdr, sizeof(hdr), timeout_ms)) return false;
  if (!body.empty() && !SendAll(fd, body.data(), body.size(), timeout_ms))
    return false;
  if (!RecvAll(fd, hdr, sizeof(hdr), timeout_ms)) return false;
  int64_t len = GetInt64BE(hdr);
  *status = hdr[9];
  if (len < 0 || len > max_resp) return false;
  resp->resize(static_cast<size_t>(len));
  if (len > 0 && !RecvAll(fd, resp->data(), resp->size(), timeout_ms))
    return false;
  return true;
}

}  // namespace

void SetRpcObserver(RpcObserver obs) {
  g_rpc_observer.store(obs, std::memory_order_release);
}

bool NetRpc(int fd, uint8_t cmd, const std::string& body, std::string* resp,
            uint8_t* status, int64_t max_resp, int timeout_ms) {
  RpcObserver obs = g_rpc_observer.load(std::memory_order_acquire);
  if (obs == nullptr)
    return NetRpcInner(fd, cmd, body, resp, status, max_resp, timeout_ms);
  *status = 0;
  int64_t t0 = MonoUs();
  bool ok = NetRpcInner(fd, cmd, body, resp, status, max_resp, timeout_ms);
  // On transport failure the status byte is whatever was (or wasn't)
  // parsed — report 0 so the observer never mistakes garbage for an
  // application answer.
  obs(fd, cmd, ok, ok ? *status : 0, MonoUs() - t0, timeout_ms);
  return ok;
}

static std::string AddrIp(const struct sockaddr_in& a) {
  char buf[INET_ADDRSTRLEN] = {0};
  inet_ntop(AF_INET, &a.sin_addr, buf, sizeof(buf));
  return buf;
}

std::string PeerIp(int fd) {
  struct sockaddr_in a;
  socklen_t len = sizeof(a);
  if (getpeername(fd, reinterpret_cast<struct sockaddr*>(&a), &len) != 0)
    return "";
  return AddrIp(a);
}

std::string SockIp(int fd) {
  struct sockaddr_in a;
  socklen_t len = sizeof(a);
  if (getsockname(fd, reinterpret_cast<struct sockaddr*>(&a), &len) != 0)
    return "";
  return AddrIp(a);
}

int PeerPort(int fd) {
  struct sockaddr_in a;
  socklen_t len = sizeof(a);
  if (getpeername(fd, reinterpret_cast<struct sockaddr*>(&a), &len) != 0)
    return 0;
  return static_cast<int>(ntohs(a.sin_port));
}

// -- EventLoop ------------------------------------------------------------

EventLoop::EventLoop() {
  epfd_ = epoll_create1(EPOLL_CLOEXEC);
  wake_fd_ = eventfd(0, EFD_CLOEXEC | EFD_NONBLOCK);
  if (wake_fd_ >= 0) {
    struct epoll_event ev;
    memset(&ev, 0, sizeof(ev));
    ev.events = EPOLLIN;
    ev.data.fd = wake_fd_;
    epoll_ctl(epfd_, EPOLL_CTL_ADD, wake_fd_, &ev);
  }
}

EventLoop::~EventLoop() {
  if (wake_fd_ >= 0) close(wake_fd_);
  if (epfd_ >= 0) close(epfd_);
}

void EventLoop::Post(std::function<void()> fn) {
  {
    std::lock_guard<RankedMutex> lk(post_mu_);
    posted_.push_back(std::move(fn));
  }
  uint64_t one = 1;
  if (wake_fd_ >= 0) {
    ssize_t n = write(wake_fd_, &one, sizeof(one));
    (void)n;  // EAGAIN just means a wakeup is already pending
  }
}

int EventLoop::DrainPosted() {
  if (wake_fd_ >= 0) {
    uint64_t junk;
    while (read(wake_fd_, &junk, sizeof(junk)) > 0) {
    }
  }
  int ran = 0;
  for (;;) {
    std::function<void()> fn;
    {
      std::lock_guard<RankedMutex> lk(post_mu_);
      if (posted_.empty()) break;
      fn = std::move(posted_.front());
      posted_.pop_front();
    }
    fn();
    ++ran;
  }
  return ran;
}

bool EventLoop::Add(int fd, uint32_t events, FdCallback cb) {
  struct epoll_event ev;
  memset(&ev, 0, sizeof(ev));
  ev.events = events;
  ev.data.fd = fd;
  if (epoll_ctl(epfd_, EPOLL_CTL_ADD, fd, &ev) != 0) return false;
  fd_cbs_[fd] = std::move(cb);
  return true;
}

bool EventLoop::Mod(int fd, uint32_t events) {
  struct epoll_event ev;
  memset(&ev, 0, sizeof(ev));
  ev.events = events;
  ev.data.fd = fd;
  return epoll_ctl(epfd_, EPOLL_CTL_MOD, fd, &ev) == 0;
}

void EventLoop::Del(int fd) {
  epoll_ctl(epfd_, EPOLL_CTL_DEL, fd, nullptr);
  fd_cbs_.erase(fd);
}

int EventLoop::AddTimer(int interval_ms, TimerCallback cb, bool repeat) {
  int id = next_timer_id_++;
  timers_[id] = Timer{NowMs() + interval_ms, interval_ms, std::move(cb), repeat};
  return id;
}

void EventLoop::CancelTimer(int timer_id) { timers_.erase(timer_id); }

int EventLoop::NextTimeoutMs() const {
  if (timers_.empty()) return 1000;
  int64_t now = NowMs();
  int64_t next = INT64_MAX;
  for (const auto& [id, t] : timers_)
    if (t.deadline_ms < next) next = t.deadline_ms;
  int64_t d = next - now;
  if (d < 0) return 0;
  if (d > 1000) return 1000;
  return static_cast<int>(d);
}

int EventLoop::FireTimers() {
  int64_t now = NowMs();
  std::vector<int> fired;
  for (auto& [id, t] : timers_)
    if (t.deadline_ms <= now) fired.push_back(id);
  int ran = 0;
  for (int id : fired) {
    auto it = timers_.find(id);
    if (it == timers_.end()) continue;
    TimerCallback cb = it->second.cb;  // copy: cb may cancel/add timers
    if (it->second.repeat) {
      it->second.deadline_ms = now + it->second.interval_ms;
    } else {
      timers_.erase(it);
    }
    cb();
    ++ran;
  }
  return ran;
}

int64_t MonoUs() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<int64_t>(ts.tv_sec) * 1000000 + ts.tv_nsec / 1000;
}

void EventLoop::Run() {
  running_ = true;
  std::vector<struct epoll_event> events(256);
  while (!stop_.load(std::memory_order_acquire)) {
    // NextTimeoutMs caps at 1000ms, so an idle loop still beats its
    // watchdog heartbeat at least once a second.
    BeatThreadHeartbeat();
    int n = epoll_wait(epfd_, events.data(), static_cast<int>(events.size()),
                       NextTimeoutMs());
    if (n < 0) {
      if (errno == EINTR) continue;
      break;
    }
    // Loop-lag clock starts when epoll_wait returns: everything until the
    // next wait is callback time during which other ready fds stall.
    int64_t t0 = iteration_hook_ ? MonoUs() : 0;
    int dispatched = 0;
    for (int i = 0; i < n; ++i) {
      if (events[i].data.fd == wake_fd_) continue;  // drained below
      auto it = fd_cbs_.find(events[i].data.fd);
      if (it != fd_cbs_.end()) {
        FdCallback cb = it->second;  // copy: cb may Del() the fd
        cb(events[i].events);
        ++dispatched;
      }
    }
    int worked = DrainPosted() + FireTimers();
    // Skip iterations that ran NOTHING (pure timeout wakeups on an idle
    // daemon would flood the lag histogram's first bucket with zeros) —
    // but a slow timer or posted task stalls the loop exactly like a
    // slow fd handler, so any callback activity counts as an iteration.
    if (iteration_hook_ && (dispatched > 0 || n > 0 || worked > 0))
      iteration_hook_(MonoUs() - t0, dispatched);
  }
  DrainPosted();  // don't strand posted work at shutdown
  running_ = false;
}

void EventLoop::Stop() {
  stop_.store(true, std::memory_order_release);
  uint64_t one = 1;
  if (wake_fd_ >= 0) {
    ssize_t n = write(wake_fd_, &one, sizeof(one));  // wake epoll_wait
    (void)n;
  }
}

}  // namespace fdfs
