#include "common/healthmon.h"

#include <algorithm>

#include "common/bytes.h"
#include "common/net.h"
#include "common/protocol_gen.h"
#include "common/stats.h"

namespace fdfs {

namespace {

// EWMA smoothing: ~5 samples to move most of the way to a new regime —
// fast enough to flag a peer within two beat intervals, slow enough
// that one dropped packet doesn't gray a healthy node.
constexpr double kAlpha = 0.2;

// Bounded table: a storage talks to its group (few peers) + trackers;
// 64 entries is an order of magnitude of headroom, and eviction keeps a
// long-lived daemon's memory and beat-trailer size flat even if
// addresses churn (tests, DHCP'd lab clusters).
constexpr size_t kMaxPeers = 64;

constexpr uint8_t kTrailerVersion = 1;
constexpr size_t kTrailerPeerLen = 16 + 8 + 8;  // ip + port + score

void AppendInt64(std::string* out, int64_t v) {
  uint8_t buf[8];
  PutInt64BE(v, buf);
  out->append(reinterpret_cast<const char*>(buf), sizeof(buf));
}

void RpcObserverFn(int fd, uint8_t cmd, bool ok, uint8_t /*status*/,
                   int64_t elapsed_us, int timeout_ms) {
  std::string ip = PeerIp(fd);
  if (ip.empty()) return;  // fd already dead; connect-failure paths feed
                           // explicitly with the intended address
  int port = PeerPort(fd);
  HealthMonitor::Global().Feed(ip + ":" + std::to_string(port),
                               HealthMonitor::OpClassFor(cmd), ok,
                               elapsed_us, timeout_ms);
}

}  // namespace

HealthMonitor& HealthMonitor::Global() {
  static HealthMonitor* g = new HealthMonitor();  // never destroyed (the
  // NetRpc observer may fire from daemon threads past static teardown)
  return *g;
}

void HealthMonitor::InstallRpcObserver() { SetRpcObserver(&RpcObserverFn); }

const char* HealthMonitor::OpClassFor(uint8_t cmd) {
  // The cmd byte alone is enough: tracker- and storage-port opcodes
  // overlap only where the meaning matches (ACTIVE_TEST, TRACE_CTX).
  switch (cmd) {
    case static_cast<uint8_t>(StorageCmd::kActiveTest):
      return "probe";
    case static_cast<uint8_t>(TrackerCmd::kStorageBeat):
      return "beat";
    case static_cast<uint8_t>(StorageCmd::kFetchOnePathBinlog):
    case static_cast<uint8_t>(StorageCmd::kFetchRecipe):
    case static_cast<uint8_t>(StorageCmd::kFetchChunk):
      return "fetch";
    case static_cast<uint8_t>(StorageCmd::kEcRelease):
      return "ec";
    case static_cast<uint8_t>(StorageCmd::kSyncCreateFile):
    case static_cast<uint8_t>(StorageCmd::kSyncDeleteFile):
    case static_cast<uint8_t>(StorageCmd::kSyncUpdateFile):
    case static_cast<uint8_t>(StorageCmd::kSyncCreateLink):
    case static_cast<uint8_t>(StorageCmd::kSyncAppendFile):
    case static_cast<uint8_t>(StorageCmd::kSyncModifyFile):
    case static_cast<uint8_t>(StorageCmd::kSyncTruncateFile):
    case static_cast<uint8_t>(StorageCmd::kSyncQueryChunks):
    case static_cast<uint8_t>(StorageCmd::kSyncCreateRecipe):
      return "sync";
    default:
      return "rpc";
  }
}

void HealthMonitor::Feed(const std::string& addr, const std::string& op,
                         bool ok, int64_t elapsed_us, int timeout_ms) {
  if (addr.empty()) return;
  // Timeout heuristic: transport failures that burned >= 90% of the
  // timeout budget are timeout-shaped (peer limping), the rest are hard
  // failures (RST, EOF — peer down or restarting).
  bool timed_out = !ok && timeout_ms > 0 &&
                   elapsed_us >= static_cast<int64_t>(timeout_ms) * 900;
  int64_t now = MonoUs();
  std::lock_guard<RankedMutex> lk(mu_);
  auto it = peers_.find(addr);
  if (it == peers_.end()) {
    if (peers_.size() >= kMaxPeers) {
      auto oldest = peers_.begin();
      for (auto pit = peers_.begin(); pit != peers_.end(); ++pit)
        if (pit->second.last_us < oldest->second.last_us) oldest = pit;
      peers_.erase(oldest);
    }
    it = peers_.emplace(addr, PeerEntry{}).first;
  }
  if (ok) {
    StatHistogram* hist = rpc_hist_.load(std::memory_order_relaxed);
    if (hist != nullptr) hist->Observe(elapsed_us);
  }
  PeerEntry& e = it->second;
  e.last_us = now;
  OpHealth& h = e.ops[op];
  ++h.ops;
  if (!ok) ++h.errors;
  if (timed_out) ++h.timeouts;
  if (ok) h.ewma_us = h.ops == 1 ? static_cast<double>(elapsed_us)
                                 : (1 - kAlpha) * h.ewma_us +
                                       kAlpha * static_cast<double>(elapsed_us);
  h.err_ewma = (1 - kAlpha) * h.err_ewma + (ok ? 0.0 : kAlpha);
  h.timeout_ewma = (1 - kAlpha) * h.timeout_ewma + (timed_out ? kAlpha : 0.0);
  h.last_us = now;
}

void HealthMonitor::SetRpcHistogram(StatHistogram* h) {
  rpc_hist_.store(h, std::memory_order_relaxed);
}

void HealthMonitor::SetStalledThreads(int n) {
  stalled_threads_.store(n, std::memory_order_relaxed);
  self_signal_seen_.store(true, std::memory_order_relaxed);
}

void HealthMonitor::SetProbe(int64_t read_us, int64_t write_us,
                             int threshold_ms) {
  probe_read_us_.store(read_us, std::memory_order_relaxed);
  probe_write_us_.store(write_us, std::memory_order_relaxed);
  probe_threshold_ms_.store(threshold_ms, std::memory_order_relaxed);
  self_signal_seen_.store(true, std::memory_order_relaxed);
}

int64_t HealthMonitor::OpScore(const OpHealth& h) {
  int64_t score = 100;
  score -= static_cast<int64_t>(h.err_ewma * 60 + 0.5);
  score -= static_cast<int64_t>(h.timeout_ewma * 40 + 0.5);
  // 10 points per 100ms of EWMA latency, capped: slowness alone can
  // take a peer to the gray edge but only errors/timeouts push it hard.
  int64_t lat_pen = static_cast<int64_t>(h.ewma_us / 100000.0 * 10.0);
  score -= std::min<int64_t>(30, lat_pen);
  return std::max<int64_t>(0, std::min<int64_t>(100, score));
}

int64_t HealthMonitor::PeerScoreLocked(const PeerEntry& e) const {
  int64_t worst = 100;
  for (const auto& [op, h] : e.ops) worst = std::min(worst, OpScore(h));
  return worst;
}

int64_t HealthMonitor::SelfScore() const {
  int64_t score = 100;
  score -= 50ll * stalled_threads_.load(std::memory_order_relaxed);
  int thr_ms = probe_threshold_ms_.load(std::memory_order_relaxed);
  if (thr_ms > 0) {
    int64_t worst = std::max(probe_read_us_.load(std::memory_order_relaxed),
                             probe_write_us_.load(std::memory_order_relaxed));
    int64_t thr_us = static_cast<int64_t>(thr_ms) * 1000;
    if (worst > 4 * thr_us)
      score -= 75;
    else if (worst > thr_us)
      score -= 50;
  }
  return std::max<int64_t>(0, std::min<int64_t>(100, score));
}

int64_t HealthMonitor::PeerScore(const std::string& addr) const {
  std::lock_guard<RankedMutex> lk(mu_);
  auto it = peers_.find(addr);
  if (it == peers_.end()) return -1;
  return PeerScoreLocked(it->second);
}

std::vector<HealthMonitor::PeerRow> HealthMonitor::Snapshot() const {
  std::vector<PeerRow> out;
  int64_t now = MonoUs();
  std::lock_guard<RankedMutex> lk(mu_);
  for (const auto& [addr, e] : peers_) {
    for (const auto& [op, h] : e.ops) {
      PeerRow r;
      r.addr = addr;
      r.op = op;
      r.score = OpScore(h);
      r.rpc_ewma_us = static_cast<int64_t>(h.ewma_us);
      r.error_pct = static_cast<int64_t>(h.err_ewma * 100 + 0.5);
      r.timeout_pct = static_cast<int64_t>(h.timeout_ewma * 100 + 0.5);
      r.ops = h.ops;
      r.errors = h.errors;
      r.timeouts = h.timeouts;
      r.age_s = h.last_us > 0 ? (now - h.last_us) / 1000000 : -1;
      out.push_back(std::move(r));
    }
  }
  // std::map iteration is already (addr, op)-sorted — pinned here
  // because the JSON/golden shape depends on it.
  return out;
}

std::string HealthMonitor::Json(const std::string& role, int port) const {
  std::vector<PeerRow> rows = Snapshot();
  std::string out = "{\"role\":";
  AppendJsonString(&out, role);
  out += ",\"port\":" + std::to_string(port);
  out += ",\"score\":" + std::to_string(SelfScore());
  out += ",\"stalled_threads\":" +
         std::to_string(stalled_threads_.load(std::memory_order_relaxed));
  out += ",\"probe\":{\"read_us\":" +
         std::to_string(probe_read_us_.load(std::memory_order_relaxed)) +
         ",\"write_us\":" +
         std::to_string(probe_write_us_.load(std::memory_order_relaxed)) +
         ",\"threshold_ms\":" +
         std::to_string(probe_threshold_ms_.load(std::memory_order_relaxed)) +
         "}";
  out += ",\"peers\":[";
  bool first = true;
  for (const PeerRow& r : rows) {
    if (!first) out += ",";
    first = false;
    out += "{\"addr\":";
    AppendJsonString(&out, r.addr);
    out += ",\"op\":";
    AppendJsonString(&out, r.op);
    out += ",\"score\":" + std::to_string(r.score) +
           ",\"rpc_ewma_us\":" + std::to_string(r.rpc_ewma_us) +
           ",\"error_pct\":" + std::to_string(r.error_pct) +
           ",\"timeout_pct\":" + std::to_string(r.timeout_pct) +
           ",\"ops\":" + std::to_string(r.ops) +
           ",\"errors\":" + std::to_string(r.errors) +
           ",\"timeouts\":" + std::to_string(r.timeouts) +
           ",\"age_s\":" + std::to_string(r.age_s) + "}";
  }
  out += "]}";
  return out;
}

std::string HealthMonitor::PackBeatTrailer() const {
  struct Scored {
    std::string ip;
    int64_t port;
    int64_t score;
  };
  std::vector<Scored> scored;
  {
    std::lock_guard<RankedMutex> lk(mu_);
    if (peers_.empty() &&
        !self_signal_seen_.load(std::memory_order_relaxed))
      return std::string();  // nothing to say: beat stays trailerless
    scored.reserve(peers_.size());
    for (const auto& [addr, e] : peers_) {
      size_t colon = addr.rfind(':');
      if (colon == std::string::npos || colon == 0) continue;
      Scored s;
      s.ip = addr.substr(0, colon);
      s.port = atoll(addr.c_str() + colon + 1);
      if (s.ip.size() >= 16 || s.port <= 0) continue;
      s.score = PeerScoreLocked(e);
      scored.push_back(std::move(s));
    }
  }
  std::string out;
  out.push_back(static_cast<char>(kTrailerVersion));
  AppendInt64(&out, SelfScore());
  AppendInt64(&out, static_cast<int64_t>(scored.size()));
  for (const Scored& s : scored) {
    PutFixedField(&out, s.ip, 16);
    AppendInt64(&out, s.port);
    AppendInt64(&out, s.score);
  }
  return out;
}

void HealthMonitor::PublishGauges(StatsRegistry* reg) const {
  // Per-ADDR (not per op class) to bound gauge cardinality; the full
  // per-op table stays available via HEALTH_STATUS.
  struct AddrGauge {
    std::string addr;
    int64_t score;
    int64_t worst_ewma_us = 0;
    int64_t worst_error_pct = 0;
    int64_t worst_timeout_pct = 0;
  };
  std::vector<AddrGauge> gauges;
  {
    std::lock_guard<RankedMutex> lk(mu_);
    gauges.reserve(peers_.size());
    for (const auto& [addr, e] : peers_) {
      AddrGauge g;
      g.addr = addr;
      g.score = PeerScoreLocked(e);
      for (const auto& [op, h] : e.ops) {
        g.worst_ewma_us =
            std::max(g.worst_ewma_us, static_cast<int64_t>(h.ewma_us));
        g.worst_error_pct = std::max(
            g.worst_error_pct, static_cast<int64_t>(h.err_ewma * 100 + 0.5));
        g.worst_timeout_pct =
            std::max(g.worst_timeout_pct,
                     static_cast<int64_t>(h.timeout_ewma * 100 + 0.5));
      }
      gauges.push_back(std::move(g));
    }
  }
  // Registry writes AFTER mu_ release: kHealthMon (195) must never hold
  // across a kStatsRegistry (70) acquisition.
  std::vector<std::string> keep;
  keep.reserve(gauges.size());
  for (const AddrGauge& g : gauges) {
    std::string base = "peer." + g.addr + ".";
    reg->SetGauge(base + "score", g.score);
    reg->SetGauge(base + "rpc_ewma_us", g.worst_ewma_us);
    reg->SetGauge(base + "error_pct", g.worst_error_pct);
    reg->SetGauge(base + "timeout_pct", g.worst_timeout_pct);
    keep.push_back(std::move(base));
  }
  reg->PruneGauges("peer.", keep);
  reg->SetGauge("health.score", SelfScore());
}

void HealthMonitor::Reset() {
  std::lock_guard<RankedMutex> lk(mu_);
  peers_.clear();
  rpc_hist_.store(nullptr, std::memory_order_relaxed);
  stalled_threads_.store(0, std::memory_order_relaxed);
  probe_read_us_.store(0, std::memory_order_relaxed);
  probe_write_us_.store(0, std::memory_order_relaxed);
  probe_threshold_ms_.store(0, std::memory_order_relaxed);
  self_signal_seen_.store(false, std::memory_order_relaxed);
}

bool ParseBeatHealthTrailer(const char* p, size_t len,
                            BeatHealthTrailer* out) {
  if (len < 1 + 8 + 8) return false;
  const uint8_t* u = reinterpret_cast<const uint8_t*>(p);
  if (u[0] != kTrailerVersion) return false;
  out->self_score = GetInt64BE(u + 1);
  int64_t n = GetInt64BE(u + 9);
  if (n < 0 || static_cast<size_t>(n) > kMaxPeers ||
      len < 17 + static_cast<size_t>(n) * kTrailerPeerLen)
    return false;
  const uint8_t* q = u + 17;
  out->peers.clear();
  out->peers.reserve(static_cast<size_t>(n));
  for (int64_t i = 0; i < n; ++i, q += kTrailerPeerLen) {
    std::string ip = GetFixedField(q, 16);
    int64_t port = GetInt64BE(q + 16);
    int64_t score = GetInt64BE(q + 24);
    if (ip.empty() || port <= 0 || port > 65535) continue;
    out->peers.emplace_back(ip + ":" + std::to_string(port), score);
  }
  return true;
}

}  // namespace fdfs
