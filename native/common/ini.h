// FastDFS-style INI reader (reference: libfastcommon ini_file_reader.c).
// Same syntax as fastdfs_tpu/common/ini_config.py: flat key=value, '#'
// comments, repeated keys, '#include file' relative to the including file.
#pragma once

#include <cstdint>
#include <map>
#include <optional>
#include <string>
#include <vector>

namespace fdfs {

class IniConfig {
 public:
  // Returns false and fills *error on IO error / include cycle.
  bool LoadFile(const std::string& path, std::string* error);
  bool LoadString(const std::string& text, std::string* error);

  std::optional<std::string> Get(const std::string& key) const;
  std::vector<std::string> GetAll(const std::string& key) const;
  std::string GetStr(const std::string& key, const std::string& dflt) const;
  int64_t GetInt(const std::string& key, int64_t dflt) const;
  bool GetBool(const std::string& key, bool dflt) const;
  // Sizes with K/M/G/T suffixes (e.g. "256KB", "64MB").
  int64_t GetBytes(const std::string& key, int64_t dflt) const;
  // Durations with s/m/h/d suffixes.
  int64_t GetSeconds(const std::string& key, int64_t dflt) const;
  bool Has(const std::string& key) const { return items_.count(key) > 0; }

 private:
  bool ParseLines(const std::string& text, const std::string& base_dir,
                  std::vector<std::string>* stack, std::string* error);
  bool LoadFileInner(const std::string& path, std::vector<std::string>* stack,
                     std::string* error);
  std::map<std::string, std::vector<std::string>> items_;
};

}  // namespace fdfs
