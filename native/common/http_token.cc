#include "common/http_token.h"

#include <cstring>

namespace fdfs {

namespace {

// MD5 (RFC 1321).  Straightforward 64-round implementation over 512-bit
// blocks; little-endian word loads/stores as the spec requires.
struct Md5Ctx {
  uint32_t a = 0x67452301, b = 0xefcdab89, c = 0x98badcfe, d = 0x10325476;
  uint64_t total_len = 0;
  uint8_t buf[64];
  size_t buf_len = 0;
};

constexpr uint32_t kK[64] = {
    0xd76aa478, 0xe8c7b756, 0x242070db, 0xc1bdceee, 0xf57c0faf, 0x4787c62a,
    0xa8304613, 0xfd469501, 0x698098d8, 0x8b44f7af, 0xffff5bb1, 0x895cd7be,
    0x6b901122, 0xfd987193, 0xa679438e, 0x49b40821, 0xf61e2562, 0xc040b340,
    0x265e5a51, 0xe9b6c7aa, 0xd62f105d, 0x02441453, 0xd8a1e681, 0xe7d3fbc8,
    0x21e1cde6, 0xc33707d6, 0xf4d50d87, 0x455a14ed, 0xa9e3e905, 0xfcefa3f8,
    0x676f02d9, 0x8d2a4c8a, 0xfffa3942, 0x8771f681, 0x6d9d6122, 0xfde5380c,
    0xa4beea44, 0x4bdecfa9, 0xf6bb4b60, 0xbebfbc70, 0x289b7ec6, 0xeaa127fa,
    0xd4ef3085, 0x04881d05, 0xd9d4d039, 0xe6db99e5, 0x1fa27cf8, 0xc4ac5665,
    0xf4292244, 0x432aff97, 0xab9423a7, 0xfc93a039, 0x655b59c3, 0x8f0ccc92,
    0xffeff47d, 0x85845dd1, 0x6fa87e4f, 0xfe2ce6e0, 0xa3014314, 0x4e0811a1,
    0xf7537e82, 0xbd3af235, 0x2ad7d2bb, 0xeb86d391};

constexpr int kShift[64] = {7, 12, 17, 22, 7, 12, 17, 22, 7, 12, 17, 22,
                            7, 12, 17, 22, 5, 9,  14, 20, 5, 9,  14, 20,
                            5, 9,  14, 20, 5, 9,  14, 20, 4, 11, 16, 23,
                            4, 11, 16, 23, 4, 11, 16, 23, 4, 11, 16, 23,
                            6, 10, 15, 21, 6, 10, 15, 21, 6, 10, 15, 21,
                            6, 10, 15, 21};

uint32_t Rotl(uint32_t x, int n) { return (x << n) | (x >> (32 - n)); }

void Md5Block(Md5Ctx* ctx, const uint8_t* p) {
  uint32_t m[16];
  for (int i = 0; i < 16; ++i) {
    m[i] = static_cast<uint32_t>(p[4 * i]) |
           (static_cast<uint32_t>(p[4 * i + 1]) << 8) |
           (static_cast<uint32_t>(p[4 * i + 2]) << 16) |
           (static_cast<uint32_t>(p[4 * i + 3]) << 24);
  }
  uint32_t a = ctx->a, b = ctx->b, c = ctx->c, d = ctx->d;
  for (int i = 0; i < 64; ++i) {
    uint32_t f;
    int g;
    if (i < 16) {
      f = (b & c) | (~b & d);
      g = i;
    } else if (i < 32) {
      f = (d & b) | (~d & c);
      g = (5 * i + 1) & 15;
    } else if (i < 48) {
      f = b ^ c ^ d;
      g = (3 * i + 5) & 15;
    } else {
      f = c ^ (b | ~d);
      g = (7 * i) & 15;
    }
    uint32_t tmp = d;
    d = c;
    c = b;
    b = b + Rotl(a + f + kK[i] + m[g], kShift[i]);
    a = tmp;
  }
  ctx->a += a;
  ctx->b += b;
  ctx->c += c;
  ctx->d += d;
}

void Md5Update(Md5Ctx* ctx, const uint8_t* data, size_t len) {
  ctx->total_len += len;
  while (len > 0) {
    size_t take = 64 - ctx->buf_len;
    if (take > len) take = len;
    memcpy(ctx->buf + ctx->buf_len, data, take);
    ctx->buf_len += take;
    data += take;
    len -= take;
    if (ctx->buf_len == 64) {
      Md5Block(ctx, ctx->buf);
      ctx->buf_len = 0;
    }
  }
}

void Md5Final(Md5Ctx* ctx, uint8_t out[16]) {
  uint64_t bit_len = ctx->total_len * 8;
  uint8_t pad = 0x80;
  Md5Update(ctx, &pad, 1);
  uint8_t zero = 0;
  while (ctx->buf_len != 56) Md5Update(ctx, &zero, 1);
  uint8_t len_le[8];
  for (int i = 0; i < 8; ++i)
    len_le[i] = static_cast<uint8_t>(bit_len >> (8 * i));
  Md5Update(ctx, len_le, 8);
  uint32_t words[4] = {ctx->a, ctx->b, ctx->c, ctx->d};
  for (int i = 0; i < 4; ++i)
    for (int j = 0; j < 4; ++j)
      out[4 * i + j] = static_cast<uint8_t>(words[i] >> (8 * j));
}

}  // namespace

std::string Md5Hex(std::string_view data) {
  Md5Ctx ctx;
  Md5Update(&ctx, reinterpret_cast<const uint8_t*>(data.data()), data.size());
  uint8_t digest[16];
  Md5Final(&ctx, digest);
  static const char* hex = "0123456789abcdef";
  std::string out(32, '0');
  for (int i = 0; i < 16; ++i) {
    out[2 * i] = hex[digest[i] >> 4];
    out[2 * i + 1] = hex[digest[i] & 0xF];
  }
  return out;
}

std::string HttpGenToken(std::string_view file_uri, std::string_view secret,
                         int64_t ts) {
  std::string buf;
  buf.reserve(file_uri.size() + secret.size() + 20);
  buf.append(file_uri);
  buf.append(secret);
  buf.append(std::to_string(ts));
  return Md5Hex(buf);
}

bool HttpCheckToken(std::string_view token, std::string_view file_uri,
                    std::string_view secret, int64_t ts, int64_t now,
                    int64_t ttl_seconds) {
  if (ttl_seconds > 0) {
    int64_t age = now >= ts ? now - ts : ts - now;
    if (age > ttl_seconds) return false;
  }
  std::string want = HttpGenToken(file_uri, secret, ts);
  if (token.size() != want.size()) return false;
  // Constant-shape comparison: no early exit on the first wrong byte.
  unsigned diff = 0;
  for (size_t i = 0; i < want.size(); ++i)
    diff |= static_cast<unsigned char>(token[i]) ^
            static_cast<unsigned char>(want[i]);
  return diff == 0;
}

}  // namespace fdfs
