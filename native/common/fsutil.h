// Filesystem helpers shared by daemons.
#pragma once

#include <string>

namespace fdfs {

bool MakeDirs(const std::string& path);          // mkdir -p
bool EnsureParentDirs(const std::string& path);  // mkdir -p dirname(path)

}  // namespace fdfs
