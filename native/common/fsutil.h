// Filesystem helpers shared by daemons.
#pragma once

#include <string>

namespace fdfs {

bool MakeDirs(const std::string& path);          // mkdir -p
bool EnsureParentDirs(const std::string& path);  // mkdir -p dirname(path)
bool ReadWholeFile(const std::string& path, std::string* out);

}  // namespace fdfs
