#include "common/log.h"

#include "common/fsutil.h"

#include <sys/stat.h>

#include <cstdio>
#include <ctime>
#include <mutex>

#include "common/lockrank.h"

namespace fdfs {

namespace {
LogLevel g_level = LogLevel::kInfo;
FILE* g_out = nullptr;  // nullptr => stderr
std::string g_path;
int64_t g_rotate_bytes = 256LL << 20;  // 0 = no size rotation
bool g_rotate_daily = true;
int64_t g_written = 0;   // bytes since open (approximate)
int g_open_day = -1;     // yday at open
RankedMutex g_mu{LockRank::kLog};
const char* kNames[] = {"DEBUG", "INFO", "WARN", "ERROR"};

int TodayYday() {
  time_t now = time(nullptr);
  struct tm tmv;
  localtime_r(&now, &tmv);
  return tmv.tm_year * 1000 + tmv.tm_yday;
}

// Rotate-if-due; g_mu held.  Rename to <path>.<YYYYMMDD-HHMMSS> and
// reopen fresh (reference: logger.c rotate_everyday + rotate_size).
void MaybeRotateLocked() {
  if (g_out == nullptr || g_path.empty()) return;
  bool by_size = g_rotate_bytes > 0 && g_written >= g_rotate_bytes;
  bool by_day = g_rotate_daily && g_open_day != TodayYday();
  if (!by_size && !by_day) return;
  fclose(g_out);
  g_out = nullptr;
  char stamp[32];
  time_t now = time(nullptr);
  struct tm tmv;
  localtime_r(&now, &tmv);
  strftime(stamp, sizeof(stamp), "%Y%m%d-%H%M%S", &tmv);
  // Uniquify: two rotations in one second must not clobber each other.
  std::string target = g_path + "." + stamp;
  struct stat st;
  for (int n = 1; stat(target.c_str(), &st) == 0 && n < 1000; ++n)
    target = g_path + "." + stamp + "." + std::to_string(n);
  rename(g_path.c_str(), target.c_str());
  g_out = fopen(g_path.c_str(), "a");
  g_written = 0;
  g_open_day = TodayYday();
}
}  // namespace

void LogSetLevel(LogLevel level) { g_level = level; }
LogLevel LogGetLevel() { return g_level; }

void LogSetFile(const std::string& path) {
  std::lock_guard<RankedMutex> lk(g_mu);
  if (g_out != nullptr) {
    fclose(g_out);
    g_out = nullptr;
  }
  g_path = path;
  g_written = 0;
  g_open_day = TodayYday();
  if (!path.empty()) {
    g_out = fopen(path.c_str(), "a");
    struct stat st;
    if (g_out != nullptr && stat(path.c_str(), &st) == 0)
      g_written = st.st_size;
  }
}

void LogSetRotation(int64_t max_bytes, bool daily) {
  std::lock_guard<RankedMutex> lk(g_mu);
  g_rotate_bytes = max_bytes;
  g_rotate_daily = daily;
}

void LogSetupFileSink(const std::string& base_path,
                      const std::string& log_file, int64_t rotate_size) {
  if (log_file.empty()) return;  // stderr sink
  MakeDirs(base_path + "/logs");
  std::string lp = log_file[0] == '/' ? log_file
                                      : base_path + "/logs/" + log_file;
  LogSetFile(lp);
  LogSetRotation(rotate_size);
}

void LogV(LogLevel level, const char* fmt, va_list ap) {
  if (static_cast<int>(level) < static_cast<int>(g_level)) return;
  char ts[32];
  time_t now = time(nullptr);
  struct tm tmv;
  localtime_r(&now, &tmv);
  strftime(ts, sizeof(ts), "%Y-%m-%d %H:%M:%S", &tmv);
  std::lock_guard<RankedMutex> lk(g_mu);
  MaybeRotateLocked();
  FILE* out = g_out != nullptr ? g_out : stderr;
  int n = fprintf(out, "[%s] %s ", ts, kNames[static_cast<int>(level)]);
  n += vfprintf(out, fmt, ap);
  fputc('\n', out);
  fflush(out);
  if (g_out != nullptr && n > 0) g_written += n + 1;
}

void Log(LogLevel level, const char* fmt, ...) {
  va_list ap;
  va_start(ap, fmt);
  LogV(level, fmt, ap);
  va_end(ap);
}

}  // namespace fdfs
