#include "common/log.h"

#include <ctime>
#include <mutex>

namespace fdfs {

namespace {
LogLevel g_level = LogLevel::kInfo;
FILE* g_out = nullptr;  // nullptr => stderr
std::mutex g_mu;
const char* kNames[] = {"DEBUG", "INFO", "WARN", "ERROR"};
}  // namespace

void LogSetLevel(LogLevel level) { g_level = level; }
LogLevel LogGetLevel() { return g_level; }

void LogSetFile(const std::string& path) {
  std::lock_guard<std::mutex> lk(g_mu);
  if (g_out != nullptr) {
    fclose(g_out);
    g_out = nullptr;
  }
  if (!path.empty()) g_out = fopen(path.c_str(), "a");
}

void LogV(LogLevel level, const char* fmt, va_list ap) {
  if (static_cast<int>(level) < static_cast<int>(g_level)) return;
  char ts[32];
  time_t now = time(nullptr);
  struct tm tmv;
  localtime_r(&now, &tmv);
  strftime(ts, sizeof(ts), "%Y-%m-%d %H:%M:%S", &tmv);
  std::lock_guard<std::mutex> lk(g_mu);
  FILE* out = g_out != nullptr ? g_out : stderr;
  fprintf(out, "[%s] %s ", ts, kNames[static_cast<int>(level)]);
  vfprintf(out, fmt, ap);
  fputc('\n', out);
  fflush(out);
}

void Log(LogLevel level, const char* fmt, ...) {
  va_list ap;
  va_start(ap, fmt);
  LogV(level, fmt, ap);
  va_end(ap);
}

}  // namespace fdfs
