#include "common/threadreg.h"

#include <stdio.h>
#include <string.h>
#include <sys/resource.h>
#include <sys/syscall.h>
#include <unistd.h>

#include "common/net.h"  // MonoUs
#include "common/stats.h"

namespace fdfs {

namespace {

// Mirror of the current thread's registered name for lock-free readers
// (profiler signal handler, slow-request logger).  Fixed buffer, not a
// std::string: the signal handler may read it mid-Leave, and a racing
// read must at worst see a truncated NUL-terminated name, never a
// freed heap pointer.
constexpr size_t kNameBufLen = 48;
thread_local char t_name[kNameBufLen] = {0};

// The calling thread's heartbeat cell.  A shared_ptr copy of the slot's
// cell, so the beat path stays valid even if Leave erases the slot on
// another code path while this thread is still unwinding.
thread_local std::shared_ptr<std::atomic<int64_t>> t_heartbeat;

int64_t TicksPerSecond() {
  static const int64_t hz = [] {
    long v = sysconf(_SC_CLK_TCK);
    return v > 0 ? static_cast<int64_t>(v) : 100;
  }();
  return hz;
}

}  // namespace

int CurrentTid() {
  static thread_local int tid = static_cast<int>(syscall(SYS_gettid));
  return tid;
}

const char* CurrentThreadName() { return t_name; }

void BeatThreadHeartbeat() {
  std::atomic<int64_t>* hb = t_heartbeat.get();
  if (hb != nullptr) hb->store(MonoUs(), std::memory_order_relaxed);
}

bool ReadThreadCpuTicks(int tid, int64_t* utime_ticks, int64_t* stime_ticks) {
  char path[64];
  snprintf(path, sizeof(path), "/proc/self/task/%d/stat", tid);
  FILE* f = fopen(path, "r");
  if (f != nullptr) {
    char buf[512];
    size_t n = fread(buf, 1, sizeof(buf) - 1, f);
    fclose(f);
    if (n > 0) {
      buf[n] = '\0';
      // comm (field 2) may contain spaces and parens; everything before
      // the LAST ')' is pid+comm, fields count from state after it.
      char* p = strrchr(buf, ')');
      if (p != nullptr) {
        ++p;
        // skip fields 3..13 (state .. cmajflt): 11 fields.
        long long ut = -1, st = -1;
        if (sscanf(p,
                   " %*c %*d %*d %*d %*d %*d %*u %*u %*u %*u %*u %llu %llu",
                   &ut, &st) == 2) {
          *utime_ticks = static_cast<int64_t>(ut);
          *stime_ticks = static_cast<int64_t>(st);
          return true;
        }
      }
    }
  }
  // /proc unavailable (or unparsable): RUSAGE_THREAD can still answer
  // for the CALLING thread — the documented fallback, so at least the
  // sampling thread's own row survives on /proc-less systems.
  if (tid == CurrentTid()) {
    struct rusage ru;
    if (getrusage(RUSAGE_THREAD, &ru) == 0) {
      int64_t hz = TicksPerSecond();
      *utime_ticks = (static_cast<int64_t>(ru.ru_utime.tv_sec) * 1000000 +
                      ru.ru_utime.tv_usec) * hz / 1000000;
      *stime_ticks = (static_cast<int64_t>(ru.ru_stime.tv_sec) * 1000000 +
                      ru.ru_stime.tv_usec) * hz / 1000000;
      return true;
    }
  }
  return false;
}

ThreadRegistry& ThreadRegistry::Global() {
  static ThreadRegistry* g = new ThreadRegistry();  // never destroyed:
  // daemon threads may outlive main()'s static teardown order.
  return *g;
}

int64_t ThreadRegistry::Join(const std::string& name) {
  int tid = CurrentTid();
  strncpy(t_name, name.c_str(), kNameBufLen - 1);
  t_name[kNameBufLen - 1] = '\0';
  auto hb = std::make_shared<std::atomic<int64_t>>(0);
  t_heartbeat = hb;
  std::lock_guard<RankedMutex> lk(mu_);
  int64_t id = next_id_++;
  Slot& s = slots_[id];
  s.name = name;
  s.tid = tid;
  s.heartbeat = std::move(hb);
  return id;
}

void ThreadRegistry::Leave(int64_t id) {
  t_name[0] = '\0';
  t_heartbeat.reset();
  std::lock_guard<RankedMutex> lk(mu_);
  slots_.erase(id);
}

std::vector<ThreadRegistry::Entry> ThreadRegistry::Entries() const {
  std::vector<Entry> out;
  std::lock_guard<RankedMutex> lk(mu_);
  out.reserve(slots_.size());
  for (const auto& [id, s] : slots_) out.push_back(Entry{s.name, s.tid});
  return out;
}

size_t ThreadRegistry::size() const {
  std::lock_guard<RankedMutex> lk(mu_);
  return slots_.size();
}

void ThreadRegistry::SampleInto(StatsRegistry* reg) {
  struct Reading {
    std::string name;
    int64_t cpu_pct = 0;
    int64_t utime_ms = 0;
    int64_t stime_ms = 0;
  };
  std::vector<Reading> readings;
  int64_t now_us = MonoUs();
  int64_t hz = TicksPerSecond();
  {
    // Sample under mu_ (the delta base lives in the slots), but never
    // with the stats-registry mutex held: gauges are written after
    // release (kThreadRegistry orders BEFORE kStatsRegistry).
    std::lock_guard<RankedMutex> lk(mu_);
    readings.reserve(slots_.size());
    for (auto& [id, s] : slots_) {
      int64_t ut = 0, st = 0;
      if (!ReadThreadCpuTicks(s.tid, &ut, &st)) continue;  // exiting thread
      Reading r;
      r.name = s.name;
      r.utime_ms = ut * 1000 / hz;
      r.stime_ms = st * 1000 / hz;
      int64_t cpu_ticks = ut + st;
      if (s.last_sample_us > 0 && now_us > s.last_sample_us) {
        int64_t dticks = cpu_ticks - s.last_cpu_ticks;
        int64_t dwall_us = now_us - s.last_sample_us;
        if (dticks < 0) dticks = 0;
        r.cpu_pct = dticks * 1000000 * 100 / hz / dwall_us;
        if (r.cpu_pct > 100) r.cpu_pct = 100;  // tick-granularity jitter
      }
      s.last_cpu_ticks = cpu_ticks;
      s.last_sample_us = now_us;
      readings.push_back(std::move(r));
    }
  }
  std::vector<std::string> keep;
  keep.reserve(readings.size());
  for (const Reading& r : readings) {
    std::string base = "thread." + r.name + ".";
    reg->SetGauge(base + "cpu_pct", r.cpu_pct);
    reg->SetGauge(base + "utime_ms", r.utime_ms);
    reg->SetGauge(base + "stime_ms", r.stime_ms);
    keep.push_back(std::move(base));
  }
  // Dead threads' gauges die with them (the sync.peer.* discipline:
  // bounded metric cardinality on a long-lived daemon).
  reg->PruneGauges("thread.", keep);
}

ThreadRegistry::WatchdogResult ThreadRegistry::WatchdogScan(
    int64_t threshold_us) {
  WatchdogResult out;
  int64_t now = MonoUs();
  std::lock_guard<RankedMutex> lk(mu_);
  for (auto& [id, s] : slots_) {
    if (!s.heartbeat) continue;
    int64_t beat = s.heartbeat->load(std::memory_order_relaxed);
    if (beat == 0) continue;  // never beaten: no heartbeat contract
    int64_t age = now - beat;
    if (age > threshold_us) {
      out.stalled.push_back(Stall{s.name, s.tid, age, !s.stalled_noted});
      s.stalled_noted = true;
    } else if (s.stalled_noted) {
      out.recovered.push_back(s.name);
      s.stalled_noted = false;
    }
  }
  return out;
}

std::vector<ThreadRegistry::HeartbeatEntry> ThreadRegistry::Heartbeats()
    const {
  std::vector<HeartbeatEntry> out;
  int64_t now = MonoUs();
  std::lock_guard<RankedMutex> lk(mu_);
  out.reserve(slots_.size());
  for (const auto& [id, s] : slots_) {
    HeartbeatEntry e;
    e.name = s.name;
    e.tid = s.tid;
    int64_t beat =
        s.heartbeat ? s.heartbeat->load(std::memory_order_relaxed) : 0;
    e.age_us = beat == 0 ? -1 : now - beat;
    out.push_back(std::move(e));
  }
  return out;
}

ScopedThreadName::ScopedThreadName(const std::string& name)
    : id_(ThreadRegistry::Global().Join(name)) {}

ScopedThreadName::~ScopedThreadName() { ThreadRegistry::Global().Leave(id_); }

}  // namespace fdfs
