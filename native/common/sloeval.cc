#include "common/sloeval.h"

#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace fdfs {

namespace {

// `op.<name>.count` / `op.<name>.errors` on storage; the tracker's
// aggregate `server.requests` / `server.errors` otherwise.  Summed so
// one rule definition serves both roles.
int64_t SumOps(const std::map<std::string, int64_t>& counters,
               const char* suffix) {
  int64_t n = 0;
  size_t slen = strlen(suffix);
  for (const auto& [name, v] : counters) {
    if (name.size() > 3 + slen && name.compare(0, 3, "op.") == 0 &&
        name.compare(name.size() - slen, slen, suffix) == 0)
      n += v;
  }
  return n;
}

int64_t Scalar(const std::map<std::string, int64_t>& m,
               const std::string& name, int64_t dflt = 0) {
  auto it = m.find(name);
  return it != m.end() ? it->second : dflt;
}

// Bucket-wise delta of every histogram whose name matches `match(name)`,
// merged into one distribution (all latency histograms share
// LatencyBucketsUs, so the merge is well-defined; a mismatched layout is
// skipped rather than corrupting the merge).
struct MergedDelta {
  std::vector<int64_t> bounds;
  std::vector<int64_t> counts;
  int64_t total = 0;
};

template <typename Match>
MergedDelta DeltaHists(const StatsSnapshot& prev, const StatsSnapshot& cur,
                       Match match) {
  MergedDelta out;
  for (const auto& [name, h] : cur.histograms) {
    if (!match(name)) continue;
    if (out.bounds.empty()) {
      out.bounds = h.bounds;
      out.counts.assign(h.counts.size(), 0);
    }
    if (h.bounds != out.bounds || h.counts.size() != out.counts.size())
      continue;
    auto pit = prev.histograms.find(name);
    const StatsSnapshot::Hist* ph =
        (pit != prev.histograms.end() && pit->second.bounds == h.bounds &&
         pit->second.counts.size() == h.counts.size())
            ? &pit->second
            : nullptr;
    for (size_t i = 0; i < h.counts.size(); ++i) {
      // Clamp at 0: a daemon restart between snapshots must read as "no
      // data", never as negative bucket mass (the monitor-side
      // hist_delta applies the same rule).
      int64_t d = h.counts[i] - (ph != nullptr ? ph->counts[i] : 0);
      if (d > 0) {
        out.counts[i] += d;
        out.total += d;
      }
    }
  }
  return out;
}

// Upper-bound p-quantile of a merged delta; overflow mass reads as 2x
// the last bound ("worse than the scale measures" must still breach).
bool DeltaQuantileUs(const MergedDelta& d, double q, double* out) {
  if (d.total <= 0 || d.bounds.empty()) return false;
  double rank = q * static_cast<double>(d.total);
  int64_t seen = 0;
  for (size_t i = 0; i < d.bounds.size(); ++i) {
    seen += d.counts[i];
    if (static_cast<double>(seen) >= rank) {
      *out = static_cast<double>(d.bounds[i]);
      return true;
    }
  }
  *out = 2.0 * static_cast<double>(d.bounds.back());
  return true;
}

double Fmt6g(double v, char* buf, size_t cap) {
  snprintf(buf, cap, "%.6g", v);
  return v;
}

}  // namespace

std::vector<SloRule> SloEvaluator::DefaultRules() {
  // threshold/clear pairs are the hysteresis band; rationale per rule in
  // OPERATIONS.md "Telemetry history, SLOs & heat".
  return {
      {"error_rate_pct", 5.0, 2.5, true},      // % of requests failing
      {"request_p99_ms", 1000.0, 500.0, true}, // op/server latency p99
      {"loop_lag_p99_ms", 250.0, 125.0, true}, // nio event-loop stall p99
      {"dio_wait_p99_ms", 500.0, 250.0, true}, // disk-queue wait p99
      {"sync_lag_s", 300.0, 150.0, true},      // replication staleness
      // "any unrepairable chunk": the gauge is an integer, so >= 1
      // exceeds 0.5 on the very first EWMA sample, and the alert clears
      // a few ticks after the count returns to 0.
      {"scrub_unrepairable", 0.5, 0.25, true},
      {"disk_fill_pct", 90.0, 85.0, true},     // fullest store path
      {"peer_rpc_p99_ms", 1000.0, 500.0, true}, // outbound peer RPC p99
      {"probe_write_ms", 1000.0, 500.0, true},  // worst store-path probe
  };
}

std::vector<SloRule> SloEvaluator::LoadRules(const IniConfig& ini) {
  std::vector<SloRule> rules = DefaultRules();
  auto get_double = [&ini](const std::string& key, double* out) {
    auto v = ini.Get(key);
    if (!v.has_value() || v->empty()) return false;
    char* end = nullptr;
    double d = strtod(v->c_str(), &end);
    if (end == v->c_str()) return false;
    *out = d;
    return true;
  };
  for (SloRule& r : rules) {
    double dflt_threshold = r.threshold, dflt_clear = r.clear;
    bool got_threshold = get_double(r.name + "_threshold", &r.threshold);
    bool got_clear = get_double(r.name + "_clear", &r.clear);
    if (got_threshold && !got_clear) {
      // Keep the hysteresis band proportional to the default's so a
      // one-key override cannot leave clear above the new threshold.
      r.clear = dflt_threshold > 0
                    ? r.threshold * (dflt_clear / dflt_threshold)
                    : dflt_clear;
    }
    if (r.clear > r.threshold) r.clear = r.threshold;
    r.enabled = ini.GetBool(r.name + "_enabled", r.enabled);
  }
  return rules;
}

bool SloEvaluator::ComputeReading(const std::string& name,
                                  const StatsSnapshot& prev,
                                  const StatsSnapshot& cur, double dt_s,
                                  double* out) {
  (void)dt_s;  // rules are ratios/quantiles/levels; rates divide here
  if (name == "error_rate_pct") {
    int64_t dops = (SumOps(cur.counters, ".count") +
                    Scalar(cur.counters, "server.requests")) -
                   (SumOps(prev.counters, ".count") +
                    Scalar(prev.counters, "server.requests"));
    int64_t derr = (SumOps(cur.counters, ".errors") +
                    Scalar(cur.counters, "server.errors")) -
                   (SumOps(prev.counters, ".errors") +
                    Scalar(prev.counters, "server.errors"));
    if (dops <= 0) return false;  // no traffic (or restart): skip tick
    if (derr < 0) return false;   // counter reset mid-window
    *out = 100.0 * static_cast<double>(derr) / static_cast<double>(dops);
    return true;
  }
  if (name == "request_p99_ms") {
    auto d = DeltaHists(prev, cur, [](const std::string& n) {
      return (n.compare(0, 3, "op.") == 0 &&
              n.size() > 11 &&
              n.compare(n.size() - 11, 11, ".latency_us") == 0) ||
             n == "server.request_us";
    });
    double us;
    if (!DeltaQuantileUs(d, 0.99, &us)) return false;
    *out = us / 1000.0;
    return true;
  }
  if (name == "loop_lag_p99_ms" || name == "dio_wait_p99_ms") {
    const char* hist = name == "loop_lag_p99_ms" ? "nio.loop_lag_us"
                                                 : "dio.queue_wait_us";
    auto d = DeltaHists(prev, cur,
                        [hist](const std::string& n) { return n == hist; });
    double us;
    if (!DeltaQuantileUs(d, 0.99, &us)) return false;
    *out = us / 1000.0;
    return true;
  }
  if (name == "sync_lag_s") {
    auto it = cur.gauges.find("sync.lag_s.max");
    if (it == cur.gauges.end()) return false;
    *out = static_cast<double>(it->second);
    return true;
  }
  if (name == "scrub_unrepairable") {
    auto it = cur.gauges.find("scrub.corrupt_unrepairable");
    if (it == cur.gauges.end()) return false;
    *out = static_cast<double>(it->second);
    return true;
  }
  if (name == "disk_fill_pct") {
    auto it = cur.gauges.find("store.disk_used_pct");
    if (it == cur.gauges.end()) return false;
    *out = static_cast<double>(it->second);
    return true;
  }
  if (name == "peer_rpc_p99_ms") {
    // Gray-failure health (ISSUE 17): p99 across every outbound peer
    // RPC this window (the health monitor observes each successful
    // NetRpc into peer.rpc_us).  Absent on the tracker — never fires.
    auto d = DeltaHists(prev, cur, [](const std::string& n) {
      return n == "peer.rpc_us";
    });
    double us;
    if (!DeltaQuantileUs(d, 0.99, &us)) return false;
    *out = us / 1000.0;
    return true;
  }
  if (name == "probe_write_ms") {
    // Worst store-path write+fsync probe this tick: the earliest signal
    // that a disk has gone gray (slow-but-not-dead) off the hot path.
    auto it = cur.gauges.find("store.probe_write_us");
    if (it == cur.gauges.end()) return false;
    *out = static_cast<double>(it->second) / 1000.0;
    return true;
  }
  return false;  // unknown rule name: never fires
}

SloEvaluator::SloEvaluator(std::vector<SloRule> rules, EventLog* events)
    : rules_spec_(rules), events_(events) {
  for (SloRule& r : rules) {
    RuleState st;
    st.rule = std::move(r);
    states_.push_back(std::move(st));
  }
}

bool SloEvaluator::IsBreached(const std::string& name) const {
  for (const RuleState& st : states_)
    if (st.rule.name == name) return st.breached;
  return false;
}

void SloEvaluator::Tick(const StatsSnapshot& prev, const StatsSnapshot& cur,
                        double dt_s) {
  int64_t active = 0;
  for (RuleState& st : states_) {
    if (!st.rule.enabled) continue;
    double reading;
    if (ComputeReading(st.rule.name, prev, cur, dt_s, &reading)) {
      st.ewma = st.have_ewma ? kAlpha * reading + (1.0 - kAlpha) * st.ewma
                             : reading;
      st.have_ewma = true;
      char vb[32], eb[32], tb[32];
      if (!st.breached && st.ewma > st.rule.threshold) {
        st.breached = true;
        transitions_.fetch_add(1, std::memory_order_relaxed);
        if (events_ != nullptr) {
          Fmt6g(reading, vb, sizeof(vb));
          Fmt6g(st.ewma, eb, sizeof(eb));
          Fmt6g(st.rule.threshold, tb, sizeof(tb));
          events_->Record(EventSeverity::kError, "slo.breach", st.rule.name,
                          std::string("value=") + vb + " ewma=" + eb +
                              " threshold=" + tb);
        }
      } else if (st.breached && st.ewma <= st.rule.clear) {
        st.breached = false;
        if (events_ != nullptr) {
          Fmt6g(reading, vb, sizeof(vb));
          Fmt6g(st.ewma, eb, sizeof(eb));
          Fmt6g(st.rule.clear, tb, sizeof(tb));
          events_->Record(EventSeverity::kInfo, "slo.recovered",
                          st.rule.name,
                          std::string("value=") + vb + " ewma=" + eb +
                              " clear=" + tb);
        }
      }
    }
    if (st.breached) ++active;
  }
  breaches_.store(active, std::memory_order_relaxed);
}

}  // namespace fdfs
