// Leveled logger (reference: libfastcommon logger.c — leveled, rotating;
// rotation is deferred to later rounds, level filtering + timestamps now).
#pragma once

#include <cstdarg>
#include <cstdio>
#include <string>

namespace fdfs {

enum class LogLevel : int { kDebug = 0, kInfo = 1, kWarn = 2, kError = 3 };

void LogSetLevel(LogLevel level);
void LogSetFile(const std::string& path);  // empty => stderr
LogLevel LogGetLevel();

void LogV(LogLevel level, const char* fmt, va_list ap);
void Log(LogLevel level, const char* fmt, ...)
    __attribute__((format(printf, 2, 3)));

#define FDFS_LOG_DEBUG(...) ::fdfs::Log(::fdfs::LogLevel::kDebug, __VA_ARGS__)
#define FDFS_LOG_INFO(...) ::fdfs::Log(::fdfs::LogLevel::kInfo, __VA_ARGS__)
#define FDFS_LOG_WARN(...) ::fdfs::Log(::fdfs::LogLevel::kWarn, __VA_ARGS__)
#define FDFS_LOG_ERROR(...) ::fdfs::Log(::fdfs::LogLevel::kError, __VA_ARGS__)

}  // namespace fdfs
