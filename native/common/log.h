// Leveled logger with size/day rotation (reference: libfastcommon
// logger.c — log_set_rotate_size / rotate_everyday).
#pragma once

#include <cstdarg>
#include <cstdio>
#include <string>

namespace fdfs {

enum class LogLevel : int { kDebug = 0, kInfo = 1, kWarn = 2, kError = 3 };

void LogSetLevel(LogLevel level);
void LogSetFile(const std::string& path);  // empty => stderr
// Rotation policy for the file sink: rotate when the file exceeds
// max_bytes (0 = no size rotation) or when the calendar day changes
// (daily = true).  The old file is renamed <path>.<YYYYMMDD-HHMMSS>.
void LogSetRotation(int64_t max_bytes, bool daily = true);
// Convenience used by both daemons: empty log_file = keep stderr;
// relative paths land under <base_path>/logs/.
void LogSetupFileSink(const std::string& base_path,
                      const std::string& log_file, int64_t rotate_size);
LogLevel LogGetLevel();

void LogV(LogLevel level, const char* fmt, va_list ap);
void Log(LogLevel level, const char* fmt, ...)
    __attribute__((format(printf, 2, 3)));

#define FDFS_LOG_DEBUG(...) ::fdfs::Log(::fdfs::LogLevel::kDebug, __VA_ARGS__)
#define FDFS_LOG_INFO(...) ::fdfs::Log(::fdfs::LogLevel::kInfo, __VA_ARGS__)
#define FDFS_LOG_WARN(...) ::fdfs::Log(::fdfs::LogLevel::kWarn, __VA_ARGS__)
#define FDFS_LOG_ERROR(...) ::fdfs::Log(::fdfs::LogLevel::kError, __VA_ARGS__)

}  // namespace fdfs
