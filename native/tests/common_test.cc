// Unit tests for the C++ common layer (no gtest in the image — plain
// CHECK macros; non-zero exit on failure).
#include <cassert>
#include <cstdio>
#include <cstring>
#include <string>

#include "common/bytes.h"
#include "common/fileid.h"
#include "common/ini.h"
#include "common/protocol_gen.h"
#include "common/stats.h"

static int g_failures = 0;

#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      ++g_failures;                                                   \
    }                                                                 \
  } while (0)

#define CHECK_EQ(a, b) CHECK((a) == (b))

using namespace fdfs;

static void TestEndian() {
  uint8_t buf[8];
  PutInt64BE(0x0102030405060708LL, buf);
  CHECK_EQ(buf[0], 1);
  CHECK_EQ(buf[7], 8);
  CHECK_EQ(GetInt64BE(buf), 0x0102030405060708LL);
  PutInt64BE(-1, buf);
  CHECK_EQ(GetInt64BE(buf), -1);
}

static void TestBase64() {
  const uint8_t data[] = {0xDE, 0xAD, 0xBE, 0xEF, 0x00};
  std::string enc = Base64UrlEncode(data, sizeof(data));
  std::string dec;
  CHECK(Base64UrlDecode(enc, &dec));
  CHECK_EQ(dec.size(), sizeof(data));
  CHECK_EQ(std::memcmp(dec.data(), data, sizeof(data)), 0);
  CHECK(!Base64UrlDecode("a+b", &dec));  // '+' not in url-safe alphabet
  CHECK(!Base64UrlDecode("abcde", &dec));  // impossible length (5 % 4 == 1)
}

static void TestCrc32() {
  // zlib golden: crc32(b"123456789") == 0xCBF43926
  CHECK_EQ(Crc32("123456789", 9), 0xCBF43926u);
  CHECK_EQ(Crc32("", 0), 0u);
}

static void TestSha1() {
  CHECK_EQ(Sha1("abc", 3).Hex(),
           std::string("a9993e364706816aba3e25717850c26c9cd0d89d"));
  CHECK_EQ(Sha1("", 0).Hex(),
           std::string("da39a3ee5e6b4b0d3255bfef95601890afd80709"));
  // streamed == one-shot across buffer boundaries
  std::string big(1000, 'x');
  Sha1Stream s;
  s.Update(big.data(), 37);
  s.Update(big.data() + 37, 63);
  s.Update(big.data() + 100, 900);
  CHECK_EQ(s.Final().Hex(), Sha1(big.data(), big.size()).Hex());
}

static void TestFileId() {
  EncodeFileIdArgs a;
  a.group = "group1";
  a.store_path_index = 0;
  a.source_ip = PackIp("192.168.1.102");
  a.create_timestamp = 1406000000;
  a.file_size = 30790;
  a.crc32 = 0xFCEFEF3Cu;
  a.ext = "jpg";
  a.uniquifier = 42;
  auto id = EncodeFileId(a);
  CHECK(id.has_value());
  auto parts = DecodeFileId(*id);
  CHECK(parts.has_value());
  CHECK_EQ(parts->group, std::string("group1"));
  CHECK_EQ(UnpackIp(parts->source_ip), std::string("192.168.1.102"));
  CHECK_EQ(parts->create_timestamp, 1406000000u);
  CHECK_EQ(parts->file_size, 30790u);
  CHECK_EQ(parts->crc32, 0xFCEFEF3Cu);
  CHECK_EQ(parts->uniquifier, 42);
  CHECK(!parts->appender);
  CHECK_EQ(parts->FullId(), *id);

  // flags
  a.appender = true;
  auto id2 = EncodeFileId(a);
  auto p2 = DecodeFileId(*id2);
  CHECK(p2.has_value() && p2->appender);

  // tampering
  std::string bad = *id;
  bad[bad.size() - 5] = bad[bad.size() - 5] == 'A' ? 'B' : 'A';
  CHECK(!DecodeFileId(bad).has_value());

  // invalid encode args
  EncodeFileIdArgs e = a;
  e.group = "this-group-name-is-way-too-long";
  CHECK(!EncodeFileId(e).has_value());
  e = a;
  e.ext = "tar.gz";
  CHECK(!EncodeFileId(e).has_value());
  e = a;
  e.uniquifier = 0x1000;
  CHECK(!EncodeFileId(e).has_value());
}

static void TestLocalPath() {
  EncodeFileIdArgs a;
  a.group = "g";
  a.source_ip = PackIp("1.2.3.4");
  a.create_timestamp = 1;
  a.file_size = 2;
  a.crc32 = 3;
  a.ext = "txt";
  auto id = EncodeFileId(a);
  auto parts = DecodeFileId(*id);
  auto lp = LocalPath("/var/p0", parts->RemoteFilename());
  CHECK(lp.has_value());
  CHECK(lp->rfind("/var/p0/data/", 0) == 0);
  CHECK(!LocalPath("/var/p0", "M00/../../passwd").has_value());
  CHECK(!LocalPath("/var/p0", "M00/00/00/../../../etc/passwd").has_value());
  CHECK(!LocalPath("/var/p0", "no/such/shape/x").has_value());
}

static void TestIni() {
  IniConfig cfg;
  std::string err;
  CHECK(cfg.LoadString(
      "# comment\nport = 22122\ndisabled=false\n"
      "tracker_server = 10.0.0.1:22122\ntracker_server = 10.0.0.2:22122\n"
      "buff_size = 256KB\ninterval = 5m\n[section]\nname=x\n",
      &err));
  CHECK_EQ(cfg.GetInt("port", 0), 22122);
  CHECK(!cfg.GetBool("disabled", true));
  CHECK_EQ(cfg.GetAll("tracker_server").size(), 2u);
  CHECK_EQ(cfg.GetBytes("buff_size", 0), 256 * 1024);
  CHECK_EQ(cfg.GetSeconds("interval", 0), 300);
  CHECK_EQ(cfg.GetStr("name", ""), std::string("x"));
  CHECK(!cfg.Has("nope"));
  IniConfig inc;
  CHECK(!inc.LoadString("#include other.conf\n", &err));  // no base dir
}

static void TestProtocolConstants() {
  CHECK_EQ(static_cast<int>(TrackerCmd::kStorageJoin), 81);
  CHECK_EQ(static_cast<int>(TrackerCmd::kServiceQueryStoreWithoutGroupOne), 101);
  CHECK_EQ(static_cast<int>(StorageCmd::kUploadFile), 11);
  CHECK_EQ(static_cast<int>(StorageCmd::kResp), 100);
  CHECK_EQ(static_cast<int>(StorageCmd::kStat), 130);
  CHECK_EQ(static_cast<int>(TrackerCmd::kServerClusterStat), 95);
  CHECK_EQ(kHeaderSize, 10);
  // Beat-blob naming contract: one name per slot, the named headline
  // stats present (the Python side asserts the same list).
  CHECK_EQ(kBeatStatCount, 28);
  CHECK_EQ(std::string(kBeatStatNames[0]), std::string("total_upload"));
  CHECK_EQ(std::string(kBeatStatNames[17]),
           std::string("dedup_bytes_saved"));
  CHECK_EQ(std::string(kBeatStatNames[21]), std::string("sync_lag_s"));
  CHECK_EQ(std::string(kBeatStatNames[23]),
           std::string("recovery_chunks_fetched"));
}

static void TestStatsRegistry() {
  StatsRegistry reg;
  reg.Counter("a.count")->fetch_add(3);
  CHECK_EQ(reg.Counter("a.count")->load(), 3);  // find-or-create finds
  reg.SetGauge("g", 42);
  reg.GaugeFn("g.fn", [] { return int64_t{7}; });
  StatHistogram* h = reg.Histogram("h", {10, 100, 1000});
  h->Observe(5);
  h->Observe(10);    // inclusive upper bound: first bucket
  h->Observe(11);    // second bucket
  h->Observe(5000);  // overflow
  CHECK_EQ(h->count(), 4);
  CHECK_EQ(h->sum(), 5 + 10 + 11 + 5000);
  CHECK_EQ(h->bucket_count(0), 2);
  CHECK_EQ(h->bucket_count(1), 1);
  CHECK_EQ(h->bucket_count(2), 0);
  CHECK_EQ(h->bucket_count(3), 1);
  std::string json = reg.Json();
  // Shape spot-checks (the full field-for-field check is the
  // cross-language golden test via `fdfs_codec stats-json`).
  CHECK(json.find("\"counters\":{\"a.count\":3}") != std::string::npos);
  CHECK(json.find("\"g\":42") != std::string::npos);
  CHECK(json.find("\"g.fn\":7") != std::string::npos);
  CHECK(json.find("\"bounds\":[10,100,1000]") != std::string::npos);
  CHECK(json.find("\"counts\":[2,1,0,1]") != std::string::npos);
  CHECK(json.find("\"sum\":5026") != std::string::npos);
}

int main() {
  TestEndian();
  TestBase64();
  TestCrc32();
  TestSha1();
  TestFileId();
  TestLocalPath();
  TestIni();
  TestProtocolConstants();
  TestStatsRegistry();
  if (g_failures == 0) {
    std::printf("common_test: ALL PASS\n");
    return 0;
  }
  std::printf("common_test: %d FAILURES\n", g_failures);
  return 1;
}
