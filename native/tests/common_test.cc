// Unit tests for the C++ common layer (no gtest in the image — plain
// CHECK macros; non-zero exit on failure).
#include <signal.h>
#include <stdlib.h>
#include <sys/stat.h>
#include <sys/wait.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cassert>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "common/bytes.h"
#include "common/eventlog.h"
#include "common/fileid.h"
#include "common/healthmon.h"
#include "common/heatsketch.h"
#include "common/ini.h"
#include "common/lockrank.h"
#include "common/metrog.h"
#include "common/net.h"
#include "common/protocol_gen.h"
#include "common/sloeval.h"
#include "common/stats.h"
#include "common/profiler.h"
#include "common/threadreg.h"
#include "common/trace.h"
#include "common/workers.h"

static int g_failures = 0;

#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      ++g_failures;                                                   \
    }                                                                 \
  } while (0)

#define CHECK_EQ(a, b) CHECK((a) == (b))

using namespace fdfs;

static void TestEndian() {
  uint8_t buf[8];
  PutInt64BE(0x0102030405060708LL, buf);
  CHECK_EQ(buf[0], 1);
  CHECK_EQ(buf[7], 8);
  CHECK_EQ(GetInt64BE(buf), 0x0102030405060708LL);
  PutInt64BE(-1, buf);
  CHECK_EQ(GetInt64BE(buf), -1);
}

static void TestBase64() {
  const uint8_t data[] = {0xDE, 0xAD, 0xBE, 0xEF, 0x00};
  std::string enc = Base64UrlEncode(data, sizeof(data));
  std::string dec;
  CHECK(Base64UrlDecode(enc, &dec));
  CHECK_EQ(dec.size(), sizeof(data));
  CHECK_EQ(std::memcmp(dec.data(), data, sizeof(data)), 0);
  CHECK(!Base64UrlDecode("a+b", &dec));  // '+' not in url-safe alphabet
  CHECK(!Base64UrlDecode("abcde", &dec));  // impossible length (5 % 4 == 1)
}

static void TestCrc32() {
  // zlib golden: crc32(b"123456789") == 0xCBF43926
  CHECK_EQ(Crc32("123456789", 9), 0xCBF43926u);
  CHECK_EQ(Crc32("", 0), 0u);
}

static void TestSha1() {
  CHECK_EQ(Sha1("abc", 3).Hex(),
           std::string("a9993e364706816aba3e25717850c26c9cd0d89d"));
  CHECK_EQ(Sha1("", 0).Hex(),
           std::string("da39a3ee5e6b4b0d3255bfef95601890afd80709"));
  // streamed == one-shot across buffer boundaries
  std::string big(1000, 'x');
  Sha1Stream s;
  s.Update(big.data(), 37);
  s.Update(big.data() + 37, 63);
  s.Update(big.data() + 100, 900);
  CHECK_EQ(s.Final().Hex(), Sha1(big.data(), big.size()).Hex());
}

static void TestFileId() {
  EncodeFileIdArgs a;
  a.group = "group1";
  a.store_path_index = 0;
  a.source_ip = PackIp("192.168.1.102");
  a.create_timestamp = 1406000000;
  a.file_size = 30790;
  a.crc32 = 0xFCEFEF3Cu;
  a.ext = "jpg";
  a.uniquifier = 42;
  auto id = EncodeFileId(a);
  CHECK(id.has_value());
  auto parts = DecodeFileId(*id);
  CHECK(parts.has_value());
  CHECK_EQ(parts->group, std::string("group1"));
  CHECK_EQ(UnpackIp(parts->source_ip), std::string("192.168.1.102"));
  CHECK_EQ(parts->create_timestamp, 1406000000u);
  CHECK_EQ(parts->file_size, 30790u);
  CHECK_EQ(parts->crc32, 0xFCEFEF3Cu);
  CHECK_EQ(parts->uniquifier, 42);
  CHECK(!parts->appender);
  CHECK_EQ(parts->FullId(), *id);

  // flags
  a.appender = true;
  auto id2 = EncodeFileId(a);
  auto p2 = DecodeFileId(*id2);
  CHECK(p2.has_value() && p2->appender);

  // tampering
  std::string bad = *id;
  bad[bad.size() - 5] = bad[bad.size() - 5] == 'A' ? 'B' : 'A';
  CHECK(!DecodeFileId(bad).has_value());

  // invalid encode args
  EncodeFileIdArgs e = a;
  e.group = "this-group-name-is-way-too-long";
  CHECK(!EncodeFileId(e).has_value());
  e = a;
  e.ext = "tar.gz";
  CHECK(!EncodeFileId(e).has_value());
  e = a;
  e.uniquifier = 0x1000;
  CHECK(!EncodeFileId(e).has_value());
}

static void TestLocalPath() {
  EncodeFileIdArgs a;
  a.group = "g";
  a.source_ip = PackIp("1.2.3.4");
  a.create_timestamp = 1;
  a.file_size = 2;
  a.crc32 = 3;
  a.ext = "txt";
  auto id = EncodeFileId(a);
  auto parts = DecodeFileId(*id);
  auto lp = LocalPath("/var/p0", parts->RemoteFilename());
  CHECK(lp.has_value());
  CHECK(lp->rfind("/var/p0/data/", 0) == 0);
  CHECK(!LocalPath("/var/p0", "M00/../../passwd").has_value());
  CHECK(!LocalPath("/var/p0", "M00/00/00/../../../etc/passwd").has_value());
  CHECK(!LocalPath("/var/p0", "no/such/shape/x").has_value());
}

static void TestIni() {
  IniConfig cfg;
  std::string err;
  CHECK(cfg.LoadString(
      "# comment\nport = 22122\ndisabled=false\n"
      "tracker_server = 10.0.0.1:22122\ntracker_server = 10.0.0.2:22122\n"
      "buff_size = 256KB\ninterval = 5m\n[section]\nname=x\n",
      &err));
  CHECK_EQ(cfg.GetInt("port", 0), 22122);
  CHECK(!cfg.GetBool("disabled", true));
  CHECK_EQ(cfg.GetAll("tracker_server").size(), 2u);
  CHECK_EQ(cfg.GetBytes("buff_size", 0), 256 * 1024);
  CHECK_EQ(cfg.GetSeconds("interval", 0), 300);
  CHECK_EQ(cfg.GetStr("name", ""), std::string("x"));
  CHECK(!cfg.Has("nope"));
  IniConfig inc;
  CHECK(!inc.LoadString("#include other.conf\n", &err));  // no base dir
}

static void TestProtocolConstants() {
  CHECK_EQ(static_cast<int>(TrackerCmd::kStorageJoin), 81);
  CHECK_EQ(static_cast<int>(TrackerCmd::kServiceQueryStoreWithoutGroupOne), 101);
  CHECK_EQ(static_cast<int>(StorageCmd::kUploadFile), 11);
  CHECK_EQ(static_cast<int>(StorageCmd::kResp), 100);
  CHECK_EQ(static_cast<int>(StorageCmd::kStat), 130);
  CHECK_EQ(static_cast<int>(TrackerCmd::kServerClusterStat), 95);
  CHECK_EQ(kHeaderSize, 10);
  // Beat-blob naming contract: one name per slot, the named headline
  // stats present (the Python side asserts the same list).
  CHECK_EQ(kBeatStatCount, 33);
  CHECK_EQ(std::string(kBeatStatNames[0]), std::string("total_upload"));
  CHECK_EQ(std::string(kBeatStatNames[17]),
           std::string("dedup_bytes_saved"));
  CHECK_EQ(std::string(kBeatStatNames[21]), std::string("sync_lag_s"));
  CHECK_EQ(std::string(kBeatStatNames[23]),
           std::string("recovery_chunks_fetched"));
  CHECK_EQ(std::string(kBeatStatNames[28]),
           std::string("rebalance_files_moved"));
  CHECK_EQ(std::string(kBeatStatNames[32]), std::string("rebalance_done"));
}

static void TestStatsRegistry() {
  StatsRegistry reg;
  reg.Counter("a.count")->fetch_add(3);
  CHECK_EQ(reg.Counter("a.count")->load(), 3);  // find-or-create finds
  reg.SetGauge("g", 42);
  reg.GaugeFn("g.fn", [] { return int64_t{7}; });
  StatHistogram* h = reg.Histogram("h", {10, 100, 1000});
  h->Observe(5);
  h->Observe(10);    // inclusive upper bound: first bucket
  h->Observe(11);    // second bucket
  h->Observe(5000);  // overflow
  CHECK_EQ(h->count(), 4);
  CHECK_EQ(h->sum(), 5 + 10 + 11 + 5000);
  CHECK_EQ(h->bucket_count(0), 2);
  CHECK_EQ(h->bucket_count(1), 1);
  CHECK_EQ(h->bucket_count(2), 0);
  CHECK_EQ(h->bucket_count(3), 1);
  std::string json = reg.Json();
  // Shape spot-checks (the full field-for-field check is the
  // cross-language golden test via `fdfs_codec stats-json`).
  CHECK(json.find("\"counters\":{\"a.count\":3}") != std::string::npos);
  CHECK(json.find("\"g\":42") != std::string::npos);
  CHECK(json.find("\"g.fn\":7") != std::string::npos);
  CHECK(json.find("\"bounds\":[10,100,1000]") != std::string::npos);
  CHECK(json.find("\"counts\":[2,1,0,1]") != std::string::npos);
  CHECK(json.find("\"sum\":5026") != std::string::npos);
}

static void TestTraceCtxWire() {
  // Wire layout golden: 8B trace_id + 4B parent + 4B flags, big-endian —
  // must match fastdfs_tpu.common.protocol.pack_trace_ctx byte-for-byte.
  const uint8_t raw[16] = {0x01, 0x02, 0x03, 0x04, 0x05, 0x06, 0x07, 0x08,
                           0xAA, 0xBB, 0xCC, 0xDD, 0x00, 0x00, 0x00, 0x03};
  TraceCtx c = ParseTraceCtx(raw);
  CHECK_EQ(c.trace_id, 0x0102030405060708ULL);
  CHECK_EQ(c.parent_span, 0xAABBCCDDu);
  CHECK_EQ(c.flags, 3u);
  CHECK(c.valid());
  uint8_t back[16];
  SerializeTraceCtx(c, back);
  CHECK_EQ(std::memcmp(raw, back, 16), 0);
  CHECK(!TraceCtx{}.valid());
  CHECK_EQ(static_cast<int>(StorageCmd::kTraceCtx),
           static_cast<int>(TrackerCmd::kTraceCtx));  // shared framing
  CHECK_EQ(static_cast<int>(StorageCmd::kTraceDump), 131);
  CHECK_EQ(static_cast<int>(TrackerCmd::kTraceDump), 96);
}

static void TestTraceRing() {
  TraceRing ring(4);
  uint32_t a = ring.NextSpanId(), b = ring.NextSpanId();
  CHECK(a != b && a != 0 && b != 0);
  CHECK(ring.NewTraceId() != ring.NewTraceId());
  for (int i = 0; i < 6; ++i) {  // wraps: 6 records into 4 slots
    TraceSpan s;
    s.trace_id = 0xABC0ULL + i;
    s.span_id = static_cast<uint32_t>(i + 1);
    s.start_us = 1000 + i;
    s.dur_us = 10;
    s.SetName(i % 2 ? "storage.recv" : "storage.upload_file");
    ring.Record(s);
  }
  CHECK_EQ(ring.recorded(), 6);
  CHECK_EQ(ring.dropped(), 2);
  std::string json = ring.Json("storage", 23000);
  CHECK(json.find("\"role\":\"storage\"") != std::string::npos);
  CHECK(json.find("\"port\":23000") != std::string::npos);
  // Oldest two overwritten; newest four present, sorted by start_us.
  CHECK(json.find("\"start_us\":1000,") == std::string::npos);
  CHECK(json.find("\"start_us\":1005,") != std::string::npos);
  size_t p2 = json.find("\"start_us\":1002");
  size_t p5 = json.find("\"start_us\":1005");
  CHECK(p2 != std::string::npos && p2 < p5);
  // Long names truncate, never overflow.
  TraceSpan longname;
  longname.trace_id = 1;
  longname.SetName("this-name-is-way-longer-than-the-forty-byte-span-field");
  CHECK_EQ(std::strlen(longname.name), sizeof(longname.name) - 1);
}

static void TestTraceRingThreaded() {
  // Lock-light claim: concurrent recorders + a dumping reader must be
  // data-race-free (tools/run_sanitizers.sh runs this under TSan).
  TraceRing ring(64);
  std::vector<std::thread> threads;
  for (int t = 0; t < 4; ++t) {
    threads.emplace_back([&ring, t] {
      for (int i = 0; i < 500; ++i) {
        TraceSpan s;
        s.trace_id = static_cast<uint64_t>(t) << 32 | i;
        s.span_id = ring.NextSpanId();
        s.start_us = i;
        s.dur_us = 1;
        s.SetName("storage.upload_file");
        ring.Record(s);
      }
    });
  }
  std::thread reader([&ring] {
    for (int i = 0; i < 50; ++i) (void)ring.Json("storage", 1);
  });
  for (auto& th : threads) th.join();
  reader.join();
  CHECK_EQ(ring.recorded(), 4 * 500);
  CHECK(ring.Json("storage", 1).find("\"spans\":[") != std::string::npos);
}

static void TestTraceCorrelator() {
  TraceCorrelator corr(2);
  TraceCtx c1{1, 10, 1}, c2{2, 20, 1}, c3{3, 30, 1}, out;
  corr.Put("M00/a", c1);
  corr.Put("M00/b", c2);
  corr.Put("M00/c", c3);  // evicts the oldest (M00/a)
  CHECK_EQ(corr.size(), 2u);
  CHECK(!corr.Take("M00/a", &out));
  CHECK(corr.Take("M00/b", &out));
  CHECK_EQ(out.trace_id, 2ULL);
  CHECK(!corr.Take("M00/b", &out));  // Take consumes
  CHECK(corr.Take("M00/c", &out));
  CHECK_EQ(corr.size(), 0u);
}

static void TestEventLog() {
  EventLog log(4);
  log.Record(EventSeverity::kWarn, "chunk.quarantined", "digest1", "spi=0");
  log.Record(EventSeverity::kInfo, "chunk.repaired", "digest1");
  std::string json = log.Json("storage", 23000);
  CHECK(json.find("\"role\":\"storage\"") != std::string::npos);
  CHECK(json.find("\"type\":\"chunk.quarantined\"") != std::string::npos);
  CHECK(json.find("\"severity\":\"warn\"") != std::string::npos);
  CHECK(json.find("\"seq\":1") != std::string::npos);
  CHECK_EQ(log.recorded(), 2);
  CHECK_EQ(log.dropped(), 0);
  // Ring wrap: capacity 4, record 6 — the oldest 2 are overwritten and
  // the dump holds exactly seqs 3..6 in order.
  for (int i = 0; i < 4; ++i)
    log.Record(EventSeverity::kError, "gc.sweep", "M00",
               "n=" + std::to_string(i));
  CHECK_EQ(log.recorded(), 6);
  CHECK_EQ(log.dropped(), 2);
  json = log.Json("storage", 23000);
  CHECK(json.find("\"seq\":1,") == std::string::npos);
  CHECK(json.find("\"seq\":3") != std::string::npos);
  CHECK(json.find("\"seq\":6") != std::string::npos);
  // Hostile bytes in key/detail must still serialize as valid JSON
  // (escaped), and over-long fields truncate instead of overflowing.
  EventLog esc(2);
  esc.Record(EventSeverity::kInfo, "config.anomaly", "a\"b\\c\nd",
             std::string(500, 'x'));
  json = esc.Json("tracker", 22122);
  CHECK(json.find("a\\\"b\\\\c\\nd") != std::string::npos);
  CHECK(json.find(std::string(127, 'x') + "\"") != std::string::npos);
}

static void TestEventLogThreaded() {
  // Lock-light claim: concurrent recorders + a dumping reader must be
  // data-race-free (tools/run_sanitizers.sh runs this under TSan) —
  // the flight-recorder twin of TestTraceRingThreaded.
  EventLog log(64);
  std::vector<std::thread> threads;
  for (int t = 0; t < 4; ++t) {
    threads.emplace_back([&log, t] {
      for (int i = 0; i < 500; ++i)
        log.Record(EventSeverity::kInfo, "request.slow",
                   "t" + std::to_string(t), "i=" + std::to_string(i));
    });
  }
  std::thread reader([&log] {
    for (int i = 0; i < 50; ++i) (void)log.Json("storage", 1);
  });
  for (auto& th : threads) th.join();
  reader.join();
  CHECK_EQ(log.recorded(), 4 * 500);
  CHECK(log.Json("storage", 1).find("\"events\":[") != std::string::npos);
}

static void TestEventLoopLagHook() {
  // The iteration hook must observe the time spent inside callbacks: a
  // deliberately-slow posted task shows up as loop lag >= its sleep.
  EventLoop loop;
  StatsRegistry reg;
  StatHistogram* lag = reg.Histogram("nio.loop_lag_us",
                                     StatsRegistry::LatencyBucketsUs());
  std::atomic<int64_t> dispatched{0};
  loop.set_iteration_hook([&](int64_t busy_us, int n_events) {
    lag->Observe(busy_us);
    dispatched.fetch_add(n_events);
  });
  loop.Post([] { usleep(20 * 1000); });
  loop.Post([&loop] { loop.Stop(); });
  loop.Run();
  CHECK(lag->count() >= 1);
  CHECK(lag->sum() >= 20000);  // the 20 ms stall is visible as lag
}

static void TestWorkerPoolQueueStats() {
  StatsRegistry reg;
  StatHistogram* wait = reg.Histogram("dio.queue_wait_us",
                                      StatsRegistry::LatencyBucketsUs());
  StatHistogram* service = reg.Histogram("dio.service_us",
                                         StatsRegistry::LatencyBucketsUs());
  WorkerPool pool(1);
  pool.SetStats(wait, service);
  // One slow task at the head of a 1-thread pool: the tasks behind it
  // must observe queue wait >= its service time.
  pool.Submit([] { usleep(30 * 1000); });
  for (int i = 0; i < 3; ++i) pool.Submit([] {});
  pool.Stop();  // drain-then-join
  CHECK_EQ(service->count(), 4);
  CHECK_EQ(wait->count(), 4);
  CHECK(service->sum() >= 30000);
  CHECK(wait->sum() >= 30000);  // the queued tasks sat behind the sleeper
}

static void TestStatsRegistryPruneGauges() {
  StatsRegistry reg;
  reg.SetGauge("sync.peer.10.0.0.2:23000.lag_s", 4);
  reg.SetGauge("sync.peer.10.0.0.2:23000.connected", 1);
  reg.SetGauge("sync.peer.10.0.0.3:23000.lag_s", 9);
  reg.SetGauge("server.connections", 2);  // outside the prefix: untouched
  // Peer .3 left the group: prune everything under sync.peer. except
  // the surviving peer's family.
  int removed = reg.PruneGauges("sync.peer.",
                                {"sync.peer.10.0.0.2:23000."});
  CHECK_EQ(removed, 1);
  std::string json = reg.Json();
  CHECK(json.find("10.0.0.3") == std::string::npos);
  CHECK(json.find("sync.peer.10.0.0.2:23000.lag_s") != std::string::npos);
  CHECK(json.find("server.connections") != std::string::npos);
  // Re-appearing peer just re-registers (SetGauge is find-or-create).
  reg.SetGauge("sync.peer.10.0.0.3:23000.lag_s", 1);
  CHECK(reg.Json().find("10.0.0.3") != std::string::npos);
}


// -- lock-rank discipline (common/lockrank.h) ------------------------------

static void TestRankedMutex() {
  // Ascending-rank acquisition is legal and balances the held stack.
  RankedMutex outer(LockRank::kScrub);
  RankedMutex inner(LockRank::kLog);
  {
    std::lock_guard<RankedMutex> a(outer);
    std::lock_guard<RankedMutex> b(inner);
    if (kLockRankEnforced) CHECK_EQ(lockrank_detail::HeldCount(), 2);
  }
  if (kLockRankEnforced) CHECK_EQ(lockrank_detail::HeldCount(), 0);
  // try_lock participates in the held stack like lock().
  CHECK(outer.try_lock());  // NOLINT(lock-guard-discipline): testing the wrapper
  if (kLockRankEnforced) CHECK_EQ(lockrank_detail::HeldCount(), 1);
  outer.unlock();  // NOLINT(lock-guard-discipline)
  // Same-rank ASCENDING order keys: the RefAll stripe protocol.
  RankedMutex s2(LockRank::kChunkStripe, 2);
  RankedMutex s5(LockRank::kChunkStripe, 5);
  {
    std::unique_lock<RankedMutex> lk2(s2);
    std::unique_lock<RankedMutex> lk5(s5);
    // Out-of-order RELEASE is fine — only acquisition order is ranked.
    lk2.unlock();
  }
  if (kLockRankEnforced) CHECK_EQ(lockrank_detail::HeldCount(), 0);
  CHECK_EQ(std::string(LockRankName(LockRank::kChunkStripe)),
           "chunkstore.stripe");
}

static void TestRankedMutexThreaded() {
  // 4 threads hammer a correctly-ordered two-lock chain plus a ranked
  // spinlock; the TSan leg proves the checker's thread_local
  // bookkeeping (and the spinlock's acquire/release) is race-free, and
  // the counters prove mutual exclusion still holds through the wrapper.
  RankedMutex a(LockRank::kStatsRegistry);
  RankedMutex b(LockRank::kWorkers);
  RankedSpinLock s(LockRank::kTraceSlot);
  int both = 0;
  int spun = 0;
  std::vector<std::thread> threads;
  for (int t = 0; t < 4; ++t) {
    threads.emplace_back([&] {
      for (int i = 0; i < 1000; ++i) {
        std::lock_guard<RankedMutex> la(a);
        std::lock_guard<RankedMutex> lb(b);
        ++both;
        SpinGuard g(s);
        ++spun;
      }
    });
  }
  for (auto& th : threads) th.join();
  CHECK_EQ(both, 4 * 1000);
  CHECK_EQ(spun, 4 * 1000);
}

// Death-test driver: re-exec THIS binary with a violation flag (fork +
// exec keeps the child single-threaded at birth, which the sanitizer
// runtimes require), expect SIGABRT, and expect BOTH lock sites in the
// report.
static void ExpectChildAborts(const char* exe, const char* flag,
                              const char* expect_a, const char* expect_b) {
  int fds[2];
  CHECK_EQ(pipe(fds), 0);
  pid_t pid = fork();
  CHECK(pid >= 0);
  if (pid == 0) {
    dup2(fds[1], 2);
    close(fds[0]);
    close(fds[1]);
    execl(exe, exe, flag, static_cast<char*>(nullptr));
    _exit(127);
  }
  close(fds[1]);
  std::string err;
  char buf[4096];
  ssize_t r;
  while ((r = read(fds[0], buf, sizeof(buf))) > 0)
    err.append(buf, static_cast<size_t>(r));
  close(fds[0]);
  int st = 0;
  waitpid(pid, &st, 0);
  if (!(WIFSIGNALED(st) && WTERMSIG(st) == SIGABRT)) {
    std::fprintf(stderr, "FAIL %s: child (%s) did not SIGABRT; stderr:\n%s\n",
                 __FILE__, flag, err.c_str());
    ++g_failures;
    return;
  }
  CHECK(err.find(expect_a) != std::string::npos);
  CHECK(err.find(expect_b) != std::string::npos);
  CHECK(err.find("held by this thread") != std::string::npos);
}

static void TestRankedMutexInversionAborts(const char* exe) {
  if (!kLockRankEnforced) {
    std::printf("common_test: lockrank death tests SKIPPED "
                "(build without -DFDFS_LOCKRANK)\n");
    return;
  }
  // A thread acquiring a LOWER rank while holding a higher one must
  // abort, reporting the acquiring lock AND the held stack.
  ExpectChildAborts(exe, "--lockrank-inversion",
                    "chunkstore.stripe", "log.global");
  // The RefAll protocol specifically: same rank, DESCENDING stripe
  // keys must abort even though ascending is sanctioned.
  ExpectChildAborts(exe, "--lockrank-stripe-descend",
                    "ascending", "chunkstore.stripe");
  // Recursive acquisition of one instance is a deadlock in production;
  // the checker turns it into a deterministic abort.
  ExpectChildAborts(exe, "--lockrank-recursive",
                    "recursive", "sync.manager");
}

// Child-process violation bodies (reached only via the flags above).
static int RunLockRankViolation(const char* flag) {
  if (std::strcmp(flag, "--lockrank-inversion") == 0) {
    RankedMutex hi(LockRank::kLog);
    RankedMutex lo(LockRank::kChunkStripe);
    std::thread t([&] {
      std::lock_guard<RankedMutex> a(hi);
      std::lock_guard<RankedMutex> b(lo);  // rank 90 under rank 210: abort
    });
    t.join();
  } else if (std::strcmp(flag, "--lockrank-stripe-descend") == 0) {
    RankedMutex s5(LockRank::kChunkStripe, 5);
    RankedMutex s2(LockRank::kChunkStripe, 2);
    std::lock_guard<RankedMutex> a(s5);
    std::lock_guard<RankedMutex> b(s2);  // descending keys: abort
  } else if (std::strcmp(flag, "--lockrank-recursive") == 0) {
    RankedMutex m(LockRank::kSync);
    m.lock();  // NOLINT(lock-guard-discipline): deliberate violation
    // On a checked build the second lock aborts in PushOrDie BEFORE
    // touching the std::mutex; on an unchecked build it would be a
    // genuine self-deadlock, so only attempt it when enforced.
    if (kLockRankEnforced)
      m.lock();  // NOLINT(lock-guard-discipline): recursive; checker aborts
    m.unlock();  // NOLINT(lock-guard-discipline)
  } else {
    std::fprintf(stderr, "unknown flag %s\n", flag);
    return 2;
  }
  // Only reachable when FDFS_LOCKRANK is compiled out.
  std::printf("no abort\n");
  return 0;
}

// ---------------------------------------------------------------------------
// Metrics journal (common/metrog.h)
// ---------------------------------------------------------------------------

static StatsSnapshot MakeSnap(int64_t ops, int64_t errs, int64_t conns,
                              std::vector<int64_t> lat_counts) {
  StatsSnapshot s;
  s.counters["op.upload_file.count"] = ops;
  s.counters["op.upload_file.errors"] = errs;
  s.gauges["server.connections"] = conns;
  StatsSnapshot::Hist h;
  h.bounds = {100, 1000, 10000};
  h.counts = std::move(lat_counts);
  h.count = 0;
  for (int64_t c : h.counts) h.count += c;
  h.sum = h.count * 10;
  s.histograms["op.upload_file.latency_us"] = h;
  return s;
}

static void TestMetricsRecordCodec() {
  // Full -> delta -> delta chain with a new series, a tombstone, and
  // histogram growth; DecodeBuffer must reconstruct absolutes exactly.
  StatsSnapshot s1 = MakeSnap(10, 1, 3, {5, 2, 0, 0});
  s1.gauges["sync.peer.10.0.0.2:23000.lag_s"] = 7;
  StatsSnapshot s2 = MakeSnap(25, 1, 4, {5, 12, 3, 1});
  s2.counters["op.download_file.count"] = 9;  // appears mid-stream
  StatsSnapshot s3 = s2;                       // unchanged tick
  std::string buf = MetricsJournal::EncodeRecord(nullptr, s1, 111);
  buf += MetricsJournal::EncodeRecord(&s1, s2, 222);
  buf += MetricsJournal::EncodeRecord(&s2, s3, 333);
  size_t valid = 0;
  auto recs = MetricsJournal::DecodeBuffer(buf, &valid);
  CHECK_EQ(valid, buf.size());
  CHECK_EQ(recs.size(), 3u);
  CHECK_EQ(recs[0].first, 111);
  CHECK(recs[0].second.counters == s1.counters);
  CHECK(recs[0].second.gauges == s1.gauges);
  CHECK(recs[1].second.counters == s2.counters);
  // the pruned peer gauge died with the delta's tombstone
  CHECK_EQ(recs[1].second.gauges.count("sync.peer.10.0.0.2:23000.lag_s"), 0u);
  CHECK_EQ(recs[1].second.histograms["op.upload_file.latency_us"].count, 21);
  CHECK_EQ(recs[1].second.histograms["op.upload_file.latency_us"].counts[1],
           12);
  CHECK(recs[2].second.counters == s3.counters);

  // Torn tail: any truncation point inside the last frame drops exactly
  // that record and keeps the prefix.
  std::string torn = buf.substr(0, buf.size() - 3);
  auto recs2 = MetricsJournal::DecodeBuffer(torn, &valid);
  CHECK_EQ(recs2.size(), 2u);
  CHECK(valid < torn.size());
  // Corrupt one payload byte of the middle record: CRC rejects it and
  // the scan stops there (a delta chain cannot skip records).
  std::string flip = buf;
  size_t first_len = MetricsJournal::EncodeRecord(nullptr, s1, 111).size();
  flip[first_len + 20] ^= 0x5A;
  auto recs3 = MetricsJournal::DecodeBuffer(flip, &valid);
  CHECK_EQ(recs3.size(), 1u);

  // Retention cap: only the NEWEST max_records snapshots are kept, the
  // whole buffer still scans (valid covers every frame), and the
  // survivors are exact absolutes even though their delta bases were
  // dropped from the result.
  auto recs4 = MetricsJournal::DecodeBuffer(buf, &valid, 2);
  CHECK_EQ(valid, buf.size());
  CHECK_EQ(recs4.size(), 2u);
  CHECK_EQ(recs4[0].first, 222);
  CHECK_EQ(recs4[1].first, 333);
  CHECK(recs4[0].second.counters == s2.counters);
  CHECK(recs4[1].second.counters == s3.counters);
}

static void TestMetricsJournalDiskAndTornTail() {
  char tmpl[] = "/tmp/fdfs_metrog_XXXXXX";
  CHECK(mkdtemp(tmpl) != nullptr);
  std::string dir = tmpl;
  std::string err;
  {
    MetricsJournal j(dir, 1 << 20);
    CHECK(j.Open(&err));
    for (int i = 1; i <= 5; ++i)
      j.Append(1000 + i, MakeSnap(i * 10, i, i, {static_cast<int64_t>(i),
                                                 0, 0, 0}));
    CHECK_EQ(j.appended(), 5);
    auto recs = j.Decode(0);
    CHECK_EQ(recs.size(), 5u);
    CHECK_EQ(recs[4].second.counters["op.upload_file.count"], 50);
    // since-filter: only the records at/after the cut
    CHECK_EQ(j.Decode(1004).size(), 2u);
  }
  // kill -9 analogue: chop bytes off the journal tail, reopen, and the
  // intact prefix must survive while appends keep working.
  std::string path = dir + "/metrics.mj";
  struct stat st;
  CHECK_EQ(stat(path.c_str(), &st), 0);
  CHECK_EQ(truncate(path.c_str(), st.st_size - 5), 0);
  {
    MetricsJournal j(dir, 1 << 20);
    CHECK(j.Open(&err));
    CHECK(j.recovered_bytes() > 0);
    auto recs = j.Decode(0);
    CHECK_EQ(recs.size(), 4u);  // the torn record is gone, prefix intact
    CHECK_EQ(recs[3].second.counters["op.upload_file.count"], 40);
    // post-recovery appends start with a fresh full record
    j.Append(2000, MakeSnap(99, 9, 9, {1, 1, 1, 1}));
    auto recs2 = j.Decode(0);
    CHECK_EQ(recs2.size(), 5u);
    CHECK_EQ(recs2[4].second.counters["op.upload_file.count"], 99);
  }
  // Rotation: a tiny cap (clamped to 64 KB; rotate past 32 KB) with fat
  // records must rotate without losing decodability, and total retained
  // bytes must stay near the cap.
  {
    std::string dir2 = dir + "/rot";
    MetricsJournal j(dir2, 1);  // clamps to 64 KB
    CHECK(j.Open(&err));
    for (int tick = 0; tick < 6; ++tick) {
      StatsSnapshot s;
      for (int k = 0; k < 3000; ++k)
        s.gauges["g." + std::to_string(k)] = tick * 3000 + k;
      j.Append(5000 + tick, s);
    }
    auto recs = j.Decode(0);
    CHECK(!recs.empty());
    CHECK_EQ(recs.back().first, 5005);
    CHECK_EQ(recs.back().second.gauges.at("g.2999"), 5 * 3000 + 2999);
    CHECK(j.bytes_retained() <= (128 << 10));
  }
}

// ---------------------------------------------------------------------------
// SLO evaluator (common/sloeval.h)
// ---------------------------------------------------------------------------

static void TestSloReadings() {
  StatsSnapshot prev = MakeSnap(100, 0, 3, {10, 0, 0, 0});
  StatsSnapshot cur = MakeSnap(200, 10, 3, {10, 0, 99, 1});
  double v = 0;
  CHECK(SloEvaluator::ComputeReading("error_rate_pct", prev, cur, 1.0, &v));
  CHECK_EQ(static_cast<int64_t>(v), 10);  // 10 errors / 100 ops
  CHECK(SloEvaluator::ComputeReading("request_p99_ms", prev, cur, 1.0, &v));
  CHECK_EQ(static_cast<int64_t>(v * 1000), 10000);  // p99 bucket <=10000us
  // Overflow mass reads as 2x the last bound — still a breach signal.
  StatsSnapshot over = MakeSnap(300, 10, 3, {10, 0, 99, 50});
  CHECK(SloEvaluator::ComputeReading("request_p99_ms", cur, over, 1.0, &v));
  CHECK_EQ(static_cast<int64_t>(v * 1000), 20000);
  // No traffic in the window: the reading is unavailable, not zero.
  CHECK(!SloEvaluator::ComputeReading("error_rate_pct", cur, cur, 1.0, &v));
  // Gauge rules read current levels.
  cur.gauges["scrub.corrupt_unrepairable"] = 2;
  CHECK(SloEvaluator::ComputeReading("scrub_unrepairable", prev, cur, 1, &v));
  CHECK_EQ(static_cast<int64_t>(v), 2);
  CHECK(!SloEvaluator::ComputeReading("disk_fill_pct", prev, cur, 1, &v));
}

static void TestSloHysteresis() {
  EventLog log(32);
  SloEvaluator slo({{"error_rate_pct", 5.0, 2.5, true}}, &log);
  auto snap_at = [](int64_t ops, int64_t errs) {
    StatsSnapshot s;
    s.counters["op.x.count"] = ops;
    s.counters["op.x.errors"] = errs;
    return s;
  };
  StatsSnapshot a = snap_at(0, 0), b = snap_at(100, 50);
  slo.Tick(a, b, 1.0);  // reading 50% -> ewma 50 -> breach
  CHECK(slo.IsBreached("error_rate_pct"));
  CHECK_EQ(slo.breaches_active(), 1);
  CHECK_EQ(slo.breach_transitions(), 1);
  // One clean tick must NOT clear it (ewma 25 > clear 2.5): no flap.
  StatsSnapshot c = snap_at(200, 50);
  slo.Tick(b, c, 1.0);
  CHECK(slo.IsBreached("error_rate_pct"));
  // Sustained clean traffic decays the EWMA below clear -> recovered.
  StatsSnapshot last = c;
  for (int i = 0; i < 5; ++i) {
    StatsSnapshot next = last;
    next.counters["op.x.count"] += 100;
    slo.Tick(last, next, 1.0);
    last = next;
  }
  CHECK(!slo.IsBreached("error_rate_pct"));
  CHECK_EQ(slo.breaches_active(), 0);
  // Exactly one breach + one recovered event, in order.
  std::string dump = log.Json("storage", 1);
  CHECK(dump.find("slo.breach") != std::string::npos);
  CHECK(dump.find("slo.recovered") != std::string::npos);
  CHECK_EQ(log.recorded(), 2);
}

static void TestSloRuleOverrides() {
  IniConfig ini;
  std::string err;
  CHECK(ini.LoadString("error_rate_pct_threshold = 1.0\n"
                       "request_p99_ms_enabled = 0\n"
                       "disk_fill_pct_threshold = 70\n"
                       "disk_fill_pct_clear = 60\n",
                       &err));
  auto rules = SloEvaluator::LoadRules(ini);
  bool saw_err = false, saw_p99 = false, saw_disk = false;
  for (const SloRule& r : rules) {
    if (r.name == "error_rate_pct") {
      saw_err = true;
      CHECK_EQ(static_cast<int64_t>(r.threshold * 10), 10);
      // clear rescaled proportionally (default 5/2.5 -> 1/0.5)
      CHECK_EQ(static_cast<int64_t>(r.clear * 10), 5);
    }
    if (r.name == "request_p99_ms") {
      saw_p99 = true;
      CHECK(!r.enabled);
    }
    if (r.name == "disk_fill_pct") {
      saw_disk = true;
      CHECK_EQ(static_cast<int64_t>(r.threshold), 70);
      CHECK_EQ(static_cast<int64_t>(r.clear), 60);
    }
  }
  CHECK(saw_err && saw_p99 && saw_disk);
}

// ---------------------------------------------------------------------------
// Heat sketch (common/heatsketch.h)
// ---------------------------------------------------------------------------

static void TestHeatSketchExactWhenUnderCapacity() {
  // Below capacity the sketch IS exact: counts, bytes, per-op splits,
  // zero error bound.
  HeatSketch sketch(8, 1);
  for (int i = 0; i < 7; ++i) sketch.Touch("hot", HeatOp::kDownload, 10, false);
  sketch.Touch("hot", HeatOp::kUpload, 100, false);
  sketch.Touch("warm", HeatOp::kDownload, 5, false);
  sketch.Touch("warm", HeatOp::kDownload, 0, true);  // one error
  auto top = sketch.Top(2);
  CHECK_EQ(top.size(), 2u);
  CHECK_EQ(top[0].key, std::string("hot"));
  CHECK_EQ(top[0].hits, 8);
  CHECK_EQ(top[0].err_bound, 0);
  CHECK_EQ(top[0].bytes, 170);
  CHECK_EQ(top[0].op_count[0], 7);
  CHECK_EQ(top[0].op_count[1], 1);
  CHECK_EQ(top[1].key, std::string("warm"));
  CHECK_EQ(top[1].hits, 2);
  CHECK_EQ(top[1].err, 1);
  // JSON shape smoke (full decode parity lives in the codec golden)
  std::string js = sketch.TopJson("storage", 23000, 1);
  CHECK(js.find("\"entries\":[{\"key\":\"hot\"") != std::string::npos);
  CHECK(js.find("\"download\":{\"count\":7,\"bytes\":70}") !=
        std::string::npos);
}

static void TestHeatSketchAccuracy() {
  // Zipf-ish synthetic under real eviction pressure: a 64-key universe
  // against 16x4 tracked slots.  The space-saving theorems must hold:
  // hits is an overcount bounded by err_bound (hits >= true >=
  // hits - err_bound), the true hottest key ranks first, and the exact
  // top-5 surfaces in the sketch's top-5 (the acceptance bar the live
  // test applies to HEAT_TOP under load_cli --zipf).
  HeatSketch sketch(16, 4);
  std::vector<int64_t> truth(64);
  for (int i = 0; i < 64; ++i) truth[i] = 1000 / (i + 1);
  // interleave rounds so eviction pressure is realistic, not sorted
  for (int round = 0; round < 1000; ++round)
    for (int i = 0; i < 64; ++i)
      if (round < truth[i])
        sketch.Touch("group1/M00/k" + std::to_string(i), HeatOp::kDownload,
                     100, false);
  int64_t total = 0;
  for (int64_t t : truth) total += t;
  CHECK_EQ(sketch.touches(), total);
  auto top = sketch.Top(5);
  CHECK_EQ(top.size(), 5u);
  std::vector<std::string> top_keys;
  for (const auto& e : top) top_keys.push_back(e.key);
  for (int i = 0; i < 5; ++i) {
    // exact top-5 ⊆ sketch top-5 (both are 5 long, so sets match)
    std::string want = "group1/M00/k" + std::to_string(i);
    CHECK(std::find(top_keys.begin(), top_keys.end(), want) !=
          top_keys.end());
  }
  CHECK_EQ(top[0].key, std::string("group1/M00/k0"));
  for (const auto& e : top) {
    int idx = atoi(e.key.c_str() + strlen("group1/M00/k"));
    CHECK(e.hits >= truth[idx]);                  // never undercounts
    CHECK(e.hits - e.err_bound <= truth[idx]);    // honest error bound
  }
}

static void TestHeatSketchThreaded() {
  // TSan target: concurrent touchers on overlapping keys + a Top reader.
  HeatSketch sketch(32, 4);
  std::atomic<bool> stop{false};
  std::thread reader([&] {
    while (!stop.load()) (void)sketch.TopJson("storage", 1, 8);
  });
  std::vector<std::thread> writers;
  for (int t = 0; t < 4; ++t) {
    writers.emplace_back([&sketch, t] {
      for (int i = 0; i < 20000; ++i)
        sketch.Touch("k" + std::to_string((i * (t + 1)) % 97),
                     static_cast<HeatOp>(i % kHeatOpCount), i % 512,
                     i % 50 == 0);
    });
  }
  for (auto& w : writers) w.join();
  stop.store(true);
  reader.join();
  CHECK_EQ(sketch.touches(), 4 * 20000);
  auto top = sketch.Top(0);
  CHECK(!top.empty());
  int64_t hits = 0;
  for (const auto& e : top) hits += e.hits;
  CHECK(hits >= 4 * 20000 / 2);  // bounded undercount from evictions only
}

// -- thread ledger & profiler ---------------------------------------------

static void TestThreadRegistryBasics() {
  fdfs::ThreadRegistry& reg = fdfs::ThreadRegistry::Global();
  size_t before = reg.size();
  CHECK(std::string(fdfs::CurrentThreadName()).empty());
  {
    fdfs::ScopedThreadName ledger("test.main");
    CHECK(std::string(fdfs::CurrentThreadName()) == "test.main");
    CHECK(reg.size() == before + 1);
    // /proc read for our own tid must succeed and report sane ticks.
    int64_t ut = -1, st = -1;
    CHECK(fdfs::ReadThreadCpuTicks(fdfs::CurrentTid(), &ut, &st));
    CHECK(ut >= 0 && st >= 0);
  }
  CHECK(reg.size() == before);
  CHECK(std::string(fdfs::CurrentThreadName()).empty());
}

static void TestThreadRegistrySampleThreaded() {
  // Named threads burn CPU; SampleInto must publish each one's gauges
  // and prune them after the threads leave.
  fdfs::StatsRegistry stats;
  std::atomic<bool> stop{false};
  std::atomic<int> ready{0};
  auto burner = [&](const char* name) {
    fdfs::ScopedThreadName ledger(name);
    ready.fetch_add(1);
    volatile uint64_t sink = 0;
    while (!stop.load()) sink += sink * 31 + 7;
  };
  std::thread t1(burner, "unit.burn/0");
  std::thread t2(burner, "unit.burn/1");
  while (ready.load() < 2) std::this_thread::yield();
  fdfs::ThreadRegistry::Global().SampleInto(&stats);
  // Second sample after measurable CPU so cpu_pct has a delta window.
  std::this_thread::sleep_for(std::chrono::milliseconds(120));
  fdfs::ThreadRegistry::Global().SampleInto(&stats);
  fdfs::StatsSnapshot snap;
  stats.Snapshot(&snap);
  for (const char* name : {"unit.burn/0", "unit.burn/1"}) {
    std::string base = std::string("thread.") + name + ".";
    CHECK(snap.gauges.count(base + "cpu_pct") == 1);
    CHECK(snap.gauges.count(base + "utime_ms") == 1);
    CHECK(snap.gauges.count(base + "stime_ms") == 1);
    int64_t pct = snap.gauges[base + "cpu_pct"];
    CHECK(pct >= 0 && pct <= 100);
  }
  // A spinning thread over a 120ms window must show real CPU on at
  // least one of its rows (scheduler noise can zero one of them).
  CHECK(snap.gauges["thread.unit.burn/0.cpu_pct"] +
            snap.gauges["thread.unit.burn/1.cpu_pct"] >
        0);
  stop.store(true);
  t1.join();
  t2.join();
  fdfs::ThreadRegistry::Global().SampleInto(&stats);
  fdfs::StatsSnapshot after;
  stats.Snapshot(&after);
  for (const auto& [name, v] : after.gauges)
    CHECK(name.rfind("thread.unit.burn", 0) != 0);
}

static void TestProfilerGateAndCapture() {
  fdfs::Profiler& prof = fdfs::Profiler::Global();
  // Feature off (profile_max_hz = 0): refuse to arm, dump ENOTSUP.
  CHECK(prof.max_hz() == 0);
  CHECK(prof.Start(97, 1) == 95);
  CHECK(!prof.ever_started());
  std::string out;
  CHECK(prof.DumpJson("test", 0, &out) == 95);

  prof.set_max_hz(200);
  CHECK(prof.Start(0, 1) == 22);
  CHECK(prof.Start(97, 0) == 22);

  // Real capture: burn CPU under an armed window, then dump.
  CHECK(prof.Start(500, 2) == 0);  // asked above the cap:
  CHECK(prof.armed_hz() == 200);   // ...clamped to profile_max_hz
  CHECK(prof.active());
  volatile uint64_t sink = 0;
  int64_t until = fdfs::MonoUs() + 300 * 1000;
  while (fdfs::MonoUs() < until) sink += sink * 31 + 7;
  CHECK(prof.Stop() == 0);
  CHECK(!prof.active());
  int64_t got = prof.samples();
  CHECK(got > 0);  // 200 Hz over 300ms of pure spin: samples must land
  CHECK(prof.DumpJson("test", 123, &out) == 0);
  CHECK(out.find("\"role\":\"test\"") != std::string::npos);
  CHECK(out.find("\"port\":123") != std::string::npos);
  CHECK(out.find("\"stacks\":[") != std::string::npos);
  CHECK(out.find("\"active\":false") != std::string::npos);
  // Stop is idempotent; re-arm resets the window.
  CHECK(prof.Stop() == 0);
  CHECK(prof.Start(100, 1) == 0);
  CHECK(prof.samples() <= got);  // counters reset on re-arm
  CHECK(prof.Stop() == 0);
}

static void TestProfilerCtlHammerAgainstLiveThreads() {
  // Signal-safety hammer: spinning threads receive SIGPROF while the
  // control path arms/disarms/dumps concurrently.  The assertion is
  // survival (no deadlock, no crash, no torn slab) — TSan and the
  // lock-rank checker judge the rest.
  fdfs::Profiler& prof = fdfs::Profiler::Global();
  prof.set_max_hz(500);
  std::atomic<bool> stop{false};
  std::vector<std::thread> burners;
  for (int i = 0; i < 3; ++i)
    burners.emplace_back([&stop, i] {
      fdfs::ScopedThreadName ledger("hammer.burn/" + std::to_string(i));
      volatile uint64_t sink = 0;
      while (!stop.load()) sink += sink * 131 + 17;
    });
  for (int round = 0; round < 25; ++round) {
    CHECK(prof.Start(500, 2) == 0);
    std::this_thread::sleep_for(std::chrono::milliseconds(2));
    if (round % 3 == 0) {
      std::string out;
      CHECK(prof.DumpJson("test", 0, &out) == 0);
      CHECK(!out.empty() && out.front() == '{' && out.back() == '}');
    }
    if (round % 2 == 0) CHECK(prof.Stop() == 0);
  }
  CHECK(prof.Stop() == 0);
  stop.store(true);
  for (auto& t : burners) t.join();
  // Leave the singleton disarmed-but-gated-off for any later test.
  prof.set_max_hz(0);
}

// -- gray-failure health layer (common/healthmon.h) ------------------------

static void TestHealthMonitorScoresAndTrailer() {
  HealthMonitor& hm = HealthMonitor::Global();
  hm.Reset();
  // No peers and no self signal yet: the beat stays trailerless (old
  // trackers see exactly the pre-health wire).
  CHECK(hm.PackBeatTrailer().empty());

  // Self score: each stalled thread costs 50; a probe past the
  // threshold costs 50, past 4x costs 75; clamped to [0, 100].
  hm.SetStalledThreads(0);
  hm.SetProbe(1500, 2500, 1000);  // 2.5ms probes, 1s threshold: clean
  CHECK_EQ(hm.SelfScore(), 100);
  hm.SetProbe(1500, 2500000, 1000);  // 2.5s write probe: gray disk
  CHECK_EQ(hm.SelfScore(), 50);
  hm.SetProbe(1500, 4100000, 1000);  // > 4x threshold: hard-degraded
  CHECK_EQ(hm.SelfScore(), 25);
  hm.SetStalledThreads(2);
  CHECK_EQ(hm.SelfScore(), 0);  // 100 - 100 - 75, clamped

  // The codec-golden fixture (tools/codec_cli.cc health-status and
  // tests/test_health.py assert the same arithmetic).
  hm.SetStalledThreads(1);
  hm.SetProbe(1500, 2500, 1000);
  for (int i = 0; i < 3; ++i)
    hm.Feed("10.0.0.2:23000", "fetch", true, 50000, 1000);
  hm.Feed("10.0.0.2:23000", "fetch", false, 950000, 1000);  // timeout-shaped
  hm.Feed("10.0.0.2:23000", "beat", true, 2000, 2000);
  hm.Feed("10.0.0.2:23000", "beat", true, 2000, 2000);
  hm.Feed("10.0.0.9:23001", "probe", false, 100, 2000);  // fast hard fail
  // fetch: 100 - round(.2*60) - round(.2*40) - 50ms latency penalty = 75;
  // beat stays 100; the composite per peer is the MIN across op classes.
  CHECK_EQ(hm.PeerScore("10.0.0.2:23000"), 75);
  CHECK_EQ(hm.PeerScore("10.0.0.9:23001"), 88);  // errors only, no latency
  CHECK_EQ(hm.PeerScore("1.2.3.4:1"), -1);       // never seen

  auto rows = hm.Snapshot();
  CHECK_EQ(rows.size(), 3u);  // (addr, op)-sorted
  CHECK(rows[0].addr == "10.0.0.2:23000" && rows[0].op == "beat");
  CHECK_EQ(rows[0].score, 100);
  CHECK_EQ(rows[0].ops, 2);
  CHECK(rows[1].op == "fetch");
  CHECK_EQ(rows[1].score, 75);
  CHECK_EQ(rows[1].rpc_ewma_us, 50000);  // failures never move latency
  CHECK_EQ(rows[1].error_pct, 20);
  CHECK_EQ(rows[1].timeout_pct, 20);
  CHECK(rows[1].ops == 4 && rows[1].errors == 1 && rows[1].timeouts == 1);
  CHECK(rows[2].addr == "10.0.0.9:23001" && rows[2].op == "probe");
  CHECK_EQ(rows[2].score, 88);
  CHECK(rows[2].errors == 1 && rows[2].timeouts == 0);

  // Beat-trailer roundtrip: 1B version + 8B self + 8B n + n x 32B.
  std::string t = hm.PackBeatTrailer();
  CHECK_EQ(t.size(), static_cast<size_t>(17 + 2 * 32));
  BeatHealthTrailer ht;
  CHECK(ParseBeatHealthTrailer(t.data(), t.size(), &ht));
  CHECK_EQ(ht.self_score, 50);
  CHECK_EQ(ht.peers.size(), 2u);
  CHECK(ht.peers[0].first == "10.0.0.2:23000" && ht.peers[0].second == 75);
  CHECK(ht.peers[1].first == "10.0.0.9:23001" && ht.peers[1].second == 88);
  std::string bad = t;
  bad[0] = 9;  // unknown version: refuse, don't guess
  CHECK(!ParseBeatHealthTrailer(bad.data(), bad.size(), &ht));
  CHECK(!ParseBeatHealthTrailer(t.data(), 16, &ht));          // short header
  CHECK(!ParseBeatHealthTrailer(t.data(), t.size() - 1, &ht));  // torn entry

  // Gauges publish per ADDR (min score across ops) and prune on Reset.
  StatsRegistry reg;
  hm.PublishGauges(&reg);
  std::string json = reg.Json();
  CHECK(json.find("\"peer.10.0.0.2:23000.score\":75") != std::string::npos);
  CHECK(json.find("\"peer.10.0.0.9:23001.score\":88") != std::string::npos);
  CHECK(json.find("\"health.score\":50") != std::string::npos);
  hm.Reset();
  hm.PublishGauges(&reg);
  CHECK(reg.Json().find("peer.10.0.0.2") == std::string::npos);

  // Op-class bucketing: the opcode -> class mapping is part of the
  // cross-language contract (mirrored in the health-status golden).
  CHECK(std::string(HealthMonitor::OpClassFor(111)) == "probe");
  CHECK(std::string(HealthMonitor::OpClassFor(83)) == "beat");
  CHECK(std::string(HealthMonitor::OpClassFor(129)) == "fetch");
  CHECK(std::string(HealthMonitor::OpClassFor(145)) == "ec");
  CHECK(std::string(HealthMonitor::OpClassFor(16)) == "sync");
  CHECK(std::string(HealthMonitor::OpClassFor(11)) == "rpc");

  CHECK(hm.PackBeatTrailer().empty());  // Reset cleared the self signal
}

static void TestThreadRegistryWatchdog() {
  ThreadRegistry& tr = ThreadRegistry::Global();
  std::atomic<bool> stop{false};
  std::atomic<bool> do_beat{false};
  std::thread victim([&] {
    ScopedThreadName ledger("watchdog.victim");
    BeatThreadHeartbeat();
    while (!stop.load()) {
      if (do_beat.exchange(false)) BeatThreadHeartbeat();
      usleep(2000);
    }
  });
  // A never-beating thread has NO heartbeat contract: the watchdog must
  // not enroll it (false-positive-free by construction).
  std::atomic<bool> stop_quiet{false};
  std::thread quiet([&] {
    ScopedThreadName ledger("watchdog.quiet");
    while (!stop_quiet.load()) usleep(2000);
  });
  usleep(60 * 1000);  // victim's last beat is now ~60ms old
  ThreadRegistry::WatchdogResult wd = tr.WatchdogScan(30 * 1000);
  bool victim_stalled = false, victim_newly = false, quiet_stalled = false;
  for (const ThreadRegistry::Stall& s : wd.stalled) {
    if (s.name == "watchdog.victim") {
      victim_stalled = true;
      victim_newly = s.newly;
      CHECK(s.age_us >= 30 * 1000);
    }
    if (s.name == "watchdog.quiet") quiet_stalled = true;
  }
  CHECK(victim_stalled && victim_newly);
  CHECK(!quiet_stalled);
  // Second scan: still stalled, but no longer NEW (one event per outage).
  wd = tr.WatchdogScan(30 * 1000);
  victim_newly = true;
  for (const ThreadRegistry::Stall& s : wd.stalled)
    if (s.name == "watchdog.victim") victim_newly = s.newly;
  CHECK(!victim_newly);
  // The thread beats again: the outage ends and is reported ONCE.
  do_beat.store(true);
  for (int i = 0; i < 100 && do_beat.load(); ++i) usleep(2000);
  wd = tr.WatchdogScan(30 * 1000);
  bool recovered = false;
  for (const std::string& n : wd.recovered)
    if (n == "watchdog.victim") recovered = true;
  CHECK(recovered);
  for (const ThreadRegistry::Stall& s : wd.stalled)
    CHECK(s.name != "watchdog.victim");
  // Heartbeats(): the DumpState ledger view — victim has an age, the
  // never-beating thread reads -1.
  bool saw_victim = false, saw_quiet = false;
  for (const ThreadRegistry::HeartbeatEntry& h : tr.Heartbeats()) {
    if (h.name == "watchdog.victim") {
      saw_victim = true;
      CHECK(h.age_us >= 0);
    }
    if (h.name == "watchdog.quiet") {
      saw_quiet = true;
      CHECK_EQ(h.age_us, -1);
    }
  }
  CHECK(saw_victim && saw_quiet);
  stop.store(true);
  stop_quiet.store(true);
  victim.join();
  quiet.join();
}

int main(int argc, char** argv) {
  if (argc > 1 && std::strncmp(argv[1], "--lockrank-", 11) == 0)
    return RunLockRankViolation(argv[1]);

  TestEndian();
  TestBase64();
  TestCrc32();
  TestSha1();
  TestFileId();
  TestLocalPath();
  TestIni();
  TestProtocolConstants();
  TestStatsRegistry();
  TestTraceCtxWire();
  TestTraceRing();
  TestTraceRingThreaded();
  TestTraceCorrelator();
  TestEventLog();
  TestEventLogThreaded();
  TestEventLoopLagHook();
  TestWorkerPoolQueueStats();
  TestStatsRegistryPruneGauges();
  TestRankedMutex();
  TestRankedMutexThreaded();
  TestRankedMutexInversionAborts(argv[0]);
  TestMetricsRecordCodec();
  TestMetricsJournalDiskAndTornTail();
  TestSloReadings();
  TestSloHysteresis();
  TestSloRuleOverrides();
  TestHeatSketchExactWhenUnderCapacity();
  TestHeatSketchAccuracy();
  TestHeatSketchThreaded();
  TestThreadRegistryBasics();
  TestThreadRegistrySampleThreaded();
  TestProfilerGateAndCapture();
  TestProfilerCtlHammerAgainstLiveThreads();
  TestHealthMonitorScoresAndTrailer();
  TestThreadRegistryWatchdog();
  if (g_failures == 0) {
    std::printf("common_test: ALL PASS\n");
    return 0;
  }
  std::printf("common_test: %d FAILURES\n", g_failures);
  return 1;
}
