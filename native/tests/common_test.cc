// Unit tests for the C++ common layer (no gtest in the image — plain
// CHECK macros; non-zero exit on failure).
#include <signal.h>
#include <sys/wait.h>
#include <unistd.h>

#include <atomic>
#include <cassert>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "common/bytes.h"
#include "common/eventlog.h"
#include "common/fileid.h"
#include "common/ini.h"
#include "common/lockrank.h"
#include "common/net.h"
#include "common/protocol_gen.h"
#include "common/stats.h"
#include "common/trace.h"
#include "common/workers.h"

static int g_failures = 0;

#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      ++g_failures;                                                   \
    }                                                                 \
  } while (0)

#define CHECK_EQ(a, b) CHECK((a) == (b))

using namespace fdfs;

static void TestEndian() {
  uint8_t buf[8];
  PutInt64BE(0x0102030405060708LL, buf);
  CHECK_EQ(buf[0], 1);
  CHECK_EQ(buf[7], 8);
  CHECK_EQ(GetInt64BE(buf), 0x0102030405060708LL);
  PutInt64BE(-1, buf);
  CHECK_EQ(GetInt64BE(buf), -1);
}

static void TestBase64() {
  const uint8_t data[] = {0xDE, 0xAD, 0xBE, 0xEF, 0x00};
  std::string enc = Base64UrlEncode(data, sizeof(data));
  std::string dec;
  CHECK(Base64UrlDecode(enc, &dec));
  CHECK_EQ(dec.size(), sizeof(data));
  CHECK_EQ(std::memcmp(dec.data(), data, sizeof(data)), 0);
  CHECK(!Base64UrlDecode("a+b", &dec));  // '+' not in url-safe alphabet
  CHECK(!Base64UrlDecode("abcde", &dec));  // impossible length (5 % 4 == 1)
}

static void TestCrc32() {
  // zlib golden: crc32(b"123456789") == 0xCBF43926
  CHECK_EQ(Crc32("123456789", 9), 0xCBF43926u);
  CHECK_EQ(Crc32("", 0), 0u);
}

static void TestSha1() {
  CHECK_EQ(Sha1("abc", 3).Hex(),
           std::string("a9993e364706816aba3e25717850c26c9cd0d89d"));
  CHECK_EQ(Sha1("", 0).Hex(),
           std::string("da39a3ee5e6b4b0d3255bfef95601890afd80709"));
  // streamed == one-shot across buffer boundaries
  std::string big(1000, 'x');
  Sha1Stream s;
  s.Update(big.data(), 37);
  s.Update(big.data() + 37, 63);
  s.Update(big.data() + 100, 900);
  CHECK_EQ(s.Final().Hex(), Sha1(big.data(), big.size()).Hex());
}

static void TestFileId() {
  EncodeFileIdArgs a;
  a.group = "group1";
  a.store_path_index = 0;
  a.source_ip = PackIp("192.168.1.102");
  a.create_timestamp = 1406000000;
  a.file_size = 30790;
  a.crc32 = 0xFCEFEF3Cu;
  a.ext = "jpg";
  a.uniquifier = 42;
  auto id = EncodeFileId(a);
  CHECK(id.has_value());
  auto parts = DecodeFileId(*id);
  CHECK(parts.has_value());
  CHECK_EQ(parts->group, std::string("group1"));
  CHECK_EQ(UnpackIp(parts->source_ip), std::string("192.168.1.102"));
  CHECK_EQ(parts->create_timestamp, 1406000000u);
  CHECK_EQ(parts->file_size, 30790u);
  CHECK_EQ(parts->crc32, 0xFCEFEF3Cu);
  CHECK_EQ(parts->uniquifier, 42);
  CHECK(!parts->appender);
  CHECK_EQ(parts->FullId(), *id);

  // flags
  a.appender = true;
  auto id2 = EncodeFileId(a);
  auto p2 = DecodeFileId(*id2);
  CHECK(p2.has_value() && p2->appender);

  // tampering
  std::string bad = *id;
  bad[bad.size() - 5] = bad[bad.size() - 5] == 'A' ? 'B' : 'A';
  CHECK(!DecodeFileId(bad).has_value());

  // invalid encode args
  EncodeFileIdArgs e = a;
  e.group = "this-group-name-is-way-too-long";
  CHECK(!EncodeFileId(e).has_value());
  e = a;
  e.ext = "tar.gz";
  CHECK(!EncodeFileId(e).has_value());
  e = a;
  e.uniquifier = 0x1000;
  CHECK(!EncodeFileId(e).has_value());
}

static void TestLocalPath() {
  EncodeFileIdArgs a;
  a.group = "g";
  a.source_ip = PackIp("1.2.3.4");
  a.create_timestamp = 1;
  a.file_size = 2;
  a.crc32 = 3;
  a.ext = "txt";
  auto id = EncodeFileId(a);
  auto parts = DecodeFileId(*id);
  auto lp = LocalPath("/var/p0", parts->RemoteFilename());
  CHECK(lp.has_value());
  CHECK(lp->rfind("/var/p0/data/", 0) == 0);
  CHECK(!LocalPath("/var/p0", "M00/../../passwd").has_value());
  CHECK(!LocalPath("/var/p0", "M00/00/00/../../../etc/passwd").has_value());
  CHECK(!LocalPath("/var/p0", "no/such/shape/x").has_value());
}

static void TestIni() {
  IniConfig cfg;
  std::string err;
  CHECK(cfg.LoadString(
      "# comment\nport = 22122\ndisabled=false\n"
      "tracker_server = 10.0.0.1:22122\ntracker_server = 10.0.0.2:22122\n"
      "buff_size = 256KB\ninterval = 5m\n[section]\nname=x\n",
      &err));
  CHECK_EQ(cfg.GetInt("port", 0), 22122);
  CHECK(!cfg.GetBool("disabled", true));
  CHECK_EQ(cfg.GetAll("tracker_server").size(), 2u);
  CHECK_EQ(cfg.GetBytes("buff_size", 0), 256 * 1024);
  CHECK_EQ(cfg.GetSeconds("interval", 0), 300);
  CHECK_EQ(cfg.GetStr("name", ""), std::string("x"));
  CHECK(!cfg.Has("nope"));
  IniConfig inc;
  CHECK(!inc.LoadString("#include other.conf\n", &err));  // no base dir
}

static void TestProtocolConstants() {
  CHECK_EQ(static_cast<int>(TrackerCmd::kStorageJoin), 81);
  CHECK_EQ(static_cast<int>(TrackerCmd::kServiceQueryStoreWithoutGroupOne), 101);
  CHECK_EQ(static_cast<int>(StorageCmd::kUploadFile), 11);
  CHECK_EQ(static_cast<int>(StorageCmd::kResp), 100);
  CHECK_EQ(static_cast<int>(StorageCmd::kStat), 130);
  CHECK_EQ(static_cast<int>(TrackerCmd::kServerClusterStat), 95);
  CHECK_EQ(kHeaderSize, 10);
  // Beat-blob naming contract: one name per slot, the named headline
  // stats present (the Python side asserts the same list).
  CHECK_EQ(kBeatStatCount, 28);
  CHECK_EQ(std::string(kBeatStatNames[0]), std::string("total_upload"));
  CHECK_EQ(std::string(kBeatStatNames[17]),
           std::string("dedup_bytes_saved"));
  CHECK_EQ(std::string(kBeatStatNames[21]), std::string("sync_lag_s"));
  CHECK_EQ(std::string(kBeatStatNames[23]),
           std::string("recovery_chunks_fetched"));
}

static void TestStatsRegistry() {
  StatsRegistry reg;
  reg.Counter("a.count")->fetch_add(3);
  CHECK_EQ(reg.Counter("a.count")->load(), 3);  // find-or-create finds
  reg.SetGauge("g", 42);
  reg.GaugeFn("g.fn", [] { return int64_t{7}; });
  StatHistogram* h = reg.Histogram("h", {10, 100, 1000});
  h->Observe(5);
  h->Observe(10);    // inclusive upper bound: first bucket
  h->Observe(11);    // second bucket
  h->Observe(5000);  // overflow
  CHECK_EQ(h->count(), 4);
  CHECK_EQ(h->sum(), 5 + 10 + 11 + 5000);
  CHECK_EQ(h->bucket_count(0), 2);
  CHECK_EQ(h->bucket_count(1), 1);
  CHECK_EQ(h->bucket_count(2), 0);
  CHECK_EQ(h->bucket_count(3), 1);
  std::string json = reg.Json();
  // Shape spot-checks (the full field-for-field check is the
  // cross-language golden test via `fdfs_codec stats-json`).
  CHECK(json.find("\"counters\":{\"a.count\":3}") != std::string::npos);
  CHECK(json.find("\"g\":42") != std::string::npos);
  CHECK(json.find("\"g.fn\":7") != std::string::npos);
  CHECK(json.find("\"bounds\":[10,100,1000]") != std::string::npos);
  CHECK(json.find("\"counts\":[2,1,0,1]") != std::string::npos);
  CHECK(json.find("\"sum\":5026") != std::string::npos);
}

static void TestTraceCtxWire() {
  // Wire layout golden: 8B trace_id + 4B parent + 4B flags, big-endian —
  // must match fastdfs_tpu.common.protocol.pack_trace_ctx byte-for-byte.
  const uint8_t raw[16] = {0x01, 0x02, 0x03, 0x04, 0x05, 0x06, 0x07, 0x08,
                           0xAA, 0xBB, 0xCC, 0xDD, 0x00, 0x00, 0x00, 0x03};
  TraceCtx c = ParseTraceCtx(raw);
  CHECK_EQ(c.trace_id, 0x0102030405060708ULL);
  CHECK_EQ(c.parent_span, 0xAABBCCDDu);
  CHECK_EQ(c.flags, 3u);
  CHECK(c.valid());
  uint8_t back[16];
  SerializeTraceCtx(c, back);
  CHECK_EQ(std::memcmp(raw, back, 16), 0);
  CHECK(!TraceCtx{}.valid());
  CHECK_EQ(static_cast<int>(StorageCmd::kTraceCtx),
           static_cast<int>(TrackerCmd::kTraceCtx));  // shared framing
  CHECK_EQ(static_cast<int>(StorageCmd::kTraceDump), 131);
  CHECK_EQ(static_cast<int>(TrackerCmd::kTraceDump), 96);
}

static void TestTraceRing() {
  TraceRing ring(4);
  uint32_t a = ring.NextSpanId(), b = ring.NextSpanId();
  CHECK(a != b && a != 0 && b != 0);
  CHECK(ring.NewTraceId() != ring.NewTraceId());
  for (int i = 0; i < 6; ++i) {  // wraps: 6 records into 4 slots
    TraceSpan s;
    s.trace_id = 0xABC0ULL + i;
    s.span_id = static_cast<uint32_t>(i + 1);
    s.start_us = 1000 + i;
    s.dur_us = 10;
    s.SetName(i % 2 ? "storage.recv" : "storage.upload_file");
    ring.Record(s);
  }
  CHECK_EQ(ring.recorded(), 6);
  CHECK_EQ(ring.dropped(), 2);
  std::string json = ring.Json("storage", 23000);
  CHECK(json.find("\"role\":\"storage\"") != std::string::npos);
  CHECK(json.find("\"port\":23000") != std::string::npos);
  // Oldest two overwritten; newest four present, sorted by start_us.
  CHECK(json.find("\"start_us\":1000,") == std::string::npos);
  CHECK(json.find("\"start_us\":1005,") != std::string::npos);
  size_t p2 = json.find("\"start_us\":1002");
  size_t p5 = json.find("\"start_us\":1005");
  CHECK(p2 != std::string::npos && p2 < p5);
  // Long names truncate, never overflow.
  TraceSpan longname;
  longname.trace_id = 1;
  longname.SetName("this-name-is-way-longer-than-the-forty-byte-span-field");
  CHECK_EQ(std::strlen(longname.name), sizeof(longname.name) - 1);
}

static void TestTraceRingThreaded() {
  // Lock-light claim: concurrent recorders + a dumping reader must be
  // data-race-free (tools/run_sanitizers.sh runs this under TSan).
  TraceRing ring(64);
  std::vector<std::thread> threads;
  for (int t = 0; t < 4; ++t) {
    threads.emplace_back([&ring, t] {
      for (int i = 0; i < 500; ++i) {
        TraceSpan s;
        s.trace_id = static_cast<uint64_t>(t) << 32 | i;
        s.span_id = ring.NextSpanId();
        s.start_us = i;
        s.dur_us = 1;
        s.SetName("storage.upload_file");
        ring.Record(s);
      }
    });
  }
  std::thread reader([&ring] {
    for (int i = 0; i < 50; ++i) (void)ring.Json("storage", 1);
  });
  for (auto& th : threads) th.join();
  reader.join();
  CHECK_EQ(ring.recorded(), 4 * 500);
  CHECK(ring.Json("storage", 1).find("\"spans\":[") != std::string::npos);
}

static void TestTraceCorrelator() {
  TraceCorrelator corr(2);
  TraceCtx c1{1, 10, 1}, c2{2, 20, 1}, c3{3, 30, 1}, out;
  corr.Put("M00/a", c1);
  corr.Put("M00/b", c2);
  corr.Put("M00/c", c3);  // evicts the oldest (M00/a)
  CHECK_EQ(corr.size(), 2u);
  CHECK(!corr.Take("M00/a", &out));
  CHECK(corr.Take("M00/b", &out));
  CHECK_EQ(out.trace_id, 2ULL);
  CHECK(!corr.Take("M00/b", &out));  // Take consumes
  CHECK(corr.Take("M00/c", &out));
  CHECK_EQ(corr.size(), 0u);
}

static void TestEventLog() {
  EventLog log(4);
  log.Record(EventSeverity::kWarn, "chunk.quarantined", "digest1", "spi=0");
  log.Record(EventSeverity::kInfo, "chunk.repaired", "digest1");
  std::string json = log.Json("storage", 23000);
  CHECK(json.find("\"role\":\"storage\"") != std::string::npos);
  CHECK(json.find("\"type\":\"chunk.quarantined\"") != std::string::npos);
  CHECK(json.find("\"severity\":\"warn\"") != std::string::npos);
  CHECK(json.find("\"seq\":1") != std::string::npos);
  CHECK_EQ(log.recorded(), 2);
  CHECK_EQ(log.dropped(), 0);
  // Ring wrap: capacity 4, record 6 — the oldest 2 are overwritten and
  // the dump holds exactly seqs 3..6 in order.
  for (int i = 0; i < 4; ++i)
    log.Record(EventSeverity::kError, "gc.sweep", "M00",
               "n=" + std::to_string(i));
  CHECK_EQ(log.recorded(), 6);
  CHECK_EQ(log.dropped(), 2);
  json = log.Json("storage", 23000);
  CHECK(json.find("\"seq\":1,") == std::string::npos);
  CHECK(json.find("\"seq\":3") != std::string::npos);
  CHECK(json.find("\"seq\":6") != std::string::npos);
  // Hostile bytes in key/detail must still serialize as valid JSON
  // (escaped), and over-long fields truncate instead of overflowing.
  EventLog esc(2);
  esc.Record(EventSeverity::kInfo, "config.anomaly", "a\"b\\c\nd",
             std::string(500, 'x'));
  json = esc.Json("tracker", 22122);
  CHECK(json.find("a\\\"b\\\\c\\nd") != std::string::npos);
  CHECK(json.find(std::string(127, 'x') + "\"") != std::string::npos);
}

static void TestEventLogThreaded() {
  // Lock-light claim: concurrent recorders + a dumping reader must be
  // data-race-free (tools/run_sanitizers.sh runs this under TSan) —
  // the flight-recorder twin of TestTraceRingThreaded.
  EventLog log(64);
  std::vector<std::thread> threads;
  for (int t = 0; t < 4; ++t) {
    threads.emplace_back([&log, t] {
      for (int i = 0; i < 500; ++i)
        log.Record(EventSeverity::kInfo, "request.slow",
                   "t" + std::to_string(t), "i=" + std::to_string(i));
    });
  }
  std::thread reader([&log] {
    for (int i = 0; i < 50; ++i) (void)log.Json("storage", 1);
  });
  for (auto& th : threads) th.join();
  reader.join();
  CHECK_EQ(log.recorded(), 4 * 500);
  CHECK(log.Json("storage", 1).find("\"events\":[") != std::string::npos);
}

static void TestEventLoopLagHook() {
  // The iteration hook must observe the time spent inside callbacks: a
  // deliberately-slow posted task shows up as loop lag >= its sleep.
  EventLoop loop;
  StatsRegistry reg;
  StatHistogram* lag = reg.Histogram("nio.loop_lag_us",
                                     StatsRegistry::LatencyBucketsUs());
  std::atomic<int64_t> dispatched{0};
  loop.set_iteration_hook([&](int64_t busy_us, int n_events) {
    lag->Observe(busy_us);
    dispatched.fetch_add(n_events);
  });
  loop.Post([] { usleep(20 * 1000); });
  loop.Post([&loop] { loop.Stop(); });
  loop.Run();
  CHECK(lag->count() >= 1);
  CHECK(lag->sum() >= 20000);  // the 20 ms stall is visible as lag
}

static void TestWorkerPoolQueueStats() {
  StatsRegistry reg;
  StatHistogram* wait = reg.Histogram("dio.queue_wait_us",
                                      StatsRegistry::LatencyBucketsUs());
  StatHistogram* service = reg.Histogram("dio.service_us",
                                         StatsRegistry::LatencyBucketsUs());
  WorkerPool pool(1);
  pool.SetStats(wait, service);
  // One slow task at the head of a 1-thread pool: the tasks behind it
  // must observe queue wait >= its service time.
  pool.Submit([] { usleep(30 * 1000); });
  for (int i = 0; i < 3; ++i) pool.Submit([] {});
  pool.Stop();  // drain-then-join
  CHECK_EQ(service->count(), 4);
  CHECK_EQ(wait->count(), 4);
  CHECK(service->sum() >= 30000);
  CHECK(wait->sum() >= 30000);  // the queued tasks sat behind the sleeper
}

static void TestStatsRegistryPruneGauges() {
  StatsRegistry reg;
  reg.SetGauge("sync.peer.10.0.0.2:23000.lag_s", 4);
  reg.SetGauge("sync.peer.10.0.0.2:23000.connected", 1);
  reg.SetGauge("sync.peer.10.0.0.3:23000.lag_s", 9);
  reg.SetGauge("server.connections", 2);  // outside the prefix: untouched
  // Peer .3 left the group: prune everything under sync.peer. except
  // the surviving peer's family.
  int removed = reg.PruneGauges("sync.peer.",
                                {"sync.peer.10.0.0.2:23000."});
  CHECK_EQ(removed, 1);
  std::string json = reg.Json();
  CHECK(json.find("10.0.0.3") == std::string::npos);
  CHECK(json.find("sync.peer.10.0.0.2:23000.lag_s") != std::string::npos);
  CHECK(json.find("server.connections") != std::string::npos);
  // Re-appearing peer just re-registers (SetGauge is find-or-create).
  reg.SetGauge("sync.peer.10.0.0.3:23000.lag_s", 1);
  CHECK(reg.Json().find("10.0.0.3") != std::string::npos);
}


// -- lock-rank discipline (common/lockrank.h) ------------------------------

static void TestRankedMutex() {
  // Ascending-rank acquisition is legal and balances the held stack.
  RankedMutex outer(LockRank::kScrub);
  RankedMutex inner(LockRank::kLog);
  {
    std::lock_guard<RankedMutex> a(outer);
    std::lock_guard<RankedMutex> b(inner);
    if (kLockRankEnforced) CHECK_EQ(lockrank_detail::HeldCount(), 2);
  }
  if (kLockRankEnforced) CHECK_EQ(lockrank_detail::HeldCount(), 0);
  // try_lock participates in the held stack like lock().
  CHECK(outer.try_lock());  // NOLINT(lock-guard-discipline): testing the wrapper
  if (kLockRankEnforced) CHECK_EQ(lockrank_detail::HeldCount(), 1);
  outer.unlock();  // NOLINT(lock-guard-discipline)
  // Same-rank ASCENDING order keys: the RefAll stripe protocol.
  RankedMutex s2(LockRank::kChunkStripe, 2);
  RankedMutex s5(LockRank::kChunkStripe, 5);
  {
    std::unique_lock<RankedMutex> lk2(s2);
    std::unique_lock<RankedMutex> lk5(s5);
    // Out-of-order RELEASE is fine — only acquisition order is ranked.
    lk2.unlock();
  }
  if (kLockRankEnforced) CHECK_EQ(lockrank_detail::HeldCount(), 0);
  CHECK_EQ(std::string(LockRankName(LockRank::kChunkStripe)),
           "chunkstore.stripe");
}

static void TestRankedMutexThreaded() {
  // 4 threads hammer a correctly-ordered two-lock chain plus a ranked
  // spinlock; the TSan leg proves the checker's thread_local
  // bookkeeping (and the spinlock's acquire/release) is race-free, and
  // the counters prove mutual exclusion still holds through the wrapper.
  RankedMutex a(LockRank::kStatsRegistry);
  RankedMutex b(LockRank::kWorkers);
  RankedSpinLock s(LockRank::kTraceSlot);
  int both = 0;
  int spun = 0;
  std::vector<std::thread> threads;
  for (int t = 0; t < 4; ++t) {
    threads.emplace_back([&] {
      for (int i = 0; i < 1000; ++i) {
        std::lock_guard<RankedMutex> la(a);
        std::lock_guard<RankedMutex> lb(b);
        ++both;
        SpinGuard g(s);
        ++spun;
      }
    });
  }
  for (auto& th : threads) th.join();
  CHECK_EQ(both, 4 * 1000);
  CHECK_EQ(spun, 4 * 1000);
}

// Death-test driver: re-exec THIS binary with a violation flag (fork +
// exec keeps the child single-threaded at birth, which the sanitizer
// runtimes require), expect SIGABRT, and expect BOTH lock sites in the
// report.
static void ExpectChildAborts(const char* exe, const char* flag,
                              const char* expect_a, const char* expect_b) {
  int fds[2];
  CHECK_EQ(pipe(fds), 0);
  pid_t pid = fork();
  CHECK(pid >= 0);
  if (pid == 0) {
    dup2(fds[1], 2);
    close(fds[0]);
    close(fds[1]);
    execl(exe, exe, flag, static_cast<char*>(nullptr));
    _exit(127);
  }
  close(fds[1]);
  std::string err;
  char buf[4096];
  ssize_t r;
  while ((r = read(fds[0], buf, sizeof(buf))) > 0)
    err.append(buf, static_cast<size_t>(r));
  close(fds[0]);
  int st = 0;
  waitpid(pid, &st, 0);
  if (!(WIFSIGNALED(st) && WTERMSIG(st) == SIGABRT)) {
    std::fprintf(stderr, "FAIL %s: child (%s) did not SIGABRT; stderr:\n%s\n",
                 __FILE__, flag, err.c_str());
    ++g_failures;
    return;
  }
  CHECK(err.find(expect_a) != std::string::npos);
  CHECK(err.find(expect_b) != std::string::npos);
  CHECK(err.find("held by this thread") != std::string::npos);
}

static void TestRankedMutexInversionAborts(const char* exe) {
  if (!kLockRankEnforced) {
    std::printf("common_test: lockrank death tests SKIPPED "
                "(build without -DFDFS_LOCKRANK)\n");
    return;
  }
  // A thread acquiring a LOWER rank while holding a higher one must
  // abort, reporting the acquiring lock AND the held stack.
  ExpectChildAborts(exe, "--lockrank-inversion",
                    "chunkstore.stripe", "log.global");
  // The RefAll protocol specifically: same rank, DESCENDING stripe
  // keys must abort even though ascending is sanctioned.
  ExpectChildAborts(exe, "--lockrank-stripe-descend",
                    "ascending", "chunkstore.stripe");
  // Recursive acquisition of one instance is a deadlock in production;
  // the checker turns it into a deterministic abort.
  ExpectChildAborts(exe, "--lockrank-recursive",
                    "recursive", "sync.manager");
}

// Child-process violation bodies (reached only via the flags above).
static int RunLockRankViolation(const char* flag) {
  if (std::strcmp(flag, "--lockrank-inversion") == 0) {
    RankedMutex hi(LockRank::kLog);
    RankedMutex lo(LockRank::kChunkStripe);
    std::thread t([&] {
      std::lock_guard<RankedMutex> a(hi);
      std::lock_guard<RankedMutex> b(lo);  // rank 90 under rank 210: abort
    });
    t.join();
  } else if (std::strcmp(flag, "--lockrank-stripe-descend") == 0) {
    RankedMutex s5(LockRank::kChunkStripe, 5);
    RankedMutex s2(LockRank::kChunkStripe, 2);
    std::lock_guard<RankedMutex> a(s5);
    std::lock_guard<RankedMutex> b(s2);  // descending keys: abort
  } else if (std::strcmp(flag, "--lockrank-recursive") == 0) {
    RankedMutex m(LockRank::kSync);
    m.lock();  // NOLINT(lock-guard-discipline): deliberate violation
    // On a checked build the second lock aborts in PushOrDie BEFORE
    // touching the std::mutex; on an unchecked build it would be a
    // genuine self-deadlock, so only attempt it when enforced.
    if (kLockRankEnforced)
      m.lock();  // NOLINT(lock-guard-discipline): recursive; checker aborts
    m.unlock();  // NOLINT(lock-guard-discipline)
  } else {
    std::fprintf(stderr, "unknown flag %s\n", flag);
    return 2;
  }
  // Only reachable when FDFS_LOCKRANK is compiled out.
  std::printf("no abort\n");
  return 0;
}

int main(int argc, char** argv) {
  if (argc > 1 && std::strncmp(argv[1], "--lockrank-", 11) == 0)
    return RunLockRankViolation(argv[1]);

  TestEndian();
  TestBase64();
  TestCrc32();
  TestSha1();
  TestFileId();
  TestLocalPath();
  TestIni();
  TestProtocolConstants();
  TestStatsRegistry();
  TestTraceCtxWire();
  TestTraceRing();
  TestTraceRingThreaded();
  TestTraceCorrelator();
  TestEventLog();
  TestEventLogThreaded();
  TestEventLoopLagHook();
  TestWorkerPoolQueueStats();
  TestStatsRegistryPruneGauges();
  TestRankedMutex();
  TestRankedMutexThreaded();
  TestRankedMutexInversionAborts(argv[0]);
  if (g_failures == 0) {
    std::printf("common_test: ALL PASS\n");
    return 0;
  }
  std::printf("common_test: %d FAILURES\n", g_failures);
  return 1;
}
