// Tracker-side unit tests (no gtest in the image — plain CHECK macros):
// the cluster brain's observability surface.  A beat's 28-slot stat blob
// must round-trip into ClusterStatJson under the generated field names —
// the same JSON the Python monitor decodes (tests/test_monitor.py drives
// the live-socket version of this).
#include <cstdio>
#include <string>

#include "common/protocol_gen.h"
#include "tracker/cluster.h"

static int g_failures = 0;

#define CHECK(cond)                                                        \
  do {                                                                     \
    if (!(cond)) {                                                         \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      ++g_failures;                                                        \
    }                                                                      \
  } while (0)

#define CHECK_EQ(a, b) CHECK((a) == (b))

using namespace fdfs;

static void TestBeatStatsRoundTripJson() {
  Cluster c;
  CHECK(c.Join("group1", "10.0.0.1", 23000, 1, /*now=*/1000).has_value());
  int64_t stats[kBeatStatCount];
  for (int i = 0; i < kBeatStatCount; ++i) stats[i] = 100 + i;
  CHECK(c.Beat("group1", "10.0.0.1", 23000, stats, kBeatStatCount, 1001));
  CHECK(c.UpdateDiskUsage("group1", "10.0.0.1", 23000, 5000, 4000));

  std::string json = c.ClusterStatJson(/*now=*/1003);
  // Liveness: status name + beat age derived from last_beat.
  CHECK(json.find("\"status_name\":\"ACTIVE\"") != std::string::npos);
  CHECK(json.find("\"beat_age_s\":2") != std::string::npos);
  CHECK(json.find("\"free_mb\":4000") != std::string::npos);
  // Every beat slot appears under its generated name with its value.
  for (int i = 0; i < kBeatStatCount; ++i) {
    std::string want = std::string("\"") + kBeatStatNames[i] +
                       "\":" + std::to_string(100 + i);
    CHECK(json.find(want) != std::string::npos);
  }
  // Group filter.
  CHECK_EQ(c.ClusterStatJson(1003, "nope"), std::string("[]"));
  CHECK(c.ClusterStatJson(1003, "group1").find("group1") !=
        std::string::npos);
}

static void TestShortBeatKeepsTail() {
  // Append-only wire contract: an older storage's shorter blob must not
  // zero the tail slots a newer beat already populated.
  Cluster c;
  CHECK(c.Join("g", "10.0.0.2", 23000, 1, 1000).has_value());
  int64_t full[kBeatStatCount];
  for (int i = 0; i < kBeatStatCount; ++i) full[i] = 7;
  CHECK(c.Beat("g", "10.0.0.2", 23000, full, kBeatStatCount, 1001));
  int64_t short20[20];
  for (int i = 0; i < 20; ++i) short20[i] = 9;
  CHECK(c.Beat("g", "10.0.0.2", 23000, short20, 20, 1002));
  std::string json = c.ClusterStatJson(1002);
  CHECK(json.find("\"total_upload\":9") != std::string::npos);
  std::string tail = std::string("\"") + kBeatStatNames[20] + "\":7";
  CHECK(json.find(tail) != std::string::npos);
}

int main() {
  TestBeatStatsRoundTripJson();
  TestShortBeatKeepsTail();
  if (g_failures == 0) {
    std::printf("tracker_test: ALL PASS\n");
    return 0;
  }
  std::printf("tracker_test: %d FAILURES\n", g_failures);
  return 1;
}
