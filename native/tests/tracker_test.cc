// Tracker-side unit tests (no gtest in the image — plain CHECK macros):
// the cluster brain's observability surface.  A beat's 28-slot stat blob
// must round-trip into ClusterStatJson under the generated field names —
// the same JSON the Python monitor decodes (tests/test_monitor.py drives
// the live-socket version of this).
#include <cstdio>
#include <string>
#include <vector>

#include "common/heatwire.h"
#include "common/jumphash.h"
#include "common/protocol_gen.h"
#include "tracker/cluster.h"
#include "tracker/hotmap.h"
#include "tracker/placement.h"

static int g_failures = 0;

#define CHECK(cond)                                                        \
  do {                                                                     \
    if (!(cond)) {                                                         \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      ++g_failures;                                                        \
    }                                                                      \
  } while (0)

#define CHECK_EQ(a, b) CHECK((a) == (b))

using namespace fdfs;

static void TestBeatStatsRoundTripJson() {
  Cluster c;
  CHECK(c.Join("group1", "10.0.0.1", 23000, 1, /*now=*/1000).has_value());
  int64_t stats[kBeatStatCount];
  for (int i = 0; i < kBeatStatCount; ++i) stats[i] = 100 + i;
  CHECK(c.Beat("group1", "10.0.0.1", 23000, stats, kBeatStatCount, 1001));
  CHECK(c.UpdateDiskUsage("group1", "10.0.0.1", 23000, 5000, 4000));

  std::string json = c.ClusterStatJson(/*now=*/1003);
  // Liveness: status name + beat age derived from last_beat.
  CHECK(json.find("\"status_name\":\"ACTIVE\"") != std::string::npos);
  CHECK(json.find("\"beat_age_s\":2") != std::string::npos);
  CHECK(json.find("\"free_mb\":4000") != std::string::npos);
  // Every beat slot appears under its generated name with its value.
  for (int i = 0; i < kBeatStatCount; ++i) {
    std::string want = std::string("\"") + kBeatStatNames[i] +
                       "\":" + std::to_string(100 + i);
    CHECK(json.find(want) != std::string::npos);
  }
  // Group filter.
  CHECK_EQ(c.ClusterStatJson(1003, "nope"), std::string("[]"));
  CHECK(c.ClusterStatJson(1003, "group1").find("group1") !=
        std::string::npos);
}

static void TestShortBeatKeepsTail() {
  // Append-only wire contract: an older storage's shorter blob must not
  // zero the tail slots a newer beat already populated.
  Cluster c;
  CHECK(c.Join("g", "10.0.0.2", 23000, 1, 1000).has_value());
  int64_t full[kBeatStatCount];
  for (int i = 0; i < kBeatStatCount; ++i) full[i] = 7;
  CHECK(c.Beat("g", "10.0.0.2", 23000, full, kBeatStatCount, 1001));
  int64_t short20[20];
  for (int i = 0; i < 20; ++i) short20[i] = 9;
  CHECK(c.Beat("g", "10.0.0.2", 23000, short20, 20, 1002));
  std::string json = c.ClusterStatJson(1002);
  CHECK(json.find("\"total_upload\":9") != std::string::npos);
  std::string tail = std::string("\"") + kBeatStatNames[20] + "\":7";
  CHECK(json.find(tail) != std::string::npos);
}

static void TestStoreLookup2Hysteresis() {
  // store_lookup = 2 flapping fix: the previous pick holds until a rival
  // leads its free space by MORE than the hysteresis delta.
  Cluster c(2);
  c.set_balance_hysteresis_mb(100);
  CHECK(c.Join("g1", "10.0.0.1", 23000, 1, 1000).has_value());
  CHECK(c.Join("g2", "10.0.0.2", 23000, 1, 1000).has_value());
  CHECK(c.UpdateDiskUsage("g1", "10.0.0.1", 23000, 10000, 5000));
  CHECK(c.UpdateDiskUsage("g2", "10.0.0.2", 23000, 10000, 5040));
  auto t = c.QueryStore("");
  CHECK(t.has_value() && t->group == "g2");  // no prior pick: most free wins
  // g1 beat: now ahead by 60 MB — inside the 100 MB band, pick holds.
  CHECK(c.UpdateDiskUsage("g1", "10.0.0.1", 23000, 10000, 5100));
  t = c.QueryStore("");
  CHECK(t.has_value() && t->group == "g2");
  // Lead grows past the band — pick moves.
  CHECK(c.UpdateDiskUsage("g1", "10.0.0.1", 23000, 10000, 5200));
  t = c.QueryStore("");
  CHECK(t.has_value() && t->group == "g1");
  // Symmetric: g2 nosing back ahead must not flap the pick back.
  CHECK(c.UpdateDiskUsage("g2", "10.0.0.2", 23000, 10000, 5250));
  t = c.QueryStore("");
  CHECK(t.has_value() && t->group == "g1");
}

static void TestPlacementLifecycle() {
  PlacementTable pt;
  CHECK(pt.EnsureGroup("g1"));
  CHECK_EQ(pt.version(), 1);
  CHECK(pt.EnsureGroup("g2"));
  CHECK(pt.EnsureGroup("g3"));
  CHECK(!pt.EnsureGroup("g2"));  // re-join: no append, no version bump
  CHECK_EQ(pt.version(), 3);
  CHECK_EQ(pt.entries().size(), 3u);
  CHECK_EQ(pt.Drain("nope"), 2);
  CHECK_EQ(pt.Drain("g2"), 0);
  CHECK_EQ(pt.version(), 4);
  CHECK_EQ(pt.Drain("g2"), 0);  // idempotent: no second bump
  CHECK_EQ(pt.version(), 4);
  auto active = pt.ActiveGroups();
  CHECK_EQ(active.size(), 2u);
  CHECK(active[0] == "g1" && active[1] == "g3");
  CHECK_EQ(pt.Retire("g1"), 22);  // active cannot retire directly
  CHECK_EQ(pt.Retire("g2"), 0);
  CHECK_EQ(pt.Reactivate("g2"), 22);  // retired is terminal
  CHECK_EQ(pt.Drain("g2"), 22);
  CHECK_EQ(pt.Reactivate("g3"), 0);  // already active: idempotent
  CHECK_EQ(pt.version(), 5);
}

static void TestPlacementJumpStability() {
  PlacementTable pt;
  pt.EnsureGroup("g1");
  pt.EnsureGroup("g2");
  pt.EnsureGroup("g3");
  // PickGroup IS jump_hash(sha1(key)) over the active list — the same
  // function the Python client and the rebalance migrator compute.
  std::vector<std::string> active = pt.ActiveGroups();
  for (const char* key : {"alpha", "bravo", "charlie", "delta"}) {
    int32_t b = JumpHash(PlacementKey(key), 3);
    CHECK_EQ(pt.PickGroup(key), active[b]);
  }
  // Adding a 4th group moves ~1/4 of keys, and every moved key lands IN
  // the new group — no key shuffles between two old groups.
  std::vector<std::string> before;
  for (int i = 0; i < 1000; ++i)
    before.push_back(pt.PickGroup("key-" + std::to_string(i)));
  pt.EnsureGroup("g4");
  int moved = 0;
  for (int i = 0; i < 1000; ++i) {
    std::string now = pt.PickGroup("key-" + std::to_string(i));
    if (now != before[i]) {
      ++moved;
      CHECK_EQ(now, std::string("g4"));
    }
  }
  CHECK(moved > 150 && moved < 350);  // expectation: 250 of 1000
}

static void TestPlacementWireRoundTrip() {
  PlacementTable pt;
  pt.EnsureGroup("g1");
  pt.EnsureGroup("g2");
  CHECK_EQ(pt.Drain("g2"), 0);
  std::vector<std::vector<PlacementTable::WireMember>> members(2);
  members[0].push_back({"10.0.0.1", 23000});
  std::string wire = pt.PackWire(members);
  PlacementTable follower;
  CHECK(follower.AdoptWire(wire));
  CHECK_EQ(follower.version(), pt.version());
  CHECK_EQ(follower.entries().size(), 2u);
  CHECK(follower.entries()[0].group == "g1" &&
        follower.entries()[0].state == GroupState::kActive);
  CHECK(follower.entries()[1].group == "g2" &&
        follower.entries()[1].state == GroupState::kDraining);
  // A truncated body is refused and leaves the table untouched.
  CHECK(!follower.AdoptWire(wire.substr(0, wire.size() - 1)));
  CHECK_EQ(follower.entries().size(), 2u);
  CHECK(!follower.AdoptWire(""));
}

static void TestPlacementSaveLoad() {
  PlacementTable pt;
  pt.EnsureGroup("g1");
  pt.EnsureGroup("g2");
  CHECK_EQ(pt.Drain("g1"), 0);
  const char* path = "/tmp/fdfs_tracker_test_placement.dat";
  CHECK(pt.Save(path));
  PlacementTable in;
  CHECK(in.Load(path));
  CHECK_EQ(in.version(), pt.version());
  CHECK_EQ(in.entries().size(), 2u);
  CHECK(in.entries()[0].group == "g1" &&
        in.entries()[0].state == GroupState::kDraining);
  std::remove(path);
  PlacementTable fresh;
  CHECK(fresh.Load(path));  // missing file = OK-empty
  CHECK_EQ(fresh.entries().size(), 0u);
}

static void TestQueryStoreHonorsPlacement() {
  // store_lookup = 3: keyed uploads route by the epoch's jump hash,
  // draining groups take no new writes, keyless clients still work.
  PlacementTable pt;
  Cluster c(3);
  c.set_placement(&pt);
  CHECK(c.Join("g1", "10.0.0.1", 23000, 1, 1000).has_value());
  CHECK(c.Join("g2", "10.0.0.2", 23000, 1, 1000).has_value());
  CHECK_EQ(pt.entries().size(), 2u);  // Join appended both to the epoch
  const std::string key = "alpha";
  auto t = c.QueryStore("", key);
  CHECK(t.has_value());
  CHECK_EQ(t->group, pt.PickGroup(key));
  // Drain the hashed group: the key re-homes to the remaining one.
  CHECK_EQ(pt.Drain(t->group), 0);
  auto t2 = c.QueryStore("", key);
  CHECK(t2.has_value() && t2->group != t->group);
  CHECK_EQ(t2->group, pt.PickGroup(key));
  // A group-pinned upload cannot dodge the drain...
  CHECK(!c.QueryStore(t->group).has_value());
  // ...and a keyless legacy client round-robins over active groups only.
  auto t3 = c.QueryStore("");
  CHECK(t3.has_value() && t3->group != t->group);
}

static int64_t BE64At(const std::string& s, size_t off) {
  int64_t v = 0;
  for (size_t i = 0; i < 8; ++i)
    v = (v << 8) | static_cast<uint8_t>(s[off + i]);
  return v;
}

// ISSUE 20: the heat window's counter-reset clamp and the
// verify-then-publish / one-epoch-drop-gap entry lifecycle — the two
// invariants the routed read path leans on.
static void TestHotMapWindowClampAndLifecycle() {
  HotMap::Config cfg;
  cfg.promote_threshold = 5;  // reads/s
  cfg.demote_threshold = 1;
  cfg.max_extra_replicas = 2;
  cfg.capacity = 4;
  HotMap hm(cfg);
  const std::string key = "group1/M00/00/01/f.bin";
  auto pick = [](const std::string& home, int want) {
    (void)home;
    std::vector<std::string> out{"group2", "group3"};
    if (static_cast<int>(out.size()) > want) out.resize(want);
    return out;
  };

  // Two nodes' cumulative beat counters fold into one cluster window:
  // 100 hits over a 1 s tick -> ewma 0.3*100 = 30/s >= 5 -> promoted.
  hm.NoteHeat("10.0.0.1:23000", {{key, 60, 60 << 10}});
  hm.NoteHeat("10.0.0.2:23000", {{key, 40, 40 << 10}});
  hm.Tick(1.0, pick, true);
  const HotMap::Entry* e = hm.Find(key);
  CHECK(e != nullptr && e->state == HotMap::State::kPending);
  CHECK_EQ(hm.promotions_total(), 1);
  // Pending entries are INVISIBLE (verify-then-publish): a full
  // snapshot carries zero entries until the fan-out is byte-verified.
  CHECK_EQ(BE64At(hm.PackWire(-1), 9), 0);
  auto tasks = hm.TasksForGroup("group1");
  CHECK_EQ(tasks.size(), 1u);
  CHECK(tasks[0].type == kHotTaskReplicate);
  // A short verified set must NOT publish...
  CHECK(!hm.AckReplicate(key, {"group2"}));
  CHECK(hm.Find(key)->state == HotMap::State::kPending);
  // ...the full one does, and the entry becomes visible.
  CHECK(hm.AckReplicate(key, {"group2", "group3"}));
  CHECK(hm.Find(key)->state == HotMap::State::kPublished);
  CHECK_EQ(BE64At(hm.PackWire(-1), 9), 1);
  int64_t v_pub = hm.version();
  CHECK(v_pub >= 1);

  // Counter-reset clamp: node 1 restarts and its cumulative counter
  // shrinks 60 -> 40.  The window must take the new ABSOLUTE (40), not
  // the negative delta (-20): ewma = 0.3*40 + 0.7*30 = 33 > 30, while
  // the unclamped fold would sag to 15.
  hm.NoteHeat("10.0.0.1:23000", {{key, 40, 40 << 10}});
  hm.Tick(1.0, pick, true);
  CHECK(hm.Find(key)->ewma > 30.0);

  // Reads served off an extra replica are credited to the HOME key
  // (alias map), so a routed read cannot cascade-promote its own copy.
  hm.NoteHeat("10.0.0.3:23000", {{"group2/M00/00/01/f.bin", 50, 50 << 10}});
  hm.Tick(1.0, pick, true);
  CHECK(hm.Find("group2/M00/00/01/f.bin") == nullptr);
  CHECK(hm.Find(key) != nullptr);

  // Idle ticks decay the EWMA below hot_demote_threshold -> retiring
  // tombstone (version bump), extra copies still on disk.
  int64_t v_before = hm.version();
  for (int i = 0;
       i < 16 && hm.Find(key)->state == HotMap::State::kPublished; ++i)
    hm.Tick(1.0, pick, true);
  CHECK(hm.Find(key)->state == HotMap::State::kRetiring);
  CHECK(hm.version() > v_before);
  CHECK_EQ(hm.demotions_total(), 1);
  // The delta since publish is a tombstone: full flag 0, one entry,
  // zero groups.
  std::string delta = hm.PackWire(v_pub);
  CHECK_EQ(delta[8], 0);
  CHECK_EQ(BE64At(delta, 9), 1);
  CHECK_EQ(BE64At(delta, 17 + 8 + key.size()), 0);
  // One-epoch gap: no drop task on the demote tick itself...
  CHECK(hm.TasksForGroup("group1").empty());
  hm.Tick(1.0, pick, true);
  // ...one tick later the bytes may go.
  auto drops = hm.TasksForGroup("group1");
  CHECK_EQ(drops.size(), 1u);
  CHECK(drops[0].type == kHotTaskDrop);
  CHECK(hm.AckDrop(key));
  CHECK(hm.Find(key) == nullptr);
}

int main() {
  TestBeatStatsRoundTripJson();
  TestHotMapWindowClampAndLifecycle();
  TestShortBeatKeepsTail();
  TestStoreLookup2Hysteresis();
  TestPlacementLifecycle();
  TestPlacementJumpStability();
  TestPlacementWireRoundTrip();
  TestPlacementSaveLoad();
  TestQueryStoreHonorsPlacement();
  if (g_failures == 0) {
    std::printf("tracker_test: ALL PASS\n");
    return 0;
  }
  std::printf("tracker_test: %d FAILURES\n", g_failures);
  return 1;
}
