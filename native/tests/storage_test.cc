// Unit tests for binlog + store + dedup units (the daemon itself is
// integration-tested from pytest via the Python client).
#include <sys/stat.h>
#include <unistd.h>

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>

#include "common/bytes.h"
#include "storage/binlog.h"
#include "storage/chunkstore.h"
#include "storage/dedup.h"
#include "storage/store.h"
#include "storage/trunk.h"

static int g_failures = 0;

#define CHECK(cond)                                                        \
  do {                                                                     \
    if (!(cond)) {                                                         \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      ++g_failures;                                                        \
    }                                                                      \
  } while (0)

using namespace fdfs;

static std::string TempDir() {
  char tmpl[] = "/tmp/fdfs_storage_test_XXXXXX";
  return mkdtemp(tmpl);
}

static void TestBinlogRecordCodec() {
  BinlogRecord rec;
  rec.timestamp = 1700000000;
  rec.op = 'C';
  rec.filename = "M00/AA/BB/name.jpg";
  std::string line = FormatBinlogRecord(rec);
  CHECK(line == "1700000000 C M00/AA/BB/name.jpg\n");
  auto back = ParseBinlogRecord(line);
  CHECK(back.has_value());
  CHECK(back->timestamp == rec.timestamp);
  CHECK(back->op == 'C');
  CHECK(back->filename == rec.filename);
  CHECK(back->extra.empty());

  rec.op = 'L';
  rec.extra = "M00/CC/DD/src.jpg";
  auto back2 = ParseBinlogRecord(FormatBinlogRecord(rec));
  CHECK(back2.has_value());
  CHECK(back2->extra == "M00/CC/DD/src.jpg");

  CHECK(!ParseBinlogRecord("garbage\n").has_value());
  CHECK(!ParseBinlogRecord("17 \n").has_value());
  CHECK(!ParseBinlogRecord("").has_value());
}

static void TestBinlogWriteReadResume() {
  std::string dir = TempDir();
  std::string err;
  BinlogWriter w;
  CHECK(w.Init(dir, 1 << 20, &err));
  for (int i = 0; i < 10; ++i)
    CHECK(w.Append('C', "M00/00/00/file" + std::to_string(i)));
  w.Flush();

  BinlogReader r;
  CHECK(r.Init(dir, dir + "/peer.mark", &err));
  for (int i = 0; i < 5; ++i) {
    auto rec = r.Next();
    CHECK(rec.has_value());
    CHECK(rec->filename == "M00/00/00/file" + std::to_string(i));
  }
  CHECK(r.SaveMark());

  // Fresh reader resumes from the mark.
  BinlogReader r2;
  CHECK(r2.Init(dir, dir + "/peer.mark", &err));
  auto rec = r2.Next();
  CHECK(rec.has_value());
  CHECK(rec->filename == "M00/00/00/file5");
  for (int i = 6; i < 10; ++i) CHECK(r2.Next().has_value());
  CHECK(!r2.Next().has_value());  // caught up

  // New writes become visible to the same reader (tailing).
  CHECK(w.Append('D', "M00/00/00/file3"));
  w.Flush();
  auto tail = r2.Next();
  CHECK(tail.has_value());
  CHECK(tail->op == 'D');
}

static void TestBinlogRotation() {
  std::string dir = TempDir();
  std::string err;
  BinlogWriter w;
  CHECK(w.Init(dir, 128, &err));  // tiny rotate size
  for (int i = 0; i < 20; ++i) CHECK(w.Append('C', "M00/00/00/f" + std::to_string(i)));
  w.Flush();
  CHECK(w.file_index() >= 1);  // rotated at least once

  BinlogReader r;
  CHECK(r.Init(dir, dir + "/m.mark", &err));
  int count = 0;
  while (r.Next().has_value()) ++count;
  CHECK(count == 20);  // reader follows rotation
}

static void TestCpuDedup() {
  std::string dir = TempDir();
  CpuDedup d(dir + "/dedup_index.dat");
  CHECK(!d.Judge("abc", 10).duplicate);
  d.Commit("abc", "group1/M00/00/00/x.bin");
  auto v = d.Judge("abc", 10);
  CHECK(v.duplicate);
  CHECK(v.dup_of == "group1/M00/00/00/x.bin");
  // snapshot round-trip
  CHECK(d.Save());
  CpuDedup d2(dir + "/dedup_index.dat");
  CHECK(d2.LoadSnapshot());
  CHECK(d2.Judge("abc", 10).duplicate);
  // forget
  d2.Forget("group1/M00/00/00/x.bin");
  CHECK(!d2.Judge("abc", 10).duplicate);
}

static void TestStoreInit() {
  std::string dir = TempDir();
  StorageConfig cfg;
  cfg.base_path = dir;
  cfg.store_paths = {dir};
  cfg.subdir_count_per_path = 4;
  StoreManager sm;
  std::string err;
  CHECK(sm.Init(cfg, &err));
  struct stat st;
  CHECK(stat((dir + "/data/03/03").c_str(), &st) == 0);
  CHECK(stat((dir + "/data/.data_init_flag").c_str(), &st) == 0);
  // second init is a no-op (flag present)
  CHECK(sm.Init(cfg, &err));
  // uniquifier wraps at 12 bits
  for (int i = 0; i < 5000; ++i) {
    int u = sm.NextUniquifier();
    CHECK(u >= 0 && u <= 0xFFF);
  }
  std::string t1 = sm.NewTmpPath(0), t2 = sm.NewTmpPath(0);
  CHECK(t1 != t2);
}


static void TestTrunkAllocator() {
  std::string dir = TempDir();
  TrunkAllocator alloc;
  std::string err;
  CHECK(alloc.Init(dir, 1 << 20, &err));  // 1 MB trunk files for the test
  CHECK(alloc.trunk_file_count() == 0);

  // First alloc creates a trunk file and splits it.
  auto a = alloc.Alloc(1000);
  CHECK(a.has_value());
  CHECK(a->trunk_id == 0 && a->offset == 0);
  CHECK(a->alloc_size >= 1000 + kTrunkHeaderSize);
  CHECK(alloc.trunk_file_count() == 1);

  // Second alloc lands after the first (split remainder).
  auto b = alloc.Alloc(5000);
  CHECK(b.has_value());
  CHECK(b->trunk_id == 0);
  CHECK(b->offset == a->alloc_size);

  // Write payloads and read them back.
  std::string pa(1000, 'x'), pb(5000, 'y');
  CHECK(WriteSlotPayload(dir, *a, pa, 111, &err));
  CHECK(WriteSlotPayload(dir, *b, pb, 222, &err));
  auto ra = ReadSlotPayload(dir, *a, 1000);
  CHECK(ra.has_value() && *ra == pa);

  // Free A; the same-size alloc reuses its exact slot.
  CHECK(alloc.Free(*a));
  auto c = alloc.Alloc(1000);
  CHECK(c.has_value());
  CHECK(c->trunk_id == a->trunk_id && c->offset == a->offset);

  // Freed slot no longer readable as data.
  CHECK(alloc.Free(*c));
  CHECK(!ReadSlotPayload(dir, *a, 1000).has_value());

  // Pool vs on-disk headers agree.
  std::string report;
  CHECK(alloc.VerifyFreeMap(&report) == 0);

  // Scan-rebuild (failover path): a fresh allocator sees the same world
  // and will not double-allocate B's live slot.
  TrunkAllocator alloc2;
  CHECK(alloc2.Init(dir, 1 << 20, &err));
  CHECK(alloc2.trunk_file_count() == 1);
  CHECK(alloc2.VerifyFreeMap(&report) == 0);
  auto d = alloc2.Alloc(5000);
  CHECK(d.has_value());
  CHECK(!(d->trunk_id == b->trunk_id && d->offset == b->offset));
  auto rb = ReadSlotPayload(dir, *b, 5000);
  CHECK(rb.has_value() && *rb == pb);

  // Oversized request refused; trunk-file exhaustion rolls to a new file.
  CHECK(!alloc2.Alloc(2 << 20).has_value());
}

static void TestTrunkReplicaWrite() {
  // WriteSlotPayload must create + extend the file on a replica that has
  // never allocated anything (sync replay path).
  std::string dir = TempDir();
  TrunkLocation loc;
  loc.trunk_id = 7;
  loc.offset = 123 * kTrunkAlignment;
  loc.alloc_size = 4 * kTrunkAlignment;
  std::string payload(900, 'z'), err;
  CHECK(WriteSlotPayload(dir, loc, payload, 42, &err));
  auto back = ReadSlotPayload(dir, loc, 900);
  CHECK(back.has_value() && *back == payload);
  CHECK(MarkSlotFree(dir, loc));
  CHECK(!ReadSlotPayload(dir, loc, 900).has_value());
}

static void TestTrunkReserveAndCompaction() {
  std::string dir = TempDir();
  TrunkAllocator alloc;
  std::string err;
  CHECK(alloc.Init(dir, 1 << 20, &err));
  CHECK(alloc.trunk_file_count() == 0);

  // Pre-allocation: demand a 3 MB reserve -> 3 fresh 1 MB trunk files,
  // all free; idempotent once satisfied.
  CHECK(alloc.EnsureFreeReserve(3 << 20) == 3);
  CHECK(alloc.trunk_file_count() == 3);
  CHECK(alloc.free_bytes() == 3 << 20);
  CHECK(alloc.EnsureFreeReserve(3 << 20) == 0);

  // Allocations now come from the reserve without creating files.
  auto a = alloc.Alloc(4000);
  CHECK(a.has_value());
  CHECK(alloc.trunk_file_count() == 3);

  // Compaction: with one slot live, exactly the OTHER fully-free files
  // beyond the keep=1 reserve are reclaimed.
  CHECK(alloc.ReclaimEmptyFiles(/*keep=*/1) == 1);
  std::string report;
  CHECK(alloc.VerifyFreeMap(&report) == 0);

  // The live slot still reads back; freeing it makes its file
  // reclaimable too (keep=0 clears everything).
  std::string pa(4000, 'q');
  CHECK(WriteSlotPayload(dir, *a, pa, 7, &err));
  auto ra = ReadSlotPayload(dir, *a, 4000);
  CHECK(ra.has_value() && *ra == pa);
  CHECK(alloc.Free(*a));
  CHECK(alloc.ReclaimEmptyFiles(/*keep=*/0) >= 1);

  // A scan-rebuild of the compacted dir agrees with the pool.
  TrunkAllocator alloc2;
  CHECK(alloc2.Init(dir, 1 << 20, &err));
  CHECK(alloc2.VerifyFreeMap(&report) == 0);
}

// -- chunk-store integrity engine (scrub/GC/quarantine) --------------------

static std::string Sha1HexOf(const std::string& data) {
  return Sha1(data.data(), data.size()).Hex();
}

static std::string ChunkStoreDir() {
  // ChunkStore expects the store path's data/ dir to exist (the daemon's
  // StoreManager pre-creates it).
  std::string dir = TempDir();
  mkdir((dir + "/data").c_str(), 0755);
  return dir;
}

static bool FileExists(const std::string& p) {
  struct stat st;
  return stat(p.c_str(), &st) == 0;
}

static void FlipFirstByte(const std::string& p) {
  FILE* f = fopen(p.c_str(), "r+b");
  CHECK(f != nullptr);
  int c = fgetc(f);
  fseek(f, 0, SEEK_SET);
  fputc(c ^ 0xFF, f);
  fclose(f);
}

static void TestChunkStoreGcGraceAndPins() {
  std::string dir = ChunkStoreDir();
  ChunkStore cs(dir, /*gc_grace_s=*/60);
  std::string payload(4096, 'x');
  std::string dig = Sha1HexOf(payload);
  bool existed = false;
  std::string err;
  CHECK(cs.PutAndRef(dig, payload.data(), payload.size(), &existed, &err));
  CHECK(!existed);

  Recipe r;
  r.logical_size = 4096;
  r.chunks.push_back({dig, 4096});

  // Grace mode: the last unref parks the chunk instead of unlinking.
  cs.UnrefAll(r);
  CHECK(FileExists(cs.ChunkPath(dig)));
  CHECK(cs.gc_pending_chunks() == 1);
  CHECK(cs.gc_pending_bytes() == 4096);

  // Inside the grace window nothing is reclaimed.
  int64_t bytes = 0;
  CHECK(cs.GcSweep(time(nullptr), &bytes) == 0);
  CHECK(bytes == 0);

  // REGRESSION (ISSUE 4 satellite): a phase-1 upload session pins the
  // chunk via PinAndMask — the pin probe runs under the SAME lock as
  // the sweep's unlink, and a pinned zero-ref chunk must survive a
  // sweep even past its grace.
  std::string need = cs.PinAndMask(r);
  CHECK(need.size() == 1);
  CHECK(need[0] == 1);  // zero-ref reads as "needed" (client re-ships)
  bytes = 0;
  CHECK(cs.GcSweep(time(nullptr) + 3600, &bytes) == 0);
  CHECK(FileExists(cs.ChunkPath(dig)));

  // The session commits: PutAndRef resurrects the parked bytes without
  // rewriting them.
  CHECK(cs.PutAndRef(dig, payload.data(), payload.size(), &existed, &err));
  CHECK(existed);
  CHECK(cs.gc_pending_chunks() == 0);
  cs.UnpinRecipe(r);
  bytes = 0;
  CHECK(cs.GcSweep(time(nullptr) + 3600, &bytes) == 0);  // live again

  // Drop the ref for real: past the grace (and unpinned) the sweep
  // reclaims bytes and count.
  cs.UnrefAll(r);
  bytes = 0;
  CHECK(cs.GcSweep(time(nullptr) + 3600, &bytes) == 1);
  CHECK(bytes == 4096);
  CHECK(!FileExists(cs.ChunkPath(dig)));
  CHECK(cs.gc_pending_chunks() == 0);
}

static void TestChunkStoreEagerModeUnchanged() {
  // gc_grace_s == 0 keeps the original semantics: unlink on last unref.
  std::string dir = ChunkStoreDir();
  ChunkStore cs(dir, 0);
  std::string payload(1024, 'y');
  std::string dig = Sha1HexOf(payload);
  bool existed = false;
  std::string err;
  CHECK(cs.PutAndRef(dig, payload.data(), payload.size(), &existed, &err));
  Recipe r;
  r.chunks.push_back({dig, 1024});
  cs.UnrefAll(r);
  CHECK(!FileExists(cs.ChunkPath(dig)));

  // Pinned delete still defers to the last unpin (stream semantics).
  CHECK(cs.PutAndRef(dig, payload.data(), payload.size(), &existed, &err));
  cs.PinRecipe(r);
  cs.UnrefAll(r);
  CHECK(FileExists(cs.ChunkPath(dig)));
  cs.UnpinRecipe(r);
  CHECK(!FileExists(cs.ChunkPath(dig)));
}

static void TestChunkStoreQuarantineRepairHeal() {
  std::string dir = ChunkStoreDir();
  ChunkStore cs(dir, 0);
  std::string payload(2048, 'q');
  std::string dig = Sha1HexOf(payload);
  bool existed = false;
  std::string err;
  CHECK(cs.PutAndRef(dig, payload.data(), payload.size(), &existed, &err));

  // Pinned chunks are exempt from quarantine (repair-in-place under a
  // live reader is unsafe).
  Recipe r;
  r.chunks.push_back({dig, 2048});
  cs.PinRecipe(r);
  CHECK(cs.Quarantine(dig) == ChunkStore::QuarantineResult::kPinned);
  cs.UnpinRecipe(r);

  // A clean chunk survives a false accusation: the under-lock re-hash
  // overrules the caller (the lock-free verify read may have raced).
  CHECK(cs.Quarantine(dig) == ChunkStore::QuarantineResult::kClean);
  FlipFirstByte(cs.ChunkPath(dig));
  CHECK(cs.Quarantine(dig) == ChunkStore::QuarantineResult::kQuarantined);
  CHECK(!FileExists(cs.ChunkPath(dig)));
  CHECK(FileExists(cs.QuarantinePath(dig)));
  CHECK(cs.quarantined_chunks() == 1);
  std::string back;
  CHECK(!cs.ReadChunk(dig, 2048, &back));  // never served again
  // Quarantined chunks read as missing so peers/clients re-ship bytes.
  CHECK(cs.HaveMask({dig})[0] == 1);
  // The live snapshot skips it; the quarantined snapshot names it.
  CHECK(cs.SnapshotLive().empty());
  CHECK(cs.SnapshotQuarantined().size() == 1);
  CHECK(cs.SnapshotQuarantined()[0].length == 2048);

  // Replica repair restores the bytes and clears the quarantine mark.
  CHECK(cs.RepairChunk(dig, payload.data(), payload.size(), &err));
  CHECK(cs.quarantined_chunks() == 0);
  CHECK(!FileExists(cs.QuarantinePath(dig)));
  CHECK(cs.ReadChunk(dig, 2048, &back));
  CHECK(back == payload);

  // Heal-on-upload: quarantine again, then a PutAndRef carrying the
  // payload (dedup hit) restores the bytes as a side effect.
  FlipFirstByte(cs.ChunkPath(dig));
  CHECK(cs.Quarantine(dig) == ChunkStore::QuarantineResult::kQuarantined);
  CHECK(cs.PutAndRef(dig, payload.data(), payload.size(), &existed, &err));
  CHECK(existed);
  CHECK(cs.quarantined_chunks() == 0);
  CHECK(cs.ReadChunk(dig, 2048, &back));
  CHECK(back == payload);

  // A deleted chunk cannot be quarantined or repaired (kGone / false).
  Recipe both;
  both.chunks.push_back({dig, 2048});
  both.chunks.push_back({dig, 2048});  // two refs taken above
  cs.UnrefAll(both);
  CHECK(cs.Quarantine(dig) == ChunkStore::QuarantineResult::kGone);
  CHECK(!cs.RepairChunk(dig, payload.data(), payload.size(), &err));
}

static void TestChunkStoreRebuildParksOrphansAndKeepsQuarantine() {
  std::string dir = ChunkStoreDir();
  std::string payload(4096, 'r');
  std::string dig = Sha1HexOf(payload);
  {
    ChunkStore cs(dir, 3600);
    bool existed = false;
    std::string err;
    CHECK(cs.PutAndRef(dig, payload.data(), payload.size(), &existed, &err));
    Recipe r;
    r.logical_size = 4096;
    r.chunks.push_back({dig, 4096});
    CHECK(WriteRecipeFile(dir + "/data/f.rcp", r, &err));
    // A second chunk never named by any recipe (an upload whose recipe
    // write crashed, or a zero-ref chunk awaiting GC at shutdown).
    std::string orphan(512, 'o');
    std::string odig = Sha1HexOf(orphan);
    CHECK(cs.PutAndRef(odig, orphan.data(), orphan.size(), &existed, &err));
    // Quarantine the recipe's (corrupted) chunk, then "restart".
    FlipFirstByte(cs.ChunkPath(dig));
    CHECK(cs.Quarantine(dig) == ChunkStore::QuarantineResult::kQuarantined);
  }
  ChunkStore cs2(dir, 3600);
  cs2.RebuildFromRecipes();
  // The referenced chunk is still quarantined after restart (its bytes
  // must not be re-admitted), and the orphan is parked for GC instead
  // of dropped — the grace window is crash-safe.
  CHECK(cs2.quarantined_chunks() == 1);
  CHECK(cs2.unique_chunks() == 1);
  CHECK(cs2.gc_pending_chunks() == 1);
  CHECK(cs2.HaveMask({dig})[0] == 1);
  std::string err;
  CHECK(cs2.RepairChunk(dig, payload.data(), payload.size(), &err));
  std::string back;
  CHECK(cs2.ReadChunk(dig, 4096, &back));
  CHECK(back == payload);
  int64_t bytes = 0;
  CHECK(cs2.GcSweep(time(nullptr) + 7200, &bytes) == 1);
  CHECK(bytes == 512);
}

int main() {
  TestBinlogRecordCodec();
  TestBinlogWriteReadResume();
  TestBinlogRotation();
  TestCpuDedup();
  TestStoreInit();
  TestTrunkAllocator();
  TestTrunkReserveAndCompaction();
  TestTrunkReplicaWrite();
  TestChunkStoreGcGraceAndPins();
  TestChunkStoreEagerModeUnchanged();
  TestChunkStoreQuarantineRepairHeal();
  TestChunkStoreRebuildParksOrphansAndKeepsQuarantine();
  if (g_failures == 0) {
    std::printf("storage_test: ALL PASS\n");
    return 0;
  }
  std::printf("storage_test: %d FAILURES\n", g_failures);
  return 1;
}
