// Unit tests for binlog + store + dedup units (the daemon itself is
// integration-tested from pytest via the Python client).
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <string>
#include <thread>
#include <vector>

#include "common/bytes.h"
#include "storage/binlog.h"
#include "storage/chunkstore.h"
#include "storage/ecstore.h"
#include "storage/dedup.h"
#include "storage/store.h"
#include "storage/trunk.h"

static int g_failures = 0;

#define CHECK(cond)                                                        \
  do {                                                                     \
    if (!(cond)) {                                                         \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      ++g_failures;                                                        \
    }                                                                      \
  } while (0)

using namespace fdfs;

static std::string TempDir() {
  char tmpl[] = "/tmp/fdfs_storage_test_XXXXXX";
  return mkdtemp(tmpl);
}

static void TestBinlogRecordCodec() {
  BinlogRecord rec;
  rec.timestamp = 1700000000;
  rec.op = 'C';
  rec.filename = "M00/AA/BB/name.jpg";
  std::string line = FormatBinlogRecord(rec);
  CHECK(line == "1700000000 C M00/AA/BB/name.jpg\n");
  auto back = ParseBinlogRecord(line);
  CHECK(back.has_value());
  CHECK(back->timestamp == rec.timestamp);
  CHECK(back->op == 'C');
  CHECK(back->filename == rec.filename);
  CHECK(back->extra.empty());

  rec.op = 'L';
  rec.extra = "M00/CC/DD/src.jpg";
  auto back2 = ParseBinlogRecord(FormatBinlogRecord(rec));
  CHECK(back2.has_value());
  CHECK(back2->extra == "M00/CC/DD/src.jpg");

  CHECK(!ParseBinlogRecord("garbage\n").has_value());
  CHECK(!ParseBinlogRecord("17 \n").has_value());
  CHECK(!ParseBinlogRecord("").has_value());
}

static void TestBinlogWriteReadResume() {
  std::string dir = TempDir();
  std::string err;
  BinlogWriter w;
  CHECK(w.Init(dir, 1 << 20, &err));
  for (int i = 0; i < 10; ++i)
    CHECK(w.Append('C', "M00/00/00/file" + std::to_string(i)));
  w.Flush();

  BinlogReader r;
  CHECK(r.Init(dir, dir + "/peer.mark", &err));
  for (int i = 0; i < 5; ++i) {
    auto rec = r.Next();
    CHECK(rec.has_value());
    CHECK(rec->filename == "M00/00/00/file" + std::to_string(i));
  }
  CHECK(r.SaveMark());

  // Fresh reader resumes from the mark.
  BinlogReader r2;
  CHECK(r2.Init(dir, dir + "/peer.mark", &err));
  auto rec = r2.Next();
  CHECK(rec.has_value());
  CHECK(rec->filename == "M00/00/00/file5");
  for (int i = 6; i < 10; ++i) CHECK(r2.Next().has_value());
  CHECK(!r2.Next().has_value());  // caught up

  // New writes become visible to the same reader (tailing).
  CHECK(w.Append('D', "M00/00/00/file3"));
  w.Flush();
  auto tail = r2.Next();
  CHECK(tail.has_value());
  CHECK(tail->op == 'D');
}

static void TestBinlogRotation() {
  std::string dir = TempDir();
  std::string err;
  BinlogWriter w;
  CHECK(w.Init(dir, 128, &err));  // tiny rotate size
  for (int i = 0; i < 20; ++i) CHECK(w.Append('C', "M00/00/00/f" + std::to_string(i)));
  w.Flush();
  CHECK(w.file_index() >= 1);  // rotated at least once

  BinlogReader r;
  CHECK(r.Init(dir, dir + "/m.mark", &err));
  int count = 0;
  while (r.Next().has_value()) ++count;
  CHECK(count == 20);  // reader follows rotation
}

static void TestCpuDedup() {
  std::string dir = TempDir();
  CpuDedup d(dir + "/dedup_index.dat");
  CHECK(!d.Judge("abc", 10).duplicate);
  d.Commit("abc", "group1/M00/00/00/x.bin");
  auto v = d.Judge("abc", 10);
  CHECK(v.duplicate);
  CHECK(v.dup_of == "group1/M00/00/00/x.bin");
  // snapshot round-trip
  CHECK(d.Save());
  CpuDedup d2(dir + "/dedup_index.dat");
  CHECK(d2.LoadSnapshot());
  CHECK(d2.Judge("abc", 10).duplicate);
  // forget
  d2.Forget("group1/M00/00/00/x.bin");
  CHECK(!d2.Judge("abc", 10).duplicate);
}

static void TestStoreInit() {
  std::string dir = TempDir();
  StorageConfig cfg;
  cfg.base_path = dir;
  cfg.store_paths = {dir};
  cfg.subdir_count_per_path = 4;
  StoreManager sm;
  std::string err;
  CHECK(sm.Init(cfg, &err));
  struct stat st;
  CHECK(stat((dir + "/data/03/03").c_str(), &st) == 0);
  CHECK(stat((dir + "/data/.data_init_flag").c_str(), &st) == 0);
  // second init is a no-op (flag present)
  CHECK(sm.Init(cfg, &err));
  // uniquifier wraps at 12 bits
  for (int i = 0; i < 5000; ++i) {
    int u = sm.NextUniquifier();
    CHECK(u >= 0 && u <= 0xFFF);
  }
  std::string t1 = sm.NewTmpPath(0), t2 = sm.NewTmpPath(0);
  CHECK(t1 != t2);
}


static void TestTrunkAllocator() {
  std::string dir = TempDir();
  TrunkAllocator alloc;
  std::string err;
  CHECK(alloc.Init(dir, 1 << 20, &err));  // 1 MB trunk files for the test
  CHECK(alloc.trunk_file_count() == 0);

  // First alloc creates a trunk file and splits it.
  auto a = alloc.Alloc(1000);
  CHECK(a.has_value());
  CHECK(a->trunk_id == 0 && a->offset == 0);
  CHECK(a->alloc_size >= 1000 + kTrunkHeaderSize);
  CHECK(alloc.trunk_file_count() == 1);

  // Second alloc lands after the first (split remainder).
  auto b = alloc.Alloc(5000);
  CHECK(b.has_value());
  CHECK(b->trunk_id == 0);
  CHECK(b->offset == a->alloc_size);

  // Write payloads and read them back.
  std::string pa(1000, 'x'), pb(5000, 'y');
  CHECK(WriteSlotPayload(dir, *a, pa, 111, &err));
  CHECK(WriteSlotPayload(dir, *b, pb, 222, &err));
  auto ra = ReadSlotPayload(dir, *a, 1000);
  CHECK(ra.has_value() && *ra == pa);

  // Free A; the same-size alloc reuses its exact slot.
  CHECK(alloc.Free(*a));
  auto c = alloc.Alloc(1000);
  CHECK(c.has_value());
  CHECK(c->trunk_id == a->trunk_id && c->offset == a->offset);

  // Freed slot no longer readable as data.
  CHECK(alloc.Free(*c));
  CHECK(!ReadSlotPayload(dir, *a, 1000).has_value());

  // Pool vs on-disk headers agree.
  std::string report;
  CHECK(alloc.VerifyFreeMap(&report) == 0);

  // Scan-rebuild (failover path): a fresh allocator sees the same world
  // and will not double-allocate B's live slot.
  TrunkAllocator alloc2;
  CHECK(alloc2.Init(dir, 1 << 20, &err));
  CHECK(alloc2.trunk_file_count() == 1);
  CHECK(alloc2.VerifyFreeMap(&report) == 0);
  auto d = alloc2.Alloc(5000);
  CHECK(d.has_value());
  CHECK(!(d->trunk_id == b->trunk_id && d->offset == b->offset));
  auto rb = ReadSlotPayload(dir, *b, 5000);
  CHECK(rb.has_value() && *rb == pb);

  // Oversized request refused; trunk-file exhaustion rolls to a new file.
  CHECK(!alloc2.Alloc(2 << 20).has_value());
}

static void TestTrunkReplicaWrite() {
  // WriteSlotPayload must create + extend the file on a replica that has
  // never allocated anything (sync replay path).
  std::string dir = TempDir();
  TrunkLocation loc;
  loc.trunk_id = 7;
  loc.offset = 123 * kTrunkAlignment;
  loc.alloc_size = 4 * kTrunkAlignment;
  std::string payload(900, 'z'), err;
  CHECK(WriteSlotPayload(dir, loc, payload, 42, &err));
  auto back = ReadSlotPayload(dir, loc, 900);
  CHECK(back.has_value() && *back == payload);
  CHECK(MarkSlotFree(dir, loc));
  CHECK(!ReadSlotPayload(dir, loc, 900).has_value());
}

static void TestTrunkReserveAndCompaction() {
  std::string dir = TempDir();
  TrunkAllocator alloc;
  std::string err;
  CHECK(alloc.Init(dir, 1 << 20, &err));
  CHECK(alloc.trunk_file_count() == 0);

  // Pre-allocation: demand a 3 MB reserve -> 3 fresh 1 MB trunk files,
  // all free; idempotent once satisfied.
  CHECK(alloc.EnsureFreeReserve(3 << 20) == 3);
  CHECK(alloc.trunk_file_count() == 3);
  CHECK(alloc.free_bytes() == 3 << 20);
  CHECK(alloc.EnsureFreeReserve(3 << 20) == 0);

  // Allocations now come from the reserve without creating files.
  auto a = alloc.Alloc(4000);
  CHECK(a.has_value());
  CHECK(alloc.trunk_file_count() == 3);

  // Compaction: with one slot live, exactly the OTHER fully-free files
  // beyond the keep=1 reserve are reclaimed.
  CHECK(alloc.ReclaimEmptyFiles(/*keep=*/1) == 1);
  std::string report;
  CHECK(alloc.VerifyFreeMap(&report) == 0);

  // The live slot still reads back; freeing it makes its file
  // reclaimable too (keep=0 clears everything).
  std::string pa(4000, 'q');
  CHECK(WriteSlotPayload(dir, *a, pa, 7, &err));
  auto ra = ReadSlotPayload(dir, *a, 4000);
  CHECK(ra.has_value() && *ra == pa);
  CHECK(alloc.Free(*a));
  CHECK(alloc.ReclaimEmptyFiles(/*keep=*/0) >= 1);

  // A scan-rebuild of the compacted dir agrees with the pool.
  TrunkAllocator alloc2;
  CHECK(alloc2.Init(dir, 1 << 20, &err));
  CHECK(alloc2.VerifyFreeMap(&report) == 0);
}

// -- chunk-store integrity engine (scrub/GC/quarantine) --------------------

static std::string Sha1HexOf(const std::string& data) {
  return Sha1(data.data(), data.size()).Hex();
}

static std::string ChunkStoreDir() {
  // ChunkStore expects the store path's data/ dir to exist (the daemon's
  // StoreManager pre-creates it).
  std::string dir = TempDir();
  mkdir((dir + "/data").c_str(), 0755);
  return dir;
}

static bool FileExists(const std::string& p) {
  struct stat st;
  return stat(p.c_str(), &st) == 0;
}

static void FlipFirstByte(const std::string& p) {
  FILE* f = fopen(p.c_str(), "r+b");
  CHECK(f != nullptr);
  int c = fgetc(f);
  fseek(f, 0, SEEK_SET);
  fputc(c ^ 0xFF, f);
  fclose(f);
}

static void TestChunkStoreGcGraceAndPins() {
  std::string dir = ChunkStoreDir();
  ChunkStore cs(dir, /*gc_grace_s=*/60);
  std::string payload(4096, 'x');
  std::string dig = Sha1HexOf(payload);
  bool existed = false;
  std::string err;
  CHECK(cs.PutAndRef(dig, payload.data(), payload.size(), &existed, &err));
  CHECK(!existed);

  Recipe r;
  r.logical_size = 4096;
  r.chunks.push_back({dig, 4096});

  // Grace mode: the last unref parks the chunk instead of unlinking.
  cs.UnrefAll(r);
  CHECK(FileExists(cs.ChunkPath(dig)));
  CHECK(cs.gc_pending_chunks() == 1);
  CHECK(cs.gc_pending_bytes() == 4096);

  // Inside the grace window nothing is reclaimed.
  int64_t bytes = 0;
  CHECK(cs.GcSweep(time(nullptr), &bytes) == 0);
  CHECK(bytes == 0);

  // REGRESSION (ISSUE 4 satellite): a phase-1 upload session pins the
  // chunk via PinAndMask — the pin probe runs under the SAME lock as
  // the sweep's unlink, and a pinned zero-ref chunk must survive a
  // sweep even past its grace.
  std::string need = cs.PinAndMask(r);
  CHECK(need.size() == 1);
  CHECK(need[0] == 1);  // zero-ref reads as "needed" (client re-ships)
  bytes = 0;
  CHECK(cs.GcSweep(time(nullptr) + 3600, &bytes) == 0);
  CHECK(FileExists(cs.ChunkPath(dig)));

  // The session commits: PutAndRef resurrects the parked bytes without
  // rewriting them.
  CHECK(cs.PutAndRef(dig, payload.data(), payload.size(), &existed, &err));
  CHECK(existed);
  CHECK(cs.gc_pending_chunks() == 0);
  cs.UnpinRecipe(r);
  bytes = 0;
  CHECK(cs.GcSweep(time(nullptr) + 3600, &bytes) == 0);  // live again

  // Drop the ref for real: past the grace (and unpinned) the sweep
  // reclaims bytes and count.
  cs.UnrefAll(r);
  bytes = 0;
  CHECK(cs.GcSweep(time(nullptr) + 3600, &bytes) == 1);
  CHECK(bytes == 4096);
  CHECK(!FileExists(cs.ChunkPath(dig)));
  CHECK(cs.gc_pending_chunks() == 0);
}

static void TestChunkStoreEagerModeUnchanged() {
  // gc_grace_s == 0 keeps the original semantics: unlink on last unref.
  std::string dir = ChunkStoreDir();
  ChunkStore cs(dir, 0);
  std::string payload(1024, 'y');
  std::string dig = Sha1HexOf(payload);
  bool existed = false;
  std::string err;
  CHECK(cs.PutAndRef(dig, payload.data(), payload.size(), &existed, &err));
  Recipe r;
  r.chunks.push_back({dig, 1024});
  cs.UnrefAll(r);
  CHECK(!FileExists(cs.ChunkPath(dig)));

  // Pinned delete still defers to the last unpin (stream semantics).
  CHECK(cs.PutAndRef(dig, payload.data(), payload.size(), &existed, &err));
  cs.PinRecipe(r);
  cs.UnrefAll(r);
  CHECK(FileExists(cs.ChunkPath(dig)));
  cs.UnpinRecipe(r);
  CHECK(!FileExists(cs.ChunkPath(dig)));
}

static void TestChunkStoreQuarantineRepairHeal() {
  std::string dir = ChunkStoreDir();
  ChunkStore cs(dir, 0);
  std::string payload(2048, 'q');
  std::string dig = Sha1HexOf(payload);
  bool existed = false;
  std::string err;
  CHECK(cs.PutAndRef(dig, payload.data(), payload.size(), &existed, &err));

  // Pinned chunks are exempt from quarantine (repair-in-place under a
  // live reader is unsafe).
  Recipe r;
  r.chunks.push_back({dig, 2048});
  cs.PinRecipe(r);
  CHECK(cs.Quarantine(dig) == ChunkStore::QuarantineResult::kPinned);
  cs.UnpinRecipe(r);

  // A clean chunk survives a false accusation: the under-lock re-hash
  // overrules the caller (the lock-free verify read may have raced).
  CHECK(cs.Quarantine(dig) == ChunkStore::QuarantineResult::kClean);
  FlipFirstByte(cs.ChunkPath(dig));
  CHECK(cs.Quarantine(dig) == ChunkStore::QuarantineResult::kQuarantined);
  CHECK(!FileExists(cs.ChunkPath(dig)));
  CHECK(FileExists(cs.QuarantinePath(dig)));
  CHECK(cs.quarantined_chunks() == 1);
  std::string back;
  CHECK(!cs.ReadChunk(dig, 2048, &back));  // never served again
  // Quarantined chunks read as missing so peers/clients re-ship bytes.
  CHECK(cs.HaveMask({dig})[0] == 1);
  // The live snapshot skips it; the quarantined snapshot names it.
  CHECK(cs.SnapshotLive().empty());
  CHECK(cs.SnapshotQuarantined().size() == 1);
  CHECK(cs.SnapshotQuarantined()[0].length == 2048);

  // Replica repair restores the bytes and clears the quarantine mark.
  CHECK(cs.RepairChunk(dig, payload.data(), payload.size(), &err));
  CHECK(cs.quarantined_chunks() == 0);
  CHECK(!FileExists(cs.QuarantinePath(dig)));
  CHECK(cs.ReadChunk(dig, 2048, &back));
  CHECK(back == payload);

  // Heal-on-upload: quarantine again, then a PutAndRef carrying the
  // payload (dedup hit) restores the bytes as a side effect.
  FlipFirstByte(cs.ChunkPath(dig));
  CHECK(cs.Quarantine(dig) == ChunkStore::QuarantineResult::kQuarantined);
  CHECK(cs.PutAndRef(dig, payload.data(), payload.size(), &existed, &err));
  CHECK(existed);
  CHECK(cs.quarantined_chunks() == 0);
  CHECK(cs.ReadChunk(dig, 2048, &back));
  CHECK(back == payload);

  // A deleted chunk cannot be quarantined or repaired (kGone / false).
  Recipe both;
  both.chunks.push_back({dig, 2048});
  both.chunks.push_back({dig, 2048});  // two refs taken above
  cs.UnrefAll(both);
  CHECK(cs.Quarantine(dig) == ChunkStore::QuarantineResult::kGone);
  CHECK(!cs.RepairChunk(dig, payload.data(), payload.size(), &err));
}

static void TestChunkStoreRebuildParksOrphansAndKeepsQuarantine() {
  std::string dir = ChunkStoreDir();
  std::string payload(4096, 'r');
  std::string dig = Sha1HexOf(payload);
  {
    ChunkStore cs(dir, 3600);
    bool existed = false;
    std::string err;
    CHECK(cs.PutAndRef(dig, payload.data(), payload.size(), &existed, &err));
    Recipe r;
    r.logical_size = 4096;
    r.chunks.push_back({dig, 4096});
    CHECK(WriteRecipeFile(dir + "/data/f.rcp", r, &err));
    // A second chunk never named by any recipe (an upload whose recipe
    // write crashed, or a zero-ref chunk awaiting GC at shutdown).
    std::string orphan(512, 'o');
    std::string odig = Sha1HexOf(orphan);
    CHECK(cs.PutAndRef(odig, orphan.data(), orphan.size(), &existed, &err));
    // Quarantine the recipe's (corrupted) chunk, then "restart".
    FlipFirstByte(cs.ChunkPath(dig));
    CHECK(cs.Quarantine(dig) == ChunkStore::QuarantineResult::kQuarantined);
  }
  ChunkStore cs2(dir, 3600);
  cs2.RebuildFromRecipes();
  // The referenced chunk is still quarantined after restart (its bytes
  // must not be re-admitted), and the orphan is parked for GC instead
  // of dropped — the grace window is crash-safe.
  CHECK(cs2.quarantined_chunks() == 1);
  CHECK(cs2.unique_chunks() == 1);
  CHECK(cs2.gc_pending_chunks() == 1);
  CHECK(cs2.HaveMask({dig})[0] == 1);
  std::string err;
  CHECK(cs2.RepairChunk(dig, payload.data(), payload.size(), &err));
  std::string back;
  CHECK(cs2.ReadChunk(dig, 4096, &back));
  CHECK(back == payload);
  int64_t bytes = 0;
  CHECK(cs2.GcSweep(time(nullptr) + 7200, &bytes) == 1);
  CHECK(bytes == 512);
}

static void TestChunkStoreReadRecipeAndPinRange() {
  std::string dir = ChunkStoreDir();
  ChunkStore cs(dir, 0);
  Recipe r;
  r.logical_size = 0;
  std::vector<std::string> digs;
  bool existed = false;
  std::string err;
  for (int i = 0; i < 3; ++i) {
    std::string pay(100, static_cast<char>('a' + i));
    digs.push_back(Sha1HexOf(pay));
    CHECK(cs.PutAndRef(digs.back(), pay.data(), pay.size(), &existed, &err));
    r.chunks.push_back({digs.back(), 100});
    r.logical_size += 100;
  }
  std::string rcp = dir + "/data/rng.rcp";
  CHECK(WriteRecipeFile(rcp, r, &err));

  // Mid-file range trims to the overlapping slice only.
  int64_t skip = -1;
  auto t = cs.ReadRecipeAndPinRange(rcp, 150, 100, &skip);
  CHECK(t.has_value() && t->logical_size == 300);
  CHECK(t->chunks.size() == 2 && skip == 50);
  CHECK(t->chunks[0].digest_hex == digs[1]);
  cs.UnpinRecipe(*t);

  // count 0 = to EOF; offset 0 covers everything.
  t = cs.ReadRecipeAndPinRange(rcp, 0, 0, &skip);
  CHECK(t.has_value() && t->chunks.size() == 3 && skip == 0);
  cs.UnpinRecipe(*t);

  // Offset past EOF: EMPTY slice (caller answers EINVAL), not nullopt.
  t = cs.ReadRecipeAndPinRange(rcp, 1000, 10, &skip);
  CHECK(t.has_value() && t->chunks.empty());
  cs.UnpinRecipe(*t);

  // A deleted chunk inside the range fails the pin (rollback, ENOENT);
  // a range NOT touching it still pins fine.
  Recipe one;
  one.chunks.push_back({digs[2], 100});
  cs.UnrefAll(one);
  CHECK(!cs.ReadRecipeAndPinRange(rcp, 150, 0, &skip).has_value());
  t = cs.ReadRecipeAndPinRange(rcp, 0, 150, &skip);
  CHECK(t.has_value() && t->chunks.size() == 2);
  cs.UnpinRecipe(*t);
}

static void TestChunkStoreReadCacheCoherence() {
  std::string dir = ChunkStoreDir();
  ChunkStore cs(dir, 0, /*read_cache_bytes=*/1 << 20);
  std::string payload(4096, 'c');
  std::string dig = Sha1HexOf(payload);
  bool existed = false;
  std::string err;
  CHECK(cs.PutAndRef(dig, payload.data(), payload.size(), &existed, &err));

  bool hit = false;
  auto p = cs.ReadChunkCached(dig, 4096, &hit);
  CHECK(p != nullptr && !hit && *p == payload);
  p = cs.ReadChunkCached(dig, 4096, &hit);
  CHECK(p != nullptr && hit && *p == payload);
  CHECK(cs.cache_hits() == 1 && cs.cache_misses() == 1);
  CHECK(cs.cache_chunks() == 1 && cs.cache_bytes() == 4096);
  CHECK(cs.CacheLookup(dig, 4096) != nullptr);

  // Quarantine invalidates in the SAME lock acquisition: a jailed
  // chunk must never be served from the cache.
  FlipFirstByte(cs.ChunkPath(dig));
  CHECK(cs.Quarantine(dig) == ChunkStore::QuarantineResult::kQuarantined);
  CHECK(cs.CacheLookup(dig, 4096) == nullptr);
  p = cs.ReadChunkCached(dig, 4096, &hit);
  CHECK(p == nullptr && !hit);  // bytes are in quarantine/, unreadable
  CHECK(cs.cache_invalidations() == 1);

  // Repair restores service with the verified bytes (fresh read).
  CHECK(cs.RepairChunk(dig, payload.data(), payload.size(), &err));
  p = cs.ReadChunkCached(dig, 4096, &hit);
  CHECK(p != nullptr && !hit && *p == payload);

  // A held shared_ptr survives eviction/invalidation (a response mid-
  // scatter keeps its bytes), but the cache itself forgets the entry
  // when the delete's unlink retires the chunk.
  auto held = cs.ReadChunkCached(dig, 4096, &hit);
  Recipe r;
  r.chunks.push_back({dig, 4096});
  cs.UnrefAll(r);  // eager mode: unlink now
  CHECK(cs.CacheLookup(dig, 4096) == nullptr);
  CHECK(cs.ReadChunkCached(dig, 4096, &hit) == nullptr);
  CHECK(held != nullptr && *held == payload);

  // An insert racing a delete must not publish a stale entry: the
  // insert re-checks liveness under the stripe lock, so a dead digest
  // never enters the cache.
  CHECK(cs.cache_chunks() == 0);

  // Capacity bound: filling past cap evicts LRU-first and the byte
  // gauge stays under cap.
  ChunkStore small(ChunkStoreDir(), 0, /*read_cache_bytes=*/8 << 10);
  std::string first_dig;
  for (int i = 0; i < 4; ++i) {
    std::string pay(4 << 10, static_cast<char>('a' + i));
    std::string d = Sha1HexOf(pay);
    if (i == 0) first_dig = d;
    CHECK(small.PutAndRef(d, pay.data(), pay.size(), &existed, &err));
    CHECK(small.ReadChunkCached(d, 4 << 10, &hit) != nullptr);
  }
  CHECK(small.cache_bytes() <= (8 << 10));
  CHECK(small.cache_evictions() >= 2);
  CHECK(small.CacheLookup(first_dig, 4 << 10) == nullptr);  // LRU victim
}

// -- slab packing (ISSUE 9) -----------------------------------------------

static void TestSlabRecordCodec() {
  std::string payload = "slab payload bytes 0123456789";
  std::string key = Sha1(payload.data(), payload.size()).Hex();
  std::string rec =
      SlabEncodeRecord(kSlabKindChunk, key, payload.data(), payload.size(),
                       1700000000);
  CHECK(rec.size() == kSlabRecordHeaderSize + key.size() + payload.size());
  SlabRecordView v;
  CHECK(SlabDecodeRecord(rec.data(), rec.size(), &v));
  CHECK(v.kind == kSlabKindChunk);
  CHECK(v.key == key);
  CHECK(v.payload_len == static_cast<int64_t>(payload.size()));
  CHECK(v.alloc_len == v.payload_len);
  CHECK(v.mtime == 1700000000);
  CHECK(v.flags == 0);
  CHECK(v.payload_crc32 == Crc32(payload.data(), payload.size()));
  CHECK(v.record_len == static_cast<int64_t>(rec.size()));
  // The dead-flag flip must NOT invalidate the header CRC (it is
  // computed with flags zeroed) — MarkDead relies on this.
  std::string dead = rec;
  dead[6] = 0x01;
  SlabRecordView vd;
  CHECK(SlabDecodeRecord(dead.data(), dead.size(), &vd));
  CHECK(vd.flags == 0x01);
  // Any OTHER header corruption must fail the frame.
  std::string bad = rec;
  bad[10] ^= 0x40;
  CHECK(!SlabDecodeRecord(bad.data(), bad.size(), &v));
  bad = rec;
  bad[0] = 'X';
  CHECK(!SlabDecodeRecord(bad.data(), bad.size(), &v));
  CHECK(!SlabDecodeRecord(rec.data(), kSlabRecordHeaderSize - 1, &v));
}

static void TestSlabStoreAppendRescanCompact() {
  std::string dir = TempDir();
  std::string slabs = dir + "/slabs";
  auto payload_for = [](int i) {
    return std::string(200 + i, static_cast<char>('a' + (i % 26)));
  };
  auto key_for = [&](int i) {
    std::string p = payload_for(i);
    return Sha1(p.data(), p.size()).Hex();
  };
  {
    SlabStore ss(slabs, 1 << 20, 25);
    ss.ScanRebuild();  // empty dir: no-op
    std::string err;
    for (int i = 0; i < 20; ++i) {
      std::string p = payload_for(i);
      CHECK(ss.Append(kSlabKindChunk, key_for(i), p.data(), p.size(),
                      false, &err));
    }
    std::string rcp = "data/00/00/file.bin.rcp";
    CHECK(ss.Append(kSlabKindRecipe, rcp, "RECIPE", 6, true, &err));
    CHECK(ss.slots_live() == 21);
    CHECK(ss.slots_dead() == 0);
    CHECK(ss.files() == 1);
    std::string back;
    CHECK(ss.Read(kSlabKindChunk, key_for(3), &back));
    CHECK(back == payload_for(3));
    char slice[8];
    CHECK(ss.ReadSlice(kSlabKindChunk, key_for(3), 2, 8, slice));
    CHECK(memcmp(slice, payload_for(3).data() + 2, 8) == 0);
    CHECK(!ss.ReadSlice(kSlabKindChunk, key_for(3), 200, 100, slice));
    // Replace semantics: re-append of an existing key kills the old.
    std::string p5 = payload_for(5);
    CHECK(ss.Append(kSlabKindChunk, key_for(5), p5.data(), p5.size(),
                    false, &err));
    CHECK(ss.slots_live() == 21);
    CHECK(ss.slots_dead() == 1);
  }
  {
    // Boot rescan rebuilds the same index from raw headers.
    SlabStore ss(slabs, 1 << 20, 25);
    ss.ScanRebuild();
    CHECK(ss.slots_live() == 21);
    CHECK(ss.slots_dead() == 1);
    std::string back;
    CHECK(ss.Read(kSlabKindRecipe, "data/00/00/file.bin.rcp", &back));
    CHECK(back == "RECIPE");
    // Torn tail: append garbage, rescan truncates it away.
    std::string path;
    {
      char name[64];
      snprintf(name, sizeof(name), "%s/%010d.slab", slabs.c_str(), 1);
      path = name;
    }
    FILE* f = fopen(path.c_str(), "ab");
    CHECK(f != nullptr);
    fwrite("FSLBgarbage-torn-tail", 1, 21, f);
    fclose(f);
    struct stat st0;
    CHECK(stat(path.c_str(), &st0) == 0);
    SlabStore ss2(slabs, 1 << 20, 25);
    ss2.ScanRebuild();
    CHECK(ss2.slots_live() == 21);
    struct stat st1;
    CHECK(stat(path.c_str(), &st1) == 0);
    CHECK(st1.st_size == st0.st_size - 21);
    // Kill most slots, compact, and verify the survivors re-read
    // byte-identically from the new slab while the victim is gone.
    for (int i = 0; i < 16; ++i)
      CHECK(ss2.MarkDead(kSlabKindChunk, key_for(i)));
    int64_t before_files = ss2.files();
    auto res = ss2.Compact(nullptr, nullptr);
    (void)before_files;
    CHECK(res.slabs_compacted == 1);
    CHECK(res.reclaimed_bytes > 0);
    CHECK(ss2.slots_dead() == 0);
    CHECK(ss2.compactions() == 1);
    for (int i = 16; i < 20; ++i) {
      CHECK(ss2.Read(kSlabKindChunk, key_for(i), &back));
      CHECK(back == payload_for(i));
    }
    CHECK(ss2.Read(kSlabKindRecipe, "data/00/00/file.bin.rcp", &back));
    CHECK(back == "RECIPE");
    CHECK(stat(path.c_str(), &st1) != 0);  // victim unlinked
  }
}

static void TestChunkStoreSlabEndToEnd() {
  std::string dir = TempDir();
  SlabOptions so;
  so.chunk_threshold = 4096;
  so.recipe_threshold = 4096;
  so.slab_bytes = 1 << 20;
  so.compact_min_dead_pct = 10;
  ChunkStore cs(dir, /*gc_grace_s=*/0, /*cache=*/1 << 20, so);
  cs.RebuildFromRecipes();
  std::string err;
  // Small chunks land in the slab (no per-chunk inode); big ones flat.
  std::string small(1000, 's'), big(8000, 'b');
  std::string dsmall = Sha1(small.data(), small.size()).Hex();
  std::string dbig = Sha1(big.data(), big.size()).Hex();
  bool existed = false;
  CHECK(cs.PutAndRef(dsmall, small.data(), small.size(), &existed, &err));
  CHECK(cs.PutAndRef(dbig, big.data(), big.size(), &existed, &err));
  struct stat st;
  CHECK(stat(cs.ChunkPath(dsmall).c_str(), &st) != 0);  // slab-resident
  CHECK(stat(cs.ChunkPath(dbig).c_str(), &st) == 0);    // flat
  CHECK(cs.slab_slots_live() == 1);
  std::string back;
  CHECK(cs.ReadChunk(dsmall, 1000, &back) && back == small);
  char part[16];
  CHECK(cs.ReadChunkSlice(dsmall, 10, 16, part));
  CHECK(memcmp(part, small.data() + 10, 16) == 0);
  bool hit = false;
  auto p = cs.ReadChunkCached(dsmall, 1000, &hit);
  CHECK(p != nullptr && *p == small && !hit);
  p = cs.ReadChunkCached(dsmall, 1000, &hit);
  CHECK(p != nullptr && hit);
  // Recipes below the threshold pack too: no sidecar inode.
  Recipe r;
  r.logical_size = 9000;
  r.chunks.push_back({dsmall, 1000});
  r.chunks.push_back({dbig, 8000});
  std::string rcp = dir + "/data/00/00/f.bin.rcp";
  StoreManager::EnsureParentDirs(rcp);
  CHECK(cs.StoreRecipe(rcp, r, &err));
  CHECK(stat(rcp.c_str(), &st) != 0);  // slab record, not an inode
  CHECK(cs.HasRecipe(rcp));
  auto got = cs.LoadRecipe(rcp);
  CHECK(got.has_value() && got->chunks.size() == 2 &&
        got->chunks[0].digest_hex == dsmall);
  auto pinned = cs.ReadRecipeAndPin(rcp);
  CHECK(pinned.has_value());
  cs.UnpinRecipe(*pinned);
  // Boot rescan: refs rebuilt from the slab-resident recipe.
  ChunkStore cs2(dir, 0, 0, so);
  cs2.RebuildFromRecipes();
  CHECK(cs2.Has(dsmall) && cs2.Has(dbig));
  CHECK(cs2.ReadChunk(dsmall, 1000, &back) && back == small);
  // Quarantine a slab-resident chunk: record dies, bytes preserved in
  // quarantine/, heal-on-upload re-appends a fresh record.
  {
    SlabStore::Slot slot;
    CHECK(cs2.slab()->Lookup(kSlabKindChunk, dsmall, &slot));
    char name[64];
    snprintf(name, sizeof(name), "%s/data/slabs/%010lld.slab", dir.c_str(),
             static_cast<long long>(slot.slab_id));
    FILE* f = fopen(name, "r+b");
    CHECK(f != nullptr);
    fseek(f, static_cast<long>(slot.payload_off), SEEK_SET);
    fputc('X', f);
    fclose(f);
  }
  CHECK(cs2.Quarantine(dsmall) == ChunkStore::QuarantineResult::kQuarantined);
  CHECK(!cs2.ReadChunk(dsmall, 1000, &back));
  CHECK(cs2.IsQuarantined(dsmall));
  bool existed2 = false;
  CHECK(cs2.PutAndRef(dsmall, small.data(), small.size(), &existed2, &err));
  CHECK(existed2);
  CHECK(!cs2.IsQuarantined(dsmall));
  CHECK(cs2.ReadChunk(dsmall, 1000, &back) && back == small);
  // Delete -> dead accounting -> compaction reclaims, survivors intact.
  int64_t dead_before = cs2.slab_bytes_dead();
  int64_t rcp_bytes = 0;
  CHECK(cs2.RemoveRecipe(rcp, &rcp_bytes));
  CHECK(rcp_bytes > 0);
  Recipe unref;
  unref.chunks.push_back({dsmall, 1000});
  unref.chunks.push_back({dbig, 8000});
  cs2.UnrefAll(unref);
  CHECK(cs2.slab_bytes_dead() > dead_before);
  std::vector<ChunkStore::ChunkInfo> corrupt;
  int64_t reclaimed = 0;
  (void)cs2.CompactSlabs(nullptr, nullptr, &corrupt, &reclaimed);
  CHECK(corrupt.empty());
  CHECK(cs2.slab_slots_dead() == 0);
}

static void TestChunkStoreSlabConcurrency() {
  // compact-vs-download and compact-vs-upload at the unit level: writer
  // / reader / deleter threads race a compaction loop on a tiny-slab
  // store.  TSan + FDFS_LOCKRANK builds are the real assertion here;
  // wrong_bytes pins byte-identical reads throughout.
  std::string dir = TempDir();
  SlabOptions so;
  so.chunk_threshold = 64 << 10;
  so.slab_bytes = 1 << 20;  // clamp floor: rolls often under churn
  so.compact_min_dead_pct = 1;
  ChunkStore cs(dir, 0, 1 << 20, so);
  cs.RebuildFromRecipes();
  constexpr int kChunks = 64;
  std::vector<std::string> payloads, digs;
  for (int i = 0; i < kChunks; ++i) {
    payloads.push_back(std::string(3000 + 131 * i,
                                   static_cast<char>('a' + i % 26)));
    digs.push_back(Sha1(payloads[i].data(), payloads[i].size()).Hex());
  }
  std::atomic<bool> stop{false};
  std::atomic<int> wrong_bytes{0};
  auto churn = [&](unsigned seed) {
    unsigned s = seed;
    while (!stop.load()) {
      int i = static_cast<int>(rand_r(&s) % kChunks);
      bool existed = false;
      std::string err;
      if (!cs.PutAndRef(digs[i], payloads[i].data(), payloads[i].size(),
                        &existed, &err))
        wrong_bytes.fetch_add(1);
      Recipe r;
      r.chunks.push_back({digs[i], static_cast<int64_t>(
                                       payloads[i].size())});
      if (rand_r(&s) % 2) cs.UnrefAll(r);
    }
  };
  auto reader = [&] {
    std::string back;
    unsigned s = 99;
    while (!stop.load()) {
      int i = static_cast<int>(rand_r(&s) % kChunks);
      Recipe r;
      r.chunks.push_back({digs[i], static_cast<int64_t>(
                                       payloads[i].size())});
      cs.PinRecipe(r);
      if (cs.Has(digs[i]) &&
          cs.ReadChunk(digs[i],
                       static_cast<int64_t>(payloads[i].size()), &back) &&
          back != payloads[i])
        wrong_bytes.fetch_add(1);
      cs.UnpinRecipe(r);
    }
  };
  auto compactor = [&] {
    while (!stop.load()) {
      std::vector<ChunkStore::ChunkInfo> corrupt;
      int64_t reclaimed = 0;
      cs.CompactSlabs(nullptr, [&] { return stop.load(); }, &corrupt,
                      &reclaimed);
      if (!corrupt.empty()) wrong_bytes.fetch_add(1);
      usleep(1000);
    }
  };
  std::vector<std::thread> ts;
  ts.emplace_back(churn, 7u);
  ts.emplace_back(churn, 11u);
  ts.emplace_back(reader);
  ts.emplace_back(reader);
  ts.emplace_back(compactor);
  usleep(400 * 1000);
  stop = true;
  for (auto& t : ts) t.join();
  CHECK(wrong_bytes.load() == 0);
  // Quiesced sanity: every live digest still reads byte-identical.
  std::string back;
  for (int i = 0; i < kChunks; ++i) {
    if (cs.Has(digs[i]))
      CHECK(cs.ReadChunk(digs[i],
                         static_cast<int64_t>(payloads[i].size()), &back) &&
            back == payloads[i]);
  }
  CHECK(cs.slab_slots_live() >= 0 && cs.slab_bytes_dead() >= 0);
}

static void TestChunkStoreStripedConcurrency() {
  // Hammer the striped store from four mutator families at once —
  // uploads/deletes, pin/unpin sessions, cached reads, and a
  // scrub-style quarantine/sweep loop.  Run under TSan via
  // tools/run_sanitizers.sh; the invariant checks at the end catch
  // lost-update bugs even in an uninstrumented build.
  std::string dir = ChunkStoreDir();
  ChunkStore cs(dir, 0, /*read_cache_bytes=*/1 << 20);
  constexpr int kChunks = 32;
  std::vector<std::string> payloads, digs;
  for (int i = 0; i < kChunks; ++i) {
    payloads.push_back(std::string(2048, 'A') + std::to_string(i));
    digs.push_back(Sha1HexOf(payloads.back()));
  }
  std::atomic<bool> stop{false};
  std::atomic<int64_t> wrong_bytes{0};
  auto churn = [&](unsigned seed) {
    unsigned r = seed;
    bool existed;
    std::string err;
    while (!stop.load()) {
      int i = static_cast<int>(r = r * 1103515245 + 12345) % kChunks;
      if (i < 0) i += kChunks;
      CHECK(cs.PutAndRef(digs[i], payloads[i].data(), payloads[i].size(),
                         &existed, &err));
      Recipe one;
      one.chunks.push_back(
          {digs[i], static_cast<int64_t>(payloads[i].size())});
      cs.UnrefAll(one);
    }
  };
  auto reader = [&] {
    unsigned r = 7;
    while (!stop.load()) {
      int i = static_cast<int>(r = r * 1103515245 + 12345) % kChunks;
      if (i < 0) i += kChunks;
      bool hit = false;
      auto p = cs.ReadChunkCached(digs[i],
                                  static_cast<int64_t>(payloads[i].size()),
                                  &hit);
      // A concurrent delete may legitimately make the read fail; bytes
      // that DO come back must be exact (the zero-wrong-bytes bar).
      if (p != nullptr && *p != payloads[i]) wrong_bytes++;
    }
  };
  auto pinner = [&] {
    Recipe all;
    for (int i = 0; i < kChunks; ++i)
      all.chunks.push_back(
          {digs[i], static_cast<int64_t>(payloads[i].size())});
    while (!stop.load()) {
      std::string need = cs.PinAndMask(all);
      CHECK(need.size() == static_cast<size_t>(kChunks));
      cs.UnpinRecipe(all);
    }
  };
  auto sweeper = [&] {
    while (!stop.load()) {
      int64_t bytes = 0;
      cs.GcSweep(time(nullptr) + 10, &bytes);
      for (int i = 0; i < kChunks; i += 5) (void)cs.Quarantine(digs[i]);
      (void)cs.SnapshotLive();
      (void)cs.unique_chunks();
    }
  };
  std::vector<std::thread> ts;
  ts.emplace_back(churn, 1u);
  ts.emplace_back(churn, 2u);
  ts.emplace_back(reader);
  ts.emplace_back(reader);
  ts.emplace_back(pinner);
  ts.emplace_back(sweeper);
  usleep(400 * 1000);
  stop = true;
  for (auto& t : ts) t.join();
  CHECK(wrong_bytes.load() == 0);
  // Quiesced: accounting must be internally consistent.
  CHECK(cs.unique_chunks() >= 0);
  CHECK(cs.gc_pending_chunks() == 0);  // eager mode, nothing pinned now
  CHECK(cs.cache_bytes() <= (1 << 20));
  // Every digest is either live-and-readable or fully gone.
  for (int i = 0; i < kChunks; ++i) {
    std::string back;
    if (cs.Has(digs[i]) && !cs.IsQuarantined(digs[i]))
      CHECK(cs.ReadChunk(digs[i], static_cast<int64_t>(payloads[i].size()),
                         &back) &&
            back == payloads[i]);
  }
}

static void TestRsCodecKillAnyM() {
  // RS(k, m) must survive EVERY combination of m shard losses, not a
  // lucky subset — walk all C(k+m, m) loss patterns for a small
  // geometry and a couple of ragged lengths.
  const int k = 4, m = 2;
  for (int64_t shard_len : {int64_t{1}, int64_t{31}, int64_t{256}}) {
    std::vector<std::string> data;
    for (int i = 0; i < k; ++i) {
      std::string s(static_cast<size_t>(shard_len), '\0');
      for (int64_t b = 0; b < shard_len; ++b)
        s[static_cast<size_t>(b)] =
            static_cast<char>((i * 131 + b * 29 + 7) & 0xFF);
      data.push_back(std::move(s));
    }
    std::vector<std::string> parity = RsEncode(data, m);
    CHECK(static_cast<int>(parity.size()) == m);
    std::vector<std::string> full = data;
    for (auto& p : parity) full.push_back(p);
    for (int a = 0; a < k + m; ++a) {
      for (int b = a + 1; b < k + m; ++b) {
        std::vector<std::string> shards = full;
        shards[a].clear();
        shards[b].clear();
        CHECK(RsReconstruct(&shards, k, m, shard_len));
        for (int i = 0; i < k + m; ++i) CHECK(shards[i] == full[i]);
      }
    }
    // m + 1 losses must FAIL, not fabricate bytes.
    std::vector<std::string> shards = full;
    shards[0].clear();
    shards[2].clear();
    shards[5].clear();
    CHECK(!RsReconstruct(&shards, k, m, shard_len));
  }
}

static void TestEcStoreStripeLifecycle() {
  std::string dir = TempDir();
  std::vector<std::pair<std::string, std::string>> chunks;
  for (int i = 0; i < 3; ++i) {
    std::string pay(200 + 37 * i, static_cast<char>('p' + i));
    chunks.emplace_back(Sha1HexOf(pay), pay);
  }
  int64_t id = -1;
  {
    EcStore ec(dir, 3, 2);
    std::string err;
    id = ec.EncodeStripe(chunks, &err);
    CHECK(id >= 0);
    CHECK(ec.VerifyStripe(id, &err));
    CHECK(ec.stripes() == 1);
    CHECK(ec.stripe_chunks() == 3);
    for (auto& c : chunks) {
      std::string out;
      CHECK(ec.Has(c.first));
      CHECK(ec.ReadChunk(c.first, &out) && out == c.second);
      // Positional read across the whole payload and a mid slice.
      std::string slice(5, '\0');
      CHECK(ec.ReadChunkSlice(c.first, 3, 5, slice.data()));
      CHECK(slice == c.second.substr(3, 5));
    }
  }
  // Cold restart adopts the stripe from the manifest alone.
  EcStore ec(dir, 3, 2);
  CHECK(ec.Rescan() == 1);
  CHECK(ec.stripe_chunks() == 3);
  // Corrupt one shard payload in place: the scrub repair must detect
  // it via CRC and rebuild it from parity, in place.
  {
    char shard[64];
    snprintf(shard, sizeof(shard), "/%010lld.s01", (long long)id);
    FlipFirstByte(dir + shard);  // header magic => header CRC fail
  }
  std::vector<EcStore::ChunkRef> lost;
  int64_t rebuilt = 0, rb = 0, rd = 0;
  CHECK(ec.VerifyRepairStripe(id, &lost, &rebuilt, &rb, &rd) ==
        EcStore::StripeHealth::kRepaired);
  CHECK(rebuilt == 1 && rb > 0);
  CHECK(ec.VerifyRepairStripe(id, &lost, &rebuilt, &rb, &rd) ==
        EcStore::StripeHealth::kHealthy);
  // Lose MORE than m shards: kLost must list the live chunks so the
  // caller can re-promote them, and DropStripe reclaims the carcass.
  for (int s = 0; s < 3; ++s) {
    char shard[64];
    snprintf(shard, sizeof(shard), "/%010lld.s%02d", (long long)id, s);
    unlink((dir + shard).c_str());
  }
  lost.clear();
  CHECK(ec.VerifyRepairStripe(id, &lost, &rebuilt, &rb, &rd) ==
        EcStore::StripeHealth::kLost);
  CHECK(lost.size() == 3);
  int64_t reclaimed = 0;
  ec.DropStripe(id, &reclaimed);
  CHECK(ec.stripes() == 0);
  CHECK(!ec.Has(chunks[0].first));

  // MarkDead reclaims the whole stripe when its last live chunk dies.
  std::string err;
  int64_t id2 = ec.EncodeStripe(chunks, &err);
  CHECK(id2 >= 0);
  int64_t freed = 0;
  CHECK(ec.MarkDead(chunks[0].first, &freed) && freed == 0);
  CHECK(ec.MarkDead(chunks[1].first, &freed) && freed == 0);
  CHECK(ec.MarkDead(chunks[2].first, &freed));
  CHECK(freed > 0);  // parity included
  CHECK(ec.stripes() == 0);

  // release.map: append + torn-tail-tolerant replay + clear.
  std::vector<std::pair<std::string, int64_t>> batch = {
      {chunks[0].first, 200}, {chunks[1].first, 237}};
  CHECK(ec.AppendReleaseMap(batch, &err));
  auto pending = ec.PendingReleases();
  CHECK(pending.size() == 2 && pending[1].second == 237);
  ec.ClearReleaseMap();
  CHECK(ec.PendingReleases().empty());
}

static void TestChunkStoreEcDemoteReleaseRemoteRead() {
  // Owner side: demote cold chunks into a stripe, reads fall through.
  std::string owner_dir = ChunkStoreDir();
  ChunkStore owner(owner_dir, 0, 0, SlabOptions{}, /*ec_k=*/2, /*ec_m=*/1);
  CHECK(owner.ec_enabled());
  Recipe r;
  std::vector<std::string> payloads, digs;
  bool existed = false;
  std::string err;
  for (int i = 0; i < 4; ++i) {
    payloads.emplace_back(500 + i, static_cast<char>('e' + i));
    digs.push_back(Sha1HexOf(payloads.back()));
    CHECK(owner.PutAndRef(digs[i], payloads[i].data(), payloads[i].size(),
                          &existed, &err));
    r.chunks.push_back({digs[i], static_cast<int64_t>(payloads[i].size())});
    r.logical_size += static_cast<int64_t>(payloads[i].size());
  }
  CHECK(WriteRecipeFile(owner_dir + "/data/ec.rcp", r, &err));
  auto cands = owner.SnapshotDemotable(time(nullptr) + 10, 1);
  CHECK(cands.size() == 4);
  int64_t nchunks = 0, nbytes = 0;
  int64_t sid = owner.DemoteToEc(cands, &nchunks, &nbytes, &err);
  CHECK(sid >= 0);
  CHECK(nchunks == 4);
  CHECK(owner.ec_stripes() == 1);
  // The flat payloads are gone; reads decode from the stripe.
  for (int i = 0; i < 4; ++i) {
    CHECK(!FileExists(owner.ChunkPath(digs[i])));
    std::string back;
    CHECK(owner.ReadChunk(digs[i], static_cast<int64_t>(payloads[i].size()),
                          &back));
    CHECK(back == payloads[i]);
  }
  // Demoted chunks are NOT demotable again.
  CHECK(owner.SnapshotDemotable(time(nullptr) + 10, 1).empty());

  // Peer side: EC_RELEASE drops the replica, journaled; reads route to
  // the remote-fetch hook (which the server wires to FETCH_CHUNK).
  std::string peer_dir = ChunkStoreDir();
  {
    ChunkStore peer(peer_dir, 0);
    for (int i = 0; i < 4; ++i)
      CHECK(peer.PutAndRef(digs[i], payloads[i].data(), payloads[i].size(),
                           &existed, &err));
    CHECK(WriteRecipeFile(peer_dir + "/data/ec.rcp", r, &err));
    std::vector<ChunkStore::ChunkInfo> infos;
    for (int i = 0; i < 4; ++i)
      infos.push_back({digs[i], static_cast<int64_t>(payloads[i].size())});
    std::string mask = peer.ReleaseChunks(infos);
    CHECK(mask == std::string(4, '\0'));
    CHECK(peer.released_chunks() == 4);
    CHECK(peer.IsReleased(digs[0]));
    CHECK(!FileExists(peer.ChunkPath(digs[0])));
    // Releasing again is idempotent (the replayed-handover case).
    CHECK(peer.ReleaseChunks(infos) == std::string(4, '\0'));
    // No hook: the read fails clean instead of fabricating bytes.
    std::string back;
    CHECK(!peer.ReadChunk(digs[0],
                          static_cast<int64_t>(payloads[0].size()), &back));
    int fetches = 0;
    peer.set_remote_fetch([&](const std::string& dig, int64_t len,
                              std::string* out) {
      ++fetches;
      std::string got;
      if (!owner.ReadChunk(dig, len, &got)) return false;
      out->swap(got);
      return true;
    });
    CHECK(peer.ReadChunk(digs[0],
                         static_cast<int64_t>(payloads[0].size()), &back));
    CHECK(back == payloads[0] && fetches == 1);
    CHECK(peer.ec_remote_reads() == 1);
    // Slice reads work through the hook too.
    std::string slice(7, '\0');
    CHECK(peer.ReadChunkSlice(digs[1], 11, 7, slice.data()));
    CHECK(slice == payloads[1].substr(11, 7));
    // A re-uploaded payload UNRELEASES: local bytes win again.
    CHECK(peer.PutAndRef(digs[2], payloads[2].data(), payloads[2].size(),
                         &existed, &err));
    CHECK(!peer.IsReleased(digs[2]));
    CHECK(peer.released_chunks() == 3);
  }
  // Restart replays released.log: marks survive for referenced digests
  // with no local payload, and the re-uploaded chunk stays local.
  ChunkStore peer2(peer_dir, 0);
  peer2.RebuildFromRecipes();
  CHECK(peer2.released_chunks() == 3);
  CHECK(peer2.IsReleased(digs[0]) && !peer2.IsReleased(digs[2]));
  std::string back;
  CHECK(peer2.ReadChunk(digs[2], static_cast<int64_t>(payloads[2].size()),
                        &back));
  CHECK(back == payloads[2]);

  // Owner restart rescans the stripe and still serves decoded reads.
  ChunkStore owner2(owner_dir, 0, 0, SlabOptions{}, 2, 1);
  owner2.RebuildFromRecipes();
  CHECK(owner2.ec_stripes() == 1);
  CHECK(owner2.ReadChunk(digs[3], static_cast<int64_t>(payloads[3].size()),
                         &back));
  CHECK(back == payloads[3]);
  // DELETE reclaims parity: with no grace window the last unref retires
  // the chunks eagerly, and the last live chunk takes the stripe with it.
  owner2.UnrefAll(r);
  CHECK(owner2.ec_stripes() == 0);
  CHECK(owner2.ec_parity_bytes() == 0);
}

int main() {
  TestBinlogRecordCodec();
  TestBinlogWriteReadResume();
  TestBinlogRotation();
  TestCpuDedup();
  TestStoreInit();
  TestTrunkAllocator();
  TestTrunkReserveAndCompaction();
  TestTrunkReplicaWrite();
  TestChunkStoreGcGraceAndPins();
  TestChunkStoreEagerModeUnchanged();
  TestChunkStoreQuarantineRepairHeal();
  TestChunkStoreRebuildParksOrphansAndKeepsQuarantine();
  TestChunkStoreReadRecipeAndPinRange();
  TestChunkStoreReadCacheCoherence();
  TestSlabRecordCodec();
  TestSlabStoreAppendRescanCompact();
  TestChunkStoreSlabEndToEnd();
  TestChunkStoreSlabConcurrency();
  TestChunkStoreStripedConcurrency();
  TestRsCodecKillAnyM();
  TestEcStoreStripeLifecycle();
  TestChunkStoreEcDemoteReleaseRemoteRead();
  if (g_failures == 0) {
    std::printf("storage_test: ALL PASS\n");
    return 0;
  }
  std::printf("storage_test: %d FAILURES\n", g_failures);
  return 1;
}
