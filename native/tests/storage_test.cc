// Unit tests for binlog + store + dedup units (the daemon itself is
// integration-tested from pytest via the Python client).
#include <sys/stat.h>
#include <unistd.h>

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>

#include "storage/binlog.h"
#include "storage/dedup.h"
#include "storage/store.h"
#include "storage/trunk.h"

static int g_failures = 0;

#define CHECK(cond)                                                        \
  do {                                                                     \
    if (!(cond)) {                                                         \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      ++g_failures;                                                        \
    }                                                                      \
  } while (0)

using namespace fdfs;

static std::string TempDir() {
  char tmpl[] = "/tmp/fdfs_storage_test_XXXXXX";
  return mkdtemp(tmpl);
}

static void TestBinlogRecordCodec() {
  BinlogRecord rec;
  rec.timestamp = 1700000000;
  rec.op = 'C';
  rec.filename = "M00/AA/BB/name.jpg";
  std::string line = FormatBinlogRecord(rec);
  CHECK(line == "1700000000 C M00/AA/BB/name.jpg\n");
  auto back = ParseBinlogRecord(line);
  CHECK(back.has_value());
  CHECK(back->timestamp == rec.timestamp);
  CHECK(back->op == 'C');
  CHECK(back->filename == rec.filename);
  CHECK(back->extra.empty());

  rec.op = 'L';
  rec.extra = "M00/CC/DD/src.jpg";
  auto back2 = ParseBinlogRecord(FormatBinlogRecord(rec));
  CHECK(back2.has_value());
  CHECK(back2->extra == "M00/CC/DD/src.jpg");

  CHECK(!ParseBinlogRecord("garbage\n").has_value());
  CHECK(!ParseBinlogRecord("17 \n").has_value());
  CHECK(!ParseBinlogRecord("").has_value());
}

static void TestBinlogWriteReadResume() {
  std::string dir = TempDir();
  std::string err;
  BinlogWriter w;
  CHECK(w.Init(dir, 1 << 20, &err));
  for (int i = 0; i < 10; ++i)
    CHECK(w.Append('C', "M00/00/00/file" + std::to_string(i)));
  w.Flush();

  BinlogReader r;
  CHECK(r.Init(dir, dir + "/peer.mark", &err));
  for (int i = 0; i < 5; ++i) {
    auto rec = r.Next();
    CHECK(rec.has_value());
    CHECK(rec->filename == "M00/00/00/file" + std::to_string(i));
  }
  CHECK(r.SaveMark());

  // Fresh reader resumes from the mark.
  BinlogReader r2;
  CHECK(r2.Init(dir, dir + "/peer.mark", &err));
  auto rec = r2.Next();
  CHECK(rec.has_value());
  CHECK(rec->filename == "M00/00/00/file5");
  for (int i = 6; i < 10; ++i) CHECK(r2.Next().has_value());
  CHECK(!r2.Next().has_value());  // caught up

  // New writes become visible to the same reader (tailing).
  CHECK(w.Append('D', "M00/00/00/file3"));
  w.Flush();
  auto tail = r2.Next();
  CHECK(tail.has_value());
  CHECK(tail->op == 'D');
}

static void TestBinlogRotation() {
  std::string dir = TempDir();
  std::string err;
  BinlogWriter w;
  CHECK(w.Init(dir, 128, &err));  // tiny rotate size
  for (int i = 0; i < 20; ++i) CHECK(w.Append('C', "M00/00/00/f" + std::to_string(i)));
  w.Flush();
  CHECK(w.file_index() >= 1);  // rotated at least once

  BinlogReader r;
  CHECK(r.Init(dir, dir + "/m.mark", &err));
  int count = 0;
  while (r.Next().has_value()) ++count;
  CHECK(count == 20);  // reader follows rotation
}

static void TestCpuDedup() {
  std::string dir = TempDir();
  CpuDedup d(dir + "/dedup_index.dat");
  CHECK(!d.Judge("abc", 10).duplicate);
  d.Commit("abc", "group1/M00/00/00/x.bin");
  auto v = d.Judge("abc", 10);
  CHECK(v.duplicate);
  CHECK(v.dup_of == "group1/M00/00/00/x.bin");
  // snapshot round-trip
  CHECK(d.Save());
  CpuDedup d2(dir + "/dedup_index.dat");
  CHECK(d2.LoadSnapshot());
  CHECK(d2.Judge("abc", 10).duplicate);
  // forget
  d2.Forget("group1/M00/00/00/x.bin");
  CHECK(!d2.Judge("abc", 10).duplicate);
}

static void TestStoreInit() {
  std::string dir = TempDir();
  StorageConfig cfg;
  cfg.base_path = dir;
  cfg.store_paths = {dir};
  cfg.subdir_count_per_path = 4;
  StoreManager sm;
  std::string err;
  CHECK(sm.Init(cfg, &err));
  struct stat st;
  CHECK(stat((dir + "/data/03/03").c_str(), &st) == 0);
  CHECK(stat((dir + "/data/.data_init_flag").c_str(), &st) == 0);
  // second init is a no-op (flag present)
  CHECK(sm.Init(cfg, &err));
  // uniquifier wraps at 12 bits
  for (int i = 0; i < 5000; ++i) {
    int u = sm.NextUniquifier();
    CHECK(u >= 0 && u <= 0xFFF);
  }
  std::string t1 = sm.NewTmpPath(0), t2 = sm.NewTmpPath(0);
  CHECK(t1 != t2);
}


static void TestTrunkAllocator() {
  std::string dir = TempDir();
  TrunkAllocator alloc;
  std::string err;
  CHECK(alloc.Init(dir, 1 << 20, &err));  // 1 MB trunk files for the test
  CHECK(alloc.trunk_file_count() == 0);

  // First alloc creates a trunk file and splits it.
  auto a = alloc.Alloc(1000);
  CHECK(a.has_value());
  CHECK(a->trunk_id == 0 && a->offset == 0);
  CHECK(a->alloc_size >= 1000 + kTrunkHeaderSize);
  CHECK(alloc.trunk_file_count() == 1);

  // Second alloc lands after the first (split remainder).
  auto b = alloc.Alloc(5000);
  CHECK(b.has_value());
  CHECK(b->trunk_id == 0);
  CHECK(b->offset == a->alloc_size);

  // Write payloads and read them back.
  std::string pa(1000, 'x'), pb(5000, 'y');
  CHECK(WriteSlotPayload(dir, *a, pa, 111, &err));
  CHECK(WriteSlotPayload(dir, *b, pb, 222, &err));
  auto ra = ReadSlotPayload(dir, *a, 1000);
  CHECK(ra.has_value() && *ra == pa);

  // Free A; the same-size alloc reuses its exact slot.
  CHECK(alloc.Free(*a));
  auto c = alloc.Alloc(1000);
  CHECK(c.has_value());
  CHECK(c->trunk_id == a->trunk_id && c->offset == a->offset);

  // Freed slot no longer readable as data.
  CHECK(alloc.Free(*c));
  CHECK(!ReadSlotPayload(dir, *a, 1000).has_value());

  // Pool vs on-disk headers agree.
  std::string report;
  CHECK(alloc.VerifyFreeMap(&report) == 0);

  // Scan-rebuild (failover path): a fresh allocator sees the same world
  // and will not double-allocate B's live slot.
  TrunkAllocator alloc2;
  CHECK(alloc2.Init(dir, 1 << 20, &err));
  CHECK(alloc2.trunk_file_count() == 1);
  CHECK(alloc2.VerifyFreeMap(&report) == 0);
  auto d = alloc2.Alloc(5000);
  CHECK(d.has_value());
  CHECK(!(d->trunk_id == b->trunk_id && d->offset == b->offset));
  auto rb = ReadSlotPayload(dir, *b, 5000);
  CHECK(rb.has_value() && *rb == pb);

  // Oversized request refused; trunk-file exhaustion rolls to a new file.
  CHECK(!alloc2.Alloc(2 << 20).has_value());
}

static void TestTrunkReplicaWrite() {
  // WriteSlotPayload must create + extend the file on a replica that has
  // never allocated anything (sync replay path).
  std::string dir = TempDir();
  TrunkLocation loc;
  loc.trunk_id = 7;
  loc.offset = 123 * kTrunkAlignment;
  loc.alloc_size = 4 * kTrunkAlignment;
  std::string payload(900, 'z'), err;
  CHECK(WriteSlotPayload(dir, loc, payload, 42, &err));
  auto back = ReadSlotPayload(dir, loc, 900);
  CHECK(back.has_value() && *back == payload);
  CHECK(MarkSlotFree(dir, loc));
  CHECK(!ReadSlotPayload(dir, loc, 900).has_value());
}

static void TestTrunkReserveAndCompaction() {
  std::string dir = TempDir();
  TrunkAllocator alloc;
  std::string err;
  CHECK(alloc.Init(dir, 1 << 20, &err));
  CHECK(alloc.trunk_file_count() == 0);

  // Pre-allocation: demand a 3 MB reserve -> 3 fresh 1 MB trunk files,
  // all free; idempotent once satisfied.
  CHECK(alloc.EnsureFreeReserve(3 << 20) == 3);
  CHECK(alloc.trunk_file_count() == 3);
  CHECK(alloc.free_bytes() == 3 << 20);
  CHECK(alloc.EnsureFreeReserve(3 << 20) == 0);

  // Allocations now come from the reserve without creating files.
  auto a = alloc.Alloc(4000);
  CHECK(a.has_value());
  CHECK(alloc.trunk_file_count() == 3);

  // Compaction: with one slot live, exactly the OTHER fully-free files
  // beyond the keep=1 reserve are reclaimed.
  CHECK(alloc.ReclaimEmptyFiles(/*keep=*/1) == 1);
  std::string report;
  CHECK(alloc.VerifyFreeMap(&report) == 0);

  // The live slot still reads back; freeing it makes its file
  // reclaimable too (keep=0 clears everything).
  std::string pa(4000, 'q');
  CHECK(WriteSlotPayload(dir, *a, pa, 7, &err));
  auto ra = ReadSlotPayload(dir, *a, 4000);
  CHECK(ra.has_value() && *ra == pa);
  CHECK(alloc.Free(*a));
  CHECK(alloc.ReclaimEmptyFiles(/*keep=*/0) >= 1);

  // A scan-rebuild of the compacted dir agrees with the pool.
  TrunkAllocator alloc2;
  CHECK(alloc2.Init(dir, 1 << 20, &err));
  CHECK(alloc2.VerifyFreeMap(&report) == 0);
}

int main() {
  TestBinlogRecordCodec();
  TestBinlogWriteReadResume();
  TestBinlogRotation();
  TestCpuDedup();
  TestStoreInit();
  TestTrunkAllocator();
  TestTrunkReserveAndCompaction();
  TestTrunkReplicaWrite();
  if (g_failures == 0) {
    std::printf("storage_test: ALL PASS\n");
    return 0;
  }
  std::printf("storage_test: %d FAILURES\n", g_failures);
  return 1;
}
