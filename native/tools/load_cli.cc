// Native load-generation harness (reference: the `test/` directory —
// test_upload.c / test_download.c / test_delete.c drive a live cluster
// from N processes and write per-op `.result` records; combine_result.c
// merges them into QPS + latency).  The rebuild's equivalent is one
// binary with subcommands; concurrency is threads (each with its own
// connections) and multiple processes compose the same way — `combine`
// merges any number of result files.
//
// Result record format (one line per op):
//   <start_us> <latency_us> <status> <bytes> <class> <file_id>
// where <class> is the priority class the op was tagged with on the
// wire (0..4) or 255 for untagged (the daemon applies its opcode
// default).  `combine` also accepts the older five-field format
// (records before the class column existed count as untagged).
//
// Usage:
//   fdfs_load upload   <tracker ip:port> <n_ops> <size> <threads> <result>
//                      [unique_payloads]   (0/absent = every op unique)
//   fdfs_load upload   <tracker ip:port> --small-files N --file-bytes B
//                      <threads> <result>
//                      (small-file corpus mode, ISSUE 9: N unique files
//                      of B bytes each — the ingest arm of the slab-
//                      packing bench, equivalent to n_ops=N size=B with
//                      every payload unique)
//   fdfs_load download <tracker ip:port> <ids_file> <n_ops> <threads> <result>
//                      [--zipf <s> [--zipf-keys N] [--zipf-seed S]]
//                      [--hot-keys K:pct]
//   fdfs_load delete   <tracker ip:port> <ids_file> <threads> <result>
//   fdfs_load combine  <result files...>     (prints one JSON line)
//   fdfs_load zipf-sample <s> <keys> <n> [seed]   (prints n key indices,
//                      one per line — the sampler the download mode
//                      uses, exposed for deterministic unit tests)
//
// `upload` also appends the minted file ids to <result>.ids for the
// download/delete phases.
//
// --open-loop --rate R (upload and download, any position after the
// mode): open-loop arrival mode (ISSUE 11's cluster load harness).
// Op i is SCHEDULED at t0 + i/R seconds across ALL threads combined,
// and its latency clock starts at the scheduled instant, not when a
// worker got around to it — so when the cluster falls behind the
// offered rate, the backlog lands in the latency percentiles instead
// of silently throttling the load (the closed-loop coordinated-
// omission failure).  Threads (<threads> = the concurrency cap) only
// bound how many ops may be in flight at once.
//
// --priority P (upload/download/delete, any position after the mode):
// tag every storage op with priority class P (0 control .. 4
// background) via the 1-byte PRIORITY prefix frame, so the admission
// ladder sheds by the declared class instead of the opcode default.
// --priority-mix <spec> instead assigns classes probabilistically:
// spec is comma-separated `[label:]class:weight` entries (e.g.
// `read:2:0.7,write:3:0.3` — labels are documentation only); op i is
// hashed deterministically onto the weight distribution, so a run's
// class assignment is reproducible regardless of thread interleaving
// (the zipf-picker discipline).  `combine` reports per-class op
// counts, admitted/shed splits (shed = EBUSY 16), and latency
// percentiles under "by_class".
//
// --conns N (upload/download/delete, any position after the mode):
// shared storage-connection budget across ALL worker threads.  Workers
// check a connection out of a pool per op; when every slot is busy the
// worker blocks until one is returned, so `--conns 1` serializes all
// storage traffic through one socket (the pre-multiplexing client
// shape) while `--conns >= threads` restores full parallelism — the
// knob that makes client-side multiplexing wins measurable from the
// harness side.  0/absent = unlimited (one conn per worker, the old
// behaviour).  Every run prints a `{"conns_budget": ...}` JSON line to
// stdout with the EFFECTIVE counts (opened/peak/waits) so the bench
// harness can verify the topology it asked for is the one it got.
//
// --zipf <s>: key-popularity mode for downloads (ISSUE 8 / ROADMAP
// item 2's load harness seed).  Instead of round-robin over the ids
// file, op i fetches the id Zipf(s) picks over a bounded key universe
// (--zipf-keys, default min(1000, #ids); rank 1 = the FIRST id in the
// file, weight 1/rank^s).  Sampling is keyed on the op index with a
// fixed seed (--zipf-seed, default 42), so a run is DETERMINISTIC
// regardless of thread count or interleaving — the heat-sketch
// acceptance test replays the exact same skew every time.
//
// --hot-keys K:pct (download; ISSUE 20's elastic-replication bench
// mode): the FIRST K ids in the file form a hot set that receives
// pct% of the ops (uniform within the set); the rest spread uniformly
// over the remaining ids.  Unlike --zipf's smooth rank curve this
// pins an exact hot-set size and traffic share, so a promotion
// threshold can be aimed at precisely K keys.  Mutually exclusive
// with --zipf.  Each record's trailing token marks its key class
// ("hot"/"cold"), and `combine` reports per-key-class op counts and
// latency percentiles under "by_key_class" — the number the bench
// compares across the promotion-on/off arms.  Deterministic on the op
// index (the zipf-picker discipline).
#include <stdio.h>
#include <string.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <fstream>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "common/bytes.h"
#include "common/net.h"
#include "common/protocol_gen.h"

using namespace fdfs;

namespace {

constexpr int kTimeoutMs = 60000;

int64_t MonoUs() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<int64_t>(ts.tv_sec) * 1000000 + ts.tv_nsec / 1000;
}

constexpr int kUntagged = 255;

struct OpRecord {
  int64_t start_us;
  int64_t latency_us;
  int status;  // 0 ok, errno-style otherwise; -1 = transport failure
  int64_t bytes;
  int cls;     // wire priority class, kUntagged when no frame was sent
  std::string file_id;
  // "hot"/"cold" under --hot-keys, "" otherwise (a trailing record
  // token; absent = unclassed, the append-only record discipline).
  std::string key_class;
};

// One request/response on a blocking fd.  Returns false on transport
// failure; *status carries the server's header status byte.
bool Rpc(int fd, uint8_t cmd, const std::string& body, std::string* resp,
         uint8_t* status) {
  return NetRpc(fd, cmd, body, resp, status, 1LL << 31, kTimeoutMs);
}

std::string PackGroup(const std::string& group) {
  std::string out(16, '\0');
  memcpy(out.data(), group.data(), std::min<size_t>(group.size(), 16));
  return out;
}

bool SplitAddr(const std::string& addr, std::string* host, int* port) {
  size_t c = addr.rfind(':');
  if (c == std::string::npos) return false;
  *host = addr.substr(0, c);
  *port = atoi(addr.c_str() + c + 1);
  return *port > 0;
}

bool SplitId(const std::string& file_id, std::string* group,
             std::string* remote) {
  size_t s = file_id.find('/');
  if (s == std::string::npos) return false;
  *group = file_id.substr(0, s);
  *remote = file_id.substr(s + 1);
  return true;
}

// A pooled connection to one peer; reconnects lazily after failures (the
// reference load clients keep one connection per process the same way).
class Peer {
 public:
  Peer(std::string host, int port) : host_(std::move(host)), port_(port) {}
  ~Peer() { Close(); }
  bool Call(uint8_t cmd, const std::string& body, std::string* resp,
            uint8_t* status, int cls = kUntagged) {
    for (int attempt = 0; attempt < 2; ++attempt) {
      if (fd_ < 0) {
        std::string err;
        fd_ = TcpConnect(host_, port_, kTimeoutMs, &err);
        if (fd_ < 0) return false;
      }
      if (cls != kUntagged) {
        // PRIORITY prefix frame (no response of its own): 10B header
        // with pkg_len=1 + the class byte, tagging the next request.
        uint8_t frame[kHeaderSize + 1] = {0};
        PutInt64BE(kPriorityFrameLen, frame);
        frame[8] = static_cast<uint8_t>(StorageCmd::kPriority);
        frame[kHeaderSize] = static_cast<uint8_t>(cls);
        if (!SendAll(fd_, frame, sizeof(frame), kTimeoutMs)) {
          Close();
          continue;
        }
      }
      if (Rpc(fd_, cmd, body, resp, status)) return true;
      Close();  // stale/broken connection: one reconnect attempt
    }
    return false;
  }
  void Close() {
    if (fd_ >= 0) {
      close(fd_);
      fd_ = -1;
    }
  }
  const std::string& host() const { return host_; }
  int port() const { return port_; }

 private:
  std::string host_;
  int port_;
  int fd_ = -1;
};

// Shared storage-connection pool (--conns N).  All workers draw their
// storage connections from here; `budget` caps the LIVE connection
// count across every endpoint, and a worker whose op finds the budget
// exhausted blocks until someone returns one.  Idle conns are parked
// per endpoint and reused LIFO (warmest socket first); when the cap is
// tight and the op targets an endpoint with no idle conn, an idle conn
// to a DIFFERENT endpoint is retired to free budget instead of
// deadlocking on endpoint churn.  budget <= 0 = unlimited, which
// degenerates to the old one-conn-per-worker shape (each worker gets
// back the conn it just returned).
class StoragePool {
 public:
  ~StoragePool() {
    for (Peer* p : all_) delete p;
  }
  // Must be called before workers start; not thread-safe.
  void set_budget(int budget) { budget_ = budget; }
  int budget() const { return budget_; }

  Peer* Checkout(const std::string& host, int port) {
    std::unique_lock<RankedMutex> lk(mu_);
    const std::string key = host + ":" + std::to_string(port);
    for (;;) {
      auto it = idle_.find(key);
      if (it != idle_.end() && !it->second.empty()) {
        Peer* p = it->second.back();
        it->second.pop_back();
        return p;
      }
      if (budget_ <= 0 || live_ < budget_) {
        ++live_;
        ++opened_;
        peak_ = std::max(peak_, live_);
        Peer* p = new Peer(host, port);
        all_.push_back(p);
        return p;
      }
      // Cap reached, nothing idle for THIS endpoint: retire an idle
      // conn to another endpoint if one exists, else wait for a return.
      bool retired = false;
      for (auto& [k, v] : idle_) {
        (void)k;
        if (!v.empty()) {
          v.back()->Close();  // freed via all_ at exit
          v.pop_back();
          --live_;
          retired = true;
          break;
        }
      }
      if (retired) continue;
      ++waits_;
      cv_.wait(lk);
    }
  }

  void Return(Peer* p) {
    std::lock_guard<RankedMutex> lk(mu_);
    idle_[p->host() + ":" + std::to_string(p->port())].push_back(p);
    cv_.notify_one();
  }

  // Effective-count report for the harness; call after workers join.
  void PrintStats() const {
    printf(
        "{\"conns_budget\": %d, \"conns_opened\": %lld, "
        "\"conns_peak\": %d, \"conn_waits\": %lld}\n",
        budget_, static_cast<long long>(opened_), peak_,
        static_cast<long long>(waits_));
  }

 private:
  mutable RankedMutex mu_{LockRank::kToolOutput};
  std::condition_variable_any cv_;
  int budget_ = 0;
  int live_ = 0;     // created minus retired (checked out or idle)
  int peak_ = 0;     // max live_ ever
  int64_t opened_ = 0;  // total connections ever created
  int64_t waits_ = 0;   // checkouts that had to block on the cap
  std::map<std::string, std::vector<Peer*>> idle_;
  std::vector<Peer*> all_;  // owns every Peer ever created
};

// RAII checkout so early-exit paths in the workers cannot leak a
// pooled connection (which under --conns 1 would wedge every worker).
class PooledPeer {
 public:
  PooledPeer(StoragePool* pool, const std::string& host, int port)
      : pool_(pool), peer_(pool->Checkout(host, port)) {}
  ~PooledPeer() { pool_->Return(peer_); }
  PooledPeer(const PooledPeer&) = delete;
  PooledPeer& operator=(const PooledPeer&) = delete;
  Peer* operator->() { return peer_; }

 private:
  StoragePool* pool_;
  Peer* peer_;
};

// tracker query_store (cmd 101): resp = 16B group + 16B ip + 8B port +
// 1B store-path index.
bool QueryStore(Peer* tracker, std::string* group, std::string* ip,
                int* port, uint8_t* spi) {
  std::string resp;
  uint8_t status = 0;
  if (!tracker->Call(
          static_cast<uint8_t>(TrackerCmd::kServiceQueryStoreWithoutGroupOne),
          "", &resp, &status) ||
      status != 0 || resp.size() < 41)
    return false;
  *group = std::string(resp.c_str(), strnlen(resp.c_str(), 16));
  *ip = std::string(resp.data() + 16, strnlen(resp.data() + 16, 16));
  *port = static_cast<int>(
      GetInt64BE(reinterpret_cast<const uint8_t*>(resp.data()) + 32));
  *spi = static_cast<uint8_t>(resp[40]);
  return true;
}

// tracker query_fetch/update (cmd 102/103): resp = 16B ip + 8B port.
bool QueryFetch(Peer* tracker, uint8_t cmd, const std::string& file_id,
                std::string* ip, int* port) {
  std::string group, remote;
  if (!SplitId(file_id, &group, &remote)) return false;
  std::string resp;
  uint8_t status = 0;
  if (!tracker->Call(cmd, PackGroup(group) + remote, &resp, &status) ||
      status != 0 || resp.size() < 24)
    return false;
  *ip = std::string(resp.data(), strnlen(resp.data(), 16));
  *port = static_cast<int>(
      GetInt64BE(reinterpret_cast<const uint8_t*>(resp.data()) + 16));
  return true;
}

// Zipf(s) sampler over key ranks [0, n): rank r carries weight
// 1/(r+1)^s.  Pick(i) hashes the op index through splitmix64 with a
// fixed seed, so the i-th operation of a run always fetches the same
// key — deterministic skew independent of thread scheduling.
class ZipfPicker {
 public:
  ZipfPicker(double s, size_t n, uint64_t seed) : seed_(seed) {
    cdf_.resize(n == 0 ? 1 : n);
    double acc = 0;
    for (size_t r = 0; r < cdf_.size(); ++r) {
      acc += 1.0 / std::pow(static_cast<double>(r + 1), s);
      cdf_[r] = acc;
    }
    total_ = acc;
  }
  size_t Pick(int64_t i) const {
    uint64_t x = seed_ + 0x9E3779B97F4A7C15ULL * (static_cast<uint64_t>(i) + 1);
    x ^= x >> 30;
    x *= 0xBF58476D1CE4E5B9ULL;
    x ^= x >> 27;
    x *= 0x94D049BB133111EBULL;
    x ^= x >> 31;
    // 53-bit mantissa -> u in [0, total): never exactly total, so
    // lower_bound always lands inside the table.
    double u = static_cast<double>(x >> 11) *
               (1.0 / 9007199254740992.0) * total_;
    return static_cast<size_t>(
        std::lower_bound(cdf_.begin(), cdf_.end(), u) - cdf_.begin());
  }
  size_t keys() const { return cdf_.size(); }

 private:
  uint64_t seed_;
  std::vector<double> cdf_;
  double total_ = 0;
};

struct Shared {
  std::string tracker_host;
  int tracker_port = 0;
  std::atomic<int64_t> next{0};
  int64_t n_ops = 0;
  int64_t size = 0;
  int64_t unique = 0;  // 0 = every payload unique
  std::vector<std::string> ids;  // download/delete input
  std::unique_ptr<ZipfPicker> zipf;  // download key-popularity mode
  // Hot-set mode (--hot-keys K:pct): op i aims at one of the first
  // hot_keys ids with probability hot_frac, else uniformly at the
  // rest.  Hashed on the op index (deterministic regardless of thread
  // interleaving, the ZipfPicker discipline).
  int64_t hot_keys = 0;
  double hot_frac = 0;
  size_t HotPick(int64_t i, bool* hot) const {
    uint64_t x = 0x40fULL + 0x9E3779B97F4A7C15ULL *
                 (static_cast<uint64_t>(i) + 1);
    x ^= x >> 30;
    x *= 0xBF58476D1CE4E5B9ULL;
    x ^= x >> 27;
    x *= 0x94D049BB133111EBULL;
    x ^= x >> 31;
    double u = static_cast<double>(x >> 11) * (1.0 / 9007199254740992.0);
    uint64_t r = x * 0xD1342543DE82EF95ULL + 0x2545F4914F6CDD1DULL;
    size_t n = ids.size();
    size_t k = static_cast<size_t>(std::min<int64_t>(
        hot_keys, static_cast<int64_t>(n)));
    *hot = u < hot_frac && k > 0;
    if (*hot) return r % k;
    if (k >= n) {  // every id is hot: nothing cold to aim at
      *hot = true;
      return r % n;
    }
    return k + r % (n - k);
  }
  // Open-loop mode (--open-loop --rate R): op i is SCHEDULED at
  // t0 + i/R regardless of how slow earlier ops were, and its latency
  // clock starts at the scheduled time — so server-side queueing shows
  // up in the percentiles instead of silently throttling the offered
  // load (the coordinated-omission fix; closed-loop when rate == 0).
  double rate = 0;
  int64_t t0_us = 0;
  // Request QoS (--priority / --priority-mix): either one fixed class
  // for every op, or a weighted distribution op i is hashed onto
  // deterministically (thread-schedule independent, the ZipfPicker
  // discipline).  kUntagged = send no frame.
  int priority = kUntagged;
  std::vector<std::pair<int, double>> prio_cdf;  // (class, cumulative wt)
  int ClassFor(int64_t i) const {
    if (prio_cdf.empty()) return priority;
    uint64_t x = 0x5eedULL + 0x9E3779B97F4A7C15ULL *
                 (static_cast<uint64_t>(i) + 1);
    x ^= x >> 30;
    x *= 0xBF58476D1CE4E5B9ULL;
    x ^= x >> 27;
    x *= 0x94D049BB133111EBULL;
    x ^= x >> 31;
    double u = static_cast<double>(x >> 11) * (1.0 / 9007199254740992.0) *
               prio_cdf.back().second;
    for (const auto& [cls, acc] : prio_cdf)
      if (u < acc) return cls;
    return prio_cdf.back().first;
  }
  // Storage connections are drawn from this shared pool; --conns N
  // caps it (0 = unlimited).  Tracker connections stay per-worker —
  // they are tiny metadata RPCs and capping them would only measure
  // tracker queueing, not the storage-edge multiplexing this knob is
  // for.
  StoragePool pool;
  RankedMutex out_mu{LockRank::kToolOutput};
  std::vector<OpRecord> records;
};

// Open-loop gate for op i: sleep until its scheduled instant and return
// it as the latency-clock origin; closed-loop ops just start now.
int64_t OpStartUs(Shared* sh, int64_t i) {
  if (sh->rate <= 0) return MonoUs();
  int64_t sched = sh->t0_us +
                  static_cast<int64_t>(static_cast<double>(i) * 1e6 / sh->rate);
  int64_t now = MonoUs();
  if (now < sched)
    usleep(static_cast<useconds_t>(sched - now));
  return sched;
}

void Emit(Shared* sh, std::vector<OpRecord>* local) {
  std::lock_guard<RankedMutex> lk(sh->out_mu);
  for (auto& r : *local) sh->records.push_back(std::move(r));
  local->clear();
}

// Payload bytes for op i: xorshift stream seeded by the payload id, so
// two ops with the same id upload IDENTICAL bytes (dedup-able) without
// the driver storing any corpus in RAM.
void FillPayload(int64_t payload_id, std::string* buf) {
  uint64_t x = 0x9E3779B97F4A7C15ULL ^ (payload_id * 0xBF58476D1CE4E5B9ULL);
  if (x == 0) x = 1;
  for (size_t i = 0; i < buf->size(); i += 8) {
    x ^= x << 13;
    x ^= x >> 7;
    x ^= x << 17;
    size_t n = std::min<size_t>(8, buf->size() - i);
    memcpy(buf->data() + i, &x, n);
  }
}

void UploadWorker(Shared* sh) {
  Peer tracker(sh->tracker_host, sh->tracker_port);
  std::string payload(static_cast<size_t>(sh->size), '\0');
  std::vector<OpRecord> local;
  for (;;) {
    int64_t i = sh->next.fetch_add(1);
    if (i >= sh->n_ops) break;
    int64_t start = OpStartUs(sh, i);
    int64_t pid = sh->unique > 0 ? (i % sh->unique) : i;
    FillPayload(pid, &payload);
    // bytes stays 0 unless the daemon ACCEPTED the upload — failed ops
    // must not inflate combine's throughput.
    int cls = sh->ClassFor(i);
    OpRecord rec{start, 0, -1, 0, cls, ""};
    std::string group, ip;
    int port = 0;
    uint8_t spi = 0;
    if (QueryStore(&tracker, &group, &ip, &port, &spi)) {
      PooledPeer storage(&sh->pool, ip, port);
      // upload wire: 1B spi, 8B size, 6B ext, body
      std::string body;
      body.reserve(15 + payload.size());
      body.push_back(static_cast<char>(spi));
      uint8_t num[8];
      PutInt64BE(sh->size, num);
      body.append(reinterpret_cast<char*>(num), 8);
      body.append("bin\0\0\0", 6);
      body += payload;
      std::string resp;
      uint8_t status = 0;
      if (storage->Call(static_cast<uint8_t>(StorageCmd::kUploadFile), body,
                        &resp, &status, cls)) {
        rec.status = status;
        if (status == 0 && resp.size() > 16) {
          std::string g(resp.c_str(), strnlen(resp.c_str(), 16));
          rec.file_id = g + "/" + resp.substr(16);
          rec.bytes = sh->size;
        }
      }
    }
    rec.latency_us = MonoUs() - rec.start_us;
    local.push_back(std::move(rec));
    if (local.size() >= 1024) Emit(sh, &local);
  }
  Emit(sh, &local);
}

void DownloadWorker(Shared* sh) {
  Peer tracker(sh->tracker_host, sh->tracker_port);
  std::vector<OpRecord> local;
  for (;;) {
    int64_t i = sh->next.fetch_add(1);
    if (i >= sh->n_ops) break;
    int64_t start = OpStartUs(sh, i);
    std::string key_class;
    size_t pick;
    if (sh->hot_keys > 0) {
      bool hot = false;
      pick = sh->HotPick(i, &hot) % sh->ids.size();
      key_class = hot ? "hot" : "cold";
    } else if (sh->zipf != nullptr) {
      pick = sh->zipf->Pick(i) % sh->ids.size();
    } else {
      pick = static_cast<size_t>(i) % sh->ids.size();
    }
    const std::string& fid = sh->ids[pick];
    int cls = sh->ClassFor(i);
    OpRecord rec{start, 0, -1, 0, cls, fid, key_class};
    std::string ip;
    int port = 0;
    if (QueryFetch(&tracker,
                   static_cast<uint8_t>(TrackerCmd::kServiceQueryFetchOne),
                   fid, &ip, &port)) {
      PooledPeer storage(&sh->pool, ip, port);
      std::string group, remote;
      SplitId(fid, &group, &remote);
      uint8_t num[16] = {0};  // offset 0, length 0 (= to EOF)
      std::string body(reinterpret_cast<char*>(num), 16);
      body += PackGroup(group) + remote;
      std::string resp;
      uint8_t status = 0;
      if (storage->Call(static_cast<uint8_t>(StorageCmd::kDownloadFile),
                        body, &resp, &status, cls)) {
        rec.status = status;
        rec.bytes = static_cast<int64_t>(resp.size());
      }
    }
    rec.latency_us = MonoUs() - rec.start_us;
    local.push_back(std::move(rec));
    if (local.size() >= 1024) Emit(sh, &local);
  }
  Emit(sh, &local);
}

void DeleteWorker(Shared* sh) {
  Peer tracker(sh->tracker_host, sh->tracker_port);
  std::vector<OpRecord> local;
  for (;;) {
    int64_t i = sh->next.fetch_add(1);
    if (i >= static_cast<int64_t>(sh->ids.size())) break;
    const std::string& fid = sh->ids[i];
    int cls = sh->ClassFor(i);
    OpRecord rec{MonoUs(), 0, -1, 0, cls, fid};
    std::string ip;
    int port = 0;
    if (QueryFetch(&tracker,
                   static_cast<uint8_t>(TrackerCmd::kServiceQueryUpdate),
                   fid, &ip, &port)) {
      PooledPeer storage(&sh->pool, ip, port);
      std::string group, remote;
      SplitId(fid, &group, &remote);
      std::string resp;
      uint8_t status = 0;
      if (storage->Call(static_cast<uint8_t>(StorageCmd::kDeleteFile),
                        PackGroup(group) + remote, &resp, &status, cls))
        rec.status = status;
    }
    rec.latency_us = MonoUs() - rec.start_us;
    local.push_back(std::move(rec));
    if (local.size() >= 1024) Emit(sh, &local);
  }
  Emit(sh, &local);
}

bool WriteResults(const Shared& sh, const std::string& path, bool with_ids) {
  std::ofstream out(path);
  if (!out) return false;
  std::ofstream ids;
  if (with_ids) ids.open(path + ".ids");
  for (const auto& r : sh.records) {
    out << r.start_us << ' ' << r.latency_us << ' ' << r.status << ' '
        << r.bytes << ' ' << r.cls << ' ' << r.file_id;
    if (!r.key_class.empty()) out << ' ' << r.key_class;
    out << '\n';
    if (with_ids && r.status == 0 && !r.file_id.empty())
      ids << r.file_id << '\n';
  }
  return true;
}

bool LoadIds(const std::string& path, std::vector<std::string>* ids) {
  std::ifstream in(path);
  if (!in) return false;
  std::string line;
  while (std::getline(in, line))
    if (!line.empty()) ids->push_back(line);
  return !ids->empty();
}

int RunWorkers(Shared* sh, int threads, void (*fn)(Shared*)) {
  sh->t0_us = MonoUs();  // open-loop schedule origin
  std::vector<std::thread> ts;
  for (int t = 0; t < threads; ++t) ts.emplace_back(fn, sh);
  for (auto& t : ts) t.join();
  // Effective connection counts on stdout (records go to the result
  // file, so stdout is free): the harness asserts the topology it
  // asked for — e.g. `--conns 1` really did run one storage socket —
  // is the one the run actually had.
  sh->pool.PrintStats();
  return 0;
}

// Strip the mode-independent flags (valid anywhere after the mode
// word) out of argv, compacting the rest so positional parsing below
// stays oblivious: --open-loop / --rate R (--rate alone implies
// open-loop; --open-loop without a rate is an error rather than a
// guess) and --conns N (shared storage-connection budget).
bool StripGlobalFlags(int* argc, char** argv, Shared* sh) {
  bool open_loop = false;
  double rate = 0;
  int w = 0;
  for (int a = 0; a < *argc; ++a) {
    std::string flag = argv[a];
    if (flag == "--open-loop") {
      open_loop = true;
    } else if (flag == "--rate" && a + 1 < *argc) {
      char* end = nullptr;
      rate = strtod(argv[++a], &end);
      if (end == argv[a] || rate <= 0) {
        fprintf(stderr, "--rate wants a positive ops/sec, got %s\n", argv[a]);
        return false;
      }
    } else if (flag == "--conns" && a + 1 < *argc) {
      char* end = nullptr;
      long conns = strtol(argv[++a], &end, 10);
      if (end == argv[a] || conns < 0) {
        fprintf(stderr, "--conns wants a non-negative count, got %s\n",
                argv[a]);
        return false;
      }
      sh->pool.set_budget(static_cast<int>(conns));
    } else if (flag == "--priority" && a + 1 < *argc) {
      char* end = nullptr;
      long cls = strtol(argv[++a], &end, 10);
      if (end == argv[a] || cls < 0 || cls > 4) {
        fprintf(stderr, "--priority wants a class 0..4, got %s\n", argv[a]);
        return false;
      }
      sh->priority = static_cast<int>(cls);
    } else if (flag == "--priority-mix" && a + 1 < *argc) {
      // Comma-separated `[label:]class:weight` entries; a malformed
      // spec must be an ERROR, not a silent fall-through to untagged —
      // the per-class verdicts downstream would be measuring nothing.
      std::string spec = argv[++a];
      double acc = 0;
      size_t pos = 0;
      while (pos <= spec.size()) {
        size_t comma = spec.find(',', pos);
        std::string entry = spec.substr(
            pos, comma == std::string::npos ? std::string::npos : comma - pos);
        pos = comma == std::string::npos ? spec.size() + 1 : comma + 1;
        if (entry.empty()) continue;
        size_t c2 = entry.rfind(':');
        size_t c1 = c2 == std::string::npos ? std::string::npos
                                            : entry.rfind(':', c2 - 1);
        // label:class:weight has two colons; class:weight has one (then
        // c1 is npos and the class starts at 0).
        size_t cls_at = c1 == std::string::npos ? 0 : c1 + 1;
        char* end = nullptr;
        long cls = c2 == std::string::npos
                       ? -1
                       : strtol(entry.c_str() + cls_at, &end, 10);
        double wt = c2 == std::string::npos
                        ? 0
                        : strtod(entry.c_str() + c2 + 1, nullptr);
        if (cls < 0 || cls > 4 || end != entry.c_str() + c2 || wt <= 0) {
          fprintf(stderr,
                  "--priority-mix wants [label:]class:weight entries "
                  "(class 0..4, weight > 0), got %s\n", entry.c_str());
          return false;
        }
        acc += wt;
        sh->prio_cdf.emplace_back(static_cast<int>(cls), acc);
      }
      if (sh->prio_cdf.empty()) {
        fprintf(stderr, "--priority-mix spec is empty\n");
        return false;
      }
    } else {
      argv[w++] = argv[a];
    }
  }
  *argc = w;
  if (open_loop && rate <= 0) {
    fprintf(stderr, "--open-loop needs --rate <ops/sec>\n");
    return false;
  }
  sh->rate = rate;
  return true;
}

int64_t Pct(const std::vector<int64_t>& sorted, double q) {
  if (sorted.empty()) return 0;
  size_t i = std::min(static_cast<size_t>(q * sorted.size()),
                      sorted.size() - 1);
  return sorted[i];
}

const char* ClassName(int cls) {
  switch (cls) {
    case 0: return "control";
    case 1: return "interactive";
    case 2: return "normal";
    case 3: return "bulk";
    case 4: return "background";
    default: return "untagged";
  }
}

// combine: merge result files -> one JSON line (combine_result.c
// analogue).  QPS uses the union wall-clock window (min start .. max
// end) so multi-process runs aggregate honestly.  Records carry an
// optional priority-class column (older five-field files parse as
// untagged); "by_class" reports per-class admitted/shed splits (shed =
// the admission ladder's EBUSY 16) with latency percentiles over the
// ADMITTED ops — a shed answers in microseconds, and folding those
// into the percentiles would make an overloaded run look fast.
int Combine(int argc, char** argv) {
  struct ClassAgg {
    std::vector<int64_t> lat;  // admitted (status 0) only
    int64_t ops = 0, shed = 0, errors = 0;
  };
  std::vector<int64_t> lat;
  std::map<int, ClassAgg> by_class;
  std::map<std::string, ClassAgg> by_key_class;
  int64_t errors = 0, shed = 0, bytes = 0, t_min = INT64_MAX, t_max = 0;
  for (int a = 0; a < argc; ++a) {
    std::ifstream in(argv[a]);
    if (!in) {
      fprintf(stderr, "cannot open %s\n", argv[a]);
      return 1;
    }
    int64_t start, latency, b;
    int status;
    std::string rest;
    while (in >> start >> latency >> status >> b) {
      std::getline(in, rest);
      // Sniff the class column: a bare-integer first token is the
      // class, anything else (a file id, or nothing) is the legacy
      // five-field shape.
      int cls = kUntagged;
      size_t tok = rest.find_first_not_of(' ');
      if (tok != std::string::npos) {
        size_t end = rest.find(' ', tok);
        std::string first = rest.substr(
            tok, end == std::string::npos ? std::string::npos : end - tok);
        if (!first.empty() &&
            first.find_first_not_of("0123456789") == std::string::npos)
          cls = atoi(first.c_str());
      }
      // A trailing "hot"/"cold" token (--hot-keys runs) tags the key
      // class; anything else is an untagged record and contributes no
      // by_key_class row.
      std::string key_class;
      size_t last_end = rest.find_last_not_of(' ');
      if (last_end != std::string::npos) {
        size_t last_sp = rest.find_last_of(' ', last_end);
        std::string last_tok =
            rest.substr(last_sp + 1, last_end - last_sp);
        if (last_tok == "hot" || last_tok == "cold") key_class = last_tok;
      }
      lat.push_back(latency);
      auto& agg = by_class[cls];
      agg.ops++;
      if (status == 0) agg.lat.push_back(latency);
      else if (status == 16) { shed++; agg.shed++; errors++; }
      else { agg.errors++; errors++; }
      if (!key_class.empty()) {
        auto& kagg = by_key_class[key_class];
        kagg.ops++;
        if (status == 0) kagg.lat.push_back(latency);
        else if (status == 16) kagg.shed++;
        else kagg.errors++;
      }
      bytes += b;
      t_min = std::min(t_min, start);
      t_max = std::max(t_max, start + latency);
    }
  }
  if (lat.empty()) {
    printf("{\"ops\": 0}\n");
    return 0;
  }
  std::sort(lat.begin(), lat.end());
  double wall_s = static_cast<double>(t_max - t_min) / 1e6;
  int64_t sum = 0;
  for (int64_t v : lat) sum += v;
  std::string classes;
  for (auto& [cls, agg] : by_class) {
    std::sort(agg.lat.begin(), agg.lat.end());
    char buf[256];
    snprintf(buf, sizeof(buf),
             "%s\"%s\": {\"ops\": %lld, \"admitted\": %lld, "
             "\"shed\": %lld, \"errors\": %lld, \"lat_p50_us\": %lld, "
             "\"lat_p99_us\": %lld}",
             classes.empty() ? "" : ", ", ClassName(cls),
             static_cast<long long>(agg.ops),
             static_cast<long long>(agg.lat.size()),
             static_cast<long long>(agg.shed),
             static_cast<long long>(agg.errors),
             static_cast<long long>(Pct(agg.lat, 0.50)),
             static_cast<long long>(Pct(agg.lat, 0.99)));
    classes += buf;
  }
  // Per-key-class (hot/cold) percentiles: the headline number for the
  // elastic-replication bench is "hot-key p99 with promotion on vs
  // off", so the hot rows need their own latency distribution rather
  // than being smeared into the global percentiles.  Emitted only when
  // at least one record carried a key-class tag, so legacy runs keep
  // their exact JSON shape.
  std::string keyclasses;
  for (auto& [kc, agg] : by_key_class) {
    std::sort(agg.lat.begin(), agg.lat.end());
    char buf[320];
    snprintf(buf, sizeof(buf),
             "%s\"%s\": {\"ops\": %lld, \"admitted\": %lld, "
             "\"shed\": %lld, \"errors\": %lld, \"lat_p50_us\": %lld, "
             "\"lat_p95_us\": %lld, \"lat_p99_us\": %lld}",
             keyclasses.empty() ? "" : ", ", kc.c_str(),
             static_cast<long long>(agg.ops),
             static_cast<long long>(agg.lat.size()),
             static_cast<long long>(agg.shed),
             static_cast<long long>(agg.errors),
             static_cast<long long>(Pct(agg.lat, 0.50)),
             static_cast<long long>(Pct(agg.lat, 0.95)),
             static_cast<long long>(Pct(agg.lat, 0.99)));
    keyclasses += buf;
  }
  std::string key_section;
  if (!keyclasses.empty())
    key_section = ", \"by_key_class\": {" + keyclasses + "}";
  printf(
      "{\"ops\": %zu, \"errors\": %lld, \"shed\": %lld, "
      "\"wall_seconds\": %.3f, "
      "\"qps\": %.1f, \"bytes\": %lld, \"GBps\": %.4f, "
      "\"lat_mean_us\": %lld, \"lat_p50_us\": %lld, \"lat_p95_us\": %lld, "
      "\"lat_p99_us\": %lld, \"lat_max_us\": %lld, \"by_class\": {%s}%s}\n",
      lat.size(), static_cast<long long>(errors),
      static_cast<long long>(shed), wall_s,
      lat.size() / std::max(wall_s, 1e-9),
      static_cast<long long>(bytes),
      bytes / std::max(wall_s, 1e-9) / 1e9,
      static_cast<long long>(sum / static_cast<int64_t>(lat.size())),
      static_cast<long long>(Pct(lat, 0.50)),
      static_cast<long long>(Pct(lat, 0.95)),
      static_cast<long long>(Pct(lat, 0.99)),
      static_cast<long long>(lat.back()), classes.c_str(),
      key_section.c_str());
  return 0;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr,
            "usage: fdfs_load upload|download|delete|combine|zipf-sample ...\n");
    return 2;
  }
  std::string mode = argv[1];
  if (mode == "combine") return Combine(argc - 2, argv + 2);
  if (mode == "zipf-sample" && (argc == 5 || argc == 6)) {
    double s = atof(argv[2]);
    int64_t keys = atoll(argv[3]);
    int64_t n = atoll(argv[4]);
    uint64_t seed = argc == 6 ? strtoull(argv[5], nullptr, 10) : 42;
    if (s <= 0 || keys <= 0 || n <= 0) {
      fprintf(stderr, "zipf-sample: s, keys, n must be positive\n");
      return 2;
    }
    ZipfPicker picker(s, static_cast<size_t>(keys), seed);
    for (int64_t i = 0; i < n; ++i)
      printf("%zu\n", picker.Pick(i));
    return 0;
  }

  Shared sh;
  if (!StripGlobalFlags(&argc, argv, &sh)) return 2;
  if (mode == "upload" && argc >= 7 &&
      std::string(argv[3]) == "--small-files") {
    // Small-file corpus mode (ISSUE 9 / config9): --small-files N
    // --file-bytes B <threads> <result>.  Every payload unique — the
    // worst case for per-object inodes, the best case for slabs.
    if (!SplitAddr(argv[2], &sh.tracker_host, &sh.tracker_port)) return 2;
    if (argc < 9 || std::string(argv[5]) != "--file-bytes") {
      fprintf(stderr,
              "usage: fdfs_load upload <tracker> --small-files N "
              "--file-bytes B <threads> <result>\n");
      return 2;
    }
    sh.n_ops = atoll(argv[4]);
    sh.size = atoll(argv[6]);
    if (sh.n_ops <= 0 || sh.size <= 0) {
      fprintf(stderr, "--small-files and --file-bytes must be positive\n");
      return 2;
    }
    int threads = atoi(argv[7]);
    sh.unique = 0;
    RunWorkers(&sh, threads, UploadWorker);
    return WriteResults(sh, argv[8], /*with_ids=*/true) ? 0 : 1;
  }
  if (mode == "upload" && argc >= 7) {
    if (!SplitAddr(argv[2], &sh.tracker_host, &sh.tracker_port)) return 2;
    sh.n_ops = atoll(argv[3]);
    sh.size = atoll(argv[4]);
    int threads = atoi(argv[5]);
    sh.unique = argc > 7 ? atoll(argv[7]) : 0;
    RunWorkers(&sh, threads, UploadWorker);
    return WriteResults(sh, argv[6], /*with_ids=*/true) ? 0 : 1;
  }
  if (mode == "download" && argc >= 7) {
    if (!SplitAddr(argv[2], &sh.tracker_host, &sh.tracker_port)) return 2;
    if (!LoadIds(argv[3], &sh.ids)) {
      fprintf(stderr, "no ids in %s\n", argv[3]);
      return 1;
    }
    sh.n_ops = atoll(argv[4]);
    int threads = atoi(argv[5]);
    // Optional key-popularity mode: --zipf <s> [--zipf-keys N]
    // [--zipf-seed S] after the positional args.
    double zipf_s = 0;
    int64_t zipf_keys = 0;
    uint64_t zipf_seed = 42;
    int64_t hot_keys = 0;
    double hot_pct = 0;
    for (int a = 7; a < argc; ++a) {
      std::string flag = argv[a];
      if (flag == "--hot-keys" && a + 1 < argc) {
        // Same error discipline as --zipf: a malformed spec must fail
        // loudly, not silently degrade to uniform traffic.
        std::string spec = argv[++a];
        size_t colon = spec.find(':');
        int64_t k = 0;
        double pct = 0;
        if (colon != std::string::npos) {
          k = strtoll(spec.c_str(), nullptr, 10);
          pct = strtod(spec.c_str() + colon + 1, nullptr);
        }
        if (colon == std::string::npos || k <= 0 || pct <= 0 ||
            pct > 100) {
          fprintf(stderr,
                  "--hot-keys wants K:pct with K>0 and 0<pct<=100, got %s\n",
                  spec.c_str());
          return 2;
        }
        hot_keys = k;
        hot_pct = pct;
      } else if (flag == "--zipf" && a + 1 < argc) {
        // A bad exponent must be an ERROR, not a silent fall-through to
        // round-robin: this flag exists to measure skew, and "measured
        // unskewed traffic believing it was zipfian" poisons the
        // harness verdicts downstream.
        char* end = nullptr;
        zipf_s = strtod(argv[++a], &end);
        if (end == argv[a] || zipf_s <= 0) {
          fprintf(stderr, "--zipf wants a positive exponent, got %s\n",
                  argv[a]);
          return 2;
        }
      } else if (flag == "--zipf-keys" && a + 1 < argc) {
        zipf_keys = atoll(argv[++a]);
      } else if (flag == "--zipf-seed" && a + 1 < argc) {
        zipf_seed = strtoull(argv[++a], nullptr, 10);
      } else {
        fprintf(stderr, "bad download flag %s\n", flag.c_str());
        return 2;
      }
    }
    if (hot_keys > 0 && zipf_s > 0) {
      fprintf(stderr, "--hot-keys and --zipf are mutually exclusive\n");
      return 2;
    }
    if (hot_keys > 0) {
      sh.hot_keys = hot_keys;
      sh.hot_frac = hot_pct / 100.0;
    }
    if (zipf_s > 0) {
      size_t universe = static_cast<size_t>(
          zipf_keys > 0 ? zipf_keys : std::min<int64_t>(1000, sh.ids.size()));
      if (universe > sh.ids.size()) universe = sh.ids.size();
      sh.zipf = std::make_unique<ZipfPicker>(zipf_s, universe, zipf_seed);
    }
    RunWorkers(&sh, threads, DownloadWorker);
    return WriteResults(sh, argv[6], /*with_ids=*/false) ? 0 : 1;
  }
  if (mode == "delete" && argc >= 6) {
    if (!SplitAddr(argv[2], &sh.tracker_host, &sh.tracker_port)) return 2;
    if (!LoadIds(argv[3], &sh.ids)) {
      fprintf(stderr, "no ids in %s\n", argv[3]);
      return 1;
    }
    sh.n_ops = static_cast<int64_t>(sh.ids.size());
    int threads = atoi(argv[4]);
    RunWorkers(&sh, threads, DeleteWorker);
    return WriteResults(sh, argv[5], /*with_ids=*/false) ? 0 : 1;
  }
  fprintf(stderr, "bad arguments for %s\n", mode.c_str());
  return 2;
}
