// Cross-language golden-check CLI: pytest drives this binary and compares
// against fastdfs_tpu/common (tests/test_native_common.py).
//
// Usage:
//   fdfs_codec encode <group> <spi> <ip> <ts> <size> <crc> <ext> <uniq>
//   fdfs_codec encode-trunk <group> <spi> <ip> <ts> <size> <crc> <ext>
//                <uniq> <trunk_id> <offset> <alloc_size>
//   fdfs_codec decode <file_id>
//   fdfs_codec sha1            (stdin -> hex)
//   fdfs_codec crc32           (stdin -> decimal)
//   fdfs_codec md5             (stdin -> hex)
//   fdfs_codec token <uri> <secret> <ts>   (anti-leech token)
//   fdfs_codec b64e <hex>      (hex bytes -> base64url)
//   fdfs_codec cdc <min> <avg_bits> <max> [seg]  (stdin -> cut offsets,
//                one per line; seg tests the streaming chunker by feeding
//                seg-byte segments)
//   fdfs_codec stats-json      (golden stats-registry snapshot: fixed
//                counters/gauges/histogram observations -> JSON, compared
//                field-for-field against the Python decoder)
//   fdfs_codec trace-json      (golden span-ring dump: fixed spans ->
//                JSON, compared field-for-field against
//                fastdfs_tpu.trace.decode_dump)
//   fdfs_codec trace-ctx <hex32>  (parse a 16-byte TRACE_CTX body and
//                print trace_id/parent/flags — wire-layout golden)
//   fdfs_codec scrub-status    (golden SCRUB_STATUS blob: fixture value
//                per kScrubStatNames slot + the hex wire encoding,
//                compared field-for-field against the Python decoder)
//   fdfs_codec metrics-history (golden METRICS_HISTORY dump: fixed
//                snapshots encoded through the journal's full/delta
//                record codec, decoded back, and emitted as the wire
//                JSON — line 2 reports the binary roundtrip verdict)
//   fdfs_codec heat-top        (golden HEAT_TOP dump: a fixed Touch
//                sequence through the space-saving sketch -> JSON,
//                compared field-for-field against the Python decoder)
//   fdfs_codec slo-conf        (stdin = slo.conf text; prints the
//                normalized rule table "name threshold clear enabled"
//                — pins conf/slo.conf parsing across languages against
//                fastdfs_tpu.monitor.parse_slo_rules)
//   fdfs_codec placement-wire  (golden QUERY_PLACEMENT response: a fixed
//                placement epoch packed through PlacementTable::PackWire
//                as hex, plus jump=<key>:<bucket> lines from the native
//                jump-hash — compared against the Python decoder and
//                fastdfs_tpu.common.jumphash, pinning both the wire
//                layout and the placement function across languages)
//   fdfs_codec group-admin     (golden GROUP_DRAIN / GROUP_REACTIVATE
//                bodies: the 16-byte group-name request and the 8-byte
//                new-version response as hex)
//   fdfs_codec profile-ctl     (golden PROFILE_CTL bodies: the 17-byte
//                start(hz,duration) and stop requests as hex, plus the
//                ack JSON — pins the control wire layout against
//                fastdfs_tpu.common.protocol's packers)
//   fdfs_codec profile-json    (golden PROFILE_DUMP body: a fixture
//                folded-stack row set through the daemon's real JSON
//                emitter (common/profiler.h ProfileJson) — compared
//                field-for-field against
//                fastdfs_tpu.monitor.decode_profile/render_folded)
//   fdfs_codec thread-ledger   (golden per-thread CPU ledger gauge
//                naming: two fixture threads join the registry, one
//                SampleInto pass, and the resulting thread.* gauge
//                keys print sorted; after both leave, a second pass
//                must prune every row — pins the thread.<name>.cpu_pct
//                /utime_ms/stime_ms contract the journal and fdfs_top
//                THREADS pane key on)
//   fdfs_codec slab-layout     (golden slab record + slot-index
//                encoding: one fixture chunk record and one recipe
//                record emitted as hex, then re-scanned with the boot
//                decoder into index lines — pins the on-disk slab
//                layout (storage/slabstore.h) against the Python
//                parser in tests/harness.py / tests/test_slab.py)
//   fdfs_codec gf-tables       (golden GF(2^8) field contract: table
//                CRCs + sample Mul/Inv/CauchyCoeff entries — pins
//                common/gf256.h against fastdfs_tpu/ops/gf256.py so a
//                regenerated table that drifts fails loudly)
//   fdfs_codec ec-status       (golden EC_STATUS blob: fixture value
//                per slot in kEcStatNames order + hex wire blob)
//   fdfs_codec ec-stripe-layout (golden EC stripe: a fixture RS(3,2)
//                encode through EcStore emitted as shard/manifest hex,
//                decoded back byte-identically — with 2 shards
//                deleted — plus the EC_RELEASE wire body; pins the
//                on-disk stripe layout AND the release wire contract
//                against tests/harness.py / tests/test_ec.py)
//   fdfs_codec health-status   (golden HEALTH_STATUS body: a fixture
//                Feed sequence through the REAL HealthMonitor -> wire
//                JSON, plus the beat-trailer bytes as hex and their
//                parse-back — pins scores, EWMA rounding, and the
//                trailer layout against fastdfs_tpu.monitor.
//                decode_health_status / tests/test_health.py)
//   fdfs_codec health-matrix   (golden HEALTH_MATRIX body: fixture
//                trailer reports folded through the REAL tracker
//                Cluster -> the N x N differential matrix JSON — pins
//                the gray/sick/ok/unknown verdict rules across
//                languages against monitor.decode_health_matrix)
//   fdfs_codec priority-frame  (golden PRIORITY prefix frame per class,
//                the full 256-entry storage + tracker born-priority
//                tables, the ladder admit matrix off a REAL controller,
//                and the retry-after body — pins protocol.py's
//                priority_frame/default_priority_class/
//                admitted_at_level against storage/admission.cc)
//   fdfs_codec admission-json  (golden ADMISSION_STATUS body: a fixture
//                controller driven through climb / hysteresis-hold /
//                relax with a per-tick transcript, then the wire JSON —
//                pins the EWMA+hysteresis ladder discipline and
//                monitor.decode_admission across languages)
//   fdfs_codec hot-map         (golden elastic-hot-replication wire set:
//                a fixture QUERY_HOT_MAP full snapshot + delta-with-
//                tombstone through PackHotMap, the beat heat trailer
//                through PackHeatTrailer with its parse-back, the
//                beat-response hot-task trailer through PackHotTasks
//                with its parse-back, and the HOT_FANOUT_DONE ack body
//                — all as hex; tests/test_hot_replication.py decodes
//                them with fastdfs_tpu.monitor.decode_hot_map and the
//                documented layouts, pinning ISSUE 20's wire contracts
//                across languages)
#include <time.h>

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "common/bytes.h"
#include "common/cdc.h"
#include "common/eventlog.h"
#include "common/fileid.h"
#include "common/healthmon.h"
#include "common/heatsketch.h"
#include "common/heatwire.h"
#include "common/http_token.h"
#include "common/ini.h"
#include "common/metrog.h"
#include "common/profiler.h"
#include "common/protocol_gen.h"
#include "common/threadreg.h"
#include "common/sloeval.h"
#include "common/stats.h"
#include "common/jumphash.h"
#include "common/trace.h"
#include "common/gf256.h"
#include "storage/admission.h"
#include "storage/ecstore.h"
#include "storage/slabstore.h"
#include "tracker/cluster.h"
#include "tracker/placement.h"

using namespace fdfs;

static std::string ReadStdin() {
  std::string out;
  char buf[65536];
  size_t n;
  while ((n = fread(buf, 1, sizeof(buf), stdin)) > 0) out.append(buf, n);
  return out;
}

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s encode|decode|sha1|crc32|b64e ...\n", argv[0]);
    return 2;
  }
  std::string cmd = argv[1];
  if (cmd == "encode" && argc == 10) {
    EncodeFileIdArgs a;
    a.group = argv[2];
    a.store_path_index = atoi(argv[3]);
    a.source_ip = PackIp(argv[4]);
    a.create_timestamp = static_cast<uint32_t>(strtoull(argv[5], nullptr, 10));
    a.file_size = strtoull(argv[6], nullptr, 10);
    a.crc32 = static_cast<uint32_t>(strtoull(argv[7], nullptr, 10));
    a.ext = argv[8][0] == '-' ? "" : argv[8];
    a.uniquifier = atoi(argv[9]);
    auto id = EncodeFileId(a);
    if (!id.has_value()) {
      fprintf(stderr, "encode failed\n");
      return 1;
    }
    printf("%s\n", id->c_str());
    return 0;
  }
  if (cmd == "encode-trunk" && argc == 13) {
    EncodeFileIdArgs a;
    a.group = argv[2];
    a.store_path_index = atoi(argv[3]);
    a.source_ip = PackIp(argv[4]);
    a.create_timestamp = static_cast<uint32_t>(strtoull(argv[5], nullptr, 10));
    a.file_size = strtoull(argv[6], nullptr, 10);
    a.crc32 = static_cast<uint32_t>(strtoull(argv[7], nullptr, 10));
    a.ext = argv[8][0] == '-' ? "" : argv[8];
    a.uniquifier = atoi(argv[9]);
    TrunkLocation loc;
    loc.trunk_id = static_cast<uint32_t>(strtoull(argv[10], nullptr, 10));
    loc.offset = static_cast<uint32_t>(strtoull(argv[11], nullptr, 10));
    loc.alloc_size = static_cast<uint32_t>(strtoull(argv[12], nullptr, 10));
    a.trunk = true;
    a.trunk_loc = &loc;
    auto id = EncodeFileId(a);
    if (!id.has_value()) {
      fprintf(stderr, "encode failed\n");
      return 1;
    }
    printf("%s\n", id->c_str());
    return 0;
  }
  if (cmd == "decode" && argc == 3) {
    auto p = DecodeFileId(argv[2]);
    if (!p.has_value()) {
      fprintf(stderr, "decode failed\n");
      return 1;
    }
    printf("group=%s spi=%d ip=%s ts=%u size=%llu crc=%u uniq=%d app=%d trunk=%d slave=%d",
           p->group.c_str(), p->store_path_index, UnpackIp(p->source_ip).c_str(),
           p->create_timestamp, static_cast<unsigned long long>(p->file_size),
           p->crc32, p->uniquifier, p->appender ? 1 : 0, p->trunk ? 1 : 0,
           p->slave ? 1 : 0);
    if (p->trunk_loc.has_value())
      printf(" tid=%u toff=%u talloc=%u", p->trunk_loc->trunk_id,
             p->trunk_loc->offset, p->trunk_loc->alloc_size);
    printf("\n");
    return 0;
  }
  if (cmd == "sha1") {
    std::string data = ReadStdin();
    printf("%s\n", Sha1(data.data(), data.size()).Hex().c_str());
    return 0;
  }
  if (cmd == "crc32") {
    std::string data = ReadStdin();
    printf("%u\n", Crc32(data.data(), data.size()));
    return 0;
  }
  if (cmd == "md5") {
    std::string data = ReadStdin();
    printf("%s\n", Md5Hex(data).c_str());
    return 0;
  }
  if (cmd == "token" && argc == 5) {
    printf("%s\n", HttpGenToken(argv[2], argv[3],
                                strtoll(argv[4], nullptr, 10))
                       .c_str());
    return 0;
  }
  if (cmd == "cdc" && (argc == 5 || argc == 6)) {
    std::string data = ReadStdin();
    const uint8_t* p = reinterpret_cast<const uint8_t*>(data.data());
    std::vector<int64_t> cuts;
    if (argc == 6) {
      size_t seg = strtoull(argv[5], nullptr, 10);
      GearChunker ck(strtoll(argv[2], nullptr, 10), atoi(argv[3]),
                     strtoll(argv[4], nullptr, 10));
      for (size_t off = 0; off < data.size(); off += seg)
        ck.Feed(p + off, std::min(seg, data.size() - off), &cuts);
      ck.Finish(&cuts);
    } else {
      cuts = GearChunkStream(p, data.size(), strtoll(argv[2], nullptr, 10),
                             atoi(argv[3]), strtoll(argv[4], nullptr, 10));
    }
    for (int64_t c : cuts) printf("%lld\n", static_cast<long long>(c));
    return 0;
  }
  if (cmd == "cdc-bench" && (argc == 5 || argc == 6)) {
    // Times the chunker itself over stdin (repeat passes, best-of),
    // excluding process startup and pipe reads — the number
    // bench_configs.py records as chunker_cpp_GBps.
    std::string data = ReadStdin();
    const uint8_t* p = reinterpret_cast<const uint8_t*>(data.data());
    int64_t mn = strtoll(argv[2], nullptr, 10);
    int avg = atoi(argv[3]);
    int64_t mx = strtoll(argv[4], nullptr, 10);
    int reps = argc == 6 ? atoi(argv[5]) : 5;
    size_t cuts = GearChunkStream(p, data.size(), mn, avg, mx).size();  // warm
    double best = 0;
    for (int r = 0; r < reps; ++r) {
      struct timespec a, b;
      clock_gettime(CLOCK_MONOTONIC, &a);
      cuts = GearChunkStream(p, data.size(), mn, avg, mx).size();
      clock_gettime(CLOCK_MONOTONIC, &b);
      double dt = (b.tv_sec - a.tv_sec) + (b.tv_nsec - a.tv_nsec) * 1e-9;
      double gbps = data.size() / dt / 1e9;
      if (gbps > best) best = gbps;
    }
    printf("{\"bytes\": %zu, \"cuts\": %zu, \"GBps\": %.4f}\n", data.size(),
           cuts, best);
    return 0;
  }
  if (cmd == "stats-json") {
    // Fixed fixture — tests/test_monitor.py builds the same registry in
    // Python and asserts every field decodes identically.
    StatsRegistry reg;
    reg.Counter("op.upload_file.count")->store(7);
    reg.Counter("op.download_file.count")->store(3);
    reg.Counter("sync.bytes_saved_wire")->store(1048576);
    reg.SetGauge("server.connections", 2);
    reg.SetGauge("sync.peer.127.0.0.1:23000.lag_s", 4);
    reg.GaugeFn("store.total_upload", [] { return int64_t{9}; });
    StatHistogram* h = reg.Histogram("op.upload_file.latency_us",
                                     StatsRegistry::LatencyBucketsUs());
    h->Observe(100);      // first bucket (inclusive bound)
    h->Observe(101);      // second bucket
    h->Observe(90000);    // 100000 bucket
    h->Observe(99999999); // overflow
    printf("%s\n", reg.Json().c_str());
    return 0;
  }
  if (cmd == "trace-json") {
    // Fixed fixture — tests/test_trace.py builds the expected spans in
    // Python and asserts every field decodes identically.
    TraceRing ring(8);
    TraceSpan root;
    root.trace_id = 0x00F00DFACE12345ULL;
    root.span_id = 0x80000001u;
    root.parent_id = 0x10u;
    root.start_us = 1700000000000000LL;
    root.dur_us = 1500;
    root.status = 0;
    root.flags = 1;
    root.SetName("storage.upload_file");
    ring.Record(root);
    TraceSpan child = root;
    child.span_id = 0x80000002u;
    child.parent_id = root.span_id;
    child.start_us = root.start_us + 100;
    child.dur_us = 900;
    child.SetName("storage.fingerprint");
    ring.Record(child);
    TraceSpan slow;
    slow.trace_id = 0xDEADBEEF00000001ULL;
    slow.span_id = 0x80000003u;
    slow.parent_id = 0;
    slow.start_us = root.start_us - 50;
    slow.dur_us = 2500000;
    slow.status = 5;
    slow.flags = 2;  // kTraceFlagSlow
    slow.SetName("tracker.query_store");
    ring.Record(slow);
    printf("%s\n", ring.Json("storage", 23000).c_str());
    return 0;
  }
  if (cmd == "trace-ctx" && argc == 3) {
    std::string hex = argv[2];
    uint8_t raw[16] = {0};
    if (hex.size() != 32) {
      fprintf(stderr, "want 32 hex chars\n");
      return 1;
    }
    for (size_t i = 0; i < 16; ++i)
      raw[i] = static_cast<uint8_t>(
          strtoul(hex.substr(i * 2, 2).c_str(), nullptr, 16));
    TraceCtx c = ParseTraceCtx(raw);
    uint8_t back[16];
    SerializeTraceCtx(c, back);
    bool roundtrip = memcmp(raw, back, 16) == 0;
    printf("trace_id=%016llx parent=%08x flags=%u roundtrip=%d\n",
           static_cast<unsigned long long>(c.trace_id), c.parent_span,
           c.flags, roundtrip ? 1 : 0);
    return 0;
  }
  if (cmd == "ingest-wire") {
    // Fixed fixture for the negotiated-upload wire layout
    // (UPLOAD_RECIPE request body, its response, the UPLOAD_CHUNKS
    // prefix) — tests/test_dedup_upload.py builds the same bytes with
    // the Python client's encoders and compares hex-for-hex, pinning
    // the cross-language contract like trace-ctx does for tracing.
    const char* payloads[3] = {nullptr, nullptr, nullptr};
    std::string p0(1000, 'a'), p1(2000, 'b'), p2(3000, 'c');
    payloads[0] = p0.data();
    payloads[1] = p1.data();
    payloads[2] = p2.data();
    const size_t lens[3] = {p0.size(), p1.size(), p2.size()};
    std::string body;
    body.push_back(static_cast<char>(3));  // store path index
    std::string ext = "bin";
    ext.resize(6, '\0');
    body += ext;
    uint8_t num[8];
    PutInt64BE(0x11223344, num);  // crc32 of the fixture (fixed)
    body.append(reinterpret_cast<char*>(num), 8);
    PutInt64BE(6000, num);  // logical size
    body.append(reinterpret_cast<char*>(num), 8);
    PutInt64BE(3, num);  // chunk count
    body.append(reinterpret_cast<char*>(num), 8);
    for (int i = 0; i < 3; ++i) {
      Sha1Digest d = Sha1(payloads[i], lens[i]);
      body.append(reinterpret_cast<const char*>(d.bytes), 20);
      PutInt64BE(static_cast<int64_t>(lens[i]), num);
      body.append(reinterpret_cast<char*>(num), 8);
    }
    auto hex = [](const std::string& s) {
      static const char* k = "0123456789abcdef";
      std::string out;
      for (unsigned char c : s) {
        out.push_back(k[c >> 4]);
        out.push_back(k[c & 0xF]);
      }
      return out;
    };
    printf("request=%s\n", hex(body).c_str());
    // Response: session 0x0102030405060708, chunk 1 present (0), the
    // others needed (1).
    std::string resp;
    PutInt64BE(0x0102030405060708LL, num);
    resp.append(reinterpret_cast<char*>(num), 8);
    resp += std::string("\x01\x00\x01", 3);
    printf("response=%s\n", hex(resp).c_str());
    // Phase-2 prefix for that session: payload = chunks 0 + 2.
    std::string pre;
    PutInt64BE(0x0102030405060708LL, num);
    pre.append(reinterpret_cast<char*>(num), 8);
    PutInt64BE(static_cast<int64_t>(lens[0] + lens[2]), num);
    pre.append(reinterpret_cast<char*>(num), 8);
    printf("chunks_prefix=%s\n", hex(pre).c_str());
    return 0;
  }
  if (cmd == "placement-wire") {
    // Fixed placement epoch — tests/test_groups.py decodes the hex with
    // the Python client's QUERY_PLACEMENT parser and re-derives every
    // jump line with fastdfs_tpu.common.jumphash, pinning the
    // store_lookup=3 contract (wire layout AND bucket function) across
    // languages.
    PlacementTable table;
    table.EnsureGroup("group1");
    table.EnsureGroup("group2");
    table.EnsureGroup("group3");
    table.Drain("group2");  // version 4: three joins + one drain
    std::vector<std::vector<PlacementTable::WireMember>> members(3);
    members[0].push_back({"10.0.0.1", 23000});
    members[1].push_back({"10.0.0.2", 23001});
    members[2].push_back({"10.0.0.3", 23002});
    members[2].push_back({"10.0.0.4", 23003});
    auto hex = [](const std::string& s) {
      static const char* k = "0123456789abcdef";
      std::string out;
      for (unsigned char c : s) {
        out.push_back(k[c >> 4]);
        out.push_back(k[c & 0xF]);
      }
      return out;
    };
    printf("version=%lld\n", static_cast<long long>(table.version()));
    printf("response=%s\n", hex(table.PackWire(members)).c_str());
    // Bucket function over the 2 ACTIVE groups (epoch order), plus the
    // raw 64-bit placement keys so both layers pin independently.
    const char* keys[4] = {"alpha", "bravo", "charlie", "delta"};
    for (const char* key : keys) {
      uint64_t pk = PlacementKey(key);
      printf("key=%s placement_key=%llu jump=%d\n", key,
             static_cast<unsigned long long>(pk), JumpHash(pk, 2));
    }
    return 0;
  }
  if (cmd == "group-admin") {
    // GROUP_DRAIN / GROUP_REACTIVATE admin bodies: 16B group-name
    // request, 8B big-endian new-placement-version OK response.
    auto hex = [](const std::string& s) {
      static const char* k = "0123456789abcdef";
      std::string out;
      for (unsigned char c : s) {
        out.push_back(k[c >> 4]);
        out.push_back(k[c & 0xF]);
      }
      return out;
    };
    std::string req;
    PutFixedField(&req, "group2", kGroupNameMaxLen);
    printf("drain_request=%s\n", hex(req).c_str());
    printf("reactivate_request=%s\n", hex(req).c_str());
    std::string resp;
    uint8_t num[8];
    PutInt64BE(4, num);  // the placement version the fixture drain minted
    resp.append(reinterpret_cast<char*>(num), 8);
    printf("ok_response=%s\n", hex(resp).c_str());
    return 0;
  }
  if (cmd == "event-json") {
    // Fixed fixture — tests/test_observability.py decodes this with
    // fastdfs_tpu.monitor.decode_events and asserts every field,
    // pinning the EVENT_DUMP wire contract across languages (the
    // flight-recorder twin of trace-json).
    EventLog log(8);
    log.Record(EventSeverity::kWarn, "chunk.quarantined",
               "00112233445566778899aabbccddeeff00112233",
               "spi=0 bytes=8192");
    log.Record(EventSeverity::kInfo, "chunk.repaired",
               "00112233445566778899aabbccddeeff00112233", "spi=0 by=replica");
    log.Record(EventSeverity::kError, "chunk.unrepairable",
               "ffeeddccbbaa99887766554433221100ffeeddcc",
               "spi=1 reason=no_replica");
    log.Record(EventSeverity::kWarn, "request.slow", "storage.upload_file",
               "peer=10.0.0.9 dur_us=2500000 status=0");
    // Escaping coverage: a hostile key must stay valid JSON.
    log.Record(EventSeverity::kInfo, "config.anomaly",
               "weird\"key\\with\nescapes", "detail=1");
    printf("%s\n", log.Json("storage", 23000).c_str());
    return 0;
  }
  if (cmd == "scrub-status") {
    // Cross-language golden for the SCRUB_STATUS wire layout: a fixed
    // fixture value per slot, emitted in kScrubStatNames order both as
    // name=value lines and as the hex-encoded wire blob.
    // tests/test_scrub.py decodes the blob with
    // fastdfs_tpu.common.protocol.unpack_scrub_stats and asserts every
    // named field — pinning slot order AND count across languages.
    std::string blob;
    for (int i = 0; i < kScrubStatCount; ++i) {
      int64_t v = 1000 + 13 * i;
      uint8_t num[8];
      PutInt64BE(v, num);
      blob.append(reinterpret_cast<char*>(num), 8);
      printf("%s=%lld\n", kScrubStatNames[i], static_cast<long long>(v));
    }
    static const char* kHex = "0123456789abcdef";
    std::string hex;
    for (unsigned char ch : blob) {
      hex.push_back(kHex[ch >> 4]);
      hex.push_back(kHex[ch & 0xF]);
    }
    printf("blob=%s\n", hex.c_str());
    return 0;
  }
  if (cmd == "metrics-history") {
    // Fixed fixture — tests/test_report.py decodes line 1 with
    // fastdfs_tpu.monitor.decode_metrics_history and asserts every
    // field, pinning the METRICS_HISTORY wire contract.  The fixture
    // deliberately exercises the journal's whole delta vocabulary:
    // value deltas, a NEW series appearing mid-stream, a pruned gauge
    // (tombstone), and histogram bucket growth.
    StatsSnapshot s1;
    s1.counters["op.upload_file.count"] = 10;
    s1.counters["op.upload_file.errors"] = 1;
    s1.gauges["server.connections"] = 3;
    s1.gauges["sync.peer.10.0.0.2:23000.lag_s"] = 7;
    StatsSnapshot::Hist h;
    h.bounds = {100, 1000, 10000};
    h.counts = {5, 2, 0, 0};
    h.sum = 900;
    h.count = 7;
    s1.histograms["op.upload_file.latency_us"] = h;

    StatsSnapshot s2 = s1;
    s2.counters["op.upload_file.count"] = 25;
    s2.counters["op.download_file.count"] = 4;  // new series
    s2.gauges.erase("sync.peer.10.0.0.2:23000.lag_s");  // pruned peer
    s2.histograms["op.upload_file.latency_us"].counts = {5, 12, 3, 1};
    s2.histograms["op.upload_file.latency_us"].sum = 31337;
    s2.histograms["op.upload_file.latency_us"].count = 21;

    StatsSnapshot s3 = s2;
    s3.gauges["server.connections"] = 0;

    std::vector<std::pair<int64_t, StatsSnapshot>> snaps = {
        {1700000000000000LL, s1},
        {1700000005000000LL, s2},
        {1700000010000000LL, s3},
    };
    std::string buf;
    const StatsSnapshot* prev = nullptr;
    for (const auto& [ts, s] : snaps) {
      buf += MetricsJournal::EncodeRecord(prev, s, ts);
      prev = &s;
    }
    size_t valid = 0;
    auto back = MetricsJournal::DecodeBuffer(buf, &valid);
    bool roundtrip = valid == buf.size() && back.size() == snaps.size();
    for (size_t i = 0; roundtrip && i < snaps.size(); ++i) {
      roundtrip = back[i].first == snaps[i].first &&
                  back[i].second.counters == snaps[i].second.counters &&
                  back[i].second.gauges == snaps[i].second.gauges;
    }
    printf("%s\n",
           MetricsJournal::SnapshotsJson("storage", 23000, back).c_str());
    printf("roundtrip=%d\n", roundtrip ? 1 : 0);
    return roundtrip ? 0 : 1;
  }
  if (cmd == "heat-top") {
    // Fixed fixture — tests/test_report.py decodes this with
    // fastdfs_tpu.monitor.decode_heat and asserts ranking + per-op
    // splits, pinning the HEAT_TOP wire contract.
    HeatSketch sketch(8, 2);
    const char* hot = "group1/M00/00/01/hotfile.bin";
    const char* warm = "group1/M00/00/02/warmfile.bin";
    const char* cold = "group1/M00/00/03/coldfile.bin";
    for (int i = 0; i < 9; ++i)
      sketch.Touch(hot, HeatOp::kDownload, 4096, false);
    sketch.Touch(hot, HeatOp::kUpload, 8192, false);
    for (int i = 0; i < 4; ++i)
      sketch.Touch(warm, HeatOp::kDownload, 1024, false);
    sketch.Touch(warm, HeatOp::kFetchChunk, 512, false);
    sketch.Touch(cold, HeatOp::kDownload, 0, true);  // one failed read
    printf("%s\n", sketch.TopJson("storage", 23000, 3).c_str());
    return 0;
  }
  if (cmd == "slo-conf") {
    // stdin = slo.conf text; output = the normalized rule table the
    // daemons will actually run.  tests/test_report.py parses the same
    // text with fastdfs_tpu.monitor.parse_slo_rules and compares line
    // for line — threshold/clear rescaling and enable flags included.
    IniConfig ini;
    std::string err;
    if (!ini.LoadString(ReadStdin(), &err)) {
      fprintf(stderr, "bad slo conf: %s\n", err.c_str());
      return 1;
    }
    for (const SloRule& r : SloEvaluator::LoadRules(ini))
      printf("%s %.6g %.6g %d\n", r.name.c_str(), r.threshold, r.clear,
             r.enabled ? 1 : 0);
    return 0;
  }
  if (cmd == "profile-ctl") {
    // PROFILE_CTL wire bodies (protocol.py): 1B action + 8B BE hz +
    // 8B BE duration seconds.  tests/test_profile.py builds the same
    // bytes with the Python packer and compares hex for hex.
    auto hex = [](const std::string& s) {
      static const char* k = "0123456789abcdef";
      std::string out;
      for (unsigned char c : s) {
        out.push_back(k[c >> 4]);
        out.push_back(k[c & 0xF]);
      }
      return out;
    };
    auto body = [](uint8_t action, int64_t hz, int64_t secs) {
      std::string b(1, static_cast<char>(action));
      uint8_t num[8];
      PutInt64BE(hz, num);
      b.append(reinterpret_cast<char*>(num), 8);
      PutInt64BE(secs, num);
      b.append(reinterpret_cast<char*>(num), 8);
      return b;
    };
    printf("start_request=%s\n", hex(body(1, 97, 5)).c_str());
    printf("stop_request=%s\n", hex(body(0, 0, 0)).c_str());
    printf("ack=%s\n", "{\"active\":true,\"hz\":97}");
    return 0;
  }
  if (cmd == "profile-json") {
    // Fixture folded stacks through the daemon's REAL dump emitter —
    // tests/test_profile.py decodes with monitor.decode_profile and
    // asserts every field plus the render_folded flamegraph lines.
    std::vector<FoldedStack> rows;
    rows.push_back({"nio.loop/0;EventLoop::Run;epoll_wait", 41});
    rows.push_back({"dio.worker/1;WorkerPool::Main;pwrite64", 17});
    rows.push_back({"dio.worker/0;WorkerPool::Main;ChunkStore::Put;fdfs::Sha1",
                    17});
    // Escaping coverage: a hostile frame must stay valid JSON.
    rows.push_back({"scrub;frame\"with\\escapes", 2});
    printf("%s\n", ProfileJson("storage", 23000, false, 97, 5, 77, 3, 1234,
                               std::move(rows))
                       .c_str());
    return 0;
  }
  if (cmd == "thread-ledger") {
    // Ledger gauge-naming golden: two named fixture threads join, one
    // sample pass publishes their rows, and after both leave a second
    // pass must prune them.  Values are timing-dependent, so the golden
    // pins NAMES (the journal/fdfs_top contract), not numbers.
    StatsRegistry reg;
    std::atomic<bool> stop{false};
    std::atomic<int> ready{0};
    auto worker = [&](const char* name) {
      ScopedThreadName ledger(name);
      ready.fetch_add(1);
      while (!stop.load()) {
      }
    };
    std::thread t1(worker, "nio.loop/0");
    std::thread t2(worker, "dio.worker/1");
    while (ready.load() < 2) {
    }
    ThreadRegistry::Global().SampleInto(&reg);
    StatsSnapshot snap;
    reg.Snapshot(&snap);
    std::string keys;
    for (const auto& [name, v] : snap.gauges) {
      if (name.rfind("thread.", 0) != 0) continue;
      if (!keys.empty()) keys += ',';
      keys += name;
    }
    printf("gauges=%s\n", keys.c_str());
    stop.store(true);
    t1.join();
    t2.join();
    ThreadRegistry::Global().SampleInto(&reg);
    StatsSnapshot after;
    reg.Snapshot(&after);
    int left = 0;
    for (const auto& [name, v] : after.gauges)
      if (name.rfind("thread.", 0) == 0) ++left;
    printf("after_leave=%d\n", left);
    printf("registered_while_live=%d\n", 2);
    return 0;
  }
  if (cmd == "slab-layout") {
    // Fixed fixture — tests/test_slab.py builds the same records with
    // the Python encoder (struct + zlib.crc32) and compares hex for
    // hex, then parses them back with tests/harness.py's header
    // scanner; the index lines below come from the C++ boot decoder,
    // pinning BOTH directions of the slab layout across languages.
    auto hex = [](const std::string& s) {
      static const char* k = "0123456789abcdef";
      std::string out;
      for (unsigned char c : s) {
        out.push_back(k[c >> 4]);
        out.push_back(k[c & 0xF]);
      }
      return out;
    };
    const int64_t mtime = 1700000000;
    std::string chunk_payload = "slab golden chunk payload 0123456789";
    std::string chunk_key =
        Sha1(chunk_payload.data(), chunk_payload.size()).Hex();
    std::string recipe_payload("FDFSRCP1golden-recipe-bytes\x00\x7f\x01",
                               30);
    std::string recipe_key = "data/00/1A/golden.bin.rcp";
    std::string buf =
        SlabEncodeRecord(kSlabKindChunk, chunk_key, chunk_payload.data(),
                         chunk_payload.size(), mtime) +
        SlabEncodeRecord(kSlabKindRecipe, recipe_key,
                         recipe_payload.data(), recipe_payload.size(),
                         mtime);
    printf("chunk_record=%s\n",
           hex(buf.substr(0, kSlabRecordHeaderSize + chunk_key.size() +
                                 chunk_payload.size()))
               .c_str());
    printf("recipe_record=%s\n",
           hex(buf.substr(kSlabRecordHeaderSize + chunk_key.size() +
                          chunk_payload.size()))
               .c_str());
    size_t off = 0;
    while (off < buf.size()) {
      SlabRecordView v;
      if (!SlabDecodeRecord(buf.data() + off, buf.size() - off, &v)) {
        printf("decode_error_at=%zu\n", off);
        return 1;
      }
      printf("index=kind:%u key:%s record_off:%zu payload_off:%zu "
             "payload_len:%lld crc:%u mtime:%lld flags:%u\n",
             v.kind, v.key.c_str(), off,
             off + kSlabRecordHeaderSize + v.key.size(),
             static_cast<long long>(v.payload_len), v.payload_crc32,
             static_cast<long long>(v.mtime), v.flags);
      off += static_cast<size_t>(v.record_len);
    }
    return 0;
  }
  if (cmd == "gf-tables") {
    // Field-contract golden: tools/gen_gf_tables.py generates BOTH
    // common/gf256.h and fastdfs_tpu/ops/gf256.py from one source of
    // truth; tests/test_ec.py recomputes these CRCs and samples from
    // the Python tables so a drifted regeneration fails loudly.
    printf("poly=0x%X\n", gf256::kPoly);
    printf("exp_crc32=%u\n", Crc32(gf256::kExp, sizeof(gf256::kExp)));
    printf("log_crc32=%u\n", Crc32(gf256::kLog, sizeof(gf256::kLog)));
    printf("exp_1=%u exp_254=%u exp_255=%u exp_509=%u\n", gf256::kExp[1],
           gf256::kExp[254], gf256::kExp[255], gf256::kExp[509]);
    printf("log_2=%u log_142=%u log_255=%u\n", gf256::kLog[2],
           gf256::kLog[142], gf256::kLog[255]);
    printf("mul_7_9=%u mul_255_255=%u inv_2=%u div_5_7=%u\n",
           gf256::Mul(7, 9), gf256::Mul(255, 255), gf256::Inv(2),
           gf256::Div(5, 7));
    // The RS(3, 2) Cauchy parity matrix the stripe golden encodes with.
    for (int j = 0; j < 2; ++j)
      for (int i = 0; i < 3; ++i)
        printf("cauchy_3_%d_%d=%u\n", j, i, gf256::CauchyCoeff(3, j, i));
    return 0;
  }
  if (cmd == "ec-status") {
    // EC_STATUS wire golden (the scrub-status pattern): fixture value
    // per slot in kEcStatNames order + the hex blob; tests/test_ec.py
    // decodes with fastdfs_tpu.common.protocol.unpack_ec_stats.
    std::string blob;
    for (int i = 0; i < kEcStatCount; ++i) {
      int64_t v = 1000 + 13 * i;
      uint8_t num[8];
      PutInt64BE(v, num);
      blob.append(reinterpret_cast<char*>(num), 8);
      printf("%s=%lld\n", kEcStatNames[i], static_cast<long long>(v));
    }
    static const char* kHex = "0123456789abcdef";
    std::string hex;
    for (unsigned char ch : blob) {
      hex.push_back(kHex[ch >> 4]);
      hex.push_back(kHex[ch & 0xF]);
    }
    printf("blob=%s\n", hex.c_str());
    return 0;
  }
  if (cmd == "ec-stripe-layout") {
    // On-disk stripe golden: one fixture RS(3, 2) encode through the
    // REAL EcStore (not a reimplementation), every shard + the manifest
    // emitted as hex for tests/test_ec.py to rebuild byte-for-byte with
    // the Python struct encoder, then decoded back with m = 2 shards
    // deleted — pinning layout AND reconstruction in one fixture.
    // Finishes with the EC_RELEASE wire body for the same chunks.
    auto hex = [](const std::string& s) {
      static const char* k = "0123456789abcdef";
      std::string out;
      for (unsigned char c : s) {
        out.push_back(k[c >> 4]);
        out.push_back(k[c & 0xF]);
      }
      return out;
    };
    char dir_tmpl[] = "/tmp/fdfs_ec_golden_XXXXXX";
    char* dir = mkdtemp(dir_tmpl);
    if (dir == nullptr) {
      fprintf(stderr, "mkdtemp failed\n");
      return 1;
    }
    std::vector<std::pair<std::string, std::string>> chunks;
    // Unequal lengths on purpose: chunk 1 spans a shard boundary and
    // the tail shard carries zero padding.
    std::string payloads[3] = {
        std::string(37, '\0'), "ec-golden-b",
        std::string("ec golden chunk payload C with some padding tail !"),
    };
    for (int i = 0; i < 37; ++i)
      payloads[0][static_cast<size_t>(i)] = static_cast<char>('A' + i % 23);
    for (const std::string& p : payloads) {
      chunks.emplace_back(Sha1(p.data(), p.size()).Hex(), p);
      printf("chunk=%s len=%zu\n", chunks.back().first.c_str(), p.size());
    }
    std::string err;
    int64_t rc = 1;
    {
      EcStore ec(dir, 3, 2);
      int64_t id = ec.EncodeStripe(chunks, &err);
      if (id < 0) {
        fprintf(stderr, "encode: %s\n", err.c_str());
        return 1;
      }
      printf("stripe_id=%lld verify=%d\n", static_cast<long long>(id),
             ec.VerifyStripe(id, &err) ? 1 : 0);
      rc = 0;
    }
    std::vector<std::string> files;
    for (int s = 0; s < 5; ++s) {
      char name[32];
      snprintf(name, sizeof(name), "0000000000.s%02d", s);
      files.push_back(name);
    }
    files.push_back("0000000000.mft");
    for (const std::string& name : files) {
      FILE* f = fopen((std::string(dir) + "/" + name).c_str(), "rb");
      if (f == nullptr) {
        fprintf(stderr, "missing %s\n", name.c_str());
        return 1;
      }
      std::string bytes;
      char buf[4096];
      size_t n;
      while ((n = fread(buf, 1, sizeof(buf), f)) > 0) bytes.append(buf, n);
      fclose(f);
      printf("file=%s bytes=%s\n", name.c_str(), hex(bytes).c_str());
    }
    // Kill-and-reconstruct in miniature: drop m = 2 shards (one data,
    // one parity), rescan cold, and every chunk must read back
    // byte-identical through the parity decode.
    remove((std::string(dir) + "/0000000000.s01").c_str());
    remove((std::string(dir) + "/0000000000.s04").c_str());
    {
      EcStore ec2(dir, 3, 2);
      ec2.Rescan();
      for (size_t i = 0; i < chunks.size(); ++i) {
        std::string out;
        bool ok = ec2.ReadChunk(chunks[i].first, &out) &&
                  out == chunks[i].second;
        printf("reconstruct_%zu=%d\n", i, ok ? 1 : 0);
        if (!ok) rc = 1;
      }
    }
    for (const std::string& name : files)
      remove((std::string(dir) + "/" + name).c_str());
    remove(dir);
    // EC_RELEASE wire body for the same chunks: 16B group + 8B count +
    // per chunk 20B raw digest + 8B BE length.
    std::string body;
    PutFixedField(&body, "group1", kGroupNameMaxLen);
    uint8_t num[8];
    PutInt64BE(static_cast<int64_t>(chunks.size()), num);
    body.append(reinterpret_cast<char*>(num), 8);
    for (const auto& ch : chunks) {
      HexToBytes(ch.first, &body);
      PutInt64BE(static_cast<int64_t>(ch.second.size()), num);
      body.append(reinterpret_cast<char*>(num), 8);
    }
    printf("release_body=%s\n", hex(body).c_str());
    return static_cast<int>(rc);
  }
  if (cmd == "health-status") {
    // Fixed fixture through the REAL HealthMonitor — tests/test_health.py
    // rebuilds the expected JSON (score formula, EWMA rounding, row
    // order) with the Python mirror and decodes the trailer hex with the
    // documented layout, pinning HEALTH_STATUS and the beat trailer
    // across languages in one golden.
    HealthMonitor& hm = HealthMonitor::Global();
    hm.Reset();
    hm.SetStalledThreads(1);
    hm.SetProbe(1500, 2500, 1000);  // under threshold: no self penalty
    // Peer A: three clean fetches, then one timeout-shaped failure.
    for (int i = 0; i < 3; ++i)
      hm.Feed("10.0.0.2:23000", "fetch", true, 50000, 1000);
    hm.Feed("10.0.0.2:23000", "fetch", false, 950000, 1000);
    // Same peer, a healthy op class: composite must take the MIN.
    hm.Feed("10.0.0.2:23000", "beat", true, 2000, 2000);
    hm.Feed("10.0.0.2:23000", "beat", true, 2000, 2000);
    // Peer B: one hard connect failure (fast fail, not timeout-shaped).
    hm.Feed("10.0.0.9:23001", "probe", false, 100, 2000);
    printf("%s\n", hm.Json("storage", 23000).c_str());
    printf("self_score=%lld\n", static_cast<long long>(hm.SelfScore()));
    printf("peer_a=%lld peer_b=%lld\n",
           static_cast<long long>(hm.PeerScore("10.0.0.2:23000")),
           static_cast<long long>(hm.PeerScore("10.0.0.9:23001")));
    std::string trailer = hm.PackBeatTrailer();
    static const char* kHex = "0123456789abcdef";
    std::string hex;
    for (unsigned char ch : trailer) {
      hex.push_back(kHex[ch >> 4]);
      hex.push_back(kHex[ch & 0xF]);
    }
    printf("trailer=%s\n", hex.c_str());
    BeatHealthTrailer ht;
    bool parsed = ParseBeatHealthTrailer(trailer.data(), trailer.size(), &ht);
    printf("parsed=%d parsed_self=%lld\n", parsed ? 1 : 0,
           static_cast<long long>(ht.self_score));
    for (const auto& [addr, score] : ht.peers)
      printf("parsed_peer=%s:%lld\n", addr.c_str(),
             static_cast<long long>(score));
    // Op-class bucketing is part of the cross-language contract too
    // (tests assert the same opcode -> class mapping).
    printf("opclass_111=%s opclass_83=%s opclass_129=%s opclass_145=%s "
           "opclass_16=%s opclass_11=%s\n",
           HealthMonitor::OpClassFor(111), HealthMonitor::OpClassFor(83),
           HealthMonitor::OpClassFor(129), HealthMonitor::OpClassFor(145),
           HealthMonitor::OpClassFor(16), HealthMonitor::OpClassFor(11));
    hm.Reset();
    return parsed ? 0 : 1;
  }
  if (cmd == "health-matrix") {
    // Fixture trailer reports folded through the REAL tracker Cluster:
    // one healthy node, one signature gray (claims 90, peers say ~37),
    // one self-admitted sick, one silent (never sent a trailer).
    Cluster cl;
    const int64_t now = 1700000000;
    cl.Join("group1", "10.0.0.1", 23000, 1, now - 500);
    cl.Join("group1", "10.0.0.2", 23000, 1, now - 500);
    cl.Join("group1", "10.0.0.3", 23000, 1, now - 500);
    cl.Join("group1", "10.0.0.4", 23000, 1, now - 500);
    cl.UpdateHealth("group1", "10.0.0.1", 23000, 100,
                    {{"10.0.0.2:23000", 40}, {"10.0.0.3:23000", 95}},
                    now - 10);
    cl.UpdateHealth("group1", "10.0.0.2", 23000, 90,
                    {{"10.0.0.1:23000", 100}, {"10.0.0.3:23000", 92}},
                    now - 8);
    cl.UpdateHealth("group1", "10.0.0.3", 23000, 30,
                    {{"10.0.0.1:23000", 98}, {"10.0.0.2:23000", 35}},
                    now - 5);
    printf("{\"role\":\"tracker\",\"port\":22122,\"gray_threshold\":60,"
           "\"nodes\":%s}\n",
           cl.HealthMatrixJson(now, 60).c_str());
    return 0;
  }
  if (cmd == "priority-frame") {
    // Golden PRIORITY prefix frame + the born-priority tables
    // (tests/test_admission.py rebuilds every line with the protocol.py
    // mirrors: priority_frame(), default_priority_class(),
    // admitted_at_level(), pack_retry_after()).  The 256-entry digit
    // strings pin the FULL opcode -> class mapping in both directions —
    // a class added on one side only shifts a digit and fails loudly.
    auto hex = [](const std::string& s) {
      static const char* k = "0123456789abcdef";
      std::string o;
      for (unsigned char ch : s) {
        o.push_back(k[ch >> 4]);
        o.push_back(k[ch & 0xF]);
      }
      return o;
    };
    for (int c = 0; c < kPriorityClassCount; ++c) {
      std::string frame(kHeaderSize + kPriorityFrameLen, '\0');
      PutInt64BE(kPriorityFrameLen,
                 reinterpret_cast<uint8_t*>(frame.data()));
      frame[8] = static_cast<char>(StorageCmd::kPriority);
      frame[9] = 0;
      frame[10] = static_cast<char>(c);
      printf("frame_%s=%s\n", PriorityClassName(static_cast<uint8_t>(c)),
             hex(frame).c_str());
    }
    std::string sdef, tdef;
    for (int i = 0; i < 256; ++i) {
      sdef.push_back(
          static_cast<char>('0' + DefaultPriorityClass(static_cast<uint8_t>(i))));
      tdef.push_back(static_cast<char>(
          '0' + DefaultTrackerPriorityClass(static_cast<uint8_t>(i))));
    }
    printf("storage_defaults=%s\n", sdef.c_str());
    printf("tracker_defaults=%s\n", tdef.c_str());
    // Ladder admit matrix straight off a REAL controller walked up rung
    // by rung (sustained breach pressure), not off the formula — pins
    // WouldAdmit at every level.
    AdmissionConfig acfg;
    AdmissionController ac(acfg);
    AdmissionSignals breach;
    breach.breaches_active = 1;
    for (int lvl = 0;; ++lvl) {
      std::string row;
      for (int c = 0; c < kPriorityClassCount; ++c)
        row.push_back(ac.WouldAdmit(static_cast<uint8_t>(c)) ? '1' : '0');
      printf("admit_level%d=%s\n", lvl, row.c_str());
      if (lvl >= AdmissionController::kMaxLevel) break;
      ac.Tick(breach);  // ewma jumps to 1.0 > 0.9: one rung per tick
    }
    std::string retry(8, '\0');
    PutInt64BE(1500, reinterpret_cast<uint8_t*>(retry.data()));
    printf("retry_after_1500=%s\n", hex(retry).c_str());
    return 0;
  }
  if (cmd == "admission-json") {
    // Golden ADMISSION_STATUS body + the EWMA/hysteresis transcript: a
    // fixture controller driven through climb, hold (the hysteresis
    // band between relax and tighten — NO flap), and relax, with the
    // ladder position printed after every tick, then the exact wire
    // JSON (monitor.decode_admission parses it back field-for-field).
    AdmissionConfig acfg;
    acfg.retry_after_ms = 250;
    AdmissionController ac(acfg);
    auto tick = [&](double breaches) {
      AdmissionSignals s;
      s.breaches_active = static_cast<int64_t>(breaches);
      int moved = ac.Tick(s);
      printf("tick breaches=%d moved=%+d level=%d ewma_milli=%lld\n",
             static_cast<int>(breaches), moved, ac.level(),
             static_cast<long long>(ac.ewma_milli()));
    };
    // Climb: sustained breach -> ewma 1.0 every tick, one rung each.
    tick(1);
    tick(1);
    tick(1);
    tick(1);  // already at kMaxLevel: moved=0
    // Sheds at reads-only: normal/bulk/background bounce, control and
    // interactive pass (and the retry hint is level-scaled: 250 * 3).
    int64_t retry_ms = 0;
    for (int c = 0; c < kPriorityClassCount; ++c) {
      bool ok = ac.AdmitOrShed(static_cast<uint8_t>(c), &retry_ms);
      printf("admit class=%d ok=%d retry_ms=%lld\n", c, ok ? 1 : 0,
             static_cast<long long>(ok ? 0 : retry_ms));
    }
    // Recovery: first zero tick decays the EWMA to 0.5 — inside the
    // hysteresis band, the ladder HOLDS (this line is the no-flap pin);
    // the second reaches 0.25 <= 0.45 and relaxes one rung.
    tick(0);
    tick(0);
    printf("%s\n", ac.StatusJson("storage", 23000).c_str());
    return 0;
  }
  if (cmd == "hot-map") {
    // Elastic hot-replication wire goldens (ISSUE 20) — every blob the
    // tracker, the elected storage, and the client exchange, from the
    // REAL codecs in common/heatwire.h.
    auto hex = [](const std::string& s) {
      static const char* k = "0123456789abcdef";
      std::string out;
      for (unsigned char c : s) {
        out.push_back(k[c >> 4]);
        out.push_back(k[c & 0xF]);
      }
      return out;
    };
    // QUERY_HOT_MAP full snapshot at version 7.
    std::vector<HotMapEntry> full;
    full.push_back({"group1/M00/00/01/hotfile.bin", {"group2", "group3"}});
    full.push_back({"group2/M00/00/02/warmfile.bin", {"group1"}});
    printf("full_response=%s\n", hex(PackHotMap(7, true, full)).c_str());
    // Delta since version 7 -> 9: one new publish + one tombstone (the
    // zero-group entry that tells clients "demoted, stop routing").
    std::vector<HotMapEntry> delta;
    delta.push_back({"group3/M00/00/05/risen.bin", {"group1"}});
    delta.push_back({"group1/M00/00/01/hotfile.bin", {}});
    printf("delta_response=%s\n", hex(PackHotMap(9, false, delta)).c_str());
    std::string since(8, '\0');
    PutInt64BE(7, reinterpret_cast<uint8_t*>(since.data()));
    printf("delta_request=%s\n", hex(since).c_str());
    // Beat heat trailer: cumulative download counters, parse-back pins
    // both directions.
    std::vector<HeatTrailerEntry> heat;
    heat.push_back({"group1/M00/00/01/hotfile.bin", 9, 36864});
    heat.push_back({"group2/M00/00/02/warmfile.bin", 4, 4096});
    std::string ht = PackHeatTrailer(heat);
    printf("heat_trailer=%s\n", hex(ht).c_str());
    std::vector<HeatTrailerEntry> heat_back;
    bool hok = ParseHeatTrailer(
        reinterpret_cast<const uint8_t*>(ht.data()), ht.size(), &heat_back);
    printf("heat_parsed=%d\n", hok ? 1 : 0);
    for (const auto& e : heat_back)
      printf("heat_entry=%s:%lld:%lld\n", e.key.c_str(),
             static_cast<long long>(e.hits),
             static_cast<long long>(e.bytes));
    // Beat-response hot-task trailer: one replicate election + one drop.
    std::vector<HotTask> tasks;
    tasks.push_back({kHotTaskReplicate, "group1/M00/00/01/hotfile.bin",
                     {"group2", "group3"}});
    tasks.push_back({kHotTaskDrop, "group2/M00/00/02/warmfile.bin",
                     {"group1"}});
    std::string tt = PackHotTasks(tasks);
    printf("task_trailer=%s\n", hex(tt).c_str());
    std::vector<HotTask> tasks_back;
    bool tok = ParseHotTasks(
        reinterpret_cast<const uint8_t*>(tt.data()), tt.size(), &tasks_back);
    printf("task_parsed=%d\n", tok ? 1 : 0);
    for (const auto& t : tasks_back) {
      std::string gs;
      for (const auto& g : t.groups) {
        if (!gs.empty()) gs += ',';
        gs += g;
      }
      printf("task_entry=%u:%s:%s\n", t.type, t.key.c_str(), gs.c_str());
    }
    // HOT_FANOUT_DONE ack: 16B home group + 1B type + 8B key_len + key
    // + 8B verified-group count + n x 16B names.
    std::string ack;
    PutFixedField(&ack, "group1", kGroupNameMaxLen);
    ack.push_back(static_cast<char>(kHotTaskReplicate));
    uint8_t num[8];
    const std::string key = "group1/M00/00/01/hotfile.bin";
    PutInt64BE(static_cast<int64_t>(key.size()), num);
    ack.append(reinterpret_cast<char*>(num), 8);
    ack += key;
    PutInt64BE(2, num);
    ack.append(reinterpret_cast<char*>(num), 8);
    PutFixedField(&ack, "group2", kGroupNameMaxLen);
    PutFixedField(&ack, "group3", kGroupNameMaxLen);
    printf("ack_body=%s\n", hex(ack).c_str());
    return (hok && tok) ? 0 : 1;
  }
  if (cmd == "b64e" && argc == 3) {
    std::string hex = argv[2];
    std::vector<uint8_t> raw;
    for (size_t i = 0; i + 1 < hex.size(); i += 2) {
      raw.push_back(static_cast<uint8_t>(
          strtoul(hex.substr(i, 2).c_str(), nullptr, 16)));
    }
    printf("%s\n", Base64UrlEncode(raw.data(), raw.size()).c_str());
    return 0;
  }
  fprintf(stderr, "bad arguments\n");
  return 2;
}
