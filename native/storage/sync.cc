#include "storage/sync.h"

#include <ctype.h>
#include <fcntl.h>
#include <string.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>

#include "common/bytes.h"
#include "common/eventlog.h"
#include "common/healthmon.h"
#include "common/log.h"
#include "common/threadreg.h"
#include "common/net.h"
#include "common/protocol_gen.h"

namespace fdfs {

namespace {

constexpr int kIoTimeoutMs = 30 * 1000;
constexpr int kConnectTimeoutMs = 3000;
constexpr int kMarkSaveEvery = 64;  // records between SaveMark() calls

// One request/response over the storage sync connection.  The peer's
// response body is always empty for SYNC_* ops; status carries the verdict.
bool SyncRpcHeaderOnly(int fd, uint8_t* status, int timeout_ms) {
  uint8_t hdr[kHeaderSize];
  if (!RecvAll(fd, hdr, sizeof(hdr), timeout_ms)) return false;
  int64_t len = GetInt64BE(hdr);
  *status = hdr[9];
  if (len < 0 || len > (1 << 20)) return false;
  if (len > 0) {
    std::string drain(static_cast<size_t>(len), '\0');
    if (!RecvAll(fd, drain.data(), drain.size(), timeout_ms)) return false;
  }
  return true;
}

bool SendHeader(int fd, uint8_t cmd, int64_t pkg_len) {
  uint8_t hdr[kHeaderSize];
  PutInt64BE(pkg_len, hdr);
  hdr[8] = cmd;
  hdr[9] = 0;
  return SendAll(fd, hdr, sizeof(hdr), kIoTimeoutMs);
}

// Streams [offset, offset+count) of local_fd to the socket.
bool SendFileBytes(int fd, int local_fd, int64_t offset, int64_t count) {
  char buf[256 * 1024];
  if (lseek(local_fd, offset, SEEK_SET) != offset) return false;
  while (count > 0) {
    size_t want = static_cast<size_t>(
        std::min<int64_t>(count, static_cast<int64_t>(sizeof(buf))));
    ssize_t n = read(local_fd, buf, want);
    if (n <= 0) return false;
    if (!SendAll(fd, buf, static_cast<size_t>(n), kIoTimeoutMs)) return false;
    count -= n;
  }
  return true;
}

}  // namespace

SyncManager::SyncManager(const StorageConfig& cfg, SyncCallbacks cbs)
    : cfg_(cfg), cbs_(std::move(cbs)),
      sync_dir_(cfg.base_path + "/data/sync") {}

SyncManager::~SyncManager() { Stop(); }

void SyncManager::UpdatePeers(const std::vector<PeerInfo>& peers) {
  std::lock_guard<RankedMutex> lk(mu_);
  if (stopped_) return;  // a post-Stop heartbeat must not respawn workers
  // Retire workers whose peer vanished from the group.  Joined in Stop(),
  // not here: the caller is a reporter thread and a join could block a
  // heartbeat behind an in-flight multi-GB replay.
  for (auto it = workers_.begin(); it != workers_.end();) {
    bool still = false;
    for (const auto& p : peers) still |= (p.Addr() == it->first);
    if (still) {
      ++it;
    } else {
      it->second->stop = true;
      retired_.push_back(std::move(it->second));
      it = workers_.erase(it);
    }
  }
  // Spawn workers for new peers.
  for (const auto& p : peers) {
    if (p.port == cfg_.port && p.ip == cfg_.bind_addr) continue;  // self
    if (workers_.count(p.Addr())) continue;
    auto w = std::make_unique<Worker>();
    w->ip = p.ip;
    w->port = p.port;
    Worker* raw = w.get();
    w->thread = std::thread(&SyncManager::WorkerMain, this, raw);
    workers_[p.Addr()] = std::move(w);
    FDFS_LOG_INFO("sync thread spawned for peer %s", p.Addr().c_str());
  }
}

void SyncManager::Stop() {
  std::map<std::string, std::unique_ptr<Worker>> workers;
  std::vector<std::unique_ptr<Worker>> retired;
  {
    std::lock_guard<RankedMutex> lk(mu_);
    stopped_ = true;
    workers.swap(workers_);
    retired.swap(retired_);
  }
  for (auto& [addr, w] : workers) w->stop = true;
  for (auto& [addr, w] : workers)
    if (w->thread.joinable()) w->thread.join();
  for (auto& w : retired)
    if (w->thread.joinable()) w->thread.join();
}

std::vector<SyncPeerState> SyncManager::States() const {
  std::lock_guard<RankedMutex> lk(mu_);
  std::vector<SyncPeerState> out;
  for (const auto& [addr, w] : workers_) {
    SyncPeerState s;
    s.addr = addr;
    s.connected = w->connected;
    s.synced_ts = w->synced_ts;
    s.records_synced = w->records_synced;
    s.records_skipped = w->records_skipped;
    out.push_back(std::move(s));
  }
  return out;
}

void SyncManager::WorkerMain(Worker* w) {
  ScopedThreadName ledger("sync." + w->ip);
  const std::string mark_path =
      sync_dir_ + "/" + w->ip + "_" + std::to_string(w->port) + ".mark";
  BinlogReader reader;
  std::string err;
  reader.Init(sync_dir_, mark_path, &err);  // fresh peer => position 0

  int fd = -1;
  std::optional<BinlogRecord> pending;
  int backoff_ms = 100;
  int since_save = 0;

  bool stall_noted = false;  // one event per outage, not per retry
  while (!w->stop) {
    BeatThreadHeartbeat();
    if (fd < 0) {
      int64_t t0 = MonoUs();
      fd = TcpConnect(w->ip, w->port, kConnectTimeoutMs, &err);
      if (fd < 0) {
        w->connected = false;
        // Connect failures never reach the NetRpc observer (no live
        // fd), so feed the gray-failure table explicitly: a peer whose
        // replication port stops answering is exactly what the health
        // matrix exists to show.
        HealthMonitor::Global().Feed(w->ip + ":" + std::to_string(w->port),
                                     "sync", false, MonoUs() - t0,
                                     kConnectTimeoutMs);
        // Flight recorder: the FIRST failed (re)connect of an outage is
        // the stall signal; the exponential-backoff retries after it are
        // noise the bounded ring should not drown in.
        if (!stall_noted && cbs_.events != nullptr) {
          stall_noted = true;
          cbs_.events->Record(
              EventSeverity::kWarn, "sync.stall",
              w->ip + ":" + std::to_string(w->port),
              pending.has_value() ? "reason=connect_failed mid_record=1"
                                  : "reason=connect_failed");
        }
        for (int i = 0; i < backoff_ms / 50 && !w->stop; ++i) {
          BeatThreadHeartbeat();  // backed off, not stalled
          usleep(50 * 1000);
        }
        backoff_ms = std::min(backoff_ms * 2, 5000);
        continue;
      }
      if (stall_noted && cbs_.events != nullptr)
        cbs_.events->Record(EventSeverity::kInfo, "sync.resumed",
                            w->ip + ":" + std::to_string(w->port));
      stall_noted = false;
      w->connected = true;
      backoff_ms = 100;
    }

    // Caught-up stamp and quiescence are captured BEFORE the EOF read.
    // Order matters on a loaded box: stamping after the read leaves a
    // preemption window (observed seconds long under 1-core suite load)
    // in which a record can be appended AND stamped, yet be covered by
    // the report — the tracker then routes reads to a replica that never
    // received the file.  Stamped first, `safe` is provably earlier than
    // the EOF read: any record with timestamp <= safe either was visible
    // to the read, or was mid-append — which the quiescence check (also
    // before the read) rules out, since in_flight covers the Append's
    // stamp→write window and later appends stamp >= safe + 1.
    int64_t safe = 0;
    bool quiet = false;
    if (!pending.has_value()) {
      safe = time(nullptr) - 1;
      quiet = !cbs_.binlog_quiescent || cbs_.binlog_quiescent();
      pending = reader.Next();
    }
    if (!pending.has_value()) {
      // Caught up: persist the cursor and idle-poll the binlog.
      if (since_save > 0) {
        reader.SaveMark();
        since_save = 0;
      }
      // Caught-up progress report: the peer has everything this source
      // produced through the PREVIOUS second.  Keeps read routing fresh
      // and completes the tracker's full-sync promotion even when the
      // binlog is empty (upstream: sync_old_done bookkeeping).
      if (cbs_.report && quiet && safe > w->synced_ts) {
        w->synced_ts = safe;
        cbs_.report(w->ip, w->port, safe);
      }
      int wait = std::max(cfg_.sync_interval_ms, 20);
      for (int i = 0; i < wait / 20 && !w->stop; ++i) {
        BeatThreadHeartbeat();  // idle-polling, not stalled
        usleep(20 * 1000);
      }
      continue;
    }

    // Replica-replay records (lowercase) are never re-forwarded — that is
    // what stops create/delete floods from circulating the group forever.
    if (islower(static_cast<unsigned char>(pending->op))) {
      pending.reset();
      continue;
    }

    // Replication ships are manually framed (SendAll/RecvAll, not
    // NetRpc), so they feed the gray-failure table explicitly: per-ship
    // outcome + wall time against the peer, op class "sync".
    int64_t ship_t0 = MonoUs();
    bool shipped = Replay(w, &fd, *pending);
    HealthMonitor::Global().Feed(w->ip + ":" + std::to_string(w->port),
                                 "sync", shipped, MonoUs() - ship_t0,
                                 kIoTimeoutMs);
    if (!shipped) {
      // Transient failure: reconnect and retry this same record.
      if (fd >= 0) {
        close(fd);
        fd = -1;
      }
      w->connected = false;
      continue;
    }
    w->synced_ts = pending->timestamp;
    w->records_synced++;
    if (cbs_.report) cbs_.report(w->ip, w->port, pending->timestamp);
    pending.reset();
    if (++since_save >= kMarkSaveEvery) {
      reader.SaveMark();
      since_save = 0;
    }
  }
  reader.SaveMark();
  if (fd >= 0) close(fd);
}

bool SyncManager::Replay(Worker* w, int* fd, const BinlogRecord& rec) {
  // Trace stitching: a recently-traced mutation ships with a TRACE_CTX
  // prefix frame so the peer's replica-replay spans join the original
  // trace, and the sender records the hop as a "sync.ship" span.
  TraceCtx ctx;
  bool traced = cbs_.trace_corr != nullptr &&
                cbs_.trace_corr->Take(rec.filename, &ctx) && ctx.valid();
  uint32_t ship_span = 0;
  int64_t t0 = 0;
  if (traced && cbs_.trace_ring != nullptr) {
    ship_span = cbs_.trace_ring->NextSpanId();
    uint8_t frame[kTraceCtxFrameLen];
    TraceCtx hop;
    hop.trace_id = ctx.trace_id;
    hop.parent_span = ship_span;  // peer spans nest under the ship span
    hop.flags = ctx.flags;
    BuildTraceCtxFrame(hop, frame);
    if (!SendAll(*fd, frame, sizeof(frame), kIoTimeoutMs)) {
      cbs_.trace_corr->Put(rec.filename, ctx);  // retry stays traced
      return false;
    }
    t0 = TraceWallUs();
  }
  bool skipped = false;
  bool ok;
  switch (rec.op) {
    case kBinlogOpCreate:
      ok = ReplayCreate(*fd, rec, &skipped);
      break;
    case kBinlogOpDelete:
      ok = ReplayDelete(*fd, rec, &skipped);
      break;
    case kBinlogOpUpdate:
      ok = ReplayUpdate(*fd, rec, &skipped);
      break;
    case kBinlogOpLink:
      ok = ReplayLink(*fd, rec, &skipped);
      break;
    case kBinlogOpAppend:
      ok = ReplayRange(*fd, static_cast<uint8_t>(StorageCmd::kSyncAppendFile),
                       rec, &skipped);
      break;
    case kBinlogOpModify:
      ok = ReplayRange(*fd, static_cast<uint8_t>(StorageCmd::kSyncModifyFile),
                       rec, &skipped);
      break;
    case kBinlogOpTruncate:
      ok = ReplayTruncate(*fd, rec, &skipped);
      break;
    default:
      FDFS_LOG_WARN("sync %s: unknown op '%c' for %s — skipping",
                    w->ip.c_str(), rec.op, rec.filename.c_str());
      skipped = true;
      ok = true;
      break;
  }
  if (ok && skipped) {
    w->records_skipped++;
    // A permanently-unreplayable record (peer rejected it) left the
    // replica without this mutation — worth a structured event, not
    // just a buried WARN line.
    if (cbs_.events != nullptr)
      cbs_.events->Record(EventSeverity::kWarn, "sync.skip", rec.filename,
                          "peer=" + w->ip + ":" + std::to_string(w->port) +
                              " op=" + std::string(1, rec.op));
  }
  if (traced && cbs_.trace_ring != nullptr) {
    if (ok) {
      TraceSpan s;
      s.trace_id = ctx.trace_id;
      s.span_id = ship_span;
      s.parent_id = ctx.parent_span;
      s.start_us = t0;
      s.dur_us = TraceWallUs() - t0;
      s.status = skipped ? 2 /*ENOENT-ish: permanently unreplayable*/ : 0;
      s.flags = ctx.flags;
      s.SetName("sync.ship");
      cbs_.trace_ring->Record(s);
    } else {
      cbs_.trace_corr->Put(rec.filename, ctx);  // reconnect + retry traced
    }
  }
  return ok;
}

// Chunk-aware create replay (SYNC_QUERY_CHUNKS + SYNC_CREATE_RECIPE):
// ship the recipe and only the chunk bytes the peer lacks.  On a
// dup-heavy corpus this moves ~unique bytes over the wire where the
// full-copy path moves every logical byte AND makes the peer re-chunk +
// re-fingerprint the lot (reference: storage_sync.c has no such mode —
// every replica costs the full file).
int SyncManager::TryReplayRecipe(int fd, const BinlogRecord& rec,
                                 bool* skipped) {
  if (!cbs_.pin_recipe || !cbs_.read_chunk) return 1;
  auto rcp = cbs_.pin_recipe(rec.filename);
  if (!rcp.has_value()) return 1;  // not stored as a recipe (or gone)
  const Recipe& r = *rcp;
  // The query body (20 B/digest) and the create's inline entry block
  // (29 B/chunk) must fit the receiver's kMaxInlineBody, or it closes
  // the connection and this record would retry forever.  The entry
  // block is the binding constraint (29 B/chunk => ~2.3M chunks at the
  // 64 MB cap); oversized recipes take the full-copy path instead.
  if (48 + 1024 + static_cast<int64_t>(r.chunks.size()) * 29 >
      kMaxInlineBody) {
    if (cbs_.unpin_recipe) cbs_.unpin_recipe(rec.filename, r);
    return 1;
  }
  struct Unpin {  // chunks stay pinned across both phases
    SyncManager* m;
    const std::string& name;
    const Recipe& r;
    ~Unpin() {
      if (m->cbs_.unpin_recipe) m->cbs_.unpin_recipe(name, r);
    }
  } unpin{this, rec.filename, r};

  // Phase 1: which chunks does the peer lack?
  std::string q;
  PutFixedField(&q, cfg_.group_name, kGroupNameMaxLen);
  uint8_t num[8];
  PutInt64BE(static_cast<int64_t>(rec.filename.size()), num);
  q.append(reinterpret_cast<char*>(num), 8);
  q += rec.filename;
  for (const RecipeEntry& e : r.chunks)
    if (!HexToBytes(e.digest_hex, &q)) return 1;
  if (!SendHeader(fd, static_cast<uint8_t>(StorageCmd::kSyncQueryChunks),
                  static_cast<int64_t>(q.size())) ||
      !SendAll(fd, q.data(), q.size(), kIoTimeoutMs))
    return -1;
  uint8_t hdr[kHeaderSize];
  if (!RecvAll(fd, hdr, sizeof(hdr), kIoTimeoutMs)) return -1;
  int64_t resp_len = GetInt64BE(hdr);
  uint8_t status = hdr[9];
  if (resp_len < 0 || resp_len > (1 << 26)) return -1;
  std::string need(static_cast<size_t>(resp_len), '\0');
  if (resp_len > 0 && !RecvAll(fd, need.data(), need.size(), kIoTimeoutMs))
    return -1;
  if (status != 0 ||
      need.size() != r.chunks.size())  // peer can't (no chunk store / old)
    return 1;

  // Phase 2: recipe + missing chunk payloads (streamed, not buffered —
  // an all-unique file would otherwise hold its full size in RAM).
  int64_t payload_len = 0;
  for (size_t i = 0; i < r.chunks.size(); ++i)
    if (need[i]) payload_len += r.chunks[i].length;
  std::string body;
  PutFixedField(&body, cfg_.group_name, kGroupNameMaxLen);
  PutInt64BE(static_cast<int64_t>(rec.filename.size()), num);
  body.append(reinterpret_cast<char*>(num), 8);
  PutInt64BE(r.logical_size, num);
  body.append(reinterpret_cast<char*>(num), 8);
  PutInt64BE(static_cast<int64_t>(r.chunks.size()), num);
  body.append(reinterpret_cast<char*>(num), 8);
  PutInt64BE(payload_len, num);
  body.append(reinterpret_cast<char*>(num), 8);
  body += rec.filename;
  for (size_t i = 0; i < r.chunks.size(); ++i) {
    if (!HexToBytes(r.chunks[i].digest_hex, &body)) return 1;
    PutInt64BE(r.chunks[i].length, num);
    body.append(reinterpret_cast<char*>(num), 8);
    body.push_back(need[i] ? 1 : 0);
  }
  if (!SendHeader(fd, static_cast<uint8_t>(StorageCmd::kSyncCreateRecipe),
                  static_cast<int64_t>(body.size()) + payload_len) ||
      !SendAll(fd, body.data(), body.size(), kIoTimeoutMs))
    return -1;
  std::string chunk;
  for (size_t i = 0; i < r.chunks.size(); ++i) {
    if (!need[i]) continue;
    if (!cbs_.read_chunk(rec.filename, r.chunks[i].digest_hex,
                         r.chunks[i].length, &chunk)) {
      // Pinned chunks only vanish on real IO errors; the header is
      // already on the wire, so abort the connection (caller retries).
      FDFS_LOG_ERROR("sync recipe: chunk %s unreadable",
                     r.chunks[i].digest_hex.c_str());
      return -1;
    }
    if (!SendAll(fd, chunk.data(), chunk.size(), kIoTimeoutMs)) return -1;
  }
  if (!SyncRpcHeaderOnly(fd, &status, kIoTimeoutMs)) return -1;
  if (status != 0) {
    FDFS_LOG_WARN("sync recipe %s: peer status %d — falling back to "
                  "full copy", rec.filename.c_str(), status);
    return 1;
  }
  (void)skipped;
  return 0;
}

// 'C': whole-file copy.  Wire: 16B group + 8B name_len + 8B size + name +
// bytes (the receiver's kSyncCreateFile layout in server.cc).
bool SyncManager::ReplayCreate(int fd, const BinlogRecord& rec,
                               bool* skipped) {
  // Recipe-stored files replicate chunk-aware when possible; 1 = the
  // file is flat/trunk/gone or the peer lacks the capability.
  int rr = TryReplayRecipe(fd, rec, skipped);
  if (rr == 0) return true;
  if (rr < 0) return false;

  ContentHandle h;
  if (cbs_.open_content) {
    auto got = cbs_.open_content(rec.filename);
    if (!got.has_value()) {
      // Deleted (or never resolvable) since the record was written: the
      // later 'D' record — or nothing — is the correct end state on the
      // peer.
      *skipped = true;
      return true;
    }
    h = *got;
  } else {
    std::string local = cbs_.resolve_local(rec.filename);
    h.fd = local.empty() ? -1 : open(local.c_str(), O_RDONLY);
    if (h.fd < 0) {
      *skipped = true;
      return true;
    }
    struct stat st;
    fstat(h.fd, &st);
    h.size = st.st_size;
  }
  std::string body;
  PutFixedField(&body, cfg_.group_name, kGroupNameMaxLen);
  uint8_t num[8];
  PutInt64BE(static_cast<int64_t>(rec.filename.size()), num);
  body.append(reinterpret_cast<char*>(num), 8);
  PutInt64BE(h.size, num);
  body.append(reinterpret_cast<char*>(num), 8);
  body += rec.filename;

  bool ok = SendHeader(fd, static_cast<uint8_t>(StorageCmd::kSyncCreateFile),
                       static_cast<int64_t>(body.size()) + h.size) &&
            SendAll(fd, body.data(), body.size(), kIoTimeoutMs) &&
            SendFileBytes(fd, h.fd, h.offset, h.size);
  close(h.fd);
  uint8_t status = 0;
  if (!ok || !SyncRpcHeaderOnly(fd, &status, kIoTimeoutMs)) return false;
  if (status != 0) {
    FDFS_LOG_WARN("sync create %s rejected by peer: status %d — skipping",
                  rec.filename.c_str(), status);
    *skipped = true;
  }
  return true;
}

// 'D': 16B group + remote filename.
bool SyncManager::ReplayDelete(int fd, const BinlogRecord& rec,
                               bool* skipped) {
  std::string body;
  PutFixedField(&body, cfg_.group_name, kGroupNameMaxLen);
  body += rec.filename;
  if (!SendHeader(fd, static_cast<uint8_t>(StorageCmd::kSyncDeleteFile),
                  static_cast<int64_t>(body.size())) ||
      !SendAll(fd, body.data(), body.size(), kIoTimeoutMs))
    return false;
  uint8_t status = 0;
  if (!SyncRpcHeaderOnly(fd, &status, kIoTimeoutMs)) return false;
  // ENOENT (2) on the peer is fine — it never had the file (e.g. created
  // and deleted before this peer's full-sync reached the create).
  if (status != 0 && status != 2) {
    FDFS_LOG_WARN("sync delete %s: peer status %d — skipping",
                  rec.filename.c_str(), status);
  }
  *skipped = (status != 0);
  return true;
}

// 'U': metadata sidecar refresh.  Wire: 16B group + 8B name_len +
// 8B meta_len + name + meta bytes (receiver kSyncUpdateFile).
bool SyncManager::ReplayUpdate(int fd, const BinlogRecord& rec,
                               bool* skipped) {
  std::string local = cbs_.resolve_local(rec.filename);
  if (local.empty()) {
    *skipped = true;
    return true;
  }
  std::string meta;
  FILE* f = fopen((local + "-m").c_str(), "r");
  if (f != nullptr) {
    char buf[4096];
    size_t n;
    while ((n = fread(buf, 1, sizeof(buf), f)) > 0) meta.append(buf, n);
    fclose(f);
  }
  std::string body;
  PutFixedField(&body, cfg_.group_name, kGroupNameMaxLen);
  uint8_t num[8];
  PutInt64BE(static_cast<int64_t>(rec.filename.size()), num);
  body.append(reinterpret_cast<char*>(num), 8);
  PutInt64BE(static_cast<int64_t>(meta.size()), num);
  body.append(reinterpret_cast<char*>(num), 8);
  body += rec.filename;
  body += meta;
  if (!SendHeader(fd, static_cast<uint8_t>(StorageCmd::kSyncUpdateFile),
                  static_cast<int64_t>(body.size())) ||
      !SendAll(fd, body.data(), body.size(), kIoTimeoutMs))
    return false;
  uint8_t status = 0;
  if (!SyncRpcHeaderOnly(fd, &status, kIoTimeoutMs)) return false;
  *skipped = (status != 0);
  return true;
}

// 'L': dedup/slave hard link.  Wire: 16B group + target \x02 src
// (receiver kSyncCreateLink).
bool SyncManager::ReplayLink(int fd, const BinlogRecord& rec, bool* skipped) {
  std::string body;
  PutFixedField(&body, cfg_.group_name, kGroupNameMaxLen);
  body += rec.filename;
  body += '\x02';
  body += rec.extra;
  if (!SendHeader(fd, static_cast<uint8_t>(StorageCmd::kSyncCreateLink),
                  static_cast<int64_t>(body.size())) ||
      !SendAll(fd, body.data(), body.size(), kIoTimeoutMs))
    return false;
  uint8_t status = 0;
  if (!SyncRpcHeaderOnly(fd, &status, kIoTimeoutMs)) return false;
  if (status != 0) {
    FDFS_LOG_WARN("sync link %s -> %s: peer status %d — skipping",
                  rec.filename.c_str(), rec.extra.c_str(), status);
    *skipped = true;
  }
  return true;
}

// 'A'/'M': byte-range replay.  The binlog extra is "offset length" (both
// sides of this protocol are ours; upstream resends whole appender files).
// Wire: 16B group + 8B name_len + 8B offset + 8B length + name + bytes.
bool SyncManager::ReplayRange(int fd, uint8_t cmd, const BinlogRecord& rec,
                              bool* skipped) {
  int64_t offset = -1, length = -1;
  if (sscanf(rec.extra.c_str(), "%lld %lld", reinterpret_cast<long long*>(&offset),
             reinterpret_cast<long long*>(&length)) != 2 ||
      offset < 0 || length < 0) {
    FDFS_LOG_WARN("sync range %s: bad extra '%s' — skipping",
                  rec.filename.c_str(), rec.extra.c_str());
    *skipped = true;
    return true;
  }
  std::string local = cbs_.resolve_local(rec.filename);
  int local_fd = local.empty() ? -1 : open(local.c_str(), O_RDONLY);
  if (local_fd < 0) {
    *skipped = true;
    return true;
  }
  struct stat st;
  fstat(local_fd, &st);
  if (offset + length > st.st_size) {
    // Truncated since; later binlog records hold the final state.
    close(local_fd);
    *skipped = true;
    return true;
  }
  std::string body;
  PutFixedField(&body, cfg_.group_name, kGroupNameMaxLen);
  uint8_t num[8];
  PutInt64BE(static_cast<int64_t>(rec.filename.size()), num);
  body.append(reinterpret_cast<char*>(num), 8);
  PutInt64BE(offset, num);
  body.append(reinterpret_cast<char*>(num), 8);
  PutInt64BE(length, num);
  body.append(reinterpret_cast<char*>(num), 8);
  body += rec.filename;

  bool ok = SendHeader(fd, cmd,
                       static_cast<int64_t>(body.size()) + length) &&
            SendAll(fd, body.data(), body.size(), kIoTimeoutMs) &&
            SendFileBytes(fd, local_fd, offset, length);
  close(local_fd);
  uint8_t status = 0;
  if (!ok || !SyncRpcHeaderOnly(fd, &status, kIoTimeoutMs)) return false;
  if (status == 16 /*EBUSY: peer-side writer lock*/) return false;  // retry
  if (status != 0) {
    FDFS_LOG_WARN("sync range %s @%lld+%lld: peer status %d — skipping",
                  rec.filename.c_str(), static_cast<long long>(offset),
                  static_cast<long long>(length), status);
    *skipped = true;
  }
  return true;
}

// 'T': extra is "new_size".  Wire: 16B group + 8B name_len + 8B new_size +
// name (receiver kSyncTruncateFile).
bool SyncManager::ReplayTruncate(int fd, const BinlogRecord& rec,
                                 bool* skipped) {
  int64_t new_size = -1;
  if (sscanf(rec.extra.c_str(), "%lld",
             reinterpret_cast<long long*>(&new_size)) != 1 ||
      new_size < 0) {
    *skipped = true;
    return true;
  }
  std::string body;
  PutFixedField(&body, cfg_.group_name, kGroupNameMaxLen);
  uint8_t num[8];
  PutInt64BE(static_cast<int64_t>(rec.filename.size()), num);
  body.append(reinterpret_cast<char*>(num), 8);
  PutInt64BE(new_size, num);
  body.append(reinterpret_cast<char*>(num), 8);
  body += rec.filename;
  if (!SendHeader(fd, static_cast<uint8_t>(StorageCmd::kSyncTruncateFile),
                  static_cast<int64_t>(body.size())) ||
      !SendAll(fd, body.data(), body.size(), kIoTimeoutMs))
    return false;
  uint8_t status = 0;
  if (!SyncRpcHeaderOnly(fd, &status, kIoTimeoutMs)) return false;
  *skipped = (status != 0);
  return true;
}

}  // namespace fdfs
