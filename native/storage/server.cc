#include "storage/server.h"

#include <errno.h>
#include <fcntl.h>
#include <string.h>
#include <sys/sendfile.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/statvfs.h>
#include <sys/time.h>
#include <sys/uio.h>
#include <time.h>
#include <unistd.h>

#include <cstdio>

#include "common/fileid.h"
#include "common/healthmon.h"
#include "common/log.h"
#include "common/profiler.h"
#include "common/protocol_gen.h"
#include "common/threadreg.h"

namespace fdfs {

namespace {

// kMaxInlineBody (the non-streamed body cap) comes from protocol_gen.h:
// it is a wire contract shared with senders (sync.cc sizes the
// chunk-aware replication messages against it).
constexpr int64_t kBinlogRotateSize = 64LL << 20;
constexpr size_t kIoBufSize = 256 * 1024;
// Per-chunk payload cap, shared by FETCH_CHUNK serving and the
// SYNC_CREATE_RECIPE entry validation: no single declared chunk may make
// a dio worker allocate more than this.
constexpr int64_t kMaxChunkPayload = 8 << 20;

std::string GroupFromField(const uint8_t* p) {
  size_t n = 0;
  while (n < static_cast<size_t>(kGroupNameMaxLen) && p[n] != 0) ++n;
  return std::string(reinterpret_cast<const char*>(p), n);
}

std::string ExtFromField(const uint8_t* p) {
  size_t n = 0;
  while (n < static_cast<size_t>(kFileExtNameMaxLen) && p[n] != 0) ++n;
  return std::string(reinterpret_cast<const char*>(p), n);
}

std::string PackGroupField(const std::string& group) {
  std::string out(kGroupNameMaxLen, '\0');
  memcpy(out.data(), group.data(),
         std::min(group.size(), static_cast<size_t>(kGroupNameMaxLen)));
  return out;
}

// Atomic metadata-sidecar write (tmp + rename).  A partial write must not
// report success: the sync sender advances its mark on status 0 and never
// retries.
bool WriteSidecarAtomic(const std::string& meta_path, const std::string& meta) {
  std::string tmp = meta_path + ".tmp";
  FILE* f = fopen(tmp.c_str(), "w");
  if (f == nullptr) return false;
  bool ok = fwrite(meta.data(), 1, meta.size(), f) == meta.size();
  ok = (fclose(f) == 0) && ok;
  if (!ok || rename(tmp.c_str(), meta_path.c_str()) != 0) {
    unlink(tmp.c_str());
    return false;
  }
  return true;
}

}  // namespace

StorageServer::StorageServer(StorageConfig cfg) : cfg_(std::move(cfg)) {}

StorageServer::~StorageServer() {
  for (auto& t : nio_) {
    for (auto& [fd, c] : t->conns) {
      if (c->file_fd >= 0) close(c->file_fd);
      if (c->send_fd >= 0) close(c->send_fd);
      close(fd);
    }
    if (t->listen_fd >= 0) close(t->listen_fd);
  }
  if (listen_fd_ >= 0) close(listen_fd_);
}

bool StorageServer::Init(std::string* error) {
  if (!MakeDirs(cfg_.base_path + "/data") || !MakeDirs(cfg_.base_path + "/logs")) {
    *error = "cannot create base_path dirs under " + cfg_.base_path;
    return false;
  }
  if (!store_.Init(cfg_, error)) return false;
  if (!binlog_.Init(cfg_.base_path + "/data/sync", kBinlogRotateSize, error))
    return false;
  // Flight recorder FIRST: every subsystem below may record into it
  // (chunk-store heals, scrub quarantines, sync stalls, config clamps).
  events_ = std::make_unique<EventLog>(
      static_cast<size_t>(cfg_.event_buffer_size));
  for (const std::string& a : cfg_.anomalies)
    events_->Record(EventSeverity::kWarn, "config.anomaly", a);
  // Telemetry history + SLOs + heat (ISSUE 8).  The journal opens (and
  // recovers its torn tail) before the first tick; a failed open logs
  // and disables journaling rather than killing the daemon —
  // observability must never take the data path down with it.
  if (cfg_.metrics_journal_mb > 0 && cfg_.slo_eval_interval_s > 0) {
    metrics_ = std::make_unique<MetricsJournal>(
        cfg_.base_path + "/data/metrics",
        static_cast<int64_t>(cfg_.metrics_journal_mb) << 20);
    std::string merr;
    if (!metrics_->Open(&merr)) {
      FDFS_LOG_WARN("metrics journal disabled: %s", merr.c_str());
      events_->Record(EventSeverity::kWarn, "config.anomaly",
                      "metrics journal disabled", merr);
      metrics_.reset();
    }
  }
  if (cfg_.slo_eval_interval_s > 0) {
    std::vector<SloRule> rules;
    if (!cfg_.slo_rules_file.empty()) {
      IniConfig slo_ini;
      std::string serr;
      if (slo_ini.LoadFile(cfg_.slo_rules_file, &serr)) {
        rules = SloEvaluator::LoadRules(slo_ini);
      } else {
        // A missing/bad override file falls back to defaults LOUDLY: an
        // operator who wrote rules must not silently run without them.
        FDFS_LOG_WARN("slo_rules_file %s: %s (using compiled-in defaults)",
                      cfg_.slo_rules_file.c_str(), serr.c_str());
        events_->Record(EventSeverity::kWarn, "config.anomaly",
                        "slo_rules_file unreadable", serr);
        rules = SloEvaluator::DefaultRules();
      }
    } else {
      rules = SloEvaluator::DefaultRules();
    }
    slo_ = std::make_unique<SloEvaluator>(std::move(rules), events_.get());
  }
  if (cfg_.heat_top_k > 0)
    heat_ = std::make_unique<HeatSketch>(cfg_.heat_top_k);
  // Admission control (ISSUE 19): always constructed — with
  // admission_control = 0 the controller still classifies and counts
  // every request (ADMISSION_STATUS and the admission.* gauges stay
  // live for triage) but never sheds.
  {
    AdmissionConfig acfg;
    acfg.enabled = cfg_.admission_control;
    acfg.tighten_threshold = cfg_.admission_tighten_pct / 100.0;
    acfg.relax_threshold = cfg_.admission_relax_pct / 100.0;
    acfg.queue_depth_high = cfg_.admission_queue_depth_high;
    acfg.loop_lag_high_ms =
        static_cast<double>(cfg_.admission_loop_lag_high_ms);
    acfg.inflight_high_bytes = cfg_.admission_inflight_high_bytes;
    acfg.retry_after_ms = cfg_.admission_retry_after_ms;
    admission_ = std::make_unique<AdmissionController>(acfg);
  }
  dedup_ = MakeDedupPlugin(cfg_.dedup_mode, cfg_.base_path, cfg_.dedup_sidecar);
  if (dedup_ != nullptr && cfg_.dedup_chunk_threshold > 0) {
    // Chunk-level dedup: one content-addressed store per store path;
    // refcounts rebuilt from recipes (doubles as orphan GC).
    SlabOptions sopts;
    sopts.chunk_threshold = cfg_.slab_chunk_threshold;
    sopts.recipe_threshold = cfg_.slab_recipe_threshold;
    sopts.slab_bytes = static_cast<int64_t>(cfg_.slab_size_mb) << 20;
    sopts.compact_min_dead_pct = cfg_.slab_compact_min_dead_pct;
    for (int i = 0; i < store_.store_path_count(); ++i) {
      chunk_stores_.push_back(std::make_unique<ChunkStore>(
          store_.store_path(i), cfg_.chunk_gc_grace_s,
          static_cast<int64_t>(cfg_.read_cache_mb) << 20, sopts,
          cfg_.ec_k, cfg_.ec_m));
      chunk_stores_.back()->set_events(events_.get());
      chunk_stores_.back()->RebuildFromRecipes();
      // Released chunks (EC cold tier): the replica lives with the
      // stripe's owner now — reads round-robin the group peers via
      // FETCH_CHUNK (the owner's ReadChunk falls through to its EC
      // stripes, so the bytes come back decoded + SHA1-gated).
      chunk_stores_.back()->set_remote_fetch(
          [this, i](const std::string& digest_hex, int64_t len,
                    std::string* out) {
            return FetchChunkFromPeers(i, digest_hex, len, out);
          });
    }
  }

  // nio work threads + per-store-path dio pools (reference:
  // storage_nio.c / storage_dio.c; storage.conf:work_threads,
  // disk_writer_threads).  Loops are created here, threads start in
  // Run().
  for (int i = 0; i < cfg_.work_threads; ++i) {
    auto t = std::make_unique<NioThread>();
    t->loop = std::make_unique<EventLoop>();
    nio_.push_back(std::move(t));
  }

  // Sharded accept (ISSUE 18): one SO_REUSEPORT listener per reactor,
  // each added to its loop BEFORE the thread starts (EventLoop::Add is
  // safe pre-Run).  All listeners of the port must carry the flag, so
  // a refusal on ANY of them unwinds the whole group and falls back to
  // the single main-loop acceptor + round-robin handoff.
  if (cfg_.nio_reuseport && !nio_.empty()) {
    std::string rp_err;
    for (auto& t : nio_) {
      t->listen_fd = TcpListenReuseport(cfg_.bind_addr, cfg_.port, &rp_err);
      if (t->listen_fd < 0) break;
      SetNonBlocking(t->listen_fd);
    }
    if (nio_.back()->listen_fd >= 0) {
      reuseport_active_ = true;
      for (auto& t : nio_) {
        NioThread* raw = t.get();
        t->loop->Add(raw->listen_fd, EPOLLIN,
                     [this, raw](uint32_t) { OnReactorAccept(raw); });
      }
    } else {
      for (auto& t : nio_) {
        if (t->listen_fd >= 0) close(t->listen_fd);
        t->listen_fd = -1;
      }
      FDFS_LOG_WARN("nio_reuseport: kernel refused (%s); "
                    "falling back to single-acceptor round-robin",
                    rp_err.c_str());
    }
  }
  if (!reuseport_active_) {
    listen_fd_ = TcpListen(cfg_.bind_addr, cfg_.port, error);
    if (listen_fd_ < 0) return false;
    SetNonBlocking(listen_fd_);
    loop_.Add(listen_fd_, EPOLLIN, [this](uint32_t ev) { OnAccept(ev); });
  }
  for (int i = 0; i < store_.store_path_count(); ++i)
    dio_pools_.push_back(std::make_unique<WorkerPool>(
        cfg_.disk_writer_threads, "dio.worker",
        i * cfg_.disk_writer_threads));

  // Trace ring before the registry (its gauges read the ring) and before
  // the sync/recovery subsystems (they record spans into it).
  trace_ = std::make_unique<TraceRing>(
      static_cast<size_t>(cfg_.trace_buffer_size));

  // Stats registry before any subsystem that feeds it: handlers and the
  // beat callback only touch pre-registered atomic pointers.
  InitStatsRegistry();

  // Gray-failure health layer (common/healthmon.h): install the passive
  // NetRpc observer before any subsystem that makes outbound RPCs
  // starts — reporter beats, sync ships, scrub/recovery FETCH_*, EC
  // fan-out all funnel through NetRpc, so from here on every one of
  // them feeds the per-peer health table for free.  The peer.rpc_us
  // histogram was registered by InitStatsRegistry just above.
  HealthMonitor::InstallRpcObserver();
  HealthMonitor::Global().SetRpcHistogram(hist_peer_rpc_);

  // Profiler ceiling (0 keeps the feature entirely off: no handler, no
  // slab); the singleton is process-global like SIGPROF itself.
  Profiler::Global().set_max_hz(cfg_.profile_max_hz);

  // Saturation telemetry (ISSUE 6): every nio event loop observes its
  // per-iteration callback time into one shared loop-lag histogram (the
  // stall a slow handler inflicts on every other conn of its loop), and
  // the per-store-path dio pools observe queue wait + service time.
  // Each loop also accumulates its own busy time so the metrics tick
  // can publish a per-loop duty cycle (nio.loop_busy_pct.<i>) — the
  // signal the shared lag histogram cannot attribute to one loop.
  auto make_hook = [this](std::atomic<int64_t>* busy) {
    return [this, busy](int64_t busy_us, int n_events) {
      hist_nio_lag_->Observe(busy_us);
      busy->fetch_add(busy_us, std::memory_order_relaxed);
      if (n_events > 0)
        ctr_nio_dispatched_->fetch_add(n_events, std::memory_order_relaxed);
    };
  };
  loop_.set_iteration_hook(make_hook(&main_loop_busy_us_));  // accept+timers
  for (auto& t : nio_) t->loop->set_iteration_hook(make_hook(&t->busy_us));
  loop_busy_last_.assign(nio_.size() + 1, 0);
  for (auto& pool : dio_pools_)
    pool->SetStats(hist_dio_wait_, hist_dio_service_);

  if (!cfg_.tracker_servers.empty()) {
    // Sync manager first: the reporter's peer lists drive its thread pool.
    SyncCallbacks scbs;
    scbs.resolve_local = [this](const std::string& remote) {
      return ResolveLocal(cfg_.group_name, remote);
    };
    scbs.report = [this](const std::string& ip, int port, int64_t ts) {
      if (reporter_ != nullptr) reporter_->ReportSyncProgress(ip, port, ts);
    };
    scbs.binlog_quiescent = [this]() { return binlog_.Quiescent(); };
    // Shared by the sync replayer and the hot-replication fan-out
    // worker: both ship logical bytes (trunk slots and chunk recipes
    // materialize; the receiver re-chunks under its own config).
    std::function<std::optional<ContentHandle>(const std::string&)>
        open_content_fn =
            [this](const std::string& remote) -> std::optional<ContentHandle> {
      auto parts = DecodeFileId(cfg_.group_name + "/" + remote);
      if (parts.has_value() && parts->trunk_loc.has_value()) {
        const TrunkLocation& loc = *parts->trunk_loc;
        std::string path = TrunkFilePath(store_.store_path(0), loc.trunk_id);
        int fd = open(path.c_str(), O_RDONLY);
        if (fd < 0) return std::nullopt;
        auto h = ReadSlotHeader(fd, loc.offset);
        if (!h.has_value() || h->type != kTrunkSlotData ||
            h->alloc_size != loc.alloc_size ||
            h->file_size != parts->file_size ||
            h->crc32 != parts->crc32) {
          close(fd);
          return std::nullopt;
        }
        ContentHandle out;
        out.fd = fd;
        out.offset = loc.offset + kTrunkHeaderSize;
        out.size = h->file_size;
        return out;
      }
      std::string local = ResolveLocal(cfg_.group_name, remote);
      if (local.empty()) return std::nullopt;
      // Logical open: plain file, or chunk recipe materialized into an
      // unlinked temp fd — replication always ships logical bytes (the
      // peer re-chunks under its own dedup config).
      int64_t size = 0;
      int fd = OpenLogical(local, &size);
      if (fd < 0) return std::nullopt;
      ContentHandle out;
      out.fd = fd;
      out.size = size;
      return out;
    };
    scbs.open_content = open_content_fn;
    // Chunk-aware replication hooks: recipe-stored files ship their
    // recipe + only-missing chunks to peers instead of logical bytes.
    scbs.pin_recipe =
        [this](const std::string& remote) -> std::optional<Recipe> {
      std::string local = ResolveLocal(cfg_.group_name, remote);
      if (local.empty()) return std::nullopt;
      ChunkStore* cs = StoreForLocal(local);
      if (cs == nullptr) return std::nullopt;
      return cs->ReadRecipeAndPin(local + ".rcp");
    };
    scbs.unpin_recipe = [this](const std::string& remote, const Recipe& r) {
      std::string local = ResolveLocal(cfg_.group_name, remote);
      ChunkStore* cs = local.empty() ? nullptr : StoreForLocal(local);
      if (cs != nullptr) cs->UnpinRecipe(r);
    };
    scbs.read_chunk = [this](const std::string& remote,
                             const std::string& digest_hex, int64_t len,
                             std::string* out) {
      std::string local = ResolveLocal(cfg_.group_name, remote);
      ChunkStore* cs = local.empty() ? nullptr : StoreForLocal(local);
      return cs != nullptr && cs->ReadChunk(digest_hex, len, out);
    };
    // Trace stitching across the replication hop: the sender consumes
    // the traced-mutation context for each record it ships (prefixing a
    // TRACE_CTX frame so the peer's replay spans join the trace) and
    // records its own sync.ship span here.
    scbs.trace_corr = &trace_corr_;
    scbs.trace_ring = trace_.get();
    scbs.events = events_.get();
    sync_ = std::make_unique<SyncManager>(cfg_, std::move(scbs));
    reporter_ = std::make_unique<TrackerReporter>(
        cfg_, [this](int64_t* out) { FillBeatStats(out); },
        [this](const std::vector<PeerInfo>& peers) {
          sync_->UpdatePeers(peers);
        });
    // Health trailer: every beat carries this node's gray score + its
    // view of its peers, in the append-only region past the pinned stat
    // slots — the tracker folds all reporters' trailers into the N x N
    // HEALTH_MATRIX.
    reporter_->set_health_trailer_fn(
        [] { return HealthMonitor::Global().PackBeatTrailer(); });
    // Heat trailer (ISSUE 20): the sketch's cumulative download
    // counters ride every beat after the health trailer; the tracker
    // windows them per node (reset-clamped), so the wire stays
    // stateless and beat loss only costs freshness.
    reporter_->set_heat_trailer_fn([this]() -> std::string {
      if (heat_ == nullptr) return std::string();
      std::vector<HeatTrailerEntry> entries;
      for (const auto& t : heat_->Top(cfg_.heat_top_k)) {
        int op = static_cast<int>(HeatOp::kDownload);
        if (t.op_count[op] <= 0) continue;
        HeatTrailerEntry he;
        he.key = t.key;
        he.hits = t.op_count[op];
        he.bytes = t.op_bytes[op];
        entries.push_back(std::move(he));
      }
      return PackHeatTrailer(entries);
    });
    // Hot-replication fan-out worker: beat responses electing this node
    // for replicate/drop work feed its queue; it pushes copies over the
    // sync-create path, byte-verifies them, and acks the tracker
    // (which is what publishes the widened replica set).
    HotReplCallbacks hcbs;
    hcbs.open_content = open_content_fn;
    hcbs.events = events_.get();
    hotrepl_ = std::make_unique<HotReplManager>(cfg_, std::move(hcbs));
    reporter_->set_hot_tasks_fn([this](const std::string& tracker_addr,
                                       const std::vector<HotTask>& tasks) {
      if (hotrepl_ != nullptr) hotrepl_->Enqueue(tracker_addr, tasks);
    });
    // Disk recovery (storage_disk_recovery.c): a wiped store path on a
    // server with prior sync state rebuilds itself from a group peer in
    // the background.  Decided BEFORE the first JOIN so the recovering
    // flag rides it — the node must never pass through ACTIVE (and take
    // reads for files it no longer has) on its way into recovery.
    recovery_ = std::make_unique<RecoveryManager>(cfg_, reporter_.get(),
                                                  &store_);
    // Each recovered file becomes its own trace (recovery.file root +
    // per-fetch child spans), with the context propagated onto the peer
    // so its FETCH_RECIPE/FETCH_CHUNK spans stitch cross-node.
    recovery_->SetTrace(trace_.get());
    // Recovered files dedup exactly like synced/uploaded ones: a rebuilt
    // node must not silently lose chunk-level dedup (its chunk store
    // would stay empty while peers dedup).  The hook runs on the
    // recovery thread, so it gets its OWN plugin instance (ChunkStore is
    // internally locked; the plugins are not).
    if (dedup_ != nullptr && !chunk_stores_.empty()) {
      // Only the sidecar plugin needs a per-thread instance (it owns a
      // socket fd); CpuDedup's FingerprintChunks is stateless, and a
      // second CpuDedup would pointlessly re-load the digest snapshot.
      if (cfg_.dedup_mode == "sidecar")
        recovery_dedup_ = MakeDedupPlugin(cfg_.dedup_mode, cfg_.base_path,
                                          cfg_.dedup_sidecar);
      DedupPlugin* rec_plugin =
          recovery_dedup_ != nullptr ? recovery_dedup_.get() : dedup_.get();
      recovery_->SetChunkedStore(
          [this, rec_plugin](const std::string& tmp, int spi, int64_t size,
                             const std::string& remote) {
            auto local = LocalPath(store_.store_path(spi), remote);
            if (!local.has_value()) return false;
            int64_t saved = 0, hits = 0;
            return ChunkedStoreWith(rec_plugin, tmp, spi, size,
                                    *local + ".rcp",
                                    cfg_.group_name + "/" + remote, &saved,
                                    &hits);
          },
          cfg_.dedup_chunk_threshold);
      // Chunk-aware rebuild: pull the peer's recipe and only the chunk
      // bytes this node's store lacks (batched, ~8 MB per round-trip —
      // a per-chunk RPC would make low-dup rebuilds RTT-bound);
      // all-or-nothing with ref rollback, falling back to the full
      // download on any failure.
      recovery_->SetRecipeRecover(
          [this, rec_plugin](
              int spi, const std::string& remote, const Recipe& r,
              const RecoveryManager::FetchChunksFn& fetch_chunks,
              int64_t* chunks_fetched, int64_t* chunks_local) {
            if (spi >= static_cast<int>(chunk_stores_.size())) return false;
            ChunkStore* cs = chunk_stores_[spi].get();
            auto local = LocalPath(store_.store_path(spi), remote);
            if (!local.has_value()) return false;
            // Resumed recovery: both write paths are atomic (rename /
            // append-then-publish), so an existing file/recipe is
            // complete — re-storing would only inflate chunk refs.
            struct stat st;
            if (stat(local->c_str(), &st) == 0 ||
                cs->HasRecipe(*local + ".rcp"))
              return true;
            Recipe done;  // every ref taken so far (rollback set)
            done.logical_size = r.logical_size;
            auto fail = [&]() {
              cs->UnrefAll(done);
              return false;
            };
            // Pass 1: reference what this node already holds.
            std::vector<RecipeEntry> missing;
            for (const RecipeEntry& e : r.chunks) {
              if (cs->RefOne(e.digest_hex))
                done.chunks.push_back(e);
              else
                missing.push_back(e);
            }
            // Honest wire accounting (ADVICE recovery.cc:591): only the
            // misses cross the network; locally-ref'd chunks are the
            // savings the chunk-aware path exists for.
            *chunks_local = static_cast<int64_t>(done.chunks.size());
            *chunks_fetched = static_cast<int64_t>(missing.size());
            // Pass 2: fetch the misses in bounded batches.
            std::string payloads;
            size_t i = 0;
            while (i < missing.size()) {
              std::vector<RecipeEntry> want;
              int64_t batch_bytes = 0;
              while (i < missing.size() && batch_bytes < (8 << 20)) {
                want.push_back(missing[i]);
                batch_bytes += missing[i].length;
                ++i;
              }
              if (!fetch_chunks(want, &payloads)) return fail();
              size_t off = 0;
              for (const RecipeEntry& e : want) {
                // Content-addressed store: verify the payload IS its
                // digest before admitting it, or a bit-rotted peer
                // chunk would poison every future dedup hit.
                if (Sha1(payloads.data() + off,
                         static_cast<size_t>(e.length))
                        .Hex() != e.digest_hex) {
                  FDFS_LOG_WARN("recovery: chunk %s failed digest check",
                                e.digest_hex.c_str());
                  return fail();
                }
                bool existed = false;
                std::string err;
                if (!cs->PutAndRef(e.digest_hex, payloads.data() + off,
                                   static_cast<size_t>(e.length), &existed,
                                   &err))
                  return fail();
                done.chunks.push_back(e);
                off += static_cast<size_t>(e.length);
              }
            }
            std::string err;
            if (!cs->StoreRecipe(*local + ".rcp", r, &err)) return fail();
            // Sidecar mode: re-register the file with the dedup engine
            // (near-dup signature + attributions) exactly as an upload
            // would — zero extra wire, the bytes are local now.  The
            // cpu plugin keeps its index in the chunk store itself, so
            // re-fingerprinting there would be pure waste.
            if (rec_plugin != nullptr &&
                std::string(rec_plugin->Name()) == "sidecar")
              ReindexRecovered(rec_plugin, *local,
                               cfg_.group_name + "/" + remote);
            return true;
          });
    }
    bool needs_recovery = recovery_->NeedsRecovery(store_.any_path_was_fresh());
    reporter_->set_recovering(needs_recovery);
    hotrepl_->Start();
    reporter_->Start();
    if (needs_recovery) recovery_->Start();
  }

  // Integrity engine: one background scrubber over every chunk store
  // (verify -> quarantine -> replica repair -> zero-ref GC).  Created
  // whenever chunk stores exist — with scrub_interval_s = 0 it only
  // runs when SCRUB_KICK forces a pass, so operators and tests can
  // drive deterministic passes on an otherwise-idle daemon.
  if (!chunk_stores_.empty()) {
    if (cfg_.dedup_mode == "sidecar")
      scrub_dedup_ = MakeDedupPlugin(cfg_.dedup_mode, cfg_.base_path,
                                     cfg_.dedup_sidecar);
    ScrubOptions sopts;
    sopts.interval_s = cfg_.scrub_interval_s;
    sopts.bandwidth_bytes_s =
        static_cast<int64_t>(cfg_.scrub_bandwidth_mb_s) << 20;
    sopts.ec_k = cfg_.ec_k;
    sopts.ec_m = cfg_.ec_m;
    sopts.ec_demote_age_s = cfg_.ec_demote_age_s;
    sopts.ec_bandwidth_bytes_s =
        static_cast<int64_t>(cfg_.ec_bandwidth_mb_s) << 20;
    // Demote ownership (jump hash) hashes over peers + self; this MUST
    // be the same "ip:port" the peers' sync lists carry for this node.
    sopts.self_id = MyIp() + ":" + std::to_string(cfg_.port);
    std::vector<ChunkStore*> stores;
    for (auto& cs : chunk_stores_) stores.push_back(cs.get());
    scrub_ = std::make_unique<ScrubManager>(
        sopts, cfg_.group_name, std::move(stores),
        [this]() {
          // Replica addresses for FETCH_CHUNK repair: the sync peer
          // list (every group member holds every chunk by design).
          std::vector<std::string> out;
          if (sync_ != nullptr)
            for (const SyncPeerState& s : sync_->States())
              out.push_back(s.addr);
          return out;
        },
        scrub_dedup_.get(), trace_.get(), events_.get());
    scrub_->Start();
  }

  // Rebalance migrator (ISSUE 11): idle until the tracker marks this
  // group DRAINING in the beat trailer, then migrates the files this
  // member was binlog source for into their jump-hash target groups.
  // Needs the reporter (drain signal + trackers) — standalone daemons
  // have nowhere to drain to.
  if (reporter_ != nullptr) {
    RebalanceOptions ropts;
    ropts.group_name = cfg_.group_name;
    ropts.base_path = cfg_.base_path;
    ropts.sync_dir = cfg_.base_path + "/data/sync";
    ropts.port = cfg_.port;
    ropts.trackers = cfg_.tracker_servers;
    rebalance_ = std::make_unique<RebalanceManager>(ropts, reporter_.get(),
                                                    events_.get());
    rebalance_->Start();
  }

  // Periodic maintenance (reference: sched_thread entries — binlog flush,
  // stat write, dedup snapshot).
  // Per-request access log (storage.conf:use_access_log).
  if (cfg_.use_access_log) {
    std::string path = cfg_.base_path + "/logs/access.log";
    access_log_ = fopen(path.c_str(), "a");
    if (access_log_ == nullptr)
      FDFS_LOG_WARN("cannot open access log %s", path.c_str());
  }
  // Restart-safe op counters (storage_write_to_stat_file analogue).
  stat_path_ = cfg_.base_path + "/data/storage_stat.dat";
  stats_.LoadFromFile(stat_path_);

  loop_.AddTimer(1000, [this]() { binlog_.Flush(); });
  loop_.AddTimer(1000, [this]() { RefreshClusterParams(); });
  loop_.AddTimer(10 * 1000, [this]() {
    stats_.SaveToFile(stat_path_);
    if (access_log_ != nullptr) fflush(access_log_);
  });
  loop_.AddTimer(60 * 1000, [this]() {
    if (dedup_ != nullptr) dedup_->Save();
  });
  // Negotiated-upload session sweep: a client that sent UPLOAD_RECIPE
  // and vanished must not pin chunks forever (a pinned chunk defers its
  // unlink on delete).  2s granularity against an upload_session_timeout
  // measured in tens of seconds is plenty.
  loop_.AddTimer(2000, [this]() { SweepIngestSessions(); });
  // Metrics tick: journal one registry snapshot and evaluate the SLO
  // rule table against the previous tick (both conf-gated above).
  if (cfg_.slo_eval_interval_s > 0 && (metrics_ != nullptr || slo_ != nullptr))
    loop_.AddTimer(cfg_.slo_eval_interval_s * 1000,
                   [this]() { MetricsTick(); });
  // Trunk maintenance (reference: trunk_create_file_advance + the
  // free-block checker driving compaction): keep one trunk file's worth
  // of pre-created free space ahead of demand and reclaim fully-free
  // files beyond the reserve.  Trunk-server role only.
  loop_.AddTimer(30 * 1000, [this]() {
    std::shared_ptr<TrunkAllocator> alloc;
    int64_t tfs;
    {
      std::lock_guard<RankedMutex> lk(trunk_mu_);
      if (!is_trunk_server_) return;
      alloc = trunk_alloc_;
      tfs = trunk_file_size_;
    }
    if (alloc == nullptr) return;
    alloc->EnsureFreeReserve(tfs);
    alloc->ReclaimEmptyFiles(/*keep=*/1);
  });

  // Active health probes: a dedicated thread so a stalled disk or
  // unreachable peer can never block the request path or the timers.
  probe_slow_noted_.assign(static_cast<size_t>(store_.store_path_count()),
                           false);
  if (cfg_.health_probe_interval_s > 0)
    health_probe_thread_ = std::thread([this] { HealthProbeMain(); });
  // DEBUG stall injection (watchdog_inject_stall_ms): a registered
  // thread that beats once, then sleeps past the watchdog threshold
  // without beating, then beats again — a deterministic stall+recovery
  // cycle for the watchdog tests.  Never enable in production.
  if (cfg_.watchdog_inject_stall_ms > 0) {
    inject_stall_thread_ = std::thread([this] {
      ScopedThreadName ledger("debug.stall");
      int64_t inject_us = static_cast<int64_t>(cfg_.watchdog_inject_stall_ms) *
                          1000;
      while (!health_stop_.load(std::memory_order_relaxed)) {
        BeatThreadHeartbeat();
        // The "stall": sit without beating for inject_ms, in small
        // sleeps so Stop() stays bounded.
        for (int64_t slept = 0;
             slept < inject_us && !health_stop_.load(std::memory_order_relaxed);
             slept += 50000)
          usleep(50000);
      }
    });
  }

  FDFS_LOG_INFO("storage daemon up: group=%s port=%d store_paths=%d dedup=%s",
                cfg_.group_name.c_str(), cfg_.port, store_.store_path_count(),
                dedup_ != nullptr ? dedup_->Name() : "none");
  return true;
}

void StorageServer::Run() {
  // nio work threads (reference: storage_nio.c one-epoll-per-thread).
  // Started here — after Init and any daemonize fork — and joined in
  // Stop(); the main loop keeps accept + timers.  Every loop thread
  // joins the CPU ledger under its stable name (threadreg.h).
  for (size_t i = 0; i < nio_.size(); ++i) {
    EventLoop* lp = nio_[i]->loop.get();
    nio_[i]->thread = std::thread([lp, i] {
      ScopedThreadName ledger("nio.loop/" + std::to_string(i));
      lp->Run();
    });
  }
  ScopedThreadName ledger("main.loop");
  loop_.Run();
}

void StorageServer::Stop() {
  // Persist first: joining reporter threads can take up to one bounded
  // tracker-RPC timeout, and durability must not ride on that.
  if (dedup_ != nullptr) dedup_->Save();
  if (!stat_path_.empty()) stats_.SaveToFile(stat_path_);
  if (access_log_ != nullptr) {
    fclose(access_log_);
    access_log_ = nullptr;
  }
  binlog_.Flush();
  // Health threads check their stop flag inside short sleep slices
  // (and the prober between probes), so these joins are bounded even
  // mid-probe against a slow disk.
  health_stop_.store(true, std::memory_order_relaxed);
  if (health_probe_thread_.joinable()) health_probe_thread_.join();
  if (inject_stall_thread_.joinable()) inject_stall_thread_.join();
  // The scrubber may be mid-pass against the chunk stores; it checks
  // its stop flag between batches, so this join is bounded.
  if (scrub_ != nullptr) scrub_->Stop();
  // The migrator checks its stop flag between files (and inside its
  // pacing sleeps), so this join is bounded too.
  if (rebalance_ != nullptr) rebalance_->Stop();
  if (recovery_ != nullptr) recovery_->Stop();
  if (sync_ != nullptr) sync_->Stop();  // persists .mark cursors
  // The fan-out worker checks its stop flag between jobs and inside
  // its socket timeouts, so this join is bounded.
  if (hotrepl_ != nullptr) hotrepl_->Stop();
  if (reporter_ != nullptr) reporter_->Stop();
  // Order matters: dio pools drain first (their completions post to the
  // nio loops, which must still be running), then the nio loops stop and
  // drain their queues, then the main loop exits.
  for (auto& pool : dio_pools_) pool->Stop();
  for (auto& t : nio_) {
    t->loop->Stop();
    if (t->thread.joinable()) t->thread.join();
  }
  loop_.Stop();
}

bool StorageServer::DrainingRefusal() const {
  return reporter_ != nullptr && reporter_->group_state() != 0;
}

std::string StorageServer::MyIp() const {
  if (reporter_ != nullptr) return reporter_->my_ip();
  if (!cfg_.bind_addr.empty() && cfg_.bind_addr != "0.0.0.0")
    return cfg_.bind_addr;
  // Acquire pairs with AdmitConn's release-publish: state 2 means
  // my_ip_ is immutable from here on (any accept thread may have been
  // the writer under sharded accept).
  if (my_ip_state_.load(std::memory_order_acquire) != 2) return "127.0.0.1";
  return my_ip_.empty() ? "127.0.0.1" : my_ip_;
}

void StorageServer::DumpState() {
  FDFS_LOG_INFO(
      "state dump: conns=%lld refused=%lld upload=%lld/%lld "
      "download=%lld/%lld delete=%lld/%lld dedup_hits=%lld saved=%lldB "
      "binlog=%d",
      static_cast<long long>(conn_count_.load()),
      static_cast<long long>(refused_conn_count_.load()),
      static_cast<long long>(stats_.success_upload),
      static_cast<long long>(stats_.total_upload),
      static_cast<long long>(stats_.success_download),
      static_cast<long long>(stats_.total_download),
      static_cast<long long>(stats_.success_delete),
      static_cast<long long>(stats_.total_delete),
      static_cast<long long>(stats_.dedup_hits),
      static_cast<long long>(stats_.dedup_bytes_saved), binlog_.file_index());
  // Flight-recorder dump for postmortems: SIGUSR1 lands the retained
  // event ring in the daemon log as one JSON line (the same contract
  // the EVENT_DUMP opcode serves; OPERATIONS.md "Saturation & flight
  // recorder").
  if (events_ != nullptr)
    FDFS_LOG_INFO("event dump: %s",
                  events_->Json("storage", cfg_.port).c_str());
  // Thread ledger with heartbeat ages: which registered thread last
  // proved liveness and how long ago — "never" marks request-scoped
  // threads that don't beat (tools, short-lived workers).  The SIGUSR1
  // face of the watchdog (OPERATIONS.md "Health, probes & gray
  // failure").
  std::string ledger;
  for (const ThreadRegistry::HeartbeatEntry& hb :
       ThreadRegistry::Global().Heartbeats()) {
    if (!ledger.empty()) ledger += " ";
    ledger += hb.name + "(" + std::to_string(hb.tid) + ")=";
    ledger += hb.age_us < 0 ? std::string("never")
                            : std::to_string(hb.age_us / 1000) + "ms";
  }
  FDFS_LOG_INFO("thread ledger: %s", ledger.c_str());
}

// -- stats registry -------------------------------------------------------

namespace {

// Opcodes this daemon serves, with their monitor-facing names.  Sidecar
// RPC opcodes (DEDUP_*) are absent: the dedup engine answers those, not
// this server.
struct ServedOp {
  StorageCmd cmd;
  const char* name;
};
constexpr ServedOp kServedOps[] = {
    {StorageCmd::kUploadFile, "upload_file"},
    {StorageCmd::kUploadAppenderFile, "upload_appender_file"},
    {StorageCmd::kUploadSlaveFile, "upload_slave_file"},
    {StorageCmd::kDownloadFile, "download_file"},
    {StorageCmd::kDeleteFile, "delete_file"},
    {StorageCmd::kSetMetadata, "set_metadata"},
    {StorageCmd::kGetMetadata, "get_metadata"},
    {StorageCmd::kQueryFileInfo, "query_file_info"},
    {StorageCmd::kAppendFile, "append_file"},
    {StorageCmd::kModifyFile, "modify_file"},
    {StorageCmd::kTruncateFile, "truncate_file"},
    {StorageCmd::kCreateLink, "create_link"},
    {StorageCmd::kNearDups, "near_dups"},
    {StorageCmd::kActiveTest, "active_test"},
    {StorageCmd::kStat, "stat"},
    {StorageCmd::kSyncCreateFile, "sync_create_file"},
    {StorageCmd::kSyncDeleteFile, "sync_delete_file"},
    {StorageCmd::kSyncUpdateFile, "sync_update_file"},
    {StorageCmd::kSyncCreateLink, "sync_create_link"},
    {StorageCmd::kSyncAppendFile, "sync_append_file"},
    {StorageCmd::kSyncModifyFile, "sync_modify_file"},
    {StorageCmd::kSyncTruncateFile, "sync_truncate_file"},
    {StorageCmd::kSyncQueryChunks, "sync_query_chunks"},
    {StorageCmd::kSyncCreateRecipe, "sync_create_recipe"},
    {StorageCmd::kUploadRecipe, "upload_recipe"},
    {StorageCmd::kUploadChunks, "upload_chunks"},
    {StorageCmd::kFetchRecipe, "fetch_recipe"},
    {StorageCmd::kFetchChunk, "fetch_chunk"},
    {StorageCmd::kTraceDump, "trace_dump"},
    {StorageCmd::kEventDump, "event_dump"},
    {StorageCmd::kMetricsHistory, "metrics_history"},
    {StorageCmd::kHeatTop, "heat_top"},
    {StorageCmd::kScrubStatus, "scrub_status"},
    {StorageCmd::kScrubKick, "scrub_kick"},
    {StorageCmd::kEcStatus, "ec_status"},
    {StorageCmd::kEcKick, "ec_kick"},
    {StorageCmd::kEcRelease, "ec_release"},
    {StorageCmd::kFetchOnePathBinlog, "fetch_one_path_binlog"},
    {StorageCmd::kTrunkAllocSpace, "trunk_alloc_space"},
    {StorageCmd::kTrunkAllocConfirm, "trunk_alloc_confirm"},
    {StorageCmd::kTrunkFreeSpace, "trunk_free_space"},
    {StorageCmd::kProfileCtl, "profile_ctl"},
    {StorageCmd::kProfileDump, "profile_dump"},
    {StorageCmd::kHealthStatus, "health_status"},
};

}  // namespace

void StorageServer::InitStatsRegistry() {
  for (const ServedOp& op : kServedOps) {
    std::string base = std::string("op.") + op.name;
    OpStats& os = op_stats_[static_cast<uint8_t>(op.cmd)];
    os.count = registry_.Counter(base + ".count");
    os.errors = registry_.Counter(base + ".errors");
    os.latency_us = registry_.Histogram(base + ".latency_us",
                                        StatsRegistry::LatencyBucketsUs());
    op_names_[static_cast<uint8_t>(op.cmd)] = op.name;
  }
  // Saturation telemetry (ISSUE 6).  nio.loop_lag_us: per-iteration
  // callback time of every nio event loop — the p99 here is how long a
  // ready connection can wait behind other handlers, the queueing
  // signal the multi-reactor refactor (ROADMAP item 5) will be judged
  // against.  dio.queue_wait_us / dio.service_us: time disk work sat
  // queued behind other disk work vs time actually serviced, across
  // every store path's pool.
  hist_nio_lag_ = registry_.Histogram("nio.loop_lag_us",
                                      StatsRegistry::LatencyBucketsUs());
  ctr_nio_dispatched_ = registry_.Counter("nio.dispatched_ops");
  registry_.GaugeFn("nio.conns_active", [this] { return conn_count_.load(); });
  // Per-reactor accept spread (ISSUE 18): fed by both accept modes, so
  // a skewed nio.accepts.<i> distribution under reuseport is the kernel
  // hashing poorly, and under fallback it's the round-robin cursor.
  registry_.GaugeFn("nio.reuseport_active",
                    [this] { return reuseport_active_ ? 1 : 0; });
  for (size_t i = 0; i < nio_.size(); ++i) {
    NioThread* t = nio_[i].get();
    registry_.GaugeFn("nio.accepts." + std::to_string(i),
                      [t] { return t->accepts.load(); });
    registry_.GaugeFn("nio.conns." + std::to_string(i),
                      [t] { return t->live_conns.load(); });
  }
  hist_dio_wait_ = registry_.Histogram("dio.queue_wait_us",
                                       StatsRegistry::LatencyBucketsUs());
  hist_dio_service_ = registry_.Histogram("dio.service_us",
                                          StatsRegistry::LatencyBucketsUs());
  registry_.GaugeFn("dio.queue_depth", [this] {
    int64_t n = 0;
    for (const auto& p : dio_pools_) n += static_cast<int64_t>(p->pending());
    return n;
  });
  // Flight-recorder health: throughput and ring-overwrite pressure.
  registry_.GaugeFn("events.recorded", [this] {
    return events_ != nullptr ? events_->recorded() : int64_t{0};
  });
  registry_.GaugeFn("events.dropped", [this] {
    return events_ != nullptr ? events_->dropped() : int64_t{0};
  });
  // Sampling profiler health (profiler.h): capture counters while a
  // window is armed, drop pressure when the slab overflows, and the
  // armed flag operators alert on (a profiler left running is overhead).
  registry_.GaugeFn("profile.samples",
                    [] { return Profiler::Global().samples(); });
  registry_.GaugeFn("profile.dropped",
                    [] { return Profiler::Global().dropped(); });
  registry_.GaugeFn("profile.active", [] {
    return static_cast<int64_t>(Profiler::Global().active() ? 1 : 0);
  });
  // SLO engine: how many rules are red right now (the one-read health
  // check fdfs_top's ALERTS line and scrapers key off).
  registry_.GaugeFn("slo.breaches_active", [this] {
    return slo_ != nullptr ? slo_->breaches_active() : int64_t{0};
  });
  registry_.GaugeFn("slo.breach_transitions", [this] {
    return slo_ != nullptr ? slo_->breach_transitions() : int64_t{0};
  });
  // Admission control & request QoS (ISSUE 19): ladder position, the
  // pressure score feeding it (milli-units — gauge-fns are int64), and
  // the admit/shed ledgers.  All atomic reads (the gauge-fn contract).
  registry_.GaugeFn("admission.level", [this] {
    return static_cast<int64_t>(admission_ != nullptr ? admission_->level()
                                                      : 0);
  });
  registry_.GaugeFn("admission.pressure_milli", [this] {
    return admission_ != nullptr ? admission_->pressure_milli() : int64_t{0};
  });
  registry_.GaugeFn("admission.ewma_milli", [this] {
    return admission_ != nullptr ? admission_->ewma_milli() : int64_t{0};
  });
  registry_.GaugeFn("admission.tightens", [this] {
    return admission_ != nullptr ? admission_->tightens() : int64_t{0};
  });
  registry_.GaugeFn("admission.relaxes", [this] {
    return admission_ != nullptr ? admission_->relaxes() : int64_t{0};
  });
  registry_.GaugeFn("admission.admitted", [this] {
    return admission_ != nullptr ? admission_->admitted() : int64_t{0};
  });
  registry_.GaugeFn("admission.shed_total", [this] {
    return admission_ != nullptr ? admission_->shed_total() : int64_t{0};
  });
  registry_.GaugeFn("admission.retry_after_ms", [this] {
    return admission_ != nullptr ? admission_->retry_after_ms() : int64_t{0};
  });
  registry_.GaugeFn("admission.inflight_bytes", [this] {
    return inflight_bytes_.load(std::memory_order_relaxed);
  });
  for (int i = 0; i < kPriorityClassCount; ++i) {
    registry_.GaugeFn(
        std::string("admission.shed.") +
            PriorityClassName(static_cast<uint8_t>(i)),
        [this, i] {
          return admission_ != nullptr ? admission_->shed_by_class(i)
                                       : int64_t{0};
        });
  }
  // Metrics journal health: retained bytes vs the conf cap, and how
  // many ticks this process has persisted.
  registry_.GaugeFn("metrics.journal_bytes", [this] {
    return metrics_ != nullptr ? metrics_->bytes_retained() : int64_t{0};
  });
  registry_.GaugeFn("metrics.journal_records", [this] {
    return metrics_ != nullptr ? metrics_->appended() : int64_t{0};
  });
  // Heat sketch health: tracked keys and lifetime touches (the
  // touches/capacity ratio bounds the sketch's overcount error).
  registry_.GaugeFn("heat.tracked", [this] {
    return heat_ != nullptr ? heat_->tracked() : int64_t{0};
  });
  registry_.GaugeFn("heat.touches", [this] {
    return heat_ != nullptr ? heat_->touches() : int64_t{0};
  });
  registry_.GaugeFn("heat.evictions", [this] {
    return heat_ != nullptr ? heat_->evictions() : int64_t{0};
  });
  // Fullest store path in percent — the disk_fill_pct SLO rule's input.
  // The gauge-fn only reads the cache: gauge-fns run UNDER the registry
  // mutex (Json/Snapshot), and a statvfs against a stalled disk or hung
  // NFS mount can block for seconds — which would freeze every STAT,
  // journal tick, and the nio loop serving them, exactly the saturation
  // this layer exists to diagnose.  RefreshDiskUsedPct runs the real
  // syscalls at startup, each metrics tick, and each beat.
  RefreshDiskUsedPct();
  registry_.GaugeFn("store.disk_used_pct",
                    [this] { return disk_used_pct_.load(); });
  // Filesystem inodes in use — refreshed off the registry lock exactly
  // like disk_used_pct (gauge-fns must never statvfs a stalled mount
  // under the registry mutex).  The number the slab-packing layout
  // (ISSUE 9) exists to flatten on small-file corpora.
  registry_.GaugeFn("store.inodes_used",
                    [this] { return inodes_used_.load(); });
  // Tracing health: ring throughput/overwrite pressure and the slow gate.
  registry_.GaugeFn("trace.spans_recorded", [this] {
    return trace_ != nullptr ? trace_->recorded() : int64_t{0};
  });
  registry_.GaugeFn("trace.spans_dropped", [this] {
    return trace_ != nullptr ? trace_->dropped() : int64_t{0};
  });
  registry_.GaugeFn("trace.slow_requests",
                    [this] { return slow_request_count_.load(); });
  // Gray-failure health layer (ISSUE 17).  peer.rpc_us: outbound RPC
  // latency across every op class, fed by the health monitor's NetRpc
  // observer — the peer_rpc_p99_ms SLO rule's input.  The probe and
  // watchdog gauge-fns only read atomics the "health.probe" thread and
  // the metrics tick refresh (the store.disk_used_pct discipline: a
  // gauge-fn must never touch a disk or a lock that can stall).
  hist_peer_rpc_ = registry_.Histogram("peer.rpc_us",
                                       StatsRegistry::LatencyBucketsUs());
  registry_.GaugeFn("store.probe_read_us",
                    [this] { return probe_read_us_.load(); });
  registry_.GaugeFn("store.probe_write_us",
                    [this] { return probe_write_us_.load(); });
  registry_.GaugeFn("watchdog.stalled_threads",
                    [this] { return stalled_threads_.load(); });
  hist_upload_bytes_ = registry_.Histogram(
      "upload.size_bytes", StatsRegistry::SizeBucketsBytes());
  hist_download_bytes_ = registry_.Histogram(
      "download.size_bytes", StatsRegistry::SizeBucketsBytes());
  ctr_sync_bytes_saved_wire_ = registry_.Counter("sync.bytes_saved_wire");
  ctr_sync_digest_mismatch_ = registry_.Counter("sync.digest_mismatch");
  ctr_chunkfetch_batches_ = registry_.Counter("chunkfetch.batches");
  ctr_chunkfetch_chunks_ = registry_.Counter("chunkfetch.chunks");
  ctr_chunkfetch_bytes_ = registry_.Counter("chunkfetch.bytes");
  ctr_dedup_chunk_hits_ = registry_.Counter("dedup.chunk_hits");
  ctr_dedup_chunk_misses_ = registry_.Counter("dedup.chunk_misses");
  // Negotiated uploads on the ingest edge (UPLOAD_RECIPE/UPLOAD_CHUNKS):
  // bytes_saved_wire counts chunk bytes the client never shipped because
  // the bitmap reported them present — the client-facing twin of
  // sync.bytes_saved_wire.
  ctr_ingest_recipe_uploads_ = registry_.Counter("ingest.recipe_uploads");
  ctr_ingest_bytes_saved_wire_ =
      registry_.Counter("ingest.bytes_saved_wire");
  ctr_ingest_fallbacks_ = registry_.Counter("ingest.recipe_fallbacks");
  registry_.GaugeFn("ingest.sessions_active", [this] {
    std::lock_guard<RankedMutex> lk(ingest_mu_);
    return static_cast<int64_t>(ingest_sessions_.size());
  });
  // Read path (PR 5): ranged-download traffic and the hot-chunk read
  // cache, summed over the per-store-path chunk stores.
  ctr_download_ranged_requests_ =
      registry_.Counter("download.ranged_requests");
  ctr_download_ranged_bytes_ = registry_.Counter("download.ranged_bytes");
  ctr_dio_preadv_batches_ = registry_.Counter("dio.preadv_batches");
  ctr_dio_preadv_spans_ = registry_.Counter("dio.preadv_spans");
  auto cache_sum = [this](int64_t (ChunkStore::*fn)() const) {
    int64_t n = 0;
    for (const auto& cs : chunk_stores_) n += (cs.get()->*fn)();
    return n;
  };
  registry_.GaugeFn("cache.hits",
                    [cache_sum] { return cache_sum(&ChunkStore::cache_hits); });
  registry_.GaugeFn("cache.misses", [cache_sum] {
    return cache_sum(&ChunkStore::cache_misses);
  });
  registry_.GaugeFn("cache.evictions", [cache_sum] {
    return cache_sum(&ChunkStore::cache_evictions);
  });
  registry_.GaugeFn("cache.invalidations", [cache_sum] {
    return cache_sum(&ChunkStore::cache_invalidations);
  });
  registry_.GaugeFn("cache.bytes", [cache_sum] {
    return cache_sum(&ChunkStore::cache_bytes);
  });
  registry_.GaugeFn("cache.chunks", [cache_sum] {
    return cache_sum(&ChunkStore::cache_chunks);
  });
  registry_.GaugeFn("cache.capacity_bytes", [cache_sum] {
    return cache_sum(&ChunkStore::cache_capacity_bytes);
  });
  // Slab packing (ISSUE 9): slot/byte live-vs-dead accounting plus the
  // compactor's lifetime work, summed over the per-store-path slab
  // stores (all zero when slab_*_threshold = 0).
  registry_.GaugeFn("slab.files", [cache_sum] {
    return cache_sum(&ChunkStore::slab_files);
  });
  registry_.GaugeFn("slab.slots_live", [cache_sum] {
    return cache_sum(&ChunkStore::slab_slots_live);
  });
  registry_.GaugeFn("slab.slots_dead", [cache_sum] {
    return cache_sum(&ChunkStore::slab_slots_dead);
  });
  registry_.GaugeFn("slab.bytes_live", [cache_sum] {
    return cache_sum(&ChunkStore::slab_bytes_live);
  });
  registry_.GaugeFn("slab.bytes_dead", [cache_sum] {
    return cache_sum(&ChunkStore::slab_bytes_dead);
  });
  registry_.GaugeFn("slab.compactions", [cache_sum] {
    return cache_sum(&ChunkStore::slab_compactions);
  });
  registry_.GaugeFn("slab.compacted_bytes", [cache_sum] {
    return cache_sum(&ChunkStore::slab_compacted_bytes);
  });

  // Snapshot-time mirrors of live state.  The restart-persisted op
  // totals keep their wire names (kBeatStatNames) under "store." so the
  // STAT JSON and the tracker's beat feed agree field-for-field.
  static_assert(kBeatStatCount == 33, "update FillBeatStats + gauges");
  for (int i = 0; i < StorageStats::kPersisted; ++i) {
    registry_.GaugeFn(std::string("store.") + kBeatStatNames[i], [this, i] {
      int64_t v[StorageStats::kPersisted] = {0};
      stats_.Snapshot(v);
      return v[i];
    });
  }
  registry_.GaugeFn("server.connections",
                    [this] { return conn_count_.load(); });
  registry_.GaugeFn("server.refused_connections",
                    [this] { return refused_conn_count_.load(); });
  registry_.GaugeFn("binlog.file_index", [this] {
    return static_cast<int64_t>(binlog_.file_index());
  });
  registry_.GaugeFn("sync.lag_s.max", [this] { return MaxSyncLagS(); });
  registry_.GaugeFn("recovery.running", [this] {
    return static_cast<int64_t>(recovery_ != nullptr && recovery_->running());
  });
  registry_.GaugeFn("recovery.chunks_fetched", [this] {
    return recovery_ != nullptr ? recovery_->chunks_pulled() : int64_t{0};
  });
  registry_.GaugeFn("recovery.chunks_local", [this] {
    return recovery_ != nullptr ? recovery_->chunks_local() : int64_t{0};
  });
  registry_.GaugeFn("recovery.files_recovered", [this] {
    return recovery_ != nullptr ? recovery_->files_recovered() : int64_t{0};
  });
  registry_.GaugeFn("recovery.files_skipped", [this] {
    return recovery_ != nullptr ? recovery_->files_skipped() : int64_t{0};
  });
  // Integrity engine: mirror the SCRUB_STATUS blob field-for-field so
  // fdfs_monitor --prometheus exports scrub health without a second
  // RPC.  Names follow the wire contract (kScrubStatNames) under the
  // scrub. prefix; all zero when scrubbing is off (no chunk store).
  for (int i = 0; i < kScrubStatCount; ++i) {
    registry_.GaugeFn(std::string("scrub.") + kScrubStatNames[i],
                      [this, i] {
                        return scrub_ != nullptr ? scrub_->StatValue(i)
                                                 : int64_t{0};
                      });
  }
  // Erasure-coded cold tier (ISSUE 16): mirror the EC_STATUS blob the
  // same way — kEcStatNames under the ec. prefix, all zero when the
  // tier is off (no stripes and no scrubber).
  for (int i = 0; i < kEcStatCount; ++i) {
    registry_.GaugeFn(std::string("ec.") + kEcStatNames[i], [this, i] {
      return scrub_ != nullptr ? scrub_->EcStatValue(i) : int64_t{0};
    });
  }
  // Rebalance migrator (ISSUE 11): same names as the beat slots so
  // fdfs_monitor/fdfs_top read drain progress from either feed.
  registry_.GaugeFn("rebalance.files_moved", [this] {
    return rebalance_ != nullptr ? rebalance_->files_moved() : int64_t{0};
  });
  registry_.GaugeFn("rebalance.bytes_moved", [this] {
    return rebalance_ != nullptr ? rebalance_->bytes_moved() : int64_t{0};
  });
  registry_.GaugeFn("rebalance.files_pending", [this] {
    return rebalance_ != nullptr ? rebalance_->files_pending() : int64_t{0};
  });
  registry_.GaugeFn("rebalance.errors", [this] {
    return rebalance_ != nullptr ? rebalance_->errors() : int64_t{0};
  });
  registry_.GaugeFn("rebalance.done", [this] {
    return rebalance_ != nullptr ? rebalance_->done() : int64_t{0};
  });
  // Hot-replication fan-out worker (ISSUE 20): elected-member progress
  // counters; all zero on nodes never elected (or trackerless runs).
  registry_.GaugeFn("hot.fanout_replicated", [this] {
    return hotrepl_ != nullptr ? hotrepl_->replicated_total() : int64_t{0};
  });
  registry_.GaugeFn("hot.fanout_dropped", [this] {
    return hotrepl_ != nullptr ? hotrepl_->dropped_total() : int64_t{0};
  });
  registry_.GaugeFn("hot.fanout_verify_failures", [this] {
    return hotrepl_ != nullptr ? hotrepl_->verify_failures() : int64_t{0};
  });
  registry_.GaugeFn("hot.fanout_failures", [this] {
    return hotrepl_ != nullptr ? hotrepl_->failures_total() : int64_t{0};
  });
  registry_.GaugeFn("hot.fanout_queue", [this] {
    return hotrepl_ != nullptr ? hotrepl_->queue_depth() : int64_t{0};
  });
}

int64_t StorageServer::MaxSyncLagS() const {
  if (sync_ == nullptr) return 0;
  int64_t now = time(nullptr);
  int64_t mx = 0;
  for (const SyncPeerState& s : sync_->States()) {
    if (s.synced_ts > 0 && now - s.synced_ts > mx) mx = now - s.synced_ts;
  }
  return mx;
}

void StorageServer::RefreshPeerGauges() {
  // Per-peer replication gauges have dynamic names (peers come and go),
  // so they are plain gauges refreshed at snapshot time — and RETIRED
  // when their peer leaves the group (ISSUE 6 registry hygiene: a
  // long-lived daemon in a churning group must not grow unbounded
  // metric cardinality; nothing caches pointers to these gauges, so
  // pruning by name is safe).
  if (sync_ == nullptr) return;
  int64_t now = time(nullptr);
  std::vector<std::string> live;
  for (const SyncPeerState& s : sync_->States()) {
    std::string base = "sync.peer." + s.addr;
    live.push_back(base + ".");
    registry_.SetGauge(base + ".connected", s.connected ? 1 : 0);
    registry_.SetGauge(
        base + ".lag_s",
        s.synced_ts > 0 && now > s.synced_ts ? now - s.synced_ts : 0);
    registry_.SetGauge(base + ".records_synced", s.records_synced);
    registry_.SetGauge(base + ".records_skipped", s.records_skipped);
  }
  registry_.PruneGauges("sync.peer.", live);
}

std::string StorageServer::BuildStatsJson() {
  RefreshPeerGauges();
  HealthMonitor::Global().PublishGauges(&registry_);
  return registry_.Json();
}

void StorageServer::RefreshDiskUsedPct() {
  int64_t worst = 0;
  int64_t inodes = 0;
  std::vector<unsigned long> seen_fsids;
  for (int i = 0; i < store_.store_path_count(); ++i) {
    struct statvfs vfs;
    if (statvfs(store_.store_path(i).c_str(), &vfs) != 0 ||
        vfs.f_blocks == 0)
      continue;
    int64_t pct = static_cast<int64_t>(
        100.0 * (1.0 - static_cast<double>(vfs.f_bavail) /
                           static_cast<double>(vfs.f_blocks)));
    if (pct > worst) worst = pct;
    // Inodes in use, deduped by filesystem id (two store paths on one
    // filesystem must not double-count): the store.inodes_used gauge
    // that the slab-packing bench (config9) reads before/after.
    bool dup = false;
    for (unsigned long id : seen_fsids) dup = dup || id == vfs.f_fsid;
    if (!dup) {
      seen_fsids.push_back(vfs.f_fsid);
      if (vfs.f_files >= vfs.f_ffree)
        inodes += static_cast<int64_t>(vfs.f_files - vfs.f_ffree);
    }
  }
  disk_used_pct_.store(worst);
  inodes_used_.store(inodes);
}

// -- gray-failure health layer (ISSUE 17) ---------------------------------

void StorageServer::HealthProbeMain() {
  ScopedThreadName ledger("health.probe");
  // First round 2s after startup (daemon fully up, reporter joined),
  // then per the conf cadence.  Sleeps are 250ms slices so Stop() stays
  // bounded, and each slice beats the heartbeat — the prober must never
  // look stalled to the watchdog it feeds.
  int64_t next_due = MonoUs() + 2 * 1000000;
  while (!health_stop_.load(std::memory_order_relaxed)) {
    BeatThreadHeartbeat();
    if (MonoUs() < next_due) {
      usleep(250000);
      continue;
    }
    RunHealthProbes();
    next_due = MonoUs() +
               static_cast<int64_t>(cfg_.health_probe_interval_s) * 1000000;
  }
}

void StorageServer::RunHealthProbes() {
  // Disk probes: one 4 KB tmp-write+fsync and one read-back per store
  // path, timed wall-clock.  A probe CAN block for seconds on a gray
  // mount — that's the measurement — which is why it runs on this
  // dedicated thread and publishes through atomics (gauge-fns and the
  // request path never touch the disk for health).
  int64_t thr_us = static_cast<int64_t>(cfg_.probe_slow_threshold_ms) * 1000;
  // A FAILED probe (open/write/fsync/read error) reads as slower than
  // any threshold: the disk.gray event fires and the score drops, which
  // is exactly what a dead mount deserves.
  int64_t fail_us = thr_us > 0 ? 8 * thr_us : 10 * 1000000;
  int64_t worst_read = 0, worst_write = 0;
  for (int i = 0; i < store_.store_path_count(); ++i) {
    std::string path = store_.store_path(i) + "/data/.health_probe.tmp";
    char block[4096];
    memset(block, 0x5a, sizeof(block));
    int64_t t0 = MonoUs();
    int64_t write_us = fail_us, read_us = fail_us;
    int fd = open(path.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (fd >= 0) {
      if (write(fd, block, sizeof(block)) ==
              static_cast<ssize_t>(sizeof(block)) &&
          fsync(fd) == 0)
        write_us = MonoUs() - t0;
      close(fd);
    }
    BeatThreadHeartbeat();
    t0 = MonoUs();
    fd = open(path.c_str(), O_RDONLY);
    if (fd >= 0) {
      if (read(fd, block, sizeof(block)) ==
          static_cast<ssize_t>(sizeof(block)))
        read_us = MonoUs() - t0;
      close(fd);
    }
    BeatThreadHeartbeat();
    worst_write = std::max(worst_write, write_us);
    worst_read = std::max(worst_read, read_us);
    // One disk.gray event per outage per path (not per probe round):
    // probe_slow_noted_ is probe-thread-only state.
    bool slow = thr_us > 0 && std::max(write_us, read_us) > thr_us;
    if (slow && !probe_slow_noted_[static_cast<size_t>(i)]) {
      probe_slow_noted_[static_cast<size_t>(i)] = true;
      FDFS_LOG_WARN("gray disk: %s probe write=%lldus read=%lldus (>%dms)",
                    store_.store_path(i).c_str(),
                    static_cast<long long>(write_us),
                    static_cast<long long>(read_us),
                    cfg_.probe_slow_threshold_ms);
      if (events_ != nullptr)
        events_->Record(EventSeverity::kWarn, "disk.gray",
                        store_.store_path(i),
                        "probe write=" + std::to_string(write_us / 1000) +
                            "ms read=" + std::to_string(read_us / 1000) +
                            "ms threshold=" +
                            std::to_string(cfg_.probe_slow_threshold_ms) +
                            "ms");
    } else if (!slow && probe_slow_noted_[static_cast<size_t>(i)]) {
      probe_slow_noted_[static_cast<size_t>(i)] = false;
      if (events_ != nullptr)
        events_->Record(EventSeverity::kInfo, "disk.recovered",
                        store_.store_path(i), "");
    }
  }
  probe_read_us_.store(worst_read);
  probe_write_us_.store(worst_write);
  HealthMonitor::Global().SetProbe(worst_read, worst_write,
                                   cfg_.probe_slow_threshold_ms);

  // Active peer probes: ACTIVE_TEST to every tracker + group sync peer,
  // so an otherwise-idle cluster still converges on peer health.  The
  // NetRpc observer records each round-trip; only CONNECT failures
  // (no fd, so the observer never sees them) are fed explicitly.
  std::vector<std::pair<std::string, int>> targets;
  for (const std::string& t : cfg_.tracker_servers) {
    size_t colon = t.rfind(':');
    if (colon == std::string::npos || colon == 0) continue;
    targets.emplace_back(t.substr(0, colon), atoi(t.c_str() + colon + 1));
  }
  if (sync_ != nullptr) {
    for (const SyncPeerState& s : sync_->States()) {
      size_t colon = s.addr.rfind(':');
      if (colon == std::string::npos || colon == 0) continue;
      targets.emplace_back(s.addr.substr(0, colon),
                           atoi(s.addr.c_str() + colon + 1));
    }
  }
  for (const auto& [host, port] : targets) {
    if (health_stop_.load(std::memory_order_relaxed)) return;
    BeatThreadHeartbeat();
    int64_t t0 = MonoUs();
    std::string err;
    int fd = TcpConnect(host, port, 2000, &err);
    if (fd < 0) {
      HealthMonitor::Global().Feed(host + ":" + std::to_string(port),
                                   "probe", false, MonoUs() - t0, 2000);
      continue;
    }
    std::string resp;
    uint8_t status = 0;
    NetRpc(fd, static_cast<uint8_t>(StorageCmd::kActiveTest), "", &resp,
           &status, 1024, 2000);
    close(fd);
  }
}

std::string StorageServer::HealthStatusJson() {
  return HealthMonitor::Global().Json("storage", cfg_.port);
}

void StorageServer::MetricsTick() {
  // One snapshot feeds both consumers: what the journal persists IS
  // what the SLO engine judged, so a post-mortem can re-derive every
  // breach from the retained history.
  RefreshDiskUsedPct();
  RefreshPeerGauges();
  int64_t now_mono = MonoUs();
  // Per-thread CPU ledger: one /proc pass per tick, published as
  // thread.<name>.* gauges so the journal snapshot below persists them.
  ThreadRegistry::Global().SampleInto(&registry_);
  // Watchdog scan (gray-failure layer): a registered daemon thread
  // whose heartbeat is older than the threshold is stalled — wedged on
  // a lock, a dead NFS mount, an unbounded syscall.  Each transition
  // records one flight-recorder event (newly stalled / recovered), and
  // the live count feeds the gauge + this node's gray score.
  if (cfg_.watchdog_stall_threshold_ms > 0) {
    ThreadRegistry::WatchdogResult wd = ThreadRegistry::Global().WatchdogScan(
        static_cast<int64_t>(cfg_.watchdog_stall_threshold_ms) * 1000);
    stalled_threads_.store(static_cast<int64_t>(wd.stalled.size()));
    HealthMonitor::Global().SetStalledThreads(
        static_cast<int>(wd.stalled.size()));
    if (events_ != nullptr) {
      for (const ThreadRegistry::Stall& s : wd.stalled) {
        if (!s.newly) continue;
        FDFS_LOG_WARN("watchdog: thread %s (tid %d) stalled %llds",
                      s.name.c_str(), s.tid,
                      static_cast<long long>(s.age_us / 1000000));
        events_->Record(EventSeverity::kWarn, "watchdog.stall", s.name,
                        "heartbeat " + std::to_string(s.age_us / 1000) +
                            "ms old (threshold " +
                            std::to_string(cfg_.watchdog_stall_threshold_ms) +
                            "ms)");
      }
      for (const std::string& name : wd.recovered)
        events_->Record(EventSeverity::kInfo, "watchdog.recovered", name, "");
    }
  }
  // Health gauges (health.score + peer.* families) refresh here so the
  // journal snapshot below persists them every tick.
  HealthMonitor::Global().PublishGauges(&registry_);
  // Per-loop duty cycle: busy-us delta over the tick's wall time.
  // Index 0 = the accept/timers loop, 1 + i = nio_[i].
  if (loop_busy_last_.size() == nio_.size() + 1) {
    int64_t dwall = now_mono - last_tick_mono_us_;
    bool have_base = last_tick_mono_us_ > 0 && dwall > 0;
    for (size_t i = 0; i < loop_busy_last_.size(); ++i) {
      int64_t busy = i == 0 ? main_loop_busy_us_.load(std::memory_order_relaxed)
                            : nio_[i - 1]->busy_us.load(std::memory_order_relaxed);
      if (have_base) {
        int64_t pct = (busy - loop_busy_last_[i]) * 100 / dwall;
        if (pct < 0) pct = 0;
        if (pct > 100) pct = 100;
        registry_.SetGauge(
            i == 0 ? "nio.loop_busy_pct.main"
                   : "nio.loop_busy_pct." + std::to_string(i - 1),
            pct);
      }
      loop_busy_last_[i] = busy;  // first tick seeds the delta base
    }
  }
  StatsSnapshot snap;
  registry_.Snapshot(&snap);
  if (metrics_ != nullptr) metrics_->Append(TraceWallUs(), snap);
  double dt_s = static_cast<double>(now_mono - last_tick_mono_us_) / 1e6;
  if (dt_s <= 0) dt_s = 1.0;
  if (slo_ != nullptr && have_tick_snap_) {
    slo_->Tick(last_tick_snap_, snap, dt_s);
  }
  // Admission ladder tick AFTER the SLO tick: breaches_active then
  // reflects THIS snapshot's verdicts, so the ladder reacts the same
  // tick a breach starts.  One rung at most per tick; tighten/relax
  // transitions land in the flight recorder (the sloeval discipline).
  if (admission_ != nullptr) {
    AdmissionSignals sig;
    sig.breaches_active = slo_ != nullptr ? slo_->breaches_active() : 0;
    for (const auto& p : dio_pools_)
      sig.queue_depth += static_cast<int64_t>(p->pending());
    sig.inflight_bytes = inflight_bytes_.load(std::memory_order_relaxed);
    double lag_ms = 0;
    if (have_tick_snap_ &&
        SloEvaluator::ComputeReading("loop_lag_p99_ms", last_tick_snap_,
                                     snap, dt_s, &lag_ms))
      sig.loop_lag_p99_ms = lag_ms;
    int moved = admission_->Tick(sig);
    if (moved != 0 && events_ != nullptr) {
      char detail[128];
      snprintf(detail, sizeof(detail), "level=%d ewma=%.6g pressure=%.6g",
               admission_->level(), admission_->ewma_milli() / 1000.0,
               admission_->pressure_milli() / 1000.0);
      events_->Record(moved > 0 ? EventSeverity::kWarn : EventSeverity::kInfo,
                      moved > 0 ? "admission.tighten" : "admission.relax",
                      admission_->level_name(), detail);
    }
  }
  last_tick_snap_ = std::move(snap);
  have_tick_snap_ = true;
  last_tick_mono_us_ = now_mono;
}

void StorageServer::FillBeatStats(int64_t* out) {
  // Beats run on the tracker-client thread: a safe place to refresh
  // the disk gauge so it stays fresh even with the metrics tick off.
  RefreshDiskUsedPct();
  for (int i = 0; i < kBeatStatCount; ++i) out[i] = 0;
  stats_.Snapshot(out);  // slots [0, kPersisted)
  out[19] = conn_count_.load();
  out[20] = refused_conn_count_.load();
  out[21] = MaxSyncLagS();
  out[22] = ctr_sync_bytes_saved_wire_ != nullptr
                ? ctr_sync_bytes_saved_wire_->load() : 0;
  out[23] = recovery_ != nullptr ? recovery_->chunks_pulled() : 0;
  out[24] = recovery_ != nullptr ? recovery_->chunks_local() : 0;
  out[25] = recovery_ != nullptr ? recovery_->files_recovered() : 0;
  out[26] = ctr_chunkfetch_batches_ != nullptr
                ? ctr_chunkfetch_batches_->load() : 0;
  out[27] = ctr_dedup_chunk_misses_ != nullptr
                ? ctr_dedup_chunk_misses_->load() : 0;
  // Rebalance migrator progress (ISSUE 11): the tracker leader's
  // auto-retire decision reads slots 30 (pending) and 32 (done) from
  // every ACTIVE member of a draining group.
  out[28] = rebalance_ != nullptr ? rebalance_->files_moved() : 0;
  out[29] = rebalance_ != nullptr ? rebalance_->bytes_moved() : 0;
  out[30] = rebalance_ != nullptr ? rebalance_->files_pending() : 0;
  out[31] = rebalance_ != nullptr ? rebalance_->errors() : 0;
  out[32] = rebalance_ != nullptr ? rebalance_->done() : 0;
}

// -- nio ------------------------------------------------------------------

bool StorageServer::AdmitConn(int fd) {
  SetNonBlocking(fd);
  SetNoDelay(fd);  // responses are header-write + body-write pairs
  if (cfg_.max_connections > 0 &&
      conn_count_.load() >= cfg_.max_connections) {
    // Polite refusal (reference: fast_task_queue pool exhaustion):
    // one EBUSY response header, then close.  A fresh socket's send
    // buffer always takes 10 bytes, so a blocking write is safe.
    uint8_t hdr[kHeaderSize] = {0};
    hdr[8] = static_cast<uint8_t>(StorageCmd::kResp);
    hdr[9] = 16;  // EBUSY
    (void)!write(fd, hdr, sizeof(hdr));
    close(fd);
    refused_conn_count_++;
    return false;
  }
  // First-conn local-ip capture, lock-free: with sharded accept this
  // races across reactor threads, so one writer wins the 0->1 CAS and
  // release-publishes state 2; MyIp() acquires before reading.
  int st = 0;
  if (my_ip_state_.load(std::memory_order_relaxed) == 0 &&
      my_ip_state_.compare_exchange_strong(st, 1,
                                           std::memory_order_relaxed)) {
    my_ip_ = SockIp(fd);
    my_ip_state_.store(2, std::memory_order_release);
  }
  // Count at accept time, not adoption: a connect burst drains the
  // whole backlog here before any nio thread runs its posted
  // AdoptConn, so a later increment would let the burst sail past the
  // cap.  CloseConn owns the decrement.
  conn_count_++;
  return true;
}

void StorageServer::OnAccept(uint32_t) {
  for (;;) {
    int fd = accept(listen_fd_, nullptr, nullptr);
    if (fd < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) return;
      FDFS_LOG_WARN("accept: %s", strerror(errno));
      return;
    }
    if (!AdmitConn(fd)) continue;
    // Round-robin handoff to a nio work thread (reference:
    // storage_nio.c pipe-notify from the accept thread).
    NioThread* t = nio_[next_nio_++ % nio_.size()].get();
    t->accepts.fetch_add(1, std::memory_order_relaxed);
    t->loop->Post([this, t, fd] { AdoptConn(t, fd); });
  }
}

void StorageServer::OnReactorAccept(NioThread* t) {
  // Runs on t's own loop thread: the kernel spread the connection to
  // this reactor's SO_REUSEPORT listener, so adoption is inline — no
  // cross-loop Post, no shared next_nio_ cursor.
  for (;;) {
    int fd = accept(t->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) return;
      FDFS_LOG_WARN("accept (reactor): %s", strerror(errno));
      return;
    }
    if (!AdmitConn(fd)) continue;
    t->accepts.fetch_add(1, std::memory_order_relaxed);
    AdoptConn(t, fd);
  }
}

void StorageServer::AdoptConn(NioThread* t, int fd) {
  auto conn = std::make_unique<Conn>();
  conn->fd = fd;
  conn->owner = t;
  Conn* raw = conn.get();
  t->conns[fd] = std::move(conn);  // conn_count_ was taken at accept
  t->live_conns.fetch_add(1, std::memory_order_relaxed);
  t->loop->Add(fd, EPOLLIN, [this, raw](uint32_t ev) { OnConnEvent(raw, ev); });
}

// Per-request latency stamps use common/net.h MonoUs() — the same
// clock WorkerPool and the loop-lag hook measure with, so queue-wait
// subtractions across producers can never mix clock sources.

void StorageServer::OffloadToDio(Conn* c, int spi, std::function<void()> work) {
  WorkerPool* pool = nullptr;
  if (!dio_pools_.empty()) {
    size_t i = (spi >= 0 && spi < static_cast<int>(dio_pools_.size()))
                   ? static_cast<size_t>(spi) : 0;
    pool = dio_pools_[i].get();
  }
  if (pool == nullptr) {  // degraded: run inline (still correct)
    work();
    return;
  }
  c->async_pending = true;
  c->work_start_us = MonoUs();  // dio-stage begin (access log AND spans)
  EventLoop* loop = ConnLoop(c);
  // Drop the fd from epoll while a worker owns the request: with
  // level-triggered epoll a readable/HUP'd socket would otherwise
  // re-fire every wait and spin this nio thread for the whole job.
  loop->Del(c->fd);
  pool->Submit([this, c, loop, work = std::move(work)] {
    // Worker context: `work` may Respond()/RespondError() — both only
    // BUILD the response while async_pending is set; the socket and
    // epoll are touched exclusively from the loop thread below.
    // Queue-wait stamp: time between submit (work_start_us) and this
    // pickup is saturation, not service — traced requests surface it as
    // a dio.queue_wait child span (the conn is worker-owned while
    // async_pending, so writing the field here is race-free).  Floor of
    // 1µs: an idle pool can pick up within the clock tick, and a 0
    // would suppress the child span — the timeline should always show
    // the wait stage, even when it reads "~0".
    c->dio_wait_us = std::max<int64_t>(MonoUs() - c->work_start_us, 1);
    work();
    loop->Post([this, c, loop] {
      c->async_pending = false;
      if (c->dead) {  // closed while the worker ran
        auto& z = c->owner->zombies;
        for (auto it = z.begin(); it != z.end(); ++it) {
          if (it->get() == c) {
            z.erase(it);
            break;
          }
        }
        return;
      }
      loop->Add(c->fd, EPOLLIN, [this, c](uint32_t ev) { OnConnEvent(c, ev); });
      if (c->state == ConnState::kSend)
        WriteConn(c);   // flush the prepared response
      else
        ReadConn(c);    // e.g. RespondError flipped to drain mode
    });
  });
}

void StorageServer::OnConnEvent(Conn* c, uint32_t events) {
  // While a dio worker owns the request, the loop must not touch the
  // conn — not even for HUP (the worker would race a CloseConn); a dead
  // peer is discovered when the response flush fails.
  if (c->async_pending) return;
  if (events & (EPOLLHUP | EPOLLERR)) {
    CloseConn(c);
    return;
  }
  if (events & EPOLLOUT) {
    if (!WriteConn(c)) return;
  }
  if (events & EPOLLIN) ReadConn(c);
}

void StorageServer::CloseConn(Conn* c) {
  // Identity check FIRST: a hypothetical double-CloseConn after the fd
  // was reused by a new conn must not close the stranger's fd or
  // double-decrement the counter.
  auto& conns = c->owner->conns;
  auto it = conns.find(c->fd);
  if (it == conns.end() || it->second.get() != c) return;
  AbortFileOp(c);  // disconnect mid-op: same rollback as an explicit error
  // Mid-request death: the admitted bytes never reached LogAccess —
  // release them here or the in-flight ledger leaks upward forever.
  if (c->inflight_acct != 0) {
    inflight_bytes_.fetch_sub(c->inflight_acct, std::memory_order_relaxed);
    c->inflight_acct = 0;
  }
  if (c->send_fd >= 0) close(c->send_fd);
  c->rstream.reset();
  int fd = c->fd;
  ConnLoop(c)->Del(fd);
  close(fd);
  conn_count_--;
  c->owner->live_conns.fetch_sub(1, std::memory_order_relaxed);
  if (c->async_pending) {
    // A dio worker still references this conn: keep the object alive as
    // a zombie until its completion callback reaps it.
    c->dead = true;
    c->fd = -1;
    c->owner->zombies.push_back(std::move(it->second));
  }
  conns.erase(it);
}

void StorageServer::ResetForNextRequest(Conn* c) {
  ReleaseBusy(c);  // normally already released; guards every exit path
  c->state = ConnState::kRecvHeader;
  c->header_got = 0;
  c->fixed.clear();
  c->fixed_need = 0;
  c->pkg_len = 0;
  c->cmd = 0;
  c->body_consumed = 0;
  c->close_after_send = false;
  c->file_fd = -1;
  c->tmp_path.clear();
  c->file_remaining = 0;
  c->file_size = 0;
  c->ext.clear();
  c->hashing = false;
  c->replica_op = 0;
  c->sync_remote.clear();
  c->range_offset = 0;
  c->slave_prefix.clear();
  c->discarding = false;
  c->pending_status = 0;
  c->pending_body.clear();
  c->priority = kPriorityUntagged;
  c->resolved_priority = 0;
  c->out.clear();
  c->out_off = 0;
  c->send_fd = -1;
  c->send_off = 0;
  c->send_remaining = 0;
  c->rstream.reset();
  c->recv_done_us = 0;
  c->work_start_us = 0;
  c->fp_us = 0;
  c->fp_lock_us = 0;
  c->cswrite_us = 0;
  c->binlog_us = 0;
  c->ingest_session = 0;
  c->ingest_chunks_total = 0;
  c->ingest_chunks_missing = 0;
  c->dio_wait_us = 0;
  c->trace_ctx = TraceCtx{};
  c->traced = false;
  c->trace_span = 0;
  // Bounded buffer budget (the other half of fast_task_queue's pooled
  // buffers): a request with an unusually large in-memory body or
  // response must not pin that capacity for the connection's lifetime —
  // max_connections × retained buffers is the daemon's memory bound.
  const size_t budget = static_cast<size_t>(cfg_.buff_size);
  if (c->fixed.capacity() > budget) std::string().swap(c->fixed);
  if (c->out.capacity() > budget) std::string().swap(c->out);
}

bool StorageServer::AcquireBusy(Conn* c, const std::string& remote) {
  std::lock_guard<RankedMutex> lk(busy_mu_);
  if (busy_files_.count(remote)) return false;
  busy_files_.insert(remote);
  c->busy_key = remote;
  return true;
}

void StorageServer::ReleaseBusy(Conn* c) {
  if (!c->busy_key.empty()) {
    std::lock_guard<RankedMutex> lk(busy_mu_);
    busy_files_.erase(c->busy_key);
    c->busy_key.clear();
  }
}

void StorageServer::AbortFileOp(Conn* c) {
  // Failure/abort cleanup for any in-flight file write.  In-place range
  // writes (append/modify, no tmp file) roll back appends by truncating to
  // the pre-op size so a retry or replica replay never sees partial bytes;
  // a partial modify rewrites existing content and has no undo, but the
  // binlog record is only emitted on success, so replicas stay on the old
  // content either way.
  if (c->file_fd >= 0) {
    if (c->tmp_path.empty()) {
      auto cmd = static_cast<StorageCmd>(c->cmd);
      if (cmd == StorageCmd::kAppendFile || cmd == StorageCmd::kSyncAppendFile)
        ftruncate(c->file_fd, c->range_offset);
    }
    close(c->file_fd);
    c->file_fd = -1;
    if (!c->tmp_path.empty()) {
      unlink(c->tmp_path.c_str());
      c->tmp_path.clear();
    }
  }
  ReleaseBusy(c);
}

void StorageServer::RespondError(Conn* c, uint8_t status) {
  // An early error can leave unread request bytes on the socket; a keepalive
  // reuse would parse them as the next header.  Drain and discard them, then
  // send the error — the connection stays usable (the reference's client
  // pool would otherwise have to reconnect after every rejected request).
  AbortFileOp(c);
  if (c->body_consumed >= c->pkg_len) {
    Respond(c, status);
    return;
  }
  c->discarding = true;
  c->pending_status = status;
  c->file_remaining = c->pkg_len - c->body_consumed;
  c->state = ConnState::kRecvFile;
}

void StorageServer::ShedRequest(Conn* c, int64_t retry_after_ms) {
  // Admission shed: EBUSY + an 8-byte BE retry-after-ms hint the client
  // honors with jittered backoff.  Same drain discipline as
  // RespondError (the connection stays usable — a shed must not force
  // a reconnect, which would ADD load under overload), but the hint
  // body has to survive the drain, hence pending_body.
  AbortFileOp(c);
  c->shed_resp = true;
  std::string hint(8, '\0');
  PutInt64BE(retry_after_ms, reinterpret_cast<uint8_t*>(hint.data()));
  if (c->body_consumed >= c->pkg_len) {
    Respond(c, 16 /*EBUSY*/, hint);
    return;
  }
  c->discarding = true;
  c->pending_status = 16;
  c->pending_body = std::move(hint);
  c->file_remaining = c->pkg_len - c->body_consumed;
  c->state = ConnState::kRecvFile;
}

void StorageServer::Respond(Conn* c, uint8_t status, const std::string& body) {
  LogAccess(c, status, static_cast<int64_t>(body.size()));
  c->out.resize(kHeaderSize);
  PutInt64BE(static_cast<int64_t>(body.size()),
             reinterpret_cast<uint8_t*>(c->out.data()));
  c->out[8] = static_cast<char>(StorageCmd::kResp);
  c->out[9] = static_cast<char>(status);
  c->out += body;
  c->out_off = 0;
  c->state = ConnState::kSend;
  // From a dio worker this only stages the response; the completion
  // callback flushes it on the loop thread.
  if (!c->async_pending) WriteConn(c);
}

void StorageServer::NoteHeat(Conn* c, HeatOp op, const std::string& key) {
  if (heat_ == nullptr) return;
  c->heat_key = key;
  c->heat_op = static_cast<uint8_t>(op);
}

void StorageServer::LogAccess(Conn* c, uint8_t status, int64_t bytes) {
  if (c->req_start_us == 0) return;  // one accounting pass per request
  // The request is answered: its bytes leave the admission in-flight
  // ledger (zeroing the field makes the subtract single-shot even if a
  // CloseConn follows).
  if (c->inflight_acct != 0) {
    inflight_bytes_.fetch_sub(c->inflight_acct, std::memory_order_relaxed);
    c->inflight_acct = 0;
  }
  int64_t now_us = MonoUs();
  // Heat telemetry: one Touch per request at the accounting choke point
  // (handlers that resolved a file-id stamped heat_key).  Uploads
  // attribute logical payload bytes; downloads/fetches the bytes served.
  if (heat_ != nullptr && !c->heat_key.empty()) {
    HeatOp hop = static_cast<HeatOp>(c->heat_op);
    int64_t hb = 0;
    if (status == 0)
      hb = hop == HeatOp::kUpload ? c->file_size : (bytes > 0 ? bytes : 0);
    heat_->Touch(c->heat_key, hop, hb, status != 0);
  }
  // Registry side (always on): per-opcode count/error/latency plus the
  // transfer-size histograms.  Handles are pre-registered atomics —
  // callable from nio loops and dio workers alike.
  // Shed requests stay out of the op stats entirely: the SLO engine
  // reads error_rate_pct / request_p99_ms off these counters, and a
  // ladder whose refusals raise the very breach that feeds its
  // pressure score would latch itself tight (the admission gauges
  // already count every shed).  The access log below still records
  // them for forensics.
  const OpStats& os = op_stats_[c->cmd];
  if (os.count != nullptr && !c->shed_resp) {
    os.count->fetch_add(1, std::memory_order_relaxed);
    if (status != 0) os.errors->fetch_add(1, std::memory_order_relaxed);
    os.latency_us->Observe(now_us - c->req_start_us);
  }
  switch (static_cast<StorageCmd>(c->cmd)) {
    case StorageCmd::kUploadFile:
    case StorageCmd::kUploadAppenderFile:
    case StorageCmd::kUploadSlaveFile:
    case StorageCmd::kUploadChunks:  // file_size = logical, not wire bytes
      if (status == 0 && hist_upload_bytes_ != nullptr)
        hist_upload_bytes_->Observe(c->file_size);
      break;
    case StorageCmd::kDownloadFile:
      if (status == 0 && hist_download_bytes_ != nullptr)
        hist_download_bytes_->Observe(bytes);
      break;
    default:
      break;
  }
  if (access_log_ != nullptr) {
    std::lock_guard<RankedMutex> lk(log_mu_);
    // "<epoch.sec> <client_ip> <cmd> <status> <bytes> <cost_us>
    //  <recv_us> <work_us> <fp_us> <fp_lock_us> <cswrite_us> <binlog_us>
    //  <req_bytes>" — per-stage split (SURVEY.md §5): recv = body receive
    // window, work = dio-stage time, then the chunked-upload splits
    // inside the work window (fingerprint wall, its sidecar-lock-wait
    // share, chunk-store writes, binlog append); req_bytes = request body
    // size (wire accounting — e.g. chunk-aware replication's savings show
    // up here).  Columns are 0 when a stage did not occur;
    // tools/access_log_stages.py aggregates them into the bench stage
    // table.
    int64_t recv_us =
        c->recv_done_us > 0 ? c->recv_done_us - c->req_start_us : 0;
    int64_t work_us =
        c->work_start_us > 0 ? now_us - c->work_start_us : 0;
    fprintf(access_log_,
            "%lld %s %d %d %lld %lld %lld %lld %lld %lld %lld %lld %lld\n",
            static_cast<long long>(time(nullptr)), c->peer_ip.c_str(), c->cmd,
            status, static_cast<long long>(bytes),
            static_cast<long long>(now_us - c->req_start_us),
            static_cast<long long>(recv_us),
            static_cast<long long>(work_us),
            static_cast<long long>(c->fp_us),
            static_cast<long long>(c->fp_lock_us),
            static_cast<long long>(c->cswrite_us),
            static_cast<long long>(c->binlog_us),
            static_cast<long long>(c->pkg_len));
  }
  // Spans AFTER the column line: the slow gate's immediate fflush then
  // pushes this request's own access-log record out with the JSON line
  // (a slow-flush that precedes the column write would publish a log in
  // which the slow request has no parseable column row — observed as a
  // fast-host race in the slow-gate integration test).
  RecordRequestSpans(c, status, now_us, bytes);
  c->req_start_us = 0;  // one line per request
  c->recv_done_us = 0;
  c->work_start_us = 0;
  c->dio_wait_us = 0;
  c->fp_us = 0;
  c->fp_lock_us = 0;
  c->cswrite_us = 0;
  c->binlog_us = 0;
  c->heat_key.clear();
  c->heat_op = 0;
}

void StorageServer::RecordRequestSpans(Conn* c, uint8_t status,
                                       int64_t now_us, int64_t bytes) {
  if (trace_ == nullptr) return;
  int64_t total_us = now_us - c->req_start_us;
  int64_t slow_us = cfg_.slow_request_threshold_ms * 1000;
  bool slow = slow_us > 0 && total_us >= slow_us;
  if (!c->traced && !slow) return;

  // Spans are stamped on the wall clock (cross-node stitching needs one
  // clock domain); stage offsets come from the monotonic stamps the
  // access log already keeps, anchored to the request's wall start.
  int64_t wall_start = TraceWallUs() - total_us;
  TraceSpan root;
  root.trace_id = c->traced ? c->trace_ctx.trace_id : trace_->NewTraceId();
  root.span_id = c->trace_span != 0 ? c->trace_span : trace_->NextSpanId();
  root.parent_id = c->traced ? c->trace_ctx.parent_span : 0;
  root.start_us = wall_start;
  root.dur_us = total_us;
  root.status = status;
  root.flags =
      (c->traced ? c->trace_ctx.flags : 0) | (slow ? kTraceFlagSlow : 0);
  const char* opname =
      op_names_[c->cmd] != nullptr ? op_names_[c->cmd] : "unknown";
  char full[sizeof(root.name)];
  std::snprintf(full, sizeof(full), "storage.%s", opname);
  root.SetName(full);
  trace_->Record(root);

  auto child = [&](const char* name, int64_t start, int64_t dur) {
    if (dur <= 0) return;
    TraceSpan s;
    s.trace_id = root.trace_id;
    s.span_id = trace_->NextSpanId();
    s.parent_id = root.span_id;
    s.start_us = start;
    s.dur_us = dur;
    s.flags = root.flags;
    s.SetName(name);
    trace_->Record(s);
  };
  // recv = body receive window; the dio work window then decomposes into
  // queue wait -> fingerprint -> chunk-store writes -> binlog
  // (sequential in the handler, so their spans are laid out
  // back-to-back).  dio.queue_wait is WAITING, not working — the span
  // that makes a saturated dio pool visible on an fdfs_trace timeline.
  int64_t recv_us =
      c->recv_done_us > 0 ? c->recv_done_us - c->req_start_us : 0;
  child("storage.recv", wall_start, recv_us);
  int64_t work_wall = wall_start + (c->work_start_us > 0
                                        ? c->work_start_us - c->req_start_us
                                        : recv_us);
  child("dio.queue_wait", work_wall, c->dio_wait_us);
  int64_t stage_wall = work_wall + c->dio_wait_us;
  child("storage.fingerprint", stage_wall, c->fp_us);
  child("storage.cs_write", stage_wall + c->fp_us, c->cswrite_us);
  child("storage.binlog", stage_wall + c->fp_us + c->cswrite_us,
        c->binlog_us);
  if (c->ingest_chunks_total > 0) {
    // Negotiated-upload annotation: how much of the recipe actually
    // crossed the wire (missing/total), spanning the request's work
    // window so the timeline shows the split alongside the stages.
    char ann[sizeof(TraceSpan{}.name)];
    std::snprintf(ann, sizeof(ann), "ingest.chunks %lld/%lld",
                  static_cast<long long>(c->ingest_chunks_missing),
                  static_cast<long long>(c->ingest_chunks_total));
    child(ann, work_wall,
          c->work_start_us > 0 ? now_us - c->work_start_us : total_us);
  }

  if (slow) {
    slow_request_count_.fetch_add(1, std::memory_order_relaxed);
    if (events_ != nullptr)
      events_->Record(EventSeverity::kWarn, "request.slow", root.name,
                      "peer=" + c->peer_ip +
                          " dur_us=" + std::to_string(total_us) +
                          " status=" + std::to_string(status));
    std::string line =
        SlowRequestJson("storage", root.name, root, c->peer_ip, bytes);
    FDFS_LOG_WARN("%s", line.c_str());
    if (access_log_ != nullptr) {
      // One compact-JSON line amid the space-separated records: the
      // plain column parser skips it, access_log_stages --slow reads it.
      // Flushed immediately — slow requests are rare and the line is
      // an operator signal, not bulk logging.
      std::lock_guard<RankedMutex> lk(log_mu_);
      fprintf(access_log_, "%s\n", line.c_str());
      fflush(access_log_);
    }
  }
}

void StorageServer::NoteTracedMutation(Conn* c, const std::string& remote) {
  if (!c->traced || trace_ == nullptr) return;
  TraceCtx ctx;
  ctx.trace_id = c->trace_ctx.trace_id;
  ctx.parent_span = c->trace_span;  // sync.ship nests under this request
  ctx.flags = c->trace_ctx.flags;
  trace_corr_.Put(remote, ctx);
}

void StorageServer::RespondFile(Conn* c, uint8_t status, int file_fd,
                                int64_t offset, int64_t count) {
  LogAccess(c, status, count);
  c->out.resize(kHeaderSize);
  PutInt64BE(count, reinterpret_cast<uint8_t*>(c->out.data()));
  c->out[8] = static_cast<char>(StorageCmd::kResp);
  c->out[9] = static_cast<char>(status);
  c->out_off = 0;
  c->send_fd = file_fd;
  c->send_off = offset;
  c->send_remaining = count;
  c->state = ConnState::kSend;
  if (!c->async_pending) WriteConn(c);
}

bool StorageServer::WriteConn(Conn* c) {
  for (;;) {
    // 1) buffered bytes
    while (c->out_off < c->out.size()) {
      ssize_t n = send(c->fd, c->out.data() + c->out_off,
                       c->out.size() - c->out_off, MSG_NOSIGNAL);
      if (n > 0) {
        c->out_off += static_cast<size_t>(n);
        continue;
      }
      if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        ConnLoop(c)->Mod(c->fd, EPOLLIN | EPOLLOUT);
        return true;
      }
      if (n < 0 && errno == EINTR) continue;
      CloseConn(c);
      return false;
    }
    // 2) file payload via sendfile
    while (c->send_remaining > 0) {
      off_t off = c->send_off;
      size_t chunk = static_cast<size_t>(
          std::min<int64_t>(c->send_remaining, 1 << 20));
      ssize_t n = sendfile(c->fd, c->send_fd, &off, chunk);
      if (n > 0) {
        c->send_off = off;
        c->send_remaining -= n;
        stats_.bytes_downloaded += n;
        continue;
      }
      if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        ConnLoop(c)->Mod(c->fd, EPOLLIN | EPOLLOUT);
        return true;
      }
      if (n < 0 && errno == EINTR) continue;
      CloseConn(c);
      return false;
    }
    // 3) recipe stream, scatter-gather (PR 5): flush the staged span
    // batch via sendmsg, then refill — cache-hit spans reference the
    // chunk store's shared LRU buffers (zero redundant copies), cold
    // spans pread into the stream's pooled buffer.  A multi-GB chunked
    // download never occupies more than one batch of memory and never
    // stalls this loop's other connections (reference: storage_dio.c
    // reads; VERDICT r2 weak #5).
    if (c->rstream != nullptr) {
      RecipeStream* rs = c->rstream.get();
      if (rs->HasPending()) {
        switch (FlushRecipeSpans(c, rs)) {
          case FlushResult::kBlocked:
            ConnLoop(c)->Mod(c->fd, EPOLLIN | EPOLLOUT);
            return true;
          case FlushResult::kError:
            CloseConn(c);
            return false;
          case FlushResult::kDone:
            break;
        }
      }
      if (rs->remaining > 0) {
        if (!RefillRecipeSpans(rs)) {
          CloseConn(c);  // header already sent; abort is the only option
          return false;
        }
        continue;  // flush what we just staged
      }
    }
    break;
  }
  if (c->state == ConnState::kSend) {
    if (c->send_fd >= 0) {
      close(c->send_fd);
      c->send_fd = -1;
    }
    c->rstream.reset();
    if (c->close_after_send) {
      CloseConn(c);
      return false;
    }
    ConnLoop(c)->Mod(c->fd, EPOLLIN);
    ResetForNextRequest(c);
  }
  return true;
}

bool StorageServer::RefillRecipeSpans(RecipeStream* rs) {
  // One round stages up to kBatchBytes across up to kMaxSpans spans —
  // enough to amortize the sendmsg syscall, small enough that a slow
  // client never parks more than ~1 MB per connection (an 8 MB chunk is
  // staged one bounded slice per round; the cache holds the whole chunk
  // so later rounds hit).  Cold spans pread into the pooled buffer,
  // which is sized ONCE per round before any span references it.
  constexpr int64_t kBatchBytes = 1 << 20;
  constexpr size_t kMaxSpans = 64;
  rs->spans.clear();
  rs->span_idx = 0;
  rs->span_off = 0;
  struct ColdRead {
    size_t span;      // index into rs->spans
    size_t entry;     // index into rs->recipe.chunks
    int64_t file_off; // offset inside the chunk payload
  };
  ColdRead cold[kMaxSpans];
  size_t n_cold = 0;
  int64_t staged = 0;
  size_t pool_bytes = 0;
  while (rs->remaining - staged > 0 && rs->spans.size() < kMaxSpans &&
         staged < kBatchBytes) {
    if (rs->idx >= rs->recipe.chunks.size()) {
      FDFS_LOG_ERROR("recipe exhausted with %lld bytes unsent",
                     static_cast<long long>(rs->remaining - staged));
      return false;
    }
    const RecipeEntry& e = rs->recipe.chunks[rs->idx];
    int64_t avail = e.length - rs->skip;
    if (avail <= 0) {  // zero-length or fully-skipped entry
      rs->idx++;
      rs->skip = 0;
      continue;
    }
    int64_t take = std::min(
        {avail, rs->remaining - staged, kBatchBytes - staged});
    RecipeStream::Span sp;
    // Cache path only for chunks that can actually LIVE in the cache:
    // a chunk bigger than the whole cache would be re-read IN FULL on
    // every staging round (the insert is always rejected), so it takes
    // the pooled pread-slice path like the cache-off case.
    std::shared_ptr<const std::string> buf;
    if (rs->cs->cache_enabled() &&
        e.length <= rs->cs->cache_capacity_bytes()) {
      bool hit = false;
      buf = rs->cs->ReadChunkCached(e.digest_hex, e.length, &hit);
      if (buf == nullptr) {
        // Unreadable (missing/short/jailed) — abort the stream.
        FDFS_LOG_ERROR("missing chunk %s mid-download",
                       e.digest_hex.c_str());
        return false;
      }
    }
    if (buf != nullptr) {
      sp.owner = std::move(buf);
      sp.off = static_cast<size_t>(rs->skip);
      sp.len = static_cast<size_t>(take);
    } else {
      sp.off = pool_bytes;
      sp.len = static_cast<size_t>(take);
      cold[n_cold++] = ColdRead{rs->spans.size(), rs->idx, rs->skip};
      pool_bytes += static_cast<size_t>(take);
    }
    rs->spans.push_back(std::move(sp));
    staged += take;
    if (take == avail) {
      rs->idx++;
      rs->skip = 0;
    } else {
      rs->skip += take;  // bounded mid-chunk stop; resume next round
    }
  }
  // The pool is final-sized before any cold read, so span offsets into
  // it stay valid for the whole round.  The whole cold set goes down as
  // ONE batched call: slab-resident spans coalesce into preadv runs
  // (one syscall per contiguous slab extent) instead of one pread per
  // span (ISSUE 18).
  rs->pool.resize(pool_bytes);
  if (n_cold > 0) {
    ChunkStore::SliceReq creqs[kMaxSpans];
    for (size_t i = 0; i < n_cold; ++i) {
      const RecipeEntry& e = rs->recipe.chunks[cold[i].entry];
      RecipeStream::Span& sp = rs->spans[cold[i].span];
      creqs[i] = ChunkStore::SliceReq{&e.digest_hex, cold[i].file_off,
                                      static_cast<int64_t>(sp.len),
                                      rs->pool.data() + sp.off};
    }
    int64_t batches = 0, vec_spans = 0;
    std::string failed;
    bool read_ok =
        rs->cs->ReadChunkSlices(creqs, n_cold, &batches, &vec_spans, &failed);
    if (batches > 0) {
      ctr_dio_preadv_batches_->fetch_add(batches, std::memory_order_relaxed);
      ctr_dio_preadv_spans_->fetch_add(vec_spans, std::memory_order_relaxed);
    }
    if (!read_ok) {
      FDFS_LOG_ERROR("missing chunk %s mid-download", failed.c_str());
      return false;
    }
  }
  rs->remaining -= staged;
  stats_.bytes_downloaded += staged;
  return true;
}

StorageServer::FlushResult StorageServer::FlushRecipeSpans(
    Conn* c, RecipeStream* rs) {
  while (rs->HasPending()) {
    struct iovec iov[64];
    size_t n = 0;
    size_t first_off = rs->span_off;
    for (size_t i = rs->span_idx;
         i < rs->spans.size() && n < sizeof(iov) / sizeof(iov[0]); ++i) {
      const RecipeStream::Span& sp = rs->spans[i];
      const char* base = sp.owner != nullptr ? sp.owner->data() + sp.off
                                             : rs->pool.data() + sp.off;
      iov[n].iov_base = const_cast<char*>(base + first_off);
      iov[n].iov_len = sp.len - first_off;
      first_off = 0;
      ++n;
    }
    struct msghdr msg = {};
    msg.msg_iov = iov;
    msg.msg_iovlen = n;
    ssize_t sent = sendmsg(c->fd, &msg, MSG_NOSIGNAL);
    if (sent <= 0) {
      if (sent < 0 && (errno == EAGAIN || errno == EWOULDBLOCK))
        return FlushResult::kBlocked;
      if (sent < 0 && errno == EINTR) continue;
      return FlushResult::kError;
    }
    size_t left = static_cast<size_t>(sent);
    while (left > 0) {
      RecipeStream::Span& sp = rs->spans[rs->span_idx];
      size_t span_left = sp.len - rs->span_off;
      if (left < span_left) {
        rs->span_off += left;
        left = 0;
      } else {
        left -= span_left;
        sp.owner.reset();  // release the cache ref as soon as it's sent
        rs->span_idx++;
        rs->span_off = 0;
      }
    }
  }
  return FlushResult::kDone;
}

void StorageServer::ReadConn(Conn* c) {
  char buf[kIoBufSize];
  const int fd = c->fd;
  // The owning NioThread outlives every conn; grab the map while `c` is
  // certainly alive (handlers below may free it).
  auto& conns = c->owner->conns;
  for (;;) {
    // Handlers (OnHeaderComplete/OnFixedComplete/OnFileComplete and the
    // Respond path) may CloseConn() and free *c — re-check liveness before
    // every state-machine step.
    auto alive = conns.find(fd);
    if (alive == conns.end() || alive->second.get() != c) return;
    if (c->async_pending) return;  // a dio worker owns this request now
    switch (c->state) {
      case ConnState::kRecvHeader: {
        ssize_t n = recv(c->fd, c->header + c->header_got,
                         kHeaderSize - c->header_got, 0);
        if (n == 0) {
          CloseConn(c);
          return;
        }
        if (n < 0) {
          if (errno == EAGAIN || errno == EWOULDBLOCK) return;
          if (errno == EINTR) continue;
          CloseConn(c);
          return;
        }
        c->header_got += static_cast<size_t>(n);
        if (c->header_got == static_cast<size_t>(kHeaderSize))
          OnHeaderComplete(c);
        break;
      }
      case ConnState::kRecvFixed: {
        size_t want = c->fixed_need - c->fixed.size();
        ssize_t n = recv(c->fd, buf, std::min(want, sizeof(buf)), 0);
        if (n == 0) {
          CloseConn(c);
          return;
        }
        if (n < 0) {
          if (errno == EAGAIN || errno == EWOULDBLOCK) return;
          if (errno == EINTR) continue;
          CloseConn(c);
          return;
        }
        c->fixed.append(buf, static_cast<size_t>(n));
        c->body_consumed += n;
        if (c->fixed.size() == c->fixed_need) OnFixedComplete(c);
        break;
      }
      case ConnState::kRecvFile: {
        size_t want = static_cast<size_t>(
            std::min<int64_t>(c->file_remaining, sizeof(buf)));
        ssize_t n = recv(c->fd, buf, want, 0);
        if (n == 0) {
          CloseConn(c);
          return;
        }
        if (n < 0) {
          if (errno == EAGAIN || errno == EWOULDBLOCK) return;
          if (errno == EINTR) continue;
          CloseConn(c);
          return;
        }
        // Account before any failure handling: these bytes left the socket,
        // so a drain triggered below must not wait for them again.
        c->file_remaining -= n;
        c->body_consumed += n;
        if (!c->discarding) {
          if (c->hashing) {
            c->sha1.Update(buf, static_cast<size_t>(n));
          }
          c->crc32 = Crc32(buf, static_cast<size_t>(n), c->crc32);
          ssize_t w = write(c->file_fd, buf, static_cast<size_t>(n));
          if (w != n) {
            FDFS_LOG_ERROR("tmp write failed: %s", strerror(errno));
            RespondError(c, static_cast<uint8_t>(5 /*EIO*/));
            // RespondError flips to drain mode unless the body is already
            // fully consumed, in which case it responded and reset.
            continue;
          }
          stats_.bytes_uploaded += n;
        }
        if (c->file_remaining == 0) {
          OnFileComplete(c);
          // Response path (or a dio worker) takes over; stop reading
          // until reset.  async_pending MUST be tested first: once the
          // job is submitted a worker may already be writing c->state,
          // and only the flag is loop-thread-owned.
          if (c->async_pending || c->state == ConnState::kSend) return;
        }
        break;
      }
      case ConnState::kSend:
        return;  // not reading while a response is in flight
    }
  }
}

// -- dispatch -------------------------------------------------------------

void StorageServer::OnHeaderComplete(Conn* c) {
  c->pkg_len = GetInt64BE(c->header);
  c->cmd = c->header[8];
  // Monotonic clock (a wall-clock/NTP step mid-request would log
  // negative latencies).  Always stamped: the stats registry's
  // per-opcode latency histograms run even without the access log.
  c->req_start_us = MonoUs();
  c->shed_resp = false;
  if (c->peer_ip.empty()) c->peer_ip = PeerIp(c->fd);
  if (c->pkg_len < 0) {
    FDFS_LOG_WARN("negative pkg_len from %s", PeerIp(c->fd).c_str());
    CloseConn(c);
    return;
  }
  auto cmd = static_cast<StorageCmd>(c->cmd);
  // Admission consult (ISSUE 19) at the header stage — before any body
  // byte is read, so a shed request costs one drain, not one disk op.
  // Prefix frames (TRACE_CTX / PRIORITY) carry metadata for the NEXT
  // request and are never consulted themselves.  The class comes from a
  // PRIORITY frame when one preceded this header (consumed here) or the
  // opcode-class table; CONTROL survives every ladder rung, so the
  // observability plane stays reachable during the overload it exists
  // to diagnose.
  if (cmd != StorageCmd::kTraceCtx && cmd != StorageCmd::kPriority) {
    uint8_t cls = c->priority != kPriorityUntagged
                      ? c->priority
                      : DefaultPriorityClass(c->cmd);
    c->priority = kPriorityUntagged;  // one frame tags one request
    if (cls > kPriorityBackground) cls = kPriorityBackground;
    c->resolved_priority = cls;
    int64_t retry_ms = 0;
    if (!admission_->AdmitOrShed(cls, &retry_ms)) {
      ShedRequest(c, retry_ms);
      return;
    }
    // Admitted: this request's declared bytes join the in-flight ledger
    // (a pressure signal — bytes accepted but not yet answered).
    c->inflight_acct = c->pkg_len;
    inflight_bytes_.fetch_add(c->inflight_acct, std::memory_order_relaxed);
  }
  switch (cmd) {
    case StorageCmd::kActiveTest:
      if (c->pkg_len != 0) {
        CloseConn(c);
        return;
      }
      Respond(c, 0);
      return;
    case StorageCmd::kStat:
      // Observability dump: empty body -> registry JSON snapshot.
      if (c->pkg_len != 0) {
        CloseConn(c);
        return;
      }
      Respond(c, 0, BuildStatsJson());
      return;
    case StorageCmd::kTraceDump:
      // Span ring dump: empty body -> {"role","port","spans":[...]}.
      if (c->pkg_len != 0) {
        CloseConn(c);
        return;
      }
      Respond(c, 0, trace_->Json("storage", cfg_.port));
      return;
    case StorageCmd::kEventDump:
      // Flight-recorder dump: empty body -> {"role","port","events":[...]}
      // (fastdfs_tpu.monitor.decode_events; fdfs_codec event-json golden).
      if (c->pkg_len != 0) {
        CloseConn(c);
        return;
      }
      Respond(c, 0, events_->Json("storage", cfg_.port));
      return;
    case StorageCmd::kMetricsHistory:
      // Metrics-journal window dump: empty body = everything retained,
      // 8B body = since-ts (epoch µs).  ENOTSUP when journaling is off
      // (metrics_journal_mb = 0) so callers can tell "no journal" from
      // "no history yet".
      if (c->pkg_len != 0 && c->pkg_len != 8) {
        CloseConn(c);
        return;
      }
      if (metrics_ == nullptr) {
        RespondError(c, 95 /*ENOTSUP*/);
        return;
      }
      if (c->pkg_len == 0) {
        // Reading + delta-decoding up to the whole journal ring is file
        // I/O plus CPU that scales with metrics_journal_mb — run it on
        // the dio pool, not this nio loop (a post-mortem query must not
        // itself spike nio.loop_lag_us).
        OffloadToDio(c, 0, [this, c] {
          Respond(c, 0, metrics_->DumpJson("storage", cfg_.port, 0));
        });
        return;
      }
      c->fixed_need = 8;
      c->state = ConnState::kRecvFixed;
      return;
    case StorageCmd::kHeatTop:
      // Hot-key top-K dump: empty body = the daemon's heat_top_k,
      // 8B body = explicit k.  ENOTSUP when the sketch is off.
      if (c->pkg_len != 0 && c->pkg_len != 8) {
        CloseConn(c);
        return;
      }
      if (heat_ == nullptr) {
        RespondError(c, 95 /*ENOTSUP*/);
        return;
      }
      if (c->pkg_len == 0) {
        Respond(c, 0, heat_->TopJson("storage", cfg_.port, cfg_.heat_top_k));
        return;
      }
      c->fixed_need = 8;
      c->state = ConnState::kRecvFixed;
      return;
    case StorageCmd::kProfileCtl:
      // Profiler control: 17B fixed body = 1B action (1=start, 0=stop)
      // + 8B BE hz + 8B BE duration seconds (protocol.py PROFILE_CTL).
      if (c->pkg_len != 17) {
        CloseConn(c);
        return;
      }
      c->fixed_need = 17;
      c->state = ConnState::kRecvFixed;
      return;
    case StorageCmd::kProfileDump:
      // Folded-stack dump: empty body -> JSON (monitor.decode_profile;
      // fdfs_codec profile-json golden).  Aggregation + symbolization
      // walk the whole slab and malloc per frame, so run on the dio
      // pool, not this nio loop (the metrics-history discipline).
      // ENOTSUP while no capture was ever started.
      if (c->pkg_len != 0) {
        CloseConn(c);
        return;
      }
      if (!Profiler::Global().ever_started()) {
        RespondError(c, 95 /*ENOTSUP*/);
        return;
      }
      OffloadToDio(c, 0, [this, c] {
        std::string j;
        int rc = Profiler::Global().DumpJson("storage", cfg_.port, &j);
        if (rc != 0)
          RespondError(c, static_cast<uint8_t>(rc));
        else
          Respond(c, 0, j);
      });
      return;
    case StorageCmd::kHealthStatus:
      // Gray-failure health table: empty body -> JSON (peer EWMA rows +
      // disk probes + watchdog counts; monitor.decode_health_status;
      // fdfs_codec health-status golden).  One bounded-size snapshot
      // under the health mutex — fine on the nio loop.
      if (c->pkg_len != 0) {
        CloseConn(c);
        return;
      }
      Respond(c, 0, HealthStatusJson());
      return;
    case StorageCmd::kScrubStatus: {
      // Integrity-engine status: empty body -> kScrubStatCount BE int64
      // slots (kScrubStatNames).  Atomics + per-store gauge reads only,
      // so serving it on the nio loop is fine.  ENOTSUP without a chunk
      // store — there is nothing to scrub.
      if (c->pkg_len != 0) {
        CloseConn(c);
        return;
      }
      if (scrub_ == nullptr) {
        Respond(c, 95 /*ENOTSUP*/);
        return;
      }
      int64_t vals[kScrubStatCount] = {0};
      scrub_->FillStats(vals);
      std::string body(kScrubStatCount * 8, '\0');
      for (int i = 0; i < kScrubStatCount; ++i)
        PutInt64BE(vals[i], reinterpret_cast<uint8_t*>(body.data()) + i * 8);
      Respond(c, 0, body);
      return;
    }
    case StorageCmd::kScrubKick:
      // Force a verify+repair+GC pass (works even with periodic
      // scrubbing off).  The kick only flips a flag under the scrub
      // mutex — the pass itself runs on the scrub thread.
      if (c->pkg_len != 0) {
        CloseConn(c);
        return;
      }
      if (scrub_ == nullptr) {
        Respond(c, 95 /*ENOTSUP*/);
        return;
      }
      scrub_->Kick();
      Respond(c, 0);
      return;
    case StorageCmd::kEcStatus: {
      // Cold-tier status: empty body -> kEcStatCount BE int64 slots
      // (kEcStatNames).  ENOTSUP when the tier is off AND no drained
      // stripes exist — same shape as SCRUB_STATUS.
      if (c->pkg_len != 0) {
        CloseConn(c);
        return;
      }
      if (scrub_ == nullptr || (cfg_.ec_k <= 0 && scrub_->EcStatValue(0) == 0)) {
        Respond(c, 95 /*ENOTSUP*/);
        return;
      }
      int64_t vals[kEcStatCount] = {0};
      scrub_->FillEcStats(vals);
      std::string body(kEcStatCount * 8, '\0');
      for (int i = 0; i < kEcStatCount; ++i)
        PutInt64BE(vals[i], reinterpret_cast<uint8_t*>(body.data()) + i * 8);
      Respond(c, 0, body);
      return;
    }
    case StorageCmd::kEcKick:
      // Force a scrub pass whose demote stage ignores the age gate —
      // the operator's "drain the replicated tier NOW" lever.
      if (c->pkg_len != 0) {
        CloseConn(c);
        return;
      }
      if (scrub_ == nullptr || cfg_.ec_k <= 0) {
        Respond(c, 95 /*ENOTSUP*/);
        return;
      }
      scrub_->EcKick();
      Respond(c, 0);
      return;
    case StorageCmd::kTraceCtx:
      // Trace-context prefix frame: 16B body, NO response; the context
      // applies to the next request on this connection.  A wrong length
      // cannot be resynced mid-stream — close.
      if (c->pkg_len != kTraceCtxLen) {
        CloseConn(c);
        return;
      }
      c->fixed_need = static_cast<size_t>(kTraceCtxLen);
      c->state = ConnState::kRecvFixed;
      return;
    case StorageCmd::kPriority:
      // Priority prefix frame (the TRACE_CTX pattern): 1B class, NO
      // response; tags the next request on this connection.  A wrong
      // length cannot be resynced mid-stream — close.
      if (c->pkg_len != kPriorityFrameLen) {
        CloseConn(c);
        return;
      }
      c->fixed_need = static_cast<size_t>(kPriorityFrameLen);
      c->state = ConnState::kRecvFixed;
      return;
    case StorageCmd::kAdmissionStatus:
      // Admission-controller state dump: empty body -> JSON (ladder
      // level, pressure/EWMA, per-class shed counts;
      // monitor.decode_admission; fdfs_codec admission-json golden).
      if (c->pkg_len != 0) {
        CloseConn(c);
        return;
      }
      Respond(c, 0, admission_->StatusJson("storage", cfg_.port));
      return;
    case StorageCmd::kUploadFile:
    case StorageCmd::kUploadAppenderFile:
      stats_.total_upload++;
      // Placement drain (ISSUE 11): a draining group takes no NEW
      // files — EBUSY sends the client back to the tracker, which no
      // longer routes stores here.  Replication (kSync*) and the
      // rebalance migrator's loopback reads/deletes stay allowed.
      if (DrainingRefusal()) {
        RespondError(c, 16 /*EBUSY*/);
        return;
      }
      if (c->pkg_len < 15) {
        RespondError(c, 22 /*EINVAL*/);
        return;
      }
      c->fixed_need = 15;  // 1B spi + 8B size + 6B ext
      c->state = ConnState::kRecvFixed;
      return;
    case StorageCmd::kSyncCreateFile:
      c->fixed_need = 32;  // 16B group + 8B name_len + 8B size, then name
      break;
    case StorageCmd::kSyncCreateRecipe:
      // 16B group + 8B name_len + 8B logical + 8B chunk_count +
      // 8B payload_len, then name + chunk entries (inline), then the
      // missing-chunk payloads (streamed to a tmp file).
      c->fixed_need = 48;
      break;
    case StorageCmd::kSyncAppendFile:
    case StorageCmd::kSyncModifyFile:
      c->fixed_need = 40;  // 16B group + 8B name_len + 8B off + 8B len, name
      break;
    case StorageCmd::kUploadChunks:
      // Negotiated upload phase 2: 8B session + 8B payload_len, then the
      // missing-chunk payloads (streamed to a tmp file).
      stats_.total_upload++;
      c->fixed_need = 16;
      break;
    case StorageCmd::kAppendFile:
      stats_.total_append++;
      c->fixed_need = 32;  // 16B group + 8B name_len + 8B append_len, name
      break;
    case StorageCmd::kModifyFile:
      stats_.total_append++;
      c->fixed_need = 40;  // 16B group + 8B name_len + 8B off + 8B len, name
      break;
    case StorageCmd::kUploadSlaveFile:
      stats_.total_upload++;
      if (DrainingRefusal()) {  // drain: no new files (see kUploadFile)
        RespondError(c, 16 /*EBUSY*/);
        return;
      }
      // 16B group + 8B master_len + 8B size + 16B prefix + 6B ext, master
      c->fixed_need = 16 + 8 + 8 + 16 + 6;
      break;
    case StorageCmd::kDownloadFile:
    case StorageCmd::kDeleteFile:
    case StorageCmd::kQueryFileInfo:
    case StorageCmd::kNearDups:
    case StorageCmd::kSetMetadata:
    case StorageCmd::kGetMetadata:
    case StorageCmd::kSyncDeleteFile:
    case StorageCmd::kSyncCreateLink:
    case StorageCmd::kSyncUpdateFile:
    case StorageCmd::kSyncTruncateFile:
    case StorageCmd::kSyncQueryChunks:
    case StorageCmd::kFetchRecipe:
    case StorageCmd::kFetchChunk:
    case StorageCmd::kUploadRecipe:
    case StorageCmd::kTruncateFile:
    case StorageCmd::kCreateLink:
    case StorageCmd::kTrunkAllocSpace:
    case StorageCmd::kTrunkAllocConfirm:
    case StorageCmd::kTrunkFreeSpace:
    case StorageCmd::kFetchOnePathBinlog:
    case StorageCmd::kEcRelease:
      if (c->pkg_len > kMaxInlineBody) {
        CloseConn(c);
        return;
      }
      c->fixed_need = static_cast<size_t>(c->pkg_len);
      if (c->fixed_need == 0) {
        Respond(c, 22 /*EINVAL*/);
        return;
      }
      c->state = ConnState::kRecvFixed;
      return;
    default:
      FDFS_LOG_WARN("unknown cmd %d from %s", c->cmd, PeerIp(c->fd).c_str());
      RespondError(c, 22 /*EINVAL*/);
      return;
  }
  // Fixed-prefix commands that broke out of the switch: the declared body
  // must at least cover the fixed prefix, or the reader would swallow the
  // next pipelined request's header as fixed data (protocol desync).
  if (c->pkg_len < static_cast<int64_t>(c->fixed_need)) {
    RespondError(c, 22 /*EINVAL*/);
    return;
  }
  c->state = ConnState::kRecvFixed;
}

void StorageServer::OnFixedComplete(Conn* c) {
  auto cmd = static_cast<StorageCmd>(c->cmd);
  switch (cmd) {
    case StorageCmd::kTraceCtx: {
      // Stash the context and allocate the next request's root span id
      // (mutation paths correlate through it before LogAccess records
      // the span).  Minimal reset — NOT ResetForNextRequest, which
      // clears the trace fields — then keep reading: the very next
      // bytes are the traced request's header.
      c->trace_ctx =
          ParseTraceCtx(reinterpret_cast<const uint8_t*>(c->fixed.data()));
      c->traced = c->trace_ctx.valid();
      c->trace_span = c->traced ? trace_->NextSpanId() : 0;
      c->state = ConnState::kRecvHeader;
      c->header_got = 0;
      c->fixed.clear();
      c->fixed_need = 0;
      c->pkg_len = 0;
      c->body_consumed = 0;
      c->req_start_us = 0;
      return;
    }
    case StorageCmd::kPriority: {
      // Stash the class for the next request (out-of-range bytes clamp
      // to background — garbage priority must never OUTRANK honest
      // traffic).  Minimal reset like kTraceCtx: the very next bytes
      // are the tagged request's header.
      uint8_t cls = static_cast<uint8_t>(c->fixed[0]);
      c->priority = cls > kPriorityBackground ? kPriorityBackground : cls;
      c->state = ConnState::kRecvHeader;
      c->header_got = 0;
      c->fixed.clear();
      c->fixed_need = 0;
      c->pkg_len = 0;
      c->body_consumed = 0;
      c->req_start_us = 0;
      return;
    }
    case StorageCmd::kMetricsHistory: {
      int64_t since = GetInt64BE(
          reinterpret_cast<const uint8_t*>(c->fixed.data()));
      // Journal read + decode off the nio loop, like the empty-body path.
      OffloadToDio(c, 0, [this, c, since] {
        Respond(c, 0, metrics_->DumpJson("storage", cfg_.port,
                                         since < 0 ? 0 : since));
      });
      return;
    }
    case StorageCmd::kHeatTop: {
      int64_t k = GetInt64BE(
          reinterpret_cast<const uint8_t*>(c->fixed.data()));
      if (k <= 0 || k > 65536) k = cfg_.heat_top_k;
      Respond(c, 0, heat_->TopJson("storage", cfg_.port,
                                   static_cast<int>(k)));
      return;
    }
    case StorageCmd::kProfileCtl: {
      const uint8_t* p = reinterpret_cast<const uint8_t*>(c->fixed.data());
      uint8_t action = p[0];
      int64_t hz = GetInt64BE(p + 1);
      int64_t secs = GetInt64BE(p + 9);
      int rc;
      if (action == 1) {
        // Range guard before the int narrowing; Start clamps to
        // profile_max_hz / kMaxDurationS on top of this.
        if (hz <= 0 || hz > 100000 || secs <= 0 || secs > 86400)
          rc = 22;
        else
          rc = Profiler::Global().Start(static_cast<int>(hz),
                                        static_cast<int>(secs));
      } else if (action == 0) {
        rc = Profiler::Global().Stop();
      } else {
        rc = 22;
      }
      if (rc != 0) {
        RespondError(c, static_cast<uint8_t>(rc));
        return;
      }
      // Ack with what actually took effect (hz may have been clamped).
      Profiler& prof = Profiler::Global();
      Respond(c, 0,
              std::string("{\"active\":") + (prof.active() ? "true" : "false") +
                  ",\"hz\":" + std::to_string(prof.armed_hz()) + "}");
      return;
    }
    case StorageCmd::kUploadFile:
    case StorageCmd::kUploadAppenderFile:
      if (!BeginUpload(c)) return;
      c->state = ConnState::kRecvFile;
      if (c->file_remaining == 0) OnFileComplete(c);  // zero-byte upload
      return;
    case StorageCmd::kSyncCreateFile: {
      // Two-stage fixed read: prefix then name.
      const uint8_t* p = reinterpret_cast<const uint8_t*>(c->fixed.data());
      int64_t name_len = GetInt64BE(p + kGroupNameMaxLen);
      int64_t size = GetInt64BE(p + kGroupNameMaxLen + 8);
      if (c->fixed.size() == 32) {
        if (name_len <= 0 || name_len > 512 || size < 0 ||
            c->pkg_len != 32 + name_len + size) {
          RespondError(c, 22);
          return;
        }
        c->fixed_need = 32 + static_cast<size_t>(name_len);
        return;  // keep reading the name
      }
      std::string group = GroupFromField(p);
      c->sync_remote = c->fixed.substr(32);
      c->file_size = size;
      c->file_remaining = size;
      if (group != cfg_.group_name ||
          !LocalPath(store_.store_path(0), c->sync_remote).has_value()) {
        RespondError(c, 22);
        return;
      }
      int spi = 0;
      sscanf(c->sync_remote.c_str(), "M%02X/", &spi);
      if (spi >= store_.store_path_count()) {
        RespondError(c, 22);
        return;
      }
      c->store_path_index = spi;
      c->tmp_path = store_.NewTmpPath(spi);
      c->file_fd = open(c->tmp_path.c_str(), O_CREAT | O_WRONLY | O_TRUNC, 0644);
      if (c->file_fd < 0) {
        RespondError(c, 5);
        return;
      }
      c->state = ConnState::kRecvFile;
      if (c->file_remaining == 0) OnFileComplete(c);
      return;
    }
    case StorageCmd::kSyncCreateRecipe: {
      const uint8_t* p = reinterpret_cast<const uint8_t*>(c->fixed.data());
      int64_t name_len = GetInt64BE(p + kGroupNameMaxLen);
      int64_t logical = GetInt64BE(p + kGroupNameMaxLen + 8);
      int64_t n_chunks = GetInt64BE(p + kGroupNameMaxLen + 16);
      int64_t payload = GetInt64BE(p + kGroupNameMaxLen + 24);
      if (c->fixed.size() == 48) {
        if (name_len <= 0 || name_len > 512 || logical < 0 ||
            n_chunks <= 0 || n_chunks > (1 << 22) || payload < 0 ||
            c->pkg_len != 48 + name_len + n_chunks * 29 + payload ||
            48 + name_len + n_chunks * 29 > kMaxInlineBody) {
          RespondError(c, 22);
          return;
        }
        c->fixed_need = static_cast<size_t>(48 + name_len + n_chunks * 29);
        return;  // keep reading name + chunk entries
      }
      std::string group = GroupFromField(p);
      c->sync_remote = c->fixed.substr(48, static_cast<size_t>(name_len));
      c->file_size = payload;
      c->file_remaining = payload;
      if (group != cfg_.group_name ||
          !LocalPath(store_.store_path(0), c->sync_remote).has_value()) {
        RespondError(c, 22);
        return;
      }
      int spi = 0;
      sscanf(c->sync_remote.c_str(), "M%02X/", &spi);
      if (spi >= store_.store_path_count() ||
          spi >= static_cast<int>(chunk_stores_.size())) {
        RespondError(c, 95 /*ENOTSUP: no chunk store for this path*/);
        return;
      }
      c->store_path_index = spi;
      c->tmp_path = store_.NewTmpPath(spi);
      c->file_fd = open(c->tmp_path.c_str(), O_CREAT | O_WRONLY | O_TRUNC,
                        0644);
      if (c->file_fd < 0) {
        RespondError(c, 5);
        return;
      }
      c->state = ConnState::kRecvFile;
      if (c->file_remaining == 0) OnFileComplete(c);
      return;
    }
    case StorageCmd::kSyncAppendFile:
    case StorageCmd::kSyncModifyFile:
      if (!BeginSyncRange(c)) return;
      if (c->state == ConnState::kRecvFile && c->file_remaining == 0)
        OnFileComplete(c);
      return;
    case StorageCmd::kAppendFile:
    case StorageCmd::kModifyFile:
      if (!BeginClientRange(c)) return;
      if (c->state == ConnState::kRecvFile && c->file_remaining == 0)
        OnFileComplete(c);
      return;
    case StorageCmd::kUploadSlaveFile:
      if (!BeginSlaveUpload(c)) return;
      if (c->state == ConnState::kRecvFile && c->file_remaining == 0)
        OnFileComplete(c);
      return;
    case StorageCmd::kSyncUpdateFile:
      HandleSyncUpdate(c);
      return;
    case StorageCmd::kSyncTruncateFile:
    case StorageCmd::kTruncateFile:
      HandleTruncate(c);
      return;
    case StorageCmd::kDownloadFile:
      HandleDownload(c);
      return;
    case StorageCmd::kDeleteFile:
    case StorageCmd::kSyncDeleteFile:
      HandleDelete(c);
      return;
    case StorageCmd::kQueryFileInfo:
      HandleQueryFileInfo(c);
      return;
    case StorageCmd::kNearDups:
      HandleNearDups(c);
      return;
    case StorageCmd::kSetMetadata:
      HandleSetMetadata(c);
      return;
    case StorageCmd::kGetMetadata:
      HandleGetMetadata(c);
      return;
    case StorageCmd::kTrunkAllocSpace:
    case StorageCmd::kTrunkAllocConfirm:
    case StorageCmd::kTrunkFreeSpace:
      HandleTrunkRpc(c);
      return;
    case StorageCmd::kFetchOnePathBinlog:
      HandleFetchOnePathBinlog(c);
      return;
    case StorageCmd::kSyncCreateLink:
    case StorageCmd::kCreateLink:
      HandleCreateLink(c);
      return;
    case StorageCmd::kSyncQueryChunks:
      HandleSyncQueryChunks(c);
      return;
    case StorageCmd::kFetchRecipe: {
      // Up to 16 MB of chunk/recipe disk reads per request: run on the
      // file's store-path dio pool, not this nio event loop (a slow disk
      // would stall every other connection on the loop).
      int spi = 0;
      if (c->fixed.size() >= 16 + 4)
        sscanf(c->fixed.c_str() + 16, "M%02X/", &spi);
      OffloadToDio(c, spi, [this, c] { HandleFetchRecipe(c); });
      return;
    }
    case StorageCmd::kFetchChunk: {
      int spi = 0;
      if (c->fixed.size() >= 24 + 4)
        sscanf(c->fixed.c_str() + 24, "M%02X/", &spi);
      OffloadToDio(c, spi, [this, c] { HandleFetchChunk(c); });
      return;
    }
    case StorageCmd::kEcRelease:
      // Chunk-store drops + released.log fsync — dio work.  Releases
      // are digest-addressed (no store-path routing: each store drops
      // what it holds), so pool 0 serializes them, which is fine for a
      // scrub-paced background RPC.
      OffloadToDio(c, 0, [this, c] { HandleEcRelease(c); });
      return;
    case StorageCmd::kUploadRecipe: {
      // Drain refusal at session START only: an in-flight session's
      // kUploadChunks may still commit (the file predates the drain
      // decision and migrates with everything else).
      if (DrainingRefusal()) {
        Respond(c, 16 /*EBUSY*/);
        return;
      }
      // Chunk-store probe + pin: cheap, but it contends on the store
      // mutex with every concurrent upload's PutAndRef — keep it off
      // the nio loop like the other chunk-store servers.
      int spi = c->fixed.empty() ? 0 : static_cast<uint8_t>(c->fixed[0]);
      OffloadToDio(c, spi == 0xFF ? 0 : spi,
                   [this, c] { HandleUploadRecipe(c); });
      return;
    }
    case StorageCmd::kUploadChunks:
      if (!BeginUploadChunks(c)) return;
      if (c->file_remaining == 0) OnFileComplete(c);  // all chunks present
      return;
    default:
      Respond(c, 22);
      return;
  }
}

void StorageServer::OnFileComplete(Conn* c) {
  c->recv_done_us = MonoUs();  // recv-stage end (access log AND spans)
  if (c->discarding) {  // rejected request: body drained, send the verdict
    Respond(c, c->pending_status, c->pending_body);
    return;
  }
  auto cmd = static_cast<StorageCmd>(c->cmd);
  if (cmd == StorageCmd::kSyncAppendFile || cmd == StorageCmd::kSyncModifyFile ||
      cmd == StorageCmd::kAppendFile || cmd == StorageCmd::kModifyFile) {
    close(c->file_fd);
    c->file_fd = -1;
    ReleaseBusy(c);
    char extra[48];
    snprintf(extra, sizeof(extra), "%lld %lld",
             static_cast<long long>(c->range_offset),
             static_cast<long long>(c->file_size));
    bool append =
        cmd == StorageCmd::kSyncAppendFile || cmd == StorageCmd::kAppendFile;
    bool source =
        cmd == StorageCmd::kAppendFile || cmd == StorageCmd::kModifyFile;
    binlog_.Append(source ? (append ? kBinlogOpAppend : kBinlogOpModify)
                          : (append ? 'a' : 'm'),
                   c->sync_remote, extra);
    if (source) {
      stats_.success_append++;
      stats_.last_source_update = time(nullptr);
    }
    Respond(c, 0);
    return;
  }
  // Heavy completions — dedup fingerprinting (a TPU RPC in sidecar
  // mode), chunk-store writes, trunk allocation RPCs, renames — run on
  // the store path's dio pool so no single upload stalls this loop's
  // other connections (reference: the nio→dio handoff in
  // storage_service.c:storage_write_to_file()).
  OffloadToDio(c, c->store_path_index, [this, c] {
    auto wcmd = static_cast<StorageCmd>(c->cmd);
    if (wcmd == StorageCmd::kUploadSlaveFile)
      FinishSlaveUpload(c);
    else if (wcmd == StorageCmd::kSyncCreateFile)
      SyncCreateComplete(c);
    else if (wcmd == StorageCmd::kSyncCreateRecipe)
      SyncRecipeComplete(c);
    else if (wcmd == StorageCmd::kUploadChunks)
      UploadChunksComplete(c);
    else
      FinishUpload(c);
  });
}

void StorageServer::SyncCreateComplete(Conn* c) {
  {
    // Replica write: place at the exact remote filename from the source.
    close(c->file_fd);
    c->file_fd = -1;
    auto tparts = DecodeFileId(cfg_.group_name + "/" + c->sync_remote);
    if (tparts.has_value() && tparts->trunk_loc.has_value()) {
      // Trunk replica: same (id, offset) slot as the source — the ID
      // encodes the location, so layouts must match byte-for-byte.
      // Staleness guard: if the slot already holds a DIFFERENT live file
      // (it was freed via the allocator RPC and reused before this replay
      // arrived), this create is for an already-deleted file — skip it
      // rather than clobber the new occupant.
      {
        std::string tp = TrunkFilePath(store_.store_path(0),
                                       tparts->trunk_loc->trunk_id);
        int gfd = open(tp.c_str(), O_RDONLY);
        if (gfd >= 0) {
          auto gh = ReadSlotHeader(gfd, tparts->trunk_loc->offset);
          close(gfd);
          if (gh.has_value() && gh->type == kTrunkSlotData &&
              gh->file_size != 0 &&
              (gh->file_size != tparts->file_size ||
               gh->crc32 != tparts->crc32)) {
            FDFS_LOG_WARN("stale trunk create %s skipped (slot reused)",
                          c->sync_remote.c_str());
            unlink(c->tmp_path.c_str());
            Respond(c, 0);
            return;
          }
        }
      }
      std::string payload, err;
      if (!ReadWholeFile(c->tmp_path, &payload) ||
          !WriteSlotPayload(store_.store_path(0), *tparts->trunk_loc,
                            payload, tparts->crc32, &err)) {
        FDFS_LOG_ERROR("trunk replica write %s: %s", c->sync_remote.c_str(),
                       err.c_str());
        unlink(c->tmp_path.c_str());
        Respond(c, 5);
        return;
      }
      unlink(c->tmp_path.c_str());
      binlog_.Append('c', c->sync_remote);
      Respond(c, 0);
      return;
    }
    std::string local = ResolveLocal(cfg_.group_name, c->sync_remote);
    if (local.empty()) {
      unlink(c->tmp_path.c_str());
      Respond(c, 22);
      return;
    }
    // Replicas dedup too: chunk-eligible synced files go through the
    // chunk store (same cut-points cluster-wide), others stay flat.
    // Appenders stay flat everywhere (mutable: later SYNC_APPEND/MODIFY
    // ops open the flat file in place — a recipe would break them).
    // Parent dirs only materialize when a flat inode is written (the
    // recipe store handles its own sidecar): slab-resident replicas
    // must cost zero fan-out directories too.
    struct stat st;
    if (!(tparts.has_value() && tparts->appender) &&
        stat(c->tmp_path.c_str(), &st) == 0 && ChunkEligible(st.st_size)) {
      int spi = 0;
      sscanf(c->sync_remote.c_str(), "M%02X/", &spi);
      int64_t saved = 0, hits = 0;
      if (StoreChunkedFromTmp(c->tmp_path, spi, st.st_size, local + ".rcp",
                              cfg_.group_name + "/" + c->sync_remote,
                              &saved, &hits)) {
        unlink(c->tmp_path.c_str());
        stats_.dedup_hits += hits;
        stats_.dedup_bytes_saved += saved;
        binlog_.Append('c', c->sync_remote);
        Respond(c, 0);
        return;
      }
    }
    StoreManager::EnsureParentDirs(local);
    if (rename(c->tmp_path.c_str(), local.c_str()) != 0) {
      unlink(c->tmp_path.c_str());
      Respond(c, 5);
      return;
    }
    binlog_.Append('c', c->sync_remote);
    Respond(c, 0);
    return;
  }
}

// Feed a recovered file's (locally assembled) bytes through the dedup
// plugin in upload-sized segments so its near-dup signature and chunk
// attributions re-enter the engine's indexes — a sidecar-mode rebuild
// would otherwise leave every recovered file invisible to NEAR_DUPS
// and un-forgettable on delete.  Best-effort: failures only cost index
// coverage, never the recovered data.
void StorageServer::ReindexRecovered(DedupPlugin* plugin,
                                     const std::string& local,
                                     const std::string& file_ref) {
  int64_t size = 0;
  int fd = OpenLogical(local, &size);
  if (fd < 0) return;
  const int64_t session = plugin->BeginChunked();
  std::string seg;
  int64_t base = 0;
  bool ok = true;
  while (ok && base < size) {
    int64_t want = std::min<int64_t>(cfg_.dedup_segment_bytes, size - base);
    seg.resize(static_cast<size_t>(want));
    int64_t got = 0;
    while (got < want) {
      ssize_t r = read(fd, seg.data() + got, want - got);
      if (r <= 0) break;
      got += r;
    }
    std::vector<ChunkFp> fps;
    ok = got == want &&
         plugin->FingerprintChunks(session, seg.data(), seg.size(), base,
                                   &fps);
    base += want;
  }
  close(fd);
  if (ok)
    plugin->CommitChunked(session, file_ref);
  else
    plugin->AbortChunked(session);
}

// FETCH_RECIPE (128): serve a recipe-stored file's chunk list to a
// rebuilding peer (chunk-aware disk recovery).  ENOENT when the file is
// flat/absent — the caller downloads logical bytes instead.
void StorageServer::HandleFetchRecipe(Conn* c) {
  const uint8_t* p = reinterpret_cast<const uint8_t*>(c->fixed.data());
  if (c->fixed.size() <= kGroupNameMaxLen) {
    Respond(c, 22);
    return;
  }
  std::string group = GroupFromField(p);
  std::string remote = c->fixed.substr(kGroupNameMaxLen);
  std::string local = ResolveLocal(group, remote);
  if (local.empty()) {
    Respond(c, 22);
    return;
  }
  auto r = LoadRecipeFor(local);
  if (!r.has_value()) {
    Respond(c, 2 /*ENOENT: flat or gone*/);
    return;
  }
  // The client rejects recipe bodies over its 64 MB cap; don't build a
  // multi-hundred-MB response it will discard (it falls back to the
  // streamed full download for such files either way).
  if (16 + r->chunks.size() * 28 > (48u << 20)) {
    Respond(c, 2);
    return;
  }
  std::string body;
  uint8_t num[8];
  PutInt64BE(r->logical_size, num);
  body.append(reinterpret_cast<char*>(num), 8);
  PutInt64BE(static_cast<int64_t>(r->chunks.size()), num);
  body.append(reinterpret_cast<char*>(num), 8);
  for (const RecipeEntry& e : r->chunks) {
    if (!HexToBytes(e.digest_hex, &body)) {
      Respond(c, 5);
      return;
    }
    PutInt64BE(e.length, num);
    body.append(reinterpret_cast<char*>(num), 8);
  }
  Respond(c, 0, body);
}

// FETCH_CHUNK (129): serve a BATCH of chunk payloads by digest
// (chunk-aware disk recovery; one round-trip per ~8 MB of missing
// bytes, not one per chunk).  ENOENT when any requested chunk is gone
// — the caller falls back to a full download of that file.
void StorageServer::HandleFetchChunk(Conn* c) {
  const uint8_t* p = reinterpret_cast<const uint8_t*>(c->fixed.data());
  if (c->fixed.size() < kGroupNameMaxLen + 16 + 1 + 28) {
    Respond(c, 22);
    return;
  }
  std::string group = GroupFromField(p);
  int64_t name_len = GetInt64BE(p + kGroupNameMaxLen);
  size_t base = kGroupNameMaxLen + 8;
  if (group != cfg_.group_name || name_len <= 0 || name_len > 512 ||
      c->fixed.size() < base + name_len + 8) {
    Respond(c, 22);
    return;
  }
  std::string remote = c->fixed.substr(base, static_cast<size_t>(name_len));
  NoteHeat(c, HeatOp::kFetchChunk, group + "/" + remote);
  int spi = 0;
  sscanf(remote.c_str(), "M%02X/", &spi);
  if (spi >= static_cast<int>(chunk_stores_.size())) {
    Respond(c, 95 /*ENOTSUP*/);
    return;
  }
  const uint8_t* q = p + base + name_len;
  int64_t count = GetInt64BE(q);
  if (count <= 0 ||
      static_cast<size_t>(count) !=
          (c->fixed.size() - base - name_len - 8) / 28 ||
      (c->fixed.size() - base - name_len - 8) % 28 != 0) {
    Respond(c, 22);
    return;
  }
  int64_t total = 0;
  for (int64_t i = 0; i < count; ++i) {
    int64_t len = GetInt64BE(q + 8 + i * 28 + 20);
    if (len <= 0 || len > kMaxChunkPayload) {
      Respond(c, 22);
      return;
    }
    total += len;
  }
  if (total > (16 << 20)) {  // batch cap: bounded response memory
    Respond(c, 22);
    return;
  }
  std::string out;
  out.reserve(static_cast<size_t>(total));
  std::string one;
  for (int64_t i = 0; i < count; ++i) {
    const uint8_t* e = q + 8 + i * 28;
    std::string dig = BytesToHex(e, 20);
    int64_t len = GetInt64BE(e + 20);
    // Consult the hot-chunk cache (lookup only — recovery/repair sweeps
    // must not evict client-hot chunks by populating it).
    if (auto cached = chunk_stores_[spi]->CacheLookup(dig, len)) {
      out += *cached;
      continue;
    }
    if (!chunk_stores_[spi]->ReadChunk(dig, len, &one)) {
      Respond(c, 2 /*ENOENT*/);
      return;
    }
    out += one;
  }
  if (ctr_chunkfetch_batches_ != nullptr) {
    ctr_chunkfetch_batches_->fetch_add(1, std::memory_order_relaxed);
    ctr_chunkfetch_chunks_->fetch_add(count, std::memory_order_relaxed);
    ctr_chunkfetch_bytes_->fetch_add(total, std::memory_order_relaxed);
  }
  Respond(c, 0, out);
}

// EC_RELEASE (145): a group peer finished encoding these chunks into a
// verified RS stripe — drop this node's replicated copies.  Body: 16B
// group + 8B count + count x (20B raw digest + 8B BE length); response
// is count bytes (0 = released, 1 = kept — pinned or quarantined
// chunks retain full-replica coverage here, which the owner treats as
// safe over-replication).  The drop is journaled to released.log (one
// fsync'd batch append) BEFORE the response, so a restart rebuilds the
// released marks and reads keep routing to the owner.
void StorageServer::HandleEcRelease(Conn* c) {
  const uint8_t* p = reinterpret_cast<const uint8_t*>(c->fixed.data());
  if (c->fixed.size() < kGroupNameMaxLen + 8) {
    Respond(c, 22);
    return;
  }
  std::string group = GroupFromField(p);
  int64_t count = GetInt64BE(p + kGroupNameMaxLen);
  size_t base = kGroupNameMaxLen + 8;
  if (group != cfg_.group_name || count <= 0 ||
      static_cast<size_t>(count) != (c->fixed.size() - base) / 28 ||
      (c->fixed.size() - base) % 28 != 0) {
    Respond(c, 22);
    return;
  }
  if (chunk_stores_.empty()) {
    Respond(c, 95 /*ENOTSUP*/);
    return;
  }
  std::vector<ChunkStore::ChunkInfo> chunks;
  chunks.reserve(static_cast<size_t>(count));
  for (int64_t i = 0; i < count; ++i) {
    const uint8_t* e = p + base + i * 28;
    ChunkStore::ChunkInfo info;
    info.digest_hex = BytesToHex(e, 20);
    info.length = GetInt64BE(e + 20);
    chunks.push_back(std::move(info));
  }
  // Digest-addressed: every store drops what it holds; a digest kept by
  // ANY store answers kept (the owner may not reclaim its coverage).
  std::string mask(static_cast<size_t>(count), '\0');
  for (auto& cs : chunk_stores_) {
    std::string m = cs->ReleaseChunks(chunks);
    for (int64_t i = 0; i < count && i < static_cast<int64_t>(m.size()); ++i)
      if (m[static_cast<size_t>(i)]) mask[static_cast<size_t>(i)] = 1;
  }
  Respond(c, 0, mask);
}

// Remote read of a released chunk: round-robin the group peers with a
// single-chunk FETCH_CHUNK.  The stripe owner's ReadChunk falls through
// to its EC tier, so this works whichever peer holds the stripe; the
// payload is SHA1-gated by the caller (ChunkStore::ReadChunk).
bool StorageServer::FetchChunkFromPeers(int spi,
                                        const std::string& digest_hex,
                                        int64_t len, std::string* out) {
  if (len <= 0 || sync_ == nullptr) return false;
  char remote[16];
  snprintf(remote, sizeof(remote), "M%02X/ecread", spi);
  std::string body;
  PutFixedField(&body, cfg_.group_name, kGroupNameMaxLen);
  uint8_t num[8];
  PutInt64BE(static_cast<int64_t>(strlen(remote)), num);
  body.append(reinterpret_cast<char*>(num), 8);
  body += remote;
  PutInt64BE(1, num);
  body.append(reinterpret_cast<char*>(num), 8);
  if (!HexToBytes(digest_hex, &body)) return false;
  PutInt64BE(len, num);
  body.append(reinterpret_cast<char*>(num), 8);
  for (const SyncPeerState& s : sync_->States()) {
    size_t colon = s.addr.rfind(':');
    if (colon == std::string::npos) continue;
    std::string err;
    int fd = TcpConnect(s.addr.substr(0, colon),
                        atoi(s.addr.c_str() + colon + 1), 3000, &err);
    if (fd < 0) continue;
    std::string resp;
    uint8_t status = 0;
    bool ok = NetRpc(fd, static_cast<uint8_t>(StorageCmd::kFetchChunk), body,
                     &resp, &status, len + 1024, cfg_.network_timeout_ms);
    close(fd);
    if (!ok || status != 0 || static_cast<int64_t>(resp.size()) != len)
      continue;
    out->swap(resp);
    return true;
  }
  return false;
}

// SYNC_QUERY_CHUNKS (126): which of these digests does this node's
// chunk store lack?  Phase 1 of chunk-aware replication; response body
// is one byte per digest (0 = present, 1 = needed).
void StorageServer::HandleSyncQueryChunks(Conn* c) {
  const uint8_t* p = reinterpret_cast<const uint8_t*>(c->fixed.data());
  if (c->fixed.size() < kGroupNameMaxLen + 8) {
    Respond(c, 22);
    return;
  }
  std::string group = GroupFromField(p);
  int64_t name_len = GetInt64BE(p + kGroupNameMaxLen);
  size_t base = kGroupNameMaxLen + 8;
  if (group != cfg_.group_name || name_len <= 0 || name_len > 512 ||
      c->fixed.size() < base + name_len ||
      (c->fixed.size() - base - name_len) % 20 != 0) {
    Respond(c, 22);
    return;
  }
  std::string remote = c->fixed.substr(base, static_cast<size_t>(name_len));
  int spi = 0;
  sscanf(remote.c_str(), "M%02X/", &spi);
  if (spi >= static_cast<int>(chunk_stores_.size())) {
    Respond(c, 95 /*ENOTSUP: no chunk store*/);
    return;
  }
  ChunkStore* cs = chunk_stores_[spi].get();
  size_t n = (c->fixed.size() - base - name_len) / 20;
  const uint8_t* digs = p + base + name_len;
  std::vector<std::string> hex;
  hex.reserve(n);
  for (size_t i = 0; i < n; ++i) hex.push_back(BytesToHex(digs + i * 20, 20));
  Respond(c, 0, cs->HaveMask(hex));
}

// UPLOAD_RECIPE (132): phase 1 of the dedup-aware negotiated upload.
// The client chunked + fingerprinted locally; answer which chunks it
// must ship (1 = needed), pin every present chunk so a concurrent
// delete cannot unlink it before phase 2 references it, and park the
// session.  ENOTSUP when this daemon has no chunk store — the client
// falls back to a plain UPLOAD_FILE (an older daemon without this
// opcode answers EINVAL, same client reaction).
void StorageServer::HandleUploadRecipe(Conn* c) {
  if (dedup_ == nullptr || chunk_stores_.empty()) {
    if (ctr_ingest_fallbacks_ != nullptr)
      ctr_ingest_fallbacks_->fetch_add(1, std::memory_order_relaxed);
    Respond(c, 95 /*ENOTSUP*/);
    return;
  }
  // body: 1B spi + 6B ext + 8B crc32 + 8B logical + 8B count + entries
  constexpr size_t kPrefix = 1 + kFileExtNameMaxLen + 8 + 8 + 8;
  if (c->fixed.size() < kPrefix + 28) {
    Respond(c, 22);
    return;
  }
  const uint8_t* p = reinterpret_cast<const uint8_t*>(c->fixed.data());
  int spi = p[0];
  std::string ext = ExtFromField(p + 1);
  uint32_t crc = static_cast<uint32_t>(GetInt64BE(p + 7));
  int64_t logical = GetInt64BE(p + 15);
  int64_t n_chunks = GetInt64BE(p + 23);
  if (spi == 0xFF) spi = store_.PickStorePath();
  if (spi >= store_.store_path_count() ||
      spi >= static_cast<int>(chunk_stores_.size())) {
    Respond(c, 95 /*ENOTSUP: no chunk store for this path*/);
    return;
  }
  if (logical < cfg_.dedup_chunk_threshold) {
    // Server-authoritative chunking threshold (the plain path's
    // ChunkEligible gate): a payload the daemon would store flat has no
    // recipe to negotiate over.  ENOTSUP => the client falls back.
    if (ctr_ingest_fallbacks_ != nullptr)
      ctr_ingest_fallbacks_->fetch_add(1, std::memory_order_relaxed);
    Respond(c, 95);
    return;
  }
  // Amplification bound on client-controlled geometry: every CDC spec in
  // the cluster cuts chunks well above this floor, so a recipe declaring
  // more entries than <logical / floor> is hostile or corrupt — without
  // the bound a 64 MB recipe of 1-byte chunks would pin and materialize
  // millions of chunk-store files for a few MB of payload.
  constexpr int64_t kMinNegotiatedChunk = 1024;
  if (logical < 0 || n_chunks <= 0 || n_chunks > (1 << 22) ||
      n_chunks > logical / kMinNegotiatedChunk + 1 ||
      c->fixed.size() != kPrefix + static_cast<size_t>(n_chunks) * 28) {
    Respond(c, 22);
    return;
  }
  auto s = std::make_unique<UploadSession>();
  s->recipe.logical_size = logical;
  s->recipe.chunks.reserve(static_cast<size_t>(n_chunks));
  int64_t covered = 0;
  const uint8_t* e = p + kPrefix;
  for (int64_t i = 0; i < n_chunks; ++i) {
    int64_t len = GetInt64BE(e + i * 28 + 20);
    // Same per-chunk cap as SYNC_CREATE_RECIPE: no declared entry may
    // make the phase-2 worker allocate unboundedly.
    if (len <= 0 || len > kMaxChunkPayload) {
      Respond(c, 22);
      return;
    }
    s->recipe.chunks.push_back({BytesToHex(e + i * 28, 20), len});
    covered += len;
  }
  if (covered != logical) {
    Respond(c, 22);
    return;
  }
  s->id = next_ingest_session_.fetch_add(1);
  s->spi = spi;
  s->ext = std::move(ext);
  s->crc32 = crc;
  s->cs = chunk_stores_[spi].get();
  // Probe + pin under ONE store-lock acquisition; from here the
  // session's destructor owns the unpin.
  s->needed = s->cs->PinAndMask(s->recipe);
  int64_t missing = 0;
  for (size_t i = 0; i < s->needed.size(); ++i) {
    if (s->needed[i] != 0) {
      ++missing;
      s->needed_bytes += s->recipe.chunks[i].length;
    }
  }
  s->deadline_s = time(nullptr) + cfg_.upload_session_timeout_s;
  c->ingest_chunks_total = n_chunks;
  c->ingest_chunks_missing = missing;
  std::string body(8, '\0');
  PutInt64BE(s->id, reinterpret_cast<uint8_t*>(body.data()));
  body += s->needed;
  {
    std::lock_guard<RankedMutex> lk(ingest_mu_);
    ingest_sessions_[s->id] = std::move(s);
  }
  Respond(c, 0, body);
}

std::unique_ptr<StorageServer::UploadSession>
StorageServer::TakeIngestSession(int64_t id) {
  std::lock_guard<RankedMutex> lk(ingest_mu_);
  auto it = ingest_sessions_.find(id);
  if (it == ingest_sessions_.end()) return nullptr;
  auto s = std::move(it->second);
  ingest_sessions_.erase(it);
  return s;
}

void StorageServer::SweepIngestSessions() {
  // Destruction (unpin) happens OUTSIDE ingest_mu_: UnpinRecipe takes
  // the chunk-store mutex, and holding both here would order them
  // against every handler path for no benefit.
  std::vector<std::unique_ptr<UploadSession>> expired;
  int64_t now = time(nullptr);
  {
    std::lock_guard<RankedMutex> lk(ingest_mu_);
    for (auto it = ingest_sessions_.begin(); it != ingest_sessions_.end();) {
      if (it->second->deadline_s <= now) {
        expired.push_back(std::move(it->second));
        it = ingest_sessions_.erase(it);
      } else {
        ++it;
      }
    }
  }
  for (auto& s : expired) {
    FDFS_LOG_WARN("negotiated upload session %lld expired "
                  "(client vanished between RECIPE and CHUNKS): pins "
                  "released",
                  static_cast<long long>(s->id));
    if (ctr_ingest_fallbacks_ != nullptr)
      ctr_ingest_fallbacks_->fetch_add(1, std::memory_order_relaxed);
    if (events_ != nullptr)
      events_->Record(EventSeverity::kWarn, "ingest.session_expired",
                      std::to_string(s->id),
                      "chunks=" + std::to_string(s->recipe.chunks.size()) +
                          " pinned_released=1");
  }
}

// UPLOAD_CHUNKS (133) prefix parse on the nio loop: resolve the
// session, validate the declared payload against what phase 1 computed,
// and open the tmp file the missing-chunk bytes stream into.
bool StorageServer::BeginUploadChunks(Conn* c) {
  const uint8_t* p = reinterpret_cast<const uint8_t*>(c->fixed.data());
  int64_t session_id = GetInt64BE(p);
  int64_t payload_len = GetInt64BE(p + 8);
  if (payload_len < 0 || c->pkg_len != 16 + payload_len) {
    RespondError(c, 22);
    return false;
  }
  int spi = -1;
  int64_t expect = -1;
  {
    std::lock_guard<RankedMutex> lk(ingest_mu_);
    auto it = ingest_sessions_.find(session_id);
    if (it != ingest_sessions_.end()) {
      spi = it->second->spi;
      expect = it->second->needed_bytes;
      // Restart the expiry clock now that the payload is arriving: the
      // phase-1 deadline covered the client's think time; without this
      // bump the sweep would expire a session whose client is actively
      // streaming a transfer longer than the timeout and force the
      // whole payload onto the plain path (~2x wire).
      it->second->deadline_s = time(nullptr) + cfg_.upload_session_timeout_s;
    }
  }
  if (spi < 0) {
    // Unknown or expired: the client falls back to a plain upload.  NOT
    // counted as a fallback — an expired session was already counted by
    // the sweep, and double-counting would skew the stuck-session
    // diagnosis OPERATIONS.md builds on this counter.
    RespondError(c, 2 /*ENOENT*/);
    return false;
  }
  if (payload_len != expect) {
    // Client/server disagree on what was missing: abort the session
    // (its pins included) rather than assemble a wrong file.
    TakeIngestSession(session_id).reset();
    if (ctr_ingest_fallbacks_ != nullptr)
      ctr_ingest_fallbacks_->fetch_add(1, std::memory_order_relaxed);
    if (events_ != nullptr)
      events_->Record(EventSeverity::kWarn, "ingest.fallback",
                      std::to_string(session_id),
                      "phase=chunks reason=payload_mismatch declared=" +
                          std::to_string(payload_len) +
                          " expected=" + std::to_string(expect));
    RespondError(c, 22);
    return false;
  }
  c->ingest_session = session_id;
  c->store_path_index = spi;
  c->file_remaining = payload_len;
  c->tmp_path = store_.NewTmpPath(spi);
  c->file_fd = open(c->tmp_path.c_str(), O_CREAT | O_WRONLY | O_TRUNC, 0644);
  if (c->file_fd < 0) {
    RespondError(c, 5);
    return false;
  }
  c->state = ConnState::kRecvFile;
  return true;
}

// UPLOAD_CHUNKS completion (dio worker): verify each shipped chunk IS
// its claimed digest, write it via PutAndRef, reference the present
// ones, store the recipe, and mint/answer the file ID exactly like
// UPLOAD_FILE.  All-or-nothing with ref rollback; any failure makes
// the client fall back to a plain upload.
void StorageServer::UploadChunksComplete(Conn* c) {
  close(c->file_fd);
  c->file_fd = -1;
  auto fail = [&](uint8_t status) {
    if (ctr_ingest_fallbacks_ != nullptr)
      ctr_ingest_fallbacks_->fetch_add(1, std::memory_order_relaxed);
    if (events_ != nullptr)
      events_->Record(EventSeverity::kWarn, "ingest.fallback",
                      std::to_string(c->ingest_session),
                      "phase=commit status=" + std::to_string(status) +
                          " peer=" + c->peer_ip);
    if (!c->tmp_path.empty()) {
      unlink(c->tmp_path.c_str());
      c->tmp_path.clear();
    }
    Respond(c, status);
  };
  // One commit per session: taking it here also closes the race with a
  // concurrent duplicate commit and the sweep timer.  The session (and
  // its pins) dies at scope exit — AFTER the refs below are taken, so
  // there is no unpinned-unreferenced window.
  auto s = TakeIngestSession(c->ingest_session);
  if (s == nullptr) {
    fail(2 /*ENOENT: expired mid-stream*/);
    return;
  }
  c->file_size = s->recipe.logical_size;  // upload-size histogram basis
  c->ingest_chunks_total = static_cast<int64_t>(s->recipe.chunks.size());
  int tmp_fd = open(c->tmp_path.c_str(), O_RDONLY);
  if (tmp_fd < 0) {
    fail(5);
    return;
  }
  int64_t t0 = MonoUs();
  Recipe done;  // refs taken so far (rollback set)
  done.logical_size = s->recipe.logical_size;
  int64_t saved = 0, hits = 0, missing = 0;
  // The file ID's crc32 is identity metadata every consumer may check
  // (trunk slots already do): compute it server-side over the logical
  // stream — shipped chunks from the wire payload, present chunks read
  // back from the store (local-disk cost, still far below re-shipping)
  // — never trust the client's claim.
  uint32_t crc = 0;
  bool ok = true;
  std::string payload;
  for (size_t i = 0; ok && i < s->recipe.chunks.size(); ++i) {
    const RecipeEntry& e = s->recipe.chunks[i];
    if (s->needed[i] != 0) {
      ++missing;
      payload.resize(static_cast<size_t>(e.length));
      int64_t got = 0;
      while (got < e.length) {
        ssize_t r = read(tmp_fd, payload.data() + got, e.length - got);
        if (r <= 0) break;
        got += r;
      }
      // Content-addressed store: the payload must BE its claimed digest
      // before PutAndRef (same check the replication receiver runs) —
      // the client computed these digests, and a buggy or hostile one
      // must not poison future dedup hits under this digest.
      if (got != e.length ||
          Sha1(payload.data(), static_cast<size_t>(e.length)).Hex() !=
              e.digest_hex) {
        FDFS_LOG_WARN("negotiated upload: chunk %s failed digest check",
                      e.digest_hex.c_str());
        ok = false;
        break;
      }
      bool existed = false;
      std::string err;
      if (!s->cs->PutAndRef(e.digest_hex, payload.data(),
                            static_cast<size_t>(e.length), &existed, &err)) {
        FDFS_LOG_ERROR("negotiated upload chunk store: %s", err.c_str());
        ok = false;
        break;
      }
      done.chunks.push_back(e);  // ref taken: in the rollback set
    } else {
      if (!s->cs->RefOne(e.digest_hex)) {
        // Deleted between the bitmap and this commit (the pin only
        // defers the unlink, it does not preserve the reference):
        // report failure and let the client re-send the whole payload.
        FDFS_LOG_WARN("negotiated upload: chunk %s vanished before commit",
                      e.digest_hex.c_str());
        ok = false;
        break;
      }
      done.chunks.push_back(e);
      if (!s->cs->ReadChunk(e.digest_hex, e.length, &payload)) {
        FDFS_LOG_WARN("negotiated upload: chunk %s unreadable at commit",
                      e.digest_hex.c_str());
        ok = false;
        break;
      }
      saved += e.length;
      ++hits;
    }
    crc = Crc32(payload.data(), static_cast<size_t>(e.length), crc);
  }
  close(tmp_fd);
  unlink(c->tmp_path.c_str());
  c->tmp_path.clear();
  c->ingest_chunks_missing = missing;
  if (ok && crc != s->crc32)
    FDFS_LOG_WARN("negotiated upload: client declared crc %u, content is %u "
                  "(ID minted from content)", s->crc32, crc);
  std::string id = ok ? MintFileId(s->spi, s->recipe.logical_size, crc,
                                   s->ext, false)
                      : "";
  auto parts = id.empty() ? std::nullopt : DecodeFileId(id);
  std::optional<std::string> local =
      parts.has_value()
          ? LocalPath(store_.store_path(s->spi), parts->RemoteFilename())
          : std::nullopt;
  std::string err;
  if (!ok || !local.has_value()) {
    s->cs->UnrefAll(done);
    fail(ok ? 22 : 5);
    return;
  }
  if (!s->cs->StoreRecipe(*local + ".rcp", done, &err)) {
    FDFS_LOG_ERROR("negotiated upload recipe write: %s", err.c_str());
    s->cs->UnrefAll(done);
    fail(5);
    return;
  }
  c->cswrite_us = MonoUs() - t0;
  stats_.dedup_hits += hits;
  stats_.dedup_bytes_saved += saved;
  if (ctr_dedup_chunk_hits_ != nullptr && hits > 0)
    ctr_dedup_chunk_hits_->fetch_add(hits, std::memory_order_relaxed);
  if (ctr_dedup_chunk_misses_ != nullptr && missing > 0)
    ctr_dedup_chunk_misses_->fetch_add(missing, std::memory_order_relaxed);
  // Wire accounting: `saved` bytes never left the client — the whole
  // point of the negotiated path.
  if (ctr_ingest_recipe_uploads_ != nullptr) {
    ctr_ingest_recipe_uploads_->fetch_add(1, std::memory_order_relaxed);
    ctr_ingest_bytes_saved_wire_->fetch_add(saved,
                                            std::memory_order_relaxed);
  }
  int64_t t_bl = MonoUs();
  binlog_.Append(kBinlogOpCreate, parts->RemoteFilename());
  c->binlog_us = MonoUs() - t_bl;
  NoteTracedMutation(c, parts->RemoteFilename());
  // Sidecar mode keeps its near-dup/attribution index OUTSIDE the chunk
  // store, and the client-side fingerprint pipeline never talked to it:
  // feed the assembled bytes through the plugin exactly as a recovered
  // file is (best-effort; the cpu plugin indexes in the chunk store
  // itself, so re-fingerprinting there would be pure waste).
  if (dedup_ != nullptr && std::string(dedup_->Name()) == "sidecar")
    ReindexRecovered(dedup_.get(), *local,
                     cfg_.group_name + "/" + parts->RemoteFilename());
  stats_.success_upload++;
  stats_.last_source_update = time(nullptr);
  NoteHeat(c, HeatOp::kUpload, cfg_.group_name + "/" + parts->RemoteFilename());
  Respond(c, 0, PackGroupField(cfg_.group_name) + parts->RemoteFilename());
}

// SYNC_CREATE_RECIPE (127): phase 2 of chunk-aware replication — take a
// reference on every chunk already present, write the shipped payloads
// for the missing ones, and store the recipe.  All-or-nothing: any
// failure rolls back taken refs and the sender falls back to the
// full-copy SYNC_CREATE_FILE.
void StorageServer::SyncRecipeComplete(Conn* c) {
  close(c->file_fd);
  c->file_fd = -1;
  const uint8_t* p = reinterpret_cast<const uint8_t*>(c->fixed.data());
  int64_t name_len = GetInt64BE(p + kGroupNameMaxLen);
  int64_t logical = GetInt64BE(p + kGroupNameMaxLen + 8);
  int64_t n_chunks = GetInt64BE(p + kGroupNameMaxLen + 16);
  std::string local = ResolveLocal(cfg_.group_name, c->sync_remote);
  if (local.empty()) {
    unlink(c->tmp_path.c_str());
    Respond(c, 22);
    return;
  }
  // Idempotent replay: already materialized (flat or recipe) => done.
  struct stat st;
  if (stat(local.c_str(), &st) == 0 || RecipeExistsFor(local)) {
    unlink(c->tmp_path.c_str());
    binlog_.Append('c', c->sync_remote);
    Respond(c, 0);
    return;
  }
  ChunkStore* cs = chunk_stores_[c->store_path_index].get();
  const uint8_t* entries = p + 48 + name_len;
  // Validate every declared length BEFORE any side effects: an oversized
  // entry (corrupt or hostile) must be rejected outright, not allowed to
  // resize a multi-GB payload buffer on this dio worker; and no refs
  // should be taken for a replay that is doomed anyway.
  for (int64_t i = 0; i < n_chunks; ++i) {
    int64_t len = GetInt64BE(entries + i * 29 + 20);
    if (len <= 0 || len > kMaxChunkPayload) {
      FDFS_LOG_WARN("sync recipe %s: chunk %lld declares %lld bytes "
                    "(cap %lld): rejected", c->sync_remote.c_str(),
                    static_cast<long long>(i), static_cast<long long>(len),
                    static_cast<long long>(kMaxChunkPayload));
      unlink(c->tmp_path.c_str());
      c->tmp_path.clear();
      Respond(c, 22);
      return;
    }
  }
  int tmp_fd = open(c->tmp_path.c_str(), O_RDONLY);
  if (tmp_fd < 0) {
    unlink(c->tmp_path.c_str());
    Respond(c, 5);
    return;
  }
  Recipe recipe;
  recipe.logical_size = logical;
  int64_t saved = 0, hits = 0, covered = 0;
  bool ok = true;
  uint8_t fail_status = 5;
  std::string payload;
  for (int64_t i = 0; ok && i < n_chunks; ++i) {
    const uint8_t* e = entries + i * 29;
    std::string hex = BytesToHex(e, 20);
    int64_t len = GetInt64BE(e + 20);  // validated above: (0, cap]
    bool needed = e[28] != 0;
    if (needed) {
      payload.resize(static_cast<size_t>(len));
      int64_t got = 0;
      while (got < len) {
        ssize_t r = read(tmp_fd, payload.data() + got, len - got);
        if (r <= 0) break;
        got += r;
      }
      // Content-addressed store: the payload must BE its claimed digest
      // before PutAndRef, or a bit-rotted peer chunk would poison every
      // future dedup hit under that digest.  Failing the replay makes
      // the sender fall back to the full-copy SYNC_CREATE_FILE.
      if (got == len &&
          Sha1(payload.data(), static_cast<size_t>(len)).Hex() != hex) {
        FDFS_LOG_WARN("sync recipe %s: chunk %s failed digest check",
                      c->sync_remote.c_str(), hex.c_str());
        if (ctr_sync_digest_mismatch_ != nullptr)
          ctr_sync_digest_mismatch_->fetch_add(1, std::memory_order_relaxed);
        ok = false;
        break;
      }
      bool existed = false;
      std::string err;
      if (got != len ||
          !cs->PutAndRef(hex, payload.data(), len, &existed, &err)) {
        ok = false;
        break;
      }
    } else if (!cs->RefOne(hex)) {
      // The chunk vanished between query and create (concurrent
      // delete): report it and let the sender fall back to full copy.
      ok = false;
      break;
    } else {
      saved += len;
      ++hits;
    }
    recipe.chunks.push_back({hex, len});
    covered += len;
  }
  close(tmp_fd);
  unlink(c->tmp_path.c_str());
  c->tmp_path.clear();
  std::string err;
  if (!ok || covered != logical ||
      !cs->StoreRecipe(local + ".rcp", recipe, &err)) {
    cs->UnrefAll(recipe);  // roll back what this replay referenced
    Respond(c, ok ? (covered != logical ? 22 : 5) : fail_status);
    return;
  }
  stats_.dedup_hits += hits;
  stats_.dedup_bytes_saved += saved;
  // Wire accounting: `saved` bytes were ref'd locally instead of shipped
  // by the replication sender — the chunk-aware protocol's whole point.
  if (ctr_sync_bytes_saved_wire_ != nullptr)
    ctr_sync_bytes_saved_wire_->fetch_add(saved, std::memory_order_relaxed);
  binlog_.Append('c', c->sync_remote);
  Respond(c, 0);
}

// -- handlers -------------------------------------------------------------

bool StorageServer::BeginUpload(Conn* c) {
  const uint8_t* p = reinterpret_cast<const uint8_t*>(c->fixed.data());
  int spi = p[0];
  int64_t size = GetInt64BE(p + 1);
  c->ext = ExtFromField(p + 9);
  if (size < 0 || c->pkg_len != 15 + size) {
    RespondError(c, 22);
    return false;
  }
  if (spi == 0xFF) {
    spi = store_.PickStorePath();
  } else if (spi >= store_.store_path_count()) {
    RespondError(c, 22);
    return false;
  }
  c->store_path_index = spi;
  c->file_size = size;
  c->file_remaining = size;
  c->crc32 = 0;
  c->hashing = dedup_ != nullptr;
  if (c->hashing) c->sha1 = Sha1Stream();
  c->tmp_path = store_.NewTmpPath(spi);
  c->file_fd = open(c->tmp_path.c_str(), O_CREAT | O_WRONLY | O_TRUNC, 0644);
  if (c->file_fd < 0) {
    FDFS_LOG_ERROR("open %s: %s", c->tmp_path.c_str(), strerror(errno));
    RespondError(c, 5);
    return false;
  }
  return true;
}

std::string StorageServer::MintFileId(int spi, int64_t size, uint32_t crc,
                                      const std::string& ext, bool appender,
                                      const TrunkLocation* trunk_loc) {
  EncodeFileIdArgs a;
  a.group = cfg_.group_name;
  a.store_path_index = spi;
  a.source_ip = PackIp(MyIp());
  a.create_timestamp = static_cast<uint32_t>(time(nullptr));
  a.file_size = static_cast<uint64_t>(size);
  a.crc32 = crc;
  a.ext = ext;
  a.uniquifier = store_.NextUniquifier();
  a.appender = appender;
  a.trunk = trunk_loc != nullptr;
  a.trunk_loc = trunk_loc;
  auto id = EncodeFileId(a);
  return id.has_value() ? *id : "";
}

// -- trunk integration ----------------------------------------------------

void StorageServer::RefreshClusterParams() {
  if (reporter_ == nullptr) return;
  // Runs on the main-loop timer; every nio/dio thread reads this state
  // (TrunkEligible/TrunkAlloc/...), so the whole transition is one
  // critical section.  The allocator pointer is swapped, never mutated
  // live — handlers that copied the shared_ptr finish on the old pool.
  std::lock_guard<RankedMutex> lk(trunk_mu_);
  auto params = reporter_->cluster_params();
  auto get = [&params](const char* key, int64_t dflt) {
    auto it = params.find(key);
    return it == params.end() ? dflt : atoll(it->second.c_str());
  };
  trunk_enabled_ = get("use_trunk_file", 0) != 0;
  slot_min_size_ = get("slot_min_size", slot_min_size_);
  slot_max_size_ = get("slot_max_size", slot_max_size_);
  trunk_file_size_ = get("trunk_file_size", trunk_file_size_);
  // Migrator pacing is a cluster param (tracker.conf:
  // rebalance_bandwidth_mb_s) so every member of a draining group
  // drains at the operator's one configured pace.
  if (rebalance_ != nullptr)
    rebalance_->set_bandwidth_mb_s(
        static_cast<int>(get("rebalance_bandwidth_mb_s", 8)));
  auto [tip, tport] = reporter_->trunk_server();
  trunk_ip_ = tip;
  trunk_port_ = tport;
  trunk_epoch_ = reporter_->trunk_epoch();
  // Slot alloc_size fields are uint32: a trunk_file_size >= 4GiB would
  // silently truncate the initial whole-file free block and corrupt the
  // allocator's view.  Refuse and disable trunk rather than corrupt
  // (latched log: this refires every param tick).
  if (trunk_enabled_ && trunk_file_size_ >= (4LL << 30)) {
    if (!trunk_size_err_logged_) {
      FDFS_LOG_ERROR("trunk_file_size %lld >= 4GiB unsupported: trunk "
                     "disabled", static_cast<long long>(trunk_file_size_));
      trunk_size_err_logged_ = true;
    }
    trunk_enabled_ = false;
  }
  bool am_trunk = trunk_enabled_ && !trunk_ip_.empty() &&
                  trunk_ip_ == MyIp() && trunk_port_ == cfg_.port;
  // A zeroed trailer means "unknown" (e.g. the reporting tracker briefly
  // cannot reach its leader), not "role lost": hold the current role
  // rather than flapping, which would void slots handed out but not yet
  // written.  A genuine move always names a different server.
  if (trunk_enabled_ && trunk_ip_.empty()) am_trunk = is_trunk_server_;
  // Any tick without the role cancels an armed-but-unexpired grace:
  // otherwise a role flap during the grace leaves a stale (expired)
  // deadline that would skip the grace entirely on the next regain.
  if (!am_trunk) trunk_regain_not_before_ = 0;
  if (am_trunk && !is_trunk_server_) {
    if (held_trunk_role_before_) {
      // REGAINING the role: slots allocated by the interim trunk server
      // may still be replicating here; a rescan now would list them free
      // and hand them out again (silent data loss).  Wait out a grace
      // period first, then rebuild the pool from a fresh disk scan.
      // (Replication lag beyond the grace is a residual risk; the
      // complete fix is an allocation epoch checked in the trunk RPC.)
      if (trunk_regain_not_before_ == 0) {
        trunk_regain_not_before_ = time(nullptr) + kTrunkRegainGraceS;
        FDFS_LOG_WARN("trunk role regained: holding %d s for in-flight "
                      "interim allocations before rescan",
                      kTrunkRegainGraceS);
      }
      if (time(nullptr) < trunk_regain_not_before_) {
        is_trunk_server_ = false;  // serve flat-file fallback meanwhile
        return;
      }
    }
    trunk_alloc_.reset();  // always rescan on a false->true transition
  }
  if (am_trunk && trunk_alloc_ == nullptr) {
    auto alloc = std::make_shared<TrunkAllocator>();
    std::string err;
    if (alloc->Init(store_.store_path(0), trunk_file_size_, &err)) {
      trunk_alloc_ = std::move(alloc);
      held_trunk_role_before_ = true;
      trunk_regain_not_before_ = 0;
      FDFS_LOG_INFO("this server is now the trunk server (%d trunk files, "
                    "%lld free bytes)",
                    trunk_alloc_->trunk_file_count(),
                    static_cast<long long>(trunk_alloc_->free_bytes()));
    } else {
      FDFS_LOG_ERROR("trunk allocator init failed: %s", err.c_str());
      am_trunk = false;
    }
  } else if (!am_trunk && is_trunk_server_) {
    trunk_alloc_.reset();  // role genuinely moved: the pool goes stale the
                           // moment the new trunk server starts allocating
    trunk_regain_not_before_ = 0;
  }
  is_trunk_server_ = am_trunk;
}

bool StorageServer::TrunkEligible(int64_t size) const {
  std::lock_guard<RankedMutex> lk(trunk_mu_);
  return trunk_enabled_ && size >= slot_min_size_ && size < slot_max_size_ &&
         (is_trunk_server_ || trunk_port_ > 0);
}

// Trunk RPC timeout: these calls run synchronously on the nio loop (as
// upstream's do on its service threads), so a dead trunk server stalls
// this event loop for at most this long before the upload falls back to a
// flat file.  The beat trailer clears a dead trunk server within ~1
// heartbeat, so the stall is one-shot, but an async alloc path would
// remove it entirely.
constexpr int kTrunkRpcTimeoutMs = 1000;

std::optional<TrunkLocation> StorageServer::TrunkAlloc(int64_t payload_size) {
  std::shared_ptr<TrunkAllocator> alloc;
  std::string ip;
  int port = 0;
  int64_t epoch = 0;
  {
    std::lock_guard<RankedMutex> lk(trunk_mu_);
    if (is_trunk_server_) alloc = trunk_alloc_;
    ip = trunk_ip_;
    port = trunk_port_;
    epoch = trunk_epoch_;
  }
  if (alloc != nullptr) return alloc->Alloc(payload_size);
  if (port > 0)
    return TrunkAllocRpc(ip, port, cfg_.group_name, payload_size, epoch,
                         kTrunkRpcTimeoutMs);
  return std::nullopt;
}

void StorageServer::TrunkFree(const TrunkLocation& loc) {
  std::shared_ptr<TrunkAllocator> alloc;
  std::string trunk_ip;
  int trunk_port = 0;
  int64_t epoch = 0;
  {
    std::lock_guard<RankedMutex> lk(trunk_mu_);
    if (is_trunk_server_) alloc = trunk_alloc_;
    trunk_ip = trunk_ip_;
    trunk_port = trunk_port_;
    epoch = trunk_epoch_;
  }
  if (alloc != nullptr) {
    alloc->Free(loc);
    return;
  }
  // Not the trunk server: free OUR copy of the slot on disk, then return
  // it to the group allocator.  (The RPC frees the trunk server's copy;
  // remaining replicas free theirs via the 'd' binlog replay.)
  MarkSlotFree(store_.store_path(0), loc);
  if (trunk_port > 0) {
    if (!TrunkFreeRpc(trunk_ip, trunk_port, cfg_.group_name, loc, epoch,
                      kTrunkRpcTimeoutMs))
      FDFS_LOG_WARN("trunk free RPC failed (id=%u off=%u): slot leaked until "
                    "the free-block checker reclaims it",
                    loc.trunk_id, loc.offset);
  }
}

std::string StorageServer::TrunkStoreUpload(Conn* c) {
  auto loc = TrunkAlloc(c->file_size);
  if (!loc.has_value()) return "";
  std::string payload;
  if (!ReadWholeFile(c->tmp_path, &payload) ||
      static_cast<int64_t>(payload.size()) != c->file_size) {
    TrunkFree(*loc);
    return "";
  }
  std::string err;
  if (!WriteSlotPayload(store_.store_path(0), *loc, payload, c->crc32,
                        &err)) {
    FDFS_LOG_ERROR("trunk slot write: %s", err.c_str());
    TrunkFree(*loc);
    return "";
  }
  // Trunk files always live under store path 0 (see trunk.h divergences).
  std::string id = MintFileId(0, c->file_size, c->crc32, c->ext,
                              /*appender=*/false, &*loc);
  if (id.empty()) {
    TrunkFree(*loc);
    return "";
  }
  bool am_trunk;
  std::string tip;
  int tport;
  int64_t tepoch;
  {
    std::lock_guard<RankedMutex> lk(trunk_mu_);
    am_trunk = is_trunk_server_;
    tip = trunk_ip_;
    tport = trunk_port_;
    tepoch = trunk_epoch_;
  }
  if (!am_trunk) TrunkConfirmRpc(tip, tport, cfg_.group_name, *loc, tepoch,
                                 kTrunkRpcTimeoutMs);
  return id;
}

void StorageServer::HandleTrunkRpc(Conn* c) {
  auto cmd = static_cast<StorageCmd>(c->cmd);
  const uint8_t* p = reinterpret_cast<const uint8_t*>(c->fixed.data());
  if (c->fixed.size() < 16 + 8 ||
      GroupFromField(p) != cfg_.group_name) {
    Respond(c, 22);
    return;
  }
  std::shared_ptr<TrunkAllocator> alloc;
  int64_t slot_max;
  int64_t my_epoch;
  {
    std::lock_guard<RankedMutex> lk(trunk_mu_);
    if (is_trunk_server_) alloc = trunk_alloc_;
    slot_max = slot_max_size_;
    my_epoch = trunk_epoch_;
  }
  if (alloc == nullptr) {
    Respond(c, 1 /*EPERM: not the trunk server*/);
    return;
  }
  // Epoch fencing: the RPC's trailing 8 bytes carry the caller's trunk
  // epoch (tracker-bumped on every role change).  A mismatch means a
  // stale trunk server serving after the role moved, or a stale client
  // — either way refuse (the caller falls back to a flat file) instead
  // of allocating a slot another server also thinks it owns.
  bool is_alloc = cmd == StorageCmd::kTrunkAllocSpace;
  size_t base = is_alloc ? 16u + 8u : 16u + 12u;
  if (c->fixed.size() < base + 8) {
    // The epoch is MANDATORY — an optional fence is no fence.
    Respond(c, 22);
    return;
  }
  int64_t caller_epoch = GetInt64BE(
      reinterpret_cast<const uint8_t*>(c->fixed.data()) + base);
  if (caller_epoch != my_epoch) {
    FDFS_LOG_WARN("trunk RPC epoch mismatch (caller %lld, mine %lld): "
                  "refusing", static_cast<long long>(caller_epoch),
                  static_cast<long long>(my_epoch));
    Respond(c, 16 /*EBUSY: stale role*/);
    return;
  }
  if (cmd == StorageCmd::kTrunkAllocSpace) {
    int64_t size = GetInt64BE(p + 16);
    if (size <= 0 || size >= slot_max) {
      Respond(c, 22);
      return;
    }
    auto loc = alloc->Alloc(size);
    if (!loc.has_value()) {
      Respond(c, 28 /*ENOSPC*/);
      return;
    }
    std::string out(12, '\0');
    uint8_t* q = reinterpret_cast<uint8_t*>(out.data());
    PutInt32BE(loc->trunk_id, q);
    PutInt32BE(loc->offset, q + 4);
    PutInt32BE(loc->alloc_size, q + 8);
    Respond(c, 0, out);
    return;
  }
  if (c->fixed.size() < 16 + 12) {
    Respond(c, 22);
    return;
  }
  TrunkLocation loc;
  loc.trunk_id = GetInt32BE(p + 16);
  loc.offset = GetInt32BE(p + 20);
  loc.alloc_size = GetInt32BE(p + 24);
  if (cmd == StorageCmd::kTrunkAllocConfirm) {
    // Allocation was durable at alloc time (see trunk.h divergences).
    Respond(c, 0);
    return;
  }
  Respond(c, alloc->Free(loc) ? 0 : 22);
}

bool StorageStats::SaveToFile(const std::string& path) const {
  // File keeps its historical 20-line shape (19 persisted counters + one
  // spare) so stat files from earlier builds load unchanged.
  int64_t v[20] = {0};
  Snapshot(v);
  std::string tmp = path + ".tmp";
  FILE* f = fopen(tmp.c_str(), "w");
  if (f == nullptr) return false;
  for (int i = 0; i < 20; ++i)
    fprintf(f, "%lld\n", static_cast<long long>(v[i]));
  fclose(f);
  return rename(tmp.c_str(), path.c_str()) == 0;
}

bool StorageStats::LoadFromFile(const std::string& path) {
  FILE* f = fopen(path.c_str(), "r");
  if (f == nullptr) return false;
  long long v[20] = {0};
  for (int i = 0; i < 20; ++i)
    if (fscanf(f, "%lld", &v[i]) != 1) break;
  fclose(f);
  total_upload = v[0]; success_upload = v[1];
  total_download = v[2]; success_download = v[3];
  total_delete = v[4]; success_delete = v[5];
  total_append = v[6]; success_append = v[7];
  total_set_meta = v[8]; success_set_meta = v[9];
  total_get_meta = v[10]; success_get_meta = v[11];
  total_query = v[12]; success_query = v[13];
  bytes_uploaded = v[14]; bytes_downloaded = v[15];
  dedup_hits = v[16]; dedup_bytes_saved = v[17];
  last_source_update = v[18];
  return true;
}

bool StorageServer::RemoteExists(const std::string& group,
                                 const std::string& remote,
                                 const std::string& local) {
  auto parts = DecodeFileId(group + "/" + remote);
  if (parts.has_value() && parts->trunk_loc.has_value()) {
    std::string tp =
        TrunkFilePath(store_.store_path(0), parts->trunk_loc->trunk_id);
    int fd = open(tp.c_str(), O_RDONLY);
    if (fd < 0) return false;
    auto h = ReadSlotHeader(fd, parts->trunk_loc->offset);
    close(fd);
    return h.has_value() && h->type == kTrunkSlotData &&
           h->alloc_size == parts->trunk_loc->alloc_size &&
           h->file_size == parts->file_size && h->crc32 == parts->crc32;
  }
  struct stat st;
  return stat(local.c_str(), &st) == 0 ||
         RecipeExistsFor(local);  // chunk recipe (flat or slab record)
}

// FETCH_ONE_PATH_BINLOG (26): binlog records whose file lives on the
// requested store path, as raw lines — the feed a recovering peer replays
// to re-download its wiped disk (storage_disk_recovery.c).  Paged: the
// optional request offset indexes the FILTERED stream and a short (or
// empty) page signals the end, so a multi-year binlog never has to fit
// in one response.
void StorageServer::HandleFetchOnePathBinlog(Conn* c) {
  constexpr int64_t kPageBytes = 8 << 20;
  if (c->fixed.size() < 17) {
    Respond(c, 22);
    return;
  }
  const uint8_t* p = reinterpret_cast<const uint8_t*>(c->fixed.data());
  if (GroupFromField(p) != cfg_.group_name) {
    Respond(c, 22);
    return;
  }
  int spi = static_cast<uint8_t>(c->fixed[16]);
  if (spi >= store_.store_path_count()) {
    Respond(c, 22);
    return;
  }
  int64_t offset = c->fixed.size() >= 25 ? GetInt64BE(p + 17) : 0;
  if (offset < 0) {
    Respond(c, 22);
    return;
  }
  Respond(c, 0, CollectOnePathBinlog(cfg_.base_path + "/data/sync", spi,
                                     offset, kPageBytes));
}

void StorageServer::HandleTrunkDownload(Conn* c, const FileIdParts& parts,
                                        int64_t offset, int64_t count) {
  const TrunkLocation& loc = *parts.trunk_loc;
  std::string path = TrunkFilePath(store_.store_path(0), loc.trunk_id);
  int fd = open(path.c_str(), O_RDONLY);
  if (fd < 0) {
    Respond(c, 2);
    return;
  }
  auto h = ReadSlotHeader(fd, loc.offset);
  // Full identity check (size AND crc): a reused slot can coincide in
  // size with the deleted file; serving the new occupant's bytes under
  // the old ID would be cross-file content disclosure.
  if (!h.has_value() || h->type != kTrunkSlotData ||
      h->alloc_size != loc.alloc_size || h->file_size != parts.file_size ||
      h->crc32 != parts.crc32) {
    close(fd);
    Respond(c, 2);  // slot reused or freed: the file is gone
    return;
  }
  int64_t size = h->file_size;
  if (offset > size) {
    close(fd);
    Respond(c, 22);
    return;
  }
  int64_t avail = size - offset;
  if (count == 0 || count > avail) count = avail;
  stats_.success_download++;
  RespondFile(c, 0, fd, loc.offset + kTrunkHeaderSize + offset, count);
}

void StorageServer::FinishUpload(Conn* c) {
  close(c->file_fd);
  c->file_fd = -1;
  bool appender =
      static_cast<StorageCmd>(c->cmd) == StorageCmd::kUploadAppenderFile;

  std::string digest;
  if (c->hashing) digest = c->sha1.Final().Hex();

  // Chunk-level dedup (north star): large uploads are CDC-chunked, the
  // chunks fingerprinted (on the TPU in sidecar mode), and only bytes the
  // chunk store has never seen are written — the file itself becomes a
  // small recipe.  Appenders stay flat (mutable).  Failure of any kind
  // falls through to the classic flat store.
  if (!appender && ChunkEligible(c->file_size)) {
    std::string id = MintFileId(c->store_path_index, c->file_size, c->crc32,
                                c->ext, false);
    std::optional<FileIdParts> parts;
    if (!id.empty()) parts = DecodeFileId(id);
    if (parts.has_value()) {
      std::string local = LocalPath(store_.store_path(c->store_path_index),
                                    parts->RemoteFilename())
                              .value();
      // No EnsureParentDirs here: a slab-resident recipe needs no
      // fan-out directory (StoreRecipe creates the chain only for the
      // flat sidecar; the flat-store fallback below makes its own).
      int64_t saved = 0, hits = 0;
      ChunkStageUs st;
      if (StoreChunkedFromTmp(c->tmp_path, c->store_path_index, c->file_size,
                              local + ".rcp",
                              cfg_.group_name + "/" + parts->RemoteFilename(),
                              &saved, &hits, &st)) {
        unlink(c->tmp_path.c_str());
        c->tmp_path.clear();
        stats_.dedup_hits += hits;
        stats_.dedup_bytes_saved += saved;
        int64_t t_bl = MonoUs();
        binlog_.Append(kBinlogOpCreate, parts->RemoteFilename());
        c->binlog_us = MonoUs() - t_bl;
        c->fp_us = st.fp;
        c->fp_lock_us = st.fp_lock;
        c->cswrite_us = st.cs_write;
        NoteTracedMutation(c, parts->RemoteFilename());
        stats_.success_upload++;
        stats_.last_source_update = time(nullptr);
        NoteHeat(c, HeatOp::kUpload,
                 cfg_.group_name + "/" + parts->RemoteFilename());
        Respond(c, 0,
                PackGroupField(cfg_.group_name) + parts->RemoteFilename());
        return;
      }
    }
  }

  // Dedup verdict (plugin boundary; appender files are mutable => exempt).
  if (dedup_ != nullptr && !appender) {
    auto verdict = dedup_->Judge(digest, c->file_size);
    if (verdict.duplicate) {
      auto dup = DecodeFileId(verdict.dup_of);
      if (dup.has_value() && dup->group == cfg_.group_name &&
          dup->store_path_index < store_.store_path_count()) {
        int spi = dup->store_path_index;
        std::string id = MintFileId(spi, c->file_size, c->crc32, c->ext, false);
        auto parts = DecodeFileId(id);
        std::string new_local =
            LocalPath(store_.store_path(spi), parts->RemoteFilename()).value();
        std::string dup_local =
            LocalPath(store_.store_path(spi), dup->RemoteFilename()).value();
        StoreManager::EnsureParentDirs(new_local);
        if (link(dup_local.c_str(), new_local.c_str()) == 0) {
          unlink(c->tmp_path.c_str());
          c->tmp_path.clear();
          stats_.dedup_hits++;
          stats_.dedup_bytes_saved += c->file_size;
          stats_.success_upload++;
          stats_.last_source_update = time(nullptr);
          binlog_.Append(kBinlogOpLink, parts->RemoteFilename(),
                         dup->RemoteFilename());
          NoteTracedMutation(c, parts->RemoteFilename());
          NoteHeat(c, HeatOp::kUpload,
                   cfg_.group_name + "/" + parts->RemoteFilename());
          Respond(c, 0, PackGroupField(cfg_.group_name) + parts->RemoteFilename());
          return;
        }
        // Stale mapping (canonical copy deleted): fall through to a normal
        // store and let Commit repoint the digest.
        dedup_->Forget(verdict.dup_of);
      }
    }
  }

  // Small-file packing (SURVEY §2.3): eligible uploads go into a trunk
  // slot instead of their own inode; failure falls back to a flat file.
  if (!appender && TrunkEligible(c->file_size)) {
    std::string tid = TrunkStoreUpload(c);
    if (!tid.empty()) {
      unlink(c->tmp_path.c_str());
      c->tmp_path.clear();
      auto tparts = DecodeFileId(tid);
      if (dedup_ != nullptr) dedup_->Commit(digest, tid);
      binlog_.Append(kBinlogOpCreate, tparts->RemoteFilename());
      NoteTracedMutation(c, tparts->RemoteFilename());
      stats_.success_upload++;
      stats_.last_source_update = time(nullptr);
      NoteHeat(c, HeatOp::kUpload,
               cfg_.group_name + "/" + tparts->RemoteFilename());
      Respond(c, 0, PackGroupField(cfg_.group_name) + tparts->RemoteFilename());
      return;
    }
  }

  std::string id = MintFileId(c->store_path_index, c->file_size, c->crc32,
                              c->ext, appender);
  if (id.empty()) {
    unlink(c->tmp_path.c_str());
    Respond(c, 22);
    return;
  }
  auto parts = DecodeFileId(id);
  std::string local = LocalPath(store_.store_path(c->store_path_index),
                                parts->RemoteFilename())
                          .value();
  StoreManager::EnsureParentDirs(local);
  if (rename(c->tmp_path.c_str(), local.c_str()) != 0) {
    FDFS_LOG_ERROR("rename %s -> %s: %s", c->tmp_path.c_str(), local.c_str(),
                   strerror(errno));
    unlink(c->tmp_path.c_str());
    Respond(c, 5);
    return;
  }
  c->tmp_path.clear();
  if (dedup_ != nullptr && !appender) dedup_->Commit(digest, id);
  int64_t t_bl = MonoUs();
  binlog_.Append(kBinlogOpCreate, parts->RemoteFilename());
  c->binlog_us = MonoUs() - t_bl;
  NoteTracedMutation(c, parts->RemoteFilename());
  stats_.success_upload++;
  stats_.last_source_update = time(nullptr);
  NoteHeat(c, HeatOp::kUpload, cfg_.group_name + "/" + parts->RemoteFilename());
  Respond(c, 0, PackGroupField(cfg_.group_name) + parts->RemoteFilename());
}

std::string StorageServer::ResolveLocal(const std::string& group,
                                        const std::string& remote) const {
  if (group != cfg_.group_name) return "";
  int spi = 0;
  if (remote.size() < 3 || sscanf(remote.c_str(), "M%02X/", &spi) != 1)
    return "";
  if (spi >= store_.store_path_count()) return "";
  auto lp = LocalPath(store_.store_path(spi), remote);
  return lp.has_value() ? *lp : "";
}

// -- chunk-level dedup (north star) ---------------------------------------

bool StorageServer::ChunkEligible(int64_t size) const {
  return dedup_ != nullptr && cfg_.dedup_chunk_threshold > 0 &&
         size >= cfg_.dedup_chunk_threshold && !chunk_stores_.empty();
}

ChunkStore* StorageServer::StoreForLocal(const std::string& local) const {
  for (int i = 0; i < store_.store_path_count() &&
                  i < static_cast<int>(chunk_stores_.size()); ++i) {
    const std::string& sp = store_.store_path(i);
    if (local.compare(0, sp.size(), sp) == 0) return chunk_stores_[i].get();
  }
  return nullptr;
}

std::optional<Recipe> StorageServer::LoadRecipeFor(
    const std::string& local) const {
  ChunkStore* cs = StoreForLocal(local);
  return cs != nullptr ? cs->LoadRecipe(local + ".rcp")
                       : ReadRecipeFile(local + ".rcp");
}

bool StorageServer::RecipeExistsFor(const std::string& local) const {
  ChunkStore* cs = StoreForLocal(local);
  if (cs != nullptr) return cs->HasRecipe(local + ".rcp");
  struct stat st;
  return stat((local + ".rcp").c_str(), &st) == 0;
}

bool StorageServer::StoreChunkedFromTmp(const std::string& tmp_path, int spi,
                                        int64_t size,
                                        const std::string& rcp_path,
                                        const std::string& file_ref,
                                        int64_t* saved_bytes,
                                        int64_t* chunk_hits,
                                        ChunkStageUs* stage) {
  return ChunkedStoreWith(dedup_.get(), tmp_path, spi, size, rcp_path,
                          file_ref, saved_bytes, chunk_hits, stage);
}

bool StorageServer::ChunkedStoreWith(DedupPlugin* plugin,
                                     const std::string& tmp_path, int spi,
                                     int64_t size, const std::string& rcp_path,
                                     const std::string& file_ref,
                                     int64_t* saved_bytes,
                                     int64_t* chunk_hits,
                                     ChunkStageUs* stage) {
  if (spi >= static_cast<int>(chunk_stores_.size())) return false;
  ChunkStore* cs = chunk_stores_[spi].get();
  int fd = open(tmp_path.c_str(), O_RDONLY);
  if (fd < 0) return false;

  // One upload = one fingerprint session; committed to `file_ref` on
  // success, aborted on any failure so the plugin never leaks pending
  // state into the next upload (flat-fallback included).
  const int64_t session = plugin->BeginChunked();
  Recipe recipe;
  recipe.logical_size = size;
  std::string seg;
  int64_t seg_base = 0;
  bool ok = true;
  while (ok && seg_base < size) {
    int64_t want = std::min<int64_t>(cfg_.dedup_segment_bytes,
                                     size - seg_base);
    seg.resize(static_cast<size_t>(want));
    int64_t got = 0;
    while (got < want) {
      ssize_t r = read(fd, seg.data() + got, want - got);
      if (r <= 0) break;
      got += r;
    }
    if (got != want) {
      ok = false;
      break;
    }
    // Fingerprint this segment (accelerated in sidecar mode: CDC +
    // batched SHA1 run on the TPU); then write only unseen chunks.
    std::vector<ChunkFp> fps;
    int64_t t0 = MonoUs();
    TakeDedupLockWaitUs();  // clear: attribute only this call's wait
    bool fp_ok = plugin->FingerprintChunks(session, seg.data(), seg.size(),
                                           seg_base, &fps);
    if (stage != nullptr) {
      stage->fp += MonoUs() - t0;
      stage->fp_lock += TakeDedupLockWaitUs();
    }
    if (!fp_ok) {
      ok = false;  // fingerprinting unavailable: caller stores flat
      break;
    }
    t0 = MonoUs();
    for (const ChunkFp& fp : fps) {
      bool existed = false;
      std::string err;
      if (!cs->PutAndRef(fp.digest_hex,
                         seg.data() + (fp.offset - seg_base), fp.length,
                         &existed, &err)) {
        FDFS_LOG_ERROR("chunk store: %s", err.c_str());
        ok = false;
        break;
      }
      if (existed) {
        *saved_bytes += fp.length;
        ++*chunk_hits;
        if (ctr_dedup_chunk_hits_ != nullptr)
          ctr_dedup_chunk_hits_->fetch_add(1, std::memory_order_relaxed);
      } else if (ctr_dedup_chunk_misses_ != nullptr) {
        ctr_dedup_chunk_misses_->fetch_add(1, std::memory_order_relaxed);
      }
      recipe.chunks.push_back({fp.digest_hex, fp.length});
    }
    if (stage != nullptr) stage->cs_write += MonoUs() - t0;
    seg_base += want;
  }
  close(fd);
  std::string err;
  if (!ok || !cs->StoreRecipe(rcp_path, recipe, &err)) {
    if (ok) FDFS_LOG_ERROR("recipe write: %s", err.c_str());
    // Roll back references taken so far; untouched chunks stay for
    // other recipes, newly-written orphans fall to the startup GC.
    cs->UnrefAll(recipe);
    plugin->AbortChunked(session);
    return false;
  }
  plugin->CommitChunked(session, file_ref);
  return true;
}

int64_t StorageServer::LogicalSize(const std::string& local) const {
  struct stat st;
  if (stat(local.c_str(), &st) == 0) return st.st_size;
  auto r = LoadRecipeFor(local);
  return r.has_value() ? r->logical_size : -1;
}

int StorageServer::OpenLogical(const std::string& local, int64_t* size) {
  int fd = open(local.c_str(), O_RDONLY);
  if (fd >= 0) {
    struct stat st;
    fstat(fd, &st);
    *size = st.st_size;
    return fd;
  }
  auto r = LoadRecipeFor(local);
  if (!r.has_value()) return -1;
  ChunkStore* cs = StoreForLocal(local);
  if (cs == nullptr) return -1;
  // Materialize into an unlinked temp file: downstream sendfile paths
  // (downloads, sync replication) keep working unchanged, and the bytes
  // are reclaimed automatically on close.  The temp lives under the
  // store path's always-present tmp/ dir, NOT next to `local` — a
  // slab-resident recipe's fan-out directory may never have existed
  // (lazy dirs are the slab layout's inode win).
  std::string tmp;
  for (int i = 0; i < store_.store_path_count(); ++i) {
    const std::string& sp = store_.store_path(i);
    if (local.compare(0, sp.size(), sp) == 0) {
      tmp = store_.NewTmpPath(i);
      break;
    }
  }
  if (tmp.empty()) tmp = local + ".assm." + std::to_string(getpid());
  fd = open(tmp.c_str(), O_CREAT | O_RDWR | O_TRUNC, 0600);
  if (fd < 0) return -1;
  unlink(tmp.c_str());
  std::string chunk;
  for (const RecipeEntry& e : r->chunks) {
    if (!cs->ReadChunk(e.digest_hex, e.length, &chunk)) {
      FDFS_LOG_ERROR("missing chunk %s for %s", e.digest_hex.c_str(),
                     local.c_str());
      close(fd);
      return -1;
    }
    size_t off = 0;
    while (off < chunk.size()) {
      ssize_t w = write(fd, chunk.data() + off, chunk.size() - off);
      if (w <= 0) {
        close(fd);
        return -1;
      }
      off += static_cast<size_t>(w);
    }
  }
  *size = r->logical_size;
  lseek(fd, 0, SEEK_SET);
  return fd;
}

int StorageServer::RemoveLogical(const std::string& local,
                                 const std::string& file_ref) {
  // Delete the recipe sidecar WITH the file id and account its bytes to
  // the integrity engine (scrub.bytes_reclaimed / recipes_reclaimed):
  // the recipe — flat .rcp inode or slab record — is real disk the
  // delete reclaims, same as the chunks GC frees later (slab records go
  // dead now and the compactor returns the bytes).
  auto drop_recipe = [this, &local, &file_ref](const std::string& rcp) {
    ChunkStore* cs = StoreForLocal(local);
    auto r = cs != nullptr ? cs->LoadRecipe(rcp) : ReadRecipeFile(rcp);
    if (!r.has_value()) return 2;
    int64_t rcp_bytes = 0;
    if (cs != nullptr) {
      if (!cs->RemoveRecipe(rcp, &rcp_bytes)) return 5;
    } else {
      struct stat st;
      rcp_bytes = stat(rcp.c_str(), &st) == 0 ? st.st_size : 0;
      if (unlink(rcp.c_str()) != 0 && errno != ENOENT) return 5;
    }
    if (cs != nullptr) cs->UnrefAll(*r);
    if (dedup_ != nullptr) dedup_->ForgetChunked(file_ref);
    if (scrub_ != nullptr) scrub_->NoteRecipeReclaimed(rcp_bytes);
    return 0;
  };
  std::string rcp = local + ".rcp";
  if (unlink(local.c_str()) == 0) {
    // Flat inode gone; also clear any stale recipe sidecar left under
    // the same name (belt-and-braces — the two should never coexist,
    // but a leaked recipe would hold chunk refs forever).
    if (RecipeExistsFor(local)) drop_recipe(rcp);
    return 0;
  }
  if (errno != ENOENT) return 5;
  return drop_recipe(rcp);
}

void StorageServer::HandleDownload(Conn* c) {
  stats_.total_download++;
  // body: 8B offset + 8B count + 16B group + remote_filename
  if (c->fixed.size() < 16 + 16 + 10) {
    Respond(c, 22);
    return;
  }
  const uint8_t* p = reinterpret_cast<const uint8_t*>(c->fixed.data());
  int64_t offset = GetInt64BE(p);
  int64_t count = GetInt64BE(p + 8);
  std::string group = GroupFromField(p + 16);
  std::string remote = c->fixed.substr(32);
  if (offset < 0 || count < 0) {
    Respond(c, 22);
    return;
  }
  // Heat: every download attempt (including failures — a hot missing
  // key is an operator signal too) counts against its file-id.
  NoteHeat(c, HeatOp::kDownload, group + "/" + remote);
  // Trunk files are served out of their slot, not an inode of their own.
  auto tparts = DecodeFileId(group + "/" + remote);
  if (tparts.has_value() && tparts->trunk_loc.has_value()) {
    if (group != cfg_.group_name) {
      Respond(c, 22);
      return;
    }
    HandleTrunkDownload(c, *tparts, offset, count);
    return;
  }
  std::string local = ResolveLocal(group, remote);
  if (local.empty()) {
    Respond(c, 22);
    return;
  }
  // Ranged request = explicit offset or byte count (the parallel
  // client splits one file into ranges; per-replica affinity makes the
  // read caches accumulate hits).  Counted once per request, with the
  // bytes actually served.
  bool ranged = offset != 0 || count != 0;
  auto note_ranged = [&](int64_t served) {
    if (ranged && ctr_download_ranged_requests_ != nullptr) {
      ctr_download_ranged_requests_->fetch_add(1, std::memory_order_relaxed);
      ctr_download_ranged_bytes_->fetch_add(served,
                                            std::memory_order_relaxed);
    }
  };
  int fd = open(local.c_str(), O_RDONLY);
  if (fd >= 0) {  // flat file: sendfile
    struct stat st;
    fstat(fd, &st);
    int64_t size = st.st_size;
    if (offset > size) {
      close(fd);
      Respond(c, 22);
      return;
    }
    int64_t avail = size - offset;
    if (count == 0 || count > avail) count = avail;
    stats_.success_download++;
    note_ranged(count);
    RespondFile(c, 0, fd, offset, count);
    return;
  }
  // Chunk recipe: stream chunk-by-chunk as the socket drains — never
  // materialize the logical file (a multi-GB download must not stall
  // this loop's other connections).
  ChunkStore* cs = StoreForLocal(local);
  if (cs == nullptr) {
    // No chunk store for this path (dedup off).  If a recipe exists the
    // file is REAL data from an earlier dedup_mode config — answer EIO
    // (retryable) so disk recovery never mistakes it for deleted; with
    // no recipe either, the file is simply gone: ENOENT, which recovery
    // treats as "deleted on the peer, skip".
    Respond(c, access((local + ".rcp").c_str(), F_OK) == 0 ? 5 : 2);
    return;
  }
  // Read + pin-per-chunk (verify under the stripe lock): a delete
  // between a plain read and a later pin could unlink chunks this
  // stream is about to send.  Ranged requests pin ONLY the overlapping
  // recipe slice — a 4-range parallel download of a many-thousand-chunk
  // file must not pay 4x full-recipe pin/unpin.
  int64_t skip = 0;
  auto r = cs->ReadRecipeAndPinRange(local + ".rcp", offset, count, &skip);
  if (!r.has_value()) {
    Respond(c, 2);
    return;
  }
  int64_t size = r->logical_size;
  if (offset > size) {
    cs->UnpinRecipe(*r);  // empty slice: no pins were taken
    Respond(c, 22);
    return;
  }
  int64_t avail = size - offset;
  if (count == 0 || count > avail) count = avail;
  auto rs = std::make_unique<RecipeStream>();
  rs->cs = cs;
  rs->remaining = count;
  rs->skip = skip;
  rs->recipe = std::move(*r);
  rs->pinned = true;  // pinned by ReadRecipeAndPin above
  stats_.success_download++;
  note_ranged(count);
  LogAccess(c, 0, count);
  c->out.resize(kHeaderSize);
  PutInt64BE(count, reinterpret_cast<uint8_t*>(c->out.data()));
  c->out[8] = static_cast<char>(StorageCmd::kResp);
  c->out[9] = 0;
  c->out_off = 0;
  c->rstream = std::move(rs);
  c->state = ConnState::kSend;
  if (!c->async_pending) WriteConn(c);
}

void StorageServer::HandleDelete(Conn* c) {
  // Chunk-recipe GC can unref thousands of chunks; run it off-loop on
  // the file's OWN store-path pool (cross-path deletes must not starve
  // another path's uploads).
  int spi = 0;
  if (c->fixed.size() >= 16 + 4)
    sscanf(c->fixed.c_str() + 16, "M%02X/", &spi);
  OffloadToDio(c, spi, [this, c] { DeleteWork(c); });
}

void StorageServer::DeleteWork(Conn* c) {
  bool replica = static_cast<StorageCmd>(c->cmd) == StorageCmd::kSyncDeleteFile;
  if (!replica) stats_.total_delete++;
  if (c->fixed.size() < 16 + 10) {
    Respond(c, 22);
    return;
  }
  const uint8_t* p = reinterpret_cast<const uint8_t*>(c->fixed.data());
  std::string group = GroupFromField(p);
  std::string remote = c->fixed.substr(16);
  auto tparts = DecodeFileId(group + "/" + remote);
  if (tparts.has_value() && tparts->trunk_loc.has_value()) {
    // Trunk delete: release the slot.  The header's identity facts must
    // match the deleting ID — an async 'd' replay arriving after the slot
    // was reused must NOT free the new occupant.
    if (group != cfg_.group_name) {
      Respond(c, 22);
      return;
    }
    std::string tpath =
        TrunkFilePath(store_.store_path(0), tparts->trunk_loc->trunk_id);
    int tfd = open(tpath.c_str(), O_RDONLY);
    std::optional<TrunkSlotHeader> h;
    if (tfd >= 0) {
      h = ReadSlotHeader(tfd, tparts->trunk_loc->offset);
      close(tfd);
    }
    bool live = h.has_value() && h->type == kTrunkSlotData &&
                h->alloc_size == tparts->trunk_loc->alloc_size &&
                h->file_size == tparts->file_size &&
                h->crc32 == tparts->crc32;
    std::string sidecar = ResolveLocal(group, remote);
    if (replica) {
      // Replay: free our local copy if this exact file still occupies the
      // slot; otherwise it is already gone (or reused) — both fine.
      if (live) MarkSlotFree(store_.store_path(0), *tparts->trunk_loc);
      if (!sidecar.empty()) unlink((sidecar + "-m").c_str());
      binlog_.Append('d', remote);
      Respond(c, 0);
      return;
    }
    if (!live) {
      Respond(c, 2);
      return;
    }
    TrunkFree(*tparts->trunk_loc);
    if (!sidecar.empty()) unlink((sidecar + "-m").c_str());
    if (dedup_ != nullptr) dedup_->Forget(group + "/" + remote);
    binlog_.Append(kBinlogOpDelete, remote);
    stats_.success_delete++;
    stats_.last_source_update = time(nullptr);
    Respond(c, 0);
    return;
  }
  std::string local = ResolveLocal(group, remote);
  if (local.empty()) {
    Respond(c, 22);
    return;
  }
  int rc = RemoveLogical(local, group + "/" + remote);
  if (rc != 0) {
    Respond(c, static_cast<uint8_t>(rc));
    return;
  }
  unlink((local + "-m").c_str());  // metadata sidecar, if any
  if (dedup_ != nullptr) dedup_->Forget(group + "/" + remote);
  binlog_.Append(replica ? 'd' : kBinlogOpDelete, remote);
  if (!replica) {
    stats_.success_delete++;
    stats_.last_source_update = time(nullptr);
  }
  Respond(c, 0);
}

void StorageServer::HandleNearDups(Conn* c) {
  // Operator near-dup query: "what is this file similar to?", answered
  // from the dedup engine's MinHash/LSH index.  Body mirrors
  // kQueryFileInfo (16B group + remote filename); response is ranked
  // text lines "<file_id> <score>".  The sidecar RPC blocks, so the
  // work leaves the nio loop.
  if (c->fixed.size() < 16 + 10) {
    Respond(c, 22);
    return;
  }
  const uint8_t* p = reinterpret_cast<const uint8_t*>(c->fixed.data());
  std::string group = GroupFromField(p);
  if (group != cfg_.group_name) {
    Respond(c, 22);
    return;
  }
  OffloadToDio(c, 0, [this, c] {
    std::string file_id = cfg_.group_name + "/" + c->fixed.substr(16);
    std::string out;
    bool no_data = false;
    if (dedup_ == nullptr || !dedup_->NearDups(file_id, &out, &no_data)) {
      Respond(c, 95);  // ENOTSUP: no near index in this dedup mode
      return;
    }
    Respond(c, no_data ? 61 : 0, out);  // ENODATA: file carries no signature
  });
}

void StorageServer::HandleQueryFileInfo(Conn* c) {
  stats_.total_query++;
  if (c->fixed.size() < 16 + 10) {
    Respond(c, 22);
    return;
  }
  const uint8_t* p = reinterpret_cast<const uint8_t*>(c->fixed.data());
  std::string group = GroupFromField(p);
  std::string remote = c->fixed.substr(16);
  // Identity facts come from the ID itself (no-metadata-database design).
  auto parts = DecodeFileId(group + "/" + remote);
  if (!parts.has_value()) {
    Respond(c, 22);
    return;
  }
  struct stat st;
  if (parts->trunk_loc.has_value()) {
    // Header-only stat: size + full identity check without touching the
    // payload bytes.
    std::string tp =
        TrunkFilePath(store_.store_path(0), parts->trunk_loc->trunk_id);
    int tfd = open(tp.c_str(), O_RDONLY);
    std::optional<TrunkSlotHeader> h;
    if (tfd >= 0) {
      h = ReadSlotHeader(tfd, parts->trunk_loc->offset);
      close(tfd);
    }
    if (!h.has_value() || h->type != kTrunkSlotData ||
        h->alloc_size != parts->trunk_loc->alloc_size ||
        h->file_size != parts->file_size || h->crc32 != parts->crc32) {
      Respond(c, 2);
      return;
    }
    st.st_size = static_cast<off_t>(h->file_size);
  } else {
    std::string local = ResolveLocal(group, remote);
    if (local.empty()) {
      Respond(c, 22);
      return;
    }
    int64_t lsize = LogicalSize(local);  // plain stat or recipe header
    if (lsize < 0) {
      Respond(c, 2);
      return;
    }
    st.st_size = static_cast<off_t>(lsize);
  }
  std::string body(40, '\0');
  uint8_t* out = reinterpret_cast<uint8_t*>(body.data());
  PutInt64BE(st.st_size, out);
  PutInt64BE(parts->create_timestamp, out + 8);
  PutInt64BE(parts->crc32, out + 16);
  std::string ip = UnpackIp(parts->source_ip);
  memcpy(out + 24, ip.data(), std::min<size_t>(ip.size(), 15));
  stats_.success_query++;
  Respond(c, 0, body);
}

void StorageServer::HandleSetMetadata(Conn* c) {
  stats_.total_set_meta++;
  // body: 16B group + 1B flag(O/M) + 8B name_len + name + metadata
  if (c->fixed.size() < 16 + 1 + 8) {
    Respond(c, 22);
    return;
  }
  const uint8_t* p = reinterpret_cast<const uint8_t*>(c->fixed.data());
  std::string group = GroupFromField(p);
  char flag = static_cast<char>(p[16]);
  int64_t name_len = GetInt64BE(p + 17);
  if (name_len <= 0 || name_len > 512 ||
      c->fixed.size() < 25 + static_cast<size_t>(name_len)) {
    Respond(c, 22);
    return;
  }
  std::string remote = c->fixed.substr(25, static_cast<size_t>(name_len));
  std::string meta = c->fixed.substr(25 + static_cast<size_t>(name_len));
  std::string local = ResolveLocal(group, remote);
  if (local.empty() || (flag != 'O' && flag != 'M')) {
    Respond(c, 22);
    return;
  }
  if (!RemoteExists(group, remote, local)) {
    Respond(c, 2);
    return;
  }
  std::string meta_path = local + "-m";
  if (flag == 'M') {
    // merge: existing records kept unless overwritten
    FILE* f = fopen(meta_path.c_str(), "r");
    if (f != nullptr) {
      std::string old;
      char buf[4096];
      size_t n;
      while ((n = fread(buf, 1, sizeof(buf), f)) > 0) old.append(buf, n);
      fclose(f);
      // naive merge: parse both, new wins
      auto parse = [](const std::string& s) {
        std::unordered_map<std::string, std::string> m;
        size_t pos = 0;
        while (pos < s.size()) {
          size_t rec_end = s.find('\x01', pos);
          if (rec_end == std::string::npos) rec_end = s.size();
          std::string rec = s.substr(pos, rec_end - pos);
          size_t sep = rec.find('\x02');
          if (sep != std::string::npos)
            m[rec.substr(0, sep)] = rec.substr(sep + 1);
          pos = rec_end + 1;
        }
        return m;
      };
      auto merged = parse(old);
      for (auto& [k, v] : parse(meta)) merged[k] = v;
      std::string out;
      for (auto& [k, v] : merged) {
        if (!out.empty()) out += '\x01';
        out += k + '\x02' + v;
      }
      meta = out;
    }
  }
  // Trunk files have no flat write that would have created the fan-out
  // dir their sidecar lives in.
  StoreManager::EnsureParentDirs(meta_path);
  if (!WriteSidecarAtomic(meta_path, meta)) {
    Respond(c, 5);
    return;
  }
  binlog_.Append(kBinlogOpUpdate, remote);
  stats_.success_set_meta++;
  stats_.last_source_update = time(nullptr);
  Respond(c, 0);
}

void StorageServer::HandleGetMetadata(Conn* c) {
  stats_.total_get_meta++;
  if (c->fixed.size() < 16 + 10) {
    Respond(c, 22);
    return;
  }
  const uint8_t* p = reinterpret_cast<const uint8_t*>(c->fixed.data());
  std::string group = GroupFromField(p);
  std::string remote = c->fixed.substr(16);
  std::string local = ResolveLocal(group, remote);
  if (local.empty()) {
    Respond(c, 22);
    return;
  }
  FILE* f = fopen((local + "-m").c_str(), "r");
  std::string meta;
  if (f != nullptr) {
    char buf[4096];
    size_t n;
    while ((n = fread(buf, 1, sizeof(buf), f)) > 0) meta.append(buf, n);
    fclose(f);
  } else if (!RemoteExists(group, remote, local)) {
    Respond(c, 2);
    return;
  }
  stats_.success_get_meta++;
  Respond(c, 0, meta);
}

// SYNC_APPEND_FILE / SYNC_MODIFY_FILE replica replay: writes a byte range
// into an existing file at an exact offset.  Two-stage fixed read like
// SYNC_CREATE; the range bytes then stream through kRecvFile straight into
// the target (no tmp file — replay is idempotent: a duplicate delivery
// rewrites the same bytes at the same offset).
bool StorageServer::BeginSyncRange(Conn* c) {
  const uint8_t* p = reinterpret_cast<const uint8_t*>(c->fixed.data());
  int64_t name_len = GetInt64BE(p + kGroupNameMaxLen);
  int64_t offset = GetInt64BE(p + kGroupNameMaxLen + 8);
  int64_t length = GetInt64BE(p + kGroupNameMaxLen + 16);
  if (c->fixed.size() == 40) {
    if (name_len <= 0 || name_len > 512 || offset < 0 || length < 0 ||
        c->pkg_len != 40 + name_len + length) {
      RespondError(c, 22);
      return false;
    }
    c->fixed_need = 40 + static_cast<size_t>(name_len);
    return true;  // keep reading the name (still kRecvFixed)
  }
  std::string group = GroupFromField(p);
  c->sync_remote = c->fixed.substr(40);
  std::string local = ResolveLocal(group, c->sync_remote);
  if (local.empty()) {
    RespondError(c, 22);
    return false;
  }
  if (!AcquireBusy(c, c->sync_remote)) {
    // The sync sender retries transiently-failed records, so EBUSY here
    // (client append racing the replay) resolves itself on the next pass.
    RespondError(c, 16 /*EBUSY*/);
    return false;
  }
  int fd = open(local.c_str(), O_WRONLY);
  if (fd < 0) {
    RespondError(c, static_cast<uint8_t>(errno == ENOENT ? 2 : 5));
    return false;
  }
  struct stat st;
  fstat(fd, &st);
  if (offset > st.st_size) {  // gap — out-of-order replay
    close(fd);
    RespondError(c, 22);
    return false;
  }
  if (lseek(fd, offset, SEEK_SET) != offset) {
    close(fd);
    RespondError(c, 5);
    return false;
  }
  c->file_fd = fd;
  c->range_offset = offset;
  c->file_size = length;
  c->file_remaining = length;
  c->state = ConnState::kRecvFile;
  return true;
}

// SYNC_UPDATE_FILE replica replay: refresh the metadata sidecar.
void StorageServer::HandleSyncUpdate(Conn* c) {
  const uint8_t* p = reinterpret_cast<const uint8_t*>(c->fixed.data());
  if (c->fixed.size() < 32) {
    Respond(c, 22);
    return;
  }
  std::string group = GroupFromField(p);
  int64_t name_len = GetInt64BE(p + kGroupNameMaxLen);
  int64_t meta_len = GetInt64BE(p + kGroupNameMaxLen + 8);
  if (name_len <= 0 || name_len > 512 || meta_len < 0 ||
      c->fixed.size() != 32 + static_cast<size_t>(name_len + meta_len)) {
    Respond(c, 22);
    return;
  }
  std::string remote = c->fixed.substr(32, static_cast<size_t>(name_len));
  std::string meta = c->fixed.substr(32 + static_cast<size_t>(name_len));
  std::string local = ResolveLocal(group, remote);
  if (local.empty()) {
    Respond(c, 22);
    return;
  }
  if (!RemoteExists(group, remote, local)) {
    Respond(c, 2);
    return;
  }
  StoreManager::EnsureParentDirs(local + "-m");
  if (!WriteSidecarAtomic(local + "-m", meta)) {
    Respond(c, 5);
    return;
  }
  binlog_.Append('u', remote);
  Respond(c, 0);
}

// TRUNCATE_FILE (client, appender files only) and SYNC_TRUNCATE_FILE
// (replica replay).  Same wire: 16B group + 8B name_len + 8B new_size +
// name.  Reference: storage_service.c:storage_server_truncate_file().
void StorageServer::HandleTruncate(Conn* c) {
  bool source = static_cast<StorageCmd>(c->cmd) == StorageCmd::kTruncateFile;
  if (source) stats_.total_append++;
  const uint8_t* p = reinterpret_cast<const uint8_t*>(c->fixed.data());
  if (c->fixed.size() < 32) {
    Respond(c, 22);
    return;
  }
  std::string group = GroupFromField(p);
  int64_t name_len = GetInt64BE(p + kGroupNameMaxLen);
  int64_t new_size = GetInt64BE(p + kGroupNameMaxLen + 8);
  if (name_len <= 0 || name_len > 512 || new_size < 0 ||
      c->fixed.size() != 32 + static_cast<size_t>(name_len)) {
    Respond(c, 22);
    return;
  }
  std::string remote = c->fixed.substr(32);
  std::string local = ResolveLocal(group, remote);
  if (local.empty()) {
    Respond(c, 22);
    return;
  }
  if (source) {
    // Only appender files are mutable (reference: EPERM on regular files).
    auto parts = DecodeFileId(group + "/" + remote);
    if (!parts.has_value() || !parts->appender) {
      Respond(c, 1 /*EPERM*/);
      return;
    }
  }
  // A truncate racing a mid-stream append/modify on the same file would
  // punch holes past the new EOF and desync the binlog from reality; the
  // per-file busy lock covers every mutation, truncate included.
  // (Released by ResetForNextRequest on every exit path.)
  if (!AcquireBusy(c, remote)) {
    Respond(c, 16 /*EBUSY*/);
    return;
  }
  if (truncate(local.c_str(), new_size) != 0) {
    Respond(c, static_cast<uint8_t>(errno == ENOENT ? 2 : 5));
    return;
  }
  binlog_.Append(source ? kBinlogOpTruncate : 't', remote,
                 std::to_string(new_size));
  if (source) {
    stats_.success_append++;
    stats_.last_source_update = time(nullptr);
  }
  Respond(c, 0);
}

// APPEND_FILE / MODIFY_FILE: client-side mutation of an appender file.
// APPEND wire:  16B group + 8B name_len + 8B length + name + bytes.
// MODIFY wire:  16B group + 8B name_len + 8B offset + 8B length + name +
// bytes.  Reference: storage_service.c:storage_append_file() /
// storage_modify_file().
bool StorageServer::BeginClientRange(Conn* c) {
  bool is_append = static_cast<StorageCmd>(c->cmd) == StorageCmd::kAppendFile;
  const size_t prefix = is_append ? 32 : 40;
  const uint8_t* p = reinterpret_cast<const uint8_t*>(c->fixed.data());
  int64_t name_len = GetInt64BE(p + kGroupNameMaxLen);
  int64_t offset = is_append ? -1 : GetInt64BE(p + kGroupNameMaxLen + 8);
  int64_t length = GetInt64BE(p + kGroupNameMaxLen + (is_append ? 8 : 16));
  if (c->fixed.size() == prefix) {
    if (name_len <= 0 || name_len > 512 || length < 0 ||
        (!is_append && offset < 0) ||
        c->pkg_len != static_cast<int64_t>(prefix) + name_len + length) {
      RespondError(c, 22);
      return false;
    }
    c->fixed_need = prefix + static_cast<size_t>(name_len);
    return true;  // keep reading the name
  }
  std::string group = GroupFromField(p);
  c->sync_remote = c->fixed.substr(prefix);
  std::string local = ResolveLocal(group, c->sync_remote);
  auto parts = DecodeFileId(group + "/" + c->sync_remote);
  if (local.empty() || !parts.has_value() || !parts->appender) {
    RespondError(c, 1 /*EPERM: not an appender file*/);
    return false;
  }
  if (!AcquireBusy(c, c->sync_remote)) {
    RespondError(c, 16 /*EBUSY: concurrent mutation of this file*/);
    return false;
  }
  int fd = open(local.c_str(), O_WRONLY);
  if (fd < 0) {
    RespondError(c, static_cast<uint8_t>(errno == ENOENT ? 2 : 5));
    return false;
  }
  struct stat st;
  fstat(fd, &st);
  if (offset < 0) offset = st.st_size;  // append lands at EOF
  if (offset > st.st_size) {
    close(fd);
    RespondError(c, 22);
    return false;
  }
  if (lseek(fd, offset, SEEK_SET) != offset) {
    close(fd);
    RespondError(c, 5);
    return false;
  }
  c->file_fd = fd;
  c->range_offset = offset;
  c->file_size = length;
  c->file_remaining = length;
  c->state = ConnState::kRecvFile;
  return true;
}

// UPLOAD_SLAVE_FILE: store a derived file under the master's name stem
// plus a prefix ("<stem><prefix>.<ext>"), so clients can address it from
// the master ID alone.  Wire: 16B group + 8B master_len + 8B size +
// 16B prefix + 6B ext + master_name + bytes.  Reference:
// storage_service.c:storage_upload_slave_file() (cmd 21).
bool StorageServer::BeginSlaveUpload(Conn* c) {
  const size_t kPrefixLen = 16 + 8 + 8 + 16 + 6;
  const uint8_t* p = reinterpret_cast<const uint8_t*>(c->fixed.data());
  int64_t master_len = GetInt64BE(p + kGroupNameMaxLen);
  int64_t size = GetInt64BE(p + kGroupNameMaxLen + 8);
  if (c->fixed.size() == kPrefixLen) {
    if (master_len <= 0 || master_len > 512 || size < 0 ||
        c->pkg_len != static_cast<int64_t>(kPrefixLen) + master_len + size) {
      RespondError(c, 22);
      return false;
    }
    c->fixed_need = kPrefixLen + static_cast<size_t>(master_len);
    return true;
  }
  std::string group = GroupFromField(p);
  c->slave_prefix = GetFixedField(p + kGroupNameMaxLen + 16, 16);
  c->ext = ExtFromField(p + kGroupNameMaxLen + 32);
  std::string master = c->fixed.substr(kPrefixLen);
  std::string master_local = ResolveLocal(group, master);
  auto parts = DecodeFileId(group + "/" + master);
  if (master_local.empty() || !parts.has_value() ||
      c->slave_prefix.empty() || !parts->prefix.empty() /*no slave-of-slave*/ ||
      !RemoteExists(group, master, master_local) /*trunk-aware*/) {
    RespondError(c, 22);
    return false;
  }
  // Derived name: master path with "<stem><prefix>[.ext]" as the filename.
  size_t slash = master.rfind('/');
  size_t dot = master.find('.', slash);
  std::string stem = dot == std::string::npos ? master : master.substr(0, dot);
  c->sync_remote = stem + c->slave_prefix;
  if (!c->ext.empty()) c->sync_remote += "." + c->ext;
  if (ResolveLocal(group, c->sync_remote).empty()) {
    RespondError(c, 22);  // prefix/ext failed name validation
    return false;
  }
  sscanf(c->sync_remote.c_str(), "M%02X/", &c->store_path_index);
  c->file_size = size;
  c->file_remaining = size;
  c->crc32 = 0;
  c->hashing = false;
  c->tmp_path = store_.NewTmpPath(c->store_path_index);
  c->file_fd = open(c->tmp_path.c_str(), O_CREAT | O_WRONLY | O_TRUNC, 0644);
  if (c->file_fd < 0) {
    RespondError(c, 5);
    return false;
  }
  c->state = ConnState::kRecvFile;
  return true;
}

void StorageServer::FinishSlaveUpload(Conn* c) {
  close(c->file_fd);
  c->file_fd = -1;
  std::string local = ResolveLocal(cfg_.group_name, c->sync_remote);
  StoreManager::EnsureParentDirs(local);
  // A slave name is deterministic — refuse to silently clobber an existing
  // slave (reference returns EEXIST).
  struct stat st;
  if (stat(local.c_str(), &st) == 0) {
    unlink(c->tmp_path.c_str());
    c->tmp_path.clear();
    Respond(c, 17 /*EEXIST*/);
    return;
  }
  if (rename(c->tmp_path.c_str(), local.c_str()) != 0) {
    unlink(c->tmp_path.c_str());
    c->tmp_path.clear();
    Respond(c, 5);
    return;
  }
  c->tmp_path.clear();
  binlog_.Append(kBinlogOpCreate, c->sync_remote);
  stats_.success_upload++;
  stats_.last_source_update = time(nullptr);
  NoteHeat(c, HeatOp::kUpload, cfg_.group_name + "/" + c->sync_remote);
  Respond(c, 0, PackGroupField(cfg_.group_name) + c->sync_remote);
}

// CREATE_LINK (client, cmd 20) and SYNC_CREATE_LINK (replica replay).
// Body: 16B group + target_remote \x02 src_remote; creates a hard link so
// the target shares the source's bytes (the dedup path uses the same
// mechanism internally).
void StorageServer::HandleCreateLink(Conn* c) {
  bool source = static_cast<StorageCmd>(c->cmd) == StorageCmd::kCreateLink;
  const uint8_t* p = reinterpret_cast<const uint8_t*>(c->fixed.data());
  if (c->fixed.size() <= static_cast<size_t>(kGroupNameMaxLen)) {
    Respond(c, 22);
    return;
  }
  std::string group = GroupFromField(p);
  std::string rest = c->fixed.substr(kGroupNameMaxLen);
  size_t sep = rest.find('\x02');
  if (group != cfg_.group_name || sep == std::string::npos) {
    Respond(c, 22);
    return;
  }
  std::string target = rest.substr(0, sep);
  std::string src = rest.substr(sep + 1);
  std::string tl = ResolveLocal(group, target);
  std::string sl = ResolveLocal(group, src);
  if (tl.empty() || sl.empty()) {
    Respond(c, 22);
    return;
  }
  StoreManager::EnsureParentDirs(tl);
  if (link(sl.c_str(), tl.c_str()) != 0 && errno != EEXIST) {
    // Chunked source: "linking" means duplicating the (tiny) recipe and
    // taking a reference on each chunk.
    bool linked = false;
    if (errno == ENOENT) {
      auto r = LoadRecipeFor(sl);
      ChunkStore* cs = StoreForLocal(sl);
      ChunkStore* tcs = StoreForLocal(tl);
      if (r.has_value() && cs != nullptr && cs->RefAll(*r)) {
        std::string err;
        // Store through the TARGET path's store so LoadRecipeFor(tl)
        // finds it in the same slab index it will later consult.
        bool stored = tcs != nullptr
                          ? tcs->StoreRecipe(tl + ".rcp", *r, &err)
                          : WriteRecipeFile(tl + ".rcp", *r, &err);
        if (stored) {
          linked = true;
        } else {
          cs->UnrefAll(*r);
          FDFS_LOG_ERROR("link recipe copy: %s", err.c_str());
        }
      }
    }
    if (!linked) {
      Respond(c, static_cast<uint8_t>(errno == ENOENT ? 2 : 5));
      return;
    }
  }
  binlog_.Append(source ? kBinlogOpLink : 'l', target, src);
  if (source) stats_.last_source_update = time(nullptr);
  Respond(c, 0);
}

}  // namespace fdfs
