// Dedup plugin boundary on the upload path.
//
// This is the rebuild's analogue of the reference's storage-plugin hook in
// storage/storage_func.h (north star: "gated behind the existing
// storage-plugin hook so the classic C path remains the default").  Two
// granularities:
//
//  * Whole-file (Judge/Commit/Forget): files below the chunking threshold
//    are judged by their stream SHA1; duplicates become hardlinks + an 'L'
//    binlog record.
//  * Chunk-level (FingerprintChunks): larger streams are content-defined
//    chunked and per-chunk fingerprinted; the daemon then writes only
//    chunks its ChunkStore has never seen and a small recipe file.  The
//    fingerprinting is the accelerated part — the sidecar runs CDC +
//    batched SHA1 + MinHash on the TPU (fastdfs_tpu/sidecar.py); the cpu
//    plugin is the serial C++ referee with identical cut-points.
//
// Modes: none (classic CRC32-only path), cpu (in-process), sidecar (TPU
// engine over a unix socket).  The sidecar path FAILS OPEN: uploads never
// block on the accelerator — unreachable sidecar means store-flat.
#pragma once

#include <cstdint>
#include <memory>
#include <mutex>

#include "common/lockrank.h"
#include <string>
#include <unordered_map>
#include <vector>

namespace fdfs {

struct ChunkFp {
  int64_t offset = 0;
  int64_t length = 0;
  std::string digest_hex;  // 40-char lowercase SHA1 of the chunk bytes
};

class DedupPlugin {
 public:
  virtual ~DedupPlugin() = default;

  struct Verdict {
    bool duplicate = false;
    std::string dup_of;  // existing file id (full "group/M.." form)
  };

  // -- whole-file granularity --------------------------------------------
  virtual Verdict Judge(const std::string& sha1_hex, int64_t file_size) = 0;
  virtual void Commit(const std::string& sha1_hex, const std::string& file_id) = 0;
  virtual void Forget(const std::string& file_id) = 0;  // on delete
  virtual bool Save() { return true; }   // snapshot (checkpoint/resume)
  virtual const char* Name() const = 0;

  // -- chunk granularity -------------------------------------------------
  // One chunked upload = one SESSION: BeginChunked() mints an id that
  // scopes all pending fingerprint state (file signature, digest
  // attributions) until CommitChunked binds it to the final file id or
  // AbortChunked discards it (flat-fallback, failed upload).  Explicit
  // sessions — not connection identity — so concurrent uploads over one
  // plugin and multi-threaded daemons (work_threads > 1) cannot
  // interleave state.
  virtual int64_t BeginChunked() { return 0; }
  // CDC + per-chunk SHA1 over one SEGMENT of an upload stream.  Segments
  // are independently chunked (CDC restarts at segment boundaries) so a
  // multi-GB file never needs a contiguous buffer; `base_offset` shifts
  // the reported chunk offsets to absolute stream positions.  Returns
  // false when chunk fingerprinting is unavailable (caller stores flat).
  virtual bool FingerprintChunks(int64_t session, const char* data,
                                 size_t len, int64_t base_offset,
                                 std::vector<ChunkFp>* out) {
    (void)session; (void)data; (void)len; (void)base_offset; (void)out;
    return false;
  }
  // Chunked-file lifecycle notifications (near-dup index bookkeeping in
  // the sidecar; no-ops for the cpu plugin — its ChunkStore IS the index).
  virtual void CommitChunked(int64_t session, const std::string& file_id) {
    (void)session; (void)file_id;
  }
  virtual void AbortChunked(int64_t session) { (void)session; }
  virtual void ForgetChunked(const std::string& file_id) { (void)file_id; }

  // Ranked near-dup report for a stored file (kNearDups command): *out
  // gets text lines "<file_id> <score>".  Returns false when this mode
  // has no near index (none/cpu — the caller answers ENOTSUP);
  // *no_data=true when the mode supports it but the file carries no
  // signature (ENODATA).
  virtual bool NearDups(const std::string& file_id, std::string* out,
                        bool* no_data) {
    (void)file_id; (void)out; (void)no_data;
    return false;
  }

  // Batched chunk-integrity verify for the scrubber (kDedupVerify RPC):
  // `payloads` is each chunk's bytes concatenated in `chunks` order
  // (lengths from ChunkFp::length; digests from digest_hex).  On
  // success *bad_mask has one byte per chunk (0 = digest matches,
  // 1 = mismatch).  Returns false when batched verification is
  // unavailable (none/cpu modes, sidecar unreachable) — the caller
  // falls back to its serial host SHA1.
  virtual bool VerifyChunks(const std::vector<ChunkFp>& chunks,
                            const std::string& payloads,
                            std::string* bad_mask) {
    (void)chunks; (void)payloads; (void)bad_mask;
    return false;
  }
};

// CPU baseline: exact SHA1 digest map, snapshotted to
// <base_path>/data/dedup_index.dat (atomic write-then-rename); chunk
// fingerprints via the serial gear CDC (common/cdc.h).
class CpuDedup : public DedupPlugin {
 public:
  explicit CpuDedup(std::string snapshot_path);
  Verdict Judge(const std::string& sha1_hex, int64_t file_size) override;
  void Commit(const std::string& sha1_hex, const std::string& file_id) override;
  void Forget(const std::string& file_id) override;
  bool Save() override;
  const char* Name() const override { return "cpu"; }
  bool FingerprintChunks(int64_t session, const char* data, size_t len,
                         int64_t base_offset,
                         std::vector<ChunkFp>* out) override;
  bool LoadSnapshot();
  size_t size() const { return by_digest_.size(); }

 private:
  std::string snapshot_path_;
  mutable RankedMutex mu_{LockRank::kDedupEngine};  // handlers run on every nio/dio thread
  std::unordered_map<std::string, std::string> by_digest_;  // sha1 -> file id
  std::unordered_map<std::string, std::string> by_file_;    // file id -> sha1
};

// Sidecar: TPU dedup engine process over a unix-domain socket, speaking
// the DEDUP_* opcodes on the standard framing (see
// fastdfs_tpu/sidecar.py).  Falls open (treats everything as unique /
// unchunkable) when the sidecar is unreachable.
class SidecarDedup : public DedupPlugin {
 public:
  explicit SidecarDedup(std::string socket_path);
  ~SidecarDedup() override;
  Verdict Judge(const std::string& sha1_hex, int64_t file_size) override;
  void Commit(const std::string& sha1_hex, const std::string& file_id) override;
  void Forget(const std::string& file_id) override;
  const char* Name() const override { return "sidecar"; }
  int64_t BeginChunked() override;
  bool FingerprintChunks(int64_t session, const char* data, size_t len,
                         int64_t base_offset,
                         std::vector<ChunkFp>* out) override;
  void CommitChunked(int64_t session, const std::string& file_id) override;
  void AbortChunked(int64_t session) override;
  void ForgetChunked(const std::string& file_id) override;
  bool NearDups(const std::string& file_id, std::string* out,
                bool* no_data) override;
  bool VerifyChunks(const std::vector<ChunkFp>& chunks,
                    const std::string& payloads,
                    std::string* bad_mask) override;

 private:
  // Connection pool: each in-flight RPC borrows its own fd, so
  // concurrent dio threads overlap their sidecar round-trips instead of
  // serializing on one shared connection (the sidecar itself only
  // serializes index mutation, not fingerprint compute).  Up to
  // kMaxIdleFds idle connections are retained.
  static constexpr int kMaxIdleFds = 4;
  // *pooled reports whether the fd came from the idle pool (a failure
  // on it retries once on a fresh connection — pooled sockets go stale
  // when the sidecar restarts).  -1 on connect failure.
  int AcquireFd(bool* pooled);
  void ReleaseFd(int fd);   // return a healthy fd to the pool
  bool Rpc(uint8_t cmd, const std::string& body, std::string* resp,
           uint8_t* status, int64_t max_resp = 1 << 20);
  std::string socket_path_;
  RankedMutex mu_{LockRank::kDedupPool};  // guards pool_
  std::vector<int> pool_;
};

std::unique_ptr<DedupPlugin> MakeDedupPlugin(const std::string& mode,
                                             const std::string& base_path,
                                             const std::string& sidecar_path);

// Thread-local sidecar lock-wait accounting: SidecarDedup adds the time
// THIS thread spent queued on the connection-pool mutex (connection
// setup is excluded — it is transport cost, not serialization).  The
// upload path reads-and-clears it around its fingerprint calls to
// attribute the wait per request in the access log.
int64_t TakeDedupLockWaitUs();

}  // namespace fdfs
