// Dedup plugin boundary on the upload path.
//
// This is the rebuild's analogue of the reference's storage-plugin hook in
// storage/storage_func.h (north star: "gated behind the existing
// storage-plugin hook so the classic C path remains the default").  The
// daemon streams every uploaded byte through an incremental SHA1 when a
// plugin is active; the plugin judges duplicates and the daemon commits
// unique bytes (dup files become hardlinks + an 'L' binlog record).
//
// Modes: none (classic CRC32-only path), cpu (in-process digest map),
// sidecar (TPU dedup engine over a unix socket — the JAX/Pallas path).
#pragma once

#include <cstdint>
#include <memory>
#include <string>
#include <unordered_map>

namespace fdfs {

class DedupPlugin {
 public:
  virtual ~DedupPlugin() = default;

  struct Verdict {
    bool duplicate = false;
    std::string dup_of;  // existing file id (full "group/M.." form)
  };

  virtual Verdict Judge(const std::string& sha1_hex, int64_t file_size) = 0;
  virtual void Commit(const std::string& sha1_hex, const std::string& file_id) = 0;
  virtual void Forget(const std::string& file_id) = 0;  // on delete
  virtual bool Save() { return true; }   // snapshot (checkpoint/resume)
  virtual const char* Name() const = 0;
};

// CPU baseline: exact SHA1 digest map, snapshotted to
// <base_path>/data/dedup_index.dat (atomic write-then-rename).
class CpuDedup : public DedupPlugin {
 public:
  explicit CpuDedup(std::string snapshot_path);
  Verdict Judge(const std::string& sha1_hex, int64_t file_size) override;
  void Commit(const std::string& sha1_hex, const std::string& file_id) override;
  void Forget(const std::string& file_id) override;
  bool Save() override;
  const char* Name() const override { return "cpu"; }
  bool LoadSnapshot();
  size_t size() const { return by_digest_.size(); }

 private:
  std::string snapshot_path_;
  std::unordered_map<std::string, std::string> by_digest_;  // sha1 -> file id
  std::unordered_map<std::string, std::string> by_file_;    // file id -> sha1
};

// Sidecar: TPU dedup engine process over a unix-domain socket, speaking
// the DEDUP_* opcodes on the standard framing (see
// fastdfs_tpu/dedup/sidecar.py).  Falls open (treats everything as unique)
// when the sidecar is unreachable, so uploads never block on the
// accelerator path.
class SidecarDedup : public DedupPlugin {
 public:
  explicit SidecarDedup(std::string socket_path);
  ~SidecarDedup() override;
  Verdict Judge(const std::string& sha1_hex, int64_t file_size) override;
  void Commit(const std::string& sha1_hex, const std::string& file_id) override;
  void Forget(const std::string& file_id) override;
  const char* Name() const override { return "sidecar"; }

 private:
  bool EnsureConnected();
  bool Rpc(uint8_t cmd, const std::string& body, std::string* resp,
           uint8_t* status);
  std::string socket_path_;
  int fd_ = -1;
};

std::unique_ptr<DedupPlugin> MakeDedupPlugin(const std::string& mode,
                                             const std::string& base_path,
                                             const std::string& sidecar_path);

}  // namespace fdfs
