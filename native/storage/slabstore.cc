#include "storage/slabstore.h"

#include <dirent.h>
#include <fcntl.h>
#include <string.h>
#include <sys/stat.h>
#include <sys/uio.h>
#include <unistd.h>

#include <algorithm>
#include <cerrno>
#include <cstdio>
#include <ctime>
#include <set>

#include "common/bytes.h"
#include "common/log.h"

namespace fdfs {

namespace {

constexpr char kSlabMagic[4] = {'F', 'S', 'L', 'B'};
constexpr uint8_t kSlabVersion = 1;
constexpr uint8_t kSlabFlagDead = 0x01;
constexpr int kFlagsOffset = 6;

int64_t RecordExtent(size_t key_len, int64_t alloc_len) {
  return static_cast<int64_t>(kSlabRecordHeaderSize + key_len) + alloc_len;
}

// Read exactly [offset, offset+len) of fd into dst; false on any short
// read or error.
bool PreadAll(int fd, char* dst, int64_t len, int64_t offset) {
  int64_t got = 0;
  while (got < len) {
    ssize_t r = pread(fd, dst + got, static_cast<size_t>(len - got),
                      offset + got);
    if (r <= 0) return false;
    got += r;
  }
  return true;
}

// Vectored read of the full iov chain at offset; false on any short
// read or error.  Advances through partial reads like PreadAll.
bool PreadvAll(int fd, struct iovec* iov, int iovcnt, int64_t offset) {
  while (iovcnt > 0) {
    ssize_t r = preadv(fd, iov, iovcnt, offset);
    if (r <= 0) return false;
    offset += r;
    while (r > 0 && iovcnt > 0) {
      if (static_cast<size_t>(r) >= iov->iov_len) {
        r -= static_cast<ssize_t>(iov->iov_len);
        ++iov;
        --iovcnt;
      } else {
        iov->iov_base = static_cast<char*>(iov->iov_base) + r;
        iov->iov_len -= static_cast<size_t>(r);
        r = 0;
      }
    }
  }
  return true;
}

bool WriteAll(int fd, const char* data, size_t len, int64_t offset) {
  size_t off = 0;
  while (off < len) {
    ssize_t w = pwrite(fd, data + off, len - off,
                       offset + static_cast<int64_t>(off));
    if (w <= 0) return false;
    off += static_cast<size_t>(w);
  }
  return true;
}

}  // namespace

std::string SlabEncodeRecord(uint8_t kind, const std::string& key,
                             const char* data, size_t len, int64_t mtime) {
  std::string rec;
  rec.reserve(kSlabRecordHeaderSize + key.size() + len);
  rec.append(kSlabMagic, sizeof(kSlabMagic));
  rec.push_back(static_cast<char>(kSlabVersion));
  rec.push_back(static_cast<char>(kind));
  rec.push_back('\0');  // flags (live); zeroed in the header CRC anyway
  rec.push_back(static_cast<char>(key.size()));
  uint8_t num[8];
  PutInt64BE(static_cast<int64_t>(len), num);  // alloc == payload today
  rec.append(reinterpret_cast<char*>(num), 8);
  PutInt64BE(static_cast<int64_t>(len), num);
  rec.append(reinterpret_cast<char*>(num), 8);
  uint8_t crc[4];
  PutInt32BE(Crc32(data, len), crc);
  rec.append(reinterpret_cast<char*>(crc), 4);
  PutInt64BE(mtime, num);
  rec.append(reinterpret_cast<char*>(num), 8);
  PutInt32BE(Crc32(rec.data(), 36), crc);
  rec.append(reinterpret_cast<char*>(crc), 4);
  rec.append(key);
  rec.append(data, len);
  return rec;
}

bool SlabDecodeRecord(const char* p, size_t avail, SlabRecordView* out) {
  if (avail < kSlabRecordHeaderSize) return false;
  if (memcmp(p, kSlabMagic, sizeof(kSlabMagic)) != 0) return false;
  const uint8_t* u = reinterpret_cast<const uint8_t*>(p);
  if (u[4] != kSlabVersion) return false;
  uint8_t kind = u[5];
  if (kind != kSlabKindChunk && kind != kSlabKindRecipe) return false;
  uint8_t flags = u[6];
  size_t key_len = u[7];
  int64_t alloc_len = GetInt64BE(u + 8);
  int64_t payload_len = GetInt64BE(u + 16);
  if (key_len == 0 || alloc_len < 0 || payload_len < 0 ||
      payload_len > alloc_len)
    return false;
  // Header CRC covers bytes [0,36) with the flags byte zeroed, so the
  // in-place dead mark never invalidates it.
  uint8_t hdr[36];
  memcpy(hdr, p, 36);
  hdr[kFlagsOffset] = 0;
  if (Crc32(hdr, 36) != GetInt32BE(u + 36)) return false;
  if (avail < kSlabRecordHeaderSize + key_len) return false;
  out->kind = kind;
  out->flags = flags;
  out->key.assign(p + kSlabRecordHeaderSize, key_len);
  out->alloc_len = alloc_len;
  out->payload_len = payload_len;
  out->payload_crc32 = GetInt32BE(u + 24);
  out->mtime = GetInt64BE(u + 28);
  out->record_len = RecordExtent(key_len, alloc_len);
  return true;
}

SlabStore::SlabStore(std::string dir, int64_t slab_bytes, int min_dead_pct)
    : dir_(std::move(dir)),
      slab_bytes_(slab_bytes < (1 << 20) ? (1 << 20) : slab_bytes),
      min_dead_pct_(min_dead_pct < 1 ? 1 : (min_dead_pct > 100 ? 100
                                                               : min_dead_pct)) {
  for (int i = 0; i < kIndexStripes; ++i) index_[i].mu.set_order_key(i);
}

SlabStore::~SlabStore() {
  if (active_fd_ >= 0) close(active_fd_);
  if (flag_fd_ >= 0) close(flag_fd_);
}

int SlabStore::StripeFor(const std::string& ikey) const {
  return static_cast<int>(std::hash<std::string>{}(ikey) %
                          static_cast<size_t>(kIndexStripes));
}

std::string SlabStore::SlabPath(int64_t slab_id) const {
  char name[32];
  snprintf(name, sizeof(name), "%010lld.slab",
           static_cast<long long>(slab_id));
  return dir_ + "/" + name;
}

void SlabStore::FlagDeadOnDisk(int64_t slab_id, int64_t record_off) const {
  // mu_ held (every call site).  The fd is cached per slab — see the
  // member comment.
  if (flag_fd_ >= 0 && flag_fd_slab_ != slab_id) {
    close(flag_fd_);
    flag_fd_ = -1;
  }
  if (flag_fd_ < 0) {
    flag_fd_ = open(SlabPath(slab_id).c_str(), O_WRONLY);
    if (flag_fd_ < 0) return;  // best-effort: RAM accounting rules
    flag_fd_slab_ = slab_id;
  }
  char dead = static_cast<char>(kSlabFlagDead);
  if (pwrite(flag_fd_, &dead, 1, record_off + kFlagsOffset) != 1)
    FDFS_LOG_WARN("slab %lld: dead-flag write at %lld failed: %s",
                  static_cast<long long>(slab_id),
                  static_cast<long long>(record_off), strerror(errno));
}

void SlabStore::AccountDeadLocked(int64_t slab_id, int64_t record_extent) {
  auto it = slabs_.find(slab_id);
  if (it != slabs_.end()) {
    it->second.live_slots--;
    it->second.dead_slots++;
    it->second.live_bytes -= record_extent;
    it->second.dead_bytes += record_extent;
  }
  slots_live_.fetch_sub(1, std::memory_order_relaxed);
  slots_dead_.fetch_add(1, std::memory_order_relaxed);
  bytes_live_.fetch_sub(record_extent, std::memory_order_relaxed);
  bytes_dead_.fetch_add(record_extent, std::memory_order_relaxed);
}

bool SlabStore::EnsureActiveLocked(int64_t need, std::string* err) {
  if (active_fd_ >= 0 && active_size_ >= slab_bytes_) {
    close(active_fd_);
    active_fd_ = -1;
  }
  if (active_fd_ < 0) {
    if (active_id_ == 0) {
      // First append of this process with no scan: start after the
      // highest existing slab (ScanRebuild normally sets this).
      active_id_ = 1;
      for (const auto& [id, info] : slabs_)
        if (id >= active_id_) active_id_ = id + 1;
    } else if (active_size_ >= slab_bytes_) {
      active_id_++;
    }
    // First append may precede any other write under the store root:
    // create the parent chain (…/data, then …/data/slabs).
    size_t slash = dir_.rfind('/');
    if (slash != std::string::npos)
      mkdir(dir_.substr(0, slash).c_str(), 0755);
    mkdir(dir_.c_str(), 0755);
    std::string path = SlabPath(active_id_);
    active_fd_ = open(path.c_str(), O_CREAT | O_WRONLY, 0644);
    if (active_fd_ < 0) {
      *err = "open " + path + ": " + strerror(errno);
      return false;
    }
    struct stat st;
    active_size_ = fstat(active_fd_, &st) == 0 ? st.st_size : 0;
    slabs_.emplace(active_id_, SlabInfo{});
    files_.store(static_cast<int64_t>(slabs_.size()),
                 std::memory_order_relaxed);
    auto& info = slabs_[active_id_];
    if (info.size_bytes < active_size_) info.size_bytes = active_size_;
  }
  (void)need;
  return true;
}

bool SlabStore::AppendInternal(uint8_t kind, const std::string& key,
                               const char* data, size_t len, bool durable,
                               const Slot* expect_old, std::string* err) {
  if (key.empty() || key.size() > kSlabKeyMaxLen) {
    *err = "slab key length " + std::to_string(key.size()) +
           " out of range";
    return false;
  }
  int64_t now = time(nullptr);
  std::string rec = SlabEncodeRecord(kind, key, data, len, now);
  Slot fresh;
  fresh.mtime = now;
  {
    std::lock_guard<RankedMutex> lk(mu_);
    if (!EnsureActiveLocked(static_cast<int64_t>(rec.size()), err))
      return false;
    int64_t off = active_size_;
    if (!WriteAll(active_fd_, rec.data(), rec.size(), off)) {
      *err = "append " + SlabPath(active_id_) + ": " + strerror(errno);
      // Trim any partial tail so a later append never leaves a torn
      // record in the middle of the file.
      if (ftruncate(active_fd_, off) != 0)
        FDFS_LOG_WARN("slab %lld: truncate after failed append: %s",
                      static_cast<long long>(active_id_), strerror(errno));
      return false;
    }
    if (durable && fsync(active_fd_) != 0) {
      *err = "fsync " + SlabPath(active_id_) + ": " + strerror(errno);
      if (ftruncate(active_fd_, off) != 0)
        FDFS_LOG_WARN("slab %lld: truncate after failed fsync: %s",
                      static_cast<long long>(active_id_), strerror(errno));
      return false;
    }
    active_size_ = off + static_cast<int64_t>(rec.size());
    fresh.slab_id = active_id_;
    fresh.record_off = off;
    fresh.payload_off = off + static_cast<int64_t>(kSlabRecordHeaderSize +
                                                   key.size());
    fresh.payload_len = static_cast<int64_t>(len);
    int64_t extent = static_cast<int64_t>(rec.size());
    auto& info = slabs_[active_id_];
    info.size_bytes = active_size_;
    info.live_slots++;
    info.live_bytes += extent;
    slots_live_.fetch_add(1, std::memory_order_relaxed);
    bytes_live_.fetch_add(extent, std::memory_order_relaxed);

    // Publish under the index stripe (mu_ still held: rank 92 -> 94,
    // and the dead-accounting of a replaced entry needs mu_ anyway).
    std::string ikey = IndexKey(kind, key);
    IndexStripe& st = index_[StripeFor(ikey)];
    std::lock_guard<RankedMutex> ilk(st.mu);
    auto it = st.map.find(ikey);
    if (expect_old != nullptr &&
        (it == st.map.end() || it->second.slab_id != expect_old->slab_id ||
         it->second.record_off != expect_old->record_off)) {
      // Compaction raced a delete or a replace of this key: the copy we
      // just appended is already stale — mark it dead, keep the index
      // as the racer left it.
      AccountDeadLocked(fresh.slab_id, extent);
      FlagDeadOnDisk(fresh.slab_id, fresh.record_off);
      return true;
    }
    if (it != st.map.end()) {
      // Replace semantics: the old record dies in place.
      Slot old = it->second;
      AccountDeadLocked(old.slab_id,
                        RecordExtent(key.size(), old.payload_len));
      FlagDeadOnDisk(old.slab_id, old.record_off);
      it->second = fresh;
    } else {
      st.map.emplace(std::move(ikey), fresh);
    }
  }
  return true;
}

bool SlabStore::Append(uint8_t kind, const std::string& key,
                       const char* data, size_t len, bool durable,
                       std::string* err) {
  return AppendInternal(kind, key, data, len, durable, nullptr, err);
}

bool SlabStore::Lookup(uint8_t kind, const std::string& key,
                       Slot* slot) const {
  std::string ikey = IndexKey(kind, key);
  const IndexStripe& st = index_[StripeFor(ikey)];
  std::lock_guard<RankedMutex> lk(st.mu);
  auto it = st.map.find(ikey);
  if (it == st.map.end()) return false;
  *slot = it->second;
  return true;
}

bool SlabStore::Has(uint8_t kind, const std::string& key) const {
  Slot s;
  return Lookup(kind, key, &s);
}

bool SlabStore::Read(uint8_t kind, const std::string& key,
                     std::string* out) const {
  // Lookup -> open -> pread, retried through a fresh lookup: a
  // compaction may unlink the slab between lookup and open, but it
  // re-appended (and re-indexed) the record before doing so, so a
  // fresh lookup lands on a live copy.  An fd opened before the unlink
  // keeps reading valid bytes (POSIX), so only the open can race — but
  // back-to-back compaction rounds can move the record again, so the
  // retry is a small loop, not a single second chance.
  for (int attempt = 0; attempt < 5; ++attempt) {
    Slot s;
    if (!Lookup(kind, key, &s)) return false;
    int fd = open(SlabPath(s.slab_id).c_str(), O_RDONLY);
    if (fd < 0) continue;
    out->resize(static_cast<size_t>(s.payload_len));
    bool ok = PreadAll(fd, out->data(), s.payload_len, s.payload_off);
    close(fd);
    if (ok) return true;
  }
  return false;
}

bool SlabStore::ReadSlice(uint8_t kind, const std::string& key,
                          int64_t offset, int64_t len, char* dst) const {
  for (int attempt = 0; attempt < 5; ++attempt) {
    Slot s;
    if (!Lookup(kind, key, &s)) return false;
    if (offset < 0 || len < 0 || offset + len > s.payload_len) return false;
    int fd = open(SlabPath(s.slab_id).c_str(), O_RDONLY);
    if (fd < 0) continue;
    bool ok = PreadAll(fd, dst, len, s.payload_off + offset);
    close(fd);
    if (ok) return true;
  }
  return false;
}

void SlabStore::ReadSlices(uint8_t kind, const SliceRead* reqs, size_t n,
                           bool* ok, int64_t* batches,
                           int64_t* vec_spans) const {
  // Records appended back-to-back sit header + key apart on disk, so
  // recipe-adjacent chunks coalesce once gaps up to a few records are
  // bridged; 4 KB keeps the wasted read under one page per seam.
  constexpr int64_t kMaxGap = 4096;
  constexpr size_t kMaxRunItems = 60;  // + bridge iovs stays far under IOV_MAX
  struct Item {
    int64_t start = 0;  // absolute file offset of the slice
    int64_t len = 0;
    char* dst = nullptr;
    size_t req = 0;
  };
  std::map<int64_t, std::vector<Item>> by_slab;
  for (size_t i = 0; i < n; ++i) {
    ok[i] = false;
    Slot s;
    if (!Lookup(kind, *reqs[i].key, &s)) continue;
    if (reqs[i].offset < 0 || reqs[i].len < 0 ||
        reqs[i].offset + reqs[i].len > s.payload_len)
      continue;
    by_slab[s.slab_id].push_back(Item{s.payload_off + reqs[i].offset,
                                      reqs[i].len, reqs[i].dst, i});
  }
  std::string scrap(static_cast<size_t>(kMaxGap), '\0');
  for (auto& [slab_id, items] : by_slab) {
    int fd = open(SlabPath(slab_id).c_str(), O_RDONLY);
    if (fd < 0) continue;  // compaction unlinked it; per-req retry path
    std::sort(items.begin(), items.end(),
              [](const Item& a, const Item& b) { return a.start < b.start; });
    size_t run_begin = 0;
    while (run_begin < items.size()) {
      // Grow the run while the next slice starts past the current end
      // (preadv only reads forward) within bridging distance.
      size_t run_end = run_begin + 1;
      int64_t end_off = items[run_begin].start + items[run_begin].len;
      while (run_end < items.size() &&
             run_end - run_begin < kMaxRunItems &&
             items[run_end].start >= end_off &&
             items[run_end].start - end_off <= kMaxGap) {
        end_off = items[run_end].start + items[run_end].len;
        ++run_end;
      }
      struct iovec iov[2 * kMaxRunItems + 1];
      int iovcnt = 0;
      int64_t cursor = items[run_begin].start;
      for (size_t i = run_begin; i < run_end; ++i) {
        if (items[i].start > cursor) {
          // Bridge the inter-record gap into the scrap buffer; every
          // gap may share it — the bytes are discarded.
          iov[iovcnt].iov_base = scrap.data();
          iov[iovcnt].iov_len = static_cast<size_t>(items[i].start - cursor);
          ++iovcnt;
        }
        iov[iovcnt].iov_base = items[i].dst;
        iov[iovcnt].iov_len = static_cast<size_t>(items[i].len);
        ++iovcnt;
        cursor = items[i].start + items[i].len;
      }
      if (PreadvAll(fd, iov, iovcnt, items[run_begin].start)) {
        *batches += 1;
        *vec_spans += static_cast<int64_t>(run_end - run_begin);
        for (size_t i = run_begin; i < run_end; ++i) ok[items[i].req] = true;
      }
      // A failed run leaves its requests ok = false: the caller's
      // per-request ReadSlice retry owns compaction races.
      run_begin = run_end;
    }
    close(fd);
  }
}

bool SlabStore::MarkDead(uint8_t kind, const std::string& key,
                         int64_t* payload_len_out) {
  std::lock_guard<RankedMutex> lk(mu_);
  std::string ikey = IndexKey(kind, key);
  IndexStripe& st = index_[StripeFor(ikey)];
  Slot s;
  {
    std::lock_guard<RankedMutex> ilk(st.mu);
    auto it = st.map.find(ikey);
    if (it == st.map.end()) return false;
    s = it->second;
    st.map.erase(it);
    AccountDeadLocked(s.slab_id, RecordExtent(key.size(), s.payload_len));
  }
  FlagDeadOnDisk(s.slab_id, s.record_off);
  if (payload_len_out != nullptr) *payload_len_out = s.payload_len;
  return true;
}

void SlabStore::ForEachLiveMeta(
    uint8_t kind, const std::function<void(const RecordMeta&)>& fn) const {
  for (const IndexStripe& st : index_) {
    std::vector<RecordMeta> batch;
    {
      std::lock_guard<RankedMutex> lk(st.mu);
      for (const auto& [ikey, slot] : st.map) {
        if (static_cast<uint8_t>(ikey[0]) != kind) continue;
        batch.push_back(
            RecordMeta{ikey.substr(1), slot.payload_len, slot.mtime});
      }
    }
    for (const RecordMeta& m : batch) fn(m);
  }
}

void SlabStore::ForEachLive(
    uint8_t kind, const std::function<void(const std::string& key,
                                           const std::string& payload)>& fn)
    const {
  // Group live slots by slab and read each slab with ONE open and
  // offset-ordered preads: the boot recipe rebuild calls this with
  // every live recipe on the node, and a per-record open/close would
  // turn startup into millions of redundant syscalls.
  struct Item {
    std::string key;
    Slot slot;
  };
  std::map<int64_t, std::vector<Item>> by_slab;
  for (const IndexStripe& st : index_) {
    std::lock_guard<RankedMutex> lk(st.mu);
    for (const auto& [ikey, slot] : st.map)
      if (static_cast<uint8_t>(ikey[0]) == kind)
        by_slab[slot.slab_id].push_back(Item{ikey.substr(1), slot});
  }
  std::string payload;
  for (auto& [slab_id, items] : by_slab) {
    std::sort(items.begin(), items.end(),
              [](const Item& a, const Item& b) {
                return a.slot.payload_off < b.slot.payload_off;
              });
    int fd = open(SlabPath(slab_id).c_str(), O_RDONLY);
    for (const Item& it : items) {
      bool ok = false;
      if (fd >= 0) {
        payload.resize(static_cast<size_t>(it.slot.payload_len));
        ok = PreadAll(fd, payload.data(), it.slot.payload_len,
                      it.slot.payload_off);
      }
      // Slab vanished/moved under us (a concurrent compaction):
      // per-key Read() re-resolves through a fresh lookup.
      if (!ok) ok = Read(kind, it.key, &payload);
      if (ok) fn(it.key, payload);
    }
    if (fd >= 0) close(fd);
  }
}

void SlabStore::ScanOneSlab(
    int64_t slab_id, const std::string& path,
    std::vector<std::pair<std::string, Slot>>* dups) {
  int fd = open(path.c_str(), O_RDONLY);
  if (fd < 0) return;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return;
  }
  int64_t size = st.st_size;
  SlabInfo info;
  info.size_bytes = size;
  int64_t off = 0;
  std::string hdr;
  while (off < size) {
    hdr.resize(kSlabRecordHeaderSize + kSlabKeyMaxLen);
    int64_t want = std::min<int64_t>(static_cast<int64_t>(hdr.size()),
                                     size - off);
    if (!PreadAll(fd, hdr.data(), want, off)) break;
    SlabRecordView v;
    if (!SlabDecodeRecord(hdr.data(), static_cast<size_t>(want), &v) ||
        off + v.record_len > size) {
      // Torn tail (crash mid-append): truncate it away so the file is a
      // clean record sequence again.  Anything after a corrupt header
      // is unreachable — same policy as the metrics journal's
      // torn-tail recovery.
      FDFS_LOG_WARN("slab %s: torn/corrupt record at offset %lld, "
                    "truncating %lld bytes",
                    path.c_str(), static_cast<long long>(off),
                    static_cast<long long>(size - off));
      if (truncate(path.c_str(), off) != 0)
        FDFS_LOG_WARN("slab %s: truncate failed: %s", path.c_str(),
                      strerror(errno));
      size = off;
      info.size_bytes = size;
      break;
    }
    int64_t extent = v.record_len;
    if (v.flags & kSlabFlagDead) {
      info.dead_slots++;
      info.dead_bytes += extent;
    } else {
      Slot slot;
      slot.slab_id = slab_id;
      slot.record_off = off;
      slot.payload_off = off + static_cast<int64_t>(kSlabRecordHeaderSize +
                                                    v.key.size());
      slot.payload_len = v.payload_len;
      slot.mtime = v.mtime;
      std::string ikey = IndexKey(v.kind, v.key);
      IndexStripe& stripe = index_[StripeFor(ikey)];
      {
        // Boot runs single-threaded, but tests rebuild a store that
        // already served — take the stripe lock like the chunk-store
        // rebuild does (mu_ is held: rank 92 -> 94).
        std::lock_guard<RankedMutex> ilk(stripe.mu);
        auto it = stripe.map.find(ikey);
        if (it != stripe.map.end()) {
          // Duplicate key: a crash between a replace/compaction append
          // and the old record's dead mark.  Scanning ascending (slab
          // id, offset) means the NEW record is the one in hand — the
          // indexed older one dies.
          dups->push_back({ikey, it->second});
          it->second = slot;
        } else {
          stripe.map.emplace(std::move(ikey), slot);
        }
      }
      info.live_slots++;
      info.live_bytes += extent;
    }
    off += extent;
  }
  close(fd);
  slabs_[slab_id] = info;
}

void SlabStore::ScanRebuild() {
  std::lock_guard<RankedMutex> lk(mu_);
  if (active_fd_ >= 0) {
    close(active_fd_);
    active_fd_ = -1;
  }
  if (flag_fd_ >= 0) {
    close(flag_fd_);
    flag_fd_ = -1;
  }
  slabs_.clear();
  for (IndexStripe& st : index_) {
    std::lock_guard<RankedMutex> ilk(st.mu);
    st.map.clear();
  }
  slots_live_ = slots_dead_ = 0;
  bytes_live_ = bytes_dead_ = 0;

  std::vector<int64_t> ids;
  DIR* d = opendir(dir_.c_str());
  if (d != nullptr) {
    struct dirent* de;
    while ((de = readdir(d)) != nullptr) {
      std::string name = de->d_name;
      if (name.size() != 15 ||
          name.compare(name.size() - 5, 5, ".slab") != 0)
        continue;
      char* end = nullptr;
      long long id = strtoll(name.c_str(), &end, 10);
      if (end == name.c_str() || id <= 0) continue;
      ids.push_back(id);
    }
    closedir(d);
  }
  std::sort(ids.begin(), ids.end());
  // The boot scan runs single-threaded before serving, so the index
  // stripes are touched without their locks only through ScanOneSlab's
  // direct map access — but tests rebuild a store that already served,
  // so hold each stripe lock around the whole scan?  The scan touches
  // every stripe per record; instead the maps were cleared above under
  // their locks and this thread is the only writer during a rebuild
  // (ChunkStore::RebuildFromRecipes documents the same contract).
  std::vector<std::pair<std::string, Slot>> dups;
  for (int64_t id : ids) ScanOneSlab(id, SlabPath(id), &dups);
  for (const auto& [ikey, old] : dups) {
    AccountDeadLocked(old.slab_id,
                      RecordExtent(ikey.size() - 1, old.payload_len));
    // AccountDeadLocked moved it live->dead but the old record was
    // counted live during its own slab's scan, so totals balance.
    FlagDeadOnDisk(old.slab_id, old.record_off);
  }
  int64_t live_slots = 0, dead_slots = 0, live_bytes = 0, dead_bytes = 0;
  for (const auto& [id, info] : slabs_) {
    live_slots += info.live_slots;
    dead_slots += info.dead_slots;
    live_bytes += info.live_bytes;
    dead_bytes += info.dead_bytes;
    if (id >= active_id_) active_id_ = id;
  }
  slots_live_ = live_slots;
  slots_dead_ = dead_slots;
  bytes_live_ = live_bytes;
  bytes_dead_ = dead_bytes;
  files_.store(static_cast<int64_t>(slabs_.size()),
               std::memory_order_relaxed);
  if (active_id_ > 0) {
    auto it = slabs_.find(active_id_);
    active_size_ = it != slabs_.end() ? it->second.size_bytes : 0;
  }
  if (!slabs_.empty())
    FDFS_LOG_INFO("slab store %s: %zu slabs, %lld live slots (%lld bytes), "
                  "%lld dead slots (%lld bytes)",
                  dir_.c_str(), slabs_.size(),
                  static_cast<long long>(live_slots),
                  static_cast<long long>(live_bytes),
                  static_cast<long long>(dead_slots),
                  static_cast<long long>(dead_bytes));
}

SlabStore::CompactResult SlabStore::Compact(
    const std::function<void(int64_t)>& pace,
    const std::function<bool()>& stop) {
  CompactResult res;
  // Victims that stayed alive this round (a corrupt record left in
  // place, an unreadable file): excluded so ONE stuck slab never
  // starves the rest of the round — they retry next pass, after the
  // quarantine machinery marks their bad slots dead.
  std::set<int64_t> skip;
  for (;;) {
    if (stop != nullptr && stop()) return res;
    // Pick the deadest eligible victim past the dead-share threshold
    // (or fully dead).  The ACTIVE slab is eligible too — it is retired
    // first (fd closed, next append rolls to a fresh id) so a small
    // store whose only slab went mostly dead still reclaims.
    int64_t victim = 0, victim_dead = 0;
    bool victim_empty = false;
    {
      std::lock_guard<RankedMutex> lk(mu_);
      for (const auto& [id, info] : slabs_) {
        if (skip.count(id)) continue;
        bool empty = info.live_slots == 0 && id != active_id_;
        bool ripe = empty ||
                    (info.size_bytes > 0 &&
                     info.dead_bytes * 100 >= info.size_bytes *
                                                  min_dead_pct_);
        if (!ripe) continue;
        if (victim == 0 || info.dead_bytes > victim_dead) {
          victim = id;
          victim_dead = info.dead_bytes;
          victim_empty = empty;
        }
      }
      if (victim != 0 && victim == active_id_) {
        if (active_fd_ >= 0) {
          close(active_fd_);
          active_fd_ = -1;
        }
        // Force EnsureActiveLocked to roll: appends (including this
        // compaction's own re-appends) land in a fresh slab.
        active_size_ = slab_bytes_;
      }
    }
    if (victim == 0) return res;

    std::string path = SlabPath(victim);
    if (!victim_empty) {
      // Copy phase: walk the victim's records; every record still
      // indexed at this exact location is live and gets re-appended
      // (verified first) before the old copy dies.
      int fd = open(path.c_str(), O_RDONLY);
      if (fd < 0) return res;
      struct stat st;
      int64_t size = fstat(fd, &st) == 0 ? st.st_size : 0;
      int64_t off = 0;
      std::string buf;
      bool scan_ok = true;
      while (off < size) {
        if (stop != nullptr && stop()) {
          close(fd);
          return res;  // victim left as-is; next pass resumes
        }
        buf.resize(kSlabRecordHeaderSize + kSlabKeyMaxLen);
        int64_t want = std::min<int64_t>(
            static_cast<int64_t>(buf.size()), size - off);
        SlabRecordView v;
        if (!PreadAll(fd, buf.data(), want, off) ||
            !SlabDecodeRecord(buf.data(), static_cast<size_t>(want), &v) ||
            off + v.record_len > size) {
          FDFS_LOG_WARN("slab compact %s: unreadable record at %lld, "
                        "aborting this slab",
                        path.c_str(), static_cast<long long>(off));
          scan_ok = false;
          break;
        }
        Slot here;
        bool live = Lookup(v.kind, v.key, &here) && here.slab_id == victim &&
                    here.record_off == off;
        if (live) {
          std::string payload;
          payload.resize(static_cast<size_t>(v.payload_len));
          if (!PreadAll(fd, payload.data(), v.payload_len,
                        here.payload_off)) {
            scan_ok = false;
            break;
          }
          if (pace != nullptr) pace(v.record_len);
          // Re-verify before the bytes move: a chunk IS its digest; a
          // recipe carries the payload CRC.  Failures stay in place and
          // go up to the quarantine/heal machinery — the slab is then
          // finished by a later pass once the bad slot is marked dead.
          bool good =
              v.kind == kSlabKindChunk
                  ? Sha1(payload.data(), payload.size()).Hex() == v.key
                  : Crc32(payload.data(), payload.size()) == v.payload_crc32;
          if (!good) {
            if (v.kind == kSlabKindChunk)
              res.corrupt_chunk_keys.push_back(v.key);
            else
              res.corrupt_recipe_keys.push_back(v.key);
          } else {
            std::string err;
            // Recipes keep their durability across the move: the copy
            // must be fsync'd before the only other copy's slab dies.
            // Chunks match the flat path (never fsync'd).
            if (!AppendInternal(v.kind, v.key, payload.data(),
                                payload.size(),
                                /*durable=*/v.kind == kSlabKindRecipe,
                                &here, &err)) {
              FDFS_LOG_WARN("slab compact: re-append of %s failed: %s",
                            v.key.c_str(), err.c_str());
              scan_ok = false;
              break;
            }
            res.copied_records++;
            compacted_bytes_.fetch_add(v.record_len,
                                       std::memory_order_relaxed);
          }
        } else if (pace != nullptr) {
          pace(kSlabRecordHeaderSize);  // header-only visit
        }
        off += v.record_len;
      }
      close(fd);
      if (!scan_ok) {
        skip.insert(victim);
        continue;
      }
    }

    // Unlink phase — only when the victim is now fully dead (corrupt
    // leftovers keep it alive until quarantine marks them dead; skip
    // it and keep compacting the rest of the round).
    bool alive = false;
    {
      std::lock_guard<RankedMutex> lk(mu_);
      auto it = slabs_.find(victim);
      if (it == slabs_.end()) {
        skip.insert(victim);
        continue;
      }
      alive = it->second.live_slots != 0;
      if (alive) {
        skip.insert(victim);
      } else {
        if (flag_fd_ >= 0 && flag_fd_slab_ == victim) {
          close(flag_fd_);
          flag_fd_ = -1;
        }
        slots_dead_.fetch_sub(it->second.dead_slots,
                              std::memory_order_relaxed);
        bytes_dead_.fetch_sub(it->second.dead_bytes,
                              std::memory_order_relaxed);
        res.reclaimed_bytes += it->second.size_bytes;
        slabs_.erase(it);
        files_.store(static_cast<int64_t>(slabs_.size()),
                     std::memory_order_relaxed);
      }
    }
    if (alive) continue;
    if (unlink(path.c_str()) != 0 && errno != ENOENT)
      FDFS_LOG_WARN("slab compact: unlink %s: %s", path.c_str(),
                    strerror(errno));
    compactions_.fetch_add(1, std::memory_order_relaxed);
    res.slabs_compacted++;
    FDFS_LOG_INFO("slab compact: slab %lld reclaimed (%lld records copied)",
                  static_cast<long long>(victim),
                  static_cast<long long>(res.copied_records));
  }
}

}  // namespace fdfs
