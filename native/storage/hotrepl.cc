#include "storage/hotrepl.h"

#include <fcntl.h>
#include <unistd.h>

#include <algorithm>
#include <cstring>

#include "common/bytes.h"
#include "common/log.h"
#include "common/net.h"
#include "common/protocol_gen.h"
#include "common/threadreg.h"

namespace fdfs {

namespace {

constexpr int kIoTimeoutMs = 30 * 1000;
constexpr int kConnectTimeoutMs = 3000;

void AppendInt64(std::string* out, int64_t v) {
  uint8_t buf[8];
  PutInt64BE(v, buf);
  out->append(reinterpret_cast<const char*>(buf), 8);
}

bool SendHeader(int fd, uint8_t cmd, int64_t pkg_len) {
  uint8_t hdr[kHeaderSize];
  PutInt64BE(pkg_len, hdr);
  hdr[8] = cmd;
  hdr[9] = 0;
  return SendAll(fd, hdr, sizeof(hdr), kIoTimeoutMs);
}

bool SendFileBytes(int fd, int local_fd, int64_t offset, int64_t count) {
  char buf[256 * 1024];
  if (lseek(local_fd, offset, SEEK_SET) != offset) return false;
  while (count > 0) {
    size_t want = static_cast<size_t>(
        std::min<int64_t>(count, static_cast<int64_t>(sizeof(buf))));
    ssize_t n = read(local_fd, buf, want);
    if (n <= 0) return false;
    if (!SendAll(fd, buf, static_cast<size_t>(n), kIoTimeoutMs)) return false;
    count -= n;
  }
  return true;
}

// Header-only response with a small drained body (the sync.cc idiom).
bool RecvStatus(int fd, uint8_t* status) {
  uint8_t hdr[kHeaderSize];
  if (!RecvAll(fd, hdr, sizeof(hdr), kIoTimeoutMs)) return false;
  int64_t len = GetInt64BE(hdr);
  *status = hdr[9];
  if (len < 0 || len > (1 << 20)) return false;
  if (len > 0) {
    std::string drain(static_cast<size_t>(len), '\0');
    if (!RecvAll(fd, drain.data(), drain.size(), kIoTimeoutMs)) return false;
  }
  return true;
}

int ConnectAddr(const std::string& addr) {
  size_t colon = addr.rfind(':');
  if (colon == std::string::npos) return -1;
  std::string err;
  return TcpConnect(addr.substr(0, colon), atoi(addr.c_str() + colon + 1),
                    kConnectTimeoutMs, &err);
}

std::string SplitRemote(const std::string& key) {
  size_t slash = key.find('/');
  return slash == std::string::npos ? std::string() : key.substr(slash + 1);
}

}  // namespace

HotReplManager::HotReplManager(const StorageConfig& cfg, HotReplCallbacks cbs)
    : cfg_(cfg), cbs_(std::move(cbs)) {}

HotReplManager::~HotReplManager() { Stop(); }

void HotReplManager::Start() {
  stop_ = false;
  thread_ = std::thread([this] { ThreadMain(); });
}

void HotReplManager::Stop() {
  stop_ = true;
  cv_.notify_all();
  if (thread_.joinable()) thread_.join();
}

void HotReplManager::Enqueue(const std::string& tracker_addr,
                             const std::vector<HotTask>& tasks) {
  std::lock_guard<RankedMutex> lk(mu_);
  for (const HotTask& t : tasks) {
    std::string id = std::to_string(t.type) + ":" + t.key;
    if (inflight_.count(id) != 0) continue;
    inflight_.insert(id);
    queue_.push_back({tracker_addr, t});
  }
  cv_.notify_one();
}

int64_t HotReplManager::queue_depth() const {
  std::lock_guard<RankedMutex> lk(mu_);
  return static_cast<int64_t>(queue_.size());
}

void HotReplManager::ThreadMain() {
  ScopedThreadName ledger("hotrepl");
  while (!stop_) {
    Job job;
    {
      std::unique_lock<RankedMutex> lk(mu_);
      cv_.wait_for(lk, std::chrono::milliseconds(500),
                   [this] { return stop_ || !queue_.empty(); });
      BeatThreadHeartbeat();
      if (stop_) return;
      if (queue_.empty()) continue;
      job = std::move(queue_.front());
      queue_.pop_front();
    }
    bool ok = job.task.type == kHotTaskDrop ? RunDrop(job) : RunReplicate(job);
    {
      // Completed or failed either way: release the dedup slot so the
      // tracker's next re-delivery (it re-sends until acked) retries.
      std::lock_guard<RankedMutex> lk(mu_);
      inflight_.erase(std::to_string(job.task.type) + ":" + job.task.key);
    }
    if (!ok) {
      failures_total_.fetch_add(1, std::memory_order_relaxed);
      if (cbs_.events != nullptr)
        cbs_.events->Record(EventSeverity::kWarn, "hot.fanout_failed",
                            job.task.key,
                            std::string("type=") +
                                (job.task.type == kHotTaskDrop ? "drop"
                                                               : "replicate"));
    }
  }
}

bool HotReplManager::QueryGroupMembers(
    const std::string& tracker_addr, const std::string& group,
    std::vector<std::pair<std::string, int>>* members) {
  members->clear();
  int fd = ConnectAddr(tracker_addr);
  if (fd < 0) return false;
  bool ok = SendHeader(fd, static_cast<uint8_t>(TrackerCmd::kQueryPlacement),
                       0);
  uint8_t hdr[kHeaderSize];
  std::string body;
  if (ok) ok = RecvAll(fd, hdr, sizeof(hdr), kIoTimeoutMs);
  if (ok) {
    int64_t len = GetInt64BE(hdr);
    ok = hdr[9] == 0 && len >= 16 && len <= (1 << 26);
    if (ok) {
      body.resize(static_cast<size_t>(len));
      ok = RecvAll(fd, body.data(), body.size(), kIoTimeoutMs);
    }
  }
  close(fd);
  if (!ok) return false;
  // QUERY_PLACEMENT: 8B version + 8B entry count + per entry (16B group
  // + 1B state + 8B member count + members x (16B ip + 8B port)).
  const uint8_t* p = reinterpret_cast<const uint8_t*>(body.data());
  int64_t count = GetInt64BE(p + 8);
  size_t off = 16;
  for (int64_t i = 0; i < count; ++i) {
    if (off + kGroupNameMaxLen + 9 > body.size()) return false;
    std::string g = GetFixedField(p + off, kGroupNameMaxLen);
    off += kGroupNameMaxLen + 1;
    int64_t n = GetInt64BE(p + off);
    off += 8;
    const size_t rec = kIpAddressSize + 8;
    if (n < 0 || static_cast<uint64_t>(n) > (body.size() - off) / rec)
      return false;
    for (int64_t m = 0; m < n; ++m) {
      if (g == group)
        members->push_back(
            {GetFixedField(p + off, kIpAddressSize),
             static_cast<int>(GetInt64BE(p + off + kIpAddressSize))});
      off += rec;
    }
  }
  return !members->empty();
}

bool HotReplManager::PushCopy(const std::string& ip, int port,
                              const std::string& group,
                              const std::string& remote) {
  auto h = cbs_.open_content ? cbs_.open_content(remote) : std::nullopt;
  if (!h.has_value()) return false;
  std::string err;
  int fd = TcpConnect(ip, port, kConnectTimeoutMs, &err);
  if (fd < 0) {
    close(h->fd);
    return false;
  }
  // kSyncCreateFile with the TARGET group in the group field: the
  // receiver's own-group check passes and it stores the copy in its own
  // tree as a replica op (binlog 'c' — never re-shipped).
  std::string body;
  PutFixedField(&body, group, kGroupNameMaxLen);
  AppendInt64(&body, static_cast<int64_t>(remote.size()));
  AppendInt64(&body, h->size);
  body += remote;
  bool ok = SendHeader(fd, static_cast<uint8_t>(StorageCmd::kSyncCreateFile),
                       static_cast<int64_t>(body.size()) + h->size) &&
            SendAll(fd, body.data(), body.size(), kIoTimeoutMs) &&
            SendFileBytes(fd, h->fd, h->offset, h->size);
  close(h->fd);
  uint8_t status = 0;
  ok = ok && RecvStatus(fd, &status) && status == 0;
  close(fd);
  return ok;
}

bool HotReplManager::VerifyCopy(const std::string& ip, int port,
                                const std::string& group,
                                const std::string& remote,
                                const std::string& want_sha1,
                                int64_t want_size) {
  std::string err;
  int fd = TcpConnect(ip, port, kConnectTimeoutMs, &err);
  if (fd < 0) return false;
  std::string body;
  AppendInt64(&body, 0);  // offset
  AppendInt64(&body, 0);  // count = whole file
  PutFixedField(&body, group, kGroupNameMaxLen);
  body += remote;
  bool ok = SendHeader(fd, static_cast<uint8_t>(StorageCmd::kDownloadFile),
                       static_cast<int64_t>(body.size())) &&
            SendAll(fd, body.data(), body.size(), kIoTimeoutMs);
  uint8_t hdr[kHeaderSize];
  int64_t got = 0;
  Sha1Stream sha;
  if (ok) ok = RecvAll(fd, hdr, sizeof(hdr), kIoTimeoutMs);
  if (ok) {
    int64_t len = GetInt64BE(hdr);
    ok = hdr[9] == 0 && len == want_size;
    char buf[256 * 1024];
    while (ok && got < len) {
      size_t want = static_cast<size_t>(
          std::min<int64_t>(len - got, static_cast<int64_t>(sizeof(buf))));
      ok = RecvAll(fd, buf, want, kIoTimeoutMs);
      if (ok) {
        sha.Update(buf, want);
        got += static_cast<int64_t>(want);
      }
    }
  }
  close(fd);
  return ok && sha.Final().Hex() == want_sha1;
}

bool HotReplManager::AckTracker(const std::string& tracker_addr, uint8_t type,
                                const std::string& key,
                                const std::vector<std::string>& groups) {
  int fd = ConnectAddr(tracker_addr);
  if (fd < 0) return false;
  std::string body;
  PutFixedField(&body, cfg_.group_name, kGroupNameMaxLen);
  body.push_back(static_cast<char>(type));
  AppendInt64(&body, static_cast<int64_t>(key.size()));
  body += key;
  AppendInt64(&body, static_cast<int64_t>(groups.size()));
  for (const std::string& g : groups) PutFixedField(&body, g, kGroupNameMaxLen);
  bool ok = SendHeader(fd, static_cast<uint8_t>(TrackerCmd::kHotFanoutDone),
                       static_cast<int64_t>(body.size())) &&
            SendAll(fd, body.data(), body.size(), kIoTimeoutMs);
  uint8_t status = 0;
  ok = ok && RecvStatus(fd, &status) && status == 0;
  close(fd);
  return ok;
}

bool HotReplManager::RunReplicate(const Job& job) {
  const std::string remote = SplitRemote(job.task.key);
  if (remote.empty()) return false;
  // Local truth first: size + SHA-1 of the logical bytes, the verify
  // baseline for every pushed copy.
  auto h = cbs_.open_content ? cbs_.open_content(remote) : std::nullopt;
  if (!h.has_value()) return false;  // gone since promotion
  Sha1Stream sha;
  char buf[256 * 1024];
  int64_t left = h->size;
  if (lseek(h->fd, h->offset, SEEK_SET) != h->offset) {
    close(h->fd);
    return false;
  }
  while (left > 0) {
    ssize_t n = read(h->fd, buf,
                     static_cast<size_t>(std::min<int64_t>(
                         left, static_cast<int64_t>(sizeof(buf)))));
    if (n <= 0) {
      close(h->fd);
      return false;
    }
    sha.Update(buf, static_cast<size_t>(n));
    left -= n;
  }
  int64_t size = h->size;
  close(h->fd);
  std::string want_sha1 = sha.Final().Hex();

  std::vector<std::string> verified;
  for (const std::string& group : job.task.groups) {
    std::vector<std::pair<std::string, int>> members;
    if (!QueryGroupMembers(job.tracker_addr, group, &members)) break;
    bool group_ok = true;
    for (const auto& [ip, port] : members) {
      if (!PushCopy(ip, port, group, remote) ||
          !VerifyCopy(ip, port, group, remote, want_sha1, size)) {
        verify_failures_.fetch_add(1, std::memory_order_relaxed);
        group_ok = false;
        break;
      }
    }
    if (group_ok) verified.push_back(group);
  }
  if (verified.size() != job.task.groups.size()) return false;
  if (!AckTracker(job.tracker_addr, kHotTaskReplicate, job.task.key, verified))
    return false;
  replicated_total_.fetch_add(1, std::memory_order_relaxed);
  FDFS_LOG_INFO("hotrepl: replicated %s to %zu group(s), verified",
                job.task.key.c_str(), verified.size());
  if (cbs_.events != nullptr)
    cbs_.events->Record(EventSeverity::kInfo, "hot.replicated", job.task.key,
                        "groups=" + std::to_string(verified.size()));
  return true;
}

bool HotReplManager::RunDrop(const Job& job) {
  const std::string remote = SplitRemote(job.task.key);
  if (remote.empty()) return false;
  for (const std::string& group : job.task.groups) {
    std::vector<std::pair<std::string, int>> members;
    if (!QueryGroupMembers(job.tracker_addr, group, &members)) return false;
    for (const auto& [ip, port] : members) {
      std::string err;
      int fd = TcpConnect(ip, port, kConnectTimeoutMs, &err);
      if (fd < 0) return false;
      std::string body;
      PutFixedField(&body, group, kGroupNameMaxLen);
      body += remote;
      bool ok =
          SendHeader(fd, static_cast<uint8_t>(StorageCmd::kSyncDeleteFile),
                     static_cast<int64_t>(body.size())) &&
          SendAll(fd, body.data(), body.size(), kIoTimeoutMs);
      uint8_t status = 0;
      ok = ok && RecvStatus(fd, &status);
      close(fd);
      // ENOENT (2) is fine: the member never had the copy.
      if (!ok || (status != 0 && status != 2)) return false;
    }
  }
  if (!AckTracker(job.tracker_addr, kHotTaskDrop, job.task.key,
                  job.task.groups))
    return false;
  dropped_total_.fetch_add(1, std::memory_order_relaxed);
  FDFS_LOG_INFO("hotrepl: dropped extra copies of %s", job.task.key.c_str());
  if (cbs_.events != nullptr)
    cbs_.events->Record(EventSeverity::kInfo, "hot.copies_dropped",
                        job.task.key, "");
  return true;
}

}  // namespace fdfs
