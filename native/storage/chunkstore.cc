#include "storage/chunkstore.h"

#include <dirent.h>
#include <fcntl.h>
#include <string.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cerrno>
#include <cstdio>
#include <ctime>

#include "common/bytes.h"
#include "common/eventlog.h"
#include "common/fsutil.h"
#include "common/log.h"

namespace fdfs {

namespace {

constexpr char kRecipeMagic[8] = {'F', 'D', 'F', 'S', 'R', 'C', 'P', '1'};

bool IsHex40(const std::string& s) {
  if (s.size() != 40) return false;
  for (char c : s)
    if (!((c >= '0' && c <= '9') || (c >= 'a' && c <= 'f'))) return false;
  return true;
}

}  // namespace

// -- recipe codec ---------------------------------------------------------
// Layout: 8B magic, 8B logical_size BE, 8B chunk_count BE, then per chunk
// 20B raw digest + 8B length BE.  Offsets are implicit (cumulative).
// The buffer forms are shared between .rcp sidecar files and slab-packed
// recipe records — identical bytes in both layouts.

std::string EncodeRecipe(const Recipe& r) {
  std::string buf(kRecipeMagic, sizeof(kRecipeMagic));
  uint8_t num[8];
  PutInt64BE(r.logical_size, num);
  buf.append(reinterpret_cast<char*>(num), 8);
  PutInt64BE(static_cast<int64_t>(r.chunks.size()), num);
  buf.append(reinterpret_cast<char*>(num), 8);
  for (const RecipeEntry& e : r.chunks) {
    for (size_t i = 0; i < 40; i += 2) {
      buf.push_back(static_cast<char>(
          strtoul(e.digest_hex.substr(i, 2).c_str(), nullptr, 16)));
    }
    PutInt64BE(e.length, num);
    buf.append(reinterpret_cast<char*>(num), 8);
  }
  return buf;
}

std::optional<Recipe> DecodeRecipe(const char* data, size_t len) {
  if (len < 24 || memcmp(data, kRecipeMagic, sizeof(kRecipeMagic)) != 0)
    return std::nullopt;
  const uint8_t* p = reinterpret_cast<const uint8_t*>(data);
  Recipe r;
  r.logical_size = GetInt64BE(p + 8);
  int64_t count = GetInt64BE(p + 16);
  if (count < 0 || count > (1 << 26))  // 64M chunks ~= 0.5 PB file
    return std::nullopt;
  if (len < 24 + static_cast<size_t>(count) * 28) return std::nullopt;
  static const char* kHex = "0123456789abcdef";
  r.chunks.reserve(static_cast<size_t>(count));
  for (int64_t i = 0; i < count; ++i) {
    const uint8_t* rec = p + 24 + i * 28;
    RecipeEntry e;
    e.digest_hex.resize(40);
    for (int b = 0; b < 20; ++b) {
      e.digest_hex[2 * b] = kHex[rec[b] >> 4];
      e.digest_hex[2 * b + 1] = kHex[rec[b] & 0xF];
    }
    e.length = GetInt64BE(rec + 20);
    if (e.length < 0) return std::nullopt;
    r.chunks.push_back(std::move(e));
  }
  return r;
}

bool WriteRecipeFile(const std::string& path, const Recipe& r,
                     std::string* err) {
  std::string tmp = path + ".tmp";
  FILE* f = fopen(tmp.c_str(), "wb");
  if (f == nullptr) {
    *err = "open " + tmp + ": " + strerror(errno);
    return false;
  }
  std::string buf = EncodeRecipe(r);
  bool ok = fwrite(buf.data(), 1, buf.size(), f) == buf.size() &&
            fflush(f) == 0 && fsync(fileno(f)) == 0;
  fclose(f);
  if (!ok || rename(tmp.c_str(), path.c_str()) != 0) {
    *err = "write " + path + ": " + strerror(errno);
    unlink(tmp.c_str());
    return false;
  }
  return true;
}

std::optional<Recipe> ReadRecipeFile(const std::string& path) {
  std::string buf;
  if (!ReadWholeFile(path, &buf)) return std::nullopt;
  return DecodeRecipe(buf.data(), buf.size());
}

// -- store ----------------------------------------------------------------

ChunkStore::ChunkStore(std::string store_path, int64_t gc_grace_s,
                       int64_t read_cache_bytes, SlabOptions slab, int ec_k,
                       int ec_m)
    : store_path_(std::move(store_path)),
      gc_grace_s_(gc_grace_s < 0 ? 0 : gc_grace_s),
      slab_opts_(slab) {
  cache_.cap_bytes = read_cache_bytes < 0 ? 0 : read_cache_bytes;
  // The slab store exists whenever packing is configured OR slab data
  // is already on disk: thresholds gate only NEW writes.  An operator
  // draining the layout (both thresholds 0, OPERATIONS.md) must keep
  // reading slab-resident records — without this, boot would treat
  // every chunk named only by a slab-resident recipe as an orphan and
  // GC it: data loss, not a drain.
  struct stat st;
  bool slabs_on_disk =
      stat((store_path_ + "/data/slabs").c_str(), &st) == 0 &&
      S_ISDIR(st.st_mode);
  if (slab_opts_.chunk_threshold > 0 || slab_opts_.recipe_threshold > 0 ||
      slabs_on_disk)
    slab_ = std::make_unique<SlabStore>(store_path_ + "/data/slabs",
                                        slab_opts_.slab_bytes,
                                        slab_opts_.compact_min_dead_pct);
  // Same drain discipline for the EC tier: ec_k = 0 with stripes on
  // disk mounts the store read-only (Rescan adopts the on-disk
  // geometry; EncodeStripe refuses) so demoted chunks stay readable
  // while scrub repair / deletes drain the stripes.
  bool ec_on_disk = stat((store_path_ + "/data/ec").c_str(), &st) == 0 &&
                    S_ISDIR(st.st_mode);
  if (ec_k > 0 || ec_on_disk)
    ec_ = std::make_unique<EcStore>(store_path_ + "/data/ec",
                                    ec_k > 0 ? ec_k : 0,
                                    ec_k > 0 ? ec_m : 0);
  // Stripe locks share one rank; the index is the ascending-protocol
  // order key the FDFS_LOCKRANK checker validates RefAll against.
  for (int i = 0; i < kStripes; ++i) stripes_[i].mu.set_order_key(i);
}

int ChunkStore::StripeIndex(const std::string& digest_hex) {
  // First hex nibble of the digest: SHA1 is uniform, so the 16 stripes
  // load-balance by construction.  Non-hex input (never produced by the
  // callers) still lands in a valid stripe.
  char c = digest_hex.empty() ? '0' : digest_hex[0];
  if (c >= '0' && c <= '9') return c - '0';
  if (c >= 'a' && c <= 'f') return c - 'a' + 10;
  return 0;
}

std::string ChunkStore::ChunkPath(const std::string& digest_hex) const {
  return store_path_ + "/data/chunks/" + digest_hex.substr(0, 2) + "/" +
         digest_hex.substr(2, 2) + "/" + digest_hex;
}

std::string ChunkStore::QuarantinePath(const std::string& digest_hex) const {
  return store_path_ + "/data/quarantine/" + digest_hex;
}

namespace {

// Write-if-absent payload write (tmp + rename; a leftover file from a
// crashed write is simply overwritten — content-addressed, so same
// digest => same bytes).
bool WriteChunkFile(const std::string& path, const char* data, size_t len,
                    std::string* err) {
  std::string tmp = path + ".tmp";
  int fd = open(tmp.c_str(), O_CREAT | O_WRONLY | O_TRUNC, 0644);
  if (fd < 0) {
    *err = "open " + tmp + ": " + strerror(errno);
    return false;
  }
  size_t off = 0;
  while (off < len) {
    ssize_t w = write(fd, data + off, len - off);
    if (w <= 0) {
      *err = "write " + tmp + ": " + strerror(errno);
      close(fd);
      unlink(tmp.c_str());
      return false;
    }
    off += static_cast<size_t>(w);
  }
  close(fd);
  if (rename(tmp.c_str(), path.c_str()) != 0) {
    *err = "rename " + path + ": " + strerror(errno);
    unlink(tmp.c_str());
    return false;
  }
  return true;
}

}  // namespace

bool ChunkStore::WriteChunkPayloadLocked(const std::string& digest_hex,
                                         const char* data, size_t len,
                                         std::string* err) {
  // stripe mu held.  The shared payload landing path: first writes,
  // heal-on-upload, and replica repair all route here so the slab-vs-
  // flat layout decision lives in exactly one place.
  if (SlabChunkEligible(static_cast<int64_t>(len))) {
    // Replace semantics mark any older record (a quarantined original,
    // a pre-repair copy) dead in place; a stale flat twin from before a
    // threshold change is dropped so it can never shadow the record.
    if (!slab_->Append(kSlabKindChunk, digest_hex, data, len,
                       /*durable=*/false, err))
      return false;
    unlink(ChunkPath(digest_hex).c_str());
    return true;
  }
  std::string path = ChunkPath(digest_hex);
  EnsureParentDirs(path);
  if (!WriteChunkFile(path, data, len, err)) return false;
  if (slab_ != nullptr) slab_->MarkDead(kSlabKindChunk, digest_hex);
  return true;
}

bool ChunkStore::PutAndRef(const std::string& digest_hex, const char* data,
                           size_t len, bool* existed, std::string* err) {
  Stripe& st = StripeFor(digest_hex);
  std::lock_guard<RankedMutex> lk(st.mu);
  // Heal-on-upload: these bytes hash to the digest (every caller
  // verifies before PutAndRef), so a quarantined chunk gets its good
  // payload restored by ANY upload/replication that carries it.
  // Best-effort — a failed restore leaves the chunk quarantined
  // (downloads keep failing loudly) but never fails the upload, which
  // historically never wrote in the already-present case.
  auto heal = [&]() {
    if (!st.quarantined.count(digest_hex)) return;
    std::string werr;
    if (WriteChunkPayloadLocked(digest_hex, data, len, &werr)) {
      st.quarantined.erase(digest_hex);
      unlink(QuarantinePath(digest_hex).c_str());
      CacheInvalidate(digest_hex);
      FDFS_LOG_INFO("chunk %s healed by incoming payload",
                    digest_hex.c_str());
      if (events_ != nullptr)
        events_->Record(EventSeverity::kInfo, "chunk.healed", digest_hex,
                        "by=upload bytes=" + std::to_string(len));
    } else {
      FDFS_LOG_WARN("quarantined chunk %s heal failed: %s",
                    digest_hex.c_str(), werr.c_str());
    }
  };
  // Released chunks heal the same way: the upload carries verified
  // bytes, so the local replica returns and the remote-fetch dependency
  // on the group owner ends.
  auto unrelease = [&]() {
    if (!st.released.count(digest_hex)) return;
    std::string werr;
    if (WriteChunkPayloadLocked(digest_hex, data, len, &werr))
      UnreleaseLocked(st, digest_hex, static_cast<int64_t>(len));
    else
      FDFS_LOG_WARN("released chunk %s re-materialize failed: %s",
                    digest_hex.c_str(), werr.c_str());
  };
  auto it = st.refs.find(digest_hex);
  if (it != st.refs.end()) {
    heal();
    unrelease();
    it->second++;
    *existed = true;
    return true;
  }
  auto z = st.zero_ref.find(digest_hex);
  if (z != st.zero_ref.end()) {
    // Zero-ref but still on disk (GC grace window, or a pinned stream
    // deferring the unlink): resurrect instead of rewriting.
    heal();
    st.refs[digest_hex] = 1;
    st.lens[digest_hex] = z->second.length;
    unique_bytes_ += z->second.length;
    zero_ref_bytes_ -= z->second.length;
    st.zero_ref.erase(z);
    *existed = true;
    return true;
  }
  // First reference: write the payload (slab record below the packing
  // threshold, flat file otherwise).
  if (!WriteChunkPayloadLocked(digest_hex, data, len, err)) return false;
  st.refs[digest_hex] = 1;
  st.lens[digest_hex] = static_cast<int64_t>(len);
  unique_bytes_ += static_cast<int64_t>(len);
  *existed = false;
  return true;
}

bool ChunkStore::RefAll(const Recipe& r) {
  // All-or-nothing across digests: lock every involved stripe together,
  // in ascending index order (the ordered multi-stripe protocol), so no
  // UnrefAll can interleave between the presence check and the refs.
  bool involved[kStripes] = {};
  for (const RecipeEntry& e : r.chunks) involved[StripeIndex(e.digest_hex)] = true;
  std::array<std::unique_lock<RankedMutex>, kStripes> locks;
  for (int i = 0; i < kStripes; ++i)
    if (involved[i]) locks[i] = std::unique_lock<RankedMutex>(stripes_[i].mu);
  for (const RecipeEntry& e : r.chunks)
    if (StripeFor(e.digest_hex).refs.find(e.digest_hex) ==
        StripeFor(e.digest_hex).refs.end())
      return false;
  for (const RecipeEntry& e : r.chunks)
    StripeFor(e.digest_hex).refs[e.digest_hex]++;
  return true;
}

bool ChunkStore::Has(const std::string& digest_hex) const {
  const Stripe& st = StripeFor(digest_hex);
  std::lock_guard<RankedMutex> lk(st.mu);
  return st.refs.find(digest_hex) != st.refs.end();
}

std::string ChunkStore::HaveMask(
    const std::vector<std::string>& digests) const {
  // One lock acquisition per stripe (not per digest): group the batch
  // by stripe, then answer each stripe's subset under its lock.
  std::string need(digests.size(), '\0');
  std::vector<uint32_t> by_stripe[kStripes];
  for (size_t i = 0; i < digests.size(); ++i)
    by_stripe[StripeIndex(digests[i])].push_back(static_cast<uint32_t>(i));
  for (int s = 0; s < kStripes; ++s) {
    if (by_stripe[s].empty()) continue;
    const Stripe& st = stripes_[s];
    std::lock_guard<RankedMutex> lk(st.mu);
    for (uint32_t i : by_stripe[s])
      need[i] = st.refs.find(digests[i]) != st.refs.end() &&
                        !st.quarantined.count(digests[i])
                    ? 0 : 1;
  }
  return need;
}

bool ChunkStore::RefOne(const std::string& digest_hex) {
  Stripe& st = StripeFor(digest_hex);
  std::lock_guard<RankedMutex> lk(st.mu);
  auto it = st.refs.find(digest_hex);
  if (it == st.refs.end()) return false;
  it->second++;
  return true;
}

void ChunkStore::RetireLocked(Stripe& s, const std::string& digest_hex,
                              int64_t length) {
  // stripe mu held; refs entry already erased.  Eager mode (no GC
  // grace) keeps the original semantics: unlink now unless an in-flight
  // stream pins the chunk, in which case the zero_ref entry defers the
  // unlink to the last UnpinRecipe.  With a grace window every zero-ref
  // chunk parks for the scrubber's GcSweep.
  unique_bytes_ -= length;
  if (gc_grace_s_ == 0 && !s.pins.count(digest_hex)) {
    UnlinkRetiredLocked(s, digest_hex);
    return;
  }
  s.zero_ref[digest_hex] = ZeroRef{length, time(nullptr)};
  zero_ref_bytes_ += length;
}

void ChunkStore::DropPayloadLocked(Stripe& s,
                                   const std::string& digest_hex) {
  (void)s;  // the stripe lock is the contract, not an input
  if (slab_ != nullptr) slab_->MarkDead(kSlabKindChunk, digest_hex);
  unlink(ChunkPath(digest_hex).c_str());
  // Strict cache coherence: a dropped payload must never be served from
  // the read cache (a later re-materialization re-admits it).
  CacheInvalidate(digest_hex);
}

void ChunkStore::UnlinkRetiredLocked(Stripe& s,
                                     const std::string& digest_hex) {
  DropPayloadLocked(s, digest_hex);
  unlink(QuarantinePath(digest_hex).c_str());
  s.quarantined.erase(digest_hex);
  // Full retirement also reclaims the chunk's EC slot (parity bytes
  // come back when its stripe's last live chunk dies) and any released
  // mark — a deleted chunk needs no remote serve path.
  if (ec_ != nullptr) ec_->MarkDead(digest_hex, nullptr);
  if (s.released.erase(digest_hex) > 0) {
    released_chunks_--;
    auto l = s.lens.find(digest_hex);
    released_bytes_ -= l != s.lens.end() ? l->second : 0;
  }
  s.lens.erase(digest_hex);
}

void ChunkStore::UnrefAll(const Recipe& r) {
  for (const RecipeEntry& e : r.chunks) {
    Stripe& st = StripeFor(e.digest_hex);
    std::lock_guard<RankedMutex> lk(st.mu);
    auto it = st.refs.find(e.digest_hex);
    if (it == st.refs.end()) continue;
    if (--it->second <= 0) {
      st.refs.erase(it);
      RetireLocked(st, e.digest_hex, e.length);
    }
  }
}

std::optional<Recipe> ChunkStore::ReadRecipeAndPin(const std::string& path) {
  // The recipe read needs no lock (both layouts are immutable once
  // published); the verify-refs-then-pin per chunk under its stripe
  // lock is what closes the race with a concurrent delete.  If any
  // chunk already lost its references (the file is mid-delete) the
  // pins taken so far roll back and the download fails with ENOENT
  // before the first byte — never mid-stream.
  auto r = LoadRecipe(path);
  if (!r.has_value()) return std::nullopt;
  for (size_t i = 0; i < r->chunks.size(); ++i) {
    Stripe& st = StripeFor(r->chunks[i].digest_hex);
    std::unique_lock<RankedMutex> lk(st.mu);
    if (st.refs.find(r->chunks[i].digest_hex) == st.refs.end()) {
      lk.unlock();
      Recipe taken;
      taken.chunks.assign(r->chunks.begin(), r->chunks.begin() + i);
      UnpinRecipe(taken);
      return std::nullopt;
    }
    st.pins[r->chunks[i].digest_hex]++;
  }
  return r;
}

std::optional<Recipe> ChunkStore::ReadRecipeAndPinRange(
    const std::string& path, int64_t offset, int64_t count,
    int64_t* skip_out) {
  auto full = LoadRecipe(path);
  if (!full.has_value() || offset < 0) return std::nullopt;
  // offset past EOF yields an EMPTY slice (no pins) rather than
  // nullopt, so the caller can distinguish "gone" (ENOENT) from "bad
  // range" (EINVAL) by logical_size.
  int64_t want = full->logical_size - offset;
  if (count > 0 && count < want) want = count;
  // Locate the overlapping slice (one pass; the recipe is already in
  // memory from the parse).
  Recipe trimmed;
  trimmed.logical_size = full->logical_size;
  size_t first = 0;
  int64_t skip = offset;
  while (first < full->chunks.size() &&
         skip >= full->chunks[first].length) {
    skip -= full->chunks[first].length;
    ++first;
  }
  size_t last = first;
  int64_t covered = -skip;
  while (last < full->chunks.size() && covered < want)
    covered += full->chunks[last++].length;
  trimmed.chunks.assign(full->chunks.begin() + first,
                        full->chunks.begin() + last);
  // Verify+pin per chunk with rollback, exactly like ReadRecipeAndPin.
  for (size_t i = 0; i < trimmed.chunks.size(); ++i) {
    Stripe& st = StripeFor(trimmed.chunks[i].digest_hex);
    std::unique_lock<RankedMutex> lk(st.mu);
    if (st.refs.find(trimmed.chunks[i].digest_hex) == st.refs.end()) {
      lk.unlock();
      Recipe taken;
      taken.chunks.assign(trimmed.chunks.begin(),
                          trimmed.chunks.begin() + i);
      UnpinRecipe(taken);
      return std::nullopt;
    }
    st.pins[trimmed.chunks[i].digest_hex]++;
  }
  *skip_out = skip;
  return trimmed;
}

std::string ChunkStore::PinAndMask(const Recipe& r) {
  std::string need(r.chunks.size(), '\0');
  for (size_t i = 0; i < r.chunks.size(); ++i) {
    // Quarantined chunks read as "needed": the client re-ships the
    // bytes and PutAndRef heals the store.  The pin taken here also
    // exempts the chunk from GcSweep and Quarantine for the session's
    // lifetime — probe and pin share this one stripe-lock acquisition.
    Stripe& st = StripeFor(r.chunks[i].digest_hex);
    std::lock_guard<RankedMutex> lk(st.mu);
    need[i] = st.refs.find(r.chunks[i].digest_hex) != st.refs.end() &&
                      !st.quarantined.count(r.chunks[i].digest_hex)
                  ? 0 : 1;
    st.pins[r.chunks[i].digest_hex]++;
  }
  return need;
}

void ChunkStore::PinRecipe(const Recipe& r) {
  for (const RecipeEntry& e : r.chunks) {
    Stripe& st = StripeFor(e.digest_hex);
    std::lock_guard<RankedMutex> lk(st.mu);
    st.pins[e.digest_hex]++;
  }
}

void ChunkStore::UnpinRecipe(const Recipe& r) {
  for (const RecipeEntry& e : r.chunks) {
    Stripe& st = StripeFor(e.digest_hex);
    std::lock_guard<RankedMutex> lk(st.mu);
    auto it = st.pins.find(e.digest_hex);
    if (it == st.pins.end()) continue;
    if (--it->second <= 0) {
      st.pins.erase(it);
      // Eager mode: the last pin drop completes a delete that was
      // deferred mid-stream — unless the chunk was re-added while the
      // stream ran (PutAndRef resurrection erased the zero_ref entry).
      // With a GC grace the entry simply waits for GcSweep.
      auto z = st.zero_ref.find(e.digest_hex);
      if (z != st.zero_ref.end() && gc_grace_s_ == 0 &&
          st.refs.find(e.digest_hex) == st.refs.end()) {
        zero_ref_bytes_ -= z->second.length;
        st.zero_ref.erase(z);
        UnlinkRetiredLocked(st, e.digest_hex);
      }
    }
  }
}

bool ChunkStore::ReadChunk(const std::string& digest_hex, int64_t expect_len,
                           std::string* out) const {
  // Slab-resident chunks read as extents of their slab record; the
  // length check keeps the flat path's "short file is corrupt"
  // semantics.  Absent from the slot index => the flat layout owns it.
  if (slab_ != nullptr) {
    SlabStore::Slot slot;
    if (slab_->Lookup(kSlabKindChunk, digest_hex, &slot)) {
      if (slot.payload_len != expect_len) return false;
      return slab_->Read(kSlabKindChunk, digest_hex, out);
    }
  }
  int fd = open(ChunkPath(digest_hex).c_str(), O_RDONLY);
  if (fd >= 0) {
    out->resize(static_cast<size_t>(expect_len));
    size_t off = 0;
    while (off < out->size()) {
      ssize_t r = read(fd, out->data() + off, out->size() - off);
      if (r <= 0) {
        close(fd);
        return false;
      }
      off += static_cast<size_t>(r);
    }
    close(fd);
    return true;
  }
  // Cold-tier fallthrough: an EC-resident chunk (payload demoted into a
  // local RS stripe) decodes transparently.
  if (ec_ != nullptr && ec_->ReadChunk(digest_hex, out) &&
      static_cast<int64_t>(out->size()) == expect_len)
    return true;
  // Released replica: the group owner holds the bytes (in parity);
  // fetch them back over the wire, SHA1-gated.  The hook runs with NO
  // lock held — network IO under a stripe lock would convoy the store.
  if (remote_fetch_ != nullptr) {
    bool released;
    {
      const Stripe& st = StripeFor(digest_hex);
      std::lock_guard<RankedMutex> lk(st.mu);
      released = st.released.count(digest_hex) != 0;
    }
    if (released) {
      std::string buf;
      if (remote_fetch_(digest_hex, expect_len, &buf) &&
          static_cast<int64_t>(buf.size()) == expect_len &&
          Sha1(buf.data(), buf.size()).Hex() == digest_hex) {
        remote_reads_.fetch_add(1, std::memory_order_relaxed);
        *out = std::move(buf);
        return true;
      }
    }
  }
  return false;
}

bool ChunkStore::ReadChunkSlice(const std::string& digest_hex,
                                int64_t offset, int64_t len,
                                char* dst) const {
  if (slab_ != nullptr && slab_->Has(kSlabKindChunk, digest_hex))
    return slab_->ReadSlice(kSlabKindChunk, digest_hex, offset, len, dst);
  int fd = open(ChunkPath(digest_hex).c_str(), O_RDONLY);
  if (fd >= 0) {
    int64_t got = 0;
    while (got < len) {
      ssize_t r = pread(fd, dst + got, static_cast<size_t>(len - got),
                        offset + got);
      if (r <= 0) {
        close(fd);
        return false;
      }
      got += r;
    }
    close(fd);
    return true;
  }
  // EC cold tier: positional reads are offset math over 1-2 data
  // shards (no decode on the healthy path).
  if (ec_ != nullptr && ec_->ReadChunkSlice(digest_hex, offset, len, dst))
    return true;
  // Released replica: fetch the WHOLE chunk from the group owner (the
  // wire round is per-chunk; slicing happens here) so the bytes can be
  // digest-verified before any of them reach the caller.
  if (remote_fetch_ != nullptr) {
    bool released = false;
    int64_t full_len = 0;
    {
      const Stripe& st = StripeFor(digest_hex);
      std::lock_guard<RankedMutex> lk(st.mu);
      if (st.released.count(digest_hex)) {
        released = true;
        auto l = st.lens.find(digest_hex);
        full_len = l != st.lens.end() ? l->second : 0;
      }
    }
    if (released && offset >= 0 && len >= 0 && offset + len <= full_len) {
      std::string buf;
      if (remote_fetch_(digest_hex, full_len, &buf) &&
          static_cast<int64_t>(buf.size()) == full_len &&
          Sha1(buf.data(), buf.size()).Hex() == digest_hex) {
        remote_reads_.fetch_add(1, std::memory_order_relaxed);
        memcpy(dst, buf.data() + offset, static_cast<size_t>(len));
        return true;
      }
    }
  }
  return false;
}

bool ChunkStore::ReadChunkSlices(const SliceReq* reqs, size_t n,
                                 int64_t* vec_batches, int64_t* vec_spans,
                                 std::string* failed) const {
  // Partition by residence: only slab-resident chunks can share a
  // preadv (flat chunks live one per inode, EC/released ones decode or
  // fetch).  Membership is probed lock-free like ReadChunkSlice; a
  // chunk that moves between the probe and the vectored read simply
  // falls back below.
  std::vector<SlabStore::SliceRead> slab_reqs;
  std::vector<size_t> slab_idx;
  for (size_t i = 0; i < n; ++i) {
    const SliceReq& r = reqs[i];
    if (slab_ != nullptr && slab_->Has(kSlabKindChunk, *r.digest_hex)) {
      slab_reqs.push_back(
          SlabStore::SliceRead{r.digest_hex, r.offset, r.len, r.dst});
      slab_idx.push_back(i);
    } else if (!ReadChunkSlice(*r.digest_hex, r.offset, r.len, r.dst)) {
      *failed = *r.digest_hex;
      return false;
    }
  }
  if (!slab_reqs.empty()) {
    std::unique_ptr<bool[]> ok(new bool[slab_reqs.size()]());
    slab_->ReadSlices(kSlabKindChunk, slab_reqs.data(), slab_reqs.size(),
                      ok.get(), vec_batches, vec_spans);
    for (size_t j = 0; j < slab_reqs.size(); ++j) {
      if (ok[j]) continue;
      // Raced a compaction (or the chunk left the slab): the full
      // fallthrough owns the retry.
      const SliceReq& r = reqs[slab_idx[j]];
      if (!ReadChunkSlice(*r.digest_hex, r.offset, r.len, r.dst)) {
        *failed = *r.digest_hex;
        return false;
      }
    }
  }
  return true;
}

// -- hot-chunk read cache -------------------------------------------------

std::shared_ptr<const std::string> ChunkStore::CacheGet(
    const std::string& digest_hex) {
  std::lock_guard<RankedMutex> lk(cache_.mu);
  auto it = cache_.index.find(digest_hex);
  if (it == cache_.index.end()) return nullptr;
  cache_.lru.splice(cache_.lru.begin(), cache_.lru, it->second);
  return it->second->data;
}

void ChunkStore::CacheInsertIfLive(const std::string& digest_hex,
                                   std::shared_ptr<const std::string> data) {
  if (data == nullptr ||
      static_cast<int64_t>(data->size()) > cache_.cap_bytes)
    return;
  // Re-check liveness UNDER the stripe lock: the disk read above ran
  // lock-free, so it may have raced a Quarantine() or a delete's
  // unlink.  Both invalidate under the stripe lock, so an insert gated
  // by the same lock can never publish a stale entry past them.
  Stripe& st = StripeFor(digest_hex);
  std::lock_guard<RankedMutex> slk(st.mu);
  if (st.refs.find(digest_hex) == st.refs.end() ||
      st.quarantined.count(digest_hex))
    return;
  std::lock_guard<RankedMutex> lk(cache_.mu);
  if (cache_.index.count(digest_hex)) return;  // racer inserted first
  cache_.lru.push_front(CacheEntry{digest_hex, std::move(data)});
  cache_.index[digest_hex] = cache_.lru.begin();
  cache_.bytes += static_cast<int64_t>(cache_.lru.front().data->size());
  while (cache_.bytes > cache_.cap_bytes && !cache_.lru.empty()) {
    CacheEntry& victim = cache_.lru.back();
    cache_.bytes -= static_cast<int64_t>(victim.data->size());
    cache_.index.erase(victim.digest_hex);
    cache_.lru.pop_back();  // in-flight spans keep the bytes via shared_ptr
    cache_.evictions.fetch_add(1, std::memory_order_relaxed);
  }
}

void ChunkStore::CacheInvalidate(const std::string& digest_hex) {
  if (cache_.cap_bytes <= 0) return;
  std::lock_guard<RankedMutex> lk(cache_.mu);
  auto it = cache_.index.find(digest_hex);
  if (it == cache_.index.end()) return;
  cache_.bytes -= static_cast<int64_t>(it->second->data->size());
  cache_.lru.erase(it->second);
  cache_.index.erase(it);
  cache_.invalidations.fetch_add(1, std::memory_order_relaxed);
}

void ChunkStore::CacheClear() {
  std::lock_guard<RankedMutex> lk(cache_.mu);
  cache_.lru.clear();
  cache_.index.clear();
  cache_.bytes = 0;
}

std::shared_ptr<const std::string> ChunkStore::ReadChunkCached(
    const std::string& digest_hex, int64_t expect_len, bool* hit) {
  *hit = false;
  if (cache_.cap_bytes <= 0) return nullptr;
  auto p = CacheGet(digest_hex);
  if (p != nullptr && static_cast<int64_t>(p->size()) == expect_len) {
    *hit = true;
    cache_.hits.fetch_add(1, std::memory_order_relaxed);
    return p;
  }
  cache_.misses.fetch_add(1, std::memory_order_relaxed);
  auto fresh = std::make_shared<std::string>();
  if (!ReadChunk(digest_hex, expect_len, fresh.get())) return nullptr;
  std::shared_ptr<const std::string> frozen = std::move(fresh);
  CacheInsertIfLive(digest_hex, frozen);
  return frozen;
}

std::shared_ptr<const std::string> ChunkStore::CacheLookup(
    const std::string& digest_hex, int64_t expect_len) {
  if (cache_.cap_bytes <= 0) return nullptr;
  auto p = CacheGet(digest_hex);
  if (p != nullptr && static_cast<int64_t>(p->size()) == expect_len) {
    cache_.hits.fetch_add(1, std::memory_order_relaxed);
    return p;
  }
  return nullptr;
}

int64_t ChunkStore::cache_bytes() const {
  std::lock_guard<RankedMutex> lk(cache_.mu);
  return cache_.bytes;
}

int64_t ChunkStore::cache_chunks() const {
  std::lock_guard<RankedMutex> lk(cache_.mu);
  return static_cast<int64_t>(cache_.lru.size());
}

int64_t ChunkStore::unique_chunks() const {
  int64_t n = 0;
  for (const Stripe& st : stripes_) {
    std::lock_guard<RankedMutex> lk(st.mu);
    n += static_cast<int64_t>(st.refs.size());
  }
  return n;
}

int64_t ChunkStore::gc_pending_chunks() const {
  int64_t n = 0;
  for (const Stripe& st : stripes_) {
    std::lock_guard<RankedMutex> lk(st.mu);
    n += static_cast<int64_t>(st.zero_ref.size());
  }
  return n;
}

int64_t ChunkStore::quarantined_chunks() const {
  int64_t n = 0;
  for (const Stripe& st : stripes_) {
    std::lock_guard<RankedMutex> lk(st.mu);
    n += static_cast<int64_t>(st.quarantined.size());
  }
  return n;
}

// -- integrity engine -----------------------------------------------------

std::vector<ChunkStore::ChunkInfo> ChunkStore::SnapshotLive(
    int prefix) const {
  static const char* kHex = "0123456789abcdef";
  char p0 = 0, p1 = 0;
  if (prefix >= 0) {
    p0 = kHex[(prefix >> 4) & 0xF];
    p1 = kHex[prefix & 0xF];
  }
  std::vector<ChunkInfo> out;
  // A byte prefix pins the stripe (stripe = high nibble), so a sliced
  // scan holds exactly one stripe lock; a full snapshot walks the 16
  // stripes one lock at a time (callers tolerate per-stripe tearing —
  // they already tolerated churn after a monolithic snapshot).
  int first = prefix >= 0 ? (prefix >> 4) & 0xF : 0;
  int last = prefix >= 0 ? first : kStripes - 1;
  for (int s = first; s <= last; ++s) {
    const Stripe& st = stripes_[s];
    std::lock_guard<RankedMutex> lk(st.mu);
    for (const auto& [dig, n] : st.refs) {
      if (prefix >= 0 && (dig[0] != p0 || dig[1] != p1)) continue;
      if (st.quarantined.count(dig)) continue;
      // Released chunks have no local bytes to verify — their integrity
      // lives with the group owner's stripe (EC repair stage).
      if (st.released.count(dig)) continue;
      auto l = st.lens.find(dig);
      out.push_back({dig, l != st.lens.end() ? l->second : 0});
    }
  }
  return out;
}

std::vector<ChunkStore::ChunkInfo> ChunkStore::SnapshotQuarantined() const {
  std::vector<ChunkInfo> out;
  for (const Stripe& st : stripes_) {
    std::lock_guard<RankedMutex> lk(st.mu);
    for (const std::string& dig : st.quarantined) {
      if (st.refs.find(dig) == st.refs.end()) continue;  // zero-ref: GC's
      auto l = st.lens.find(dig);
      out.push_back({dig, l != st.lens.end() ? l->second : 0});
    }
  }
  return out;
}

bool ChunkStore::IsQuarantined(const std::string& digest_hex) const {
  const Stripe& st = StripeFor(digest_hex);
  std::lock_guard<RankedMutex> lk(st.mu);
  return st.quarantined.count(digest_hex) != 0;
}

ChunkStore::QuarantineResult ChunkStore::Quarantine(
    const std::string& digest_hex) {
  Stripe& st = StripeFor(digest_hex);
  std::lock_guard<RankedMutex> lk(st.mu);
  if (st.refs.find(digest_hex) == st.refs.end())
    return QuarantineResult::kGone;  // deleted since the snapshot
  if (st.pins.count(digest_hex)) return QuarantineResult::kPinned;
  // Slab-resident chunk: re-verify the record extent under the lock,
  // then preserve the bad bytes in quarantine/ (the flat path's rename
  // equivalent — forensics plus the heal/repair contract) and kill the
  // slot.  Compaction reclaims the dead extent later; the quarantine
  // mark is what routes re-uploads and replica repairs to the heal
  // path, exactly as for flat files.
  if (slab_ != nullptr && slab_->Has(kSlabKindChunk, digest_hex)) {
    std::string payload;
    bool readable = slab_->Read(kSlabKindChunk, digest_hex, &payload);
    if (readable && Sha1(payload.data(), payload.size()).Hex() == digest_hex)
      return QuarantineResult::kClean;
    mkdir((store_path_ + "/data/quarantine").c_str(), 0755);
    if (readable) {
      std::string werr;
      if (!WriteChunkFile(QuarantinePath(digest_hex), payload.data(),
                          payload.size(), &werr))
        FDFS_LOG_WARN("quarantine copy of slab chunk %s: %s",
                      digest_hex.c_str(), werr.c_str());
    }
    slab_->MarkDead(kSlabKindChunk, digest_hex);
    st.quarantined.insert(digest_hex);
    CacheInvalidate(digest_hex);
    return QuarantineResult::kQuarantined;
  }
  // Flat chunk: re-verify under the lock — the scrubber's verify read
  // ran lock-free, so it may have raced a delete + re-upload of this
  // digest and hashed a half-gone file.  No writer of this digest can
  // interleave with this read, so a clean hash here is authoritative.
  {
    int fd = open(ChunkPath(digest_hex).c_str(), O_RDONLY);
    if (fd >= 0) {
      Sha1Stream sha;
      char buf[65536];
      ssize_t r;
      while ((r = read(fd, buf, sizeof(buf))) > 0)
        sha.Update(buf, static_cast<size_t>(r));
      close(fd);
      if (r == 0 && sha.Final().Hex() == digest_hex)
        return QuarantineResult::kClean;
    }
  }
  mkdir((store_path_ + "/data/quarantine").c_str(), 0755);
  // A rename failure (e.g. the file already vanished) still marks the
  // chunk quarantined: either way the bytes are not servable, and the
  // mark is what routes re-uploads/repairs to the heal path.
  if (rename(ChunkPath(digest_hex).c_str(),
             QuarantinePath(digest_hex).c_str()) != 0 &&
      errno != ENOENT)
    FDFS_LOG_WARN("quarantine rename %s: %s", digest_hex.c_str(),
                  strerror(errno));
  st.quarantined.insert(digest_hex);
  // Same-lock cache invalidation: after this returns, no download can
  // serve the jailed bytes from the read cache (inserts re-check the
  // quarantine mark under this lock).
  CacheInvalidate(digest_hex);
  return QuarantineResult::kQuarantined;
}

bool ChunkStore::RepairChunk(const std::string& digest_hex, const char* data,
                             size_t len, std::string* err) {
  Stripe& st = StripeFor(digest_hex);
  std::lock_guard<RankedMutex> lk(st.mu);
  if (st.refs.find(digest_hex) == st.refs.end()) {
    *err = "no longer referenced";
    return false;
  }
  if (!WriteChunkPayloadLocked(digest_hex, data, len, err)) return false;
  st.quarantined.erase(digest_hex);
  unlink(QuarantinePath(digest_hex).c_str());
  st.lens[digest_hex] = static_cast<int64_t>(len);
  // A repair RE-PROMOTES the chunk to the replicated tier: the local
  // payload is authoritative again, so any released mark clears and any
  // stale EC slot dies (the scrubber's kLost fallback routes here — the
  // stripe it came from is being dropped).
  if (st.released.count(digest_hex))
    UnreleaseLocked(st, digest_hex, static_cast<int64_t>(len));
  if (ec_ != nullptr) ec_->MarkDead(digest_hex, nullptr);
  // The repaired payload hashes to the digest, so a cached copy would
  // be byte-identical — but drop it anyway: the cache must never hold
  // an entry that predates a quarantine episode.
  CacheInvalidate(digest_hex);
  return true;
}

// -- erasure-coded cold tier ----------------------------------------------

void ChunkStore::AppendReleasedLog(const std::string& records) const {
  int fd = open(ReleasedLogPath().c_str(), O_CREAT | O_WRONLY | O_APPEND,
                0644);
  if (fd < 0) {
    FDFS_LOG_WARN("released.log open: %s", strerror(errno));
    return;
  }
  if (write(fd, records.data(), records.size()) !=
          static_cast<ssize_t>(records.size()) ||
      fsync(fd) != 0)
    FDFS_LOG_WARN("released.log append: %s", strerror(errno));
  close(fd);
}

void ChunkStore::UnreleaseLocked(Stripe& s, const std::string& digest_hex,
                                 int64_t len) {
  if (s.released.erase(digest_hex) == 0) return;
  released_chunks_--;
  released_bytes_ -= len;
  AppendReleasedLog("H " + digest_hex + "\n");
}

bool ChunkStore::IsReleased(const std::string& digest_hex) const {
  const Stripe& st = StripeFor(digest_hex);
  std::lock_guard<RankedMutex> lk(st.mu);
  return st.released.count(digest_hex) != 0;
}

std::vector<ChunkStore::ChunkInfo> ChunkStore::SnapshotDemotable(
    int64_t now_s, int64_t age_s) const {
  std::vector<ChunkInfo> out;
  if (ec_ == nullptr) return out;
  // Pass 1 (locked, per stripe): the cheap state filters.  The EC probe
  // runs under the stripe lock by rank (90 -> 96), and pins are the one
  // liveness signal demotion respects in advance — an EC-resident read
  // still serves pinned streams, but skipping hot pinned chunks avoids
  // demoting what a session is actively shipping.
  std::vector<ChunkInfo> candidates;
  for (const Stripe& st : stripes_) {
    std::lock_guard<RankedMutex> lk(st.mu);
    for (const auto& [dig, n] : st.refs) {
      if (st.quarantined.count(dig) || st.released.count(dig) ||
          st.pins.count(dig))
        continue;
      if (ec_->Has(dig)) continue;
      auto l = st.lens.find(dig);
      candidates.push_back({dig, l != st.lens.end() ? l->second : 0});
    }
  }
  // Pass 2 (lock-free): coldness by payload mtime — flat file stat, or
  // the slab record's meta.  A chunk that vanished between the passes
  // simply fails both probes and drops out.
  for (ChunkInfo& c : candidates) {
    int64_t mtime = -1;
    if (slab_ != nullptr) {
      SlabStore::Slot slot;
      if (slab_->Lookup(kSlabKindChunk, c.digest_hex, &slot))
        mtime = slot.mtime;
    }
    if (mtime < 0) {
      struct stat fst;
      if (stat(ChunkPath(c.digest_hex).c_str(), &fst) == 0)
        mtime = static_cast<int64_t>(fst.st_mtime);
    }
    if (mtime >= 0 && now_s - mtime >= age_s)
      out.push_back(std::move(c));
  }
  return out;
}

int64_t ChunkStore::DemoteToEc(const std::vector<ChunkInfo>& chunks,
                               int64_t* chunks_demoted,
                               int64_t* bytes_demoted, std::string* err) {
  if (ec_ == nullptr) {
    *err = "ec tier disabled";
    return -1;
  }
  // Phase 1 (lock-free): read + SHA1-verify each candidate — the
  // stripe must never inherit bytes that would fail their own digest.
  std::vector<std::pair<std::string, std::string>> batch;
  for (const ChunkInfo& c : chunks) {
    std::string payload;
    if (!ReadChunk(c.digest_hex, c.length, &payload)) continue;
    if (Sha1(payload.data(), payload.size()).Hex() != c.digest_hex)
      continue;  // scrub's verify stage owns corruption; skip here
    if (ec_->Has(c.digest_hex)) continue;
    batch.emplace_back(c.digest_hex, std::move(payload));
  }
  if (batch.empty()) {
    *err = "no demotable chunks survived re-verify";
    return -1;
  }
  int64_t id = ec_->EncodeStripe(batch, err);
  if (id < 0) return -1;
  // Verify-then-release, local half: re-read the stripe from disk
  // through the decode path before ANY copy (local or replica) is
  // surrendered.
  if (!ec_->VerifyStripe(id, err)) {
    ec_->DropStripe(id, nullptr);
    return -1;
  }
  // Phase 2 (locked per digest): drop the local payload; refs/lens stay
  // and reads fall through to the stripe.  A digest deleted since phase
  // 1 has no refs — kill its freshly-encoded EC slot too, or the
  // content-addressed index would resurrect a deleted chunk.
  for (auto& [dig, payload] : batch) {
    Stripe& st = StripeFor(dig);
    std::lock_guard<RankedMutex> lk(st.mu);
    if (st.refs.find(dig) == st.refs.end()) {
      ec_->MarkDead(dig, nullptr);
      continue;
    }
    if (st.quarantined.count(dig)) continue;  // repair machinery owns it
    DropPayloadLocked(st, dig);
    if (chunks_demoted != nullptr) ++*chunks_demoted;
    if (bytes_demoted != nullptr)
      *bytes_demoted += static_cast<int64_t>(payload.size());
  }
  return id;
}

std::string ChunkStore::ReleaseChunks(const std::vector<ChunkInfo>& chunks) {
  std::string kept(chunks.size(), '\0');
  std::string journal;
  for (size_t i = 0; i < chunks.size(); ++i) {
    const std::string& dig = chunks[i].digest_hex;
    Stripe& st = StripeFor(dig);
    std::lock_guard<RankedMutex> lk(st.mu);
    auto it = st.refs.find(dig);
    if (it == st.refs.end()) continue;      // never held: nothing retained
    if (st.released.count(dig)) continue;   // idempotent replay
    if (st.pins.count(dig) || st.quarantined.count(dig)) {
      // An in-flight stream still reads the local bytes, or the
      // quarantine/repair lifecycle owns them — keep the replica; the
      // owner keeps full-copy coverage for this digest and may retry
      // next pass.
      kept[i] = 1;
      continue;
    }
    DropPayloadLocked(st, dig);
    st.released.insert(dig);
    released_chunks_++;
    released_bytes_ += chunks[i].length;
    journal += "R " + dig + " " + std::to_string(chunks[i].length) + "\n";
  }
  // One durable append for the whole batch BEFORE the response: the
  // owner treats a 0 byte as permission to count this replica gone, so
  // the mark must survive a crash (or a restart would serve the digest
  // as locally-missing instead of remote-fetching).
  if (!journal.empty()) AppendReleasedLog(journal);
  return kept;
}

// -- recipe sidecars (slab-aware) -----------------------------------------

std::string ChunkStore::RecipeSlabKey(const std::string& rcp_path) const {
  // Keys are store-root-relative so replicas (different absolute roots)
  // and relocated stores derive identical keys from identical layouts.
  if (rcp_path.compare(0, store_path_.size(), store_path_) == 0) {
    size_t start = store_path_.size();
    while (start < rcp_path.size() && rcp_path[start] == '/') ++start;
    return rcp_path.substr(start);
  }
  return rcp_path;
}

bool ChunkStore::StoreRecipe(const std::string& rcp_path, const Recipe& r,
                             std::string* err) {
  std::string key = RecipeSlabKey(rcp_path);
  // Size-probe arithmetically (24B header + 28B/chunk) so a recipe that
  // stays flat — every file past ~19 MB at default thresholds — is not
  // encoded twice on the upload hot path.
  int64_t encoded_size = 24 + 28 * static_cast<int64_t>(r.chunks.size());
  if (slab_ != nullptr && slab_opts_.recipe_threshold > 0 &&
      key.size() <= kSlabKeyMaxLen &&
      encoded_size < slab_opts_.recipe_threshold) {
    std::string buf = EncodeRecipe(r);
    // durable: recipes keep WriteRecipeFile's fsync guarantee — the
    // recipe IS the file's existence, chunks are resurrectable.
    if (!slab_->Append(kSlabKindRecipe, key, buf.data(), buf.size(),
                       /*durable=*/true, err))
      return false;
    // A flat sidecar from before a threshold change must not shadow
    // (or double-count refs for) the slab record.
    unlink(rcp_path.c_str());
    return true;
  }
  // Flat sidecar: the recipe is the only thing that needs the file-id
  // directory fan-out, so the dirs are created HERE, not by callers — a
  // slab-resident recipe must cost zero inodes, fan-out dirs included
  // (they dominate the inode bill on small-file corpora otherwise).
  EnsureParentDirs(rcp_path);
  if (!WriteRecipeFile(rcp_path, r, err)) return false;
  if (slab_ != nullptr) slab_->MarkDead(kSlabKindRecipe, key);
  return true;
}

std::optional<Recipe> ChunkStore::LoadRecipe(
    const std::string& rcp_path) const {
  if (slab_ != nullptr) {
    std::string payload;
    if (slab_->Read(kSlabKindRecipe, RecipeSlabKey(rcp_path), &payload))
      return DecodeRecipe(payload.data(), payload.size());
  }
  return ReadRecipeFile(rcp_path);
}

bool ChunkStore::HasRecipe(const std::string& rcp_path) const {
  if (slab_ != nullptr &&
      slab_->Has(kSlabKindRecipe, RecipeSlabKey(rcp_path)))
    return true;
  struct stat st;
  return stat(rcp_path.c_str(), &st) == 0;
}

bool ChunkStore::RemoveRecipe(const std::string& rcp_path,
                              int64_t* bytes_out) {
  bool found = false;
  int64_t bytes = 0;
  if (slab_ != nullptr) {
    int64_t payload_len = 0;
    if (slab_->MarkDead(kSlabKindRecipe, RecipeSlabKey(rcp_path),
                        &payload_len)) {
      found = true;
      bytes += payload_len;
    }
  }
  struct stat st;
  if (stat(rcp_path.c_str(), &st) == 0 && unlink(rcp_path.c_str()) == 0) {
    found = true;
    bytes += st.st_size;
  }
  if (bytes_out != nullptr) *bytes_out = bytes;
  return found;
}

int64_t ChunkStore::CompactSlabs(const std::function<void(int64_t)>& pace,
                                 const std::function<bool()>& stop,
                                 std::vector<ChunkInfo>* corrupt,
                                 int64_t* reclaimed) {
  if (slab_ == nullptr) return 0;
  SlabStore::CompactResult res = slab_->Compact(pace, stop);
  if (reclaimed != nullptr) *reclaimed += res.reclaimed_bytes;
  // Copy-time re-verify failures ride the standard quarantine/heal
  // machinery: the caller (scrub pass) runs HandleCorrupt on each,
  // which quarantines the slot (marking it dead — letting the next
  // compaction finish the slab) and repairs from a group replica.
  if (corrupt != nullptr) {
    for (const std::string& dig : res.corrupt_chunk_keys) {
      int64_t len = 0;
      {
        const Stripe& st = StripeFor(dig);
        std::lock_guard<RankedMutex> lk(st.mu);
        auto it = st.lens.find(dig);
        if (it != st.lens.end()) len = it->second;
      }
      corrupt->push_back({dig, len});
    }
  }
  for (const std::string& key : res.corrupt_recipe_keys) {
    // Preserve the bytes for forensics, then KILL the slot: a live
    // corrupt recipe would keep HasRecipe() true, which blocks the
    // idempotent sync-replay re-store and recovery's resume check —
    // the file would stay unreadable forever despite healthy replicas,
    // and its slab could never finish compacting.  Dead, the name
    // reads as absent and replica re-sync/recovery recreates it.
    std::string payload, werr;
    if (slab_->Read(kSlabKindRecipe, key, &payload)) {
      mkdir((store_path_ + "/data/quarantine").c_str(), 0755);
      std::string qname = key;
      for (char& c : qname)
        if (c == '/') c = '_';
      if (!WriteChunkFile(store_path_ + "/data/quarantine/recipe_" + qname,
                          payload.data(), payload.size(), &werr))
        FDFS_LOG_WARN("slab compact: quarantine copy of recipe %s: %s",
                      key.c_str(), werr.c_str());
    }
    slab_->MarkDead(kSlabKindRecipe, key);
    FDFS_LOG_ERROR("slab compact: recipe record %s failed re-verify — "
                   "slot killed (bytes preserved under data/quarantine/); "
                   "replica re-sync/recovery recreates the file",
                   key.c_str());
    if (events_ != nullptr)
      events_->Record(EventSeverity::kError, "slab.recipe_corrupt", key,
                      "bytes=" + std::to_string(payload.size()));
  }
  if (events_ != nullptr && res.slabs_compacted > 0)
    events_->Record(EventSeverity::kInfo, "slab.compact", store_path_,
                    "slabs=" + std::to_string(res.slabs_compacted) +
                        " reclaimed_bytes=" +
                        std::to_string(res.reclaimed_bytes) +
                        " copied=" + std::to_string(res.copied_records));
  return res.slabs_compacted;
}

int64_t ChunkStore::GcSweep(int64_t now_s, int64_t* bytes) {
  int64_t reclaimed = 0;
  for (Stripe& st : stripes_) {
    std::lock_guard<RankedMutex> lk(st.mu);
    for (auto it = st.zero_ref.begin(); it != st.zero_ref.end();) {
      if (now_s - it->second.since_s < gc_grace_s_ ||
          st.pins.count(it->first)) {
        // Inside the grace window, or pinned by an in-flight stream /
        // phase-1 upload session — the pin probe shares this stripe
        // lock with the unlink, so PinAndMask can never lose the race.
        ++it;
        continue;
      }
      UnlinkRetiredLocked(st, it->first);
      zero_ref_bytes_ -= it->second.length;
      *bytes += it->second.length;
      ++reclaimed;
      it = st.zero_ref.erase(it);
    }
  }
  return reclaimed;
}

namespace {

void WalkRecipes(const std::string& dir,
                 const std::function<bool(const std::string&)>& skip_flat,
                 std::unordered_map<std::string, int64_t>* refs,
                 std::unordered_map<std::string, int64_t>* lens) {
  DIR* d = opendir(dir.c_str());
  if (d == nullptr) return;
  struct dirent* de;
  while ((de = readdir(d)) != nullptr) {
    std::string name = de->d_name;
    if (name == "." || name == "..") continue;
    std::string path = dir + "/" + name;
    struct stat st;
    if (stat(path.c_str(), &st) != 0) continue;
    if (S_ISDIR(st.st_mode)) {
      if (name != "chunks" && name != "sync" && name != "tmp" &&
          name != "slabs" && name != "ec")
        WalkRecipes(path, skip_flat, refs, lens);
    } else if (name.size() > 4 &&
               name.compare(name.size() - 4, 4, ".rcp") == 0) {
      if (skip_flat != nullptr && skip_flat(path)) continue;
      auto r = ReadRecipeFile(path);
      if (!r.has_value()) {
        FDFS_LOG_WARN("unreadable recipe %s ignored", path.c_str());
        continue;
      }
      for (const RecipeEntry& e : r->chunks) {
        (*refs)[e.digest_hex]++;
        (*lens)[e.digest_hex] = e.length;
      }
    }
  }
  closedir(d);
}

}  // namespace

void ChunkStore::RebuildFromRecipes() {
  // Slab slot index first: recipes may live there, and the orphan scan
  // below needs the chunk records indexed.  Same no-binlog philosophy —
  // the slab headers on disk are the ground truth.
  if (slab_ != nullptr) slab_->ScanRebuild();
  // EC stripe manifests next (same ground-truth philosophy; also
  // collects orphan shards from crashed encodes).
  if (ec_ != nullptr) ec_->Rescan();

  std::unordered_map<std::string, int64_t> refs, lens;
  // Cross-layout dedup: a crash inside StoreRecipe (between the slab
  // append and the flat-twin unlink, or vice versa) can leave BOTH
  // representations of one recipe on disk.  They encode the identical
  // Recipe (one StoreRecipe call wrote both), so count refs from the
  // slab copy only and drop the flat twin — double-counting would pin
  // the file's chunks with refs that no single delete can release.
  auto skip_flat = [this](const std::string& rcp_path) {
    if (slab_ == nullptr ||
        !slab_->Has(kSlabKindRecipe, RecipeSlabKey(rcp_path)))
      return false;
    FDFS_LOG_INFO("recipe %s exists in both layouts (crash window): "
                  "keeping the slab record, dropping the flat twin",
                  rcp_path.c_str());
    unlink(rcp_path.c_str());
    return true;
  };
  WalkRecipes(store_path_ + "/data", skip_flat, &refs, &lens);
  if (slab_ != nullptr) {
    slab_->ForEachLive(
        kSlabKindRecipe,
        [&](const std::string& key, const std::string& payload) {
          auto r = DecodeRecipe(payload.data(), payload.size());
          if (!r.has_value()) {
            FDFS_LOG_WARN("unreadable slab recipe %s ignored", key.c_str());
            return;
          }
          for (const RecipeEntry& e : r->chunks) {
            refs[e.digest_hex]++;
            lens[e.digest_hex] = e.length;
          }
        });
  }

  // GC pass: any chunk file not named by a recipe is an orphan — a
  // crash leftover, or (with a GC grace window) a deliberately-retired
  // zero-ref chunk whose grace had not expired at shutdown.  Eager mode
  // drops orphans on the spot (the original behavior); grace mode
  // parks them in zero_ref aged by file mtime, so the grace window is
  // crash-safe instead of resetting on every restart.
  int64_t orphans = 0, parked = 0, bytes = 0;
  std::unordered_map<std::string, ZeroRef> zero;
  std::string croot = store_path_ + "/data/chunks";
  DIR* d1 = opendir(croot.c_str());
  if (d1 != nullptr) {
    struct dirent* e1;
    while ((e1 = readdir(d1)) != nullptr) {
      if (e1->d_name[0] == '.') continue;
      std::string l1 = croot + "/" + e1->d_name;
      DIR* d2 = opendir(l1.c_str());
      if (d2 == nullptr) continue;
      struct dirent* e2;
      while ((e2 = readdir(d2)) != nullptr) {
        if (e2->d_name[0] == '.') continue;
        std::string l2 = l1 + "/" + e2->d_name;
        DIR* d3 = opendir(l2.c_str());
        if (d3 == nullptr) continue;
        struct dirent* e3;
        while ((e3 = readdir(d3)) != nullptr) {
          std::string name = e3->d_name;
          if (name[0] == '.') continue;
          if (IsHex40(name) && refs.find(name) != refs.end()) continue;
          std::string path = l2 + "/" + name;
          struct stat st;
          if (IsHex40(name) && gc_grace_s_ > 0 &&
              stat(path.c_str(), &st) == 0) {
            zero[name] = ZeroRef{static_cast<int64_t>(st.st_size),
                                 static_cast<int64_t>(st.st_mtime)};
            lens[name] = static_cast<int64_t>(st.st_size);
            ++parked;
          } else {
            unlink(path.c_str());
            ++orphans;
          }
        }
        closedir(d3);
      }
      closedir(d2);
    }
    closedir(d1);
  }
  // Slab-resident orphans: live chunk records no recipe names.  Grace
  // mode parks them (aged by the record's mtime, so the window is
  // crash-safe like the flat path's file-mtime aging); eager mode marks
  // the slots dead on the spot.
  if (slab_ != nullptr) {
    std::vector<std::string> dead;
    slab_->ForEachLiveMeta(
        kSlabKindChunk, [&](const SlabStore::RecordMeta& m) {
          if (refs.find(m.key) != refs.end()) {
            lens.emplace(m.key, m.payload_len);
            return;
          }
          if (gc_grace_s_ > 0) {
            zero[m.key] = ZeroRef{m.payload_len,
                                  m.mtime > 0 ? m.mtime : time(nullptr)};
            lens[m.key] = m.payload_len;
            ++parked;
          } else {
            dead.push_back(m.key);
            ++orphans;
          }
        });
    for (const std::string& key : dead)
      slab_->MarkDead(kSlabKindChunk, key);
  }

  // Quarantine survives restarts: a referenced digest whose bytes sit in
  // quarantine/ must keep reading as missing (and healable), or a
  // restart would silently re-admit the corrupt state.  Unreferenced
  // quarantine files are corrupt garbage nobody names — drop them.
  std::unordered_set<std::string> quarantined;
  std::string qroot = store_path_ + "/data/quarantine";
  DIR* qd = opendir(qroot.c_str());
  if (qd != nullptr) {
    struct dirent* qe;
    while ((qe = readdir(qd)) != nullptr) {
      std::string name = qe->d_name;
      if (name[0] == '.') continue;
      // Forensic copies of corrupt slab RECIPES (CompactSlabs) keep
      // their bytes across restarts — the operator drains them by hand
      // like chunk quarantine files.
      if (name.compare(0, 7, "recipe_") == 0) continue;
      if (IsHex40(name) && refs.find(name) != refs.end()) {
        struct stat st;
        if (stat(ChunkPath(name).c_str(), &st) == 0 ||
            (slab_ != nullptr && slab_->Has(kSlabKindChunk, name))) {
          // A healed copy already lives in chunks/ or the slab store
          // (crash between the repair write and the quarantine
          // unlink): prefer it.
          unlink((qroot + "/" + name).c_str());
        } else {
          quarantined.insert(name);
        }
      } else {
        unlink((qroot + "/" + name).c_str());
      }
    }
    closedir(qd);
  }

  // Distribute the rebuilt maps into their stripes.  Startup runs
  // before serving, but take the locks anyway — Rebuild is also called
  // in tests against a store that already served.
  size_t unique = 0;
  int64_t ub = 0, zb = 0;
  std::array<Stripe, kStripes> fresh;
  for (auto& [dig, n] : refs) {
    Stripe& st = fresh[StripeIndex(dig)];
    st.refs[dig] = n;
  }
  for (auto& [dig, l] : lens) fresh[StripeIndex(dig)].lens[dig] = l;
  for (auto& [dig, z] : zero) {
    fresh[StripeIndex(dig)].zero_ref[dig] = z;
    zb += z.length;
  }
  for (auto& dig : quarantined) fresh[StripeIndex(dig)].quarantined.insert(dig);
  for (const auto& [dig, n] : refs) ub += lens[dig];
  unique = refs.size();
  for (int s = 0; s < kStripes; ++s) {
    Stripe& st = stripes_[s];
    std::lock_guard<RankedMutex> lk(st.mu);
    st.refs = std::move(fresh[s].refs);
    st.lens = std::move(fresh[s].lens);
    st.zero_ref = std::move(fresh[s].zero_ref);
    st.quarantined = std::move(fresh[s].quarantined);
    st.pins.clear();
    st.released.clear();  // re-derived from released.log below
  }
  unique_bytes_ = ub;
  zero_ref_bytes_ = zb;
  bytes = ub;
  // released.log replay: re-mark replicas this node surrendered via
  // EC_RELEASE.  A mark survives only while it is still true — the
  // digest must be referenced and genuinely payload-less locally (a
  // heal that crashed before its 'H' append shows up as bytes on disk
  // and wins).  The journal is rewritten compacted with the surviving
  // set, so it never grows unboundedly across release/heal churn.
  released_chunks_ = 0;
  released_bytes_ = 0;
  {
    std::unordered_map<std::string, int64_t> marks;
    std::string jbuf;
    if (ReadWholeFile(ReleasedLogPath(), &jbuf)) {
      size_t pos = 0;
      while (pos < jbuf.size()) {
        size_t eol = jbuf.find('\n', pos);
        if (eol == std::string::npos) eol = jbuf.size();
        std::string line = jbuf.substr(pos, eol - pos);
        pos = eol + 1;
        if (line.size() >= 42 && line[1] == ' ' &&
            IsHex40(line.substr(2, 40))) {
          if (line[0] == 'R')
            marks[line.substr(2, 40)] =
                strtoll(line.c_str() + 42, nullptr, 10);
          else if (line[0] == 'H')
            marks.erase(line.substr(2, 40));
        }
      }
    }
    std::string compacted;
    for (const auto& [dig, mlen] : marks) {
      Stripe& st = stripes_[StripeIndex(dig)];
      std::lock_guard<RankedMutex> lk(st.mu);
      if (st.refs.find(dig) == st.refs.end()) continue;  // deleted
      struct stat fst;
      if (stat(ChunkPath(dig).c_str(), &fst) == 0 ||
          (slab_ != nullptr && slab_->Has(kSlabKindChunk, dig)))
        continue;  // bytes came back (heal crashed pre-'H'): not released
      st.released.insert(dig);
      int64_t l = mlen;
      auto li = st.lens.find(dig);
      if (li != st.lens.end()) l = li->second;
      released_chunks_++;
      released_bytes_ += l;
      compacted += "R " + dig + " " + std::to_string(l) + "\n";
    }
    if (marks.empty() && compacted.empty()) {
      unlink(ReleasedLogPath().c_str());
    } else {
      std::string tmp = ReleasedLogPath() + ".tmp";
      std::string werr;
      if (WriteChunkFile(tmp, compacted.data(), compacted.size(), &werr)) {
        if (rename(tmp.c_str(), ReleasedLogPath().c_str()) != 0)
          FDFS_LOG_WARN("released.log rewrite: %s", strerror(errno));
      } else {
        FDFS_LOG_WARN("released.log rewrite: %s", werr.c_str());
      }
    }
  }
  CacheClear();
  if (unique > 0 || orphans > 0 || parked > 0 || !quarantined.empty())
    FDFS_LOG_INFO("chunk store: %zu unique chunks (%lld bytes), %lld "
                  "orphans collected, %lld awaiting GC, %zu quarantined",
                  unique, static_cast<long long>(bytes),
                  static_cast<long long>(orphans),
                  static_cast<long long>(parked), quarantined.size());
}

}  // namespace fdfs
