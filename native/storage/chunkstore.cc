#include "storage/chunkstore.h"

#include <dirent.h>
#include <fcntl.h>
#include <string.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cerrno>
#include <cstdio>

#include "common/bytes.h"
#include "common/log.h"

namespace fdfs {

namespace {

constexpr char kRecipeMagic[8] = {'F', 'D', 'F', 'S', 'R', 'C', 'P', '1'};

bool IsHex40(const std::string& s) {
  if (s.size() != 40) return false;
  for (char c : s)
    if (!((c >= '0' && c <= '9') || (c >= 'a' && c <= 'f'))) return false;
  return true;
}

}  // namespace

// -- recipe codec ---------------------------------------------------------
// Layout: 8B magic, 8B logical_size BE, 8B chunk_count BE, then per chunk
// 20B raw digest + 8B length BE.  Offsets are implicit (cumulative).

bool WriteRecipeFile(const std::string& path, const Recipe& r,
                     std::string* err) {
  std::string tmp = path + ".tmp";
  FILE* f = fopen(tmp.c_str(), "wb");
  if (f == nullptr) {
    *err = "open " + tmp + ": " + strerror(errno);
    return false;
  }
  std::string buf(kRecipeMagic, sizeof(kRecipeMagic));
  uint8_t num[8];
  PutInt64BE(r.logical_size, num);
  buf.append(reinterpret_cast<char*>(num), 8);
  PutInt64BE(static_cast<int64_t>(r.chunks.size()), num);
  buf.append(reinterpret_cast<char*>(num), 8);
  for (const RecipeEntry& e : r.chunks) {
    for (size_t i = 0; i < 40; i += 2) {
      buf.push_back(static_cast<char>(
          strtoul(e.digest_hex.substr(i, 2).c_str(), nullptr, 16)));
    }
    PutInt64BE(e.length, num);
    buf.append(reinterpret_cast<char*>(num), 8);
  }
  bool ok = fwrite(buf.data(), 1, buf.size(), f) == buf.size() &&
            fflush(f) == 0 && fsync(fileno(f)) == 0;
  fclose(f);
  if (!ok || rename(tmp.c_str(), path.c_str()) != 0) {
    *err = "write " + path + ": " + strerror(errno);
    unlink(tmp.c_str());
    return false;
  }
  return true;
}

std::optional<Recipe> ReadRecipeFile(const std::string& path) {
  FILE* f = fopen(path.c_str(), "rb");
  if (f == nullptr) return std::nullopt;
  char hdr[24];
  if (fread(hdr, 1, sizeof(hdr), f) != sizeof(hdr) ||
      memcmp(hdr, kRecipeMagic, sizeof(kRecipeMagic)) != 0) {
    fclose(f);
    return std::nullopt;
  }
  Recipe r;
  r.logical_size = GetInt64BE(reinterpret_cast<uint8_t*>(hdr) + 8);
  int64_t count = GetInt64BE(reinterpret_cast<uint8_t*>(hdr) + 16);
  if (count < 0 || count > (1 << 26)) {  // 64M chunks ~= 0.5 PB file
    fclose(f);
    return std::nullopt;
  }
  static const char* kHex = "0123456789abcdef";
  r.chunks.reserve(static_cast<size_t>(count));
  for (int64_t i = 0; i < count; ++i) {
    uint8_t rec[28];
    if (fread(rec, 1, sizeof(rec), f) != sizeof(rec)) {
      fclose(f);
      return std::nullopt;
    }
    RecipeEntry e;
    e.digest_hex.resize(40);
    for (int b = 0; b < 20; ++b) {
      e.digest_hex[2 * b] = kHex[rec[b] >> 4];
      e.digest_hex[2 * b + 1] = kHex[rec[b] & 0xF];
    }
    e.length = GetInt64BE(rec + 20);
    if (e.length < 0) {
      fclose(f);
      return std::nullopt;
    }
    r.chunks.push_back(std::move(e));
  }
  fclose(f);
  return r;
}

// -- store ----------------------------------------------------------------

ChunkStore::ChunkStore(std::string store_path)
    : store_path_(std::move(store_path)) {}

std::string ChunkStore::ChunkPath(const std::string& digest_hex) const {
  return store_path_ + "/data/chunks/" + digest_hex.substr(0, 2) + "/" +
         digest_hex.substr(2, 2) + "/" + digest_hex;
}

bool ChunkStore::PutAndRef(const std::string& digest_hex, const char* data,
                           size_t len, bool* existed, std::string* err) {
  std::string path = ChunkPath(digest_hex);
  std::lock_guard<std::mutex> lk(mu_);
  auto it = refs_.find(digest_hex);
  if (it != refs_.end()) {
    it->second++;
    *existed = true;
    return true;
  }
  auto d = deferred_.find(digest_hex);
  if (d != deferred_.end()) {
    // Zero-ref but still on disk (a pinned stream deferred the unlink):
    // resurrect instead of rewriting, cancelling the deferral — its
    // bytes were never subtracted from unique_bytes_.
    deferred_.erase(d);
    refs_[digest_hex] = 1;
    *existed = true;
    return true;
  }
  // First reference: write the payload (write-if-absent; a leftover file
  // from a crashed write is simply overwritten — content-addressed, so
  // same digest => same bytes).
  std::string dir1 = store_path_ + "/data/chunks";
  std::string dir2 = dir1 + "/" + digest_hex.substr(0, 2);
  std::string dir3 = dir2 + "/" + digest_hex.substr(2, 2);
  mkdir(dir1.c_str(), 0755);
  mkdir(dir2.c_str(), 0755);
  mkdir(dir3.c_str(), 0755);
  std::string tmp = path + ".tmp";
  int fd = open(tmp.c_str(), O_CREAT | O_WRONLY | O_TRUNC, 0644);
  if (fd < 0) {
    *err = "open " + tmp + ": " + strerror(errno);
    return false;
  }
  size_t off = 0;
  while (off < len) {
    ssize_t w = write(fd, data + off, len - off);
    if (w <= 0) {
      *err = "write " + tmp + ": " + strerror(errno);
      close(fd);
      unlink(tmp.c_str());
      return false;
    }
    off += static_cast<size_t>(w);
  }
  close(fd);
  if (rename(tmp.c_str(), path.c_str()) != 0) {
    *err = "rename " + path + ": " + strerror(errno);
    unlink(tmp.c_str());
    return false;
  }
  refs_[digest_hex] = 1;
  unique_bytes_ += static_cast<int64_t>(len);
  *existed = false;
  return true;
}

bool ChunkStore::RefAll(const Recipe& r) {
  std::lock_guard<std::mutex> lk(mu_);
  for (const RecipeEntry& e : r.chunks)
    if (refs_.find(e.digest_hex) == refs_.end()) return false;
  for (const RecipeEntry& e : r.chunks) refs_[e.digest_hex]++;
  return true;
}

bool ChunkStore::Has(const std::string& digest_hex) const {
  std::lock_guard<std::mutex> lk(mu_);
  return refs_.find(digest_hex) != refs_.end();
}

std::string ChunkStore::HaveMask(
    const std::vector<std::string>& digests) const {
  std::string need(digests.size(), '\0');
  std::lock_guard<std::mutex> lk(mu_);
  for (size_t i = 0; i < digests.size(); ++i)
    need[i] = refs_.find(digests[i]) != refs_.end() ? 0 : 1;
  return need;
}

bool ChunkStore::RefOne(const std::string& digest_hex) {
  std::lock_guard<std::mutex> lk(mu_);
  auto it = refs_.find(digest_hex);
  if (it == refs_.end()) return false;
  it->second++;
  return true;
}

void ChunkStore::UnrefAll(const Recipe& r) {
  std::lock_guard<std::mutex> lk(mu_);
  for (const RecipeEntry& e : r.chunks) {
    auto it = refs_.find(e.digest_hex);
    if (it == refs_.end()) continue;
    if (--it->second <= 0) {
      refs_.erase(it);
      if (pins_.count(e.digest_hex)) {
        // An in-flight download still streams this chunk: defer the
        // unlink to the last UnpinRecipe.
        deferred_[e.digest_hex] = e.length;
      } else {
        unlink(ChunkPath(e.digest_hex).c_str());
        unique_bytes_ -= e.length;
      }
    }
  }
}

std::optional<Recipe> ChunkStore::ReadRecipeAndPin(const std::string& path) {
  // The file read stays OUTSIDE mu_ (a cold read is milliseconds, and
  // mu_ serializes every upload RefAll / delete UnrefAll across all dio
  // threads); recipe files are immutable once renamed into place, so
  // the verify-refs_-then-pin under the lock is what closes the race
  // with a concurrent delete.
  auto r = ReadRecipeFile(path);
  if (!r.has_value()) return std::nullopt;
  std::lock_guard<std::mutex> lk(mu_);
  for (const RecipeEntry& e : r->chunks)
    if (refs_.find(e.digest_hex) == refs_.end()) return std::nullopt;
  for (const RecipeEntry& e : r->chunks) pins_[e.digest_hex]++;
  return r;
}

std::string ChunkStore::PinAndMask(const Recipe& r) {
  std::string need(r.chunks.size(), '\0');
  std::lock_guard<std::mutex> lk(mu_);
  for (size_t i = 0; i < r.chunks.size(); ++i) {
    need[i] = refs_.find(r.chunks[i].digest_hex) != refs_.end() ? 0 : 1;
    pins_[r.chunks[i].digest_hex]++;
  }
  return need;
}

void ChunkStore::PinRecipe(const Recipe& r) {
  std::lock_guard<std::mutex> lk(mu_);
  for (const RecipeEntry& e : r.chunks) pins_[e.digest_hex]++;
}

void ChunkStore::UnpinRecipe(const Recipe& r) {
  std::lock_guard<std::mutex> lk(mu_);
  for (const RecipeEntry& e : r.chunks) {
    auto it = pins_.find(e.digest_hex);
    if (it == pins_.end()) continue;
    if (--it->second <= 0) {
      pins_.erase(it);
      auto d = deferred_.find(e.digest_hex);
      if (d != deferred_.end()) {
        // ...unless the chunk was re-added while the stream ran.
        if (refs_.find(e.digest_hex) == refs_.end()) {
          unlink(ChunkPath(e.digest_hex).c_str());
          unique_bytes_ -= d->second;
        }
        deferred_.erase(d);
      }
    }
  }
}

bool ChunkStore::ReadChunk(const std::string& digest_hex, int64_t expect_len,
                           std::string* out) const {
  int fd = open(ChunkPath(digest_hex).c_str(), O_RDONLY);
  if (fd < 0) return false;
  out->resize(static_cast<size_t>(expect_len));
  size_t off = 0;
  while (off < out->size()) {
    ssize_t r = read(fd, out->data() + off, out->size() - off);
    if (r <= 0) {
      close(fd);
      return false;
    }
    off += static_cast<size_t>(r);
  }
  close(fd);
  return true;
}

int64_t ChunkStore::unique_chunks() const {
  std::lock_guard<std::mutex> lk(mu_);
  return static_cast<int64_t>(refs_.size());
}

int64_t ChunkStore::unique_bytes() const {
  std::lock_guard<std::mutex> lk(mu_);
  return unique_bytes_;
}

namespace {

void WalkRecipes(const std::string& dir,
                 std::unordered_map<std::string, int64_t>* refs,
                 std::unordered_map<std::string, int64_t>* lens) {
  DIR* d = opendir(dir.c_str());
  if (d == nullptr) return;
  struct dirent* de;
  while ((de = readdir(d)) != nullptr) {
    std::string name = de->d_name;
    if (name == "." || name == "..") continue;
    std::string path = dir + "/" + name;
    struct stat st;
    if (stat(path.c_str(), &st) != 0) continue;
    if (S_ISDIR(st.st_mode)) {
      if (name != "chunks" && name != "sync" && name != "tmp")
        WalkRecipes(path, refs, lens);
    } else if (name.size() > 4 &&
               name.compare(name.size() - 4, 4, ".rcp") == 0) {
      auto r = ReadRecipeFile(path);
      if (!r.has_value()) {
        FDFS_LOG_WARN("unreadable recipe %s ignored", path.c_str());
        continue;
      }
      for (const RecipeEntry& e : r->chunks) {
        (*refs)[e.digest_hex]++;
        (*lens)[e.digest_hex] = e.length;
      }
    }
  }
  closedir(d);
}

}  // namespace

void ChunkStore::RebuildFromRecipes() {
  std::unordered_map<std::string, int64_t> refs, lens;
  WalkRecipes(store_path_ + "/data", &refs, &lens);

  // GC pass: any chunk file not named by a recipe is an orphan from a
  // crash between chunk write and recipe write (or after a delete that
  // crashed mid-unref) — safe to drop.
  int64_t orphans = 0, bytes = 0;
  std::string croot = store_path_ + "/data/chunks";
  DIR* d1 = opendir(croot.c_str());
  if (d1 != nullptr) {
    struct dirent* e1;
    while ((e1 = readdir(d1)) != nullptr) {
      if (e1->d_name[0] == '.') continue;
      std::string l1 = croot + "/" + e1->d_name;
      DIR* d2 = opendir(l1.c_str());
      if (d2 == nullptr) continue;
      struct dirent* e2;
      while ((e2 = readdir(d2)) != nullptr) {
        if (e2->d_name[0] == '.') continue;
        std::string l2 = l1 + "/" + e2->d_name;
        DIR* d3 = opendir(l2.c_str());
        if (d3 == nullptr) continue;
        struct dirent* e3;
        while ((e3 = readdir(d3)) != nullptr) {
          std::string name = e3->d_name;
          if (name[0] == '.') continue;
          if (!IsHex40(name) || refs.find(name) == refs.end()) {
            unlink((l2 + "/" + name).c_str());
            ++orphans;
          }
        }
        closedir(d3);
      }
      closedir(d2);
    }
    closedir(d1);
  }

  std::lock_guard<std::mutex> lk(mu_);
  refs_ = std::move(refs);
  unique_bytes_ = 0;
  for (const auto& [dig, n] : refs_) unique_bytes_ += lens[dig];
  bytes = unique_bytes_;
  if (!refs_.empty() || orphans > 0)
    FDFS_LOG_INFO("chunk store: %zu unique chunks (%lld bytes), %lld "
                  "orphans collected",
                  refs_.size(), static_cast<long long>(bytes),
                  static_cast<long long>(orphans));
}

}  // namespace fdfs
