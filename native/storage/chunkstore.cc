#include "storage/chunkstore.h"

#include <dirent.h>
#include <fcntl.h>
#include <string.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cerrno>
#include <cstdio>
#include <ctime>

#include "common/bytes.h"
#include "common/log.h"

namespace fdfs {

namespace {

constexpr char kRecipeMagic[8] = {'F', 'D', 'F', 'S', 'R', 'C', 'P', '1'};

bool IsHex40(const std::string& s) {
  if (s.size() != 40) return false;
  for (char c : s)
    if (!((c >= '0' && c <= '9') || (c >= 'a' && c <= 'f'))) return false;
  return true;
}

}  // namespace

// -- recipe codec ---------------------------------------------------------
// Layout: 8B magic, 8B logical_size BE, 8B chunk_count BE, then per chunk
// 20B raw digest + 8B length BE.  Offsets are implicit (cumulative).

bool WriteRecipeFile(const std::string& path, const Recipe& r,
                     std::string* err) {
  std::string tmp = path + ".tmp";
  FILE* f = fopen(tmp.c_str(), "wb");
  if (f == nullptr) {
    *err = "open " + tmp + ": " + strerror(errno);
    return false;
  }
  std::string buf(kRecipeMagic, sizeof(kRecipeMagic));
  uint8_t num[8];
  PutInt64BE(r.logical_size, num);
  buf.append(reinterpret_cast<char*>(num), 8);
  PutInt64BE(static_cast<int64_t>(r.chunks.size()), num);
  buf.append(reinterpret_cast<char*>(num), 8);
  for (const RecipeEntry& e : r.chunks) {
    for (size_t i = 0; i < 40; i += 2) {
      buf.push_back(static_cast<char>(
          strtoul(e.digest_hex.substr(i, 2).c_str(), nullptr, 16)));
    }
    PutInt64BE(e.length, num);
    buf.append(reinterpret_cast<char*>(num), 8);
  }
  bool ok = fwrite(buf.data(), 1, buf.size(), f) == buf.size() &&
            fflush(f) == 0 && fsync(fileno(f)) == 0;
  fclose(f);
  if (!ok || rename(tmp.c_str(), path.c_str()) != 0) {
    *err = "write " + path + ": " + strerror(errno);
    unlink(tmp.c_str());
    return false;
  }
  return true;
}

std::optional<Recipe> ReadRecipeFile(const std::string& path) {
  FILE* f = fopen(path.c_str(), "rb");
  if (f == nullptr) return std::nullopt;
  char hdr[24];
  if (fread(hdr, 1, sizeof(hdr), f) != sizeof(hdr) ||
      memcmp(hdr, kRecipeMagic, sizeof(kRecipeMagic)) != 0) {
    fclose(f);
    return std::nullopt;
  }
  Recipe r;
  r.logical_size = GetInt64BE(reinterpret_cast<uint8_t*>(hdr) + 8);
  int64_t count = GetInt64BE(reinterpret_cast<uint8_t*>(hdr) + 16);
  if (count < 0 || count > (1 << 26)) {  // 64M chunks ~= 0.5 PB file
    fclose(f);
    return std::nullopt;
  }
  static const char* kHex = "0123456789abcdef";
  r.chunks.reserve(static_cast<size_t>(count));
  for (int64_t i = 0; i < count; ++i) {
    uint8_t rec[28];
    if (fread(rec, 1, sizeof(rec), f) != sizeof(rec)) {
      fclose(f);
      return std::nullopt;
    }
    RecipeEntry e;
    e.digest_hex.resize(40);
    for (int b = 0; b < 20; ++b) {
      e.digest_hex[2 * b] = kHex[rec[b] >> 4];
      e.digest_hex[2 * b + 1] = kHex[rec[b] & 0xF];
    }
    e.length = GetInt64BE(rec + 20);
    if (e.length < 0) {
      fclose(f);
      return std::nullopt;
    }
    r.chunks.push_back(std::move(e));
  }
  fclose(f);
  return r;
}

// -- store ----------------------------------------------------------------

ChunkStore::ChunkStore(std::string store_path, int64_t gc_grace_s)
    : store_path_(std::move(store_path)),
      gc_grace_s_(gc_grace_s < 0 ? 0 : gc_grace_s) {}

std::string ChunkStore::ChunkPath(const std::string& digest_hex) const {
  return store_path_ + "/data/chunks/" + digest_hex.substr(0, 2) + "/" +
         digest_hex.substr(2, 2) + "/" + digest_hex;
}

std::string ChunkStore::QuarantinePath(const std::string& digest_hex) const {
  return store_path_ + "/data/quarantine/" + digest_hex;
}

namespace {

// Write-if-absent payload write (tmp + rename; a leftover file from a
// crashed write is simply overwritten — content-addressed, so same
// digest => same bytes).
bool WriteChunkFile(const std::string& path, const char* data, size_t len,
                    std::string* err) {
  std::string tmp = path + ".tmp";
  int fd = open(tmp.c_str(), O_CREAT | O_WRONLY | O_TRUNC, 0644);
  if (fd < 0) {
    *err = "open " + tmp + ": " + strerror(errno);
    return false;
  }
  size_t off = 0;
  while (off < len) {
    ssize_t w = write(fd, data + off, len - off);
    if (w <= 0) {
      *err = "write " + tmp + ": " + strerror(errno);
      close(fd);
      unlink(tmp.c_str());
      return false;
    }
    off += static_cast<size_t>(w);
  }
  close(fd);
  if (rename(tmp.c_str(), path.c_str()) != 0) {
    *err = "rename " + path + ": " + strerror(errno);
    unlink(tmp.c_str());
    return false;
  }
  return true;
}

}  // namespace

bool ChunkStore::PutAndRef(const std::string& digest_hex, const char* data,
                           size_t len, bool* existed, std::string* err) {
  std::string path = ChunkPath(digest_hex);
  std::lock_guard<std::mutex> lk(mu_);
  // Heal-on-upload: these bytes hash to the digest (every caller
  // verifies before PutAndRef), so a quarantined chunk gets its good
  // payload restored by ANY upload/replication that carries it.
  // Best-effort — a failed restore leaves the chunk quarantined
  // (downloads keep failing loudly) but never fails the upload, which
  // historically never wrote in the already-present case.
  auto heal = [&]() {
    if (!quarantined_.count(digest_hex)) return;
    std::string werr;
    if (WriteChunkFile(path, data, len, &werr)) {
      quarantined_.erase(digest_hex);
      unlink(QuarantinePath(digest_hex).c_str());
      FDFS_LOG_INFO("chunk %s healed by incoming payload",
                    digest_hex.c_str());
    } else {
      FDFS_LOG_WARN("quarantined chunk %s heal failed: %s",
                    digest_hex.c_str(), werr.c_str());
    }
  };
  auto it = refs_.find(digest_hex);
  if (it != refs_.end()) {
    heal();
    it->second++;
    *existed = true;
    return true;
  }
  auto z = zero_ref_.find(digest_hex);
  if (z != zero_ref_.end()) {
    // Zero-ref but still on disk (GC grace window, or a pinned stream
    // deferring the unlink): resurrect instead of rewriting.
    heal();
    refs_[digest_hex] = 1;
    lens_[digest_hex] = z->second.length;
    unique_bytes_ += z->second.length;
    zero_ref_bytes_ -= z->second.length;
    zero_ref_.erase(z);
    *existed = true;
    return true;
  }
  // First reference: write the payload.
  std::string dir1 = store_path_ + "/data/chunks";
  std::string dir2 = dir1 + "/" + digest_hex.substr(0, 2);
  std::string dir3 = dir2 + "/" + digest_hex.substr(2, 2);
  mkdir(dir1.c_str(), 0755);
  mkdir(dir2.c_str(), 0755);
  mkdir(dir3.c_str(), 0755);
  if (!WriteChunkFile(path, data, len, err)) return false;
  refs_[digest_hex] = 1;
  lens_[digest_hex] = static_cast<int64_t>(len);
  unique_bytes_ += static_cast<int64_t>(len);
  *existed = false;
  return true;
}

bool ChunkStore::RefAll(const Recipe& r) {
  std::lock_guard<std::mutex> lk(mu_);
  for (const RecipeEntry& e : r.chunks)
    if (refs_.find(e.digest_hex) == refs_.end()) return false;
  for (const RecipeEntry& e : r.chunks) refs_[e.digest_hex]++;
  return true;
}

bool ChunkStore::Has(const std::string& digest_hex) const {
  std::lock_guard<std::mutex> lk(mu_);
  return refs_.find(digest_hex) != refs_.end();
}

std::string ChunkStore::HaveMask(
    const std::vector<std::string>& digests) const {
  std::string need(digests.size(), '\0');
  std::lock_guard<std::mutex> lk(mu_);
  for (size_t i = 0; i < digests.size(); ++i)
    need[i] = refs_.find(digests[i]) != refs_.end() &&
                      !quarantined_.count(digests[i])
                  ? 0 : 1;
  return need;
}

bool ChunkStore::RefOne(const std::string& digest_hex) {
  std::lock_guard<std::mutex> lk(mu_);
  auto it = refs_.find(digest_hex);
  if (it == refs_.end()) return false;
  it->second++;
  return true;
}

void ChunkStore::RetireLocked(const std::string& digest_hex,
                              int64_t length) {
  // mu_ held; refs_ entry already erased.  Eager mode (no GC grace)
  // keeps the original semantics: unlink now unless an in-flight stream
  // pins the chunk, in which case the zero_ref_ entry defers the unlink
  // to the last UnpinRecipe.  With a grace window every zero-ref chunk
  // parks for the scrubber's GcSweep.
  unique_bytes_ -= length;
  if (gc_grace_s_ == 0 && !pins_.count(digest_hex)) {
    UnlinkRetiredLocked(digest_hex);
    return;
  }
  zero_ref_[digest_hex] = ZeroRef{length, time(nullptr)};
  zero_ref_bytes_ += length;
}

void ChunkStore::UnlinkRetiredLocked(const std::string& digest_hex) {
  unlink(ChunkPath(digest_hex).c_str());
  unlink(QuarantinePath(digest_hex).c_str());
  quarantined_.erase(digest_hex);
  lens_.erase(digest_hex);
}

void ChunkStore::UnrefAll(const Recipe& r) {
  std::lock_guard<std::mutex> lk(mu_);
  for (const RecipeEntry& e : r.chunks) {
    auto it = refs_.find(e.digest_hex);
    if (it == refs_.end()) continue;
    if (--it->second <= 0) {
      refs_.erase(it);
      RetireLocked(e.digest_hex, e.length);
    }
  }
}

std::optional<Recipe> ChunkStore::ReadRecipeAndPin(const std::string& path) {
  // The file read stays OUTSIDE mu_ (a cold read is milliseconds, and
  // mu_ serializes every upload RefAll / delete UnrefAll across all dio
  // threads); recipe files are immutable once renamed into place, so
  // the verify-refs_-then-pin under the lock is what closes the race
  // with a concurrent delete.
  auto r = ReadRecipeFile(path);
  if (!r.has_value()) return std::nullopt;
  std::lock_guard<std::mutex> lk(mu_);
  for (const RecipeEntry& e : r->chunks)
    if (refs_.find(e.digest_hex) == refs_.end()) return std::nullopt;
  for (const RecipeEntry& e : r->chunks) pins_[e.digest_hex]++;
  return r;
}

std::string ChunkStore::PinAndMask(const Recipe& r) {
  std::string need(r.chunks.size(), '\0');
  std::lock_guard<std::mutex> lk(mu_);
  for (size_t i = 0; i < r.chunks.size(); ++i) {
    // Quarantined chunks read as "needed": the client re-ships the
    // bytes and PutAndRef heals the store.  The pin taken here also
    // exempts the chunk from GcSweep and Quarantine for the session's
    // lifetime — probe and pin share this one lock acquisition.
    need[i] = refs_.find(r.chunks[i].digest_hex) != refs_.end() &&
                      !quarantined_.count(r.chunks[i].digest_hex)
                  ? 0 : 1;
    pins_[r.chunks[i].digest_hex]++;
  }
  return need;
}

void ChunkStore::PinRecipe(const Recipe& r) {
  std::lock_guard<std::mutex> lk(mu_);
  for (const RecipeEntry& e : r.chunks) pins_[e.digest_hex]++;
}

void ChunkStore::UnpinRecipe(const Recipe& r) {
  std::lock_guard<std::mutex> lk(mu_);
  for (const RecipeEntry& e : r.chunks) {
    auto it = pins_.find(e.digest_hex);
    if (it == pins_.end()) continue;
    if (--it->second <= 0) {
      pins_.erase(it);
      // Eager mode: the last pin drop completes a delete that was
      // deferred mid-stream — unless the chunk was re-added while the
      // stream ran (PutAndRef resurrection erased the zero_ref_ entry).
      // With a GC grace the entry simply waits for GcSweep.
      auto z = zero_ref_.find(e.digest_hex);
      if (z != zero_ref_.end() && gc_grace_s_ == 0 &&
          refs_.find(e.digest_hex) == refs_.end()) {
        zero_ref_bytes_ -= z->second.length;
        zero_ref_.erase(z);
        UnlinkRetiredLocked(e.digest_hex);
      }
    }
  }
}

bool ChunkStore::ReadChunk(const std::string& digest_hex, int64_t expect_len,
                           std::string* out) const {
  int fd = open(ChunkPath(digest_hex).c_str(), O_RDONLY);
  if (fd < 0) return false;
  out->resize(static_cast<size_t>(expect_len));
  size_t off = 0;
  while (off < out->size()) {
    ssize_t r = read(fd, out->data() + off, out->size() - off);
    if (r <= 0) {
      close(fd);
      return false;
    }
    off += static_cast<size_t>(r);
  }
  close(fd);
  return true;
}

int64_t ChunkStore::unique_chunks() const {
  std::lock_guard<std::mutex> lk(mu_);
  return static_cast<int64_t>(refs_.size());
}

int64_t ChunkStore::unique_bytes() const {
  std::lock_guard<std::mutex> lk(mu_);
  return unique_bytes_;
}

int64_t ChunkStore::gc_pending_chunks() const {
  std::lock_guard<std::mutex> lk(mu_);
  return static_cast<int64_t>(zero_ref_.size());
}

int64_t ChunkStore::gc_pending_bytes() const {
  std::lock_guard<std::mutex> lk(mu_);
  return zero_ref_bytes_;
}

int64_t ChunkStore::quarantined_chunks() const {
  std::lock_guard<std::mutex> lk(mu_);
  return static_cast<int64_t>(quarantined_.size());
}

// -- integrity engine -----------------------------------------------------

std::vector<ChunkStore::ChunkInfo> ChunkStore::SnapshotLive(
    int prefix) const {
  static const char* kHex = "0123456789abcdef";
  char p0 = 0, p1 = 0;
  if (prefix >= 0) {
    p0 = kHex[(prefix >> 4) & 0xF];
    p1 = kHex[prefix & 0xF];
  }
  std::vector<ChunkInfo> out;
  std::lock_guard<std::mutex> lk(mu_);
  if (prefix < 0) out.reserve(refs_.size());
  for (const auto& [dig, n] : refs_) {
    if (prefix >= 0 && (dig[0] != p0 || dig[1] != p1)) continue;
    if (quarantined_.count(dig)) continue;
    auto l = lens_.find(dig);
    out.push_back({dig, l != lens_.end() ? l->second : 0});
  }
  return out;
}

std::vector<ChunkStore::ChunkInfo> ChunkStore::SnapshotQuarantined() const {
  std::vector<ChunkInfo> out;
  std::lock_guard<std::mutex> lk(mu_);
  for (const std::string& dig : quarantined_) {
    if (refs_.find(dig) == refs_.end()) continue;  // zero-ref: GC's problem
    auto l = lens_.find(dig);
    out.push_back({dig, l != lens_.end() ? l->second : 0});
  }
  return out;
}

bool ChunkStore::IsQuarantined(const std::string& digest_hex) const {
  std::lock_guard<std::mutex> lk(mu_);
  return quarantined_.count(digest_hex) != 0;
}

ChunkStore::QuarantineResult ChunkStore::Quarantine(
    const std::string& digest_hex) {
  std::lock_guard<std::mutex> lk(mu_);
  if (refs_.find(digest_hex) == refs_.end())
    return QuarantineResult::kGone;  // deleted since the snapshot
  if (pins_.count(digest_hex)) return QuarantineResult::kPinned;
  // Re-verify under the lock: the scrubber's verify read ran lock-free,
  // so it may have raced a delete + re-upload of this digest and hashed
  // a half-gone file.  No writer can interleave with this read, so a
  // clean hash here is authoritative.
  {
    int fd = open(ChunkPath(digest_hex).c_str(), O_RDONLY);
    if (fd >= 0) {
      Sha1Stream sha;
      char buf[65536];
      ssize_t r;
      while ((r = read(fd, buf, sizeof(buf))) > 0)
        sha.Update(buf, static_cast<size_t>(r));
      close(fd);
      if (r == 0 && sha.Final().Hex() == digest_hex)
        return QuarantineResult::kClean;
    }
  }
  mkdir((store_path_ + "/data/quarantine").c_str(), 0755);
  // A rename failure (e.g. the file already vanished) still marks the
  // chunk quarantined: either way the bytes are not servable, and the
  // mark is what routes re-uploads/repairs to the heal path.
  if (rename(ChunkPath(digest_hex).c_str(),
             QuarantinePath(digest_hex).c_str()) != 0 &&
      errno != ENOENT)
    FDFS_LOG_WARN("quarantine rename %s: %s", digest_hex.c_str(),
                  strerror(errno));
  quarantined_.insert(digest_hex);
  return QuarantineResult::kQuarantined;
}

bool ChunkStore::RepairChunk(const std::string& digest_hex, const char* data,
                             size_t len, std::string* err) {
  std::lock_guard<std::mutex> lk(mu_);
  if (refs_.find(digest_hex) == refs_.end()) {
    *err = "no longer referenced";
    return false;
  }
  if (!WriteChunkFile(ChunkPath(digest_hex), data, len, err)) return false;
  quarantined_.erase(digest_hex);
  unlink(QuarantinePath(digest_hex).c_str());
  lens_[digest_hex] = static_cast<int64_t>(len);
  return true;
}

int64_t ChunkStore::GcSweep(int64_t now_s, int64_t* bytes) {
  std::lock_guard<std::mutex> lk(mu_);
  int64_t reclaimed = 0;
  for (auto it = zero_ref_.begin(); it != zero_ref_.end();) {
    if (now_s - it->second.since_s < gc_grace_s_ ||
        pins_.count(it->first)) {
      // Inside the grace window, or pinned by an in-flight stream /
      // phase-1 upload session — the pin probe shares this lock with
      // the unlink, so PinAndMask can never lose the race.
      ++it;
      continue;
    }
    UnlinkRetiredLocked(it->first);
    zero_ref_bytes_ -= it->second.length;
    *bytes += it->second.length;
    ++reclaimed;
    it = zero_ref_.erase(it);
  }
  return reclaimed;
}

namespace {

void WalkRecipes(const std::string& dir,
                 std::unordered_map<std::string, int64_t>* refs,
                 std::unordered_map<std::string, int64_t>* lens) {
  DIR* d = opendir(dir.c_str());
  if (d == nullptr) return;
  struct dirent* de;
  while ((de = readdir(d)) != nullptr) {
    std::string name = de->d_name;
    if (name == "." || name == "..") continue;
    std::string path = dir + "/" + name;
    struct stat st;
    if (stat(path.c_str(), &st) != 0) continue;
    if (S_ISDIR(st.st_mode)) {
      if (name != "chunks" && name != "sync" && name != "tmp")
        WalkRecipes(path, refs, lens);
    } else if (name.size() > 4 &&
               name.compare(name.size() - 4, 4, ".rcp") == 0) {
      auto r = ReadRecipeFile(path);
      if (!r.has_value()) {
        FDFS_LOG_WARN("unreadable recipe %s ignored", path.c_str());
        continue;
      }
      for (const RecipeEntry& e : r->chunks) {
        (*refs)[e.digest_hex]++;
        (*lens)[e.digest_hex] = e.length;
      }
    }
  }
  closedir(d);
}

}  // namespace

void ChunkStore::RebuildFromRecipes() {
  std::unordered_map<std::string, int64_t> refs, lens;
  WalkRecipes(store_path_ + "/data", &refs, &lens);

  // GC pass: any chunk file not named by a recipe is an orphan — a
  // crash leftover, or (with a GC grace window) a deliberately-retired
  // zero-ref chunk whose grace had not expired at shutdown.  Eager mode
  // drops orphans on the spot (the original behavior); grace mode
  // parks them in zero_ref_ aged by file mtime, so the grace window is
  // crash-safe instead of resetting on every restart.
  int64_t orphans = 0, parked = 0, bytes = 0;
  std::unordered_map<std::string, ZeroRef> zero;
  std::string croot = store_path_ + "/data/chunks";
  DIR* d1 = opendir(croot.c_str());
  if (d1 != nullptr) {
    struct dirent* e1;
    while ((e1 = readdir(d1)) != nullptr) {
      if (e1->d_name[0] == '.') continue;
      std::string l1 = croot + "/" + e1->d_name;
      DIR* d2 = opendir(l1.c_str());
      if (d2 == nullptr) continue;
      struct dirent* e2;
      while ((e2 = readdir(d2)) != nullptr) {
        if (e2->d_name[0] == '.') continue;
        std::string l2 = l1 + "/" + e2->d_name;
        DIR* d3 = opendir(l2.c_str());
        if (d3 == nullptr) continue;
        struct dirent* e3;
        while ((e3 = readdir(d3)) != nullptr) {
          std::string name = e3->d_name;
          if (name[0] == '.') continue;
          if (IsHex40(name) && refs.find(name) != refs.end()) continue;
          std::string path = l2 + "/" + name;
          struct stat st;
          if (IsHex40(name) && gc_grace_s_ > 0 &&
              stat(path.c_str(), &st) == 0) {
            zero[name] = ZeroRef{static_cast<int64_t>(st.st_size),
                                 static_cast<int64_t>(st.st_mtime)};
            lens[name] = static_cast<int64_t>(st.st_size);
            ++parked;
          } else {
            unlink(path.c_str());
            ++orphans;
          }
        }
        closedir(d3);
      }
      closedir(d2);
    }
    closedir(d1);
  }

  // Quarantine survives restarts: a referenced digest whose bytes sit in
  // quarantine/ must keep reading as missing (and healable), or a
  // restart would silently re-admit the corrupt state.  Unreferenced
  // quarantine files are corrupt garbage nobody names — drop them.
  std::unordered_set<std::string> quarantined;
  std::string qroot = store_path_ + "/data/quarantine";
  DIR* qd = opendir(qroot.c_str());
  if (qd != nullptr) {
    struct dirent* qe;
    while ((qe = readdir(qd)) != nullptr) {
      std::string name = qe->d_name;
      if (name[0] == '.') continue;
      if (IsHex40(name) && refs.find(name) != refs.end()) {
        struct stat st;
        if (stat(ChunkPath(name).c_str(), &st) == 0) {
          // A healed copy already lives in chunks/ (crash between the
          // repair write and the quarantine unlink): prefer it.
          unlink((qroot + "/" + name).c_str());
        } else {
          quarantined.insert(name);
        }
      } else {
        unlink((qroot + "/" + name).c_str());
      }
    }
    closedir(qd);
  }

  std::lock_guard<std::mutex> lk(mu_);
  refs_ = std::move(refs);
  lens_ = std::move(lens);
  zero_ref_ = std::move(zero);
  quarantined_ = std::move(quarantined);
  unique_bytes_ = 0;
  zero_ref_bytes_ = 0;
  for (const auto& [dig, n] : refs_) unique_bytes_ += lens_[dig];
  for (const auto& [dig, z] : zero_ref_) zero_ref_bytes_ += z.length;
  bytes = unique_bytes_;
  if (!refs_.empty() || orphans > 0 || parked > 0 || !quarantined_.empty())
    FDFS_LOG_INFO("chunk store: %zu unique chunks (%lld bytes), %lld "
                  "orphans collected, %lld awaiting GC, %zu quarantined",
                  refs_.size(), static_cast<long long>(bytes),
                  static_cast<long long>(orphans),
                  static_cast<long long>(parked), quarantined_.size());
}

}  // namespace fdfs
