#include "storage/binlog.h"

#include <errno.h>
#include <fcntl.h>
#include <string.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

#include <cstdio>
#include <cstdlib>

#include "common/log.h"
#include "storage/store.h"

namespace fdfs {

namespace {
constexpr char kExtraSep = '\x02';
}

std::string FormatBinlogRecord(const BinlogRecord& rec) {
  std::string line = std::to_string(rec.timestamp);
  line += ' ';
  line += rec.op;
  line += ' ';
  line += rec.filename;
  if (!rec.extra.empty()) {
    line += kExtraSep;
    line += rec.extra;
  }
  line += '\n';
  return line;
}

std::optional<BinlogRecord> ParseBinlogRecord(const std::string& line) {
  size_t s1 = line.find(' ');
  if (s1 == std::string::npos || s1 == 0) return std::nullopt;
  if (s1 + 2 >= line.size() || line[s1 + 2] != ' ') return std::nullopt;
  BinlogRecord rec;
  char* end = nullptr;
  rec.timestamp = std::strtoll(line.c_str(), &end, 10);
  if (end != line.c_str() + s1) return std::nullopt;
  rec.op = line[s1 + 1];
  std::string rest = line.substr(s1 + 3);
  while (!rest.empty() && (rest.back() == '\n' || rest.back() == '\r'))
    rest.pop_back();
  if (rest.empty()) return std::nullopt;
  size_t sep = rest.find(kExtraSep);
  if (sep != std::string::npos) {
    rec.filename = rest.substr(0, sep);
    rec.extra = rest.substr(sep + 1);
  } else {
    rec.filename = rest;
  }
  return rec;
}

// -- writer ---------------------------------------------------------------

std::string BinlogWriter::FilePath(int file_index) const {
  char name[32];
  std::snprintf(name, sizeof(name), "/binlog.%03d", file_index);
  return dir_ + name;
}

bool BinlogWriter::Init(const std::string& base_dir, int64_t rotate_size,
                        std::string* error) {
  dir_ = base_dir;
  rotate_size_ = rotate_size;
  if (!MakeDirs(dir_)) {
    *error = "mkdir " + dir_ + " failed";
    return false;
  }
  // Resume at the highest existing binlog file.
  file_index_ = 0;
  for (int i = 0; i < 1000; ++i) {
    struct stat st;
    if (stat(FilePath(i).c_str(), &st) == 0) {
      file_index_ = i;
    } else {
      break;
    }
  }
  return OpenCurrent(error);
}

bool BinlogWriter::OpenCurrent(std::string* error) {
  if (fd_ >= 0) close(fd_);
  fd_ = open(FilePath(file_index_).c_str(), O_CREAT | O_WRONLY | O_APPEND, 0644);
  if (fd_ < 0) {
    *error = "open " + FilePath(file_index_) + ": " + strerror(errno);
    return false;
  }
  struct stat st;
  fstat(fd_, &st);
  offset_ = st.st_size;
  return true;
}

bool BinlogWriter::Append(char op, const std::string& filename,
                          const std::string& extra) {
  // Appends arrive from every nio work thread and the dio pools
  // (reference: the binlog write lock in storage/storage_sync.c).
  std::lock_guard<RankedMutex> lk(mu_);
  if (fd_ < 0) return false;
  // in_flight_ MUST cover the stamp→write window; see Quiescent().
  struct InFlight {
    std::atomic<int>* n;
    explicit InFlight(std::atomic<int>* p) : n(p) { n->fetch_add(1); }
    ~InFlight() { n->fetch_sub(1); }
  } guard(&in_flight_);
  BinlogRecord rec;
  rec.timestamp = static_cast<int64_t>(time(nullptr));
  rec.op = op;
  rec.filename = filename;
  rec.extra = extra;
  std::string line = FormatBinlogRecord(rec);
  ssize_t n = write(fd_, line.data(), line.size());
  if (n != static_cast<ssize_t>(line.size())) {
    FDFS_LOG_ERROR("binlog write failed: %s", strerror(errno));
    return false;
  }
  offset_ += n;
  if (rotate_size_ > 0 && offset_ >= rotate_size_) {
    ++file_index_;
    std::string err;
    if (!OpenCurrent(&err)) {
      FDFS_LOG_ERROR("binlog rotate failed: %s", err.c_str());
      return false;
    }
  }
  return true;
}

void BinlogWriter::Position(int* file_index, int64_t* offset) const {
  std::lock_guard<RankedMutex> lk(mu_);
  *file_index = file_index_;
  *offset = offset_;
}

void BinlogWriter::Flush() {
  std::lock_guard<RankedMutex> lk(mu_);
  if (fd_ >= 0) fdatasync(fd_);
}

void BinlogWriter::Close() {
  std::lock_guard<RankedMutex> lk(mu_);
  if (fd_ >= 0) {
    close(fd_);
    fd_ = -1;
  }
}

// -- reader ---------------------------------------------------------------

bool BinlogReader::Init(const std::string& dir, const std::string& mark_path,
                        std::string* error) {
  dir_ = dir;
  mark_path_ = mark_path;
  file_index_ = 0;
  offset_ = 0;
  records_read_ = 0;
  // Mark format (reference .mark files): "file_index offset records\n".
  FILE* f = fopen(mark_path.c_str(), "r");
  if (f != nullptr) {
    long long off = 0, recs = 0;
    if (fscanf(f, "%d %lld %lld", &file_index_, &off, &recs) == 3) {
      offset_ = off;
      records_read_ = recs;
    }
    fclose(f);
  }
  (void)error;
  return true;
}

bool BinlogReader::FillBuf() {
  if (fd_ < 0) {
    char name[32];
    std::snprintf(name, sizeof(name), "/binlog.%03d", file_index_);
    fd_ = open((dir_ + name).c_str(), O_RDONLY);
    if (fd_ < 0) return false;
    lseek(fd_, offset_, SEEK_SET);
  }
  char tmp[65536];
  ssize_t n = read(fd_, tmp, sizeof(tmp));
  if (n <= 0) {
    // Possibly rotated: if the next file exists and we are at EOF of the
    // current, advance.
    char next_name[32];
    std::snprintf(next_name, sizeof(next_name), "/binlog.%03d", file_index_ + 1);
    struct stat st;
    if (stat((dir_ + next_name).c_str(), &st) == 0) {
      // Only advance when the current file has no unread bytes.
      struct stat cur;
      if (fstat(fd_, &cur) == 0 && offset_ >= cur.st_size) {
        close(fd_);
        fd_ = -1;
        ++file_index_;
        offset_ = 0;
        return FillBuf();
      }
    }
    return false;
  }
  buf_.append(tmp, static_cast<size_t>(n));
  return true;
}

std::optional<BinlogRecord> BinlogReader::Next() {
  for (;;) {
    size_t nl = buf_.find('\n', buf_pos_);
    if (nl == std::string::npos) {
      buf_.erase(0, buf_pos_);
      buf_pos_ = 0;
      if (!FillBuf()) return std::nullopt;
      continue;
    }
    std::string line = buf_.substr(buf_pos_, nl - buf_pos_ + 1);
    buf_pos_ = nl + 1;
    offset_ += static_cast<int64_t>(line.size());
    auto rec = ParseBinlogRecord(line);
    if (rec.has_value()) {
      ++records_read_;
      return rec;
    }
    FDFS_LOG_WARN("skipping malformed binlog line: %s", line.c_str());
  }
}

bool BinlogReader::SaveMark() {
  std::string tmp = mark_path_ + ".tmp";
  FILE* f = fopen(tmp.c_str(), "w");
  if (f == nullptr) return false;
  fprintf(f, "%d %lld %lld\n", file_index_, static_cast<long long>(offset_),
          static_cast<long long>(records_read_));
  fclose(f);
  return rename(tmp.c_str(), mark_path_.c_str()) == 0;
}

}  // namespace fdfs

namespace fdfs {

std::string CollectOnePathBinlog(const std::string& sync_dir, int spi,
                                 int64_t offset, int64_t max_bytes) {
  char want[8];
  std::snprintf(want, sizeof(want), "M%02X/", spi);
  std::string out;
  int64_t filtered_pos = 0;  // byte position within the filtered stream
  for (int idx = 0; static_cast<int64_t>(out.size()) < max_bytes; ++idx) {
    char name[32];
    std::snprintf(name, sizeof(name), "/binlog.%03d", idx);
    FILE* f = fopen((sync_dir + name).c_str(), "r");
    if (f == nullptr) break;
    char line[4096];
    while (fgets(line, sizeof(line), f) != nullptr) {
      auto rec = ParseBinlogRecord(line);
      if (!rec.has_value()) continue;
      if (rec->filename.rfind(want, 0) != 0) continue;
      int64_t len = static_cast<int64_t>(strlen(line));
      if (filtered_pos >= offset) out.append(line, len);
      filtered_pos += len;
      if (static_cast<int64_t>(out.size()) >= max_bytes) break;
    }
    fclose(f);
  }
  return out;
}

}  // namespace fdfs
