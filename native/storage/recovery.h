// Disk recovery: rebuild a wiped/replaced store path from a group peer.
//
// Reference: storage/storage_disk_recovery.c —
// storage_disk_recovery_start() fetches the one-path binlog from a peer
// (STORAGE_PROTO_CMD_FETCH_ONE_PATH_BINLOG) and re-downloads every file it
// lists; the recovering server is held out of read routing (status
// RECOVERY upstream; WAIT_SYNC/SYNCING here via the tracker's re-enter-
// sync handshake) until it declares done.
//
// Honest divergences: upstream restores CREATE_LINK files as links; the
// rebuild re-downloads the content (a full copy — correct bytes, more
// space).  Metadata sidecars are restored via GET_METADATA from the peer.
// Beyond upstream: recipe-stored files rebuild CHUNK-AWARE (FETCH_RECIPE
// + FETCH_CHUNK pull only the chunk bytes the local store lacks), so a
// dup-heavy path costs ~unique bytes of wire instead of every logical
// byte; any failure falls back per-file to the full download.
#pragma once

#include <atomic>
#include <cstdint>
#include <functional>
#include <string>
#include <thread>
#include <vector>

#include "common/trace.h"
#include "storage/chunkstore.h"
#include "storage/config.h"
#include "storage/store.h"
#include "storage/tracker_client.h"

namespace fdfs {

class RecoveryManager {
 public:
  RecoveryManager(const StorageConfig& cfg, TrackerReporter* reporter,
                  StoreManager* store);
  ~RecoveryManager();

  // Whether recovery is needed: a store path was freshly (re-)initialized
  // although this server had previously joined a group (sync marks
  // exist), or a prior recovery never finished (.recovery marker).
  // Decided BEFORE the reporter joins so the JOIN can carry the
  // recovering flag (the node must never pass through ACTIVE with a
  // wiped disk).
  bool NeedsRecovery(bool data_was_fresh) const;
  // Chunk-dedup parity: recovered files at or above `threshold` bytes are
  // routed through the server's chunk store exactly like uploaded/synced
  // ones (fn(tmp_path, spi, size, remote) -> stored?).  Unset or failing
  // hook falls back to the flat rename.
  using ChunkedStoreFn = std::function<bool(
      const std::string& tmp_path, int spi, int64_t size,
      const std::string& remote)>;
  void SetChunkedStore(ChunkedStoreFn fn, int64_t threshold) {
    chunked_store_ = std::move(fn);
    chunk_threshold_ = threshold;
  }

  // Chunk-aware recovery: materialize `recipe` for `remote` on store
  // path `spi`, taking refs on chunks already present locally and
  // calling `fetch_chunks(want, out)` — one BATCHED peer round-trip
  // returning the payloads concatenated in `want` order — for the
  // rest.  Returns false on any failure — the caller then falls back
  // to the full-file download.  Dup-heavy rebuilds move only unique
  // bytes over the wire this way.
  using FetchChunksFn = std::function<bool(
      const std::vector<RecipeEntry>& want, std::string* out)>;
  // The hook reports *chunks_fetched (pulled over the wire) and
  // *chunks_local (satisfied by refs on chunks this node already held)
  // so the recovery counters reflect wire traffic, not recipe sizes
  // (ADVICE recovery.cc:591 — the old accounting charged every chunk of
  // every recovered recipe as "pulled").
  using RecipeRecoverFn = std::function<bool(
      int spi, const std::string& remote, const Recipe& recipe,
      const FetchChunksFn& fetch_chunks, int64_t* chunks_fetched,
      int64_t* chunks_local)>;
  void SetRecipeRecover(RecipeRecoverFn fn) {
    recipe_recover_ = std::move(fn);
  }

  // Distributed tracing: each recovered file becomes one trace
  // ("recovery.file" root + per-fetch child spans), its context
  // prefixed onto the peer RPCs so the serving node's FETCH_RECIPE /
  // FETCH_CHUNK / DOWNLOAD spans stitch cross-node.  null = untraced.
  void SetTrace(TraceRing* ring) { trace_ = ring; }

  // Start the background rebuild (call only when NeedsRecovery).
  void Start();
  void Stop();
  bool running() const { return running_; }
  int64_t files_recovered() const { return files_recovered_; }
  int64_t files_skipped() const { return files_skipped_; }
  int64_t chunks_pulled() const { return chunks_pulled_; }
  int64_t chunks_local() const { return chunks_local_; }

 private:
  struct TrackerReply {
    bool reached = false;
    uint8_t status = 0;
    std::string body;
  };
  void ThreadMain();
  // One RPC against every configured tracker (each holds independent
  // sync state for this node).
  std::vector<TrackerReply> TrackerRpcAll(uint8_t cmd,
                                          const std::string& body);
  // Marker phase record: "fetch" while data is being rebuilt, "notify"
  // once complete but with done-notify acks still outstanding.
  std::string ReadMarkerPhase() const;
  void WriteMarkerPhase(const std::string& phase) const;
  // Retry the done-notify against every tracker until each acks (or
  // shutdown); returns true when all acked.
  bool NotifyAllTrackers(const std::string& self);
  bool RecoverPath(const PeerInfo& peer, int spi);
  // All peer RPCs reuse one keepalive connection (*fd, -1 = closed);
  // callees reconnect once on IO failure.  Millions of small files would
  // otherwise pay a TCP handshake per file (twice, with metadata).
  bool EnsurePeerConn(const PeerInfo& peer, int* fd);
  bool FetchOnePathBinlog(const PeerInfo& peer, int* fd, int spi,
                          std::string* lines);
  bool DownloadToFile(const PeerInfo& peer, int* fd,
                      const std::string& remote,
                      const std::string& dest_path, bool* missing);
  bool FetchMetadata(const PeerInfo& peer, int* fd, const std::string& remote,
                     std::string* meta);
  bool StoreRecovered(const std::string& remote, const std::string& tmp_path);
  // Chunk-aware pulls (FETCH_RECIPE / FETCH_CHUNK).  FetchRecipe returns
  // false on transport failure; *flat = true when the peer stores the
  // file flat (ENOENT) — download normally then.
  bool FetchRecipe(const PeerInfo& peer, int* fd, const std::string& remote,
                   Recipe* recipe, bool* flat);
  bool FetchChunks(const PeerInfo& peer, int* fd, const std::string& remote,
                   const std::vector<RecipeEntry>& want, std::string* out);
  // TRACE_CTX prefix frame for the next peer RPC (no-op when the
  // current file is untraced); false = transport failure.
  bool SendTracePrefix(int fd);
  // Record a child span of the current file's trace (no-op untraced).
  void RecordFetchSpan(const char* name, int64_t start_us, bool ok);
  // Close (record) the current file's root span and clear the context.
  void CloseFileTrace(int64_t start_us, bool ok);

  StorageConfig cfg_;
  TrackerReporter* reporter_;
  StoreManager* store_;
  std::string marker_path_;
  std::thread thread_;
  std::atomic<bool> stop_{false};
  std::atomic<bool> running_{false};
  std::atomic<int64_t> files_recovered_{0};
  std::atomic<int64_t> files_skipped_{0};
  std::atomic<int64_t> chunks_pulled_{0};  // fetched over the wire
  std::atomic<int64_t> chunks_local_{0};   // satisfied by local refs
  ChunkedStoreFn chunked_store_;
  RecipeRecoverFn recipe_recover_;
  int64_t chunk_threshold_ = 0;
  // Recovery runs on ONE thread, so the current file's trace context
  // needs no locking; parent_span holds the file's root span id.
  TraceRing* trace_ = nullptr;
  TraceCtx cur_trace_;
};

}  // namespace fdfs
