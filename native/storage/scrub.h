// Integrity engine: background chunk-store scrubbing, bit-rot
// quarantine + replica repair, and zero-ref chunk GC.
//
// Motivation (ISSUE 4): the chunk store is the system of record for
// every byte, but nothing ever re-read a chunk after PutAndRef — one
// bit-rotted chunk silently poisons every future dedup hit — and
// DELETE_FILE only dropped refcounts, so with a GC grace window nothing
// reclaimed zero-ref chunks.  This manager runs one background thread
// per daemon that, every scrub_interval_s (or on SCRUB_KICK):
//
//   1. VERIFY: walks each store path's live chunks at a configurable
//      pace (scrub_bandwidth_mb_s token bucket), re-computes SHA1 per
//      chunk — batched on the TPU sidecar via DEDUP_VERIFY when
//      available, serial host SHA1 (SHA-NI) otherwise — and compares
//      against the content address;
//   2. QUARANTINE + REPAIR: mismatches move into
//      <store_path>/data/quarantine/ (never served again) and are
//      repaired by pulling the digest from a group replica over the
//      existing FETCH_CHUNK machinery, verifying the payload before
//      RepairChunk writes it back.  No replica serving the digest =>
//      scrub.corrupt_unrepairable (retried every pass);
//   3. GC: reclaims zero-ref chunks older than chunk_gc_grace_s
//      (ChunkStore::GcSweep — the pin probe shares the unlink's lock,
//      so phase-1 upload-session pins are race-free exempt);
//   4. SLAB COMPACTION (ISSUE 9): copies live records out of slab
//      files whose dead share crossed slab_compact_min_dead_pct and
//      unlinks them (ChunkStore::CompactSlabs), paced by the same
//      token bucket; copy-time re-verify failures feed back into the
//      quarantine/repair machinery above.
//
// Observable through the SCRUB_STATUS opcode (kScrubStatNames blob),
// the stats registry (scrub.* gauges), and the trace ring (scrub.pass
// root span + scrub.repair children).
//
// Reference departure: upstream FastDFS has no scrubbing at all — disk
// errors surface only when a client download happens to hit them.
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <functional>
#include <mutex>

#include "common/lockrank.h"
#include <string>
#include <thread>
#include <vector>

#include "common/protocol_gen.h"
#include "common/trace.h"
#include "storage/chunkstore.h"
#include "storage/dedup.h"

namespace fdfs {

struct ScrubOptions {
  int interval_s = 0;          // 0 = no periodic passes (kick still works)
  int64_t bandwidth_bytes_s = 0;  // verify read pace; 0 = unlimited
  // (the GC grace window lives in ChunkStore — GcSweep enforces it)

  // Erasure-coded cold tier (stage 5; storage.conf ec_* keys).  ec_k =
  // 0 disables demotion (existing stripes still repair + drain).
  int ec_k = 0;
  int ec_m = 0;
  int64_t ec_demote_age_s = 0;        // payload mtime age gate
  int64_t ec_bandwidth_bytes_s = 0;   // demote/repair IO pace; 0 = unlimited
  std::string self_id;  // this node's "ip:port" for jump-hash ownership
};

class ScrubManager {
 public:
  // "ip:port" strings of this group's replicas (the sync peer list).
  using PeerListFn = std::function<std::vector<std::string>()>;

  // chunk_stores[i] serves store path i; plugin (may be null) supplies
  // the batched sidecar verify — it must be this thread's OWN instance
  // (the plugins are not thread-safe; ChunkStore is).  events (may be
  // null) is the flight recorder: quarantine/repair/unrepairable/GC
  // become structured cluster events alongside the log lines.
  ScrubManager(ScrubOptions opts, std::string group_name,
               std::vector<ChunkStore*> chunk_stores, PeerListFn peers,
               DedupPlugin* plugin, TraceRing* trace,
               class EventLog* events = nullptr);
  ~ScrubManager();

  void Start();
  void Stop();

  // Schedule a full verify+repair+GC pass now (SCRUB_KICK).
  void Kick();

  // EC_KICK: schedule a pass whose stage 5 demotes every eligible cold
  // chunk IMMEDIATELY (the age gate drops to 0 for that one pass) — the
  // operator's "drain the replicated tier now" lever, and what makes
  // the kill-and-reconstruct acceptance test runnable without waiting
  // out ec_demote_age_s.
  void EcKick();

  // Fill kScrubStatCount slots in kScrubStatNames order (SCRUB_STATUS
  // body).
  void FillStats(int64_t* out) const;
  // Fill kEcStatCount slots in kEcStatNames order (EC_STATUS body).
  void FillEcStats(int64_t* out) const;
  int64_t EcStatValue(int i) const;
  // One slot on its own — the registry's per-gauge read path, so a
  // snapshot evaluating 18 scrub gauges does not pay 18 full fills
  // (each store-derived slot costs one chunk-store lock per store;
  // the rest are single atomic loads).
  int64_t StatValue(int i) const;

  // Recipe-sidecar reclamation accounting: DELETE_FILE calls this with
  // the .rcp file's size so operator dashboards see recipe bytes under
  // scrub.bytes_reclaimed alongside GC'd chunk bytes.
  void NoteRecipeReclaimed(int64_t bytes);

  bool running() const { return running_.load(); }
  int64_t passes() const { return passes_.load(); }
  int64_t chunks_repaired() const { return chunks_repaired_.load(); }
  int64_t chunks_reclaimed() const { return chunks_reclaimed_.load(); }
  int64_t bytes_reclaimed() const { return bytes_reclaimed_.load(); }
  int64_t corrupt_unrepairable() const {
    return corrupt_unrepairable_.load();
  }

 private:
  void ThreadMain();
  void RunPass();
  // Verify one batch of chunks read from store `spi`; returns the
  // number found corrupt.  `infos`/`payloads` are index-aligned;
  // entries whose payload could not even be read arrive pre-marked in
  // `bad`.
  void VerifyBatch(int spi, const std::vector<ChunkStore::ChunkInfo>& infos,
                   const std::vector<std::string>& payloads,
                   std::vector<char>* bad);
  // Quarantine + repair one corrupt chunk (records a scrub.repair span).
  // already_quarantined skips the quarantine step for the per-pass
  // repair retry of leftovers from earlier passes.
  void HandleCorrupt(int spi, const ChunkStore::ChunkInfo& info,
                     bool already_quarantined = false);
  // Pull one chunk's payload from any group replica via FETCH_CHUNK;
  // the result is digest-verified before this returns true.
  bool FetchFromReplica(int spi, const std::string& digest_hex, int64_t len,
                        std::string* out);
  // Token-bucket pacing for verify reads (sleeps in small stop_-aware
  // slices so shutdown never waits on a bandwidth debt).
  void Pace(int64_t bytes_read, int64_t pass_start_us);
  // Same token-bucket shape over the SEPARATE ec_bandwidth budget, so
  // stripe encodes/repairs pace independently of verify reads.
  void PaceEc(int64_t bytes, int64_t pass_start_us);

  // Stage 5a: repair every local stripe (CRC shards; <= m bad rebuilt
  // from parity in place, > m falls back to per-chunk FETCH_CHUNK
  // re-promotion + DropStripe).
  void RunEcRepair(int spi, int64_t pass_start_us, int64_t* ec_paced);
  // Stage 5b: demote cold chunks this node owns (jump hash over the
  // sorted group member list) into RS(k, m) stripes, then release the
  // replicated copies group-wide via the release.map handover.
  void RunEcDemote(int spi, int64_t age_s, int64_t pass_start_us,
                   int64_t* ec_paced);
  // One EC_RELEASE round: ship the batch to every group peer; true only
  // when EVERY peer answered (the bar for clearing release.map).
  bool SendReleaseToPeers(
      int spi, const std::vector<std::pair<std::string, int64_t>>& batch);

  ScrubOptions opts_;
  std::string group_name_;
  std::vector<ChunkStore*> stores_;
  PeerListFn peers_;
  DedupPlugin* plugin_;
  TraceRing* trace_;
  class EventLog* events_;

  std::thread thread_;
  RankedMutex mu_{LockRank::kScrub};
  std::condition_variable_any cv_;
  bool stop_ = false;
  bool kicked_ = false;
  // One-shot age-gate override armed by EcKick().
  std::atomic<bool> ec_kicked_{false};

  // SCRUB_STATUS counters (kScrubStatNames).  Plain atomics: written by
  // the scrub thread, snapshotted by nio loops serving SCRUB_STATUS.
  std::atomic<bool> running_{false};
  std::atomic<int64_t> passes_{0};
  std::atomic<int64_t> pass_chunks_done_{0};
  std::atomic<int64_t> pass_chunks_total_{0};
  std::atomic<int64_t> chunks_verified_{0};
  std::atomic<int64_t> bytes_verified_{0};
  std::atomic<int64_t> chunks_corrupt_{0};
  std::atomic<int64_t> chunks_repaired_{0};
  std::atomic<int64_t> corrupt_unrepairable_{0};
  std::atomic<int64_t> skipped_pinned_{0};
  std::atomic<int64_t> chunks_reclaimed_{0};
  std::atomic<int64_t> bytes_reclaimed_{0};
  std::atomic<int64_t> recipes_reclaimed_{0};
  std::atomic<int64_t> last_pass_unix_{0};
  std::atomic<int64_t> last_pass_dur_us_{0};

  // EC_STATUS counters (kEcStatNames; store-derived slots read the
  // chunk stores directly in EcStatValue).
  std::atomic<int64_t> ec_demoted_chunks_{0};
  std::atomic<int64_t> ec_demoted_bytes_{0};
  std::atomic<int64_t> ec_reconstructed_shards_{0};
  std::atomic<int64_t> ec_reconstructed_bytes_{0};
  std::atomic<int64_t> ec_repair_fallback_chunks_{0};
  std::atomic<int64_t> ec_last_demote_unix_{0};

  // Current pass's trace context (scrub.repair children attach to it).
  TraceCtx pass_ctx_;
};

}  // namespace fdfs
