#include "storage/config.h"

namespace fdfs {

bool StorageConfig::Load(const IniConfig& ini, std::string* error) {
  anomalies.clear();
  auto note = [this](const std::string& what) { anomalies.push_back(what); };
  group_name = ini.GetStr("group_name", group_name);
  bind_addr = ini.GetStr("bind_addr", "");
  port = static_cast<int>(ini.GetInt("port", port));
  base_path = ini.GetStr("base_path", "");
  if (base_path.empty()) {
    *error = "base_path is required";
    return false;
  }
  store_paths.clear();
  int n = static_cast<int>(ini.GetInt("store_path_count", 0));
  if (n == 0) {
    // Upstream default: store_path0 defaults to base_path.
    auto sp0 = ini.Get("store_path0");
    store_paths.push_back(sp0.has_value() && !sp0->empty() ? *sp0 : base_path);
  } else {
    for (int i = 0; i < n; ++i) {
      auto v = ini.Get("store_path" + std::to_string(i));
      if (!v.has_value() || v->empty()) {
        *error = "store_path" + std::to_string(i) + " missing";
        return false;
      }
      store_paths.push_back(*v);
    }
  }
  if (store_paths.size() > 256) {
    *error = "too many store paths (max 256)";
    return false;
  }
  subdir_count_per_path =
      static_cast<int>(ini.GetInt("subdir_count_per_path", subdir_count_per_path));
  if (subdir_count_per_path < 1 || subdir_count_per_path > 256) {
    *error = "subdir_count_per_path must be in [1,256]";
    return false;
  }
  buff_size = static_cast<int>(ini.GetBytes("buff_size", buff_size));
  network_timeout_ms =
      static_cast<int>(ini.GetSeconds("network_timeout", 30) * 1000);
  tracker_servers = ini.GetAll("tracker_server");
  heart_beat_interval_s =
      static_cast<int>(ini.GetSeconds("heart_beat_interval", 30));
  stat_report_interval_s =
      static_cast<int>(ini.GetSeconds("stat_report_interval", 60));
  sync_interval_ms = static_cast<int>(ini.GetInt("sync_interval_ms", 100));
  work_threads = static_cast<int>(ini.GetInt("work_threads", work_threads));
  if (work_threads < 1) work_threads = 1;
  if (work_threads > 64) {
    note("work_threads clamped to 64");
    work_threads = 64;
  }
  nio_reuseport = ini.GetBool("nio_reuseport", nio_reuseport);
  disk_writer_threads = static_cast<int>(
      ini.GetInt("disk_writer_threads", disk_writer_threads));
  if (disk_writer_threads < 1) disk_writer_threads = 1;
  if (disk_writer_threads > 64) {
    note("disk_writer_threads clamped to 64");
    disk_writer_threads = 64;
  }
  max_connections =
      static_cast<int>(ini.GetInt("max_connections", max_connections));
  if (max_connections < 0) max_connections = 0;
  dedup_mode = ini.GetStr("dedup_mode", "none");
  if (dedup_mode != "none" && dedup_mode != "cpu" && dedup_mode != "sidecar") {
    *error = "dedup_mode must be none|cpu|sidecar";
    return false;
  }
  dedup_sidecar = ini.GetStr("dedup_sidecar", "");
  dedup_chunk_threshold = ini.GetBytes("dedup_chunk_threshold", 64 * 1024);
  dedup_segment_bytes =
      ini.GetBytes("dedup_segment_bytes", 64LL * 1024 * 1024);
  if (dedup_segment_bytes < (1 << 20)) dedup_segment_bytes = 1 << 20;
  upload_session_timeout_s = static_cast<int>(
      ini.GetSeconds("upload_session_timeout", upload_session_timeout_s));
  if (upload_session_timeout_s < 1) upload_session_timeout_s = 1;
  log_level = ini.GetStr("log_level", "info");
  log_file = ini.GetStr("log_file", "");
  log_rotate_size = ini.GetBytes("log_rotate_size", log_rotate_size);
  use_access_log = ini.GetBool("use_access_log", false);
  trace_buffer_size =
      static_cast<int>(ini.GetInt("trace_buffer_size", trace_buffer_size));
  if (trace_buffer_size < 16) trace_buffer_size = 16;
  slow_request_threshold_ms =
      ini.GetInt("slow_request_threshold_ms", slow_request_threshold_ms);
  if (slow_request_threshold_ms < 0) slow_request_threshold_ms = 0;
  scrub_interval_s = static_cast<int>(
      ini.GetSeconds("scrub_interval_s", scrub_interval_s));
  if (scrub_interval_s < 0) scrub_interval_s = 0;
  scrub_bandwidth_mb_s = static_cast<int>(
      ini.GetInt("scrub_bandwidth_mb_s", scrub_bandwidth_mb_s));
  if (scrub_bandwidth_mb_s < 0) scrub_bandwidth_mb_s = 0;
  // 1 TB/s cap: keeps the pacing arithmetic far from int64 limits (a
  // larger value is indistinguishable from unpaced anyway).
  if (scrub_bandwidth_mb_s > (1 << 20)) {
    note("scrub_bandwidth_mb_s clamped to 1 TB/s");
    scrub_bandwidth_mb_s = 1 << 20;
  }
  chunk_gc_grace_s = ini.GetSeconds("chunk_gc_grace_s", chunk_gc_grace_s);
  if (chunk_gc_grace_s < 0) chunk_gc_grace_s = 0;
  slab_chunk_threshold =
      ini.GetBytes("slab_chunk_threshold", slab_chunk_threshold);
  if (slab_chunk_threshold < 0) slab_chunk_threshold = 0;
  slab_recipe_threshold =
      ini.GetBytes("slab_recipe_threshold", slab_recipe_threshold);
  if (slab_recipe_threshold < 0) slab_recipe_threshold = 0;
  slab_size_mb = static_cast<int>(ini.GetInt("slab_size_mb", slab_size_mb));
  if (slab_size_mb < 1) {
    note("slab_size_mb raised to 1");
    slab_size_mb = 1;
  }
  // 1 GB cap: compaction rewrites a whole victim slab per pass slice,
  // and a bigger slab only dilutes the dead-share trigger.
  if (slab_size_mb > 1024) {
    note("slab_size_mb clamped to 1024");
    slab_size_mb = 1024;
  }
  // A record must FIT a slab with room to spare or the active slab
  // rolls on every append; cap both thresholds at half the slab.
  int64_t slab_cap = (static_cast<int64_t>(slab_size_mb) << 20) / 2;
  if (slab_chunk_threshold > slab_cap) {
    note("slab_chunk_threshold clamped to slab_size_mb/2");
    slab_chunk_threshold = slab_cap;
  }
  if (slab_recipe_threshold > slab_cap) {
    note("slab_recipe_threshold clamped to slab_size_mb/2");
    slab_recipe_threshold = slab_cap;
  }
  slab_compact_min_dead_pct = static_cast<int>(
      ini.GetInt("slab_compact_min_dead_pct", slab_compact_min_dead_pct));
  if (slab_compact_min_dead_pct < 1) slab_compact_min_dead_pct = 1;
  if (slab_compact_min_dead_pct > 100) slab_compact_min_dead_pct = 100;
  read_cache_mb = static_cast<int>(ini.GetInt("read_cache_mb",
                                              read_cache_mb));
  if (read_cache_mb < 0) read_cache_mb = 0;
  // 64 GB cap: the cache is per store path and RAM-resident.
  if (read_cache_mb > (64 << 10)) {
    note("read_cache_mb clamped to 64 GB");
    read_cache_mb = 64 << 10;
  }
  event_buffer_size = static_cast<int>(
      ini.GetInt("event_buffer_size", event_buffer_size));
  if (event_buffer_size < 16) event_buffer_size = 16;
  if (event_buffer_size > (1 << 20)) {
    note("event_buffer_size clamped to 1M");
    event_buffer_size = 1 << 20;
  }
  metrics_journal_mb = static_cast<int>(
      ini.GetInt("metrics_journal_mb", metrics_journal_mb));
  if (metrics_journal_mb < 0) metrics_journal_mb = 0;
  // METRICS_HISTORY reads both ring files whole before decoding, so the
  // cap is also a transient dump-memory bound (the decode itself is
  // bounded at kMaxDecodedSnapshots full registries regardless of ring
  // size).  256 MB of delta records is weeks of history — far past the
  // point where `--since` windows, not ring depth, limit a post-mortem.
  if (metrics_journal_mb > 256) {
    note("metrics_journal_mb clamped to 256");
    metrics_journal_mb = 256;
  }
  ec_k = static_cast<int>(ini.GetInt("ec_k", ec_k));
  if (ec_k < 0) ec_k = 0;
  // 32 data shards already puts a single chunk read across up to 2 of
  // 32 files; wider stripes only grow the blast radius of a stripe
  // loss without improving the (k+m)/k overhead much past k=16.
  if (ec_k > 32) {
    note("ec_k clamped to 32");
    ec_k = 32;
  }
  ec_m = static_cast<int>(ini.GetInt("ec_m", ec_m));
  if (ec_m < 1) {
    note("ec_m raised to 1");
    ec_m = 1;
  }
  // The Cauchy construction needs k + m <= 256 over GF(2^8); 8 parity
  // shards is beyond any sane durability target at group scale.
  if (ec_m > 8) {
    note("ec_m clamped to 8");
    ec_m = 8;
  }
  ec_demote_age_s = ini.GetSeconds("ec_demote_age_s", ec_demote_age_s);
  if (ec_demote_age_s < 0) ec_demote_age_s = 0;
  ec_bandwidth_mb_s = static_cast<int>(
      ini.GetInt("ec_bandwidth_mb_s", ec_bandwidth_mb_s));
  if (ec_bandwidth_mb_s < 0) ec_bandwidth_mb_s = 0;
  if (ec_bandwidth_mb_s > (1 << 20)) {
    note("ec_bandwidth_mb_s clamped to 1 TB/s");
    ec_bandwidth_mb_s = 1 << 20;
  }
  slo_eval_interval_s = static_cast<int>(
      ini.GetSeconds("slo_eval_interval_s", slo_eval_interval_s));
  if (slo_eval_interval_s < 0) slo_eval_interval_s = 0;
  slo_rules_file = ini.GetStr("slo_rules_file", "");
  profile_max_hz = static_cast<int>(
      ini.GetInt("profile_max_hz", profile_max_hz));
  if (profile_max_hz < 0) profile_max_hz = 0;
  // ITIMER_PROF has ~1ms kernel granularity, so rates past 1000 Hz only
  // add handler overhead without adding samples.
  if (profile_max_hz > 1000) {
    note("profile_max_hz clamped to 1000");
    profile_max_hz = 1000;
  }
  health_probe_interval_s = static_cast<int>(
      ini.GetSeconds("health_probe_interval_s", health_probe_interval_s));
  if (health_probe_interval_s < 0) health_probe_interval_s = 0;
  probe_slow_threshold_ms = static_cast<int>(
      ini.GetInt("probe_slow_threshold_ms", probe_slow_threshold_ms));
  if (probe_slow_threshold_ms < 0) probe_slow_threshold_ms = 0;
  watchdog_stall_threshold_ms = static_cast<int>(
      ini.GetInt("watchdog_stall_threshold_ms", watchdog_stall_threshold_ms));
  if (watchdog_stall_threshold_ms < 0) watchdog_stall_threshold_ms = 0;
  // Sub-second thresholds false-positive on the 1s-bounded idle waits
  // every loop uses between beats.
  if (watchdog_stall_threshold_ms > 0 && watchdog_stall_threshold_ms < 2000) {
    note("watchdog_stall_threshold_ms raised to 2000");
    watchdog_stall_threshold_ms = 2000;
  }
  watchdog_inject_stall_ms = static_cast<int>(
      ini.GetInt("watchdog_inject_stall_ms", watchdog_inject_stall_ms));
  if (watchdog_inject_stall_ms < 0) watchdog_inject_stall_ms = 0;
  admission_control = ini.GetBool("admission_control", admission_control);
  admission_tighten_pct = static_cast<int>(
      ini.GetInt("admission_tighten_pct", admission_tighten_pct));
  admission_relax_pct = static_cast<int>(
      ini.GetInt("admission_relax_pct", admission_relax_pct));
  if (admission_tighten_pct < 1) {
    note("admission_tighten_pct raised to 1");
    admission_tighten_pct = 1;
  }
  // The relax threshold must sit strictly below tighten or the ladder
  // oscillates every tick — the exact flap the hysteresis band exists
  // to forbid (same clamp discipline as sloeval's clear <= threshold).
  if (admission_relax_pct >= admission_tighten_pct) {
    note("admission_relax_pct clamped below admission_tighten_pct");
    admission_relax_pct = admission_tighten_pct / 2;
  }
  if (admission_relax_pct < 0) admission_relax_pct = 0;
  admission_queue_depth_high =
      ini.GetInt("admission_queue_depth_high", admission_queue_depth_high);
  if (admission_queue_depth_high < 0) admission_queue_depth_high = 0;
  admission_loop_lag_high_ms =
      ini.GetInt("admission_loop_lag_high_ms", admission_loop_lag_high_ms);
  if (admission_loop_lag_high_ms < 0) admission_loop_lag_high_ms = 0;
  admission_inflight_high_bytes = ini.GetBytes(
      "admission_inflight_high_bytes", admission_inflight_high_bytes);
  if (admission_inflight_high_bytes < 0) admission_inflight_high_bytes = 0;
  admission_retry_after_ms =
      ini.GetInt("admission_retry_after_ms", admission_retry_after_ms);
  if (admission_retry_after_ms < 1) {
    note("admission_retry_after_ms raised to 1");
    admission_retry_after_ms = 1;
  }
  heat_top_k = static_cast<int>(ini.GetInt("heat_top_k", heat_top_k));
  if (heat_top_k < 0) heat_top_k = 0;
  // heat_top_k is the sketch's PER-STRIPE capacity, and a full stripe
  // evicts by scanning all its entries under the stripe mutex on the
  // request path — 1024 keeps that scan a few µs while still tracking
  // 8K keys per node (8 stripes), 32x the default.
  if (heat_top_k > 1024) {
    note("heat_top_k clamped to 1024");
    heat_top_k = 1024;
  }
  return true;
}

}  // namespace fdfs
