// Replication sender: per-peer sync threads tailing the binlog.
//
// Reference: storage/storage_sync.c — storage_sync_thread_entrance() tails
// data/sync/binlog.NNN through a "<ip>_<port>.mark" cursor, replays each
// source-op record on the group peer via STORAGE_PROTO_CMD_SYNC_* and
// reports the synced-through timestamp to the tracker (which gates read
// routing on it, tracker_mem_get_storage_by_filename()).
//
// Honest divergence from upstream: there is no SYNC_SRC_REQ/DEST_REQ
// negotiation (tracker_deal_storage_sync_* in tracker_service.c).  A peer
// first seen simply gets a fresh mark at position 0, so the full binlog
// history replays to it — the same end state as upstream's need_sync_old
// full-sync, without the three-way handshake.  Lowercase (replica-replay)
// records are never forwarded, which is what terminates the flood.
#pragma once

#include <atomic>
#include <cstdint>
#include <functional>
#include <map>
#include <memory>
#include <mutex>

#include "common/lockrank.h"
#include <string>
#include <thread>
#include <vector>

#include "common/trace.h"
#include "storage/binlog.h"
#include "storage/chunkstore.h"
#include "storage/config.h"
#include "storage/tracker_client.h"

namespace fdfs {

// Readable byte range backing a remote filename (flat file or trunk slot).
// The sync sender streams [offset, offset+size) from fd and closes it.
struct ContentHandle {
  int fd = -1;
  int64_t offset = 0;
  int64_t size = 0;
};

struct SyncCallbacks {
  // remote filename "Mxx/aa/bb/name" -> local path ("" when unresolvable).
  std::function<std::string(const std::string&)> resolve_local;
  // Trunk-aware content opener used by create replay; nullopt = the file
  // is gone (the later 'D' record is the correct end state on the peer).
  std::function<std::optional<ContentHandle>(const std::string&)> open_content;
  // Source-side progress report feeding the tracker's sync-timestamp
  // vectors (TrackerReporter::ReportSyncProgress).
  std::function<void(const std::string& ip, int port, int64_t ts)> report;
  // BinlogWriter::Quiescent — gates the caught-up wall-clock report (a
  // stamp captured before an unfinished write could be in a past second).
  std::function<bool()> binlog_quiescent;
  // Chunk-aware replication hooks (unset => every create ships logical
  // bytes).  pin_recipe returns the file's recipe with its chunks
  // PINNED (a concurrent delete cannot unlink bytes mid-send);
  // unpin_recipe releases them; read_chunk reads one chunk's payload.
  std::function<std::optional<Recipe>(const std::string& remote)> pin_recipe;
  std::function<void(const std::string& remote, const Recipe&)> unpin_recipe;
  std::function<bool(const std::string& remote, const std::string& digest_hex,
                     int64_t len, std::string* out)> read_chunk;
  // Distributed tracing (both may be null = untraced replication).  The
  // correlator maps a recently-traced mutation's remote filename to its
  // context; the sender consumes it, prefixes the replay with a
  // TRACE_CTX frame (the peer's replica-replay spans join the trace),
  // and records its own "sync.ship" span into the ring.  Transport
  // failures restore the entry so the retried record stays traced.
  TraceCorrelator* trace_corr = nullptr;
  TraceRing* trace_ring = nullptr;
  // Flight recorder (may be null): replication stalls (peer connect
  // failures / mid-replay transport drops) and permanently-skipped
  // records become structured cluster events.
  class EventLog* events = nullptr;
};

struct SyncPeerState {
  std::string addr;
  bool connected = false;
  int64_t synced_ts = 0;
  int64_t records_synced = 0;
  int64_t records_skipped = 0;
};

class SyncManager {
 public:
  SyncManager(const StorageConfig& cfg, SyncCallbacks cbs);
  ~SyncManager();

  // Reconcile sync threads with the tracker-reported peer list: spawn for
  // new peers, retire threads for vanished ones.  Thread-safe (called from
  // reporter threads).
  void UpdatePeers(const std::vector<PeerInfo>& peers);
  void Stop();
  std::vector<SyncPeerState> States() const;

 private:
  struct Worker {
    std::string ip;
    int port = 0;
    std::thread thread;
    std::atomic<bool> stop{false};
    std::atomic<bool> connected{false};
    std::atomic<int64_t> synced_ts{0};
    std::atomic<int64_t> records_synced{0};
    std::atomic<int64_t> records_skipped{0};
  };

  void WorkerMain(Worker* w);
  // Replays one record on the peer.  Returns true when the record is done
  // with (synced OR permanently unreplayable => skip); false on transient
  // IO failure (caller reconnects and retries the same record).
  bool Replay(Worker* w, int* fd, const BinlogRecord& rec);
  bool ReplayCreate(int fd, const BinlogRecord& rec, bool* skipped);
  // Chunk-aware create replay: recipe + only-missing chunks.  Returns
  // 0 = replayed (or correctly skipped), 1 = fall back to the
  // full-copy path, -1 = transport failure (caller reconnects).
  int TryReplayRecipe(int fd, const BinlogRecord& rec, bool* skipped);
  bool ReplayDelete(int fd, const BinlogRecord& rec, bool* skipped);
  bool ReplayUpdate(int fd, const BinlogRecord& rec, bool* skipped);
  bool ReplayLink(int fd, const BinlogRecord& rec, bool* skipped);
  bool ReplayRange(int fd, uint8_t cmd, const BinlogRecord& rec,
                   bool* skipped);
  bool ReplayTruncate(int fd, const BinlogRecord& rec, bool* skipped);

  StorageConfig cfg_;
  SyncCallbacks cbs_;
  std::string sync_dir_;
  mutable RankedMutex mu_{LockRank::kSync};
  bool stopped_ = false;
  std::map<std::string, std::unique_ptr<Worker>> workers_;  // key "ip:port"
  // Workers whose peer vanished: stop-flagged immediately, joined in
  // Stop()/dtor — never on the reporter thread, whose heartbeats must not
  // block behind an in-flight transfer.
  std::vector<std::unique_ptr<Worker>> retired_;
};

}  // namespace fdfs
