// Binlog writer/reader: the durable replication log.
//
// Reference: storage/storage_sync.c — storage_binlog_write() appends
// "<timestamp> <op_char> <filename>\n" records to data/sync/binlog.NNN
// (rotating at a fixed size); per-peer sync threads tail it via
// "<ip>_<port>.mark" cursor files.  Op chars: source ops are uppercase
// (C reate, D elete, U pdate, A ppend, M odify, T runcate, L ink), replica
// replays are lowercase.
#pragma once

#include <atomic>
#include <mutex>

#include "common/lockrank.h"
#include <cstdint>
#include <optional>
#include <string>

namespace fdfs {

constexpr char kBinlogOpCreate = 'C';
constexpr char kBinlogOpDelete = 'D';
constexpr char kBinlogOpUpdate = 'U';
constexpr char kBinlogOpAppend = 'A';
constexpr char kBinlogOpModify = 'M';
constexpr char kBinlogOpTruncate = 'T';
constexpr char kBinlogOpLink = 'L';

struct BinlogRecord {
  int64_t timestamp = 0;
  char op = 0;
  std::string filename;  // remote filename "Mxx/aa/bb/name[.ext]"
  // 'L' (link) records carry "filename\x02src_filename".
  std::string extra;
};

std::string FormatBinlogRecord(const BinlogRecord& rec);
std::optional<BinlogRecord> ParseBinlogRecord(const std::string& line);

class BinlogWriter {
 public:
  // base_dir: <base_path>/data/sync; creates binlog.000 etc.
  bool Init(const std::string& base_dir, int64_t rotate_size, std::string* error);
  bool Append(char op, const std::string& filename,
              const std::string& extra = "");
  // Current write position (file_index, offset) — what a fully-caught-up
  // reader would hold.
  void Position(int* file_index, int64_t* offset) const;
  std::string FilePath(int file_index) const;
  int file_index() const { return file_index_; }
  void Flush();
  void Close();
  // True when no Append sits between its timestamp capture and its write()
  // completing — the only window where a record stamped in a PAST second
  // can still be invisible to a reader at EOF.  Sync threads gate their
  // caught-up "synced through now-1" reports on this.
  bool Quiescent() const { return in_flight_.load() == 0; }

 private:
  bool OpenCurrent(std::string* error);
  std::string dir_;
  int64_t rotate_size_ = 0;
  int file_index_ = 0;
  int64_t offset_ = 0;
  int fd_ = -1;
  std::atomic<int> in_flight_{0};
  mutable RankedMutex mu_{LockRank::kBinlog};  // appends come from every nio/dio thread
};

// One-path binlog extraction (FETCH_ONE_PATH_BINLOG 26, the feed for disk
// recovery): records in the sync dir whose filename lives on store path
// `spi`, as raw binlog lines — paged by byte offset into the FILTERED
// stream so neither side ever buffers the whole history (a page always
// ends on a record boundary; a short page means end).  Reference:
// storage/storage_sync.c:fdfs_binlog_reader (one-path filter mode).
std::string CollectOnePathBinlog(const std::string& sync_dir, int spi,
                                 int64_t offset, int64_t max_bytes);

// Sequential reader with a persistent cursor (mark file).
class BinlogReader {
 public:
  // mark_path: cursor file; binlog dir as in writer.
  bool Init(const std::string& dir, const std::string& mark_path,
            std::string* error);
  // Next record, or nullopt when caught up.  Advances the in-memory
  // cursor; call SaveMark() to persist.
  std::optional<BinlogRecord> Next();
  bool SaveMark();
  int file_index() const { return file_index_; }
  int64_t offset() const { return offset_; }
  int64_t records_read() const { return records_read_; }

 private:
  std::string dir_;
  std::string mark_path_;
  int file_index_ = 0;
  int64_t offset_ = 0;
  int64_t records_read_ = 0;
  int fd_ = -1;
  std::string buf_;
  size_t buf_pos_ = 0;
  bool FillBuf();
};

}  // namespace fdfs
