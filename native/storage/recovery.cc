#include "storage/recovery.h"

#include <dirent.h>
#include <fcntl.h>
#include <string.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <set>

#include "common/bytes.h"
#include "common/fileid.h"
#include "common/fsutil.h"
#include "common/log.h"
#include "common/threadreg.h"
#include "common/net.h"
#include "common/protocol_gen.h"
#include "storage/binlog.h"
#include "storage/trunk.h"

namespace fdfs {

namespace {

constexpr int kRpcTimeoutMs = 10000;

bool Rpc(int fd, uint8_t cmd, const std::string& body, std::string* resp,
         uint8_t* status, int64_t max_resp) {
  return NetRpc(fd, cmd, body, resp, status, max_resp, kRpcTimeoutMs);
}

bool HasMarkFiles(const std::string& sync_dir) {
  DIR* d = opendir(sync_dir.c_str());
  if (d == nullptr) return false;
  bool found = false;
  struct dirent* de;
  while ((de = readdir(d)) != nullptr) {
    std::string name = de->d_name;
    if (name.size() > 5 && name.rfind(".mark") == name.size() - 5) {
      found = true;
      break;
    }
  }
  closedir(d);
  return found;
}

}  // namespace

RecoveryManager::RecoveryManager(const StorageConfig& cfg,
                                 TrackerReporter* reporter,
                                 StoreManager* store)
    : cfg_(cfg), reporter_(reporter), store_(store),
      marker_path_(cfg.base_path + "/data/.recovery") {}

RecoveryManager::~RecoveryManager() { Stop(); }

void RecoveryManager::Stop() {
  stop_ = true;
  if (thread_.joinable()) thread_.join();
}

bool RecoveryManager::NeedsRecovery(bool data_was_fresh) const {
  struct stat st;
  if (stat(marker_path_.c_str(), &st) == 0) return true;  // unfinished
  return data_was_fresh && HasMarkFiles(cfg_.base_path + "/data/sync");
}

void RecoveryManager::Start() {
  // The marker doubles as a phase record: "fetch" (data still being
  // rebuilt) vs "notify" (data complete, done-notify not yet acked by
  // every tracker).  A restart in the notify phase must NOT redo the
  // fetch — only finish telling the trackers.
  if (ReadMarkerPhase() != "notify") WriteMarkerPhase("fetch");
  FDFS_LOG_WARN("disk recovery: starting background rebuild");
  running_ = true;
  thread_ = std::thread(&RecoveryManager::ThreadMain, this);
}

std::string RecoveryManager::ReadMarkerPhase() const {
  FILE* f = fopen(marker_path_.c_str(), "r");
  if (f == nullptr) return "";
  char buf[32] = {0};
  size_t n = fread(buf, 1, sizeof(buf) - 1, f);
  fclose(f);
  std::string s(buf, n);
  while (!s.empty() && (s.back() == '\n' || s.back() == ' ')) s.pop_back();
  return s;
}

void RecoveryManager::WriteMarkerPhase(const std::string& phase) const {
  // A lost marker is NOT fail-safe: a crash mid-fetch with no marker
  // rejoins "healthy" and the tracker clears its recovery hold for a
  // half-rebuilt node.  Never fail silently here.
  std::string tmp = marker_path_ + ".tmp";
  FILE* f = fopen(tmp.c_str(), "w");
  if (f == nullptr || fputs(phase.c_str(), f) == EOF ||
      fflush(f) != 0 || fsync(fileno(f)) != 0) {
    FDFS_LOG_ERROR("recovery marker write %s FAILED (%s): a crash before "
                   "completion may rejoin a half-rebuilt node as healthy",
                   tmp.c_str(), strerror(errno));
    if (f != nullptr) fclose(f);
    return;
  }
  fclose(f);
  if (rename(tmp.c_str(), marker_path_.c_str()) != 0)
    FDFS_LOG_ERROR("recovery marker rename %s: %s", marker_path_.c_str(),
                   strerror(errno));
}

// Done-notify to EVERY tracker, retrying each until it acks: a tracker
// unreachable at completion would otherwise hold this node in WAIT_SYNC
// (with the sentinel sync_until_ts) and exclude it from that tracker's
// read routing indefinitely.  Re-sends to already-acked trackers are
// idempotent, so acks are simply accumulated across rounds.
bool RecoveryManager::NotifyAllTrackers(const std::string& self) {
  std::vector<bool> acked(cfg_.tracker_servers.size(), false);
  int backoff_ms = 500;
  int unreachable_rounds = 0;
  while (!stop_) {
    auto replies =
        TrackerRpcAll(static_cast<uint8_t>(TrackerCmd::kStorageSyncNotify),
                      self);
    bool all = true, progress = false;
    for (size_t i = 0; i < replies.size(); ++i) {
      if (replies[i].reached && replies[i].status == 0) {
        if (!acked[i]) progress = true;
        acked[i] = true;
      }
      if (!acked[i]) all = false;
    }
    if (all) return true;
    // Bound the loop for permanently-decommissioned trackers left in the
    // config: once every *reachable* tracker has acked and the remainder
    // stayed dark for many rounds, declare done — a held tracker that
    // later returns clears the hold itself when our healthy (non-
    // recovering) JOIN arrives (Cluster::Join sentinel path).
    bool rest_unreachable = true;
    for (size_t i = 0; i < replies.size(); ++i)
      if (!acked[i] && replies[i].reached) rest_unreachable = false;
    unreachable_rounds = (rest_unreachable && !progress)
                             ? unreachable_rounds + 1 : 0;
    if (unreachable_rounds >= 20) {
      FDFS_LOG_WARN("disk recovery: done-notify gave up on unreachable "
                    "tracker(s); their holds clear on our next JOIN");
      return true;
    }
    for (int i = 0; i < backoff_ms / 100 && !stop_; ++i) {
      BeatThreadHeartbeat();  // backed off, not stalled
      usleep(100 * 1000);
    }
    backoff_ms = std::min(backoff_ms * 2, 10000);
  }
  return false;
}

// One RPC against EVERY configured tracker (each holds its own copy of
// this node's sync state and must see the re-enter query / done-notify).
// Returns per-tracker (reached, status, body); reached=false rows have
// undefined status/body.
std::vector<RecoveryManager::TrackerReply> RecoveryManager::TrackerRpcAll(
    uint8_t cmd, const std::string& body) {
  std::vector<TrackerReply> out;
  for (const std::string& addr : cfg_.tracker_servers) {
    TrackerReply r;
    size_t colon = addr.rfind(':');
    if (colon != std::string::npos) {
      std::string err;
      int fd = TcpConnect(addr.substr(0, colon),
                          atoi(addr.c_str() + colon + 1), 3000, &err);
      if (fd >= 0) {
        r.reached = Rpc(fd, cmd, body, &r.body, &r.status, 4096);
        close(fd);
      }
    }
    out.push_back(std::move(r));
  }
  return out;
}

void RecoveryManager::ThreadMain() {
  ScopedThreadName ledger("recovery");
  // Wait for the reporter to join a tracker and learn the peer list.
  std::vector<PeerInfo> peers;
  for (int i = 0; i < 300 && !stop_; ++i) {
    peers = reporter_->peers();
    if (!peers.empty()) break;
    BeatThreadHeartbeat();  // waiting on the reporter, not stalled
    usleep(100 * 1000);
  }

  std::string self;
  PutFixedField(&self, cfg_.group_name, kGroupNameMaxLen);
  PutFixedField(&self, reporter_->my_ip(), kIpAddressSize);
  {
    char num[8];
    PutInt64BE(cfg_.port, reinterpret_cast<uint8_t*>(num));
    self.append(num, 8);
  }

  // Restart mid-notify: the data fetch already completed, only the
  // done-notify to the trackers is outstanding.
  if (ReadMarkerPhase() == "notify") {
    FDFS_LOG_WARN("disk recovery: resuming done-notify phase");
    reporter_->set_recovering(false);
    if (NotifyAllTrackers(self)) {
      unlink(marker_path_.c_str());
      FDFS_LOG_INFO("disk recovery: done-notify completed");
    }
    running_ = false;
    return;
  }

  // Re-enter full-sync, then rebuild; every failure retries with backoff
  // (a dead source is re-negotiated each round).  Going ACTIVE with a
  // wiped disk is never an option, so this loop runs until it succeeds,
  // the group turns out to be source-less (sole member), or shutdown.
  // Each round queries EVERY tracker (arming each one's hold) and only
  // two outcomes terminate the negotiation: a source (status 0 + body)
  // or every reachable tracker answering "settled" (status 0, empty
  // body).  Anything else — tracker down, unknown node because our JOIN
  // has not landed there yet (status 2), or EAGAIN (11) — retries:
  // misreading an error as "settled" would promote a wiped node.
  (void)peers;
  int backoff_ms = 1000;
  while (!stop_) {
    PeerInfo source;
    bool have_source = false;
    bool settled = false;
    while (!stop_) {
      BeatThreadHeartbeat();
      auto replies = TrackerRpcAll(
          static_cast<uint8_t>(TrackerCmd::kStorageSyncDestQuery), self);
      int reached = 0, settled_count = 0;
      for (const TrackerReply& r : replies) {
        if (!r.reached) continue;
        ++reached;
        if (r.status == 0 && r.body.size() >= kIpAddressSize + 16 &&
            !have_source) {
          const uint8_t* p = reinterpret_cast<const uint8_t*>(r.body.data());
          source.ip = GetFixedField(p, kIpAddressSize);
          source.port = static_cast<int>(GetInt64BE(p + kIpAddressSize));
          have_source = true;
        } else if (r.status == 0) {
          ++settled_count;
        }
      }
      if (have_source) break;
      if (reached > 0 && settled_count == reached) {
        settled = true;
        break;
      }
      usleep(500 * 1000);
    }
    if (stop_ || settled) break;
    if (!have_source) continue;

    FDFS_LOG_INFO("disk recovery: rebuilding from %s:%d", source.ip.c_str(),
                  source.port);
    bool all_ok = true;
    for (int spi = 0; spi < store_->store_path_count() && !stop_; ++spi)
      all_ok = RecoverPath(source, spi) && all_ok;
    if (all_ok) break;
    FDFS_LOG_WARN("disk recovery round failed: retrying in %d ms",
                  backoff_ms);
    for (int i = 0; i < backoff_ms / 100 && !stop_; ++i) {
      BeatThreadHeartbeat();  // backed off, not stalled
      usleep(100 * 1000);
    }
    backoff_ms = std::min(backoff_ms * 2, 30000);
  }

  if (!stop_) {
    WriteMarkerPhase("notify");  // fetch done; survives a crash mid-notify
    reporter_->set_recovering(false);  // future re-joins are normal again
    if (NotifyAllTrackers(self)) {
      unlink(marker_path_.c_str());
      FDFS_LOG_INFO("disk recovery complete: %lld files restored, %lld "
                    "skipped, %lld chunks fetched over the wire, %lld "
                    "satisfied by local refs",
                    static_cast<long long>(files_recovered_.load()),
                    static_cast<long long>(files_skipped_.load()),
                    static_cast<long long>(chunks_pulled_.load()),
                    static_cast<long long>(chunks_local_.load()));
    }
  }
  running_ = false;
}

bool RecoveryManager::EnsurePeerConn(const PeerInfo& peer, int* fd) {
  if (*fd >= 0) return true;
  std::string err;
  *fd = TcpConnect(peer.ip, peer.port, 3000, &err);
  return *fd >= 0;
}

bool RecoveryManager::SendTracePrefix(int fd) {
  if (trace_ == nullptr || !cur_trace_.valid()) return true;
  uint8_t frame[kTraceCtxFrameLen];
  BuildTraceCtxFrame(cur_trace_, frame);
  return SendAll(fd, frame, sizeof(frame), kRpcTimeoutMs);
}

void RecoveryManager::RecordFetchSpan(const char* name, int64_t start_us,
                                      bool ok) {
  if (trace_ == nullptr || !cur_trace_.valid()) return;
  TraceSpan s;
  s.trace_id = cur_trace_.trace_id;
  s.span_id = trace_->NextSpanId();
  s.parent_id = cur_trace_.parent_span;
  s.start_us = start_us;
  s.dur_us = TraceWallUs() - start_us;
  s.status = ok ? 0 : 5 /*EIO*/;
  s.SetName(name);
  trace_->Record(s);
}

bool RecoveryManager::FetchOnePathBinlog(const PeerInfo& peer, int* fd,
                                         int spi, std::string* lines) {
  // Paged pull: a page shorter than the server's window is the end (a
  // non-final page is always filled to >= the window; an exactly-full
  // final page just costs one extra empty-page roundtrip).
  constexpr int64_t kPageFloor = 8 << 20;  // == server kPageBytes
  lines->clear();
  for (;;) {
    if (!EnsurePeerConn(peer, fd)) return false;
    std::string body;
    PutFixedField(&body, cfg_.group_name, kGroupNameMaxLen);
    body.push_back(static_cast<char>(spi));
    char num[8];
    PutInt64BE(static_cast<int64_t>(lines->size()),
               reinterpret_cast<uint8_t*>(num));
    body.append(num, 8);
    std::string page;
    uint8_t status = 0;
    if (!Rpc(*fd, static_cast<uint8_t>(StorageCmd::kFetchOnePathBinlog),
             body, &page, &status, 64 << 20) ||
        status != 0) {
      close(*fd);
      *fd = -1;
      return false;
    }
    lines->append(page);
    if (static_cast<int64_t>(page.size()) < kPageFloor) return true;
  }
}

bool RecoveryManager::DownloadToFile(const PeerInfo& peer, int* fd,
                                     const std::string& remote,
                                     const std::string& dest_path,
                                     bool* missing) {
  // Streamed, not buffered: recovered files can be arbitrarily large (the
  // size field is 48 bits) and must never have to fit in memory.
  *missing = false;
  if (!EnsurePeerConn(peer, fd)) return false;
  if (!SendTracePrefix(*fd)) {
    close(*fd);
    *fd = -1;
    return false;
  }
  std::string body(16, '\0');  // 8B offset 0 + 8B count 0 (whole file)
  PutFixedField(&body, cfg_.group_name, kGroupNameMaxLen);
  body += remote;
  uint8_t hdr[kHeaderSize];
  PutInt64BE(static_cast<int64_t>(body.size()), hdr);
  hdr[8] = static_cast<uint8_t>(StorageCmd::kDownloadFile);
  hdr[9] = 0;
  bool ok = SendAll(*fd, hdr, sizeof(hdr), kRpcTimeoutMs) &&
            SendAll(*fd, body.data(), body.size(), kRpcTimeoutMs) &&
            RecvAll(*fd, hdr, sizeof(hdr), kRpcTimeoutMs);
  if (!ok) {
    close(*fd);
    *fd = -1;
    return false;
  }
  int64_t len = GetInt64BE(hdr);
  uint8_t status = hdr[9];
  if (status != 0 || len < 0) {
    // Error responses carry no body; the connection stays in sync.
    *missing = true;
    return status == 2;  // ENOENT: deleted since the record — skip is fine
  }
  int out = open(dest_path.c_str(), O_CREAT | O_WRONLY | O_TRUNC, 0644);
  if (out < 0) {
    close(*fd);
    *fd = -1;
    return false;
  }
  char buf[256 * 1024];
  int64_t left = len;
  while (left > 0 && !stop_) {
    size_t want = static_cast<size_t>(
        std::min<int64_t>(left, static_cast<int64_t>(sizeof(buf))));
    if (!RecvAll(*fd, buf, want, kRpcTimeoutMs) ||
        write(out, buf, want) != static_cast<ssize_t>(want)) {
      close(out);
      close(*fd);
      *fd = -1;
      unlink(dest_path.c_str());
      return false;
    }
    left -= static_cast<int64_t>(want);
  }
  close(out);
  if (left > 0) {  // stop_ interrupted mid-stream
    close(*fd);
    *fd = -1;
    unlink(dest_path.c_str());
    return false;
  }
  return true;
}

bool RecoveryManager::FetchRecipe(const PeerInfo& peer, int* fd,
                                  const std::string& remote, Recipe* recipe,
                                  bool* flat) {
  *flat = false;
  if (!EnsurePeerConn(peer, fd)) return false;
  if (!SendTracePrefix(*fd)) {
    close(*fd);
    *fd = -1;
    return false;
  }
  std::string body;
  PutFixedField(&body, cfg_.group_name, kGroupNameMaxLen);
  body += remote;
  std::string resp;
  uint8_t status = 0;
  if (!Rpc(*fd, static_cast<uint8_t>(StorageCmd::kFetchRecipe), body, &resp,
           &status, 64 << 20)) {
    close(*fd);
    *fd = -1;
    return false;
  }
  if (status != 0) {
    // ENOENT: flat (or gone — the later download answers that);
    // anything else (old peer, EINVAL): also just download normally.
    *flat = true;
    return true;
  }
  if (resp.size() < 16) return false;
  const uint8_t* p = reinterpret_cast<const uint8_t*>(resp.data());
  recipe->logical_size = GetInt64BE(p);
  int64_t n = GetInt64BE(p + 8);
  // Divide, don't multiply: a huge n could wrap 28*n modulo 2^64 past
  // the equality check and then blow up reserve()/the parse loop.
  if (n <= 0 || static_cast<size_t>(n) != (resp.size() - 16) / 28 ||
      (resp.size() - 16) % 28 != 0) {
    *flat = true;  // malformed: be safe, take the full-download path
    return true;
  }
  recipe->chunks.clear();
  recipe->chunks.reserve(static_cast<size_t>(n));
  int64_t covered = 0;
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* e = p + 16 + i * 28;
    int64_t len = GetInt64BE(e + 20);
    if (len <= 0) {
      *flat = true;
      return true;
    }
    recipe->chunks.push_back({BytesToHex(e, 20), len});
    covered += len;
  }
  if (covered != recipe->logical_size) {
    *flat = true;
    return true;
  }
  return true;
}

bool RecoveryManager::FetchChunks(const PeerInfo& peer, int* fd,
                                  const std::string& remote,
                                  const std::vector<RecipeEntry>& want,
                                  std::string* out) {
  if (want.empty()) {
    out->clear();
    return true;
  }
  if (!EnsurePeerConn(peer, fd)) return false;
  if (!SendTracePrefix(*fd)) {
    close(*fd);
    *fd = -1;
    return false;
  }
  std::string body;
  PutFixedField(&body, cfg_.group_name, kGroupNameMaxLen);
  uint8_t num[8];
  PutInt64BE(static_cast<int64_t>(remote.size()), num);
  body.append(reinterpret_cast<char*>(num), 8);
  body += remote;
  PutInt64BE(static_cast<int64_t>(want.size()), num);
  body.append(reinterpret_cast<char*>(num), 8);
  int64_t total = 0;
  for (const RecipeEntry& e : want) {
    if (!HexToBytes(e.digest_hex, &body)) return false;
    PutInt64BE(e.length, num);
    body.append(reinterpret_cast<char*>(num), 8);
    total += e.length;
  }
  uint8_t status = 0;
  if (!Rpc(*fd, static_cast<uint8_t>(StorageCmd::kFetchChunk), body, out,
           &status, 17 << 20)) {
    close(*fd);
    *fd = -1;
    return false;
  }
  return status == 0 && static_cast<int64_t>(out->size()) == total;
}

bool RecoveryManager::FetchMetadata(const PeerInfo& peer, int* fd,
                                    const std::string& remote,
                                    std::string* meta) {
  if (!EnsurePeerConn(peer, fd)) return false;
  std::string body;
  PutFixedField(&body, cfg_.group_name, kGroupNameMaxLen);
  body += remote;
  uint8_t status = 0;
  if (!Rpc(*fd, static_cast<uint8_t>(StorageCmd::kGetMetadata), body, meta,
           &status, 16 << 20)) {
    close(*fd);
    *fd = -1;
    return false;
  }
  return status == 0 && !meta->empty();
}

bool RecoveryManager::StoreRecovered(const std::string& remote,
                                     const std::string& tmp_path) {
  auto parts = DecodeFileId(cfg_.group_name + "/" + remote);
  if (parts.has_value() && parts->trunk_loc.has_value()) {
    // Trunk slots are bounded by slot_max_size; reading the staged file
    // back into memory is fine here.
    std::string content, err;
    if (!ReadWholeFile(tmp_path, &content) ||
        !WriteSlotPayload(store_->store_path(0), *parts->trunk_loc, content,
                          parts->crc32, &err)) {
      FDFS_LOG_ERROR("recovery trunk write %s: %s", remote.c_str(),
                     err.c_str());
      unlink(tmp_path.c_str());
      return false;
    }
    unlink(tmp_path.c_str());
    return true;
  }
  int spi = 0;
  sscanf(remote.c_str(), "M%02X/", &spi);
  if (spi >= store_->store_path_count()) {
    unlink(tmp_path.c_str());
    return false;
  }
  auto local = LocalPath(store_->store_path(spi), remote);
  if (!local.has_value()) {
    unlink(tmp_path.c_str());
    return false;
  }
  // Dedup parity with the upload/sync paths: chunk-eligible recovered
  // files go through the chunk store (recipe + content-addressed chunks)
  // so a rebuilt node deduplicates like its peers; failure of any kind
  // falls back to the flat copy.  Appenders stay flat everywhere
  // (mutable: later APPEND/MODIFY ops open the flat file in place).
  // Parent fan-out dirs materialize only with a flat inode — a
  // slab-resident recipe costs zero inodes, dirs included.
  struct stat st;
  if (chunked_store_ && chunk_threshold_ > 0 &&
      !(parts.has_value() && parts->appender) &&
      stat(tmp_path.c_str(), &st) == 0 && st.st_size >= chunk_threshold_) {
    if (chunked_store_(tmp_path, spi, st.st_size, remote)) {
      unlink(tmp_path.c_str());
      return true;
    }
  }
  StoreManager::EnsureParentDirs(*local);
  if (rename(tmp_path.c_str(), local->c_str()) != 0) {
    unlink(tmp_path.c_str());
    return false;
  }
  return true;
}

bool RecoveryManager::RecoverPath(const PeerInfo& peer, int spi) {
  int conn = -1;
  std::string lines;
  if (!FetchOnePathBinlog(peer, &conn, spi, &lines)) {
    FDFS_LOG_ERROR("recovery: fetch one-path binlog (path %d) from %s:%d "
                   "failed", spi, peer.ip.c_str(), peer.port);
    if (conn >= 0) close(conn);
    return false;
  }
  // Unique filenames, in first-seen order; every op type names a file that
  // should exist now unless later deleted (the peer answers ENOENT then).
  std::set<std::string> seen;
  std::vector<std::string> files;
  size_t pos = 0;
  while (pos < lines.size()) {
    size_t nl = lines.find('\n', pos);
    std::string line = lines.substr(pos, nl == std::string::npos
                                             ? std::string::npos
                                             : nl - pos + 1);
    pos = nl == std::string::npos ? lines.size() : nl + 1;
    auto rec = ParseBinlogRecord(line);
    if (!rec.has_value()) continue;
    if (rec->op == 'D' || rec->op == 'd') continue;  // gone; skip fast
    if (seen.insert(rec->filename).second) files.push_back(rec->filename);
  }
  FDFS_LOG_INFO("recovery: path %d has %zu candidate files", spi,
                files.size());
  bool all_ok = true;
  for (const std::string& remote : files) {
    if (stop_) break;
    // One trace per recovered file: fetch RPCs carry the context to the
    // peer (its FETCH_* spans stitch in); the root span closes below.
    int64_t t_file = 0;
    if (trace_ != nullptr) {
      cur_trace_.trace_id = trace_->NewTraceId();
      cur_trace_.parent_span = trace_->NextSpanId();  // the file root span
      cur_trace_.flags = 0;
      t_file = TraceWallUs();
    }
    bool file_ok = true;
    // Chunk-aware pull first: recipe + only locally-missing chunk bytes
    // (dup-heavy rebuilds re-fetch unique bytes once, not per file).
    // Any failure — old peer, vanished chunk, local IO — falls back to
    // the full-file download below.
    bool stored = false;
    if (recipe_recover_) {
      Recipe r;
      bool flat = false;
      int64_t t0 = TraceWallUs();
      bool got = FetchRecipe(peer, &conn, remote, &r, &flat);
      RecordFetchSpan("recovery.fetch_recipe", t0, got);
      if (got && !flat) {
        int64_t fetched = 0, local = 0;
        stored = recipe_recover_(
            spi, remote, r,
            [&](const std::vector<RecipeEntry>& want, std::string* out) {
              int64_t t1 = TraceWallUs();
              bool ok = FetchChunks(peer, &conn, remote, want, out);
              RecordFetchSpan("recovery.fetch_chunks", t1, ok);
              return ok;
            },
            &fetched, &local);
        if (stored) {
          chunks_pulled_ += fetched;
          chunks_local_ += local;
        }
      }
    }
    if (!stored) {
      std::string staged = store_->NewTmpPath(spi);
      bool missing = false;
      int64_t t0 = TraceWallUs();
      bool got = DownloadToFile(peer, &conn, remote, staged, &missing);
      RecordFetchSpan("recovery.download", t0, got);
      if (!got) {
        FDFS_LOG_WARN("recovery: download %s failed", remote.c_str());
        all_ok = false;
        CloseFileTrace(t_file, false);
        continue;
      }
      if (missing) {  // deleted on the peer since the record was written
        files_skipped_++;
        CloseFileTrace(t_file, true);
        continue;
      }
      if (!StoreRecovered(remote, staged)) {
        all_ok = false;
        file_ok = false;
      }
    }
    if (!file_ok) {
      CloseFileTrace(t_file, false);
      continue;
    }
    std::string meta;
    if (FetchMetadata(peer, &conn, remote, &meta)) {
      auto local = LocalPath(store_->store_path(spi), remote);
      if (local.has_value()) {
        EnsureParentDirs(*local);
        std::string mtmp = *local + "-m.rec";
        FILE* f = fopen(mtmp.c_str(), "w");
        if (f != nullptr) {
          fwrite(meta.data(), 1, meta.size(), f);
          fclose(f);
          rename(mtmp.c_str(), (*local + "-m").c_str());
        }
      }
    }
    files_recovered_++;
    CloseFileTrace(t_file, true);
  }
  cur_trace_ = TraceCtx{};
  if (conn >= 0) close(conn);
  return all_ok && !stop_;
}

void RecoveryManager::CloseFileTrace(int64_t start_us, bool ok) {
  if (trace_ == nullptr || !cur_trace_.valid()) return;
  TraceSpan s;
  s.trace_id = cur_trace_.trace_id;
  s.span_id = cur_trace_.parent_span;  // the pre-allocated root id
  s.parent_id = 0;
  s.start_us = start_us;
  s.dur_us = TraceWallUs() - start_us;
  s.status = ok ? 0 : 5 /*EIO*/;
  s.SetName("recovery.file");
  trace_->Record(s);
  cur_trace_ = TraceCtx{};
}

}  // namespace fdfs
