#include "storage/admission.h"

#include <cstdio>

#include "common/protocol_gen.h"

namespace fdfs {

namespace {

// %.6g like sloeval's event details, so thresholds read identically in
// slo.breach and admission.tighten events.
std::string Fmt6g(double v) {
  char buf[64];
  std::snprintf(buf, sizeof(buf), "%.6g", v);
  return buf;
}

}  // namespace

const char* PriorityClassName(uint8_t cls) {
  switch (cls) {
    case kPriorityControl: return "control";
    case kPriorityInteractive: return "interactive";
    case kPriorityNormal: return "normal";
    case kPriorityBulk: return "bulk";
    default: return "background";
  }
}

uint8_t DefaultPriorityClass(uint8_t storage_cmd) {
  switch (static_cast<StorageCmd>(storage_cmd)) {
    // Observability/admin plane: always answer — it is how operators
    // (and the admission subsystem's own status op) see in.
    case StorageCmd::kStat:
    case StorageCmd::kTraceDump:
    case StorageCmd::kEventDump:
    case StorageCmd::kMetricsHistory:
    case StorageCmd::kHeatTop:
    case StorageCmd::kScrubStatus:
    case StorageCmd::kScrubKick:
    case StorageCmd::kEcStatus:
    case StorageCmd::kEcKick:
    case StorageCmd::kHealthStatus:
    case StorageCmd::kAdmissionStatus:
    case StorageCmd::kProfileCtl:
    case StorageCmd::kProfileDump:
    case StorageCmd::kActiveTest:
    case StorageCmd::kQueryFileInfo:
      return kPriorityControl;
    // Client reads survive to the last rung.
    case StorageCmd::kDownloadFile:
    case StorageCmd::kGetMetadata:
    case StorageCmd::kNearDups:
      return kPriorityInteractive;
    // Negotiated bulk ingest: the big-payload path, shed before plain
    // writes.
    case StorageCmd::kUploadRecipe:
    case StorageCmd::kUploadChunks:
      return kPriorityBulk;
    // Replication, recovery, and EC traffic is born background: peers
    // retry from their binlog cursors, so shedding it first trades
    // sync lag (bounded, measured, recoverable) for client latency.
    case StorageCmd::kSyncCreateFile:
    case StorageCmd::kSyncDeleteFile:
    case StorageCmd::kSyncUpdateFile:
    case StorageCmd::kSyncCreateLink:
    case StorageCmd::kSyncAppendFile:
    case StorageCmd::kSyncModifyFile:
    case StorageCmd::kSyncTruncateFile:
    case StorageCmd::kSyncQueryChunks:
    case StorageCmd::kSyncCreateRecipe:
    case StorageCmd::kFetchOnePathBinlog:
    case StorageCmd::kFetchRecipe:
    case StorageCmd::kFetchChunk:
    case StorageCmd::kEcRelease:
      return kPriorityBackground;
    default:
      return kPriorityNormal;  // client writes: uploads, appends, deletes
  }
}

uint8_t DefaultTrackerPriorityClass(uint8_t tracker_cmd) {
  switch (static_cast<TrackerCmd>(tracker_cmd)) {
    // The expensive observability dumps: a lagging single-loop tracker
    // sheds dashboards before it sheds beats or lookups.
    case TrackerCmd::kServerClusterStat:
    case TrackerCmd::kTraceDump:
    case TrackerCmd::kEventDump:
    case TrackerCmd::kMetricsHistory:
    case TrackerCmd::kProfileDump:
    case TrackerCmd::kHealthMatrix:
      return kPriorityBulk;
    default:
      // Beats, joins, sync negotiation, service queries, leader RPCs:
      // the cluster's control plane, never shed by default.
      return kPriorityControl;
  }
}

double AdmissionController::PressureScore(const AdmissionConfig& cfg,
                                          const AdmissionSignals& s) {
  // One active SLO breach reads as 1.0 — a sustained breach alone walks
  // the ladder up; multiple concurrent breaches push harder.
  double score = static_cast<double>(s.breaches_active);
  if (cfg.queue_depth_high > 0)
    score = std::max(score, static_cast<double>(s.queue_depth) /
                                static_cast<double>(cfg.queue_depth_high));
  if (cfg.loop_lag_high_ms > 0 && s.loop_lag_p99_ms >= 0)
    score = std::max(score, s.loop_lag_p99_ms / cfg.loop_lag_high_ms);
  if (cfg.inflight_high_bytes > 0)
    score = std::max(score, static_cast<double>(s.inflight_bytes) /
                                static_cast<double>(cfg.inflight_high_bytes));
  return score;
}

int AdmissionController::Tick(const AdmissionSignals& s) {
  if (!cfg_.enabled) return 0;
  double score = PressureScore(cfg_, s);
  ewma_ = have_ewma_ ? kAlpha * score + (1 - kAlpha) * ewma_ : score;
  have_ewma_ = true;
  pressure_milli_.store(static_cast<int64_t>(score * 1000),
                        std::memory_order_relaxed);
  ewma_milli_.store(static_cast<int64_t>(ewma_ * 1000),
                    std::memory_order_relaxed);
  int lvl = level_.load(std::memory_order_relaxed);
  if (ewma_ > cfg_.tighten_threshold && lvl < kMaxLevel) {
    level_.store(lvl + 1, std::memory_order_relaxed);
    tightens_.fetch_add(1, std::memory_order_relaxed);
    return +1;
  }
  if (ewma_ <= cfg_.relax_threshold && lvl > 0) {
    level_.store(lvl - 1, std::memory_order_relaxed);
    relaxes_.fetch_add(1, std::memory_order_relaxed);
    return -1;
  }
  return 0;
}

bool AdmissionController::AdmitOrShed(uint8_t cls, int64_t* retry_after_ms) {
  if (WouldAdmit(cls)) {
    admitted_.fetch_add(1, std::memory_order_relaxed);
    return true;
  }
  shed_.fetch_add(1, std::memory_order_relaxed);
  shed_class_[ClampClass(cls)].fetch_add(1, std::memory_order_relaxed);
  if (retry_after_ms) *retry_after_ms = this->retry_after_ms();
  return false;
}

const char* AdmissionController::level_name() const {
  switch (level()) {
    case 0: return "admit-all";
    case 1: return "shed-background";
    case 2: return "shed-bulk";
    default: return "reads-only";
  }
}

std::string AdmissionController::StatusJson(const char* role,
                                            int port) const {
  std::string out = "{\"role\":\"";
  out += role;
  out += "\",\"port\":" + std::to_string(port);
  out += ",\"enabled\":";
  out += cfg_.enabled ? "true" : "false";
  out += ",\"level\":" + std::to_string(level());
  out += ",\"level_name\":\"";
  out += level_name();
  out += "\",\"pressure\":" + Fmt6g(pressure_milli() / 1000.0);
  out += ",\"ewma\":" + Fmt6g(ewma_milli() / 1000.0);
  out += ",\"tighten_threshold\":" + Fmt6g(cfg_.tighten_threshold);
  out += ",\"relax_threshold\":" + Fmt6g(cfg_.relax_threshold);
  out += ",\"tightens\":" + std::to_string(tightens());
  out += ",\"relaxes\":" + std::to_string(relaxes());
  out += ",\"retry_after_ms\":" + std::to_string(retry_after_ms());
  out += ",\"admitted\":" + std::to_string(admitted());
  out += ",\"shed\":" + std::to_string(shed_total());
  out += ",\"shed_by_class\":{";
  for (int c = 0; c < kPriorityClassCount; ++c) {
    if (c) out += ",";
    out += "\"";
    out += PriorityClassName(static_cast<uint8_t>(c));
    out += "\":" + std::to_string(shed_by_class(c));
  }
  out += "}}";
  return out;
}

}  // namespace fdfs
