#include "storage/tracker_client.h"

#include <string.h>
#include <sys/stat.h>
#include <sys/statvfs.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>

#include "common/bytes.h"
#include "common/log.h"
#include "common/threadreg.h"
#include "common/net.h"
#include "common/protocol_gen.h"

namespace fdfs {

namespace {

// Tracker RPCs are tiny; cap the blocking timeout so daemon shutdown never
// waits out the full data-path network_timeout on a dead tracker.
constexpr int kTrackerRpcTimeoutMs = 5000;

void AppendInt64(std::string* out, int64_t v) {
  char buf[8];
  PutInt64BE(v, reinterpret_cast<uint8_t*>(buf));
  out->append(buf, 8);
}

bool Rpc(int fd, uint8_t cmd, const std::string& body, std::string* resp,
         uint8_t* status, int timeout_ms) {
  return NetRpc(fd, cmd, body, resp, status, 16 << 20, timeout_ms);
}

bool SplitAddr(const std::string& addr, std::string* host, int* port) {
  size_t colon = addr.rfind(':');
  if (colon == std::string::npos || colon == 0) return false;
  *host = addr.substr(0, colon);
  *port = atoi(addr.c_str() + colon + 1);
  return *port > 0;
}

}  // namespace

TrackerReporter::TrackerReporter(StorageConfig cfg, StatsSnapshotFn stats_fn,
                                 PeersCallback peers_cb)
    : cfg_(std::move(cfg)), stats_fn_(std::move(stats_fn)),
      peers_cb_(std::move(peers_cb)) {
  // A configured bind address IS this server's identity (required for
  // same-host clusters, where every daemon gets its own loopback IP —
  // upstream forbids two group members per IP for the same reason).
  if (!cfg_.bind_addr.empty() && cfg_.bind_addr != "0.0.0.0")
    my_ip_ = cfg_.bind_addr;
}

TrackerReporter::~TrackerReporter() { Stop(); }

void TrackerReporter::Start() {
  // Snapshot the persisted identity before ANY thread can rewrite it.
  {
    FILE* f = fopen((cfg_.base_path + "/data/.server_identity").c_str(), "r");
    if (f != nullptr) {
      char ip[64] = {0};
      int port = 0;
      if (fscanf(f, "%63s %d", ip, &port) == 2) {
        std::lock_guard<RankedMutex> lk(mu_);
        recorded_ip_ = ip;
        recorded_port_ = port;
      }
      fclose(f);
    }
  }
  for (const std::string& addr : cfg_.tracker_servers) {
    std::string host;
    int port;
    if (!SplitAddr(addr, &host, &port)) {
      FDFS_LOG_ERROR("bad tracker_server %s", addr.c_str());
      continue;
    }
    threads_.emplace_back(&TrackerReporter::ThreadMain, this, host, port);
  }
}

void TrackerReporter::Stop() {
  stop_ = true;
  for (auto& t : threads_)
    if (t.joinable()) t.join();
  threads_.clear();
}

std::string TrackerReporter::my_ip() const {
  std::lock_guard<RankedMutex> lk(mu_);
  return my_ip_.empty() ? "127.0.0.1" : my_ip_;
}

std::vector<PeerInfo> TrackerReporter::peers() const {
  std::lock_guard<RankedMutex> lk(mu_);
  return peers_;
}

void TrackerReporter::ReportSyncProgress(const std::string& dest_ip,
                                         int dest_port, int64_t ts) {
  // Cumulative latest-timestamp map, NOT a drain queue: every tracker's
  // beat sends the full current vector.  A drain queue would deliver each
  // report to whichever tracker thread flushed first and starve the
  // others' read routing (multi-tracker clusters).
  std::lock_guard<RankedMutex> lk(mu_);
  for (auto& r : pending_sync_reports_) {
    if (r.dest_ip == dest_ip && r.dest_port == dest_port) {
      r.ts = std::max(r.ts, ts);
      return;
    }
  }
  pending_sync_reports_.push_back({dest_ip, dest_port, ts});
}

bool TrackerReporter::ParsePeers(const std::string& body, bool* peers_changed,
                                 std::vector<HotTask>* hot_tasks) {
  if (body.size() < 8) return false;
  const uint8_t* p = reinterpret_cast<const uint8_t*>(body.data());
  int64_t count = GetInt64BE(p);
  const size_t rec = kIpAddressSize + 8 + 1;
  // Divide, don't multiply: count * rec could wrap size_t and pass the
  // bound check on a hostile length.
  if (count < 0 || static_cast<size_t>(count) > (body.size() - 8) / rec)
    return false;
  std::vector<PeerInfo> peers;
  for (int64_t i = 0; i < count; ++i) {
    const uint8_t* q = p + 8 + i * rec;
    PeerInfo pi;
    pi.ip = GetFixedField(q, kIpAddressSize);
    pi.port = static_cast<int>(GetInt64BE(q + kIpAddressSize));
    pi.status = q[kIpAddressSize + 8];
    peers.push_back(std::move(pi));
  }
  // Optional trailer: the group's elected trunk server (beat responses).
  // A zeroed trailer means "no trunk server right now" and MUST clear the
  // cache — keeping a dead address would burn a connect timeout on every
  // small upload forever.  Only a response with no trailer at all (JOIN)
  // leaves the cache untouched.
  size_t tail = 8 + static_cast<size_t>(count) * rec;
  bool have_trailer = body.size() >= tail + kIpAddressSize + 8;
  std::string tip;
  int tport = 0;
  int64_t tepoch = 0;
  // Placement trailer extension (append-only, prefix-tolerant like the
  // trunk fields): 1B group placement state + 8B placement epoch
  // version.  Absent on old trackers — keep the last value rather than
  // resetting, so a mixed-version tracker set cannot flap a draining
  // group back to accepting writes.
  bool have_state = body.size() >= tail + kIpAddressSize + 17;
  int gstate = 0;
  int64_t pversion = 0;
  if (have_trailer) {
    const uint8_t* q = p + tail;
    tip = GetFixedField(q, kIpAddressSize);
    tport = static_cast<int>(GetInt64BE(q + kIpAddressSize));
    if (body.size() >= tail + kIpAddressSize + 16)
      tepoch = GetInt64BE(q + kIpAddressSize + 8);
    if (have_state) {
      gstate = q[kIpAddressSize + 16];
      if (body.size() >= tail + kIpAddressSize + 25)
        pversion = GetInt64BE(q + kIpAddressSize + 17);
    }
    // Hot-task trailer (common/heatwire.h, ISSUE 20): replicate/drop
    // assignments for keys this node was elected to fan out.  Appended
    // after the placement fields; absent on old trackers and on beats
    // with nothing assigned here.
    size_t hot_off = tail + kIpAddressSize + 25;
    if (hot_tasks != nullptr && body.size() > hot_off)
      ParseHotTasks(p + hot_off, body.size() - hot_off, hot_tasks);
  }
  {
    std::lock_guard<RankedMutex> lk(mu_);
    if (peers_changed != nullptr) *peers_changed = peers != peers_;
    peers_ = peers;
    if (have_trailer) {
      trunk_ip_ = tip;
      trunk_port_ = tport;
      trunk_epoch_ = tepoch;
      if (have_state) {
        group_state_ = gstate;
        placement_version_ = pversion;
      }
    }
  }
  return true;
}

void TrackerReporter::NotifyPeersChanged() {
  std::vector<PeerInfo> peers;
  {
    std::lock_guard<RankedMutex> lk(mu_);
    peers = peers_;
  }
  if (peers_cb_) peers_cb_(peers);
}

std::pair<std::string, int> TrackerReporter::trunk_server() const {
  std::lock_guard<RankedMutex> lk(mu_);
  return {trunk_ip_, trunk_port_};
}

int64_t TrackerReporter::trunk_epoch() const {
  std::lock_guard<RankedMutex> lk(mu_);
  return trunk_epoch_;
}

int TrackerReporter::group_state() const {
  std::lock_guard<RankedMutex> lk(mu_);
  return group_state_;
}

int64_t TrackerReporter::placement_version() const {
  std::lock_guard<RankedMutex> lk(mu_);
  return placement_version_;
}

bool TrackerReporter::DoJoin(int fd, int64_t* chlog_off) {
  CheckIpChanged(fd);
  std::string body;
  PutFixedField(&body, cfg_.group_name, kGroupNameMaxLen);
  PutFixedField(&body, my_ip(), kIpAddressSize);
  AppendInt64(&body, cfg_.port);
  AppendInt64(&body, static_cast<int64_t>(cfg_.store_paths.size()));
  AppendInt64(&body, recovering_ ? 1 : 0);  // flags: bit0 = disk recovery
  std::string resp;
  uint8_t status;
  if (!Rpc(fd, static_cast<uint8_t>(TrackerCmd::kStorageJoin), body, &resp,
           &status, kTrackerRpcTimeoutMs) ||
      status != 0)
    return false;
  bool changed = false;
  if (!ParsePeers(resp, &changed)) return false;
  PersistIdentity();
  DoParameterReq(fd);
  // Rename cursors BEFORE workers spawn for renamed addresses.
  DoChangelogReq(fd, chlog_off);
  if (changed) NotifyPeersChanged();
  // During disk recovery the negotiation belongs to the recovery thread
  // (SYNC_DEST_QUERY with held promotion), not the join path.
  if (!recovering_) DoSyncDestReq(fd);
  return true;
}

void TrackerReporter::CheckIpChanged(int fd) {
  // Uses the identity snapshot from Start(), NOT the file: each tracker
  // thread must independently send the rename (PersistIdentity rewrites
  // the file after the first join, which would silence the others).
  std::string old_ip;
  {
    std::lock_guard<RankedMutex> lk(mu_);
    old_ip = recorded_ip_;
    if (recorded_port_ != cfg_.port) return;  // port change = new identity
  }
  if (old_ip.empty() || my_ip() == old_ip) return;
  FDFS_LOG_WARN("own IP changed %s -> %s: asking tracker to rewrite",
                old_ip.c_str(), my_ip().c_str());
  std::string body;
  PutFixedField(&body, cfg_.group_name, kGroupNameMaxLen);
  PutFixedField(&body, old_ip, kIpAddressSize);
  PutFixedField(&body, my_ip(), kIpAddressSize);
  AppendInt64(&body, cfg_.port);
  std::string resp;
  uint8_t status;
  Rpc(fd, static_cast<uint8_t>(TrackerCmd::kStorageReportIpChanged), body,
      &resp, &status, kTrackerRpcTimeoutMs);
  // ENOENT (already renamed / unknown) is fine — JOIN follows either way.
}

void TrackerReporter::PersistIdentity() {
  std::string path = cfg_.base_path + "/data/.server_identity";
  std::string tmp = path + ".tmp";
  FILE* f = fopen(tmp.c_str(), "w");
  if (f == nullptr) return;
  fprintf(f, "%s %d\n", my_ip().c_str(), cfg_.port);
  fclose(f);
  rename(tmp.c_str(), path.c_str());
}

void TrackerReporter::DoChangelogReq(int fd, int64_t* chlog_off) {
  std::string body(8, '\0');
  PutInt64BE(*chlog_off, reinterpret_cast<uint8_t*>(body.data()));
  std::string resp;
  uint8_t status;
  if (!Rpc(fd, static_cast<uint8_t>(TrackerCmd::kStorageChangelogReq), body,
           &resp, &status, kTrackerRpcTimeoutMs) ||
      status != 0 || resp.empty())
    return;
  *chlog_off += static_cast<int64_t>(resp.size());
  // Lines: "<ts> <group> <old_ip:port> <new_ip:port>" — rename our sync
  // cursors for renamed peers so their replication position survives.
  std::string sync_dir = cfg_.base_path + "/data/sync";
  size_t pos = 0;
  while (pos < resp.size()) {
    size_t nl = resp.find('\n', pos);
    std::string line = resp.substr(pos, nl == std::string::npos
                                            ? std::string::npos
                                            : nl - pos);
    pos = nl == std::string::npos ? resp.size() : nl + 1;
    char grp[64], olda[128], newa[128];
    long long ts;
    if (sscanf(line.c_str(), "%lld %63s %127s %127s", &ts, grp, olda,
               newa) != 4 ||
        cfg_.group_name != grp)
      continue;
    auto mark_name = [](std::string addr) {
      size_t colon = addr.rfind(':');
      if (colon != std::string::npos) addr[colon] = '_';
      return addr + ".mark";
    };
    std::string from = sync_dir + "/" + mark_name(olda);
    std::string to = sync_dir + "/" + mark_name(newa);
    struct stat st;
    if (stat(from.c_str(), &st) == 0 && stat(to.c_str(), &st) != 0) {
      if (rename(from.c_str(), to.c_str()) == 0)
        FDFS_LOG_INFO("renamed sync cursor %s -> %s (peer IP change)",
                      from.c_str(), to.c_str());
    }
  }
}

void TrackerReporter::DoSyncDestReq(int fd) {
  // Ask who should full-sync us (tracker side decides WAIT_SYNC→SYNCING→
  // ACTIVE; replication itself is source-driven, so the answer is
  // informational here — the negotiation is what arms the promotion).
  std::string body;
  PutFixedField(&body, cfg_.group_name, kGroupNameMaxLen);
  PutFixedField(&body, my_ip(), kIpAddressSize);
  AppendInt64(&body, cfg_.port);
  std::string resp;
  uint8_t status;
  if (!Rpc(fd, static_cast<uint8_t>(TrackerCmd::kStorageSyncDestReq), body,
           &resp, &status, kTrackerRpcTimeoutMs) ||
      status != 0)
    return;
  if (resp.size() >= kIpAddressSize + 16) {
    const uint8_t* p = reinterpret_cast<const uint8_t*>(resp.data());
    FDFS_LOG_INFO("full-sync source assigned: %s:%lld until_ts=%lld",
                  GetFixedField(p, kIpAddressSize).c_str(),
                  static_cast<long long>(GetInt64BE(p + kIpAddressSize)),
                  static_cast<long long>(GetInt64BE(p + kIpAddressSize + 8)));
  }
}

void TrackerReporter::DoParameterReq(int fd) {
  std::string resp;
  uint8_t status;
  if (!Rpc(fd, static_cast<uint8_t>(TrackerCmd::kStorageParameterReq), "",
           &resp, &status, kTrackerRpcTimeoutMs) ||
      status != 0)
    return;
  std::map<std::string, std::string> params;
  size_t pos = 0;
  while (pos < resp.size()) {
    size_t nl = resp.find('\n', pos);
    std::string line = resp.substr(pos, nl == std::string::npos
                                            ? std::string::npos
                                            : nl - pos);
    pos = nl == std::string::npos ? resp.size() : nl + 1;
    size_t eq = line.find('=');
    if (eq != std::string::npos && eq > 0)
      params[line.substr(0, eq)] = line.substr(eq + 1);
  }
  std::lock_guard<RankedMutex> lk(mu_);
  cluster_params_ = std::move(params);
}

std::map<std::string, std::string> TrackerReporter::cluster_params() const {
  std::lock_guard<RankedMutex> lk(mu_);
  return cluster_params_;
}

bool TrackerReporter::DoBeat(int fd, int64_t* chlog_off,
                             const std::string& tracker_addr) {
  std::string body;
  PutFixedField(&body, cfg_.group_name, kGroupNameMaxLen);
  PutFixedField(&body, my_ip(), kIpAddressSize);
  AppendInt64(&body, cfg_.port);
  int64_t stats[kBeatStatCount] = {0};
  if (stats_fn_) stats_fn_(stats);
  for (int i = 0; i < kBeatStatCount; ++i) AppendInt64(&body, stats[i]);
  // Health trailer rides the append-only region past the pinned stat
  // slots (the tracker reads min(available, kBeatStatCount) slots and
  // parses anything further as a versioned trailer; an older tracker
  // ignores it entirely).
  if (health_trailer_fn_) body += health_trailer_fn_();
  // Heat trailer after the health trailer (version bytes disambiguate;
  // the tracker's FindHeatTrailer skips a well-formed health trailer).
  if (heat_trailer_fn_) body += heat_trailer_fn_();
  std::string resp;
  uint8_t status;
  if (!Rpc(fd, static_cast<uint8_t>(TrackerCmd::kStorageBeat), body, &resp,
           &status, kTrackerRpcTimeoutMs))
    return false;
  if (status != 0) return false;  // tracker lost us: re-JOIN
  bool changed = false;
  std::vector<HotTask> hot_tasks;
  ParsePeers(resp, &changed, &hot_tasks);
  if (!hot_tasks.empty() && hot_tasks_fn_)
    hot_tasks_fn_(tracker_addr, hot_tasks);
  if (changed) {
    // A changed peer list may be a renamed peer: apply the changelog
    // first so its sync cursor is renamed before a fresh worker (with a
    // zero-position mark) would be spawned for the "new" address.
    DoChangelogReq(fd, chlog_off);
    NotifyPeersChanged();
  }

  // Send the current sync-progress vector (source-side, SURVEY §2.2
  // sync).  Copied, not drained — see ReportSyncProgress.
  std::vector<SyncProgress> reports;
  {
    std::lock_guard<RankedMutex> lk(mu_);
    reports = pending_sync_reports_;
  }
  for (const auto& r : reports) {
    std::string sbody;
    PutFixedField(&sbody, cfg_.group_name, kGroupNameMaxLen);
    PutFixedField(&sbody, my_ip(), kIpAddressSize);
    AppendInt64(&sbody, cfg_.port);
    PutFixedField(&sbody, r.dest_ip, kIpAddressSize);
    AppendInt64(&sbody, r.dest_port);
    AppendInt64(&sbody, r.ts);
    std::string sresp;
    uint8_t sstatus;
    Rpc(fd, static_cast<uint8_t>(TrackerCmd::kStorageSyncReport), sbody,
        &sresp, &sstatus, kTrackerRpcTimeoutMs);
  }
  return true;
}

bool TrackerReporter::DoDiskReport(int fd) {
  struct statvfs sv;
  int64_t total_mb = 0, free_mb = 0;
  if (statvfs(cfg_.store_paths[0].c_str(), &sv) == 0) {
    total_mb = static_cast<int64_t>(sv.f_blocks) * sv.f_frsize >> 20;
    free_mb = static_cast<int64_t>(sv.f_bavail) * sv.f_frsize >> 20;
  }
  std::string body;
  PutFixedField(&body, cfg_.group_name, kGroupNameMaxLen);
  PutFixedField(&body, my_ip(), kIpAddressSize);
  AppendInt64(&body, cfg_.port);
  AppendInt64(&body, total_mb);
  AppendInt64(&body, free_mb);
  std::string resp;
  uint8_t status;
  return Rpc(fd, static_cast<uint8_t>(TrackerCmd::kStorageReportDiskUsage),
             body, &resp, &status, kTrackerRpcTimeoutMs);
}

void TrackerReporter::ThreadMain(std::string host, int port) {
  ScopedThreadName ledger("reporter." + host);
  int fd = -1;
  bool joined = false;
  int64_t last_beat = 0, last_disk = 0;
  int64_t chlog_off = 0;  // per-tracker changelog resume cursor
  while (!stop_) {
    BeatThreadHeartbeat();  // 200ms cadence loop (watchdog enrollment)
    if (fd < 0) {
      std::string err;
      fd = TcpConnect(host, port, 3000, &err);
      if (fd < 0) {
        for (int i = 0; i < 20 && !stop_; ++i) usleep(100 * 1000);
        continue;
      }
      {
        std::lock_guard<RankedMutex> lk(mu_);
        if (my_ip_.empty()) my_ip_ = SockIp(fd);
      }
      joined = false;
    }
    int64_t now = time(nullptr);
    bool ok = true;
    if (!joined) {
      ok = DoJoin(fd, &chlog_off);
      if (ok) {
        joined = true;
        last_beat = now;
        FDFS_LOG_INFO("joined tracker %s:%d as %s:%d", host.c_str(), port,
                      my_ip().c_str(), cfg_.port);
        ok = DoDiskReport(fd);
        last_disk = now;
      }
    } else if (now - last_beat >= cfg_.heart_beat_interval_s) {
      ok = DoBeat(fd, &chlog_off, host + ":" + std::to_string(port));
      if (!ok) joined = false;  // status!=0 or IO error: rejoin
      last_beat = now;
    } else if (now - last_disk >= cfg_.stat_report_interval_s) {
      ok = DoDiskReport(fd);
      last_disk = now;
    }
    if (!ok && fd >= 0 && !joined) {
      close(fd);
      fd = -1;
      continue;
    }
    usleep(200 * 1000);
  }
  if (fd >= 0) {
    // Polite QUIT (reference: tracker_quit on shutdown).
    std::string resp;
    uint8_t status;
    Rpc(fd, static_cast<uint8_t>(TrackerCmd::kQuit), "", &resp, &status, 1000);
    close(fd);
  }
}

}  // namespace fdfs
