// Storage daemon configuration (reference: conf/storage.conf parsed by
// storage/storage_func.c:storage_load_from_conf_file()).
#pragma once

#include <string>
#include <vector>

#include "common/ini.h"

namespace fdfs {

struct StorageConfig {
  std::string group_name = "group1";
  std::string bind_addr;           // empty = all interfaces
  int port = 23000;
  std::string base_path;           // logs, stat, sync state
  std::vector<std::string> store_paths;  // store_path0..N (data roots)
  // Pre-created data-dir fan-out per store path.  NOTE: the subdir *spread*
  // inside file IDs is a protocol constant (always mod 256, see
  // common/fileid.h) so clients can validate IDs without knowing server
  // config; this knob only controls how much of the fan-out Init
  // pre-creates (the rest is mkdir'd lazily).
  int subdir_count_per_path = 256;
  int buff_size = 256 * 1024;      // chunked IO size
  int network_timeout_ms = 30000;
  // nio work threads (reference storage.conf:work_threads /
  // storage_nio.c): connections are distributed round-robin over this
  // many event loops.  Init() always spawns this many dedicated nio
  // threads (with 1, all connections share one nio thread; the main
  // loop only accepts).
  int work_threads = 4;
  // Sharded accept (ISSUE 18): each nio loop binds its own SO_REUSEPORT
  // listening socket and owns every connection it accepts — no
  // cross-loop handoff, accept pressure spread by the kernel.  When the
  // kernel refuses the option the daemon falls back to the single
  // main-loop acceptor with round-robin handoff (an anomaly notes the
  // fallback).  0 disables sharding outright.
  bool nio_reuseport = true;
  // dio pool size PER STORE PATH (reference storage.conf:
  // disk_writer_threads / storage_dio.c): chunk-store writes,
  // fingerprint RPCs, trunk allocation, and deletes run here.
  int disk_writer_threads = 2;
  // Accept-time connection cap (reference storage.conf:max_connections /
  // fast_task_queue.c — the task-buffer pool is the bound upstream; here
  // the cap is explicit).  Past the cap the daemon answers one EBUSY
  // response header and closes — a polite refusal the client surfaces as
  // a status error instead of ECONNRESET.  0 = unlimited.
  int max_connections = 256;
  std::vector<std::string> tracker_servers;  // "ip:port"
  int heart_beat_interval_s = 30;
  int stat_report_interval_s = 60;
  int sync_interval_ms = 100;      // binlog tail poll when idle
  std::string dedup_mode = "none"; // none | cpu | sidecar
  std::string dedup_sidecar;       // unix socket path when mode=sidecar
  // Chunk-level dedup threshold: uploads >= this many bytes are CDC-
  // chunked into the content-addressed chunk store (recipe file on disk);
  // smaller files use whole-file dedup.  0 disables chunking.
  int64_t dedup_chunk_threshold = 64 * 1024;
  // Segment size for streaming fingerprint RPCs (CDC restarts per
  // segment so a multi-GB upload never needs a contiguous buffer).
  int64_t dedup_segment_bytes = 64LL * 1024 * 1024;
  // Negotiated-upload session lifetime: a client that sent
  // UPLOAD_RECIPE but never completed UPLOAD_CHUNKS holds pins on the
  // chunks its bitmap reported present; the sweep timer aborts (and
  // unpins) sessions older than this, so a vanished client can never
  // leak pins.  Must cover the client's think time between the two
  // requests plus one payload upload.
  int upload_session_timeout_s = 30;
  std::string log_level = "info";
  // Optional file sink (empty = stderr) with size/day rotation
  // (reference: logger.c; base_path-relative paths allowed).
  std::string log_file;
  int64_t log_rotate_size = 256LL << 20;
  // Per-request access log (storage.conf:use_access_log): op, client ip,
  // status, bytes, cost in µs — logs/access.log.
  bool use_access_log = false;
  // Distributed tracing (common/trace.h): capacity of the span ring
  // buffer dumped via StorageCmd::TRACE_DUMP, and the slow-request
  // threshold — a request slower than this is span-retained even when
  // untraced and logged as one structured JSON line.  0 disables the
  // slow gate (traced requests still record).
  int trace_buffer_size = 4096;
  int64_t slow_request_threshold_ms = 1000;
  // Integrity engine (storage/scrub.h).  scrub_interval_s: cadence of
  // the background verify+repair+GC pass (0 = no periodic passes;
  // SCRUB_KICK still forces one).  scrub_bandwidth_mb_s: verify read
  // pace so scrubbing never starves foreground IO (0 = unlimited).
  // chunk_gc_grace_s: how long a zero-ref chunk's bytes stay on disk
  // before a GC pass may reclaim them (0 = unlink eagerly on delete,
  // the pre-scrubber behavior).
  int scrub_interval_s = 86400;
  int scrub_bandwidth_mb_s = 0;
  int64_t chunk_gc_grace_s = 0;
  // Slab packing (storage/slabstore.h; OPERATIONS.md "Slab packing &
  // compaction"): chunks below slab_chunk_threshold and encoded
  // recipes below slab_recipe_threshold are appended into
  // slab_size_mb slab files under <store_path>/data/slabs/ instead of
  // per-object inodes — the billion-small-files layout.  Thresholds of
  // 0 disable packing for that class (both 0 = flat layout only).
  // slab_compact_min_dead_pct: a slab becomes a compaction victim once
  // deletes mark that share of its bytes dead (the scrub pass drives
  // paced compaction).
  int64_t slab_chunk_threshold = 64 * 1024;
  int64_t slab_recipe_threshold = 64 * 1024;
  int slab_size_mb = 64;
  int slab_compact_min_dead_pct = 25;
  // Hot-chunk read cache (per store path): bounded LRU of chunk
  // payloads consulted by DOWNLOAD_FILE / FETCH_CHUNK, invalidated on
  // quarantine and GC unlink (OPERATIONS.md "Read path, caching &
  // parallel downloads").  0 disables it.
  int read_cache_mb = 64;
  // Flight recorder (common/eventlog.h): capacity of the bounded ring
  // of structured cluster events dumped via StorageCmd::EVENT_DUMP and
  // on SIGUSR1 (OPERATIONS.md "Saturation & flight recorder").
  int event_buffer_size = 1024;
  // Telemetry history + SLOs + heat (OPERATIONS.md "Telemetry history,
  // SLOs & heat").  metrics_journal_mb: on-disk cap of the metrics
  // history ring (common/metrog.h) dumped via METRICS_HISTORY; 0
  // disables journaling.  slo_eval_interval_s: cadence of the journal
  // tick AND the SLO rule evaluation (common/sloeval.h); 0 disables
  // both.  slo_rules_file: optional conf/slo.conf-style override of the
  // compiled-in rule table (empty = defaults).  heat_top_k: tracked
  // keys per stripe of the hot-file sketch (common/heatsketch.h)
  // behind HEAT_TOP; 0 disables heat telemetry.
  int metrics_journal_mb = 8;
  int slo_eval_interval_s = 5;
  std::string slo_rules_file;
  int heat_top_k = 32;
  // Erasure-coded cold tier (storage/ecstore.h; OPERATIONS.md
  // "Erasure-coded cold tier").  ec_k/ec_m: RS(k, m) stripe geometry —
  // ec_k = 0 (default) disables demotion entirely (existing stripes
  // still serve, repair, and drain).  ec_demote_age_s: chunk payload
  // mtime age before scrub stage 5 may demote it.  ec_bandwidth_mb_s:
  // demote/repair IO pace, a SEPARATE token bucket from
  // scrub_bandwidth_mb_s (0 = unlimited).
  int ec_k = 0;
  int ec_m = 2;
  int64_t ec_demote_age_s = 7 * 86400;
  int ec_bandwidth_mb_s = 0;
  // Sampling-profiler ceiling (common/profiler.h; OPERATIONS.md
  // "Profiling & the thread ledger"): the maximum PROFILE_CTL sampling
  // rate this daemon will arm.  0 (the default) disables the profiler
  // entirely — no signal handler, no slab, PROFILE_CTL answers ENOTSUP.
  int profile_max_hz = 0;
  // Gray-failure health layer (common/healthmon.h; OPERATIONS.md
  // "Health, probes & gray failure").  health_probe_interval_s: cadence
  // of the active probe loop — ACTIVE_TEST pings to the trackers + the
  // group's ACTIVE peers plus a per-store-path disk probe (4 KB
  // tmp-write+fsync, then read back); 0 disables active probing (the
  // passive NetRpc table and watchdog still run).
  // probe_slow_threshold_ms: a disk probe slower than this records a
  // disk.gray flight-recorder event and halves the node's gray score.
  // watchdog_stall_threshold_ms: a registered daemon thread whose
  // heartbeat is older than this is reported stalled (watchdog.stall
  // event + gauge + gray score); 0 disables the watchdog.
  // watchdog_inject_stall_ms: DEBUG — spawn a thread that beats once
  // then sleeps forever, guaranteeing one watchdog trip (tests only).
  int health_probe_interval_s = 30;
  int probe_slow_threshold_ms = 1000;
  int watchdog_stall_threshold_ms = 5000;
  int watchdog_inject_stall_ms = 0;
  // Admission control & request QoS (storage/admission.h; OPERATIONS.md
  // "Overload control & request QoS").  admission_control gates the
  // whole subsystem (requests are still priority-classified and counted
  // when off, but nothing is shed).  The ladder moves one rung per
  // metrics tick when the pressure EWMA crosses admission_tighten_pct /
  // admission_relax_pct (percent of the 1.0 "at the configured limit"
  // score; relax must sit below tighten — that gap is the anti-flap
  // hysteresis band).  The *_high knobs are the normalization points
  // where each raw signal reads as 100% pressure: total dio jobs
  // pending, reactor loop-lag p99, and admitted-but-unanswered request
  // bytes.  admission_retry_after_ms is the base EBUSY backoff hint;
  // the wire carries base x current level.
  bool admission_control = true;
  int admission_tighten_pct = 90;
  int admission_relax_pct = 45;
  int64_t admission_queue_depth_high = 64;
  int64_t admission_loop_lag_high_ms = 100;
  int64_t admission_inflight_high_bytes = 256LL << 20;
  int64_t admission_retry_after_ms = 500;
  // Config values Load() silently clamped or corrected — surfaced as
  // "config.anomaly" flight-recorder events at startup so a daemon
  // running on not-what-the-operator-wrote config is diagnosable.
  std::vector<std::string> anomalies;

  // Parse + validate; false with *error on problems.
  bool Load(const IniConfig& ini, std::string* error);
};

}  // namespace fdfs
