#include "storage/ecstore.h"

#include <dirent.h>
#include <fcntl.h>
#include <string.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cerrno>
#include <cstdio>

#include "common/bytes.h"
#include "common/eventlog.h"
#include "common/fsutil.h"
#include "common/gf256.h"
#include "common/log.h"

namespace fdfs {

namespace {

constexpr char kShardMagic[8] = {'F', 'D', 'F', 'S', 'E', 'C', 'S', '1'};
constexpr char kManifestMagic[8] = {'F', 'D', 'F', 'S', 'E', 'C', 'M', '1'};
constexpr size_t kShardHeader = 52;
constexpr size_t kManifestFixed = 40;
constexpr size_t kManifestPerChunk = 37;

// 256x256 product table: field mul as one gather instead of two log
// lookups + an add — the XOR inner loops below touch it per byte.
// Built once, 64 KiB, read-only afterwards.
const uint8_t* MulTable() {
  static const uint8_t* table = [] {
    auto* t = new uint8_t[256 * 256];
    for (int a = 0; a < 256; ++a)
      for (int b = 0; b < 256; ++b)
        t[a * 256 + b] = gf256::Mul(static_cast<uint8_t>(a),
                                    static_cast<uint8_t>(b));
    return t;
  }();
  return table;
}

// out ^= c * src over shard_len bytes (the RS inner loop).
void XorMulInto(uint8_t c, const uint8_t* src, uint8_t* out, int64_t len) {
  if (c == 0) return;
  const uint8_t* row = MulTable() + static_cast<size_t>(c) * 256;
  if (c == 1) {
    for (int64_t i = 0; i < len; ++i) out[i] ^= src[i];
    return;
  }
  for (int64_t i = 0; i < len; ++i) out[i] ^= row[src[i]];
}

// Gauss-Jordan inverse over GF(2^8).  k <= 255 and typically <= 32, so
// the cubic cost is microseconds; singular is impossible for Cauchy
// submatrices (any-k property) — hitting it means corrupted indices.
bool InvertMatrix(std::vector<uint8_t>* a_io, int k) {
  std::vector<uint8_t>& a = *a_io;
  std::vector<uint8_t> inv(static_cast<size_t>(k) * k, 0);
  for (int i = 0; i < k; ++i) inv[i * k + i] = 1;
  for (int col = 0; col < k; ++col) {
    int pivot = -1;
    for (int r = col; r < k; ++r)
      if (a[r * k + col] != 0) { pivot = r; break; }
    if (pivot < 0) return false;
    if (pivot != col) {
      for (int c = 0; c < k; ++c) {
        std::swap(a[col * k + c], a[pivot * k + c]);
        std::swap(inv[col * k + c], inv[pivot * k + c]);
      }
    }
    uint8_t scale = gf256::Inv(a[col * k + col]);
    for (int c = 0; c < k; ++c) {
      a[col * k + c] = gf256::Mul(scale, a[col * k + c]);
      inv[col * k + c] = gf256::Mul(scale, inv[col * k + c]);
    }
    for (int r = 0; r < k; ++r) {
      uint8_t f = a[r * k + col];
      if (r == col || f == 0) continue;
      for (int c = 0; c < k; ++c) {
        a[r * k + c] ^= gf256::Mul(f, a[col * k + c]);
        inv[r * k + c] ^= gf256::Mul(f, inv[col * k + c]);
      }
    }
  }
  a = std::move(inv);
  return true;
}

bool WriteFileDurable(const std::string& path, const std::string& buf,
                      std::string* err) {
  std::string tmp = path + ".tmp";
  int fd = open(tmp.c_str(), O_CREAT | O_WRONLY | O_TRUNC, 0644);
  if (fd < 0) {
    *err = "open " + tmp + ": " + strerror(errno);
    return false;
  }
  size_t off = 0;
  while (off < buf.size()) {
    ssize_t w = write(fd, buf.data() + off, buf.size() - off);
    if (w <= 0) {
      *err = "write " + tmp + ": " + strerror(errno);
      close(fd);
      unlink(tmp.c_str());
      return false;
    }
    off += static_cast<size_t>(w);
  }
  bool ok = fsync(fd) == 0;
  close(fd);
  if (!ok || rename(tmp.c_str(), path.c_str()) != 0) {
    *err = "commit " + path + ": " + strerror(errno);
    unlink(tmp.c_str());
    return false;
  }
  return true;
}

}  // namespace

// -- RS codec -------------------------------------------------------------

std::vector<std::string> RsEncode(const std::vector<std::string>& data,
                                  int m) {
  int k = static_cast<int>(data.size());
  int64_t shard_len = k > 0 ? static_cast<int64_t>(data[0].size()) : 0;
  std::vector<std::string> parity(static_cast<size_t>(m),
                                  std::string(shard_len, '\0'));
  for (int j = 0; j < m; ++j) {
    auto* out = reinterpret_cast<uint8_t*>(parity[j].data());
    for (int i = 0; i < k; ++i)
      XorMulInto(gf256::CauchyCoeff(k, j, i),
                 reinterpret_cast<const uint8_t*>(data[i].data()), out,
                 shard_len);
  }
  return parity;
}

bool RsReconstruct(std::vector<std::string>* shards, int k, int m,
                   int64_t shard_len) {
  std::vector<std::string>& sh = *shards;
  if (static_cast<int>(sh.size()) != k + m) return false;
  // Pick the first k present shards as the decode basis (any k work —
  // the Cauchy any-k property).
  std::vector<int> present;
  for (int s = 0; s < k + m && static_cast<int>(present.size()) < k; ++s)
    if (!sh[s].empty()) present.push_back(s);
  if (static_cast<int>(present.size()) < k) return false;
  bool data_missing = false;
  for (int i = 0; i < k; ++i)
    if (sh[i].empty()) data_missing = true;
  std::vector<std::string> data(static_cast<size_t>(k));
  if (!data_missing) {
    for (int i = 0; i < k; ++i) data[i] = sh[i];
  } else {
    // rows of [I; C] for the present basis, inverted
    std::vector<uint8_t> mat(static_cast<size_t>(k) * k, 0);
    for (int r = 0; r < k; ++r) {
      int s = present[r];
      for (int i = 0; i < k; ++i)
        mat[r * k + i] = s < k ? (i == s ? 1 : 0)
                               : gf256::CauchyCoeff(k, s - k, i);
    }
    if (!InvertMatrix(&mat, k)) return false;
    for (int i = 0; i < k; ++i) {
      if (!sh[i].empty()) {
        data[i] = sh[i];
        continue;
      }
      data[i].assign(static_cast<size_t>(shard_len), '\0');
      auto* out = reinterpret_cast<uint8_t*>(data[i].data());
      for (int r = 0; r < k; ++r)
        XorMulInto(mat[i * k + r],
                   reinterpret_cast<const uint8_t*>(sh[present[r]].data()),
                   out, shard_len);
    }
  }
  for (int i = 0; i < k; ++i)
    if (sh[i].empty()) sh[i] = data[i];
  // Missing parity shards re-encode from the (now complete) data rows.
  for (int j = 0; j < m; ++j) {
    if (!sh[k + j].empty()) continue;
    sh[k + j].assign(static_cast<size_t>(shard_len), '\0');
    auto* out = reinterpret_cast<uint8_t*>(sh[k + j].data());
    for (int i = 0; i < k; ++i)
      XorMulInto(gf256::CauchyCoeff(k, j, i),
                 reinterpret_cast<const uint8_t*>(data[i].data()), out,
                 shard_len);
  }
  return true;
}

// -- store ----------------------------------------------------------------

EcStore::EcStore(std::string dir, int k, int m)
    : dir_(std::move(dir)), k_(k), m_(m) {
  // ChunkStore mounts this before its data/ tree necessarily exists
  // (first boot on a fresh store path) — own the whole prefix.
  MakeDirs(dir_);
}

std::string EcStore::ShardPath(int64_t stripe_id, int shard_idx) const {
  char buf[64];
  snprintf(buf, sizeof(buf), "/%010lld.s%02d",
           static_cast<long long>(stripe_id), shard_idx);
  return dir_ + buf;
}

std::string EcStore::ManifestPath(int64_t stripe_id) const {
  char buf[64];
  snprintf(buf, sizeof(buf), "/%010lld.mft",
           static_cast<long long>(stripe_id));
  return dir_ + buf;
}

int64_t EcStore::Rescan() {
  std::lock_guard<RankedMutex> lk(mu_);
  MakeDirs(dir_);
  stripes_.clear();
  index_.clear();
  next_stripe_id_ = 0;
  std::vector<std::string> shard_files;
  DIR* d = opendir(dir_.c_str());
  if (d != nullptr) {
    struct dirent* de;
    while ((de = readdir(d)) != nullptr) {
      std::string name = de->d_name;
      if (name.size() == 14 &&
          name.compare(name.size() - 4, 4, ".mft") == 0) {
        int64_t id = strtoll(name.c_str(), nullptr, 10);
        std::string buf;
        if (!ReadWholeFile(dir_ + "/" + name, &buf) ||
            buf.size() < kManifestFixed + 4 ||
            memcmp(buf.data(), kManifestMagic, 8) != 0) {
          FDFS_LOG_WARN("ec: unreadable manifest %s ignored", name.c_str());
          continue;
        }
        const uint8_t* p = reinterpret_cast<const uint8_t*>(buf.data());
        uint32_t crc = GetInt32BE(p + buf.size() - 4);
        if (Crc32(buf.data(), buf.size() - 4) != crc) {
          FDFS_LOG_WARN("ec: manifest %s failed crc — stripe ignored "
                        "(shards kept for forensics)", name.c_str());
          continue;
        }
        Stripe s;
        s.k = static_cast<int>(GetInt32BE(p + 8));
        s.m = static_cast<int>(GetInt32BE(p + 12));
        s.shard_len = GetInt64BE(p + 16);
        s.data_len = GetInt64BE(p + 24);
        int64_t count = GetInt64BE(p + 32);
        // Drain mode (ec_k = 0 with stripes on disk): adopt the on-disk
        // geometry so existing stripes stay readable; EncodeStripe still
        // refuses, so the tier only shrinks.
        if (k_ == 0 && s.k > 0 && s.k + s.m <= 255) {
          k_ = s.k;
          m_ = s.m;
          drained_ = true;
        }
        if (s.k != k_ || s.m != m_) {
          FDFS_LOG_ERROR("ec: stripe %lld has geometry %d+%d but this "
                         "daemon runs %d+%d — stripe ignored (set ec_k/"
                         "ec_m back, or drain before re-gearing)",
                         static_cast<long long>(id), s.k, s.m, k_, m_);
          continue;
        }
        if (count < 0 ||
            buf.size() !=
                kManifestFixed +
                    static_cast<size_t>(count) * kManifestPerChunk + 4)
          continue;
        for (int64_t c = 0; c < count; ++c) {
          const uint8_t* rec = p + kManifestFixed + c * kManifestPerChunk;
          ChunkSlot slot;
          slot.digest_hex = BytesToHex(rec, 20);
          slot.offset = GetInt64BE(rec + 20);
          slot.length = GetInt64BE(rec + 28);
          slot.dead = rec[36] != 0;
          s.chunks.push_back(std::move(slot));
        }
        for (size_t c = 0; c < s.chunks.size(); ++c)
          if (!s.chunks[c].dead)
            index_[s.chunks[c].digest_hex] =
                Loc{id, static_cast<int32_t>(c)};
        if (id >= next_stripe_id_) next_stripe_id_ = id + 1;
        stripes_[id] = std::move(s);
      } else if (name.size() > 4 && name[0] != '.' &&
                 name.find(".s") == 10) {
        shard_files.push_back(name);
      }
    }
    closedir(d);
  }
  // Orphan shard files — a crash before the manifest commit.  Shards of
  // a manifest that failed CRC are NOT orphans (the id is known): those
  // stay for the operator / a future repair pass.
  int64_t orphans = 0;
  for (const std::string& name : shard_files) {
    int64_t id = strtoll(name.c_str(), nullptr, 10);
    struct stat st;
    if (stat(ManifestPath(id).c_str(), &st) != 0) {
      unlink((dir_ + "/" + name).c_str());
      ++orphans;
      if (id >= next_stripe_id_) next_stripe_id_ = id + 1;
    }
  }
  RecountLocked();
  if (!stripes_.empty() || orphans > 0)
    FDFS_LOG_INFO("ec store: %zu stripes, %zu live chunks, %lld orphan "
                  "shard files collected",
                  stripes_.size(), index_.size(),
                  static_cast<long long>(orphans));
  return static_cast<int64_t>(stripes_.size());
}

void EcStore::RecountLocked() {
  int64_t chunks = 0, data = 0, physical = 0;
  for (const auto& [id, s] : stripes_) {
    (void)id;
    for (const ChunkSlot& c : s.chunks) {
      if (c.dead) continue;
      ++chunks;
      data += c.length;
    }
    physical += static_cast<int64_t>(s.k + s.m) *
                (s.shard_len + static_cast<int64_t>(kShardHeader));
  }
  stripes_gauge_.store(static_cast<int64_t>(stripes_.size()));
  chunks_gauge_.store(chunks);
  data_bytes_gauge_.store(data);
  parity_bytes_gauge_.store(physical > data ? physical - data : 0);
}

bool EcStore::WriteShardLocked(int64_t stripe_id, const Stripe& s, int idx,
                               const std::string& payload,
                               std::string* err) const {
  std::string buf(kShardHeader, '\0');
  memcpy(buf.data(), kShardMagic, 8);
  auto* p = reinterpret_cast<uint8_t*>(buf.data());
  PutInt64BE(stripe_id, p + 8);
  PutInt32BE(static_cast<uint32_t>(idx), p + 16);
  PutInt32BE(static_cast<uint32_t>(s.k), p + 20);
  PutInt32BE(static_cast<uint32_t>(s.m), p + 24);
  PutInt64BE(s.shard_len, p + 28);
  PutInt64BE(s.data_len, p + 36);
  PutInt32BE(Crc32(payload.data(), payload.size()), p + 44);
  PutInt32BE(Crc32(buf.data(), 48), p + 48);
  buf += payload;
  return WriteFileDurable(ShardPath(stripe_id, idx), buf, err);
}

bool EcStore::WriteManifestLocked(int64_t stripe_id, const Stripe& s,
                                  std::string* err) const {
  std::string buf(kManifestFixed, '\0');
  memcpy(buf.data(), kManifestMagic, 8);
  auto* p = reinterpret_cast<uint8_t*>(buf.data());
  PutInt32BE(static_cast<uint32_t>(s.k), p + 8);
  PutInt32BE(static_cast<uint32_t>(s.m), p + 12);
  PutInt64BE(s.shard_len, p + 16);
  PutInt64BE(s.data_len, p + 24);
  PutInt64BE(static_cast<int64_t>(s.chunks.size()), p + 32);
  for (const ChunkSlot& c : s.chunks) {
    std::string raw;
    HexToBytes(c.digest_hex, &raw);
    raw.resize(20, '\0');
    buf += raw;
    uint8_t num[8];
    PutInt64BE(c.offset, num);
    buf.append(reinterpret_cast<char*>(num), 8);
    PutInt64BE(c.length, num);
    buf.append(reinterpret_cast<char*>(num), 8);
    buf.push_back(c.dead ? '\x01' : '\x00');
  }
  uint8_t crc[4];
  PutInt32BE(Crc32(buf.data(), buf.size()), crc);
  buf.append(reinterpret_cast<char*>(crc), 4);
  return WriteFileDurable(ManifestPath(stripe_id), buf, err);
}

int64_t EcStore::EncodeStripe(
    const std::vector<std::pair<std::string, std::string>>& chunks,
    std::string* err) {
  if (chunks.empty()) {
    *err = "empty stripe";
    return -1;
  }
  std::lock_guard<RankedMutex> lk(mu_);
  if (k_ <= 0 || m_ <= 0 || drained_) {
    *err = "ec tier is read-only (ec_k = 0: drain mode)";
    return -1;
  }
  Stripe s;
  s.k = k_;
  s.m = m_;
  for (const auto& [dig, payload] : chunks) {
    ChunkSlot slot;
    slot.digest_hex = dig;
    slot.offset = s.data_len;
    slot.length = static_cast<int64_t>(payload.size());
    s.data_len += slot.length;
    s.chunks.push_back(std::move(slot));
  }
  s.shard_len = (s.data_len + k_ - 1) / k_;
  if (s.shard_len == 0) s.shard_len = 1;  // degenerate all-empty chunks
  // Concatenate + split into k data shards (zero-padded tail).
  std::vector<std::string> data(static_cast<size_t>(k_),
                                std::string(s.shard_len, '\0'));
  {
    int64_t off = 0;
    for (const auto& [dig, payload] : chunks) {
      (void)dig;
      for (size_t i = 0; i < payload.size(); ++i, ++off)
        data[off / s.shard_len][off % s.shard_len] = payload[i];
    }
  }
  std::vector<std::string> parity = RsEncode(data, m_);
  int64_t id = next_stripe_id_++;
  for (int i = 0; i < k_; ++i)
    if (!WriteShardLocked(id, s, i, data[i], err)) return -1;
  for (int j = 0; j < m_; ++j)
    if (!WriteShardLocked(id, s, k_ + j, parity[j], err)) return -1;
  // Manifest rename = commit.  Before it, the shard files are invisible
  // to Rescan; after it, the stripe is fully durable.
  if (!WriteManifestLocked(id, s, err)) return -1;
  for (size_t c = 0; c < s.chunks.size(); ++c)
    index_[s.chunks[c].digest_hex] = Loc{id, static_cast<int32_t>(c)};
  stripes_[id] = std::move(s);
  RecountLocked();
  if (events_ != nullptr)
    events_->Record(EventSeverity::kInfo, "ec.stripe_encoded",
                    std::to_string(id),
                    "chunks=" + std::to_string(chunks.size()) + " bytes=" +
                        std::to_string(stripes_[id].data_len));
  return id;
}

bool EcStore::ReadShardLocked(int64_t stripe_id, const Stripe& s, int idx,
                              std::string* out) const {
  std::string buf;
  if (!ReadWholeFile(ShardPath(stripe_id, idx), &buf)) return false;
  if (buf.size() != kShardHeader + static_cast<size_t>(s.shard_len) ||
      memcmp(buf.data(), kShardMagic, 8) != 0)
    return false;
  const uint8_t* p = reinterpret_cast<const uint8_t*>(buf.data());
  if (GetInt32BE(p + 48) != Crc32(buf.data(), 48)) return false;
  if (GetInt64BE(p + 8) != stripe_id ||
      static_cast<int>(GetInt32BE(p + 16)) != idx ||
      GetInt64BE(p + 28) != s.shard_len)
    return false;
  if (GetInt32BE(p + 44) !=
      Crc32(buf.data() + kShardHeader, static_cast<size_t>(s.shard_len)))
    return false;
  out->assign(buf, kShardHeader, static_cast<size_t>(s.shard_len));
  return true;
}

bool EcStore::LoadDataShardsLocked(int64_t stripe_id, const Stripe& s,
                                   std::vector<std::string>* data) const {
  std::vector<std::string> shards(static_cast<size_t>(s.k + s.m));
  int present = 0;
  for (int i = 0; i < s.k + s.m && present < s.k; ++i)
    if (ReadShardLocked(stripe_id, s, i, &shards[i])) ++present;
  if (present < s.k) return false;
  if (!RsReconstruct(&shards, s.k, s.m, s.shard_len)) return false;
  data->assign(shards.begin(), shards.begin() + s.k);
  return true;
}

bool EcStore::Has(const std::string& digest_hex) const {
  std::lock_guard<RankedMutex> lk(mu_);
  return index_.find(digest_hex) != index_.end();
}

bool EcStore::ReadChunk(const std::string& digest_hex,
                        std::string* out) const {
  std::lock_guard<RankedMutex> lk(mu_);
  auto it = index_.find(digest_hex);
  if (it == index_.end()) return false;
  const Stripe& s = stripes_.at(it->second.stripe_id);
  const ChunkSlot& c = s.chunks[static_cast<size_t>(it->second.slot)];
  // Healthy path: offset math over the 1-2 data shard files that cover
  // [offset, offset+length), no field arithmetic.
  out->resize(static_cast<size_t>(c.length));
  bool ok = true;
  {
    int64_t off = c.offset, got = 0;
    std::string shard;
    int cached_idx = -1;
    while (got < c.length && ok) {
      int idx = static_cast<int>(off / s.shard_len);
      int64_t in_shard = off % s.shard_len;
      int64_t take = s.shard_len - in_shard;
      if (take > c.length - got) take = c.length - got;
      if (idx != cached_idx) {
        ok = ReadShardLocked(it->second.stripe_id, s, idx, &shard);
        cached_idx = idx;
      }
      if (ok) memcpy(out->data() + got, shard.data() + in_shard,
                     static_cast<size_t>(take));
      got += take;
      off += take;
    }
  }
  if (ok && Sha1(out->data(), out->size()).Hex() == digest_hex) return true;
  // Shard lost or bytes rotted: decode the stripe from parity.
  std::vector<std::string> data;
  if (!LoadDataShardsLocked(it->second.stripe_id, s, &data)) return false;
  for (int64_t i = 0; i < c.length; ++i) {
    int64_t off = c.offset + i;
    (*out)[static_cast<size_t>(i)] =
        data[static_cast<size_t>(off / s.shard_len)]
            [static_cast<size_t>(off % s.shard_len)];
  }
  return Sha1(out->data(), out->size()).Hex() == digest_hex;
}

bool EcStore::ReadChunkSlice(const std::string& digest_hex, int64_t offset,
                             int64_t len, char* dst) const {
  std::lock_guard<RankedMutex> lk(mu_);
  auto it = index_.find(digest_hex);
  if (it == index_.end()) return false;
  const Stripe& s = stripes_.at(it->second.stripe_id);
  const ChunkSlot& c = s.chunks[static_cast<size_t>(it->second.slot)];
  if (offset < 0 || len < 0 || offset + len > c.length) return false;
  std::string shard;
  int cached_idx = -1;
  bool ok = true;
  int64_t off = c.offset + offset, got = 0;
  while (got < len && ok) {
    int idx = static_cast<int>(off / s.shard_len);
    int64_t in_shard = off % s.shard_len;
    int64_t take = s.shard_len - in_shard;
    if (take > len - got) take = len - got;
    if (idx != cached_idx) {
      ok = ReadShardLocked(it->second.stripe_id, s, idx, &shard);
      cached_idx = idx;
    }
    if (ok) memcpy(dst + got, shard.data() + in_shard,
                   static_cast<size_t>(take));
    got += take;
    off += take;
  }
  if (ok) return true;
  std::vector<std::string> data;
  if (!LoadDataShardsLocked(it->second.stripe_id, s, &data)) return false;
  for (int64_t i = 0; i < len; ++i) {
    int64_t o = c.offset + offset + i;
    dst[i] = data[static_cast<size_t>(o / s.shard_len)]
                 [static_cast<size_t>(o % s.shard_len)];
  }
  return true;
}

bool EcStore::MarkDead(const std::string& digest_hex,
                       int64_t* reclaimed_bytes) {
  std::lock_guard<RankedMutex> lk(mu_);
  auto it = index_.find(digest_hex);
  if (it == index_.end()) return false;
  int64_t id = it->second.stripe_id;
  Stripe& s = stripes_[id];
  s.chunks[static_cast<size_t>(it->second.slot)].dead = true;
  index_.erase(it);
  bool any_live = false;
  for (const ChunkSlot& c : s.chunks)
    if (!c.dead) any_live = true;
  if (!any_live) {
    int64_t freed = 0;
    for (int i = 0; i < s.k + s.m; ++i) {
      struct stat st;
      if (stat(ShardPath(id, i).c_str(), &st) == 0) freed += st.st_size;
      unlink(ShardPath(id, i).c_str());
    }
    struct stat st;
    if (stat(ManifestPath(id).c_str(), &st) == 0) freed += st.st_size;
    unlink(ManifestPath(id).c_str());
    stripes_.erase(id);
    if (reclaimed_bytes != nullptr) *reclaimed_bytes += freed;
    RecountLocked();
    return true;
  }
  // Dead flag must survive a restart (or GC'd chunks resurrect into the
  // index at Rescan); manifest rewrite is tmp+rename like the commit.
  std::string err;
  if (!WriteManifestLocked(id, s, &err))
    FDFS_LOG_WARN("ec: manifest rewrite after MarkDead(%s): %s",
                  digest_hex.c_str(), err.c_str());
  RecountLocked();
  return true;
}

bool EcStore::VerifyStripe(int64_t stripe_id, std::string* err) {
  std::lock_guard<RankedMutex> lk(mu_);
  auto it = stripes_.find(stripe_id);
  if (it == stripes_.end()) {
    *err = "no such stripe";
    return false;
  }
  const Stripe& s = it->second;
  // Parity-heavy decode basis: take the LAST k shards, so every parity
  // shard participates and the check exercises real reconstruction
  // (data-only would just re-read the bytes we wrote).
  std::vector<std::string> shards(static_cast<size_t>(s.k + s.m));
  for (int i = s.k + s.m - 1, kept = 0; i >= 0 && kept < s.k; --i) {
    if (!ReadShardLocked(stripe_id, s, i, &shards[i])) {
      *err = "shard " + std::to_string(i) + " unreadable";
      return false;
    }
    ++kept;
  }
  if (!RsReconstruct(&shards, s.k, s.m, s.shard_len)) {
    *err = "reconstruct failed";
    return false;
  }
  for (const ChunkSlot& c : s.chunks) {
    if (c.dead) continue;
    std::string payload(static_cast<size_t>(c.length), '\0');
    for (int64_t i = 0; i < c.length; ++i) {
      int64_t off = c.offset + i;
      payload[static_cast<size_t>(i)] =
          shards[static_cast<size_t>(off / s.shard_len)]
                [static_cast<size_t>(off % s.shard_len)];
    }
    if (Sha1(payload.data(), payload.size()).Hex() != c.digest_hex) {
      *err = "chunk " + c.digest_hex + " decodes wrong";
      return false;
    }
  }
  return true;
}

std::vector<int64_t> EcStore::StripeIds() const {
  std::lock_guard<RankedMutex> lk(mu_);
  std::vector<int64_t> ids;
  ids.reserve(stripes_.size());
  for (const auto& [id, s] : stripes_) {
    (void)s;
    ids.push_back(id);
  }
  return ids;
}

EcStore::StripeHealth EcStore::VerifyRepairStripe(
    int64_t stripe_id, std::vector<ChunkRef>* lost_live,
    int64_t* shards_rebuilt, int64_t* bytes_rebuilt, int64_t* bytes_read) {
  std::lock_guard<RankedMutex> lk(mu_);
  auto it = stripes_.find(stripe_id);
  if (it == stripes_.end()) return StripeHealth::kHealthy;
  const Stripe& s = it->second;
  std::vector<std::string> shards(static_cast<size_t>(s.k + s.m));
  std::vector<int> bad;
  for (int i = 0; i < s.k + s.m; ++i) {
    if (ReadShardLocked(stripe_id, s, i, &shards[i]))
      *bytes_read += s.shard_len;
    else
      bad.push_back(i);
  }
  if (bad.empty()) return StripeHealth::kHealthy;
  if (static_cast<int>(bad.size()) > s.m ||
      !RsReconstruct(&shards, s.k, s.m, s.shard_len)) {
    // Past parity: report the live chunks so the scrubber refills them
    // from group replicas (FETCH_CHUNK) and re-promotes to the
    // replicated tier.
    for (const ChunkSlot& c : s.chunks)
      if (!c.dead) lost_live->push_back({c.digest_hex, c.length});
    if (events_ != nullptr)
      events_->Record(EventSeverity::kError, "ec.stripe_lost",
                      std::to_string(stripe_id),
                      "bad_shards=" + std::to_string(bad.size()));
    return StripeHealth::kLost;
  }
  for (int i : bad) {
    std::string err;
    if (!WriteShardLocked(stripe_id, s, i, shards[i], &err)) {
      FDFS_LOG_WARN("ec: shard %lld.%d rewrite failed: %s",
                    static_cast<long long>(stripe_id), i, err.c_str());
      continue;
    }
    ++*shards_rebuilt;
    *bytes_rebuilt += s.shard_len;
  }
  if (events_ != nullptr)
    events_->Record(EventSeverity::kWarn, "ec.stripe_repaired",
                    std::to_string(stripe_id),
                    "shards=" + std::to_string(bad.size()));
  return StripeHealth::kRepaired;
}

void EcStore::DropStripe(int64_t stripe_id, int64_t* reclaimed_bytes) {
  std::lock_guard<RankedMutex> lk(mu_);
  auto it = stripes_.find(stripe_id);
  if (it == stripes_.end()) return;
  int64_t freed = 0;
  for (int i = 0; i < it->second.k + it->second.m; ++i) {
    struct stat st;
    if (stat(ShardPath(stripe_id, i).c_str(), &st) == 0)
      freed += st.st_size;
    unlink(ShardPath(stripe_id, i).c_str());
  }
  struct stat st;
  if (stat(ManifestPath(stripe_id).c_str(), &st) == 0) freed += st.st_size;
  unlink(ManifestPath(stripe_id).c_str());
  for (const ChunkSlot& c : it->second.chunks)
    if (!c.dead) index_.erase(c.digest_hex);
  stripes_.erase(it);
  if (reclaimed_bytes != nullptr) *reclaimed_bytes += freed;
  RecountLocked();
}

// -- release.map ----------------------------------------------------------
// Text journal, one "digest_hex length" line per pending chunk: the
// owner appends + fsyncs BEFORE the first EC_RELEASE goes out, so a
// crash mid-handover replays the batch next pass (the RPC is
// idempotent on peers).  Truncated once every peer answered.

bool EcStore::AppendReleaseMap(
    const std::vector<std::pair<std::string, int64_t>>& batch,
    std::string* err) {
  std::lock_guard<RankedMutex> lk(mu_);
  std::string path = dir_ + "/release.map";
  int fd = open(path.c_str(), O_CREAT | O_WRONLY | O_APPEND, 0644);
  if (fd < 0) {
    *err = "open " + path + ": " + strerror(errno);
    return false;
  }
  std::string buf;
  for (const auto& [dig, len] : batch)
    buf += dig + " " + std::to_string(len) + "\n";
  bool ok = write(fd, buf.data(), buf.size()) ==
                static_cast<ssize_t>(buf.size()) &&
            fsync(fd) == 0;
  close(fd);
  if (!ok) *err = "append " + path + ": " + strerror(errno);
  return ok;
}

std::vector<std::pair<std::string, int64_t>> EcStore::PendingReleases()
    const {
  std::lock_guard<RankedMutex> lk(mu_);
  std::vector<std::pair<std::string, int64_t>> out;
  std::string buf;
  if (!ReadWholeFile(dir_ + "/release.map", &buf)) return out;
  size_t pos = 0;
  while (pos < buf.size()) {
    size_t eol = buf.find('\n', pos);
    if (eol == std::string::npos) eol = buf.size();
    std::string line = buf.substr(pos, eol - pos);
    pos = eol + 1;
    size_t sp = line.find(' ');
    if (sp != 40) continue;  // torn tail line from a crash mid-append
    out.emplace_back(line.substr(0, 40),
                     strtoll(line.c_str() + 41, nullptr, 10));
  }
  return out;
}

void EcStore::ClearReleaseMap() {
  std::lock_guard<RankedMutex> lk(mu_);
  unlink((dir_ + "/release.map").c_str());
}

}  // namespace fdfs
