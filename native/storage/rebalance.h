// Rebalance migrator: drain a group by re-placing its files into their
// jump-hash target groups (ISSUE 11 / ROADMAP multi-group scale-out).
//
// When the tracker marks this group DRAINING (placement epoch, served in
// the beat trailer), every member runs migration passes over the files
// it was the binlog SOURCE for (uppercase ops — the same partitioning
// the sync threads use, so exactly one member owns each file and the
// group migrates in parallel without coordination).  Per file:
//
//   1. read the bytes via a loopback DOWNLOAD_FILE on this daemon (the
//      server materializes recipes, checks quarantine — one read path);
//   2. pick the target group: jump_hash(placement key of the old file
//      id) over the epoch's ACTIVE groups (QUERY_PLACEMENT), so a
//      drain spreads its files exactly like fresh mode-3 uploads;
//   3. upload to a target member — negotiated when possible (loopback
//      FETCH_RECIPE, then UPLOAD_RECIPE / UPLOAD_CHUNKS shipping only
//      the chunks the target lacks), flat UPLOAD_FILE otherwise;
//   4. verify byte identity (download the new copy, compare SHA1)
//      BEFORE touching the source;
//   5. append "<old_id> <new_id>" to <base_path>/data/rebalance.map
//      (the operator/client forwarding record), then delete the source
//      copy via a loopback DELETE_FILE (binlog D + replication + chunk
//      unref all ride the standard path).
//
// The map append lands before the source delete, so a crash between
// them re-runs as: map says moved -> verify target -> delete only.
// Passes are paced by the scrub token-bucket discipline
// (rebalance_bandwidth_mb_s, a cluster param the tracker serves); a
// pass that drains the inventory reports done=1 in the beat stats and
// the tracker leader auto-retires the group once every ACTIVE member
// agrees.
//
// Reference departure: upstream FastDFS cannot shrink a cluster —
// groups are forever and "migration" is rsync plus prayer.  This
// manager makes drain a first-class, verified, paced operation.
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <mutex>

#include "common/lockrank.h"
#include <string>
#include <thread>
#include <vector>

namespace fdfs {

class EventLog;
class TrackerReporter;

struct RebalanceOptions {
  std::string group_name;
  std::string base_path;    // rebalance.map home
  std::string sync_dir;     // <base_path>/data/sync (binlog inventory)
  int port = 23000;         // loopback self RPCs
  std::vector<std::string> trackers;  // "ip:port" for QUERY_PLACEMENT
  int poll_interval_s = 2;  // drain-state poll cadence
};

class RebalanceManager {
 public:
  RebalanceManager(RebalanceOptions opts, TrackerReporter* reporter,
                   EventLog* events = nullptr);
  ~RebalanceManager();

  void Start();
  void Stop();
  // Run a migration pass now if the group is draining (tests/operators).
  void Kick();

  // Cluster-param delivery (tracker kStorageParameterReq ->
  // RefreshClusterParams): migration byte pace, 0 = unpaced.
  void set_bandwidth_mb_s(int v) { bandwidth_mb_s_.store(v); }

  // Beat stat slots (protocol_gen.h kBeatStatNames rebalance_*).
  int64_t files_moved() const { return files_moved_.load(); }
  int64_t bytes_moved() const { return bytes_moved_.load(); }
  int64_t files_pending() const { return files_pending_.load(); }
  int64_t errors() const { return errors_.load(); }
  // 1 once a pass emptied the inventory while draining; cleared when
  // the group leaves the draining state.
  int64_t done() const { return done_.load(); }
  int64_t passes() const { return passes_.load(); }

 private:
  // Placement epoch as QUERY_PLACEMENT serves it, reduced to what
  // migration needs: the ACTIVE groups in epoch order + their members.
  struct TargetGroup {
    std::string name;
    std::vector<std::pair<std::string, int>> members;  // ip, port
  };
  // One lazily-(re)connected peer; Call retries once on a stale fd.
  struct Conn {
    std::string host;
    int port = 0;
    int fd = -1;
    ~Conn();
    void Reset(const std::string& h, int p);
    bool Call(uint8_t cmd, const std::string& body, std::string* resp,
              uint8_t* status);
    void Close();
  };

  void ThreadMain();
  void RunPass();
  bool Stopped();
  // Binlog walk: files this member is SOURCE for and has not deleted.
  std::vector<std::string> LoadInventory();
  // QUERY_PLACEMENT against any reachable tracker; false when none
  // answers (the pass aborts and retries later).
  bool FetchPlacement(std::vector<TargetGroup>* active);
  // Move one file; already_mapped = rebalance.map already records a new
  // id for it (crash recovery: verify + delete only).  Returns false on
  // any failure (retried next pass; the source copy is never deleted
  // before the target copy verified).
  bool MigrateOne(const std::string& remote,
                  const std::vector<TargetGroup>& active, int64_t seq,
                  const std::string& mapped_new_id);
  // Upload `bytes` for old file `remote` to `member`; *new_id gets
  // "group/remote" on success.  Negotiates via the recipe when the
  // source stored one, flat UPLOAD_FILE otherwise.
  bool UploadToTarget(Conn* target, const std::string& remote,
                      const std::string& bytes, std::string* new_id);
  bool VerifyRemote(Conn* target, const std::string& new_id,
                    const std::string& expect_bytes);
  void AppendMap(const std::string& old_id, const std::string& new_id);
  // Scrub-style token bucket over cumulative migrated bytes.
  void Pace(int64_t bytes_done, int64_t pass_start_us);

  RebalanceOptions opts_;
  TrackerReporter* reporter_;
  EventLog* events_;

  std::thread thread_;
  RankedMutex mu_{LockRank::kRebalance};  // stop/kick signalling only
  std::condition_variable_any cv_;
  bool stop_ = false;
  bool kicked_ = false;

  Conn self_;    // loopback reads/deletes
  Conn target_;  // current upload destination (re-resolved on change)

  // Current pass's pacing state (migration-thread only).
  int64_t pass_paced_ = 0;
  int64_t pass_start_us_ = 0;

  std::atomic<int> bandwidth_mb_s_{0};
  std::atomic<int64_t> files_moved_{0};
  std::atomic<int64_t> bytes_moved_{0};
  std::atomic<int64_t> files_pending_{0};
  std::atomic<int64_t> errors_{0};
  std::atomic<int64_t> done_{0};
  std::atomic<int64_t> passes_{0};
};

}  // namespace fdfs
