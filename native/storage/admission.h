// Admission control & request QoS: the subsystem that sheds load BEFORE
// saturation kills every caller's p99 (ROADMAP item 5; upstream FastDFS
// queues past capacity unboundedly and collapses for everyone at once).
//
// Every request has a 5-class priority (protocol.py PriorityClass —
// control, interactive reads, normal writes, bulk ingest, background).
// A client may tag a request explicitly with a PRIORITY prefix frame
// (the TRACE_CTX pattern: one class byte, no response, applies to the
// next request); untagged requests default by opcode
// (DefaultPriorityClass) so replication/recovery/EC traffic is born
// background and an un-upgraded client still degrades sanely.
//
// The controller runs an admission-level LADDER:
//   level 0  admit everything
//   level 1  shed background
//   level 2  shed bulk + background
//   level 3  shed everything but control + interactive reads
// (class c admitted at level L iff c + L <= 4).  The level moves at
// most one rung per metrics tick, driven by a composite pressure score
// — SLO breach count (sloeval), dio queue depth, reactor loop-lag p99,
// and admitted-but-unanswered request bytes, each normalized so 1.0
// means "at the configured limit" — smoothed through the SAME
// EWMA+hysteresis discipline as sloeval (alpha 0.5; tighten only when
// the EWMA exceeds tighten_threshold, relax only when it falls to
// relax_threshold < tighten_threshold), so one noisy sample can
// neither shed nor un-shed and the ladder cannot flap.
//
// A shed request is answered EBUSY with an 8-byte big-endian
// retry-after hint (ms, level-scaled) as the response body; the Python
// client honors it with jittered backoff and does NOT dead-mark the
// peer (an admission EBUSY is the daemon protecting itself, not dying).
//
// Concurrency: Tick() runs on the owning daemon's main loop only (the
// metrics timer).  Admit()/AdmitOrShed() run on any nio thread and read
// one atomic level; counters are relaxed atomics read by registry
// gauge-fns.  No locks, no new ranks.
#pragma once

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <string>

namespace fdfs {

// Mirrors fastdfs_tpu.common.protocol.PriorityClass (pinned by the
// fdfs_codec priority-frame golden).
constexpr uint8_t kPriorityControl = 0;
constexpr uint8_t kPriorityInteractive = 1;
constexpr uint8_t kPriorityNormal = 2;
constexpr uint8_t kPriorityBulk = 3;
constexpr uint8_t kPriorityBackground = 4;
constexpr int kPriorityClassCount = 5;
// Conn-level sentinel: no PRIORITY frame seen, resolve by opcode.
constexpr uint8_t kPriorityUntagged = 0xFF;

const char* PriorityClassName(uint8_t cls);

// Born-priority of an untagged request, by opcode.  The Python mirror
// is protocol.default_priority_class; the two tables are pinned
// against each other by the fdfs_codec priority-frame golden.
uint8_t DefaultPriorityClass(uint8_t storage_cmd);
// Tracker port: the expensive observability dumps are born bulk, the
// cluster-critical plane (beats, joins, service queries) control.
uint8_t DefaultTrackerPriorityClass(uint8_t tracker_cmd);

struct AdmissionConfig {
  bool enabled = true;
  // Ladder movement: tighten a level when the pressure EWMA exceeds
  // tighten_threshold, relax one when it falls to relax_threshold.
  // The gap between them is the hysteresis band where the level holds.
  double tighten_threshold = 0.9;
  double relax_threshold = 0.45;
  // Normalization points: the signal value that reads as 1.0 pressure.
  int64_t queue_depth_high = 64;        // dio jobs pending
  double loop_lag_high_ms = 100.0;      // reactor loop-lag p99
  int64_t inflight_high_bytes = 256ll << 20;  // admitted unanswered bytes
  // Base backoff hint; the wire carries base * current level.
  int64_t retry_after_ms = 500;
};

// One tick's worth of pressure inputs, computed by the owning daemon
// (the storage server reads its SLO engine, dio pools, loop-lag
// histograms, and in-flight byte ledger; the tracker its single loop).
// loop_lag_p99_ms < 0 means "unavailable this tick" (no traffic
// crossed the window) and the component is skipped.
struct AdmissionSignals {
  int64_t breaches_active = 0;
  int64_t queue_depth = 0;
  double loop_lag_p99_ms = -1.0;
  int64_t inflight_bytes = 0;
};

class AdmissionController {
 public:
  explicit AdmissionController(const AdmissionConfig& cfg) : cfg_(cfg) {}

  // Evaluate one tick: fold the signals into the pressure EWMA and move
  // the ladder at most one rung.  Returns +1 (tightened), -1 (relaxed),
  // or 0.  Main-loop only (single caller by contract).
  int Tick(const AdmissionSignals& s);

  // Header-stage consult (any thread).  `cls` must already be resolved
  // (never kPriorityUntagged).  True = admit.  On shed, bumps the
  // per-class counter and writes the level-scaled retry-after hint.
  bool AdmitOrShed(uint8_t cls, int64_t* retry_after_ms);
  bool WouldAdmit(uint8_t cls) const {
    int lvl = level_.load(std::memory_order_relaxed);
    return !cfg_.enabled || lvl <= 0 || ClampClass(cls) + lvl <= kPriorityBackground;
  }

  int level() const { return level_.load(std::memory_order_relaxed); }
  const char* level_name() const;
  int64_t retry_after_ms() const {
    return cfg_.retry_after_ms * std::max(level(), 1);
  }
  // Milli-units so gauge-fns stay integer (pressure 1.0 -> 1000).
  int64_t pressure_milli() const {
    return pressure_milli_.load(std::memory_order_relaxed);
  }
  int64_t ewma_milli() const {
    return ewma_milli_.load(std::memory_order_relaxed);
  }
  int64_t tightens() const { return tightens_.load(std::memory_order_relaxed); }
  int64_t relaxes() const { return relaxes_.load(std::memory_order_relaxed); }
  int64_t admitted() const { return admitted_.load(std::memory_order_relaxed); }
  int64_t shed_total() const { return shed_.load(std::memory_order_relaxed); }
  int64_t shed_by_class(int cls) const {
    return shed_class_[ClampClass(static_cast<uint8_t>(cls))].load(
        std::memory_order_relaxed);
  }

  // ADMISSION_STATUS response body (JSON; decoded by
  // fastdfs_tpu.monitor.decode_admission, pinned by the fdfs_codec
  // admission-json golden).
  std::string StatusJson(const char* role, int port) const;

  const AdmissionConfig& config() const { return cfg_; }

  // The composite score: max over normalized components, so the most
  // pressured dimension drives the ladder (a saturated dio queue must
  // not be averaged away by an idle network loop).
  static double PressureScore(const AdmissionConfig& cfg,
                              const AdmissionSignals& s);

  static constexpr double kAlpha = 0.5;  // EWMA weight of the new sample
  static constexpr int kMaxLevel = 3;

 private:
  static uint8_t ClampClass(uint8_t cls) {
    return cls > kPriorityBackground ? kPriorityBackground : cls;
  }

  AdmissionConfig cfg_;
  double ewma_ = 0;       // main-loop state
  bool have_ewma_ = false;
  std::atomic<int> level_{0};
  std::atomic<int64_t> pressure_milli_{0};
  std::atomic<int64_t> ewma_milli_{0};
  std::atomic<int64_t> tightens_{0};
  std::atomic<int64_t> relaxes_{0};
  std::atomic<int64_t> admitted_{0};
  std::atomic<int64_t> shed_{0};
  std::atomic<int64_t> shed_class_[kPriorityClassCount] = {};
};

}  // namespace fdfs
