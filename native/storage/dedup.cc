#include "storage/dedup.h"

#include <string.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <time.h>
#include <unistd.h>

#include <atomic>
#include <cstdio>

#include "common/bytes.h"
#include "common/cdc.h"
#include "common/gear_gen.h"
#include "common/log.h"
#include "common/net.h"
#include "common/protocol_gen.h"

namespace fdfs {

// -- CpuDedup -------------------------------------------------------------

CpuDedup::CpuDedup(std::string snapshot_path)
    : snapshot_path_(std::move(snapshot_path)) {}

DedupPlugin::Verdict CpuDedup::Judge(const std::string& sha1_hex, int64_t) {
  Verdict v;
  std::lock_guard<RankedMutex> lk(mu_);
  auto it = by_digest_.find(sha1_hex);
  if (it != by_digest_.end()) {
    v.duplicate = true;
    v.dup_of = it->second;
  }
  return v;
}

void CpuDedup::Commit(const std::string& sha1_hex, const std::string& file_id) {
  std::lock_guard<RankedMutex> lk(mu_);
  by_digest_.emplace(sha1_hex, file_id);  // first writer wins
  by_file_[file_id] = sha1_hex;
}

void CpuDedup::Forget(const std::string& file_id) {
  std::lock_guard<RankedMutex> lk(mu_);
  auto it = by_file_.find(file_id);
  if (it == by_file_.end()) return;
  auto dit = by_digest_.find(it->second);
  // Only drop the digest entry if it still names this file (another file
  // with identical bytes may have replaced it as the canonical copy).
  if (dit != by_digest_.end() && dit->second == file_id) by_digest_.erase(dit);
  by_file_.erase(it);
}

bool CpuDedup::Save() {
  std::lock_guard<RankedMutex> lk(mu_);
  std::string tmp = snapshot_path_ + ".tmp";
  FILE* f = fopen(tmp.c_str(), "w");
  if (f == nullptr) return false;
  for (const auto& [digest, id] : by_digest_)
    fprintf(f, "%s %s\n", digest.c_str(), id.c_str());
  fclose(f);
  return rename(tmp.c_str(), snapshot_path_.c_str()) == 0;
}

bool CpuDedup::FingerprintChunks(int64_t /*session*/, const char* data,
                                 size_t len, int64_t base_offset,
                                 std::vector<ChunkFp>* out) {
  const uint8_t* p = reinterpret_cast<const uint8_t*>(data);
  std::vector<int64_t> cuts = GearChunkStream(
      p, len, kCdcDefaultMinSize, kCdcDefaultAvgBits, kCdcDefaultMaxSize);
  int64_t last = 0;
  for (int64_t cut : cuts) {
    ChunkFp fp;
    fp.offset = base_offset + last;
    fp.length = cut - last;
    fp.digest_hex = Sha1(data + last, static_cast<size_t>(cut - last)).Hex();
    out->push_back(std::move(fp));
    last = cut;
  }
  return true;
}

bool CpuDedup::LoadSnapshot() {
  FILE* f = fopen(snapshot_path_.c_str(), "r");
  if (f == nullptr) return true;  // no snapshot yet
  char digest[64], id[512];
  while (fscanf(f, "%63s %511s", digest, id) == 2) {
    by_digest_[digest] = id;
    by_file_[id] = digest;
  }
  fclose(f);
  FDFS_LOG_INFO("dedup(cpu): loaded %zu digests from snapshot",
                by_digest_.size());
  return true;
}

// -- SidecarDedup ---------------------------------------------------------

SidecarDedup::SidecarDedup(std::string socket_path)
    : socket_path_(std::move(socket_path)) {}

SidecarDedup::~SidecarDedup() {
  for (int fd : pool_) close(fd);
}

static thread_local int64_t tls_dedup_lock_wait_us = 0;

int64_t TakeDedupLockWaitUs() {
  int64_t v = tls_dedup_lock_wait_us;
  tls_dedup_lock_wait_us = 0;
  return v;
}

static int64_t DedupMonoUs() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<int64_t>(ts.tv_sec) * 1000000 + ts.tv_nsec / 1000;
}

int SidecarDedup::AcquireFd(bool* pooled) {
  {
    // Only the pool-mutex wait counts as "lock wait" — connection setup
    // below is transport cost, not serialization.
    const int64_t t0 = DedupMonoUs();
    std::lock_guard<RankedMutex> lk(mu_);
    tls_dedup_lock_wait_us += DedupMonoUs() - t0;
    if (!pool_.empty()) {
      int fd = pool_.back();
      pool_.pop_back();
      *pooled = true;
      return fd;
    }
  }
  *pooled = false;
  int fd = socket(AF_UNIX, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  struct sockaddr_un addr;
  memset(&addr, 0, sizeof(addr));
  addr.sun_family = AF_UNIX;
  strncpy(addr.sun_path, socket_path_.c_str(), sizeof(addr.sun_path) - 1);
  if (connect(fd, reinterpret_cast<struct sockaddr*>(&addr), sizeof(addr)) != 0) {
    close(fd);
    return -1;
  }
  return fd;
}

void SidecarDedup::ReleaseFd(int fd) {
  std::lock_guard<RankedMutex> lk(mu_);
  if (static_cast<int>(pool_.size()) >= kMaxIdleFds) {
    close(fd);
    return;
  }
  pool_.push_back(fd);
}

bool SidecarDedup::Rpc(uint8_t cmd, const std::string& body, std::string* resp,
                       uint8_t* status, int64_t max_resp) {
  // Each RPC borrows its own pooled connection, so concurrent dio
  // threads overlap their sidecar round-trips.  A failure on a POOLED
  // fd retries once on a fresh connection: after a sidecar restart the
  // pool holds up to kMaxIdleFds dead sockets, and without the retry
  // each of those would fail one upload into the flat-store path.
  const int timeout_ms = 60000;
  for (int attempt = 0; attempt < 2; ++attempt) {
    bool pooled = false;
    int fd = AcquireFd(&pooled);
    if (fd < 0) return false;
    uint8_t hdr[kHeaderSize];
    PutInt64BE(static_cast<int64_t>(body.size()), hdr);
    hdr[8] = cmd;
    hdr[9] = 0;
    // Generous timeout for fingerprint segments (first TPU compile of a
    // new bucket shape can take tens of seconds); the rest is instant.
    if (!SendAll(fd, hdr, sizeof(hdr), timeout_ms) ||
        !SendAll(fd, body.data(), body.size(), timeout_ms) ||
        !RecvAll(fd, hdr, sizeof(hdr), timeout_ms)) {
      close(fd);
      if (pooled) continue;  // stale pooled socket: retry fresh
      return false;
    }
    int64_t len = GetInt64BE(hdr);
    *status = hdr[9];
    if (len < 0 || len > max_resp) {
      FDFS_LOG_WARN("dedup(sidecar): bogus response length %lld",
                    static_cast<long long>(len));
      close(fd);
      return false;
    }
    resp->resize(static_cast<size_t>(len));
    if (len > 0 && !RecvAll(fd, resp->data(), resp->size(), timeout_ms)) {
      close(fd);
      return false;
    }
    ReleaseFd(fd);
    return true;
  }
  return false;
}

DedupPlugin::Verdict SidecarDedup::Judge(const std::string& sha1_hex, int64_t) {
  Verdict v;
  std::string resp;
  uint8_t status = 0;
  if (!Rpc(static_cast<uint8_t>(StorageCmd::kDedupQuery), sha1_hex, &resp,
           &status)) {
    FDFS_LOG_WARN("dedup(sidecar): unreachable, treating as unique");
    return v;  // fail open
  }
  if (status == 0 && !resp.empty()) {
    v.duplicate = true;
    v.dup_of = resp;
  }
  return v;
}

void SidecarDedup::Commit(const std::string& sha1_hex,
                          const std::string& file_id) {
  std::string resp;
  uint8_t status = 0;
  Rpc(static_cast<uint8_t>(StorageCmd::kDedupCommit),
      "commitfile " + sha1_hex + " " + file_id, &resp, &status);
}

void SidecarDedup::Forget(const std::string& file_id) {
  std::string resp;
  uint8_t status = 0;
  Rpc(static_cast<uint8_t>(StorageCmd::kDedupCommit),
      std::string("forget ") + file_id, &resp, &status);
}

// Sessions scope the sidecar's pending per-upload state.  The id embeds
// the daemon pid (multiple daemons may share one sidecar) and draws from
// one PROCESS-WIDE counter — the server holds two SidecarDedup instances
// (main loop + recovery thread), and per-instance counters would mint
// colliding ids for exactly the concurrent-upload case sessions exist
// to separate.
int64_t SidecarDedup::BeginChunked() {
  static std::atomic<int64_t> counter{0};
  return (static_cast<int64_t>(getpid()) << 32) |
         (counter.fetch_add(1, std::memory_order_relaxed) + 1);
}

// Fingerprint RPC (cmd 125): the daemon runs the native AVX2 CDC itself
// (identical gear table => identical cut points) and ships the cut
// offsets with the bytes — chunking is branchy scalar work the CPU does
// at GB/s, while the accelerator round-trip carries only the FLOP-heavy
// hash batches.  Request body: 8B BE session id + 8B BE base_offset +
// 8B BE n_cuts + n_cuts x 8B relative exclusive ends + raw segment.
// Response: 8B BE chunk_count then per chunk 8B offset + 8B length +
// 20B raw digest.
bool SidecarDedup::FingerprintChunks(int64_t session, const char* data,
                                     size_t len, int64_t base_offset,
                                     std::vector<ChunkFp>* out) {
  std::vector<int64_t> cuts = GearChunkStream(
      reinterpret_cast<const uint8_t*>(data), len, kCdcDefaultMinSize,
      kCdcDefaultAvgBits, kCdcDefaultMaxSize);
  std::string body;
  body.reserve(24 + cuts.size() * 8 + len);
  uint8_t num[8];
  PutInt64BE(session, num);
  body.append(reinterpret_cast<char*>(num), 8);
  PutInt64BE(base_offset, num);
  body.append(reinterpret_cast<char*>(num), 8);
  PutInt64BE(static_cast<int64_t>(cuts.size()), num);
  body.append(reinterpret_cast<char*>(num), 8);
  for (int64_t cut : cuts) {
    PutInt64BE(cut, num);
    body.append(reinterpret_cast<char*>(num), 8);
  }
  body.append(data, len);
  std::string resp;
  uint8_t status = 0;
  if (!Rpc(static_cast<uint8_t>(StorageCmd::kDedupFingerprintCuts), body,
           &resp, &status, /*max_resp=*/256 << 20) ||
      status != 0 || resp.size() < 8) {
    FDFS_LOG_WARN("dedup(sidecar): fingerprint unavailable, storing flat");
    return false;
  }
  const uint8_t* p = reinterpret_cast<const uint8_t*>(resp.data());
  int64_t count = GetInt64BE(p);
  if (count < 0 || resp.size() != 8 + static_cast<size_t>(count) * 36)
    return false;
  static const char* kHex = "0123456789abcdef";
  int64_t covered = 0;
  for (int64_t i = 0; i < count; ++i) {
    const uint8_t* rec = p + 8 + i * 36;
    ChunkFp fp;
    fp.offset = GetInt64BE(rec);
    fp.length = GetInt64BE(rec + 8);
    if (fp.length <= 0 || fp.offset != base_offset + covered) return false;
    fp.digest_hex.resize(40);
    for (int b = 0; b < 20; ++b) {
      fp.digest_hex[2 * b] = kHex[rec[16 + b] >> 4];
      fp.digest_hex[2 * b + 1] = kHex[rec[16 + b] & 0xF];
    }
    covered += fp.length;
    out->push_back(std::move(fp));
  }
  return covered == static_cast<int64_t>(len);
}

void SidecarDedup::CommitChunked(int64_t session, const std::string& file_id) {
  std::string resp;
  uint8_t status = 0;
  Rpc(static_cast<uint8_t>(StorageCmd::kDedupCommit),
      "commitchunks " + std::to_string(session) + " " + file_id, &resp,
      &status);
}

void SidecarDedup::AbortChunked(int64_t session) {
  std::string resp;
  uint8_t status = 0;
  Rpc(static_cast<uint8_t>(StorageCmd::kDedupCommit),
      "abort " + std::to_string(session), &resp, &status);
}

void SidecarDedup::ForgetChunked(const std::string& file_id) {
  std::string resp;
  uint8_t status = 0;
  Rpc(static_cast<uint8_t>(StorageCmd::kDedupCommit),
      std::string("forget ") + file_id, &resp, &status);
}

bool SidecarDedup::NearDups(const std::string& file_id, std::string* out,
                            bool* no_data) {
  std::string resp;
  uint8_t status = 0;
  if (!Rpc(static_cast<uint8_t>(StorageCmd::kDedupNeardups), file_id, &resp,
           &status))
    return false;  // sidecar down: same ENOTSUP surface as mode=cpu
  if (status == 61) {  // ENODATA: known mode, unindexed file
    *no_data = true;
    return true;
  }
  if (status != 0) return false;
  *out = std::move(resp);
  *no_data = false;
  return true;
}

bool SidecarDedup::VerifyChunks(const std::vector<ChunkFp>& chunks,
                                const std::string& payloads,
                                std::string* bad_mask) {
  if (chunks.empty()) {
    bad_mask->clear();
    return true;
  }
  // kDedupVerify body: 8B count + count x (8B length + 20B raw digest)
  // + the payloads concatenated; response = count bytes (0 ok / 1 bad).
  std::string body;
  uint8_t num[8];
  PutInt64BE(static_cast<int64_t>(chunks.size()), num);
  body.append(reinterpret_cast<char*>(num), 8);
  int64_t total = 0;
  for (const ChunkFp& c : chunks) {
    PutInt64BE(c.length, num);
    body.append(reinterpret_cast<char*>(num), 8);
    if (!HexToBytes(c.digest_hex, &body)) return false;
    total += c.length;
  }
  if (total != static_cast<int64_t>(payloads.size())) return false;
  body += payloads;
  std::string resp;
  uint8_t status = 0;
  if (!Rpc(static_cast<uint8_t>(StorageCmd::kDedupVerify), body, &resp,
           &status, static_cast<int64_t>(chunks.size()) + 1024) ||
      status != 0 || resp.size() != chunks.size())
    return false;  // sidecar down/old: caller verifies serially
  *bad_mask = std::move(resp);
  return true;
}

std::unique_ptr<DedupPlugin> MakeDedupPlugin(const std::string& mode,
                                             const std::string& base_path,
                                             const std::string& sidecar_path) {
  if (mode == "cpu") {
    auto p = std::make_unique<CpuDedup>(base_path + "/data/dedup_index.dat");
    p->LoadSnapshot();
    return p;
  }
  if (mode == "sidecar") return std::make_unique<SidecarDedup>(sidecar_path);
  return nullptr;  // none
}

}  // namespace fdfs
