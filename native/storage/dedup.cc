#include "storage/dedup.h"

#include <string.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#include <cstdio>

#include "common/bytes.h"
#include "common/log.h"
#include "common/net.h"
#include "common/protocol_gen.h"

namespace fdfs {

// -- CpuDedup -------------------------------------------------------------

CpuDedup::CpuDedup(std::string snapshot_path)
    : snapshot_path_(std::move(snapshot_path)) {}

DedupPlugin::Verdict CpuDedup::Judge(const std::string& sha1_hex, int64_t) {
  Verdict v;
  auto it = by_digest_.find(sha1_hex);
  if (it != by_digest_.end()) {
    v.duplicate = true;
    v.dup_of = it->second;
  }
  return v;
}

void CpuDedup::Commit(const std::string& sha1_hex, const std::string& file_id) {
  by_digest_.emplace(sha1_hex, file_id);  // first writer wins
  by_file_[file_id] = sha1_hex;
}

void CpuDedup::Forget(const std::string& file_id) {
  auto it = by_file_.find(file_id);
  if (it == by_file_.end()) return;
  auto dit = by_digest_.find(it->second);
  // Only drop the digest entry if it still names this file (another file
  // with identical bytes may have replaced it as the canonical copy).
  if (dit != by_digest_.end() && dit->second == file_id) by_digest_.erase(dit);
  by_file_.erase(it);
}

bool CpuDedup::Save() {
  std::string tmp = snapshot_path_ + ".tmp";
  FILE* f = fopen(tmp.c_str(), "w");
  if (f == nullptr) return false;
  for (const auto& [digest, id] : by_digest_)
    fprintf(f, "%s %s\n", digest.c_str(), id.c_str());
  fclose(f);
  return rename(tmp.c_str(), snapshot_path_.c_str()) == 0;
}

bool CpuDedup::LoadSnapshot() {
  FILE* f = fopen(snapshot_path_.c_str(), "r");
  if (f == nullptr) return true;  // no snapshot yet
  char digest[64], id[512];
  while (fscanf(f, "%63s %511s", digest, id) == 2) {
    by_digest_[digest] = id;
    by_file_[id] = digest;
  }
  fclose(f);
  FDFS_LOG_INFO("dedup(cpu): loaded %zu digests from snapshot",
                by_digest_.size());
  return true;
}

// -- SidecarDedup ---------------------------------------------------------

SidecarDedup::SidecarDedup(std::string socket_path)
    : socket_path_(std::move(socket_path)) {}

SidecarDedup::~SidecarDedup() {
  if (fd_ >= 0) close(fd_);
}

bool SidecarDedup::EnsureConnected() {
  if (fd_ >= 0) return true;
  int fd = socket(AF_UNIX, SOCK_STREAM, 0);
  if (fd < 0) return false;
  struct sockaddr_un addr;
  memset(&addr, 0, sizeof(addr));
  addr.sun_family = AF_UNIX;
  strncpy(addr.sun_path, socket_path_.c_str(), sizeof(addr.sun_path) - 1);
  if (connect(fd, reinterpret_cast<struct sockaddr*>(&addr), sizeof(addr)) != 0) {
    close(fd);
    return false;
  }
  fd_ = fd;
  return true;
}

bool SidecarDedup::Rpc(uint8_t cmd, const std::string& body, std::string* resp,
                       uint8_t* status) {
  if (!EnsureConnected()) return false;
  uint8_t hdr[kHeaderSize];
  PutInt64BE(static_cast<int64_t>(body.size()), hdr);
  hdr[8] = cmd;
  hdr[9] = 0;
  if (!SendAll(fd_, hdr, sizeof(hdr), 5000) ||
      !SendAll(fd_, body.data(), body.size(), 5000) ||
      !RecvAll(fd_, hdr, sizeof(hdr), 5000)) {
    close(fd_);
    fd_ = -1;
    return false;
  }
  int64_t len = GetInt64BE(hdr);
  *status = hdr[9];
  if (len < 0 || len > (1 << 20)) {  // sidecar replies are tiny; fail open
    FDFS_LOG_WARN("dedup(sidecar): bogus response length %lld",
                  static_cast<long long>(len));
    close(fd_);
    fd_ = -1;
    return false;
  }
  resp->resize(static_cast<size_t>(len));
  if (len > 0 && !RecvAll(fd_, resp->data(), resp->size(), 5000)) {
    close(fd_);
    fd_ = -1;
    return false;
  }
  return true;
}

DedupPlugin::Verdict SidecarDedup::Judge(const std::string& sha1_hex, int64_t) {
  Verdict v;
  std::string resp;
  uint8_t status = 0;
  if (!Rpc(static_cast<uint8_t>(StorageCmd::kDedupQuery), sha1_hex, &resp,
           &status)) {
    FDFS_LOG_WARN("dedup(sidecar): unreachable, treating as unique");
    return v;  // fail open
  }
  if (status == 0 && !resp.empty()) {
    v.duplicate = true;
    v.dup_of = resp;
  }
  return v;
}

void SidecarDedup::Commit(const std::string& sha1_hex,
                          const std::string& file_id) {
  std::string resp;
  uint8_t status = 0;
  Rpc(static_cast<uint8_t>(StorageCmd::kDedupCommit), sha1_hex + " " + file_id,
      &resp, &status);
}

void SidecarDedup::Forget(const std::string& file_id) {
  std::string resp;
  uint8_t status = 0;
  Rpc(static_cast<uint8_t>(StorageCmd::kDedupFingerprint),
      std::string("forget ") + file_id, &resp, &status);
}

std::unique_ptr<DedupPlugin> MakeDedupPlugin(const std::string& mode,
                                             const std::string& base_path,
                                             const std::string& sidecar_path) {
  if (mode == "cpu") {
    auto p = std::make_unique<CpuDedup>(base_path + "/data/dedup_index.dat");
    p->LoadSnapshot();
    return p;
  }
  if (mode == "sidecar") return std::make_unique<SidecarDedup>(sidecar_path);
  return nullptr;  // none
}

}  // namespace fdfs
