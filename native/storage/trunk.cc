#include "storage/trunk.h"

#include <dirent.h>
#include <fcntl.h>
#include <string.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <unordered_map>
#include <unordered_set>

#include "common/bytes.h"
#include "common/fsutil.h"
#include "common/log.h"
#include "common/net.h"
#include "common/protocol_gen.h"

namespace fdfs {

namespace {

int64_t AlignSlot(int64_t payload_size) {
  int64_t need = payload_size + kTrunkHeaderSize;
  return (need + kTrunkAlignment - 1) / kTrunkAlignment * kTrunkAlignment;
}

void PackHeader(const TrunkSlotHeader& h, uint8_t out[kTrunkHeaderSize]) {
  PutInt16BE(kTrunkMagic, out);
  out[2] = static_cast<uint8_t>(h.type);
  out[3] = 0;
  PutInt32BE(h.alloc_size, out + 4);
  PutInt32BE(h.file_size, out + 8);
  PutInt32BE(h.crc32, out + 12);
  PutInt32BE(h.mtime, out + 16);
  PutInt32BE(0, out + 20);  // reserved
}

bool UnpackHeader(const uint8_t in[kTrunkHeaderSize], TrunkSlotHeader* h) {
  if (GetInt16BE(in) != kTrunkMagic) return false;
  h->type = static_cast<char>(in[2]);
  if (h->type != kTrunkSlotData && h->type != kTrunkSlotFree) return false;
  h->alloc_size = GetInt32BE(in + 4);
  h->file_size = GetInt32BE(in + 8);
  h->crc32 = GetInt32BE(in + 12);
  h->mtime = GetInt32BE(in + 16);
  return true;
}

int OpenTrunkFd(const std::string& store_path, uint32_t trunk_id,
                bool create) {
  std::string path = TrunkFilePath(store_path, trunk_id);
  if (create) {
    std::string dir = path.substr(0, path.rfind('/'));
    MakeDirs(dir);
  }
  return open(path.c_str(), create ? (O_RDWR | O_CREAT) : O_RDWR, 0644);
}

}  // namespace

std::string TrunkFilePath(const std::string& store_path, uint32_t trunk_id) {
  char buf[40];
  std::snprintf(buf, sizeof(buf), "/data/trunk/%02X/%06u.tk",
                trunk_id & 0xFF, trunk_id);
  return store_path + buf;
}

bool WriteSlotHeader(int fd, int64_t offset, const TrunkSlotHeader& h) {
  uint8_t buf[kTrunkHeaderSize];
  PackHeader(h, buf);
  return pwrite(fd, buf, sizeof(buf), offset) ==
         static_cast<ssize_t>(sizeof(buf));
}

std::optional<TrunkSlotHeader> ReadSlotHeader(int fd, int64_t offset) {
  uint8_t buf[kTrunkHeaderSize];
  if (pread(fd, buf, sizeof(buf), offset) !=
      static_cast<ssize_t>(sizeof(buf)))
    return std::nullopt;
  TrunkSlotHeader h;
  if (!UnpackHeader(buf, &h)) return std::nullopt;
  return h;
}

bool WriteSlotPayload(const std::string& store_path, const TrunkLocation& loc,
                      const std::string& payload, uint32_t crc32,
                      std::string* error) {
  if (payload.size() + kTrunkHeaderSize > loc.alloc_size) {
    *error = "payload does not fit the slot";
    return false;
  }
  int fd = OpenTrunkFd(store_path, loc.trunk_id, /*create=*/true);
  if (fd < 0) {
    *error = std::string("open trunk file: ") + strerror(errno);
    return false;
  }
  // Replicas may land here before any local allocation ever happened:
  // extend the sparse file so the slot exists at the replicated offset.
  struct stat st;
  fstat(fd, &st);
  int64_t end = static_cast<int64_t>(loc.offset) + loc.alloc_size;
  if (st.st_size < end && ftruncate(fd, end) != 0) {
    *error = std::string("extend trunk file: ") + strerror(errno);
    close(fd);
    return false;
  }
  TrunkSlotHeader h;
  h.type = kTrunkSlotData;
  h.alloc_size = loc.alloc_size;
  h.file_size = static_cast<uint32_t>(payload.size());
  h.crc32 = crc32;
  h.mtime = static_cast<uint32_t>(time(nullptr));
  bool ok = WriteSlotHeader(fd, loc.offset, h) &&
            pwrite(fd, payload.data(), payload.size(),
                   loc.offset + kTrunkHeaderSize) ==
                static_cast<ssize_t>(payload.size());
  if (!ok) *error = std::string("slot write: ") + strerror(errno);
  close(fd);
  return ok;
}

std::optional<std::string> ReadSlotPayload(const std::string& store_path,
                                           const TrunkLocation& loc,
                                           int64_t expect_file_size) {
  int fd = OpenTrunkFd(store_path, loc.trunk_id, /*create=*/false);
  if (fd < 0) return std::nullopt;
  auto h = ReadSlotHeader(fd, loc.offset);
  if (!h.has_value() || h->type != kTrunkSlotData ||
      h->alloc_size != loc.alloc_size ||
      (expect_file_size >= 0 &&
       h->file_size != static_cast<uint32_t>(expect_file_size))) {
    close(fd);
    return std::nullopt;
  }
  std::string out(h->file_size, '\0');
  ssize_t n = pread(fd, out.data(), out.size(), loc.offset + kTrunkHeaderSize);
  close(fd);
  if (n != static_cast<ssize_t>(out.size())) return std::nullopt;
  return out;
}

bool MarkSlotFree(const std::string& store_path, const TrunkLocation& loc) {
  int fd = OpenTrunkFd(store_path, loc.trunk_id, /*create=*/false);
  if (fd < 0) return false;
  auto h = ReadSlotHeader(fd, loc.offset);
  // Already-free slots are rejected: a duplicate/replayed FREE would
  // otherwise push a second pool entry and the same byte range would be
  // handed to two different uploads (double-alloc corruption).
  if (!h.has_value() || h->type != kTrunkSlotData ||
      h->alloc_size != loc.alloc_size) {
    close(fd);
    return false;
  }
  h->type = kTrunkSlotFree;
  h->file_size = 0;
  h->crc32 = 0;
  bool ok = WriteSlotHeader(fd, loc.offset, *h);
  close(fd);
  return ok;
}

// -- allocator ------------------------------------------------------------

bool TrunkAllocator::Init(const std::string& store_path,
                          int64_t trunk_file_size, std::string* error) {
  std::lock_guard<RankedMutex> lk(mu_);
  store_path_ = store_path;
  trunk_file_size_ = trunk_file_size;
  return ScanRebuildLocked(error);
}

bool TrunkAllocator::ScanFileLocked(
    uint32_t trunk_id, const std::string& path,
    std::map<int64_t, std::vector<Block>>* pool) const {
  int fd = open(path.c_str(), O_RDONLY);
  if (fd < 0) return false;
  struct stat st;
  fstat(fd, &st);
  int64_t off = 0;
  while (off + kTrunkHeaderSize <= st.st_size) {
    auto h = ReadSlotHeader(fd, off);
    if (!h.has_value() || h->alloc_size < kTrunkHeaderSize ||
        off + h->alloc_size > st.st_size) {
      // Torn header chain (crash mid-split): everything from here on is
      // unreachable by any handed-out ID, so reclaim it as one free block.
      int64_t rest = st.st_size - off;
      if (rest >= kTrunkMinSplit) {
        TrunkSlotHeader fh;
        fh.type = kTrunkSlotFree;
        fh.alloc_size = static_cast<uint32_t>(rest);
        int wfd = open(path.c_str(), O_WRONLY);
        if (wfd >= 0) {
          WriteSlotHeader(wfd, off, fh);
          close(wfd);
        }
        (*pool)[rest].push_back(
            {trunk_id, static_cast<uint32_t>(off)});
        FDFS_LOG_WARN("trunk %06u: torn chain at %lld, reclaimed %lld bytes",
                      trunk_id, static_cast<long long>(off),
                      static_cast<long long>(rest));
      }
      break;
    }
    if (h->type == kTrunkSlotFree)
      (*pool)[h->alloc_size].push_back(
          {trunk_id, static_cast<uint32_t>(off)});
    off += h->alloc_size;
  }
  close(fd);
  return true;
}

bool TrunkAllocator::ScanRebuildLocked(std::string* error) {
  free_.clear();
  next_id_ = 0;
  std::string root = store_path_ + "/data/trunk";
  MakeDirs(root);
  DIR* d = opendir(root.c_str());
  if (d == nullptr) {
    *error = "opendir " + root;
    return false;
  }
  int files = 0;
  struct dirent* sub;
  while ((sub = readdir(d)) != nullptr) {
    if (sub->d_name[0] == '.') continue;
    std::string subdir = root + "/" + sub->d_name;
    DIR* d2 = opendir(subdir.c_str());
    if (d2 == nullptr) continue;
    struct dirent* de;
    while ((de = readdir(d2)) != nullptr) {
      unsigned id;
      if (sscanf(de->d_name, "%06u.tk", &id) != 1) continue;
      if (ScanFileLocked(id, subdir + "/" + de->d_name, &free_)) {
        ++files;
        next_id_ = std::max(next_id_, id + 1);
      }
    }
    closedir(d2);
  }
  closedir(d);
  int64_t fb = 0;
  for (const auto& [size, blocks] : free_) fb += size * blocks.size();
  FDFS_LOG_INFO("trunk allocator: %d files scanned, %lld free bytes, next=%u",
                files, static_cast<long long>(fb), next_id_);
  return true;
}

std::optional<TrunkLocation> TrunkAllocator::CreateTrunkFileLocked(
    std::string* error) {
  uint32_t id = next_id_++;
  int fd = OpenTrunkFd(store_path_, id, /*create=*/true);
  if (fd < 0) {
    *error = std::string("create trunk file: ") + strerror(errno);
    return std::nullopt;
  }
  // Sparse pre-allocation (reference: trunk_create_file_advance pre-creates
  // 64 MB files) with one whole-file free block.
  TrunkSlotHeader h;
  h.type = kTrunkSlotFree;
  h.alloc_size = static_cast<uint32_t>(trunk_file_size_);
  bool ok = ftruncate(fd, trunk_file_size_) == 0 && WriteSlotHeader(fd, 0, h);
  close(fd);
  if (!ok) {
    *error = std::string("init trunk file: ") + strerror(errno);
    return std::nullopt;
  }
  TrunkLocation loc;
  loc.trunk_id = id;
  loc.offset = 0;
  loc.alloc_size = static_cast<uint32_t>(trunk_file_size_);
  clean_files_.insert(id);
  return loc;
}

std::optional<TrunkLocation> TrunkAllocator::Alloc(int64_t payload_size) {
  std::lock_guard<RankedMutex> lk(mu_);
  int64_t need = AlignSlot(payload_size);
  if (need > trunk_file_size_) return std::nullopt;

  auto it = free_.lower_bound(need);  // best fit
  TrunkLocation block;
  if (it == free_.end()) {
    std::string err;
    auto fresh = CreateTrunkFileLocked(&err);
    if (!fresh.has_value()) {
      FDFS_LOG_ERROR("trunk alloc: %s", err.c_str());
      return std::nullopt;
    }
    block = *fresh;
  } else {
    block.trunk_id = it->second.back().trunk_id;
    block.offset = it->second.back().offset;
    block.alloc_size = static_cast<uint32_t>(it->first);
    it->second.pop_back();
    if (it->second.empty()) free_.erase(it);
  }

  clean_files_.erase(block.trunk_id);  // a peer may now learn of this file
  int fd = OpenTrunkFd(store_path_, block.trunk_id, /*create=*/false);
  if (fd < 0) {
    // Popped block goes back on ANY failure — a transient EIO must not
    // leak capacity from the pool until the next scan-rebuild.
    free_[block.alloc_size].push_back({block.trunk_id, block.offset});
    return std::nullopt;
  }
  int64_t remainder = static_cast<int64_t>(block.alloc_size) - need;
  uint32_t used = remainder >= kTrunkMinSplit
                      ? static_cast<uint32_t>(need)
                      : block.alloc_size;  // tiny remainder stays padding
  // 'D' header FIRST: it makes the allocation durable (a rebuilt
  // allocator will never hand this slot out again), and ordering it
  // before the split keeps every failure path a clean whole-block
  // restore.
  TrunkSlotHeader dh;
  dh.type = kTrunkSlotData;
  dh.alloc_size = used;
  dh.mtime = static_cast<uint32_t>(time(nullptr));
  if (!WriteSlotHeader(fd, block.offset, dh)) {
    close(fd);
    free_[block.alloc_size].push_back({block.trunk_id, block.offset});
    return std::nullopt;
  }
  if (used != block.alloc_size) {
    TrunkSlotHeader fh;
    fh.type = kTrunkSlotFree;
    fh.alloc_size = static_cast<uint32_t>(remainder);
    if (!WriteSlotHeader(fd, block.offset + need, fh)) {
      // Pool still owns the remainder (Alloc never re-reads headers); the
      // missing 'F' header only matters to a future scan-rebuild, whose
      // torn-chain reclaim recovers exactly this extent.
      FDFS_LOG_WARN("trunk %06u: split header write failed at %lld",
                    block.trunk_id,
                    static_cast<long long>(block.offset + need));
    }
    free_[remainder].push_back(
        {block.trunk_id, block.offset + static_cast<uint32_t>(need)});
  }
  close(fd);
  TrunkLocation out;
  out.trunk_id = block.trunk_id;
  out.offset = block.offset;
  out.alloc_size = used;
  return out;
}

int TrunkAllocator::EnsureFreeReserve(int64_t min_free_bytes) {
  std::lock_guard<RankedMutex> lk(mu_);
  int64_t have = 0;
  for (const auto& [size, blocks] : free_)
    have += size * static_cast<int64_t>(blocks.size());
  int created = 0;
  while (have < min_free_bytes) {
    std::string err;
    auto loc = CreateTrunkFileLocked(&err);
    if (!loc.has_value()) {
      FDFS_LOG_WARN("trunk pre-allocation stopped: %s", err.c_str());
      break;
    }
    free_[loc->alloc_size].push_back({loc->trunk_id, loc->offset});
    have += loc->alloc_size;
    ++created;
  }
  return created;
}

int TrunkAllocator::ReclaimEmptyFiles(int keep) {
  std::lock_guard<RankedMutex> lk(mu_);
  // A trunk file is reclaimable when its free blocks cover every byte
  // (frees are not merged, so sum per trunk id).
  std::unordered_map<uint32_t, int64_t> free_per_file;
  for (const auto& [size, blocks] : free_)
    for (const Block& b : blocks) free_per_file[b.trunk_id] += size;
  std::vector<uint32_t> empty;
  for (const auto& [id, bytes] : free_per_file)
    if (bytes >= trunk_file_size_ && clean_files_.count(id)) empty.push_back(id);
  if (static_cast<int>(empty.size()) <= keep) return 0;
  std::sort(empty.begin(), empty.end());
  // Keep the LOWEST ids as the hot reserve; reclaim the rest.
  std::unordered_set<uint32_t> victims(empty.begin() + keep, empty.end());
  for (auto it = free_.begin(); it != free_.end();) {
    auto& blocks = it->second;
    blocks.erase(std::remove_if(blocks.begin(), blocks.end(),
                                [&](const Block& b) {
                                  return victims.count(b.trunk_id) > 0;
                                }),
                 blocks.end());
    it = blocks.empty() ? free_.erase(it) : std::next(it);
  }
  for (uint32_t id : victims) {
    clean_files_.erase(id);
    unlink(TrunkFilePath(store_path_, id).c_str());
  }
  FDFS_LOG_INFO("trunk compaction: reclaimed %zu empty trunk files",
                victims.size());
  return static_cast<int>(victims.size());
}

bool TrunkAllocator::Free(const TrunkLocation& loc) {
  std::lock_guard<RankedMutex> lk(mu_);
  if (!MarkSlotFree(store_path_, loc)) return false;
  free_[loc.alloc_size].push_back({loc.trunk_id, loc.offset});
  return true;
}

int64_t TrunkAllocator::free_bytes() const {
  std::lock_guard<RankedMutex> lk(mu_);
  int64_t fb = 0;
  for (const auto& [size, blocks] : free_) fb += size * blocks.size();
  return fb;
}

int TrunkAllocator::trunk_file_count() const {
  std::lock_guard<RankedMutex> lk(mu_);
  return static_cast<int>(next_id_);
}

int TrunkAllocator::VerifyFreeMap(std::string* report) const {
  std::lock_guard<RankedMutex> lk(mu_);
  std::map<int64_t, std::vector<Block>> disk;
  for (uint32_t id = 0; id < next_id_; ++id)
    ScanFileLocked(id, TrunkFilePath(store_path_, id), &disk);
  auto count = [](const std::map<int64_t, std::vector<Block>>& m) {
    size_t n = 0;
    for (const auto& [s, v] : m) n += v.size();
    return n;
  };
  int mismatches = 0;
  for (const auto& [size, blocks] : disk) {
    auto it = free_.find(size);
    size_t have = it == free_.end() ? 0 : it->second.size();
    if (have != blocks.size())
      mismatches += static_cast<int>(
          std::max(have, blocks.size()) - std::min(have, blocks.size()));
  }
  for (const auto& [size, blocks] : free_)
    if (disk.find(size) == disk.end())
      mismatches += static_cast<int>(blocks.size());
  if (report != nullptr) {
    char buf[160];
    std::snprintf(buf, sizeof(buf),
                  "disk_free_blocks=%zu pool_free_blocks=%zu mismatches=%d",
                  count(disk), count(free_), mismatches);
    *report = buf;
  }
  return mismatches;
}

// -- trunk server RPCs ----------------------------------------------------

namespace {

constexpr int64_t kRpcMax = 4096;

// Pooled connection to the elected trunk server (reference:
// connection_pool.c — the daemon used to open a fresh TCP connection
// per allocation RPC).  One cached fd PER THREAD: trunk RPCs run on
// every nio/dio worker, and a process-global fd would serialize all of
// them on one mutex held across network IO.  The cache survives across
// calls and reconnects when the endpoint moves or the socket dies.
struct TrunkRpcCache {
  std::string ip;
  int port = 0;
  int fd = -1;
  ~TrunkRpcCache() {
    if (fd >= 0) close(fd);
  }
};
thread_local TrunkRpcCache g_trunk_rpc;

bool TrunkRpcExchange(int fd, uint8_t cmd, const std::string& body,
                      std::string* resp, uint8_t* status, int timeout_ms) {
  uint8_t hdr[kHeaderSize];
  PutInt64BE(static_cast<int64_t>(body.size()), hdr);
  hdr[8] = cmd;
  hdr[9] = 0;
  if (!SendAll(fd, hdr, sizeof(hdr), timeout_ms) ||
      !SendAll(fd, body.data(), body.size(), timeout_ms) ||
      !RecvAll(fd, hdr, sizeof(hdr), timeout_ms))
    return false;
  int64_t len = GetInt64BE(hdr);
  *status = hdr[9];
  if (len < 0 || len > kRpcMax) return false;
  resp->resize(static_cast<size_t>(len));
  return len == 0 || RecvAll(fd, resp->data(), resp->size(), timeout_ms);
}

bool TrunkRpc(const std::string& ip, int port, uint8_t cmd,
              const std::string& body, std::string* resp, uint8_t* status,
              int timeout_ms) {
  bool reused = g_trunk_rpc.fd >= 0 && g_trunk_rpc.ip == ip &&
                g_trunk_rpc.port == port;
  if (g_trunk_rpc.fd >= 0 && !reused) {
    close(g_trunk_rpc.fd);  // trunk server moved
    g_trunk_rpc.fd = -1;
  }
  if (g_trunk_rpc.fd < 0) {
    std::string err;
    g_trunk_rpc.fd = TcpConnect(ip, port, timeout_ms, &err);
    if (g_trunk_rpc.fd < 0) return false;
    g_trunk_rpc.ip = ip;
    g_trunk_rpc.port = port;
    reused = false;
  }
  if (TrunkRpcExchange(g_trunk_rpc.fd, cmd, body, resp, status, timeout_ms))
    return true;
  close(g_trunk_rpc.fd);
  g_trunk_rpc.fd = -1;
  // A REUSED connection may simply have gone stale (trunk server
  // restarted): reconnect and retry the whole exchange once.  A fresh
  // connection's failure is real — and no blind retry after a recv-side
  // failure could double-allocate a slot, so the retry happens only via
  // this single reconnect path.
  if (!reused) return false;
  std::string err;
  g_trunk_rpc.fd = TcpConnect(ip, port, timeout_ms, &err);
  if (g_trunk_rpc.fd < 0) return false;
  g_trunk_rpc.ip = ip;
  g_trunk_rpc.port = port;
  if (TrunkRpcExchange(g_trunk_rpc.fd, cmd, body, resp, status, timeout_ms))
    return true;
  close(g_trunk_rpc.fd);
  g_trunk_rpc.fd = -1;
  return false;
}

std::string PackLoc(const TrunkLocation& loc) {
  std::string out(12, '\0');
  uint8_t* p = reinterpret_cast<uint8_t*>(out.data());
  PutInt32BE(loc.trunk_id, p);
  PutInt32BE(loc.offset, p + 4);
  PutInt32BE(loc.alloc_size, p + 8);
  return out;
}

}  // namespace

std::optional<TrunkLocation> TrunkAllocRpc(const std::string& ip, int port,
                                           const std::string& group,
                                           int64_t payload_size,
                                           int64_t epoch, int timeout_ms) {
  std::string body;
  PutFixedField(&body, group, kGroupNameMaxLen);
  char num[8];
  PutInt64BE(payload_size, reinterpret_cast<uint8_t*>(num));
  body.append(num, 8);
  PutInt64BE(epoch, reinterpret_cast<uint8_t*>(num));
  body.append(num, 8);
  std::string resp;
  uint8_t status = 0;
  if (!TrunkRpc(ip, port, static_cast<uint8_t>(StorageCmd::kTrunkAllocSpace),
                body, &resp, &status, timeout_ms) ||
      status != 0 || resp.size() < 12)
    return std::nullopt;
  const uint8_t* p = reinterpret_cast<const uint8_t*>(resp.data());
  TrunkLocation loc;
  loc.trunk_id = GetInt32BE(p);
  loc.offset = GetInt32BE(p + 4);
  loc.alloc_size = GetInt32BE(p + 8);
  return loc;
}

bool TrunkConfirmRpc(const std::string& ip, int port, const std::string& group,
                     const TrunkLocation& loc, int64_t epoch, int timeout_ms) {
  std::string body;
  PutFixedField(&body, group, kGroupNameMaxLen);
  body += PackLoc(loc);
  char num[8];
  PutInt64BE(epoch, reinterpret_cast<uint8_t*>(num));
  body.append(num, 8);
  std::string resp;
  uint8_t status = 0;
  return TrunkRpc(ip, port,
                  static_cast<uint8_t>(StorageCmd::kTrunkAllocConfirm), body,
                  &resp, &status, timeout_ms) &&
         status == 0;
}

bool TrunkFreeRpc(const std::string& ip, int port, const std::string& group,
                  const TrunkLocation& loc, int64_t epoch, int timeout_ms) {
  std::string body;
  PutFixedField(&body, group, kGroupNameMaxLen);
  body += PackLoc(loc);
  char num[8];
  PutInt64BE(epoch, reinterpret_cast<uint8_t*>(num));
  body.append(num, 8);
  std::string resp;
  uint8_t status = 0;
  return TrunkRpc(ip, port,
                  static_cast<uint8_t>(StorageCmd::kTrunkFreeSpace), body,
                  &resp, &status, timeout_ms) &&
         status == 0;
}

}  // namespace fdfs
