// Storage daemon: epoll nio loop + request handlers + upload pipeline.
//
// Reference map (SURVEY.md §2.2):
// - connection state machine / stage flags → storage/storage_nio.c
//   (client_sock_read/client_sock_write, FDFS_STORAGE_STAGE_NIO_*)
// - per-command handlers → storage/storage_service.c
//   (storage_deal_task, storage_upload_file, storage_server_download_file…)
// - chunked disk IO with rolling checksum → storage/storage_dio.c
//   (dio_write_file: the loop the dedup plugin instruments)
// - binlog on every mutation → storage/storage_sync.c:storage_binlog_write
#pragma once

#include <sys/epoll.h>

#include <array>
#include <atomic>
#include <cstdio>
#include <cstdint>
#include <memory>
#include <mutex>

#include "common/lockrank.h"
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "common/eventlog.h"
#include "common/heatsketch.h"
#include "common/metrog.h"
#include "common/sloeval.h"
#include "common/stats.h"
#include "common/trace.h"
#include "common/workers.h"

#include "common/bytes.h"
#include "common/protocol_gen.h"
#include "common/net.h"
#include "storage/admission.h"
#include "storage/binlog.h"
#include "storage/chunkstore.h"
#include "storage/config.h"
#include "storage/dedup.h"
#include "storage/hotrepl.h"
#include "storage/recovery.h"
#include "storage/rebalance.h"
#include "storage/scrub.h"
#include "storage/store.h"
#include "storage/sync.h"
#include "storage/tracker_client.h"
#include "storage/trunk.h"

namespace fdfs {

// Per-op counters (reference: FDFSStorageStat in tracker/tracker_types.h,
// reported to the tracker with each beat).  Atomics: written by the nio
// loop, snapshotted by the tracker-reporter thread.
struct StorageStats {
  std::atomic<int64_t> total_upload{0}, success_upload{0};
  std::atomic<int64_t> total_download{0}, success_download{0};
  std::atomic<int64_t> total_delete{0}, success_delete{0};
  std::atomic<int64_t> total_append{0}, success_append{0};
  std::atomic<int64_t> total_set_meta{0}, success_set_meta{0};
  std::atomic<int64_t> total_get_meta{0}, success_get_meta{0};
  std::atomic<int64_t> total_query{0}, success_query{0};
  std::atomic<int64_t> dedup_hits{0};
  std::atomic<int64_t> dedup_bytes_saved{0};
  std::atomic<int64_t> bytes_uploaded{0}, bytes_downloaded{0};
  std::atomic<int64_t> last_source_update{0};  // ts of last client mutation

  // Restart-safe counters (reference: storage_write_to_stat_file() /
  // data/storage_stat.dat).
  bool SaveToFile(const std::string& path) const;
  bool LoadFromFile(const std::string& path);

  // Restart-persisted slot count: slots [0, kPersisted) of the beat blob
  // (protocol_gen.h kBeatStatNames) come from this struct; the server's
  // beat callback fills the live slots above it.
  static constexpr int kPersisted = 19;

  // Beat-blob prefix (shared contract with tracker/cluster.cc JSON).
  // Writes exactly kPersisted slots; the caller owns the rest.
  void Snapshot(int64_t* out) const {
    out[0] = total_upload; out[1] = success_upload;
    out[2] = total_download; out[3] = success_download;
    out[4] = total_delete; out[5] = success_delete;
    out[6] = total_append; out[7] = success_append;
    out[8] = total_set_meta; out[9] = success_set_meta;
    out[10] = total_get_meta; out[11] = success_get_meta;
    out[12] = total_query; out[13] = success_query;
    out[14] = bytes_uploaded; out[15] = bytes_downloaded;
    out[16] = dedup_hits; out[17] = dedup_bytes_saved;
    out[18] = last_source_update;
  }
};

class StorageServer {
 public:
  explicit StorageServer(StorageConfig cfg);
  ~StorageServer();

  bool Init(std::string* error);
  void Run();
  void Stop();
  EventLoop& loop() { return loop_; }
  const StorageStats& stats() const { return stats_; }
  StatsRegistry& registry() { return registry_; }
  const StorageConfig& config() const { return cfg_; }
  BinlogWriter& binlog() { return binlog_; }
  TrackerReporter* reporter() { return reporter_.get(); }
  void DumpState();  // SIGUSR1 analogue of storage_dump.c

 private:
  enum class ConnState { kRecvHeader, kRecvFixed, kRecvFile, kSend };

  struct NioThread;  // one epoll loop + its connections (storage_nio.c)

  // Streaming source for recipe (chunked-file) downloads, assembled
  // scatter-gather (the PR 5 read-path overhaul): per refill round a
  // bounded batch of spans is staged — cache-hit spans REFERENCE the
  // read cache's shared buffers (zero copy), cold spans pread into one
  // pooled buffer (reused across rounds; its capacity is the only
  // steady-state allocation) — and the whole batch flushes to the
  // socket via one sendmsg iovec per round.  A multi-GB logical file
  // never occupies more than one batch of memory and never stalls the
  // loop's other connections (the reference's dio read loop).
  struct RecipeStream {
    struct Span {
      // Cache-hit spans hold the cache entry alive via `owner` (an
      // eviction or invalidation mid-send cannot free the bytes);
      // cold spans index into `pool` (offset, not pointer — the pool
      // resizes once per round BEFORE any span is flushed).
      std::shared_ptr<const std::string> owner;
      size_t off = 0;   // offset into *owner or pool
      size_t len = 0;
    };
    Recipe recipe;
    ChunkStore* cs = nullptr;
    size_t idx = 0;          // next recipe entry
    int64_t skip = 0;        // bytes to skip inside entry `idx` (range start)
    int64_t remaining = 0;   // logical bytes still to send
    bool pinned = false;
    std::vector<Span> spans;   // current round, [span_idx..) unsent
    size_t span_idx = 0;
    size_t span_off = 0;       // progress inside spans[span_idx]
    std::string pool;          // cold-read buffer for the current round
    bool HasPending() const { return span_idx < spans.size(); }
    // Pins (ChunkStore::PinRecipe) keep the chunks on disk while the
    // stream is in flight even if the file is deleted concurrently —
    // the POSIX open-fd guarantee flat files get from sendfile.
    ~RecipeStream() {
      if (pinned && cs != nullptr) cs->UnpinRecipe(recipe);
    }
  };

  // Negotiated-upload session (UPLOAD_RECIPE -> UPLOAD_CHUNKS): phase 1
  // parked the parsed recipe here with a pin on every chunk (present
  // ones must survive concurrent delete/GC until the commit references
  // them).  Owned by ingest_sessions_ between the two requests; phase 2
  // takes it out (one commit per session), and the sweep timer expires
  // sessions whose client vanished.  The destructor unpins, so every
  // exit path — commit, abort, timeout, shutdown — releases the pins.
  struct UploadSession {
    int64_t id = 0;
    int spi = 0;
    std::string ext;
    uint32_t crc32 = 0;
    Recipe recipe;           // full chunk list (lengths pre-validated)
    std::string needed;      // phase-1 bitmap (1 = client ships)
    int64_t needed_bytes = 0;
    ChunkStore* cs = nullptr;
    int64_t deadline_s = 0;  // wall-clock expiry (sweep timer)
    ~UploadSession() {
      if (cs != nullptr) cs->UnpinRecipe(recipe);
    }
  };

  struct Conn {
    int fd = -1;
    ConnState state = ConnState::kRecvHeader;
    // recv
    uint8_t header[kHeaderSize];
    size_t header_got = 0;
    int64_t pkg_len = 0;
    uint8_t cmd = 0;
    std::string fixed;          // in-memory body (or fixed prefix for upload)
    size_t fixed_need = 0;
    int64_t body_consumed = 0;  // bytes of pkg_len read so far
    bool close_after_send = false;  // early error left unread request bytes
    // upload streaming
    int file_fd = -1;
    std::string tmp_path;
    int64_t file_remaining = 0;
    int64_t file_size = 0;
    int store_path_index = 0;
    std::string ext;
    Sha1Stream sha1;
    uint32_t crc32 = 0;
    bool hashing = false;
    uint8_t replica_op = 0;     // set for SYNC_* ops (no binlog re-emit)
    std::string sync_remote;    // target remote filename for SYNC_CREATE
    int64_t range_offset = 0;   // append/modify replay write position
    std::string slave_prefix;   // UPLOAD_SLAVE_FILE name prefix
    bool discarding = false;    // draining a rejected request's body bytes
    uint8_t pending_status = 0; // error to send once the drain completes
    std::string pending_body;   // response body for that error (shed hint)
    std::string busy_key;       // in-place-mutated file this conn holds
    // send
    std::string out;
    size_t out_off = 0;
    int send_fd = -1;
    int64_t send_off = 0;
    int64_t send_remaining = 0;
    std::unique_ptr<RecipeStream> rstream;  // chunked download source
    // threading
    NioThread* owner = nullptr;   // the nio loop this conn lives on
    bool async_pending = false;   // a dio worker owns the request right now
    bool dead = false;            // closed while async_pending: zombie
    // How long THIS request sat in the dio queue before a worker picked
    // it up (stamped by the worker; inside the work window).  Traced
    // requests get it as a dio.queue_wait child span so fdfs_trace
    // timelines separate waiting from working.
    int64_t dio_wait_us = 0;
    // access log bookkeeping (per-stage timings, SURVEY.md §5: the
    // rebuild logs recv/work splits, not just the total)
    int64_t req_start_us = 0;
    int64_t recv_done_us = 0;   // body fully received (recv stage end)
    int64_t work_start_us = 0;  // dio-stage begin (fingerprint/write)
    // chunked-upload stage splits within the work window (0 when the
    // request did not take that stage)
    int64_t fp_us = 0;          // fingerprint wall (sidecar RPC / serial)
    int64_t fp_lock_us = 0;     // share of fp_us spent queued on the
                                // sidecar RPC mutex (engine serialization)
    int64_t cswrite_us = 0;     // chunk-store writes
    int64_t binlog_us = 0;      // binlog append
    std::string peer_ip;
    // Negotiated upload (UPLOAD_CHUNKS): the session this request
    // commits, plus the missing/total split RecordRequestSpans turns
    // into the ingest.chunks trace annotation (set by both phases).
    int64_t ingest_session = 0;
    int64_t ingest_chunks_total = 0;
    int64_t ingest_chunks_missing = 0;
    // Hot-key heat telemetry: handlers that resolve a file-id stamp it
    // here (with the op class) so LogAccess — the accounting choke
    // point — feeds the heat sketch exactly once per request.
    std::string heat_key;
    uint8_t heat_op = 0;  // HeatOp
    // Distributed tracing: context from a TRACE_CTX prefix frame,
    // consumed by the next request (ResetForNextRequest clears it).
    // trace_span is the request's root span id, allocated when the
    // frame completes so mutation paths can correlate (binlog ->
    // replication) before the span itself is recorded at LogAccess.
    TraceCtx trace_ctx;
    bool traced = false;
    uint32_t trace_span = 0;
    // Request QoS: class from a PRIORITY prefix frame (kPriorityUntagged
    // = none seen; the dispatch then defaults by opcode).  Consumed by
    // the next request like trace_ctx.  resolved_priority is the class
    // the admission consult actually used, kept for the access log.
    uint8_t priority = 0xFF;
    uint8_t resolved_priority = 0;
    // Bytes this request added to the server-wide in-flight ledger at
    // admission (its pkg_len); subtracted exactly once when the request
    // finishes (LogAccess) or the conn dies mid-request (CloseConn).
    int64_t inflight_acct = 0;
    // This request was refused by the admission ladder: keep it out of
    // the per-opcode count/error/latency stats — a shed EBUSY feeding
    // the error_rate_pct SLO would hold the breach (= pressure 1.0)
    // active and the ladder could never relax off its own refusals.
    // The admission controller's shed counters carry the accounting.
    bool shed_resp = false;
  };

  struct NioThread {
    std::unique_ptr<EventLoop> loop;
    std::thread thread;
    std::unordered_map<int, std::unique_ptr<Conn>> conns;  // loop-thread only
    std::vector<std::unique_ptr<Conn>> zombies;            // await dio done
    // Cumulative handler time, fed by this loop's iteration hook and
    // read by the metrics tick for nio.loop_busy_pct.<i> (the per-loop
    // duty cycle the shared loop-lag histogram cannot attribute).
    std::atomic<int64_t> busy_us{0};
    // Sharded accept (ISSUE 18): this reactor's own SO_REUSEPORT
    // listening fd (-1 in round-robin fallback mode, where the main
    // loop accepts and posts).
    int listen_fd = -1;
    // Per-reactor spread telemetry, fed by BOTH accept modes (the
    // reactor's own accept handler, or the main-loop round-robin
    // assignment) so nio.accepts.<i> / nio.conns.<i> always mean "this
    // reactor's share".  Read by gauge-fns under the registry mutex —
    // atomics only.
    std::atomic<int64_t> accepts{0};
    std::atomic<int64_t> live_conns{0};
  };
  // Honest divergence from the reference's fast_task_queue.c pooled-task
  // buffers: each Conn owns its recv/send std::strings, which retain
  // their capacity across requests on a kept-alive connection — the
  // steady-state allocation behavior of the pool without the free-list.
  // The queue half of fast_task_queue maps to WorkerPool (workers.h).

  // -- nio ---------------------------------------------------------------
  EventLoop* ConnLoop(Conn* c) { return c->owner ? c->owner->loop.get() : &loop_; }
  void AdoptConn(NioThread* t, int fd);   // runs on t's loop thread
  // Hand the rest of the current request to the store path's dio pool;
  // `work` runs on a worker (it may build a response via Respond but must
  // not touch the socket/epoll), then the conn resumes on its loop.
  void OffloadToDio(Conn* c, int spi, std::function<void()> work);
  void OnAccept(uint32_t events);
  // Reactor-owned accept (reuseport mode): runs ON t's loop thread, so
  // the accepted conn is adopted inline — no cross-loop Post.
  void OnReactorAccept(NioThread* t);
  // Shared accept tail of both modes: cap refusal + first-conn local-ip
  // capture.  Returns false when the conn was refused (and closed).
  bool AdmitConn(int fd);
  void OnConnEvent(Conn* c, uint32_t events);
  void ReadConn(Conn* c);
  bool WriteConn(Conn* c);          // false => conn closed
  void CloseConn(Conn* c);
  void ResetForNextRequest(Conn* c);
  void Respond(Conn* c, uint8_t status, const std::string& body = "");
  // Stage the next scatter-gather batch of a recipe download (cache
  // lookups + pooled cold preads); false => a chunk vanished mid-stream
  // (caller aborts the connection — the header already went out).
  bool RefillRecipeSpans(RecipeStream* rs);
  // Flush staged spans with sendmsg; same contract as WriteConn's other
  // stages: true = keep going / parked on EPOLLOUT, false = conn closed.
  enum class FlushResult { kDone, kBlocked, kError };
  FlushResult FlushRecipeSpans(Conn* c, RecipeStream* rs);
  // Error response that may leave unread request bytes: drains them (the
  // connection stays usable) and rolls back any in-flight file write.
  void RespondError(Conn* c, uint8_t status);
  // Admission shed: EBUSY carrying the 8-byte BE retry-after-ms hint —
  // RespondError's drain discipline, plus a staged response body.
  void ShedRequest(Conn* c, int64_t retry_after_ms);
  void AbortFileOp(Conn* c);
  // Per-file writer exclusion for streamed in-place mutations: two appends
  // to one appender file interleaving across epoll rounds would corrupt it.
  bool AcquireBusy(Conn* c, const std::string& remote);
  void ReleaseBusy(Conn* c);
  void RespondFile(Conn* c, uint8_t status, int file_fd, int64_t offset,
                   int64_t count);
  // Access log (storage.conf:use_access_log; reference: the per-request
  // "op client_ip status bytes cost_us" lines storage_service.c emits).
  // Also the per-request accounting choke point: every response path runs
  // through here exactly once (req_start_us guards re-entry), so the
  // stats registry's per-opcode counters and latency histograms update
  // here regardless of whether the access log is enabled.
  void LogAccess(Conn* c, uint8_t status, int64_t bytes);
  // Stamp the request's heat-sketch attribution (file-id key + op
  // class); LogAccess feeds the sketch from it exactly once.
  void NoteHeat(Conn* c, HeatOp op, const std::string& key);

  // -- stats registry (common/stats.h; STAT opcode) ----------------------
  // Pre-register per-opcode counters/histograms and the gauge mirrors of
  // live state so hot paths only touch cached atomic pointers.
  void InitStatsRegistry();
  // -- tracing (common/trace.h; TRACE_CTX / TRACE_DUMP opcodes) ----------
  // Retain this request's spans (root + stage children) when it is
  // traced or exceeded the slow threshold; called from LogAccess (the
  // per-request accounting choke point).
  void RecordRequestSpans(Conn* c, uint8_t status, int64_t now_us,
                          int64_t bytes);
  // Remember a traced mutation's context keyed by remote filename so
  // the replication sender stitches the sync hop into the same trace.
  void NoteTracedMutation(Conn* c, const std::string& remote);
  // Refresh the per-peer sync gauges (peers come and go, so these are
  // plain gauges re-set — and pruned — at snapshot time) ahead of a
  // STAT serialization or a metrics-journal tick.
  void RefreshPeerGauges();
  // Refresh snapshot-time gauges (per-peer sync lag) and serialize.
  std::string BuildStatsJson();
  // Metrics tick (slo_eval_interval_s): snapshot the registry, append
  // to the metrics journal, and evaluate the SLO rule table against the
  // previous tick's snapshot (common/metrog.h, common/sloeval.h).
  void MetricsTick();
  // Beat callback: persisted prefix from stats_, live slots from the
  // registry/subsystems (fills kBeatStatCount slots).
  void FillBeatStats(int64_t* out);
  int64_t MaxSyncLagS() const;
  // statvfs every store path and cache the fullest-path percentage.
  // Called at startup, each metrics tick (main loop), and each beat
  // (tracker-client thread) — NEVER from the store.disk_used_pct
  // gauge-fn itself: gauge-fns run under the registry mutex on the nio
  // loop, and statvfs on a stalled mount can block for seconds.
  void RefreshDiskUsedPct();
  // -- gray-failure health layer (common/healthmon.h; HEALTH_STATUS) -----
  // Dedicated "health.probe" thread: every health_probe_interval_s it
  // ACTIVE_TESTs the trackers + the group's sync peers (feeding the
  // passive per-peer table through the NetRpc observer) and runs the
  // per-store-path disk probes (4 KB tmp-write+fsync + read-back) —
  // off the request path, the store.disk_used_pct discipline.
  void HealthProbeMain();
  void RunHealthProbes();
  // HEALTH_STATUS wire body (healthmon Json: peer table + probes +
  // watchdog counts).
  std::string HealthStatusJson();

  // -- dispatch ----------------------------------------------------------
  void OnHeaderComplete(Conn* c);
  void OnFixedComplete(Conn* c);
  void OnFileComplete(Conn* c);
  void SyncCreateComplete(Conn* c);  // replica create (dio worker)
  // Chunk-aware replication receiver (SYNC_QUERY_CHUNKS /
  // SYNC_CREATE_RECIPE): answer which chunks are missing, then build
  // the replica from refs + shipped payloads.
  void HandleSyncQueryChunks(Conn* c);
  void SyncRecipeComplete(Conn* c);  // dio worker
  // Chunk-aware disk-recovery servers (FETCH_RECIPE / FETCH_CHUNK): let
  // a rebuilding peer pull recipes and only the chunk bytes it lacks.
  void HandleFetchRecipe(Conn* c);
  void HandleFetchChunk(Conn* c);
  // Erasure-coded cold tier (EC_RELEASE receiver + the released-chunk
  // remote read hook installed on every chunk store).
  void HandleEcRelease(Conn* c);       // dio worker
  bool FetchChunkFromPeers(int spi, const std::string& digest_hex,
                           int64_t len, std::string* out);
  // Dedup-aware negotiated upload (UPLOAD_RECIPE / UPLOAD_CHUNKS; both
  // run on the store path's dio pool): phase 1 probes + pins + parks a
  // session, phase 2 verifies the shipped chunks and assembles the file.
  void HandleUploadRecipe(Conn* c);    // dio worker
  bool BeginUploadChunks(Conn* c);     // nio: parse prefix, open tmp
  void UploadChunksComplete(Conn* c);  // dio worker
  std::unique_ptr<UploadSession> TakeIngestSession(int64_t id);
  void SweepIngestSessions();          // timer: expire vanished clients
  // Re-register a recovered file's signature/attributions with the
  // dedup plugin (sidecar-mode rebuilds; bytes are local, wire cost 0).
  void ReindexRecovered(DedupPlugin* plugin, const std::string& local,
                        const std::string& file_ref);
  void DeleteWork(Conn* c);          // delete body (dio worker)

  // -- handlers (storage_service.c analogues) ----------------------------
  bool BeginUpload(Conn* c);        // parse fixed, open tmp file
  void FinishUpload(Conn* c);       // mint id, dedup, commit, binlog
  void HandleDownload(Conn* c);
  void HandleDelete(Conn* c);
  void HandleQueryFileInfo(Conn* c);
  void HandleNearDups(Conn* c);
  void HandleSetMetadata(Conn* c);
  void HandleGetMetadata(Conn* c);
  bool BeginClientRange(Conn* c);   // APPEND_FILE / MODIFY_FILE
  void HandleTruncate(Conn* c);     // TRUNCATE_FILE (+ sync replay path)
  bool BeginSlaveUpload(Conn* c);   // UPLOAD_SLAVE_FILE prefix parse
  void FinishSlaveUpload(Conn* c);
  void HandleCreateLink(Conn* c);   // CREATE_LINK + SYNC_CREATE_LINK
  void HandleSyncUpdate(Conn* c);
  bool BeginSyncRange(Conn* c);     // SYNC_APPEND / SYNC_MODIFY prefix parse

  std::string MintFileId(int spi, int64_t size, uint32_t crc,
                         const std::string& ext, bool appender,
                         const TrunkLocation* trunk_loc = nullptr);
  // -- trunk integration (storage/trunk_mgr analogues) -------------------
  void RefreshClusterParams();       // 1s timer: params + trunk role
  bool TrunkEligible(int64_t size) const;
  // Allocate a slot locally (trunk server) or via RPC; nullopt => caller
  // falls back to a flat file.
  std::optional<TrunkLocation> TrunkAlloc(int64_t payload_size);
  void TrunkFree(const TrunkLocation& loc);
  // Store tmp-file content into a trunk slot and mint the ID; "" on
  // failure (caller falls back to flat).
  std::string TrunkStoreUpload(Conn* c);
  void HandleTrunkRpc(Conn* c);      // cmds 27/28/29 server side
  void HandleFetchOnePathBinlog(Conn* c);  // cmd 26 (disk-recovery feed)
  void HandleTrunkDownload(Conn* c, const FileIdParts& parts, int64_t offset,
                           int64_t count);
  // Resolve "group/remote" or "remote" to a local path; empty on error.
  std::string ResolveLocal(const std::string& group,
                           const std::string& remote) const;
  // Existence check that understands trunk names: flat inode present, or
  // the trunk slot is live with this ID's exact identity.
  bool RemoteExists(const std::string& group, const std::string& remote,
                    const std::string& local);
  std::string MyIp() const;

  // -- chunk-level dedup (north star; chunkstore.h) ----------------------
  // Whether this upload takes the chunked path (plugin active, chunking
  // enabled, size over threshold).
  bool ChunkEligible(int64_t size) const;
  ChunkStore* StoreForLocal(const std::string& local) const;
  // Slab-aware recipe access for call sites that may lack a chunk store
  // (dedup off): route through the store's recipe codec (slab record or
  // flat sidecar) when one exists, else the flat .rcp file directly.
  std::optional<Recipe> LoadRecipeFor(const std::string& local) const;
  bool RecipeExistsFor(const std::string& local) const;
  // Chunk the tmp file via the dedup plugin, write unique chunks into the
  // store-path's chunk store, and write the recipe at `rcp_path`.
  // *saved_bytes accumulates duplicate-chunk bytes.  False => caller
  // stores the file flat (fingerprinting unavailable or IO error).
  // Per-upload stage attribution (access-log columns; the bench stage
  // table): fingerprint wall time (sidecar RPC incl. lock wait in
  // sidecar mode, serial CDC+SHA1 in cpu mode), the lock-wait share of
  // it, and chunk-store write time.
  struct ChunkStageUs {
    int64_t fp = 0;
    int64_t fp_lock = 0;
    int64_t cs_write = 0;
  };
  bool StoreChunkedFromTmp(const std::string& tmp_path, int spi,
                           int64_t size, const std::string& rcp_path,
                           const std::string& file_ref,
                           int64_t* saved_bytes, int64_t* chunk_hits,
                           ChunkStageUs* stage = nullptr);
  // Same, against an explicit plugin (the recovery thread uses its own
  // instance — the plugins are not thread-safe, the ChunkStore is).
  bool ChunkedStoreWith(DedupPlugin* plugin, const std::string& tmp_path,
                        int spi, int64_t size, const std::string& rcp_path,
                        const std::string& file_ref, int64_t* saved_bytes,
                        int64_t* chunk_hits, ChunkStageUs* stage = nullptr);
  // Open the logical content at `local`: a plain fd, or a recipe
  // materialized into an unlinked temp file.  -1 when missing.
  int OpenLogical(const std::string& local, int64_t* size);
  // Logical size without opening (plain stat or recipe header); -1 when
  // missing.
  int64_t LogicalSize(const std::string& local) const;
  // Delete logical content: plain unlink, or recipe removal + chunk
  // unref.  Returns errno-style status (0 ok, 2 missing, 5 io).
  int RemoveLogical(const std::string& local, const std::string& file_ref);
  // True when the tracker marked this group draining/retired in the
  // beat trailer: new-file uploads answer EBUSY (reads, replication,
  // and the migrator's loopback ops stay allowed).
  bool DrainingRefusal() const;

  StorageConfig cfg_;
  StoreManager store_;
  BinlogWriter binlog_;
  std::unique_ptr<DedupPlugin> dedup_;
  std::unique_ptr<DedupPlugin> recovery_dedup_;  // recovery-thread instance
  // One content-addressed chunk store per store path (chunk-level dedup).
  std::vector<std::unique_ptr<ChunkStore>> chunk_stores_;
  // Integrity engine: background scrub/quarantine/repair/GC over the
  // chunk stores (storage/scrub.h; SCRUB_STATUS / SCRUB_KICK opcodes).
  // scrub_dedup_ is the scrub thread's own sidecar plugin instance for
  // the batched DEDUP_VERIFY path (plugins are not thread-safe).
  std::unique_ptr<DedupPlugin> scrub_dedup_;
  std::unique_ptr<ScrubManager> scrub_;
  // Rebalance migrator (ISSUE 11): drains this group's files into
  // their jump-hash target groups once the tracker marks the group
  // DRAINING (storage/rebalance.h; rebalance_* beat slots).
  std::unique_ptr<RebalanceManager> rebalance_;
  std::unique_ptr<TrackerReporter> reporter_;
  std::unique_ptr<SyncManager> sync_;
  std::unique_ptr<RecoveryManager> recovery_;
  // Hot-replication fan-out worker (ISSUE 20): runs the tracker's
  // replicate/drop elections delivered in beat-response trailers.
  std::unique_ptr<HotReplManager> hotrepl_;
  EventLoop loop_;                      // main: accept + timers
  int listen_fd_ = -1;
  // nio work threads (storage.conf:work_threads); each reactor owns the
  // connections it accepts for their whole lifetime (reference:
  // storage_nio.c per-thread epoll loops).  With nio_reuseport active
  // every reactor accepts on its own SO_REUSEPORT listener; otherwise
  // the main loop accepts and assigns round-robin.
  std::vector<std::unique_ptr<NioThread>> nio_;
  bool reuseport_active_ = false;       // set once in Init
  size_t next_nio_ = 0;                 // main-loop only (accept)
  std::atomic<int64_t> conn_count_{0};
  std::atomic<int64_t> refused_conn_count_{0};  // over max_connections
  std::atomic<int64_t> disk_used_pct_{0};       // RefreshDiskUsedPct cache
  // Filesystem inodes in use across the store paths (deduped by fsid),
  // refreshed with disk_used_pct_ OFF the registry lock — the
  // store.inodes_used gauge is what the slab-packing win (ISSUE 9) is
  // judged against on small-file corpora.
  std::atomic<int64_t> inodes_used_{0};
  // Gray-failure health layer (ISSUE 17).  Probe latencies are the
  // worst store path's most recent round (gauge-fns read the atomics,
  // never the disk — the disk_used_pct discipline); stalled_threads_
  // mirrors the last watchdog scan for the watchdog.stalled_threads
  // gauge.  probe_slow_noted_ is probe-thread-only state for
  // one-disk.gray-event-per-outage.
  std::atomic<int64_t> probe_read_us_{0};
  std::atomic<int64_t> probe_write_us_{0};
  std::atomic<int64_t> stalled_threads_{0};
  std::atomic<bool> health_stop_{false};
  std::thread health_probe_thread_;
  std::thread inject_stall_thread_;  // watchdog_inject_stall_ms debug aid
  std::vector<bool> probe_slow_noted_;  // per store path; probe thread only
  // dio pools, one per store path (storage.conf:disk_writer_threads;
  // reference: storage_dio.c per-path reader/writer queues).
  std::vector<std::unique_ptr<WorkerPool>> dio_pools_;
  RankedMutex busy_mu_{LockRank::kBusyFiles};
  std::unordered_set<std::string> busy_files_;  // remote names being mutated
  RankedMutex log_mu_{LockRank::kAccessLog};  // access_log_ writes
  StorageStats stats_;
  // Named-stat registry behind the STAT opcode.  Per-opcode handles are
  // indexed by the raw cmd byte (O(1), no lock on the request path).
  StatsRegistry registry_;
  struct OpStats {
    std::atomic<int64_t>* count = nullptr;
    std::atomic<int64_t>* errors = nullptr;
    StatHistogram* latency_us = nullptr;
  };
  std::array<OpStats, 256> op_stats_{};
  // Monitor-facing opcode names (kServedOps), indexed by raw cmd byte —
  // shared by the stats registry and span naming.
  std::array<const char*, 256> op_names_{};
  // Span ring behind TRACE_DUMP + the traced-mutation correlator feeding
  // the replication sender.  slow_request_count_ backs the
  // trace.slow_requests registry gauge.
  std::unique_ptr<TraceRing> trace_;
  TraceCorrelator trace_corr_;
  std::atomic<int64_t> slow_request_count_{0};
  // Flight recorder behind EVENT_DUMP + the SIGUSR1 dump (ISSUE 6):
  // structured cluster events from the scrubber, chunk stores,
  // replication sender, ingest sessions, the slow gate, and config
  // anomalies.  Created in Init() before every subsystem that records.
  std::unique_ptr<EventLog> events_;
  // Telemetry history + SLO engine + heat sketch (ISSUE 8): the metrics
  // journal persists one registry snapshot per tick (METRICS_HISTORY),
  // the evaluator turns the same snapshots into slo.breach/recovered
  // flight-recorder events, and the sketch ranks hot file-ids
  // (HEAT_TOP).  Any may be null (conf-disabled).
  std::unique_ptr<MetricsJournal> metrics_;
  std::unique_ptr<SloEvaluator> slo_;
  std::unique_ptr<HeatSketch> heat_;
  // Admission control & request QoS (ISSUE 19; storage/admission.h):
  // consulted at the request-header stage on every nio thread, ticked
  // on the metrics timer from the same snapshots as slo_.
  // inflight_bytes_ is the admitted-but-unanswered request-byte ledger
  // (one of the controller's pressure signals, and the
  // admission.inflight_bytes gauge).
  std::unique_ptr<AdmissionController> admission_;
  std::atomic<int64_t> inflight_bytes_{0};
  // Previous tick's snapshot (main-loop only: the tick timer is the
  // sole reader/writer) — the delta base for SLO readings.
  StatsSnapshot last_tick_snap_;
  bool have_tick_snap_ = false;
  int64_t last_tick_mono_us_ = 0;
  // Per-loop duty cycle (nio.loop_busy_pct.*): the accept/timers loop's
  // busy accumulator plus per-tick deltas for it and every nio loop
  // (main-loop only, like last_tick_snap_).  Index 0 = the main loop,
  // 1 + i = nio_[i].
  std::atomic<int64_t> main_loop_busy_us_{0};
  std::vector<int64_t> loop_busy_last_;
  // Saturation telemetry handles (nio loop lag / dio queue health),
  // pre-registered so the per-iteration hook touches only atomics.
  StatHistogram* hist_nio_lag_ = nullptr;
  std::atomic<int64_t>* ctr_nio_dispatched_ = nullptr;
  StatHistogram* hist_dio_wait_ = nullptr;
  StatHistogram* hist_dio_service_ = nullptr;
  // Outbound peer-RPC latency (all op classes), Observed by the health
  // monitor on every successful NetRpc — the peer_rpc_p99_ms SLO input.
  StatHistogram* hist_peer_rpc_ = nullptr;
  StatHistogram* hist_upload_bytes_ = nullptr;
  StatHistogram* hist_download_bytes_ = nullptr;
  std::atomic<int64_t>* ctr_sync_bytes_saved_wire_ = nullptr;
  std::atomic<int64_t>* ctr_sync_digest_mismatch_ = nullptr;
  std::atomic<int64_t>* ctr_chunkfetch_batches_ = nullptr;
  std::atomic<int64_t>* ctr_chunkfetch_chunks_ = nullptr;
  std::atomic<int64_t>* ctr_chunkfetch_bytes_ = nullptr;
  std::atomic<int64_t>* ctr_dedup_chunk_hits_ = nullptr;
  std::atomic<int64_t>* ctr_dedup_chunk_misses_ = nullptr;
  // Negotiated-upload (ingest edge) accounting: completed recipe
  // uploads, chunk bytes the client did NOT ship because the store
  // already held them, and server-observable fallbacks (no chunk
  // store, failed/expired sessions — the client then re-sends via
  // plain UPLOAD_FILE).
  std::atomic<int64_t>* ctr_ingest_recipe_uploads_ = nullptr;
  std::atomic<int64_t>* ctr_ingest_bytes_saved_wire_ = nullptr;
  std::atomic<int64_t>* ctr_ingest_fallbacks_ = nullptr;
  // Ranged downloads (the parallel client splits a file into ranges):
  // requests with a nonzero offset or an explicit byte count, and the
  // bytes they actually served.
  std::atomic<int64_t>* ctr_download_ranged_requests_ = nullptr;
  std::atomic<int64_t>* ctr_download_ranged_bytes_ = nullptr;
  // Vectored cold-span reads (ISSUE 18): per RecipeStream refill round
  // the slab-resident cold spans batch into one preadv per (slab file,
  // contiguous run).  spans > batches is the syscall-reduction proof on
  // a chunked corpus; per-span pread fallbacks don't count here.
  std::atomic<int64_t>* ctr_dio_preadv_batches_ = nullptr;
  std::atomic<int64_t>* ctr_dio_preadv_spans_ = nullptr;
  // Parked phase-1 sessions keyed by id (ingest_mu_); swept by timer.
  RankedMutex ingest_mu_{LockRank::kIngestSessions};
  std::unordered_map<int64_t, std::unique_ptr<UploadSession>>
      ingest_sessions_;
  std::atomic<int64_t> next_ingest_session_{1};
  // Local IP as seen by the first accepted connection, published
  // lock-free: with sharded accept ANY reactor thread may capture it
  // while handlers on other threads read it.  State 0 = empty, 1 = a
  // writer owns the string, 2 = set (release-published; readers acquire
  // before touching my_ip_).
  std::string my_ip_;
  std::atomic<int> my_ip_state_{0};

  // Trunk state (cluster-global params from the tracker; SURVEY §2.3).
  // Guarded by trunk_mu_: mutated by the main-loop param timer, read by
  // every nio/dio thread.  Handlers copy the shared_ptr under the lock
  // and use the allocator outside it (the allocator locks internally);
  // the timer swaps the pointer, never mutates a live allocator.
  mutable RankedMutex trunk_mu_{LockRank::kTrunkRole};
  bool trunk_enabled_ = false;
  int64_t slot_min_size_ = 256;
  int64_t slot_max_size_ = 16 * 1024 * 1024;
  int64_t trunk_file_size_ = 64LL * 1024 * 1024;
  std::string trunk_ip_;
  int trunk_port_ = 0;
  int64_t trunk_epoch_ = 0;  // fencing token (see trunk.h RPC note)
  bool is_trunk_server_ = false;
  // Role-regain safety: after losing and regaining the trunk role, hold
  // this many seconds before rescanning (interim allocations may still be
  // replicating in); see RefreshClusterParams.
  static constexpr int kTrunkRegainGraceS = 3;
  bool held_trunk_role_before_ = false;
  int64_t trunk_regain_not_before_ = 0;
  bool trunk_size_err_logged_ = false;
  std::shared_ptr<TrunkAllocator> trunk_alloc_;
  FILE* access_log_ = nullptr;
  std::string stat_path_;
};

}  // namespace fdfs
