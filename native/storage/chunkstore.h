// Content-addressed chunk store + file recipes: the disk layer of
// chunk-level dedup.
//
// North star (BASELINE.json): the upload path chunks each stream
// (CDC), fingerprints the chunks (SHA1 — on TPU in sidecar mode), and
// writes only bytes the store has never seen.  This class owns the
// physical side:
//
//   <store_path>/data/chunks/<d0d1>/<d2d3>/<40-hex>   chunk payloads
//   <local path>.rcp                                  per-file recipes
//
// A recipe lists (digest, length) per chunk; logical reads reassemble.
// The store is self-healing: Put() is write-if-absent keyed by content
// digest, so a stale "duplicate" verdict can never lose data — the byte
// payload is always provided alongside the digest.
//
// Refcounts are RAM-only and rebuilt by scanning every recipe at startup
// (which doubles as orphan-chunk GC); crash-safety therefore never
// depends on a refcount file.  Single acquisition order: this class is
// self-locked and calls nothing that locks.
//
// Reference anchor: replaces the inode-per-file write in
// storage/storage_dio.c:dio_write_file() for deduplicated uploads.
#pragma once

#include <cstdint>
#include <mutex>
#include <optional>
#include <string>
#include <unordered_map>
#include <vector>

namespace fdfs {

struct RecipeEntry {
  std::string digest_hex;  // 40-char lowercase SHA1
  int64_t length = 0;
};

struct Recipe {
  int64_t logical_size = 0;
  std::vector<RecipeEntry> chunks;
};

// Recipe file codec ("FDFSRCP1" magic + BE fields; see chunkstore.cc).
bool WriteRecipeFile(const std::string& path, const Recipe& r,
                     std::string* err);
std::optional<Recipe> ReadRecipeFile(const std::string& path);

class ChunkStore {
 public:
  explicit ChunkStore(std::string store_path);

  // Scan every *.rcp under the data dir: rebuild refcounts and delete
  // orphaned chunk files.  Call once at startup, before serving.
  void RebuildFromRecipes();

  // Write-if-absent + take a reference.  Returns true when the chunk was
  // already present (the dedup "hit"); *err set only on write failure.
  bool PutAndRef(const std::string& digest_hex, const char* data,
                 size_t len, bool* existed, std::string* err);

  // Drop one reference per entry of the recipe; chunks reaching zero are
  // unlinked.
  void UnrefAll(const Recipe& r);

  // Take one additional reference per recipe entry (recipe duplication:
  // CREATE_LINK of a chunked file).  False (and no refs taken) if any
  // chunk is absent.
  bool RefAll(const Recipe& r);

  // Is this chunk live (referenced by at least one recipe)?
  bool Has(const std::string& digest_hex) const;

  // Batched presence check under ONE lock acquisition: byte i of the
  // result is 0 when digests[i] is live, 1 when it must be shipped.
  // (The chunk-aware replication receiver runs this on the nio loop —
  // per-digest locking would serialize against every concurrent
  // upload's PutAndRef.)
  std::string HaveMask(const std::vector<std::string>& digests) const;

  // Take one reference on an already-live chunk; false when absent
  // (the replication receiver then reports the race and the sender
  // falls back to a full copy).
  bool RefOne(const std::string& digest_hex);

  // Read one chunk fully into *out (resized).  False when missing/short.
  bool ReadChunk(const std::string& digest_hex, int64_t expect_len,
                 std::string* out) const;

  // Presence probe + pin in ONE lock acquisition, for the negotiated
  // upload's phase-1 answer: byte i of the result is 0 when chunk i is
  // live (and now pinned against unlink until the session's
  // UnpinRecipe), 1 when the client must ship it.  A separate
  // HaveMask-then-PinRecipe would let a delete unlink a "present" chunk
  // in the gap; pinning absent digests is harmless (the unpin erases
  // the entry), so every entry is pinned and the whole recipe unpins.
  std::string PinAndMask(const Recipe& r);

  // Transient stream pins: an in-flight chunked download holds a pin per
  // recipe entry so a concurrent delete cannot unlink bytes it is still
  // sending (POSIX open-fd semantics for flat files, recreated here).
  // A pinned chunk whose refcount hits zero defers its unlink until the
  // last pin drops.  Pins are RAM-only — a crash loses only streams.
  void PinRecipe(const Recipe& r);
  void UnpinRecipe(const Recipe& r);

  // Read a recipe file and pin its chunks atomically w.r.t. UnrefAll: a
  // delete landing between a plain ReadRecipeFile and PinRecipe could
  // unref+unlink chunks the stream is about to send.  Under the store
  // mutex: read, verify every chunk is still referenced, then pin.
  // nullopt (no pins taken) when the recipe is gone or any chunk was
  // already unreferenced — the caller fails the download with ENOENT
  // before the first byte, not mid-stream.
  std::optional<Recipe> ReadRecipeAndPin(const std::string& path);

  std::string ChunkPath(const std::string& digest_hex) const;

  int64_t unique_chunks() const;
  int64_t unique_bytes() const;

 private:
  std::string store_path_;
  mutable std::mutex mu_;
  std::unordered_map<std::string, int64_t> refs_;
  std::unordered_map<std::string, int64_t> pins_;      // in-flight streams
  std::unordered_map<std::string, int64_t> deferred_;  // digest -> length
  int64_t unique_bytes_ = 0;
};

}  // namespace fdfs
