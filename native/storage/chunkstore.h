// Content-addressed chunk store + file recipes: the disk layer of
// chunk-level dedup.
//
// North star (BASELINE.json): the upload path chunks each stream
// (CDC), fingerprints the chunks (SHA1 — on TPU in sidecar mode), and
// writes only bytes the store has never seen.  This class owns the
// physical side:
//
//   <store_path>/data/chunks/<d0d1>/<d2d3>/<40-hex>   chunk payloads
//   <local path>.rcp                                  per-file recipes
//
// A recipe lists (digest, length) per chunk; logical reads reassemble.
// The store is self-healing: Put() is write-if-absent keyed by content
// digest, so a stale "duplicate" verdict can never lose data — the byte
// payload is always provided alongside the digest.
//
// Refcounts are RAM-only and rebuilt by scanning every recipe at startup
// (which doubles as orphan-chunk GC); crash-safety therefore never
// depends on a refcount file.
//
// Locking (the PR 5 read-path overhaul): the per-digest state (refs,
// lengths, pins, zero-ref parking, quarantine marks) is SHARDED into
// kStripes lock stripes keyed by the digest's first hex nibble, so
// concurrent downloads, uploads, deletes, and the scrub pass stop
// convoying on one mutex.  Every invariant from the integrity engine
// era is PER-DIGEST (probe+pin in one acquisition, pin-vs-GC-unlink in
// one acquisition, quarantine re-verify under the same lock as the
// rename), so a single stripe lock preserves each of them; the only
// cross-digest atomicity anywhere is RefAll's all-or-nothing check,
// which takes its (few) stripes in ascending index order — the
// deadlock-free ordered multi-stripe protocol.  ReadRecipeAndPin keeps
// its fail-before-first-byte contract by verify+pin per chunk with
// rollback: a delete interleaving mid-recipe makes the pin step find
// the unref'd chunk and the whole download fails cleanly with no pins
// held, exactly as the monolithic lock produced.  Aggregate byte/count
// accounting is atomics.  This class is self-locked and calls nothing
// that locks (the read cache has its own mutex, always acquired AFTER
// a stripe lock, never before).
//
// Hot-chunk read cache: a bounded LRU of whole chunk payloads
// (storage.conf:read_cache_mb; 0 = off) consulted by the download and
// FETCH_CHUNK serving paths.  Entries are shared_ptr<const string>, so
// an eviction or invalidation never frees bytes a response is still
// scattering into the socket.  Strict coherence with mutation: inserts
// re-check refs+quarantine UNDER the digest's stripe lock, and
// Quarantine(), RepairChunk(), and the GC/delete unlink invalidate
// under that same lock — a quarantined or swept chunk can never be
// served from the cache afterward.  Slab-resident chunks key the cache
// identically to flat ones (by digest), so the same invalidation
// points cover both layouts.
//
// Slab packing (ISSUE 9 / ROADMAP item 1): chunks below
// slab_chunk_threshold and recipe payloads below slab_recipe_threshold
// live as records inside <store_path>/data/slabs/*.slab
// (storage/slabstore.h) instead of per-object inodes.  Every
// per-digest invariant is unchanged — the slab store is a payload
// landing zone consulted under the SAME stripe-lock acquisitions that
// previously wrote/unlinked flat files (slab lock ranks sit between
// kChunkStripe and kReadCache).  Recipes load/store through
// StoreRecipe/LoadRecipe, which route small ones into the slab keyed
// by their sidecar path relative to the store root (mixed stores read
// both layouts, so flipping the thresholds is always safe).
//
// Erasure-coded cold tier (ISSUE 16 / ROADMAP item 2): when ec_k > 0
// the store owns an EcStore (<store_path>/data/ec/, storage/ecstore.h)
// and three new per-digest states exist.  EC-RESIDENT (owner): the
// payload was demoted into an RS(k, m) stripe and the local flat/slab
// copy dropped — refs/lens are unchanged and reads fall through
// flat -> slab -> EC transparently.  RELEASED (peer): scrub stage 5's
// verify-then-release handover (EC_RELEASE) dropped this node's replica
// because the group owner holds the bytes in parity — refs/lens are
// unchanged, presence answers (HaveMask/PinAndMask) still report the
// chunk held (it is, group-wide), and a local read remote-fetches from
// the owner via the set_remote_fetch hook (SHA1-verified, cache-
// warmed).  Released marks survive restarts via data/released.log
// ("R <digest> <len>" / "H <digest>" records, replayed by
// RebuildFromRecipes); heal paths (PutAndRef, RepairChunk) clear the
// mark the moment verified bytes land locally again.  Deletes reclaim
// parity through EcStore::MarkDead from the same stripe-lock unlink
// path that reclaims flat/slab bytes.
//
// Reference anchor: replaces the inode-per-file write in
// storage/storage_dio.c:dio_write_file() for deduplicated uploads.
#pragma once

#include <array>

#include "common/lockrank.h"
#include <atomic>
#include <cstdint>
#include <functional>
#include <list>
#include <memory>
#include <mutex>
#include <optional>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "storage/ecstore.h"
#include "storage/slabstore.h"

namespace fdfs {

struct RecipeEntry {
  std::string digest_hex;  // 40-char lowercase SHA1
  int64_t length = 0;
};

struct Recipe {
  int64_t logical_size = 0;
  std::vector<RecipeEntry> chunks;
};

// Recipe codec ("FDFSRCP1" magic + BE fields; see chunkstore.cc).  The
// buffer forms are the shared core: recipe files and slab-resident
// recipe records carry identical bytes.
std::string EncodeRecipe(const Recipe& r);
std::optional<Recipe> DecodeRecipe(const char* data, size_t len);
bool WriteRecipeFile(const std::string& path, const Recipe& r,
                     std::string* err);
std::optional<Recipe> ReadRecipeFile(const std::string& path);

// Slab-packing knobs (storage.conf slab_* keys; see slabstore.h).
// Thresholds of 0 disable packing for that record class; both 0 = no
// slab store at all (the pre-slab flat layout).
struct SlabOptions {
  int64_t chunk_threshold = 0;   // chunks below this pack into slabs
  int64_t recipe_threshold = 0;  // encoded recipes below this pack too
  int64_t slab_bytes = 64LL << 20;
  int compact_min_dead_pct = 25;
};

class ChunkStore {
 public:
  // gc_grace_s: how long a zero-ref chunk's bytes linger on disk before
  // a GcSweep may reclaim them (0 = unlink eagerly on the last unref,
  // the pre-scrubber behavior).  read_cache_bytes bounds the hot-chunk
  // LRU read cache (0 = off).  ec_k/ec_m enable the erasure-coded cold
  // tier (storage.conf ec_k/ec_m; 0 = off — like the slab store, an
  // EcStore also mounts read-only when data/ec/ already holds stripes,
  // so flipping ec_k to 0 drains the tier instead of stranding it).
  explicit ChunkStore(std::string store_path, int64_t gc_grace_s = 0,
                      int64_t read_cache_bytes = 0,
                      SlabOptions slab = SlabOptions{}, int ec_k = 0,
                      int ec_m = 0);

  // Flight recorder (common/eventlog.h; may stay null): the store
  // reports heal-on-upload — a quarantined chunk restored by an
  // incoming verified payload — so postmortems see the full
  // quarantine -> heal lifecycle, not just the scrubber's half.  Set
  // once at startup, before serving.
  void set_events(class EventLog* events) { events_ = events; }

  // Scan every *.rcp under the data dir: rebuild refcounts and delete
  // orphaned chunk files.  Call once at startup, before serving.
  void RebuildFromRecipes();

  // Write-if-absent + take a reference.  Returns true when the chunk was
  // already present (the dedup "hit"); *err set only on write failure.
  bool PutAndRef(const std::string& digest_hex, const char* data,
                 size_t len, bool* existed, std::string* err);

  // Drop one reference per entry of the recipe; chunks reaching zero are
  // unlinked.
  void UnrefAll(const Recipe& r);

  // Take one additional reference per recipe entry (recipe duplication:
  // CREATE_LINK of a chunked file).  False (and no refs taken) if any
  // chunk is absent.  All-or-nothing across digests: the involved
  // stripes are locked together in ascending index order.
  bool RefAll(const Recipe& r);

  // Is this chunk live (referenced by at least one recipe)?
  bool Has(const std::string& digest_hex) const;

  // Batched presence check, one lock acquisition PER STRIPE (not per
  // digest): byte i of the result is 0 when digests[i] is live, 1 when
  // it must be shipped.
  std::string HaveMask(const std::vector<std::string>& digests) const;

  // Take one reference on an already-live chunk; false when absent
  // (the replication receiver then reports the race and the sender
  // falls back to a full copy).
  bool RefOne(const std::string& digest_hex);

  // Read one chunk fully into *out (resized).  False when missing/short.
  bool ReadChunk(const std::string& digest_hex, int64_t expect_len,
                 std::string* out) const;

  // Positional read of [offset, offset+len) of a chunk's payload into
  // dst (pread; no heap) — the cold-span path of the scatter-gather
  // download assembly.  False when missing/short.
  bool ReadChunkSlice(const std::string& digest_hex, int64_t offset,
                      int64_t len, char* dst) const;

  // One request of a batched cold-span round (ISSUE 18).
  struct SliceReq {
    const std::string* digest_hex = nullptr;  // borrowed for the call
    int64_t offset = 0;
    int64_t len = 0;
    char* dst = nullptr;
  };
  // Batched positional reads for one RecipeStream response round:
  // slab-resident chunks route through SlabStore::ReadSlices (one
  // preadv per contiguous slab run), everything else — flat, EC,
  // released — takes the per-request fallthrough.  *vec_batches /
  // *vec_spans accumulate the preadv syscall count and the requests
  // they served (the dio.preadv_* counter feed).  False on the first
  // unreadable chunk, with *failed naming its digest.
  bool ReadChunkSlices(const SliceReq* reqs, size_t n, int64_t* vec_batches,
                       int64_t* vec_spans, std::string* failed) const;

  // -- hot-chunk read cache ----------------------------------------------
  bool cache_enabled() const { return cache_.cap_bytes > 0; }
  // Cache lookup + disk read-through + insert, for DOWNLOAD_FILE: the
  // returned buffer is immutable and keep-alive (safe across eviction
  // and invalidation).  *hit reports whether the cache served it.
  // nullptr when the cache is off, the chunk is unreadable, or its size
  // does not match expect_len.  Inserts re-check liveness/quarantine
  // under the digest's stripe lock (see header comment).
  std::shared_ptr<const std::string> ReadChunkCached(
      const std::string& digest_hex, int64_t expect_len, bool* hit);
  // Lookup WITHOUT read-through or insert, for FETCH_CHUNK (recovery /
  // scrub-repair traffic must not evict client-hot chunks).
  std::shared_ptr<const std::string> CacheLookup(
      const std::string& digest_hex, int64_t expect_len);
  int64_t cache_hits() const { return cache_.hits.load(); }
  int64_t cache_misses() const { return cache_.misses.load(); }
  int64_t cache_evictions() const { return cache_.evictions.load(); }
  int64_t cache_invalidations() const { return cache_.invalidations.load(); }
  int64_t cache_bytes() const;
  int64_t cache_chunks() const;
  int64_t cache_capacity_bytes() const { return cache_.cap_bytes; }

  // Presence probe + pin in ONE stripe-lock acquisition per chunk, for
  // the negotiated upload's phase-1 answer: byte i of the result is 0
  // when chunk i is live (and now pinned against unlink until the
  // session's UnpinRecipe), 1 when the client must ship it.  A separate
  // HaveMask-then-PinRecipe would let a delete unlink a "present" chunk
  // in the gap; pinning absent digests is harmless (the unpin erases
  // the entry), so every entry is pinned and the whole recipe unpins.
  std::string PinAndMask(const Recipe& r);

  // Transient stream pins: an in-flight chunked download holds a pin per
  // recipe entry so a concurrent delete cannot unlink bytes it is still
  // sending (POSIX open-fd semantics for flat files, recreated here).
  // A pinned chunk whose refcount hits zero defers its unlink until the
  // last pin drops.  Pins are RAM-only — a crash loses only streams.
  void PinRecipe(const Recipe& r);
  void UnpinRecipe(const Recipe& r);

  // Read a recipe file and pin its chunks, failing before the first
  // byte: each chunk is verified still-referenced and pinned under its
  // stripe lock; if any chunk was already unreferenced (a concurrent
  // delete), the pins taken so far roll back and the caller fails the
  // download with ENOENT — never mid-stream.
  std::optional<Recipe> ReadRecipeAndPin(const std::string& path);

  // Ranged variant for the parallel download client: pin (and return)
  // ONLY the recipe entries overlapping [offset, offset+count) of the
  // logical file (count 0 = to EOF) — a 4-range parallel download of a
  // many-thousand-chunk file must not pay 4x full-recipe pin/unpin and
  // skip scans.  The returned Recipe keeps the FULL logical_size but
  // holds just the overlapping chunk slice; *skip_out is the byte
  // offset inside its first entry.  UnpinRecipe on the returned
  // (trimmed) recipe releases exactly the pins taken.  nullopt (no
  // pins) when the recipe is gone or a chunk was unreferenced; offset
  // PAST EOF returns an EMPTY slice instead, so the caller can tell
  // "bad range" (EINVAL, by logical_size) from "gone" (ENOENT).
  std::optional<Recipe> ReadRecipeAndPinRange(const std::string& path,
                                              int64_t offset, int64_t count,
                                              int64_t* skip_out);

  std::string ChunkPath(const std::string& digest_hex) const;
  std::string QuarantinePath(const std::string& digest_hex) const;

  // -- recipe sidecars (slab-aware; storage/slabstore.h) -----------------
  // All take the recipe's SIDECAR PATH (<local>.rcp) like the old
  // file-level codec did; small recipes land as slab records keyed by
  // that path relative to the store root, large ones stay flat files.
  // Loads consult both layouts, so a threshold change never strands
  // existing data.
  bool StoreRecipe(const std::string& rcp_path, const Recipe& r,
                   std::string* err);
  std::optional<Recipe> LoadRecipe(const std::string& rcp_path) const;
  bool HasRecipe(const std::string& rcp_path) const;
  // Remove whichever representation exists; *bytes_out (optional) gets
  // the on-disk bytes reclaimed (scrub.bytes_reclaimed accounting).
  // False when no recipe existed under the path.
  bool RemoveRecipe(const std::string& rcp_path, int64_t* bytes_out);

  // -- slab packing ------------------------------------------------------
  bool slab_enabled() const { return slab_ != nullptr; }
  SlabStore* slab() { return slab_.get(); }  // tests / stats plumbing
  // slab.* registry gauges (all 0 when packing is off).
  int64_t slab_files() const { return slab_ ? slab_->files() : 0; }
  int64_t slab_slots_live() const { return slab_ ? slab_->slots_live() : 0; }
  int64_t slab_slots_dead() const { return slab_ ? slab_->slots_dead() : 0; }
  int64_t slab_bytes_live() const { return slab_ ? slab_->bytes_live() : 0; }
  int64_t slab_bytes_dead() const { return slab_ ? slab_->bytes_dead() : 0; }
  int64_t slab_compactions() const {
    return slab_ ? slab_->compactions() : 0;
  }
  int64_t slab_compacted_bytes() const {
    return slab_ ? slab_->compacted_bytes() : 0;
  }

  // -- erasure-coded cold tier (storage/ecstore.h) -----------------------
  struct ChunkInfo {
    std::string digest_hex;
    int64_t length = 0;
  };
  bool ec_enabled() const { return ec_ != nullptr; }
  EcStore* ec() { return ec_.get(); }  // scrub stage 5 / tests / stats
  const EcStore* ec() const { return ec_.get(); }
  // ec.* registry gauges (all 0 when the tier is off).
  int64_t ec_stripes() const { return ec_ ? ec_->stripes() : 0; }
  int64_t ec_stripe_chunks() const {
    return ec_ ? ec_->stripe_chunks() : 0;
  }
  int64_t ec_data_bytes() const { return ec_ ? ec_->data_bytes() : 0; }
  int64_t ec_parity_bytes() const { return ec_ ? ec_->parity_bytes() : 0; }
  int64_t released_chunks() const { return released_chunks_.load(); }
  int64_t released_bytes() const { return released_bytes_.load(); }
  int64_t ec_remote_reads() const { return remote_reads_.load(); }

  // Remote-replica fetch for RELEASED chunks: the server installs a
  // group-peer FETCH_CHUNK round here at startup.  Called WITHOUT any
  // lock held (it does network IO); the returned bytes are SHA1-checked
  // by the caller before serving.  Null = released chunks read as
  // missing (single-node stores).
  using RemoteFetchFn = std::function<bool(
      const std::string& digest_hex, int64_t length, std::string* out)>;
  void set_remote_fetch(RemoteFetchFn fn) { remote_fetch_ = std::move(fn); }

  // Demotion candidates for scrub stage 5: live, unpinned,
  // unquarantined, unreleased, not yet EC-resident, and COLD — payload
  // mtime (flat file stat / slab record meta) at or past age_s seconds
  // old at now_s.  The mtime probes run lock-free after a locked
  // candidate scan, so a many-million-chunk store never stats under a
  // stripe lock.
  std::vector<ChunkInfo> SnapshotDemotable(int64_t now_s,
                                           int64_t age_s) const;

  // Owner-side demotion: read + SHA1-verify each chunk, encode ONE
  // RS(k, m) stripe, re-verify it from disk through the decode path,
  // then drop the local flat/slab payloads (refs/lens stay — reads fall
  // through to the stripe).  Chunks that vanished, fail their hash, or
  // are already EC-resident are skipped silently (the next pass
  // re-snapshots).  Returns the stripe id, or -1 with *err (nothing
  // demoted — a failed verify also unwinds the stripe).
  int64_t DemoteToEc(const std::vector<ChunkInfo>& chunks,
                     int64_t* chunks_demoted, int64_t* bytes_demoted,
                     std::string* err);

  // Peer-side EC_RELEASE: drop the local replica of chunks the group
  // owner now holds in parity.  Byte i of the result is 0 when chunk i
  // is released here (or was never held — nothing retained either way),
  // 1 when it is KEPT (pinned by an in-flight stream, or quarantined —
  // the scrub repair machinery owns that lifecycle).  Idempotent: a
  // replayed release of an already-released digest answers 0.  Released
  // marks are journaled to data/released.log before the response so a
  // crash cannot resurrect a dropped replica as "held".
  std::string ReleaseChunks(const std::vector<ChunkInfo>& chunks);
  bool IsReleased(const std::string& digest_hex) const;

  // -- integrity engine (storage/scrub.*) --------------------------------
  // Live (referenced, non-quarantined) chunks for a verify pass.
  // prefix -1 snapshots everything in one call; 0..255 filters to
  // digests whose first byte equals it, so a scrubber walking the 256
  // slices in turn holds one stripe lock for one allocation-light
  // filter scan at a time and never keeps a many-million-entry
  // snapshot resident across an hours-long paced pass.
  std::vector<ChunkInfo> SnapshotLive(int prefix = -1) const;
  // Currently quarantined chunks still named by a recipe (repair targets).
  std::vector<ChunkInfo> SnapshotQuarantined() const;
  bool IsQuarantined(const std::string& digest_hex) const;

  enum class QuarantineResult { kQuarantined, kGone, kPinned, kClean };
  // Move a corrupt chunk's bytes aside so no download/replication path
  // ever serves them again.  kPinned when an in-flight stream still
  // holds the chunk (repair-in-place under a reader is not safe — the
  // scrubber retries next pass); kGone when the chunk lost its last
  // reference meanwhile; kClean when a re-read UNDER THE LOCK hashes
  // correctly — the caller's lock-free verify read raced a delete +
  // re-upload of the same digest, and the bytes on disk now are good
  // (quarantining them would jail a freshly-written chunk).  Probe,
  // re-verify, rename, and read-cache invalidation happen in one
  // stripe-lock acquisition, which no PutAndRef/UnrefAll of this
  // digest can interleave.
  QuarantineResult Quarantine(const std::string& digest_hex);
  // Restore verified bytes for a still-referenced digest (replica
  // repair).  False when the digest is no longer live (deleted — drop
  // it) or the write fails.  The caller MUST have verified
  // SHA1(data) == digest_hex.
  bool RepairChunk(const std::string& digest_hex, const char* data,
                   size_t len, std::string* err);
  // Reclaim zero-ref chunks whose grace expired at `now_s`, skipping
  // pinned ones — probe and unlink under one stripe-lock acquisition,
  // so a concurrent PinAndMask either pinned the chunk first (sweep
  // skips it) or finds it already gone (reports it as needed).
  // Returns the number of chunks unlinked; *bytes accumulates sizes.
  int64_t GcSweep(int64_t now_s, int64_t* bytes);

  // Paced online compaction of dead slab space (driven from the scrub
  // pass, sharing its token bucket via `pace` and its shutdown flag via
  // `stop`).  Chunk records that failed the copy-time re-verify come
  // back in *corrupt so the caller can route them through the standard
  // quarantine/repair machinery (ScrubManager::HandleCorrupt); corrupt
  // recipe records are only counted — their files fail loudly on read
  // and heal via replica re-sync.  Returns slabs reclaimed; *reclaimed
  // accumulates unlinked slab-file bytes.  No-op when packing is off.
  int64_t CompactSlabs(const std::function<void(int64_t)>& pace,
                       const std::function<bool()>& stop,
                       std::vector<ChunkInfo>* corrupt, int64_t* reclaimed);

  int64_t unique_chunks() const;
  int64_t unique_bytes() const { return unique_bytes_.load(); }
  int64_t gc_pending_chunks() const;
  int64_t gc_pending_bytes() const { return zero_ref_bytes_.load(); }
  int64_t quarantined_chunks() const;

 private:
  struct ZeroRef {
    int64_t length = 0;
    int64_t since_s = 0;  // wall clock of the last unref (or file mtime)
  };
  // One lock stripe: all per-digest state for digests whose first hex
  // nibble selects this stripe lives here, guarded by `mu`.
  struct Stripe {
    mutable RankedMutex mu{LockRank::kChunkStripe};
    std::unordered_map<std::string, int64_t> refs;
    std::unordered_map<std::string, int64_t> lens;  // digest -> byte length
    std::unordered_map<std::string, int64_t> pins;  // in-flight streams
    std::unordered_map<std::string, ZeroRef> zero_ref;  // awaiting GC
    std::unordered_set<std::string> quarantined;
    // Replica dropped via EC_RELEASE (group owner holds the bytes in
    // parity); refs/lens entries remain, reads remote-fetch.
    std::unordered_set<std::string> released;
  };
  static constexpr int kStripes = 16;
  static int StripeIndex(const std::string& digest_hex);
  Stripe& StripeFor(const std::string& digest_hex) {
    return stripes_[StripeIndex(digest_hex)];
  }
  const Stripe& StripeFor(const std::string& digest_hex) const {
    return stripes_[StripeIndex(digest_hex)];
  }

  // stripe mu held.  Park a zero-ref chunk for GC or unlink it eagerly
  // (gc_grace_s_ == 0 and unpinned).
  void RetireLocked(Stripe& s, const std::string& digest_hex,
                    int64_t length);
  // stripe mu held.  Unlink a zero-ref chunk's bytes (chunks/,
  // quarantine/, any slab record, any EC slot, any released mark) and
  // invalidate any cached copy.
  void UnlinkRetiredLocked(Stripe& s, const std::string& digest_hex);
  // stripe mu held.  Drop just the LOCAL PAYLOAD (flat file / slab
  // record + cached copy), keeping refs/lens/quarantine state — the
  // shared core of UnlinkRetiredLocked (full retirement), DemoteToEc
  // (bytes now live in the EC stripe), and ReleaseChunks (bytes now
  // live on the group owner).
  void DropPayloadLocked(Stripe& s, const std::string& digest_hex);
  // stripe mu held.  Clear a released mark because verified bytes just
  // landed locally (heal-on-upload, replica repair); journals 'H'.
  void UnreleaseLocked(Stripe& s, const std::string& digest_hex,
                       int64_t len);
  std::string ReleasedLogPath() const {
    return store_path_ + "/data/released.log";
  }
  // Append released.log records ('R' digest len / 'H' digest) with one
  // fsync per call — ReleaseChunks batches a whole EC_RELEASE body into
  // one append so the journal is durable before the response commits
  // the owner to dropping coverage.
  void AppendReleasedLog(const std::string& records) const;
  // Should a fresh chunk payload of this size land in the slab store?
  bool SlabChunkEligible(int64_t len) const {
    return slab_ != nullptr && slab_opts_.chunk_threshold > 0 &&
           len < slab_opts_.chunk_threshold;
  }
  // stripe mu held.  Write/replace a chunk payload in whichever layout
  // its size selects (slab record or flat file) — the shared landing
  // path of PutAndRef's first write, heal-on-upload, and RepairChunk.
  bool WriteChunkPayloadLocked(const std::string& digest_hex,
                               const char* data, size_t len,
                               std::string* err);
  // Slab key for a recipe sidecar path (relative to the store root).
  std::string RecipeSlabKey(const std::string& rcp_path) const;

  // -- read cache internals ----------------------------------------------
  struct CacheEntry {
    std::string digest_hex;
    std::shared_ptr<const std::string> data;
  };
  struct ReadCache {
    int64_t cap_bytes = 0;
    mutable RankedMutex mu{LockRank::kReadCache};
    std::list<CacheEntry> lru;  // front = most recent
    std::unordered_map<std::string, std::list<CacheEntry>::iterator> index;
    int64_t bytes = 0;
    std::atomic<int64_t> hits{0}, misses{0}, evictions{0},
        invalidations{0};
  };
  std::shared_ptr<const std::string> CacheGet(const std::string& digest_hex);
  // Insert (caller holds NO stripe lock; this re-takes the digest's
  // stripe lock to re-check liveness — see header comment).
  void CacheInsertIfLive(const std::string& digest_hex,
                         std::shared_ptr<const std::string> data);
  // stripe mu held (or startup): drop a digest's cached copy.
  void CacheInvalidate(const std::string& digest_hex);
  void CacheClear();

  std::string store_path_;
  int64_t gc_grace_s_ = 0;
  SlabOptions slab_opts_;
  std::unique_ptr<SlabStore> slab_;  // null = flat layout only
  std::unique_ptr<EcStore> ec_;      // null = no erasure-coded tier
  RemoteFetchFn remote_fetch_;
  class EventLog* events_ = nullptr;
  std::array<Stripe, kStripes> stripes_;
  std::atomic<int64_t> unique_bytes_{0};
  std::atomic<int64_t> zero_ref_bytes_{0};
  std::atomic<int64_t> released_chunks_{0};
  std::atomic<int64_t> released_bytes_{0};
  // Counted from const read paths (the fallthrough serve), hence mutable.
  mutable std::atomic<int64_t> remote_reads_{0};
  mutable ReadCache cache_;
};

}  // namespace fdfs
