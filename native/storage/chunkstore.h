// Content-addressed chunk store + file recipes: the disk layer of
// chunk-level dedup.
//
// North star (BASELINE.json): the upload path chunks each stream
// (CDC), fingerprints the chunks (SHA1 — on TPU in sidecar mode), and
// writes only bytes the store has never seen.  This class owns the
// physical side:
//
//   <store_path>/data/chunks/<d0d1>/<d2d3>/<40-hex>   chunk payloads
//   <local path>.rcp                                  per-file recipes
//
// A recipe lists (digest, length) per chunk; logical reads reassemble.
// The store is self-healing: Put() is write-if-absent keyed by content
// digest, so a stale "duplicate" verdict can never lose data — the byte
// payload is always provided alongside the digest.
//
// Refcounts are RAM-only and rebuilt by scanning every recipe at startup
// (which doubles as orphan-chunk GC); crash-safety therefore never
// depends on a refcount file.  Single acquisition order: this class is
// self-locked and calls nothing that locks.
//
// Integrity lifecycle (the anti-entropy subsystem in storage/scrub.h):
//
//  * Zero-ref GC.  With gc_grace_s == 0 (default) a chunk whose last
//    reference drops is unlinked immediately (deferred only while a
//    stream pin holds it — the original semantics).  With a grace
//    window, zero-ref chunks park in zero_ref_ (bytes stay on disk,
//    resurrectable by PutAndRef) until a GcSweep older than the grace
//    reclaims them; the pin probe runs under the SAME lock as the
//    unlink, so an upload session's PinAndMask can never lose a chunk
//    to a sweep in the probe-to-pin gap.
//  * Quarantine.  A scrub pass that finds bit-rot moves the bad bytes
//    into <store_path>/data/quarantine/<digest> (never served again)
//    while the refcount entry stays live; Have/PinAndMask report the
//    chunk as missing so uploads re-ship the bytes, and PutAndRef /
//    RepairChunk with verified payloads heal it in place.
//
// Reference anchor: replaces the inode-per-file write in
// storage/storage_dio.c:dio_write_file() for deduplicated uploads.
#pragma once

#include <cstdint>
#include <mutex>
#include <optional>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace fdfs {

struct RecipeEntry {
  std::string digest_hex;  // 40-char lowercase SHA1
  int64_t length = 0;
};

struct Recipe {
  int64_t logical_size = 0;
  std::vector<RecipeEntry> chunks;
};

// Recipe file codec ("FDFSRCP1" magic + BE fields; see chunkstore.cc).
bool WriteRecipeFile(const std::string& path, const Recipe& r,
                     std::string* err);
std::optional<Recipe> ReadRecipeFile(const std::string& path);

class ChunkStore {
 public:
  // gc_grace_s: how long a zero-ref chunk's bytes linger on disk before
  // a GcSweep may reclaim them (0 = unlink eagerly on the last unref,
  // the pre-scrubber behavior).
  explicit ChunkStore(std::string store_path, int64_t gc_grace_s = 0);

  // Scan every *.rcp under the data dir: rebuild refcounts and delete
  // orphaned chunk files.  Call once at startup, before serving.
  void RebuildFromRecipes();

  // Write-if-absent + take a reference.  Returns true when the chunk was
  // already present (the dedup "hit"); *err set only on write failure.
  bool PutAndRef(const std::string& digest_hex, const char* data,
                 size_t len, bool* existed, std::string* err);

  // Drop one reference per entry of the recipe; chunks reaching zero are
  // unlinked.
  void UnrefAll(const Recipe& r);

  // Take one additional reference per recipe entry (recipe duplication:
  // CREATE_LINK of a chunked file).  False (and no refs taken) if any
  // chunk is absent.
  bool RefAll(const Recipe& r);

  // Is this chunk live (referenced by at least one recipe)?
  bool Has(const std::string& digest_hex) const;

  // Batched presence check under ONE lock acquisition: byte i of the
  // result is 0 when digests[i] is live, 1 when it must be shipped.
  // (The chunk-aware replication receiver runs this on the nio loop —
  // per-digest locking would serialize against every concurrent
  // upload's PutAndRef.)
  std::string HaveMask(const std::vector<std::string>& digests) const;

  // Take one reference on an already-live chunk; false when absent
  // (the replication receiver then reports the race and the sender
  // falls back to a full copy).
  bool RefOne(const std::string& digest_hex);

  // Read one chunk fully into *out (resized).  False when missing/short.
  bool ReadChunk(const std::string& digest_hex, int64_t expect_len,
                 std::string* out) const;

  // Presence probe + pin in ONE lock acquisition, for the negotiated
  // upload's phase-1 answer: byte i of the result is 0 when chunk i is
  // live (and now pinned against unlink until the session's
  // UnpinRecipe), 1 when the client must ship it.  A separate
  // HaveMask-then-PinRecipe would let a delete unlink a "present" chunk
  // in the gap; pinning absent digests is harmless (the unpin erases
  // the entry), so every entry is pinned and the whole recipe unpins.
  std::string PinAndMask(const Recipe& r);

  // Transient stream pins: an in-flight chunked download holds a pin per
  // recipe entry so a concurrent delete cannot unlink bytes it is still
  // sending (POSIX open-fd semantics for flat files, recreated here).
  // A pinned chunk whose refcount hits zero defers its unlink until the
  // last pin drops.  Pins are RAM-only — a crash loses only streams.
  void PinRecipe(const Recipe& r);
  void UnpinRecipe(const Recipe& r);

  // Read a recipe file and pin its chunks atomically w.r.t. UnrefAll: a
  // delete landing between a plain ReadRecipeFile and PinRecipe could
  // unref+unlink chunks the stream is about to send.  Under the store
  // mutex: read, verify every chunk is still referenced, then pin.
  // nullopt (no pins taken) when the recipe is gone or any chunk was
  // already unreferenced — the caller fails the download with ENOENT
  // before the first byte, not mid-stream.
  std::optional<Recipe> ReadRecipeAndPin(const std::string& path);

  std::string ChunkPath(const std::string& digest_hex) const;
  std::string QuarantinePath(const std::string& digest_hex) const;

  // -- integrity engine (storage/scrub.*) --------------------------------
  struct ChunkInfo {
    std::string digest_hex;
    int64_t length = 0;
  };
  // Live (referenced, non-quarantined) chunks for a verify pass.
  // prefix -1 snapshots everything in one call; 0..255 filters to
  // digests whose first byte equals it, so a scrubber walking the 256
  // slices in turn holds the lock for one allocation-light filter scan
  // at a time and never keeps a many-million-entry snapshot resident
  // across an hours-long paced pass.
  std::vector<ChunkInfo> SnapshotLive(int prefix = -1) const;
  // Currently quarantined chunks still named by a recipe (repair targets).
  std::vector<ChunkInfo> SnapshotQuarantined() const;
  bool IsQuarantined(const std::string& digest_hex) const;

  enum class QuarantineResult { kQuarantined, kGone, kPinned, kClean };
  // Move a corrupt chunk's bytes aside so no download/replication path
  // ever serves them again.  kPinned when an in-flight stream still
  // holds the chunk (repair-in-place under a reader is not safe — the
  // scrubber retries next pass); kGone when the chunk lost its last
  // reference meanwhile; kClean when a re-read UNDER THE LOCK hashes
  // correctly — the caller's lock-free verify read raced a delete +
  // re-upload of the same digest, and the bytes on disk now are good
  // (quarantining them would jail a freshly-written chunk).  Probe,
  // re-verify, and rename happen in one lock acquisition, which no
  // PutAndRef/UnrefAll can interleave.
  QuarantineResult Quarantine(const std::string& digest_hex);
  // Restore verified bytes for a still-referenced digest (replica
  // repair).  False when the digest is no longer live (deleted — drop
  // it) or the write fails.  The caller MUST have verified
  // SHA1(data) == digest_hex.
  bool RepairChunk(const std::string& digest_hex, const char* data,
                   size_t len, std::string* err);
  // Reclaim zero-ref chunks whose grace expired at `now_s`, skipping
  // pinned ones — probe and unlink under one lock acquisition, so a
  // concurrent PinAndMask either pinned the chunk first (sweep skips
  // it) or finds it already gone (reports it as needed).  Returns the
  // number of chunks unlinked; *bytes accumulates their sizes.
  int64_t GcSweep(int64_t now_s, int64_t* bytes);

  int64_t unique_chunks() const;
  int64_t unique_bytes() const;
  int64_t gc_pending_chunks() const;
  int64_t gc_pending_bytes() const;
  int64_t quarantined_chunks() const;

 private:
  struct ZeroRef {
    int64_t length = 0;
    int64_t since_s = 0;  // wall clock of the last unref (or file mtime)
  };
  // mu_ held.  Park a zero-ref chunk for GC or unlink it eagerly
  // (gc_grace_s_ == 0 and unpinned).
  void RetireLocked(const std::string& digest_hex, int64_t length);
  // mu_ held.  Unlink a zero-ref chunk's bytes (chunks/ and quarantine/).
  void UnlinkRetiredLocked(const std::string& digest_hex);

  std::string store_path_;
  int64_t gc_grace_s_ = 0;
  mutable std::mutex mu_;
  std::unordered_map<std::string, int64_t> refs_;
  std::unordered_map<std::string, int64_t> lens_;  // digest -> byte length
  std::unordered_map<std::string, int64_t> pins_;  // in-flight streams
  std::unordered_map<std::string, ZeroRef> zero_ref_;  // awaiting GC
  std::unordered_set<std::string> quarantined_;
  int64_t unique_bytes_ = 0;
  int64_t zero_ref_bytes_ = 0;
};

}  // namespace fdfs
