// Trunk small-file packing: slot IO + free-slot allocator + alloc RPCs.
//
// Reference map (SURVEY.md §2.3, storage/trunk_mgr/):
// - slot codec + slot header + read/write inside a trunk file
//   → trunk_shared.c (trunk_file_info_encode/decode, trunk_file_get_content)
// - free-slot allocator with split on alloc → trunk_mem.c
//   (trunk_alloc_space/trunk_free_space, AVL trees per slot size)
// - non-trunk-server members RPC the group's elected trunk server
//   → trunk_client.c (trunk_client_trunk_alloc_space)
//
// Honest divergences from upstream, chosen for the rebuild:
// - Allocator state is derived entirely from the slot headers on disk
//   (ScanRebuild at boot / failover) instead of a trunk binlog + snapshot
//   (upstream trunk_sync.c / storage_trunk_init).  The headers are the
//   ground truth upstream's free-block checker validates against; scanning
//   them removes an entire class of snapshot/replay divergence bugs and
//   makes trunk-server failover the same code path as a normal boot.
// - Trunk files always live under store path 0 (upstream lets the
//   allocator spread them over store paths; the file-ID still reserves the
//   M%02X slot so this can be widened later).
// - Allocation is durable at alloc time; TRUNK_ALLOC_CONFIRM (28) is an
//   acknowledgement and a failed writer frees explicitly (29), where
//   upstream tracks unconfirmed allocations.
#pragma once

#include <cstdint>
#include <map>
#include <mutex>

#include "common/lockrank.h"
#include <unordered_set>
#include <optional>
#include <string>
#include <vector>

#include "common/fileid.h"

namespace fdfs {

constexpr int kTrunkHeaderSize = 24;
constexpr uint16_t kTrunkMagic = 0xFD54;
constexpr char kTrunkSlotData = 'D';
constexpr char kTrunkSlotFree = 'F';
constexpr int64_t kTrunkAlignment = 256;   // slot sizes rounded up to this
constexpr int64_t kTrunkMinSplit = 1024;   // smaller remainders stay padding

// 24-byte on-disk slot header at each block start.
struct TrunkSlotHeader {
  char type = kTrunkSlotFree;   // 'D' data | 'F' free
  uint32_t alloc_size = 0;      // whole block incl. this header
  uint32_t file_size = 0;       // payload bytes ('D' only)
  uint32_t crc32 = 0;
  uint32_t mtime = 0;
};

// data/trunk/<id&0xFF as %02X>/<id as %06u>.tk under a store path — the
// path is a pure function of the id so replicas place content identically.
std::string TrunkFilePath(const std::string& store_path, uint32_t trunk_id);

bool WriteSlotHeader(int fd, int64_t offset, const TrunkSlotHeader& h);
std::optional<TrunkSlotHeader> ReadSlotHeader(int fd, int64_t offset);

// Write header + payload into the trunk file for `loc`, creating/extending
// the file when needed (replica replay path; also used by the source after
// a successful Alloc).  Verifies payload fits the slot.
bool WriteSlotPayload(const std::string& store_path, const TrunkLocation& loc,
                      const std::string& payload, uint32_t crc32,
                      std::string* error);

// Read back the payload for `loc` ('D' slot with matching sizes).
std::optional<std::string> ReadSlotPayload(const std::string& store_path,
                                           const TrunkLocation& loc,
                                           int64_t expect_file_size);

// Mark the slot free on disk (delete path; replicas do only this — the
// allocator pool lives on the trunk server).
bool MarkSlotFree(const std::string& store_path, const TrunkLocation& loc);

// Free-slot allocator run by the group's elected trunk server.
// Thread-safe (the nio loop allocates; tests poke it directly).
class TrunkAllocator {
 public:
  // Scans every trunk file's header chain to rebuild the free pool.
  bool Init(const std::string& store_path, int64_t trunk_file_size,
            std::string* error);

  // Reserve a slot able to hold `payload_size` bytes (+header).  Writes the
  // 'D' header (and any split remainder's 'F' header) before returning, so
  // a rebuilt allocator never double-allocates a handed-out slot.
  std::optional<TrunkLocation> Alloc(int64_t payload_size);

  // Return a slot to the pool (and mark it free on disk).
  bool Free(const TrunkLocation& loc);

  int64_t free_bytes() const;
  int trunk_file_count() const;

  // Free-block checker (trunk_free_block_checker.c analogue): re-scan the
  // headers and compare with the in-memory pool; returns the number of
  // mismatched blocks (0 = consistent).
  int VerifyFreeMap(std::string* report) const;

  // Pre-allocation (reference: trunk_create_file_advance): create fresh
  // trunk files until at least `min_free_bytes` of pool capacity exists,
  // so allocation bursts never pay file-creation latency inline.
  // Returns the number of files created.
  int EnsureFreeReserve(int64_t min_free_bytes);

  // Compaction: unlink fully-free trunk files that were NEVER allocated
  // from (pre-created reserve only; keeping `keep` as the hot reserve).
  // Files that ever held a slot are excluded — their creation replicated
  // to group peers via slot writes, and a local unlink would silently
  // diverge the group's on-disk trunk sets.  Returns files reclaimed.
  int ReclaimEmptyFiles(int keep = 1);

 private:
  struct Block {
    uint32_t trunk_id;
    uint32_t offset;
  };
  bool ScanRebuildLocked(std::string* error);
  bool ScanFileLocked(uint32_t trunk_id, const std::string& path,
                      std::map<int64_t, std::vector<Block>>* pool) const;
  std::optional<TrunkLocation> CreateTrunkFileLocked(std::string* error);

  mutable RankedMutex mu_{LockRank::kTrunkAlloc};
  std::string store_path_;
  int64_t trunk_file_size_ = 0;
  uint32_t next_id_ = 0;
  // Trunk ids created this run and never allocated from: the only files
  // compaction may unlink (no peer has ever seen them).  Scan-rebuilt
  // files are conservatively excluded.
  std::unordered_set<uint32_t> clean_files_;
  // size -> blocks of exactly that size (best-fit via lower_bound).
  std::map<int64_t, std::vector<Block>> free_;
};

// -- trunk server RPCs (storage <-> elected trunk server, cmds 27-29) ----
// Every RPC carries the caller's trunk EPOCH (the tracker bumps it on
// each trunk-server change): the serving trunk server rejects a
// mismatch, so neither a stale trunk server nor a stale client can
// allocate against a moved role (the split-brain the round-2 advisor
// flagged; the regain grace now only covers replication lag).
std::optional<TrunkLocation> TrunkAllocRpc(const std::string& ip, int port,
                                           const std::string& group,
                                           int64_t payload_size,
                                           int64_t epoch, int timeout_ms);
bool TrunkConfirmRpc(const std::string& ip, int port, const std::string& group,
                     const TrunkLocation& loc, int64_t epoch, int timeout_ms);
bool TrunkFreeRpc(const std::string& ip, int port, const std::string& group,
                  const TrunkLocation& loc, int64_t epoch, int timeout_ms);

}  // namespace fdfs
