// Erasure-coded cold tier: RS(k, m) stripes of cold chunk payloads.
//
// North star (ROADMAP item 2 / ISSUE 16): full intra-group replication
// pays 2-3x bytes for every chunk forever.  Cold chunks past
// ec_demote_age_s are concatenated into stripes, split into k equal
// data shards, and extended with m systematic Cauchy parity shards
// (GF(2^8) tables from tools/gen_gf_tables.py — the same field the
// Python kernels in fastdfs_tpu/ops/rs_code.py run, pinned by the
// fdfs_codec gf-tables golden).  The stripe survives ANY m shard
// losses at (k+m)/k overhead; the replicated copies are then released
// group-wide by scrub stage 5's verify-then-release handover.
//
// Disk layout, under <store_path>/data/ec/ :
//
//   <10-digit-id>.s<NN>   shard files (NN = 00..k+m-1), CRC-framed:
//     0   8B  magic "FDFSECS1"
//     8   8B  stripe id BE
//     16  4B  shard index BE
//     20  4B  k BE
//     24  4B  m BE
//     28  8B  shard_len BE
//     36  8B  data_len BE (logical bytes in the stripe's data region)
//     44  4B  payload crc32 BE
//     48  4B  header crc32 BE (over bytes 0..47)
//     52      shard payload (shard_len bytes)
//
//   <10-digit-id>.mft     stripe manifest, keyed by chunk digests:
//     0   8B  magic "FDFSECM1"
//     8   4B  k BE
//     12  4B  m BE
//     16  8B  shard_len BE
//     24  8B  data_len BE
//     32  8B  chunk count BE
//     40      per chunk: 20B raw digest + 8B offset BE + 8B length BE
//             + 1B dead flag                              (37B each)
//     end 4B  crc32 BE over everything before it
//
//   release.map           verify-then-release journal (see below)
//   released.log          peer-side released-chunk journal (owned by
//                         ChunkStore, documented here for the layout)
//
// The MANIFEST RENAME IS THE COMMIT POINT (the recipe-file discipline):
// shard files are written first, the manifest lands tmp+rename+fsync,
// and Rescan() unlinks any shard file whose stripe has no manifest — a
// crash mid-encode costs nothing but orphan cleanup.  The data region
// is the chunks' payloads concatenated; shard_len = ceil(data_len / k)
// with zero padding, so a healthy chunk read is pure offset math over
// 1-2 data shard files (no field arithmetic).  Parity decode runs only
// when a shard read fails or a full-chunk read fails its SHA1 check.
//
// Deletes (Quarantine/GC/DELETE reclaiming parity bytes): MarkDead
// flips the chunk's dead flag and rewrites the manifest; when the last
// live chunk dies the WHOLE stripe — parity included — is unlinked and
// its physical bytes reported reclaimed.  Partially-dead stripes keep
// their bytes (EC stripe compaction is deferred work; the parity_bytes
// gauge makes the dead fraction visible — OPERATIONS.md runbook).
//
// release.map (rebalance.map discipline): before any peer is told to
// drop its replica of a freshly-encoded batch (EC_RELEASE), the batch
// is appended here and fsynced.  A crash between the EC commit and the
// peer handover replays the batch next pass — the release RPC is
// idempotent on the peer — and the file is truncated once every peer
// answered.
//
// Locking: one mutex (LockRank::kEcStore = 96), self-locked, calls
// nothing that locks.  Shard-file IO runs under it by design (the
// kTrunkAlloc/kSlabStore precedent) — this is a COLD tier; hot reads
// hit the replicated layouts or the read cache first, and an EC read
// that serializes behind another is still a disk-bound cold read.
// ChunkStore calls in while holding a digest stripe lock (rank 90), so
// 96 sits between kSlabIndex and kReadCache.
#pragma once

#include <atomic>
#include <cstdint>
#include <functional>
#include <map>
#include <string>
#include <unordered_map>
#include <utility>
#include <vector>

#include "common/lockrank.h"

namespace fdfs {

// -- RS(k, m) codec over GF(2^8) (common/gf256.h tables) ------------------
// Shared with fdfs_codec (gf-tables golden) and storage_test units.

// m parity shards for k equal-length data shards (systematic Cauchy).
std::vector<std::string> RsEncode(const std::vector<std::string>& data,
                                  int m);
// Fill the absent entries of `shards` (size k+m; absent = empty string,
// present entries all shard_len bytes) by decoding any k present
// shards.  False when fewer than k are present.  Rebuilds data AND
// parity shards.
bool RsReconstruct(std::vector<std::string>* shards, int k, int m,
                   int64_t shard_len);

class EventLog;

class EcStore {
 public:
  // dir = <store_path>/data/ec.  Geometry is fixed per store lifetime;
  // Rescan() refuses manifests with a different k/m (operator error —
  // re-silvering across geometries is not built).
  EcStore(std::string dir, int k, int m);

  void set_events(EventLog* events) { events_ = events; }

  int k() const { return k_; }
  int m() const { return m_; }

  // Boot scan: load every manifest (CRC-checked), index live chunks,
  // unlink orphan shard files from crashed encodes.  Returns stripes.
  int64_t Rescan();

  // Encode chunks (digest_hex, payload) into one committed stripe.
  // Returns the stripe id, or -1 with *err.  The caller owns candidate
  // selection and pacing; digests already EC-resident are a caller bug
  // (the index keeps the OLD location — content-addressed, same bytes).
  int64_t EncodeStripe(
      const std::vector<std::pair<std::string, std::string>>& chunks,
      std::string* err);

  // The "verify" of verify-then-release: re-read every shard from disk,
  // CRC-check, reconstruct the data region from a parity-heavy subset
  // of k shards (exercising the decode path, not just the write-back),
  // and SHA1-check every live chunk against its digest.
  bool VerifyStripe(int64_t stripe_id, std::string* err);

  bool Has(const std::string& digest_hex) const;
  // Full chunk payload; SHA1-verified, reconstructing from parity when
  // a shard is missing/corrupt.  False when not EC-resident (or the
  // stripe lost more than m shards).
  bool ReadChunk(const std::string& digest_hex, std::string* out) const;
  // Positional read; trusts shard bytes (no SHA1 — slices cannot be
  // digest-checked), reconstructing only on IO failure.
  bool ReadChunkSlice(const std::string& digest_hex, int64_t offset,
                      int64_t len, char* dst) const;

  // Flip the chunk dead; unlink the whole stripe when its last live
  // chunk dies (*reclaimed_bytes += physical bytes freed then).  False
  // when the digest is not EC-resident.
  bool MarkDead(const std::string& digest_hex, int64_t* reclaimed_bytes);

  // -- scrub repair --------------------------------------------------------
  std::vector<int64_t> StripeIds() const;
  enum class StripeHealth { kHealthy, kRepaired, kLost };
  struct ChunkRef {
    std::string digest_hex;
    int64_t length = 0;
  };
  // CRC-verify every shard of a stripe; <= m bad/missing shards are
  // reconstructed from parity and rewritten in place (kRepaired); more
  // are unrecoverable (kLost) and *lost_live gets the stripe's live
  // chunks so the caller can refill them via FETCH_CHUNK.  *bytes_read
  // reports IO for the caller's pacing.
  StripeHealth VerifyRepairStripe(int64_t stripe_id,
                                  std::vector<ChunkRef>* lost_live,
                                  int64_t* shards_rebuilt,
                                  int64_t* bytes_rebuilt,
                                  int64_t* bytes_read);
  // Drop a stripe entirely (after a kLost fallback re-promoted its
  // chunks to the replicated tier).
  void DropStripe(int64_t stripe_id, int64_t* reclaimed_bytes);

  // -- release.map ---------------------------------------------------------
  bool AppendReleaseMap(
      const std::vector<std::pair<std::string, int64_t>>& batch,
      std::string* err);
  std::vector<std::pair<std::string, int64_t>> PendingReleases() const;
  void ClearReleaseMap();

  // -- gauges (atomics: read by stats gauge-fns, must never block) ---------
  int64_t stripes() const { return stripes_gauge_.load(); }
  int64_t stripe_chunks() const { return chunks_gauge_.load(); }
  int64_t data_bytes() const { return data_bytes_gauge_.load(); }
  // Physical bytes on disk beyond the live chunks' logical bytes:
  // parity shards + padding + dead (deleted-but-unreclaimed) regions.
  int64_t parity_bytes() const { return parity_bytes_gauge_.load(); }

 private:
  struct ChunkSlot {
    std::string digest_hex;
    int64_t offset = 0;  // into the stripe's data region
    int64_t length = 0;
    bool dead = false;
  };
  struct Stripe {
    int k = 0, m = 0;
    int64_t shard_len = 0;
    int64_t data_len = 0;
    std::vector<ChunkSlot> chunks;
  };
  struct Loc {
    int64_t stripe_id = 0;
    int32_t slot = 0;
  };

  std::string ShardPath(int64_t stripe_id, int shard_idx) const;
  std::string ManifestPath(int64_t stripe_id) const;
  // mu_ held.  Read + CRC-check one shard's payload; false on any
  // mismatch (caller reconstructs).
  bool ReadShardLocked(int64_t stripe_id, const Stripe& s, int idx,
                       std::string* out) const;
  // mu_ held.  All k data shards of a stripe, reconstructing from
  // parity when needed; false past parity.
  bool LoadDataShardsLocked(int64_t stripe_id, const Stripe& s,
                            std::vector<std::string>* data) const;
  bool WriteShardLocked(int64_t stripe_id, const Stripe& s, int idx,
                        const std::string& payload, std::string* err) const;
  bool WriteManifestLocked(int64_t stripe_id, const Stripe& s,
                           std::string* err) const;
  void RecountLocked();

  std::string dir_;
  int k_ = 0, m_ = 0;
  // Constructed with k = 0 over existing stripes: geometry adopted from
  // the manifests at Rescan, encodes refused (read-only drain).
  bool drained_ = false;
  EventLog* events_ = nullptr;
  mutable RankedMutex mu_{LockRank::kEcStore};
  std::map<int64_t, Stripe> stripes_;              // ordered for StripeIds
  std::unordered_map<std::string, Loc> index_;     // live digests only
  int64_t next_stripe_id_ = 0;
  std::atomic<int64_t> stripes_gauge_{0}, chunks_gauge_{0},
      data_bytes_gauge_{0}, parity_bytes_gauge_{0};
};

}  // namespace fdfs
