#include "storage/store.h"

#include <errno.h>
#include <fcntl.h>
#include <string.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdio>

#include "common/log.h"

namespace fdfs {

bool StoreManager::Init(const StorageConfig& cfg, std::string* error) {
  paths_ = cfg.store_paths;
  subdir_count_ = cfg.subdir_count_per_path;
  for (const std::string& p : paths_) {
    std::string data = p + "/data";
    std::string flag = data + "/.data_init_flag";
    struct stat st;
    if (stat(flag.c_str(), &st) == 0) continue;  // already initialized
    any_fresh_ = true;
    // Pre-create the two-level fan-out (reference:
    // storage_make_data_dirs()).
    for (int i = 0; i < subdir_count_; ++i) {
      char sub[64];
      std::snprintf(sub, sizeof(sub), "%s/%02X", data.c_str(), i);
      if (!MakeDirs(sub)) {
        *error = std::string("mkdir ") + sub + ": " + strerror(errno);
        return false;
      }
      for (int j = 0; j < subdir_count_; ++j) {
        char sub2[80];
        std::snprintf(sub2, sizeof(sub2), "%s/%02X", sub, j);
        if (mkdir(sub2, 0755) != 0 && errno != EEXIST) {
          *error = std::string("mkdir ") + sub2 + ": " + strerror(errno);
          return false;
        }
      }
    }
    if (!MakeDirs(p + "/tmp")) {
      *error = "mkdir " + p + "/tmp failed";
      return false;
    }
    int fd = open(flag.c_str(), O_CREAT | O_WRONLY, 0644);
    if (fd < 0) {
      *error = "create " + flag + " failed";
      return false;
    }
    close(fd);
    FDFS_LOG_INFO("initialized data dirs under %s (%d^2 subdirs)", p.c_str(),
                  subdir_count_);
  }
  return true;
}

int StoreManager::PickStorePath() {
  // Round-robin across nio work threads; wrap with a plain mod (the
  // counter only feeds distribution, exact fairness does not matter).
  return static_cast<int>(
      next_path_.fetch_add(1, std::memory_order_relaxed) %
      static_cast<uint64_t>(paths_.size()));
}

std::string StoreManager::NewTmpPath(int spi) {
  char buf[64];
  std::snprintf(buf, sizeof(buf), "/tmp/upload_%d_%u", getpid(),
                tmp_seq_.fetch_add(1));
  return paths_[static_cast<size_t>(spi)] + buf;
}

}  // namespace fdfs
