// Store-path management: data-dir layout, tmp files, uniquifier counter.
//
// Reference: storage/storage_func.c — storage_func_init() /
// storage_make_data_dirs() create <store_path>/data with
// subdir_count_per_path² two-level dirs on first boot (".data_init_flag"
// bookkeeping), and tmp space for in-flight uploads.
#pragma once

#include <atomic>
#include <cstdint>
#include <string>
#include <vector>

#include "common/fsutil.h"
#include "storage/config.h"

namespace fdfs {

class StoreManager {
 public:
  bool Init(const StorageConfig& cfg, std::string* error);

  int PickStorePath();  // round-robin (reference: store_path rr policy)
  // True when Init created at least one data dir from scratch — on a
  // server with prior sync state this means the disk was wiped/replaced
  // (disk-recovery trigger, storage_disk_recovery.c).
  bool any_path_was_fresh() const { return any_fresh_; }
  int store_path_count() const { return static_cast<int>(paths_.size()); }
  const std::string& store_path(int i) const { return paths_[i]; }
  int subdir_count() const { return subdir_count_; }

  // Fresh tmp path for an in-flight upload on store path spi.
  std::string NewTmpPath(int spi);
  // 12-bit rolling uniquifier for file-ID minting.
  int NextUniquifier() { return static_cast<int>(uniq_.fetch_add(1) & 0xFFF); }

  // Ensure the two-level subdir for a local file path exists (lazy backstop;
  // Init pre-creates the full fan-out).
  static bool EnsureParentDirs(const std::string& path) {
    return ::fdfs::EnsureParentDirs(path);
  }

 private:
  std::vector<std::string> paths_;
  int subdir_count_ = 256;
  std::atomic<uint32_t> uniq_{0};
  std::atomic<uint32_t> tmp_seq_{0};
  std::atomic<uint64_t> next_path_{0};
  bool any_fresh_ = false;
};

}  // namespace fdfs
